"""Cross-store chunk copier / repair job.

Copies a dataset shard's part keys and (time-ranged) chunks from one
ColumnStore to another and validates the copy bit-for-bit — the DR
repair tool the reference runs as a Spark job
(spark-jobs/src/main/scala/filodb/repair/ChunkCopier.scala:25: Cassandra
token-range scan of the source chunks table, writes to the target
keyspace, used to backfill a replica cluster or repair corruption)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import List

from filodb_tpu.core.memstore import ChunkSetInfo


@dataclass
class ChunkCopierStats:
    part_keys: int = 0
    chunks_copied: int = 0
    bytes_copied: int = 0
    chunks_validated: int = 0
    validation_failures: int = 0


class ChunkCopier:
    """Copy one shard of one dataset between two ColumnStores."""

    def __init__(self, source, target):
        self.source = source
        self.target = target

    def run(self, dataset: str, shard: int, start_ms: int = 0,
            end_ms: int = 1 << 62, target_dataset: str = None,
            validate: bool = True) -> ChunkCopierStats:
        stats = ChunkCopierStats()
        tds = target_dataset or dataset
        entries = list(self.source.scan_part_keys(dataset, shard))
        for e in entries:
            chunks = self.source.read_chunks(dataset, shard, e.part_key,
                                             start_ms, end_ms)
            if chunks:
                infos = [ChunkSetInfo(c.chunk_id, c.num_rows, c.start_ts,
                                      c.end_ts, c.vectors)
                         for c in chunks]
                self.target.write_chunks(tds, shard, e.part_key, infos)
                stats.chunks_copied += len(infos)
                stats.bytes_copied += sum(
                    sum(len(v) for v in c.vectors) for c in chunks)
            stats.part_keys += 1
        self.target.write_part_keys(tds, shard, entries)
        if validate:
            self._validate(dataset, tds, shard, start_ms, end_ms, stats)
        return stats

    def _validate(self, dataset: str, tds: str, shard: int,
                  start_ms: int, end_ms: int,
                  stats: ChunkCopierStats) -> None:
        """Re-read every copied chunk from the target and compare the
        encoded vectors byte-for-byte (the copier moves opaque encoded
        chunks; any divergence means corruption in flight)."""
        for e in self.source.scan_part_keys(dataset, shard):
            src = {c.chunk_id: c for c in self.source.read_chunks(
                dataset, shard, e.part_key, start_ms, end_ms)}
            dst = {c.chunk_id: c for c in self.target.read_chunks(
                tds, shard, e.part_key, start_ms, end_ms)}
            for cid, c in src.items():
                d = dst.get(cid)
                if d is None or d.vectors != c.vectors \
                        or d.num_rows != c.num_rows:
                    stats.validation_failures += 1
                else:
                    stats.chunks_validated += 1

    def diff(self, dataset: str, shard: int, start_ms: int = 0,
             end_ms: int = 1 << 62) -> List[bytes]:
        """Part keys whose chunk sets differ between the stores (repair
        planning: run diff first, copy only what's missing)."""
        out = []
        for e in self.source.scan_part_keys(dataset, shard):
            src = {c.chunk_id for c in self.source.read_chunks(
                dataset, shard, e.part_key, start_ms, end_ms)}
            dst = {c.chunk_id for c in self.target.read_chunks(
                dataset, shard, e.part_key, start_ms, end_ms)}
            if src - dst:
                out.append(e.part_key)
        return out
