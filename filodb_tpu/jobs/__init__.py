"""Operational batch jobs (the reference's spark-jobs family beyond the
chunk downsampler): downsample-index migration, cross-store chunk
repair/copy, and cardinality busting."""

from filodb_tpu.jobs.index_migration import DSIndexJob, DSIndexStats
from filodb_tpu.jobs.chunk_copier import ChunkCopier, ChunkCopierStats
from filodb_tpu.jobs.cardbuster import CardBuster, CardBusterStats

__all__ = ["DSIndexJob", "DSIndexStats", "ChunkCopier",
           "ChunkCopierStats", "CardBuster", "CardBusterStats"]
