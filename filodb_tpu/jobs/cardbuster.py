"""Cardinality buster: bulk-delete part keys (and their chunks) that
match label filters from a persisted shard — the cleanup tool for
cardinality explosions the reference ships as
spark-jobs/src/main/scala/filodb/cardbuster/CardinalityBuster.scala
(delete-by-filter over the index + chunks tables)."""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Optional, Sequence

from filodb_tpu.core.index import ColumnFilter
from filodb_tpu.core.record import PartKey


def _match(f: ColumnFilter, v: str) -> bool:
    if f.op == "eq":
        return v == f.value
    if f.op == "neq":
        return v != f.value
    if f.op == "in":
        return v in f.value
    if f.op == "nin":
        return v not in f.value
    if f.op == "re":
        return re.fullmatch(f.value, v) is not None
    if f.op == "nre":
        return re.fullmatch(f.value, v) is None
    if f.op == "prefix":
        return v.startswith(f.value)
    return False


@dataclass
class CardBusterStats:
    scanned: int = 0
    deleted: int = 0


class CardBuster:
    """Delete persisted series whose labels match ALL given filters."""

    def __init__(self, column_store):
        self.store = column_store

    def run(self, dataset: str, shard: int,
            filters: Sequence[ColumnFilter],
            start_ms: Optional[int] = None,
            end_ms: Optional[int] = None,
            dry_run: bool = False) -> CardBusterStats:
        """Filters must be non-empty (an empty filter set would wipe the
        shard — the reference requires explicit delete filters too)."""
        if not filters:
            raise ValueError("cardbuster requires at least one filter")
        stats = CardBusterStats()
        doomed = []
        for e in self.store.scan_part_keys(dataset, shard):
            stats.scanned += 1
            if start_ms is not None and e.end_ts < start_ms:
                continue
            if end_ms is not None and e.start_ts > end_ms:
                continue
            labels = PartKey.from_bytes(e.part_key).label_map
            if all(_match(f, labels.get(f.label, "")) for f in filters):
                doomed.append(e.part_key)
        if doomed and not dry_run:
            self.store.delete_part_keys(dataset, shard, doomed)
        stats.deleted = len(doomed)
        return stats
