"""Downsample-index migration job.

The chunk downsampler (downsample/job.py) writes ds chunks + the part
keys it touched, but a series whose retention/lifecycle changed between
downsampler runs (stopped publishing, restarted later) leaves the
downsample datasets' part-key index stale. This job syncs raw part-key
index updates into every downsample dataset's index, mapping each
schema to its declared downsample schema — the reference runs this as
its own Spark job
(spark-jobs/src/main/scala/filodb/downsampler/index/DSIndexJob.scala:
migrateWithDownsamplePartKeys, updated-in-window partkeys from the raw
index upserted into the downsample Cassandra index)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional, Sequence

from filodb_tpu.core.record import PartKey
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, Schemas
from filodb_tpu.downsample.job import ds_dataset
from filodb_tpu.store import PartKeyEntry


@dataclass
class DSIndexStats:
    scanned: int = 0
    migrated: int = 0
    skipped_schemas: Dict[str, int] = field(default_factory=dict)


class DSIndexJob:
    """Sync raw part-key index updates into the downsample datasets."""

    def __init__(self, column_store, schemas: Optional[Schemas] = None,
                 resolutions: Sequence[int] = (300_000, 3_600_000)):
        self.store = column_store
        self.schemas = schemas or DEFAULT_SCHEMAS
        self.resolutions = tuple(resolutions)

    def run(self, dataset: str, shard: int,
            updated_since_ms: int = 0) -> DSIndexStats:
        """Migrate part keys whose end time moved at/after
        ``updated_since_ms`` (0 = full sync)."""
        stats = DSIndexStats()
        out: Dict[str, list] = {ds_dataset(dataset, res): []
                                for res in self.resolutions}
        for e in self.store.scan_part_keys(dataset, shard):
            stats.scanned += 1
            if e.end_ts < updated_since_ms:
                continue
            pk = PartKey.from_bytes(e.part_key)
            schema = self.schemas.by_id(pk.schema_id)
            ds_name = schema.downsample_schema
            if not schema.downsamplers or not ds_name:
                stats.skipped_schemas[schema.name] = \
                    stats.skipped_schemas.get(schema.name, 0) + 1
                continue
            ds_schema = self.schemas.by_name(ds_name)
            ds_pk = PartKey(ds_schema.schema_id, pk.labels).to_bytes()
            for name in out:
                out[name].append(PartKeyEntry(ds_pk, e.start_ts,
                                              e.end_ts))
            stats.migrated += 1
        for name, entries in out.items():
            self.store.write_part_keys(name, shard, entries)
        return stats
