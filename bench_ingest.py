"""Ingest benchmark: samples/sec through the single-shard ingest path +
encode (flush) throughput + bytes/sample on the wire.

Reference harness: jmh/src/main/scala/filodb.jmh/IngestionBenchmark.scala
(ingestRecords: BinaryRecord containers -> TimeSeriesShard.ingest) and the
~5 bytes/sample off-heap sizing rule (conf/timeseries-dev-source.conf).

Also measures the storage-integrity rail's write-path cost: WAL append
throughput with CRC framing on vs off (group commit opened wide so the
delta is the checksum+header work, not fsync), reported as
``wal_append.checksum_overhead_pct``.

Prints ONE JSON line:
  {"metric": "ingest_samples_per_s", "value": ..., "unit": "samples/s",
   "encode_samples_per_s": ..., "bytes_per_sample": ..., "native": bool,
   "wal_append": {"framed_samples_per_s": ..., "unframed_samples_per_s":
   ..., "checksum_overhead_pct": ..., "crc_algo": ...}}
"""

import json
import os
import shutil
import tempfile
import time

import numpy as np

from filodb_tpu.core.memstore import TimeSeriesShard
from filodb_tpu.core.record import RecordBuilder
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetRef
from filodb_tpu.ingest.stream import LogIngestionStream
from filodb_tpu.memory import nibblepack as nbp
from filodb_tpu.store import integrity

S = 200            # series
N = 720            # samples/series (2h at 10s)
T0 = 1_600_000_000_000


def _containers(half: int):
    """Half 0/1: same series, consecutive time windows (steady-state
    ingest is measured on half 1, after half 0 created the partitions —
    jmh IngestionBenchmark also measures a warm shard)."""
    b = RecordBuilder(DEFAULT_SCHEMAS)
    rng = np.random.default_rng(7 + half)
    incs = rng.uniform(0.0, 5.0, (S, N))
    vals = np.cumsum(incs, axis=1) + half * 5.0 * N
    jit = rng.integers(-500, 500, (S, N))
    t_base = T0 + half * N * 10_000
    for s in range(S):
        labels = {"_metric_": "reqs_total", "_ws_": "demo",
                  "_ns_": "App-0", "instance": f"i{s}"}
        ts_row = t_base + np.arange(N) * 10_000 + jit[s]
        v_row = vals[s]
        for t in range(N):
            b.add_sample("prom-counter", labels, int(ts_row[t]),
                         float(v_row[t]))
    return b.containers()


def measure():
    warm = _containers(0)
    conts = _containers(1)
    total = sum(len(c) for c in conts)

    # ingest path: container -> partitions -> write buffers; partition
    # creation (index inserts) happens on the warm pass, the timed pass
    # is the steady-state appender path. Buffers hold a full pass (1024
    # > N) so encode cost lands in the flush pass below, like the
    # reference: jmh IngestionBenchmark times ingestRecords (appenders),
    # encoding happens at optimize/flush
    shard = TimeSeriesShard(DatasetRef("timeseries"), DEFAULT_SCHEMAS, 0,
                            max_chunk_rows=1024)
    for c in warm:
        shard.ingest(c)
    shard.flush_all()
    t0 = time.perf_counter()
    for c in conts:
        shard.ingest(c)
    t_ingest = time.perf_counter() - t0

    # encode path: write buffers -> immutable compressed chunks
    t0 = time.perf_counter()
    shard.flush_all()
    t_encode = time.perf_counter() - t0

    enc_bytes = 0
    enc_rows = 0
    for part in shard.partitions.values():
        for ch in part.chunks:
            enc_bytes += sum(len(v) for v in ch.vectors)
            enc_rows += ch.num_rows

    wal = _measure_wal_append(conts, total)

    out = {
        "metric": "ingest_samples_per_s",
        "value": round(total / t_ingest, 1),
        "unit": "samples/s",
        "encode_samples_per_s": round(total / t_encode, 1),
        "bytes_per_sample": round(enc_bytes / max(enc_rows, 1), 2),
        "samples": total,
        "native_codec": nbp._native is not None,
        "wal_append": wal,
    }
    return out


def _measure_wal_append(conts, total):
    """WAL append throughput, CRC framing on vs off. Group commit is
    opened wide (one fsync at close) so the measured delta is the
    integrity rail's CPU cost — CRC + 12-byte header per record — not
    disk sync latency."""
    rates = {}
    for framed in (True, False):
        root = tempfile.mkdtemp(prefix="bench-wal-")
        try:
            s = LogIngestionStream(
                os.path.join(root, "stream.log"), DEFAULT_SCHEMAS,
                group_commit_s=3600.0, group_commit_bytes=1 << 40,
                integrity_frames=framed)
            for c in conts:            # warm: file + page cache + index
                s.append(c)
            t0 = time.perf_counter()
            for _ in range(3):
                for c in conts:
                    s.append(c)
            dt = time.perf_counter() - t0
            s.close()
            rates[framed] = 3 * total / dt
        finally:
            shutil.rmtree(root, ignore_errors=True)
    overhead = (rates[False] - rates[True]) / rates[False] * 100.0
    return {
        "framed_samples_per_s": round(rates[True], 1),
        "unframed_samples_per_s": round(rates[False], 1),
        "checksum_overhead_pct": round(overhead, 2),
        "crc_algo": integrity.CRC_ALGO,
    }


def main():
    print(json.dumps(measure()))


if __name__ == "__main__":
    main()
