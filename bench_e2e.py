"""End-to-end latency under load: the gatling-equivalent harness
(gatling/src/test FiloDBSimulation; conf/promperf-*.conf).

Starts a REAL standalone node (subprocess: gateway TCP ingest -> durable
streams -> ingestion drivers -> HTTP), seeds a working set, then drives
N concurrent query_range clients while the gateway keeps ingesting live
samples. Reports client-observed p50/p95/p99 latency and qps for the
full HTTP -> parse -> plan -> device -> JSON path, plus the
server-reported span timings (parse/plan/exec) from the final response.

Prints ONE JSON line.
"""

import json
import os
import pathlib
import select
import socket
import subprocess
import sys
import tempfile
import threading
import time
import urllib.parse
import urllib.request

import numpy as np

REPO = pathlib.Path(__file__).resolve().parent
T0 = 1_600_000_000
N_INSTANCES = 16
SEED_SAMPLES = 360             # 1h at 10s (the dev-seed
# producer is a Python loop; bigger sets take minutes to seed)
CLIENTS = 8
QUERIES_PER_CLIENT = 25
QUERIES = [
    "rate(http_requests_total[5m])",
    "sum(rate(http_requests_total[5m])) by (instance)",
    "avg_over_time(heap_usage[10m])",
    "max(heap_usage) by (instance)",
]


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def _get(port, path, **params):
    qs = urllib.parse.urlencode(params, doseq=True)
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}?{qs}", timeout=120) as r:
        return json.loads(r.read())


def measure():
    tmp = tempfile.mkdtemp(prefix="filodb-e2e-")
    port, gw_port = _free_port(), _free_port()
    cfg = {
        "num-shards": 4, "port": port, "gateway-port": gw_port,
        "data-dir": os.path.join(tmp, "data"),
        "stream-dir": os.path.join(tmp, "streams"),
        "flush-interval-s": 1.0,
        "seed-dev-data": True, "seed-start-ms": T0 * 1000,
        "seed-samples": SEED_SAMPLES, "seed-instances": N_INSTANCES,
        "query-sample-limit": 0, "query-series-limit": 0,
    }
    cfg_path = os.path.join(tmp, "server.json")
    with open(cfg_path, "w") as f:
        json.dump(cfg, f)
    env = dict(os.environ)
    # this rig reaches the TPU through a serialized ~100ms tunnel, which
    # makes CONCURRENT dispatch pathological (an artifact of the dev
    # environment, not the server design) — the latency-under-load
    # harness therefore runs the node on the CPU backend by default; on
    # a host with local TPUs set FILODB_E2E_PLATFORM=tpu
    env["JAX_PLATFORMS"] = os.environ.get("FILODB_E2E_PLATFORM", "cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "filodb_tpu.standalone.server",
         "--config", cfg_path],
        cwd=str(REPO), env=env, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL)
    try:
        buf = b""
        deadline = time.monotonic() + 180
        while time.monotonic() < deadline and b"\n" not in buf:
            r, _, _ = select.select([proc.stdout], [], [], 1.0)
            if r:
                ch = proc.stdout.read1(4096)
                if not ch:
                    raise RuntimeError("server died during startup")
                buf += ch
        line = json.loads(buf.split(b"\n", 1)[0])
        assert line["port"] == port

        end_s = T0 + (SEED_SAMPLES - 1) * 10

        def one_query(i):
            q = QUERIES[i % len(QUERIES)]
            span = 900 + (i % 4) * 600           # 15-45m windows
            start = T0 + 600 + (i * 37) % 600
            t0 = time.perf_counter()
            body = _get(port, "/promql/timeseries/api/v1/query_range",
                        query=q, start=start, end=start + span, step=60)
            dt = time.perf_counter() - t0
            assert body["status"] == "success"
            return dt, body.get("stats", {}).get("timings", {})

        # warm compile caches per query shape before measuring
        for i in range(len(QUERIES)):
            one_query(i)

        # live ingest load: a writer streams new samples via the gateway
        stop = threading.Event()

        def writer():
            t = SEED_SAMPLES
            while not stop.is_set():
                lines = []
                ts_ns = (T0 + t * 10) * 1_000_000_000
                for s in range(N_INSTANCES):
                    lines.append(
                        f"http_requests_total,instance=i{s} "
                        f"counter={(t + 1) * (s + 1)} {ts_ns}")
                try:
                    with socket.create_connection(
                            ("127.0.0.1", gw_port), timeout=10) as sk:
                        sk.sendall(("\n".join(lines) + "\n").encode())
                except OSError:
                    pass
                t += 1
                time.sleep(0.05)         # ~640 samples/s live
        wt = threading.Thread(target=writer, daemon=True)
        wt.start()

        lats, timings = [], []
        lock = threading.Lock()

        def client(cid):
            for i in range(QUERIES_PER_CLIENT):
                dt, tm = one_query(cid * QUERIES_PER_CLIENT + i)
                with lock:
                    lats.append(dt)
                    if tm:
                        timings.append(tm)
        t0 = time.perf_counter()
        threads = [threading.Thread(target=client, args=(c,))
                   for c in range(CLIENTS)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        stop.set()
        wt.join(timeout=5)

        lats_ms = np.asarray(lats) * 1000
        last = timings[-1] if timings else {}
        return {
            "metric": "e2e_query_p50_ms",
            "value": round(float(np.percentile(lats_ms, 50)), 2),
            "unit": "ms",
            "p95_ms": round(float(np.percentile(lats_ms, 95)), 2),
            "p99_ms": round(float(np.percentile(lats_ms, 99)), 2),
            "qps": round(len(lats) / wall, 1),
            "clients": CLIENTS,
            "queries": len(lats),
            "live_ingest": True,
            "server_spans_last": last,
        }
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=20)
        except subprocess.TimeoutExpired:
            proc.kill()


def main():
    print(json.dumps(measure()))


if __name__ == "__main__":
    main()
