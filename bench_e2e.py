"""End-to-end latency under load: the gatling-equivalent harness
(gatling/src/test FiloDBSimulation; conf/promperf-*.conf).

Starts a REAL standalone node (subprocess: gateway TCP ingest -> durable
streams -> ingestion drivers -> HTTP), seeds a working set, then drives
a CONCURRENCY SWEEP (1/8/32/64 in-flight clients) of query_range
traffic while the gateway keeps ingesting live samples. Clients hold
persistent HTTP/1.1 keep-alive connections (gatling's default — the
server speaks HTTP/1.1 so the per-request TCP handshake + thread spawn
disappears from steady-state serving). Reports client-observed p50/p95
latency and qps per level, the serving fast path's micro-batcher
occupancy (scraped from /metrics deltas), and the server span timings
(parse/plan/exec + plan-cache disposition) from the final response.

Headline fields (value/p95_ms/qps) come from the 8-client level for
continuity with earlier BENCH rounds.

A second DASHBOARD scenario re-issues the same query texts with a
sliding window from 8 clients — the refresh pattern the results cache
(query/resultcache.py) targets — and reports cache-off vs warm-cache
qps/p50 plus the hit ratio and cached-steps-served scraped from
/metrics ("dashboard" in the output JSON).

A third WORKER SWEEP drives the process-sharded serving tier
(standalone/supervisor.py): for 1/2/4/N worker processes behind one
SO_REUSEPORT public port, a fixed closed-loop client level measures
e2e qps/p50 plus per-worker qps and batcher occupancy (scraped from
each worker's private /metrics), and pins byte-identity of the data
section against the 1-worker deployment ("worker_sweep" in the output
JSON). The GIL plateau only breaks with real cores: on a 1-core rig
the sweep documents the overhead floor, on a >=4-core host it is the
>=3x acceptance measurement.

Prints ONE JSON line.
"""

import http.client
import json
import os
import pathlib
import select
import socket
import subprocess
import sys
import tempfile
import threading
import time
import urllib.parse

import numpy as np

REPO = pathlib.Path(__file__).resolve().parent
T0 = 1_600_000_000
N_INSTANCES = 16
SEED_SAMPLES = 360             # 1h at 10s (the dev-seed
# producer is a Python loop; bigger sets take minutes to seed)
LEVELS = (1, 8, 32, 64)
HEADLINE_LEVEL = 8
QUERIES = [
    "rate(http_requests_total[5m])",
    "sum(rate(http_requests_total[5m])) by (instance)",
    "avg_over_time(heap_usage[10m])",
    "max(heap_usage) by (instance)",
]


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


class KeepAliveClient:
    """One persistent HTTP/1.1 keep-alive connection per client thread,
    speaking raw sockets with pre-built request bytes — what native
    load generators (wrk, gatling) do, so the harness measures the
    SERVER, not Python's http.client object machinery."""

    def __init__(self, port: int):
        self.port = port
        self.sock = None
        self.buf = b""

    def _connect(self):
        self.sock = socket.create_connection(("127.0.0.1", self.port),
                                             timeout=120)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.buf = b""

    def get_raw(self, path, **params) -> bytes:
        qs = urllib.parse.urlencode(params, doseq=True)
        req = (f"GET {path}?{qs} HTTP/1.1\r\n"
               f"Host: 127.0.0.1\r\nAccept-Encoding: identity\r\n\r\n"
               ).encode()
        for attempt in (0, 1):
            if self.sock is None:
                self._connect()
            try:
                self.sock.sendall(req)
                return self._read_response()
            except OSError:
                # server closed the idle connection: reconnect once
                self.close()
                if attempt:
                    raise

    def _read_response(self) -> bytes:
        # headers
        while b"\r\n\r\n" not in self.buf:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise OSError("connection closed mid-response")
            self.buf += chunk
        head, self.buf = self.buf.split(b"\r\n\r\n", 1)
        clen = 0
        for ln in head.split(b"\r\n")[1:]:
            k, _, v = ln.partition(b":")
            if k.lower() == b"content-length":
                clen = int(v.strip())
                break
        while len(self.buf) < clen:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise OSError("connection closed mid-body")
            self.buf += chunk
        body, self.buf = self.buf[:clen], self.buf[clen:]
        if not head.startswith(b"HTTP/1.1 200") \
                and not head.startswith(b"HTTP/1.0 200"):
            raise AssertionError(head.split(b"\r\n", 1)[0] + b" "
                                 + body[:120])
        return body

    def get(self, path, **params):
        return json.loads(self.get_raw(path, **params))

    def close(self):
        if self.sock is not None:
            try:
                self.sock.close()
            except OSError:
                pass
            self.sock = None
            self.buf = b""

def _scrape_metric(client, name):
    try:
        body = client.get_raw("/metrics").decode()
    except (OSError, AssertionError):
        return 0.0
    for ln in body.splitlines():
        # family with labels or bare (label-less gauges print no braces)
        if ln.startswith(f"filodb_{name}{{") \
                or ln.startswith(f"filodb_{name} "):
            try:
                return float(ln.rsplit(" ", 1)[1])
            except ValueError:
                return 0.0
    return 0.0


def _scrape_histogram(client, name):
    """{le_seconds: cumulative_count} + total count for one histogram
    family from /metrics (filodb_<name>_bucket lines)."""
    try:
        body = client.get_raw("/metrics").decode()
    except (OSError, AssertionError):
        return {}, 0
    buckets = {}
    count = 0
    for ln in body.splitlines():
        if ln.startswith(f"filodb_{name}_bucket{{le="):
            le_s = ln.split('le="', 1)[1].split('"', 1)[0]
            le = float("inf") if le_s == "+Inf" else float(le_s)
            buckets[le] = float(ln.rsplit(" ", 1)[1])
        elif ln.startswith(f"filodb_{name}_count"):
            count = float(ln.rsplit(" ", 1)[1])
    return buckets, count


def _hist_quantiles(b0, c0, b1, c1, qs=(0.5, 0.95, 0.99)):
    """Quantiles (ms) from the DELTA of two cumulative-bucket
    snapshots — i.e. what a PromQL histogram_quantile(rate(...)) would
    report for the measurement window (linear interpolation within the
    winning bucket)."""
    les = sorted(b1)
    deltas = []
    prev = 0.0
    for le in les:
        cum = b1[le] - b0.get(le, 0.0)
        deltas.append(cum - prev)
        prev = cum
    total = c1 - c0
    if total <= 0:
        return {q: float("nan") for q in qs}
    out = {}
    for q in qs:
        rank = q * total
        cum = 0.0
        lo = 0.0
        val = les[-1]
        for le, d in zip(les, deltas):
            if cum + d >= rank:
                hi = le if le != float("inf") else lo
                frac = (rank - cum) / d if d else 0.0
                val = lo + (hi - lo) * frac
                break
            cum += d
            lo = le
        out[q] = val * 1000.0
    return out


def measure():
    tmp = tempfile.mkdtemp(prefix="filodb-e2e-")
    port, gw_port = _free_port(), _free_port()
    cfg = {
        "num-shards": 4, "port": port, "gateway-port": gw_port,
        "data-dir": os.path.join(tmp, "data"),
        "stream-dir": os.path.join(tmp, "streams"),
        "flush-interval-s": 1.0,
        "seed-dev-data": True, "seed-start-ms": T0 * 1000,
        "seed-samples": SEED_SAMPLES, "seed-instances": N_INSTANCES,
        "query-sample-limit": 0, "query-series-limit": 0,
    }
    cfg_path = os.path.join(tmp, "server.json")
    with open(cfg_path, "w") as f:
        json.dump(cfg, f)
    env = dict(os.environ)
    # this rig reaches the TPU through a serialized ~100ms tunnel, which
    # makes CONCURRENT dispatch pathological (an artifact of the dev
    # environment, not the server design) — the latency-under-load
    # harness therefore runs the node on the CPU backend by default; on
    # a host with local TPUs set FILODB_E2E_PLATFORM=tpu
    env["JAX_PLATFORMS"] = os.environ.get("FILODB_E2E_PLATFORM", "cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "filodb_tpu.standalone.server",
         "--config", cfg_path],
        cwd=str(REPO), env=env, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL)
    try:
        buf = b""
        deadline = time.monotonic() + 180
        while time.monotonic() < deadline and b"\n" not in buf:
            r, _, _ = select.select([proc.stdout], [], [], 1.0)
            if r:
                ch = proc.stdout.read1(4096)
                if not ch:
                    raise RuntimeError("server died during startup")
                buf += ch
        line = json.loads(buf.split(b"\n", 1)[0])
        assert line["port"] == port

        def one_query(client, i, want_timings=False):
            q = QUERIES[i % len(QUERIES)]
            span = 900 + (i % 4) * 600           # 15-45m windows
            start = T0 + 600 + (i * 37) % 600
            t0 = time.perf_counter()
            raw = client.get_raw("/promql/timeseries/api/v1/query_range",
                                 query=q, start=start, end=start + span,
                                 step=60)
            dt = time.perf_counter() - t0
            # a load generator verifies status without re-parsing every
            # 18KB body on the measurement path (gatling checks do the
            # same); timings are parsed on a sample of responses
            assert raw.startswith(b'{"status":"success"') \
                or raw.startswith(b'{"status": "success"'), raw[:120]
            if not want_timings:
                return dt, {}
            body = json.loads(raw)
            return dt, body.get("stats", {}).get("timings", {})

        # live ingest load: a writer streams new samples via the gateway.
        # Started BEFORE compile warmup so the warmup also covers the
        # write-buffer-tail splice shapes live ingest creates (the tail
        # steps take the packed kernel path with their own shape set).
        stop = threading.Event()

        def writer():
            t = SEED_SAMPLES
            while not stop.is_set():
                lines = []
                ts_ns = (T0 + t * 10) * 1_000_000_000
                for s in range(N_INSTANCES):
                    lines.append(
                        f"http_requests_total,instance=i{s} "
                        f"counter={(t + 1) * (s + 1)} {ts_ns}")
                try:
                    with socket.create_connection(
                            ("127.0.0.1", gw_port), timeout=10) as sk:
                        sk.sendall(("\n".join(lines) + "\n").encode())
                except OSError:
                    pass
                t += 1
                time.sleep(0.05)         # ~640 samples/s live
        wt = threading.Thread(target=writer, daemon=True)
        wt.start()
        time.sleep(1.5)          # at least one flush: tails exist

        # warm compile caches per query shape before measuring — the
        # sequential kernel shapes, the micro-batched (vmapped)
        # batch-width buckets each concurrency level will hit, and the
        # live-tail splice shapes
        warm = KeepAliveClient(port)
        for rep in range(3):
            for i in range(len(QUERIES)):
                one_query(warm, i + 4 * rep)
        for burst in (3, 8):
            for qi in range(len(QUERIES)):
                ths = []
                for c in range(burst):
                    def wfire(cc=c, qq=qi):
                        cl = KeepAliveClient(port)
                        one_query(cl, qq + 4 * cc)
                        cl.close()
                    ths.append(threading.Thread(target=wfire))
                for t in ths:
                    t.start()
                for t in ths:
                    t.join()

        def run_level(clients, duration_s=2.5):
            """Fixed-DURATION closed-loop level (wrk-style): every
            client loops until the shared deadline, so one slow client
            can't skew qps by leaving the others idle at the end."""
            lats, timings = [], []
            lock = threading.Lock()
            t_end = [0.0]

            def client_loop(cid):
                # ramp-up: stagger connects so a level's start isn't a
                # thundering herd of simultaneous TCP handshakes (load
                # generators ramp users in; the herd would only measure
                # the accept loop)
                time.sleep(cid * 0.002)
                cl = KeepAliveClient(port)
                i = 0
                while time.perf_counter() < t_end[0]:
                    dt, tm = one_query(cl, cid * 100_000 + i,
                                       want_timings=(i % 16 == 15))
                    i += 1
                    with lock:
                        lats.append(dt)
                        if tm:
                            timings.append(tm)
                cl.close()

            b0 = _scrape_metric(warm, "batcher_batches_total")
            q0 = _scrape_metric(warm, "batcher_queries_total")
            hb0, hc0 = _scrape_histogram(warm, "query_latency_seconds")
            t0 = time.perf_counter()
            t_end[0] = t0 + duration_s
            threads = [threading.Thread(target=client_loop, args=(c,))
                       for c in range(clients)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = time.perf_counter() - t0
            b1 = _scrape_metric(warm, "batcher_batches_total")
            q1 = _scrape_metric(warm, "batcher_queries_total")
            hb1, hc1 = _scrape_histogram(warm, "query_latency_seconds")
            lats_ms = np.asarray(lats) * 1000
            occ = (q1 - q0) / (b1 - b0) if b1 > b0 else 1.0
            # server-side quantiles derived from the /metrics histogram
            # delta over this level — the scrapeable answer to the same
            # question the client-side percentiles measure (bucket
            # resolution, so expect agreement to the bucket width)
            hq = _hist_quantiles(hb0, hc0, hb1, hc1)
            return {
                "clients": clients,
                "queries": len(lats),
                "e2e_qps": round(len(lats) / wall, 1),
                "p50_ms": round(float(np.percentile(lats_ms, 50)), 2),
                "p95_ms": round(float(np.percentile(lats_ms, 95)), 2),
                "p99_ms": round(float(np.percentile(lats_ms, 99)), 2),
                "hist_p50_ms": round(hq[0.5], 2),
                "hist_p95_ms": round(hq[0.95], 2),
                "hist_p99_ms": round(hq[0.99], 2),
                "batcher_occupancy": round(occ, 2),
            }, (timings[-1] if timings else {})

        sweep = []
        last_timings = {}
        headline = None
        for level in LEVELS:
            res, tm = run_level(level)
            sweep.append(res)
            if tm:
                last_timings = tm
            if level == HEADLINE_LEVEL:
                headline = res

        # -- dashboard scenario: N clients re-issuing the SAME queries
        # with a sliding window (the refresh-every-few-seconds pattern
        # the results cache targets). The window slides one step per
        # SLIDE_S of wall time, shared by all clients — like a real
        # dashboard, where the refresh interval is shorter than the
        # step, most refreshes repeat the previous window exactly and
        # a slide recomputes only the newest step(s). Measured twice
        # over the same server: &cache=false (full recompute per
        # refresh) vs cache on, with hit ratio + cached-steps-served
        # scraped from /metrics deltas.
        SLIDE_S = 0.5

        def dashboard_query(client, cid, t_base, use_cache):
            q = QUERIES[cid % len(QUERIES)]
            slide = int((time.perf_counter() - t_base) / SLIDE_S)
            start = T0 + 600 + (slide % 30) * 60
            params = dict(query=q, start=start, end=start + 1800,
                          step=60)
            if not use_cache:
                params["cache"] = "false"
            t0 = time.perf_counter()
            raw = client.get_raw(
                "/promql/timeseries/api/v1/query_range", **params)
            dt = time.perf_counter() - t0
            assert raw.startswith(b'{"status":"success"'), raw[:120]
            return dt

        def run_dashboard(clients, use_cache, duration_s=2.5):
            lats = []
            lock = threading.Lock()
            t_end = [0.0]
            t_base = [0.0]

            def client_loop(cid):
                time.sleep(cid * 0.002)
                cl = KeepAliveClient(port)
                while time.perf_counter() < t_end[0]:
                    dt = dashboard_query(cl, cid, t_base[0], use_cache)
                    with lock:
                        lats.append(dt)
                cl.close()

            t0 = time.perf_counter()
            t_base[0] = t0
            t_end[0] = t0 + duration_s
            threads = [threading.Thread(target=client_loop, args=(c,))
                       for c in range(clients)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = time.perf_counter() - t0
            lats_ms = np.asarray(lats) * 1000
            return {
                "queries": len(lats),
                "qps": round(len(lats) / wall, 1),
                "p50_ms": round(float(np.percentile(lats_ms, 50)), 2),
                "p95_ms": round(float(np.percentile(lats_ms, 95)), 2),
            }

        def rc_counters():
            return {k: _scrape_metric(warm, f"result_cache_{k}_total")
                    for k in ("hits", "partial_hits", "misses",
                              "cached_steps_served",
                              "computed_steps_served")}

        # two levels: 1 client measures unloaded serving latency (the
        # p50 win), 8 closed-loop clients measure throughput on the
        # saturated 1-core rig (where p50 is queueing-dominated in both
        # modes and understates the service-time ratio)
        dash_levels = []
        for dash_clients in (1, 8):
            # cold baseline: every refresh recomputes the whole range
            dash_off = run_dashboard(dash_clients, use_cache=False)
            # warm the extents, then measure steady-state cache serving
            run_dashboard(dash_clients, use_cache=True, duration_s=1.0)
            c0 = rc_counters()
            dash_on = run_dashboard(dash_clients, use_cache=True)
            c1 = rc_counters()
            served = (c1["hits"] - c0["hits"]
                      + c1["partial_hits"] - c0["partial_hits"])
            lookups = served + c1["misses"] - c0["misses"]
            cached_steps = (c1["cached_steps_served"]
                            - c0["cached_steps_served"])
            total_steps = cached_steps + (c1["computed_steps_served"]
                                          - c0["computed_steps_served"])
            dash_levels.append({
                "clients": dash_clients,
                "cache_off": dash_off,
                "cache_warm": dash_on,
                "hit_ratio": round(served / lookups, 3)
                if lookups else 0.0,
                "cached_steps_served": int(cached_steps),
                "cached_step_ratio": round(cached_steps / total_steps,
                                           3) if total_steps else 0.0,
                "qps_speedup": round(dash_on["qps"] / dash_off["qps"],
                                     2) if dash_off["qps"] else 0.0,
                "p50_speedup": round(
                    dash_off["p50_ms"] / dash_on["p50_ms"], 2)
                if dash_on["p50_ms"] else 0.0,
            })
        dashboard = {
            "levels": dash_levels,
            "hit_ratio": dash_levels[-1]["hit_ratio"],
            "cached_steps_served": sum(l["cached_steps_served"]
                                       for l in dash_levels),
            # headline: throughput under load, latency unloaded
            "qps_speedup": dash_levels[-1]["qps_speedup"],
            "p50_speedup": dash_levels[0]["p50_speedup"],
        }
        stop.set()
        wt.join(timeout=5)
        headline = headline or sweep[-1]

        return {
            "metric": "e2e_query_p50_ms",
            "value": headline["p50_ms"],
            "unit": "ms",
            "p95_ms": headline["p95_ms"],
            "p99_ms": headline["p99_ms"],
            "hist_p50_ms": headline["hist_p50_ms"],
            "hist_p95_ms": headline["hist_p95_ms"],
            "hist_p99_ms": headline["hist_p99_ms"],
            "qps": headline["e2e_qps"],
            "clients": headline["clients"],
            "queries": headline["queries"],
            "live_ingest": True,
            "keep_alive": True,
            "batcher_occupancy": headline["batcher_occupancy"],
            "sweep": sweep,
            "dashboard": dashboard,
            "server_spans_last": last_timings,
        }
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=20)
        except subprocess.TimeoutExpired:
            proc.kill()


# -- worker sweep: the process-sharded serving tier ------------------------

SWEEP_SAMPLES = 180         # 30min at 10s — enough for the 15-45m windows
SWEEP_INSTANCES = 8
SWEEP_SHARDS = 4
SWEEP_CLIENTS = 16
SWEEP_QUERIES = [
    "rate(http_requests_total[5m])",
    "sum(rate(http_requests_total[5m])) by (instance)",
    "avg_over_time(heap_usage[10m])",
    "max(heap_usage) by (instance)",
]


def _sweep_corpus(stream_dir):
    """Test-owned WAL producer plane (the Kafka analogue): every worker
    consumes its own shard-group's streams regardless of fleet size."""
    from filodb_tpu.core.schemas import DEFAULT_SCHEMAS
    from filodb_tpu.gateway.producer import TestTimeseriesProducer
    from filodb_tpu.ingest import LogIngestionStream
    prod = TestTimeseriesProducer(DEFAULT_SCHEMAS,
                                  num_shards=SWEEP_SHARDS)
    streams = {}
    for sh in range(SWEEP_SHARDS):
        path = os.path.join(stream_dir, f"shard={sh}", "stream.log")
        streams[sh] = LogIngestionStream(path, DEFAULT_SCHEMAS)
    for builders in (prod.gauges(T0 * 1000, SWEEP_SAMPLES,
                                 SWEEP_INSTANCES),
                     prod.counters(T0 * 1000, SWEEP_SAMPLES,
                                   SWEEP_INSTANCES)):
        for sh, b in builders.items():
            for c in b.containers():
                streams[sh].append(c)
    for s in streams.values():
        s.close()


def _spawn_supervisor(cfg):
    cfg_dir = tempfile.mkdtemp(prefix="filodb-sweep-cfg-")
    cfg_path = os.path.join(cfg_dir, "sup.json")
    with open(cfg_path, "w") as f:
        json.dump(cfg, f)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = os.environ.get("FILODB_E2E_PLATFORM", "cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "filodb_tpu.standalone.supervisor",
         "--config", cfg_path],
        cwd=str(REPO), env=env, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL)
    buf = b""
    deadline = time.monotonic() + 300
    while time.monotonic() < deadline and b"\n" not in buf:
        r, _, _ = select.select([proc.stdout], [], [], 1.0)
        if r:
            ch = proc.stdout.read1(4096)
            if not ch:
                raise RuntimeError("supervisor died during startup")
            buf += ch
    return proc, json.loads(buf.split(b"\n", 1)[0])


def _sweep_query(client, i, cache=True):
    q = SWEEP_QUERIES[i % len(SWEEP_QUERIES)]
    span = 900 + (i % 4) * 600
    start = T0 + 600 + (i * 37) % 300
    params = dict(query=q, start=start, end=start + span, step=60)
    if not cache:
        params["cache"] = "false"
    t0 = time.perf_counter()
    raw = client.get_raw("/promql/timeseries/api/v1/query_range",
                         **params)
    dt = time.perf_counter() - t0
    assert raw.startswith(b'{"status":"success"'), raw[:120]
    return dt, raw


def _worker_counts(port):
    """Per-worker counters scraped off a PRIVATE port."""
    cl = KeepAliveClient(port)
    out = {
        "queries": _scrape_metric(cl, "query_latency_seconds_count"),
        "batches": _scrape_metric(cl, "batcher_batches_total"),
        "batched": _scrape_metric(cl, "batcher_queries_total"),
    }
    cl.close()
    return out


def measure_worker_sweep():
    import shutil
    cores = os.cpu_count() or 1
    levels = sorted({1, 2, 4, cores} & set(range(1, max(cores, 4) + 1)))
    out_levels = []
    golden = None
    for workers in levels:
        tmp = tempfile.mkdtemp(prefix=f"filodb-sweep-w{workers}-")
        _sweep_corpus(os.path.join(tmp, "streams"))
        cfg = {
            "num-shards": SWEEP_SHARDS, "port": _free_port(),
            "serving-workers": workers,
            "supervisor-port": 0,
            "run-dir": os.path.join(tmp, "run"),
            "data-dir": os.path.join(tmp, "data"),
            "stream-dir": os.path.join(tmp, "streams"),
            "flush-interval-s": 0.5,
            "max-chunks-size": 100,
            "query-sample-limit": 0, "query-series-limit": 0,
            # the production data plane: sibling leaf dispatch rides
            # protobuf+NibblePack over persistent channels (ports
            # advertised via health gossip)
            "grpc-port": 0,
            # admission sized for the level so the GLOBAL quota is not
            # the bottleneck under SWEEP_CLIENTS closed-loop clients
            "max-inflight-queries": max(8, 2 * workers),
        }
        proc, line = _spawn_supervisor(cfg)
        try:
            pub = line["port"]
            worker_ports = [w["port"] for w in line["workers"]]
            want = 2 * SWEEP_INSTANCES

            # replay + full results
            probe = KeepAliveClient(pub)
            deadline = time.monotonic() + 240
            while time.monotonic() < deadline:
                try:
                    _, raw = _sweep_query(probe, 0, cache=False)
                    if raw.count(b'"metric"') >= SWEEP_INSTANCES:
                        break
                except (OSError, AssertionError):
                    probe.close()
                time.sleep(0.3)
            time.sleep(2.0)         # settle: chunks + watermarks

            # warm EVERY worker's compile/plan caches, entry and peer
            # paths alike (per-process caches: each interpreter pays
            # its own warmup)
            for port in worker_ports:
                wcl = KeepAliveClient(port)
                for rep in range(2):
                    for i in range(len(SWEEP_QUERIES)):
                        _sweep_query(wcl, i + 4 * rep)
                wcl.close()
            for rep in range(2 * workers):
                for i in range(len(SWEEP_QUERIES)):
                    _sweep_query(probe, i + 4 * rep)

            # byte-identity vs the 1-worker deployment (data section;
            # the stats tail carries wall-clock timings)
            _, raw = _sweep_query(probe, 0, cache=False)
            data = raw.partition(b',"stats":')[0]
            if golden is None:
                golden = data
            identical = data == golden
            probe.close()

            # fixed closed-loop level through the PUBLIC port
            lats = []
            lock = threading.Lock()
            t_end = [0.0]

            def client_loop(cid):
                time.sleep(cid * 0.002)
                cl = KeepAliveClient(pub)
                i = 0
                while time.perf_counter() < t_end[0]:
                    dt, _ = _sweep_query(cl, cid * 100_000 + i)
                    i += 1
                    with lock:
                        lats.append(dt)
                cl.close()

            before = {p: _worker_counts(p) for p in worker_ports}
            t0 = time.perf_counter()
            t_end[0] = t0 + 2.5
            threads = [threading.Thread(target=client_loop, args=(c,))
                       for c in range(SWEEP_CLIENTS)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = time.perf_counter() - t0
            after = {p: _worker_counts(p) for p in worker_ports}
            per_worker = {}
            for idx, p in enumerate(worker_ports):
                dq = after[p]["queries"] - before[p]["queries"]
                db = after[p]["batches"] - before[p]["batches"]
                dbq = after[p]["batched"] - before[p]["batched"]
                per_worker[str(idx)] = {
                    "qps": round(dq / wall, 1),
                    "batcher_occupancy": round(dbq / db, 2)
                    if db > 0 else 1.0,
                }
            lats_ms = np.asarray(lats) * 1000
            out_levels.append({
                "workers": workers,
                "clients": SWEEP_CLIENTS,
                "queries": len(lats),
                "e2e_qps": round(len(lats) / wall, 1),
                "p50_ms": round(float(np.percentile(lats_ms, 50)), 2),
                "p95_ms": round(float(np.percentile(lats_ms, 95)), 2),
                "byte_identical": identical,
                "per_worker": per_worker,
            })
        finally:
            proc.terminate()
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                proc.kill()
            shutil.rmtree(tmp, ignore_errors=True)
    base_qps = out_levels[0]["e2e_qps"] if out_levels else 0.0
    best = max(out_levels, key=lambda l: l["e2e_qps"]) \
        if out_levels else None
    return {
        "cores": cores,
        "levels": out_levels,
        "byte_identical": all(l["byte_identical"] for l in out_levels),
        "best_workers": best["workers"] if best else 0,
        "qps_speedup_vs_1worker": round(best["e2e_qps"] / base_qps, 2)
        if best and base_qps else 0.0,
    }


# -- noisy-neighbor scenario (tenant QoS, query/qos.py) ---------------------
# One abusive tenant hammering monster scans next to N interactive
# tenants issuing cheap dashboard queries, measured twice over identical
# servers: QoS off (the abuser's scans head-of-line block everyone) vs
# QoS on (the abuser throttles to its budget / degrades; interactive
# latency stays near the unloaded baseline). The headline number is
# interactive p99 under load vs the same server unloaded.

NOISY_INTERACTIVE_Q = dict(query="sum(rate(heap_usage[1m]))",
                           start=T0 + 600, end=T0 + 900, step=30)
# two monster shapes: sort(...) is results-cache-UNCACHEABLE (order
# depends on the grid bounds), so every issue is a full recompute —
# the worst-case scan QoS must throttle; the plain rate(...) matrix is
# cacheable, so under QoS the brownout's stale rung can answer it
NOISY_ABUSE_QS = [
    dict(query='sort(rate({_metric_=~"heap_usage|http_requests_total"}'
               '[10m]))',
         start=T0 + 600, end=T0 + SEED_SAMPLES * 10 - 10, step=10),
    dict(query='rate({_metric_=~"heap_usage|http_requests_total"}'
               '[10m])',
         start=T0 + 600, end=T0 + SEED_SAMPLES * 10 - 10, step=10),
]
NOISY_ABUSE_BUDGET = [50, 2000]         # rate units/s, burst


def _spawn_node(cfg):
    cfg_dir = tempfile.mkdtemp(prefix="filodb-noisy-cfg-")
    cfg_path = os.path.join(cfg_dir, "node.json")
    with open(cfg_path, "w") as f:
        json.dump(cfg, f)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = os.environ.get("FILODB_E2E_PLATFORM", "cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "filodb_tpu.standalone.server",
         "--config", cfg_path],
        cwd=str(REPO), env=env, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL)
    buf = b""
    deadline = time.monotonic() + 180
    while time.monotonic() < deadline and b"\n" not in buf:
        r, _, _ = select.select([proc.stdout], [], [], 1.0)
        if r:
            ch = proc.stdout.read1(4096)
            if not ch:
                raise RuntimeError("node died during startup")
            buf += ch
    return proc, json.loads(buf.split(b"\n", 1)[0])


def measure_noisy_neighbor(interactive_clients=3, abuse_clients=1,
                           duration_s=3.0):
    out = {"interactive_clients": interactive_clients,
           "abuse_clients": abuse_clients,
           "abuse_budget": NOISY_ABUSE_BUDGET}
    for mode in ("qos_off", "qos_on"):
        port = _free_port()
        cfg = {
            "num-shards": 4, "port": port, "gateway-port": None,
            "seed-dev-data": True, "seed-start-ms": T0 * 1000,
            "seed-samples": SEED_SAMPLES,
            "seed-instances": N_INSTANCES,
            "query-sample-limit": 0, "query-series-limit": 0,
            "max-inflight-queries": 8,
            "admission-wait-s": 2.0,
            "grpc-port": None,
        }
        if mode == "qos_on":
            cfg["qos-tenant-overrides"] = {
                "abuser": NOISY_ABUSE_BUDGET}
        proc, _line = _spawn_node(cfg)
        try:
            # unloaded interactive baseline (p50/p99 with no abuser)
            def interactive_once(cl):
                t0 = time.perf_counter()
                raw = cl.get_raw(
                    "/promql/timeseries/api/v1/query_range",
                    tenant="interactive", **NOISY_INTERACTIVE_Q)
                dt = time.perf_counter() - t0
                assert raw.startswith(b'{"status":"success"'), raw[:120]
                return dt

            cl = KeepAliveClient(port)
            interactive_once(cl)                # warm compile
            # warm the cacheable abuse shape's extent under an
            # UNBUDGETED tenant (the realistic dashboard world): the
            # abuser's brownout then serves the stale rung for it
            cl.get_raw("/promql/timeseries/api/v1/query_range",
                       tenant="warmup", **NOISY_ABUSE_QS[1])
            cl.close()

            lats, abuse_out = [], {"clean": 0, "shed": 0,
                                   "throttled": 0, "failed": 0}
            lock = threading.Lock()
            t_end = [0.0]

            def interactive_loop(cid):
                c = KeepAliveClient(port)
                while time.perf_counter() < t_end[0]:
                    dt = interactive_once(c)
                    with lock:
                        lats.append(dt)
                c.close()

            def abuse_loop():
                c = KeepAliveClient(port)
                i = 0
                while time.perf_counter() < t_end[0]:
                    i += 1
                    # the keep-alive client asserts 200; a 429 raises
                    # with the status line + body head in the message
                    try:
                        raw = c.get_raw(
                            "/promql/timeseries/api/v1/query_range",
                            tenant="abuser",
                            **NOISY_ABUSE_QS[i % len(NOISY_ABUSE_QS)])
                    except AssertionError as e:
                        # body fully drained before the raise: the
                        # keep-alive connection stays usable
                        with lock:
                            if "429" in str(e):
                                abuse_out["throttled"] += 1
                            else:
                                abuse_out["failed"] += 1
                        continue
                    with lock:
                        if b'shed(' in raw:
                            abuse_out["shed"] += 1
                        else:
                            abuse_out["clean"] += 1
                c.close()

            def run_phase(with_abuse):
                """Interactive percentiles at the SAME interactive
                concurrency, with/without the abuser — the unloaded
                baseline must carry the identical client-side load so
                the ratio isolates the NEIGHBOR, not the GIL."""
                lats.clear()
                threads = [threading.Thread(target=interactive_loop,
                                            args=(c,))
                           for c in range(interactive_clients)]
                if with_abuse:
                    threads += [threading.Thread(target=abuse_loop)
                                for _ in range(abuse_clients)]
                t_end[0] = time.perf_counter() + duration_s
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
                lats_ms = np.asarray(lats) * 1000
                return {
                    "p50_ms":
                        round(float(np.percentile(lats_ms, 50)), 2),
                    "p99_ms":
                        round(float(np.percentile(lats_ms, 99)), 2),
                    "queries": len(lats),
                }

            base = run_phase(with_abuse=False)
            loaded = run_phase(with_abuse=True)
            out[mode] = {
                "interactive_unloaded_p50_ms": base["p50_ms"],
                "interactive_unloaded_p99_ms": base["p99_ms"],
                "interactive_loaded_p50_ms": loaded["p50_ms"],
                "interactive_loaded_p99_ms": loaded["p99_ms"],
                "interactive_queries": loaded["queries"],
                "abuse": dict(abuse_out),
                "interactive_p99_vs_unloaded": round(
                    loaded["p99_ms"] / max(base["p99_ms"], 1e-9), 2),
            }
        finally:
            proc.terminate()
            try:
                proc.wait(timeout=20)
            except subprocess.TimeoutExpired:
                proc.kill()
    return out


# -- self-monitoring overhead (obs/selfmon.py) ------------------------------
# Identical servers, identical closed-loop client load, measured with
# the self-monitoring pipeline OFF vs ON at the default interval: the
# loop's registry walk + RecordBuilder + ingest must cost <=2% qps/p99
# (the PR acceptance bound). The ON server also reports how much
# internal telemetry it banked meanwhile (ticks/samples), so the
# overhead number is tied to real self-ingest volume.

def measure_selfmon_overhead(clients=8, duration_s=2.5,
                             interval_s=5.0, trials=3):
    """INTERLEAVED best-of-``trials`` per mode: both servers (loop off
    / loop on) are alive for the whole measurement and trials
    alternate off/on/off/on — single-trial qps on a 1-core
    oversubscribed dev rig swings +/-20% run to run (warm-up compiles,
    GC, container neighbors), and a serial off-then-on design
    confounds that drift with the effect being measured. Best trial
    per mode (min-of-N convention) is the comparator."""
    out = {"clients": clients, "interval_s": interval_s,
           "trials": trials}
    procs = {}
    ports = {}
    try:
        for mode in ("selfmon_off", "selfmon_on"):
            port = _free_port()
            cfg = {
                "num-shards": 4, "port": port, "gateway-port": None,
                "seed-dev-data": True, "seed-start-ms": T0 * 1000,
                "seed-samples": SEED_SAMPLES,
                "seed-instances": N_INSTANCES,
                "query-sample-limit": 0, "query-series-limit": 0,
                "max-inflight-queries": 8,
                "grpc-port": None,
            }
            if mode == "selfmon_on":
                cfg["self-monitor"] = True
                cfg["self-monitor-interval-s"] = interval_s
            procs[mode], _line = _spawn_node(cfg)
            ports[mode] = port

        def one(cl, i):
            t0 = time.perf_counter()
            raw = cl.get_raw(
                "/promql/timeseries/api/v1/query_range",
                query="rate(http_requests_total[5m])",
                start=T0 + 600 + (i % 8) * 10,
                end=T0 + 900 + (i % 8) * 10, step=30)
            dt = time.perf_counter() - t0
            assert raw.startswith(b'{"status":"success"'), raw[:120]
            return dt

        for mode in ("selfmon_off", "selfmon_on"):
            warm = KeepAliveClient(ports[mode])
            for i in range(8):      # compile every query shape
                one(warm, i)
            warm.close()
        # settle the loop: the FIRST ticks create the internal series
        # (index inserts + first flush) — a one-time transient, not the
        # steady state being measured. Wait ~2 ticks so measurement
        # sees the append-only regime.
        time.sleep(min(2.2 * interval_s, 12.0))

        def run_trial(port):
            lats = []
            lock = threading.Lock()
            t_end = time.perf_counter() + duration_s

            def loop(cid):
                c = KeepAliveClient(port)
                i = 0
                while time.perf_counter() < t_end:
                    dt = one(c, cid * 13 + i)
                    i += 1
                    with lock:
                        lats.append(dt)
                c.close()
            threads = [threading.Thread(target=loop, args=(c,))
                       for c in range(clients)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            lats_ms = np.asarray(lats) * 1000
            return {
                "qps": round(len(lats) / duration_s, 1),
                "p50_ms": round(float(np.percentile(lats_ms, 50)), 2),
                "p99_ms": round(float(np.percentile(lats_ms, 99)), 2),
                "queries": len(lats),
            }

        runs = {"selfmon_off": [], "selfmon_on": []}
        for t in range(max(1, trials)):
            # alternate within-round order: rig drift inside a round
            # (GC, neighbors warming) must not systematically favor
            # one mode
            order = ("selfmon_off", "selfmon_on") if t % 2 == 0 \
                else ("selfmon_on", "selfmon_off")
            for mode in order:
                runs[mode].append(run_trial(ports[mode]))
        for mode, rs in runs.items():
            # trial 0 is warm-up on both sides (residual compiles, page
            # cache): drop it, then MEAN the steady trials — a ratio of
            # means is far more stable than a ratio of extremes on a
            # rig whose per-trial qps swings +/-20%
            steady = rs[1:] if len(rs) > 1 else rs
            entry = {
                "qps": round(sum(r["qps"] for r in steady)
                             / len(steady), 1),
                "p50_ms": round(sum(r["p50_ms"] for r in steady)
                                / len(steady), 2),
                "p99_ms": round(sum(r["p99_ms"] for r in steady)
                                / len(steady), 2),
                "queries": sum(r["queries"] for r in steady),
            }
            entry["all_qps"] = [r["qps"] for r in rs]
            entry["all_p99_ms"] = [r["p99_ms"] for r in rs]
            if mode == "selfmon_on":
                cl = KeepAliveClient(ports[mode])
                entry["selfmon"] = _scrape_metric(
                    cl, "selfmon_samples_ingested_total")
                entry["selfmon_ticks"] = _scrape_metric(
                    cl, "selfmon_ticks_total")
                # the noise-free overhead number: the loop's own tick
                # histogram gives mean collect+ingest wall time; duty
                # cycle = tick_s / interval_s bounds the steady-state
                # qps cost independent of client-side trial noise
                tick_sum = _scrape_metric(cl, "selfmon_tick_seconds_sum")
                tick_n = _scrape_metric(cl, "selfmon_tick_seconds_count")
                if tick_n:
                    entry["tick_ms_avg"] = round(
                        1000 * tick_sum / tick_n, 2)
                    entry["duty_cycle"] = round(
                        (tick_sum / tick_n) / interval_s, 5)
                cl.close()
            out[mode] = entry
    finally:
        for proc in procs.values():
            proc.terminate()
        for proc in procs.values():
            try:
                proc.wait(timeout=20)
            except subprocess.TimeoutExpired:
                proc.kill()
    if out.get("selfmon_off", {}).get("qps"):
        off, on = out["selfmon_off"], out["selfmon_on"]
        out["qps_ratio_on_vs_off"] = round(
            on["qps"] / max(off["qps"], 1e-9), 4)
        out["p99_ratio_on_vs_off"] = round(
            on["p99_ms"] / max(off["p99_ms"], 1e-9), 4)
    return out


def measure_profiler_overhead(clients=8, duration_s=2.5, hz=29.0,
                              trials=3):
    """Sampling-profiler cost under the 8-client dashboard load, same
    interleaved best-of-``trials`` design as the selfmon harness: two
    servers (profiler off / on at the default hz) alive for the whole
    measurement, trials alternating. Besides client-side qps/p99, the
    sampler's own tick histogram gives the noise-free number: duty
    cycle = mean tick cost x hz. The /debug/profile report closes the
    attribution acceptance (fraction of samples landing on a declared
    thread root)."""
    out = {"clients": clients, "hz": hz, "trials": trials}
    procs = {}
    ports = {}
    try:
        for mode in ("profiler_off", "profiler_on"):
            port = _free_port()
            cfg = {
                "num-shards": 4, "port": port, "gateway-port": None,
                "seed-dev-data": True, "seed-start-ms": T0 * 1000,
                "seed-samples": SEED_SAMPLES,
                "seed-instances": N_INSTANCES,
                "query-sample-limit": 0, "query-series-limit": 0,
                "max-inflight-queries": 8,
                "grpc-port": None,
            }
            if mode == "profiler_on":
                cfg["profiler-enabled"] = True
                cfg["profiler-hz"] = hz
            procs[mode], _line = _spawn_node(cfg)
            ports[mode] = port

        def one(cl, i):
            t0 = time.perf_counter()
            raw = cl.get_raw(
                "/promql/timeseries/api/v1/query_range",
                query="rate(http_requests_total[5m])",
                start=T0 + 600 + (i % 8) * 10,
                end=T0 + 900 + (i % 8) * 10, step=30)
            dt = time.perf_counter() - t0
            assert raw.startswith(b'{"status":"success"'), raw[:120]
            return dt

        for mode in ("profiler_off", "profiler_on"):
            warm = KeepAliveClient(ports[mode])
            for i in range(8):      # compile every query shape
                one(warm, i)
            warm.close()

        def run_trial(port):
            lats = []
            lock = threading.Lock()
            t_end = time.perf_counter() + duration_s

            def loop(cid):
                c = KeepAliveClient(port)
                i = 0
                while time.perf_counter() < t_end:
                    dt = one(c, cid * 13 + i)
                    i += 1
                    with lock:
                        lats.append(dt)
                c.close()
            threads = [threading.Thread(target=loop, args=(c,))
                       for c in range(clients)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            lats_ms = np.asarray(lats) * 1000
            return {
                "qps": round(len(lats) / duration_s, 1),
                "p50_ms": round(float(np.percentile(lats_ms, 50)), 2),
                "p99_ms": round(float(np.percentile(lats_ms, 99)), 2),
                "queries": len(lats),
            }

        runs = {"profiler_off": [], "profiler_on": []}
        for t in range(max(1, trials)):
            order = ("profiler_off", "profiler_on") if t % 2 == 0 \
                else ("profiler_on", "profiler_off")
            for mode in order:
                runs[mode].append(run_trial(ports[mode]))
        for mode, rs in runs.items():
            steady = rs[1:] if len(rs) > 1 else rs
            entry = {
                "qps": round(sum(r["qps"] for r in steady)
                             / len(steady), 1),
                "p50_ms": round(sum(r["p50_ms"] for r in steady)
                                / len(steady), 2),
                "p99_ms": round(sum(r["p99_ms"] for r in steady)
                                / len(steady), 2),
                "queries": sum(r["queries"] for r in steady),
            }
            entry["all_qps"] = [r["qps"] for r in rs]
            entry["all_p99_ms"] = [r["p99_ms"] for r in rs]
            if mode == "profiler_on":
                cl = KeepAliveClient(ports[mode])
                tick_sum = _scrape_metric(
                    cl, "profiler_tick_seconds_sum")
                tick_n = _scrape_metric(
                    cl, "profiler_tick_seconds_count")
                if tick_n:
                    entry["ticks"] = int(tick_n)
                    entry["tick_us_avg"] = round(
                        1e6 * tick_sum / tick_n, 1)
                    # ticks fire hz times per second: the sampler's
                    # steady-state CPU share is tick cost x hz
                    entry["duty_cycle"] = round(
                        (tick_sum / tick_n) * hz, 6)
                rep = json.loads(cl.get_raw("/debug/profile"))
                entry["samples"] = rep["data"]["samples"]
                entry["attribution_fraction"] = \
                    rep["data"]["attribution_fraction"]
                entry["roots"] = {
                    k: v for k, v in sorted(
                        rep["data"]["roots"].items(),
                        key=lambda kv: -kv[1])[:8]}
                cl.close()
            out[mode] = entry
    finally:
        for proc in procs.values():
            proc.terminate()
        for proc in procs.values():
            try:
                proc.wait(timeout=20)
            except subprocess.TimeoutExpired:
                proc.kill()
    if out.get("profiler_off", {}).get("qps"):
        off, on = out["profiler_off"], out["profiler_on"]
        out["qps_ratio_on_vs_off"] = round(
            on["qps"] / max(off["qps"], 1e-9), 4)
        out["p99_ratio_on_vs_off"] = round(
            on["p99_ms"] / max(off["p99_ms"], 1e-9), 4)
    return out


def measure_rules_overhead(clients=8, duration_s=2.5,
                           rule_interval_s=1.0):
    """The dashboard-conversion win (recording rules, filodb_tpu/rules):
    the SAME dashboard aggregate measured two ways on one live server —
    (a) as a direct warm-cache query over the raw counters, and (b) as
    a one-series read of the recording rule's precomputed output from
    /promql/__rules__. The rule converts the per-user rate() work into
    O(rules) background ticks, so (b) should serve at >= the direct
    warm-cache qps while the standing cost is the rule-tick duty cycle
    (reported from the engine's own filodb_rule_tick_seconds
    histogram, noise-free)."""
    out = {"clients": clients, "rule_interval_s": rule_interval_s}
    # seed AT wall-now: rule ticks evaluate at now and must see data
    now_s = int(time.time())
    seed_start = (now_s - SEED_SAMPLES * 10) * 1000
    port = _free_port()
    cfg = {
        "num-shards": 4, "port": port, "gateway-port": None,
        "seed-dev-data": True, "seed-start-ms": seed_start,
        "seed-samples": SEED_SAMPLES, "seed-instances": N_INSTANCES,
        "query-sample-limit": 0, "query-series-limit": 0,
        "max-inflight-queries": 8, "grpc-port": None,
        # old steps settle fast so consecutive rule ticks are
        # cache-warm tail recomputes
        "results-cache-hot-window-ms": 2_000.0,
        "rules-eval-span-steps": 8,
        "rules": {"groups": [{
            "name": "bench", "interval": rule_interval_s, "rules": [
                {"record": "bench:req:rate5m",
                 "expr": "sum(rate(http_requests_total[5m]))"}]}]},
    }
    proc, _line = _spawn_node(cfg)
    try:
        # let the engine tick a few times (first ticks create the
        # internal series — a one-time transient)
        time.sleep(4 * rule_interval_s)

        # both paths use the BENCH_r08 dashboard methodology: a
        # SLIDING window (refresh interval shorter than the step, so
        # most refreshes repeat the window and a slide recomputes only
        # the tail). The direct path's tail recompute re-runs rate()
        # over every instance's counter; the recorded path's tail is
        # one precomputed series — that asymmetry IS the conversion.
        SLIDE_S = 0.5
        t_base = time.perf_counter()
        d_base = now_s - 3000

        def one_direct(cl):
            slide = int((time.perf_counter() - t_base) / SLIDE_S)
            start = d_base + (slide % 20) * 60
            t0 = time.perf_counter()
            raw = cl.get_raw(
                "/promql/timeseries/api/v1/query_range",
                query="sum(rate(http_requests_total[5m]))",
                start=start, end=start + 1800, step=60)
            dt = time.perf_counter() - t0
            assert raw.startswith(b'{"status":"success"'), raw[:120]
            return dt

        def one_recorded(cl):
            # the recorded series' natural dashboard: the window
            # slides with the wall clock at the rule's own cadence
            now = int(time.time())
            t0 = time.perf_counter()
            raw = cl.get_raw(
                "/promql/__rules__/api/v1/query_range",
                query="bench:req:rate5m",
                start=now - 90, end=now - 2,
                step=max(1, int(rule_interval_s)))
            dt = time.perf_counter() - t0
            assert raw.startswith(b'{"status":"success"'), raw[:120]
            return dt

        def run_level(one):
            lats = []
            lock = threading.Lock()
            t_end = [0.0]

            def loop(cid):
                time.sleep(cid * 0.002)
                cl = KeepAliveClient(port)
                while time.perf_counter() < t_end[0]:
                    dt = one(cl)
                    with lock:
                        lats.append(dt)
                cl.close()
            t0 = time.perf_counter()
            t_end[0] = t0 + duration_s
            threads = [threading.Thread(target=loop, args=(c,))
                       for c in range(clients)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = time.perf_counter() - t0
            lats_ms = np.asarray(lats) * 1000
            return {"queries": len(lats),
                    "qps": round(len(lats) / wall, 1),
                    "p50_ms": round(float(np.percentile(lats_ms, 50)),
                                    2),
                    "p99_ms": round(float(np.percentile(lats_ms, 99)),
                                    2)}

        warm = KeepAliveClient(port)
        for _ in range(4):          # compile + warm both shapes
            one_direct(warm)
            one_recorded(warm)
        # interleaved trials, warm-up dropped (the selfmon-bench
        # methodology: single trials swing +/-20% on a 1-core rig)
        runs = {"direct_warm_cache": [], "recorded_series": []}
        for t in range(3):
            order = (("direct_warm_cache", one_direct),
                     ("recorded_series", one_recorded)) if t % 2 == 0 \
                else (("recorded_series", one_recorded),
                      ("direct_warm_cache", one_direct))
            for name, fn in order:
                runs[name].append(run_level(fn))
        for name, rs in runs.items():
            steady = rs[1:] if len(rs) > 1 else rs
            out[name] = {
                "qps": round(sum(r["qps"] for r in steady)
                             / len(steady), 1),
                "p50_ms": round(sum(r["p50_ms"] for r in steady)
                                / len(steady), 2),
                "p99_ms": round(sum(r["p99_ms"] for r in steady)
                                / len(steady), 2),
                "all_qps": [r["qps"] for r in rs],
            }
        out["qps_ratio_recorded_vs_direct"] = round(
            out["recorded_series"]["qps"]
            / max(out["direct_warm_cache"]["qps"], 1e-9), 3)
        # the standing cost, from the engine's own histogram: mean
        # tick wall seconds / interval = duty cycle
        tick_sum = _scrape_metric(warm, "rule_tick_seconds_sum")
        tick_n = _scrape_metric(warm, "rule_tick_seconds_count")
        if tick_n:
            out["rule_ticks"] = int(tick_n)
            out["tick_ms_avg"] = round(1000 * tick_sum / tick_n, 2)
            out["rule_duty_cycle"] = round(
                (tick_sum / tick_n) / rule_interval_s, 5)
        out["rule_samples_written"] = _scrape_metric(
            warm, "rule_samples_written_total")
        warm.close()
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=20)
        except subprocess.TimeoutExpired:
            proc.kill()
    return out


def main():
    # focused runs: `python bench_e2e.py profiler_overhead ...` runs
    # only the named measure_* sections (a full run takes minutes; the
    # per-PR BENCH files usually pin one section)
    sections = sys.argv[1:]
    if sections:
        out = {}
        for name in sections:
            fn = globals().get(f"measure_{name}")
            if fn is None:
                raise SystemExit(f"unknown section {name!r}")
            out[name] = fn()
        print(json.dumps(out))
        return
    out = measure()
    try:
        out["worker_sweep"] = measure_worker_sweep()
    except Exception as e:  # noqa: BLE001 — the sweep must not void
        out["worker_sweep"] = {"error": repr(e)}    # the main bench
    try:
        out["noisy_neighbor"] = measure_noisy_neighbor()
    except Exception as e:  # noqa: BLE001
        out["noisy_neighbor"] = {"error": repr(e)}
    try:
        out["selfmon_overhead"] = measure_selfmon_overhead()
    except Exception as e:  # noqa: BLE001
        out["selfmon_overhead"] = {"error": repr(e)}
    try:
        out["rules_overhead"] = measure_rules_overhead()
    except Exception as e:  # noqa: BLE001
        out["rules_overhead"] = {"error": repr(e)}
    try:
        out["profiler_overhead"] = measure_profiler_overhead()
    except Exception as e:  # noqa: BLE001
        out["profiler_overhead"] = {"error": repr(e)}
    print(json.dumps(out))


if __name__ == "__main__":
    main()
