"""Dev harness: per-config group-sum kernel timings on the big tiles."""
import sys
import time

import numpy as np
import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402

import bench as B  # noqa: E402
from filodb_tpu.query import pallas_kernels as pk  # noqa: E402
from filodb_tpu.query import tilestore as tst  # noqa: E402

S, N, DT, WINDOW, STEP, NG = B.S, B.N, B.DT, B.WINDOW, B.STEP, B.N_GROUPS
BASE = B.BASE


def mark(m):
    print(f"[{time.strftime('%H:%M:%S')}] {m}", flush=True)


def main():
    ts, vals = B._gen_device()
    tiles = tst.AlignedTiles([{} for _ in range(S)], BASE, DT,
                             np.ones((S, N), bool), ts, vals)
    del ts, vals
    ST = STEP // DT
    DSPAN = WINDOW // STEP
    cv_t = tiles.t_channel("cv")
    cv_t.block_until_ready()
    tiles._channels.clear()
    tiles.vals = None
    v_p = tiles.t_perm_fixed_tiled("cv", ST)
    base = tiles.t_fixed_base("cv")
    v_p.block_until_ready()
    del cv_t
    tiles.ts = tiles.valid = None
    tiles._tch.clear()
    tiles._tperm.clear()
    T = (N * DT - WINDOW - 300_000) // STEP
    onehot = jnp.zeros((S, NG), jnp.float32).at[
        jnp.arange(S), jnp.arange(S) // (S // NG)].set(1.0)
    noop = jax.jit(lambda x: jnp.zeros((NG, T), jnp.float32) + x)
    np.asarray(noop(jnp.float32(0)))

    K = 32

    def chain(hi, lo):
        @jax.jit
        def many(shift, v_p, base, oh):
            acc = jnp.zeros((T, NG), jnp.float32)
            kl0s = jnp.arange(K, dtype=jnp.int32) + shift
            w0es = (jnp.arange(K, dtype=jnp.int32) + shift) * DT + WINDOW

            def body(a, p):
                kl0, w0e = p
                s_, c_ = pk.counter_groupsum(
                    "rate", ST, DSPAN, hi, lo, v_p, base, oh,
                    kl0, w0e, WINDOW, STEP, T)
                return a + jnp.where(c_ > 0, s_, 0.0), jnp.int32(0)
            acc, _ = jax.lax.scan(body, acc, (kl0s, w0es))
            return acc
        return many

    for name, hi, lo in (("BOTH/BOTH", pk.GS_BOTH, pk.GS_BOTH),
                         ("CUR/ALT", pk.GS_CUR, pk.GS_ALT)):
        many = chain(hi, lo)
        mark(f"compile {name}")
        np.asarray(many(jnp.int32(0), v_p, base, onehot))
        mark(f"compiled {name}")
        best = []
        for i in range(4):
            fl = min(B._timed(lambda: np.asarray(noop(jnp.float32(j))))
                     for j in range(2))
            t = B._timed(lambda: np.asarray(
                many(jnp.int32(1 + i), v_p, base, onehot)))
            best.append(max(t - min(fl, t * 0.5), t * 0.05) / K)
        ms = np.median(best) * 1000
        mark(f"{name}: {ms:.2f} ms/query")


if __name__ == "__main__":
    main()
