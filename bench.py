"""Benchmark: PromQL `sum(rate(counter[5m])) by (job)` samples-scanned/sec
on device (the BASELINE.json north-star workload, promperf shape —
reference harness: jmh/src/main/scala/filodb.jmh/QueryInMemoryBenchmark.scala,
which also measures queries over a WARM in-memory store).

Path measured: the aligned device tile store (filodb_tpu.query.tilestore) —
pack-time prefix/fill precomputation, query-time shared-column selection +
extrapolated-rate epilogue + grouped MXU aggregation, all one XLA program.

Timing notes: the axon tunnel adds ~0.1s per host sync and transfers at
~27 MB/s, so K queries (shifted step grids) are chained inside one program
with a tiny [G, T] output, the sync floor is subtracted, and the cost is
amortized. Prints ONE JSON line. vs_baseline = device throughput / numpy
oracle (CPU reference path) throughput, since the reference publishes no
absolute numbers (BASELINE.md).
"""

import json
import time

import numpy as np

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402

S, N, T = 65_536, 512, 180
N_GROUPS = 16
DT = 10_000
WINDOW = 300_000
STEP = 60_000
K = 20


def _timed(fn):
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


def _gen(seed=42):
    rng = np.random.default_rng(seed)
    ts = np.sort((np.arange(1, N + 1, dtype=np.int64) * DT)[None, :]
                 + rng.integers(-2000, 2000, (S, N)), axis=1)
    vals = np.cumsum(rng.uniform(0.0, 5.0, (S, N)), axis=1)
    return ts, vals


def main():
    from filodb_tpu.query import tilestore as tst

    ts, vals = _gen()
    tiles = tst.AlignedTiles([{} for _ in range(S)], DT, DT,
                             np.ones((S, N), bool),
                             ts.astype(np.float64), vals)
    arrs = tst._tiles_arrays(tiles, "rate")
    gids = jnp.asarray((np.arange(S) % N_GROUPS).astype(np.int32))

    consts = tuple(jnp.asarray(np.int64(v)) for v in
                   (tiles.num_slots, tiles.base_ms, tiles.dt_ms))

    @jax.jit
    def many(arrs, gids, w0s, w0e, step):
        onehot = (gids[:, None] == jnp.arange(N_GROUPS)[None, :]
                  ).astype(jnp.float64)
        acc = jnp.zeros((N_GROUPS, T))
        for k in range(K):
            local = tst._eval_core("rate", T, arrs, *consts,
                                   w0s + k * 1000, w0e + k * 1000, step)
            ok = ~jnp.isnan(local)
            acc = acc + jnp.where(
                onehot.T @ ok.astype(jnp.float64) > 0,
                onehot.T @ jnp.where(ok, local, 0.0), 0.0)
        return acc

    # empirical host-sync floor: a trivial program with the same output
    # shape (the axon tunnel adds ~0.1s RTT; locally this is ~0)
    noop = jax.jit(lambda g: jnp.zeros((N_GROUPS, T)) + g[0])
    np.asarray(noop(gids))
    floor = min(_timed(lambda: np.asarray(noop(gids))) for _ in range(3))

    args = (jnp.asarray(np.int64(0)), jnp.asarray(np.int64(WINDOW)),
            jnp.asarray(np.int64(STEP)))
    np.asarray(many(arrs, gids, *args))          # compile + pack warm
    best = float("inf")
    for _ in range(3):
        best = min(best, _timed(lambda: np.asarray(many(arrs, gids, *args))))
    per_query = max(best - min(floor, best * 0.5), best * 0.05) / K
    device_sps = S * N / per_query

    # CPU numpy-oracle on a subsample, extrapolated (reference exec path)
    from filodb_tpu.query import rangefn as rf
    S_cpu = 512
    t0 = time.perf_counter()
    acc = np.zeros(T)
    for i in range(S_cpu):
        row = rf.evaluate("rate", ts[i], vals[i], WINDOW, STEP,
                          WINDOW + (T - 1) * STEP, WINDOW)
        acc += np.where(np.isnan(row), 0.0, row)
    oracle_sps = S_cpu * N / (time.perf_counter() - t0)

    print(json.dumps({
        "metric": "rate_sum_by_samples_scanned_per_sec",
        "value": round(device_sps),
        "unit": "samples/s",
        "vs_baseline": round(device_sps / oracle_sps, 2),
    }))


if __name__ == "__main__":
    main()
