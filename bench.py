"""Benchmark: PromQL `sum(rate(counter[5m])) by (job)` samples-scanned/sec
on device (the BASELINE.json north-star workload, promperf shape —
reference harness: jmh/src/main/scala/filodb.jmh/QueryInMemoryBenchmark.scala).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "samples/s", "vs_baseline": N}
vs_baseline = device throughput / numpy-oracle (CPU reference path)
throughput, since the reference publishes no absolute numbers
(BASELINE.md: its contract is the harness, not results).
"""

import json
import time

import numpy as np

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402


def _gen_tiles(S, N, seed=42):
    """Counter series tiles [S, N] at 10s cadence with jittered phase."""
    rng = np.random.default_rng(seed)
    dt = 10_000
    ts = (np.arange(N, dtype=np.int64) * dt)[None, :] \
        + rng.integers(0, dt, (S, 1))
    vals = np.cumsum(rng.uniform(0.0, 5.0, (S, N)), axis=1)
    lens = np.full(S, N, dtype=np.int32)
    return ts, vals, lens


def main():
    from filodb_tpu.query.tpu import _window_endpoint
    from __graft_entry__ import _rate_sum_step

    S, N = 65_536, 512            # 33.5M samples scanned per query
    n_groups = 16
    T = 180                       # 3h of 1-minute output steps
    window_ms = 300_000
    ts, vals, lens = _gen_tiles(S, N)
    gids = (np.arange(S) % n_groups).astype(np.int32)
    step_ms = 60_000
    wend = np.int64(window_ms) + np.arange(T, dtype=np.int64) * step_ms
    wstart = wend - window_ms

    dev_args = tuple(jax.device_put(jnp.asarray(a))
                     for a in (ts, vals, lens, gids)) + (
        jnp.asarray(wstart[0]), jnp.asarray(wend[0]),
        jnp.asarray(np.int64(step_ms)))
    fn = jax.jit(_rate_sum_step(n_groups, T))
    np.asarray(fn(*dev_args))                  # compile + settle
    iters = 10
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*dev_args)
    np.asarray(out)                            # host sync (tunnel-safe)
    dt_dev = (time.perf_counter() - t0) / iters
    device_sps = S * N / dt_dev

    # CPU numpy-oracle on a subsample, extrapolated (reference exec path)
    from filodb_tpu.query import rangefn as rf
    S_cpu = 512
    t0 = time.perf_counter()
    acc = np.zeros(T)
    for i in range(S_cpu):
        row = rf.evaluate("rate", ts[i], vals[i], int(wend[0]), step_ms,
                          int(wend[-1]), window_ms)
        acc += np.where(np.isnan(row), 0.0, row)
    dt_cpu = time.perf_counter() - t0
    oracle_sps = S_cpu * N / dt_cpu

    print(json.dumps({
        "metric": "rate_sum_by_samples_scanned_per_sec",
        "value": round(device_sps),
        "unit": "samples/s",
        "vs_baseline": round(device_sps / oracle_sps, 2),
    }))


if __name__ == "__main__":
    main()
