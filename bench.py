"""Benchmark: PromQL `sum(rate(counter[5m])) by (job)` on device — the
BASELINE.json north-star workload at a scaled shape.

Shape: 65,536 series x 8h at 10s scrape (2,880 samples) = 188.7M samples
resident as aligned device tiles; the query grid covers the whole span
(475 steps at 60s, 5m windows). This is 1/57th of the full north star
(10M series x 24h on v5e-8); the printed extrapolation states what the
measured per-chip throughput implies for that target.

Path measured: the production fused Pallas group-sum kernel
(`pallas_kernels.counter_groupsum`, dispatched by
`tilestore.groupsum_counters`): the whole `sum by` of `rate` runs as
ONE pass — per step-tile, the 4 boundary row-families are DMA'd
HBM->VMEM as contiguous blocks of the s-tile-major stride-permuted
channels (double-buffered), the f32 extrapolation epilogue runs in
VMEM on int32 relative timestamps + exact 3xf32-split boundary deltas,
and group sums/counts leave the chip as [T, G] only. Parity vs the f64
oracle is pinned at 1e-5 relative by tests/test_tilestore.py (XLA
formulations of the same computation measured 5.5-12ms/query: row
gathers run at ~140 GB/s, and the [T, S] rate intermediate + its
grouping consumers cost an extra materialization pass).

Honesty notes:
- Data is generated ON DEVICE (the axon tunnel moves ~27 MB/s; shipping
  3 GB of tiles would swamp the measurement). Tile build + compile are
  excluded (warm store, like the reference's QueryInMemoryBenchmark
  which also measures a warm in-memory store).
- K queries with shifted step grids are chained in one program and the
  empirical host-sync floor — re-sampled right before every rep, since
  tunnel latency drifts tens of ms — is subtracted, because one tunnel
  roundtrip (~0.1s) would otherwise dominate a ~5ms query.
- `vs_baseline` divides by a BATCHED numpy oracle (the same aligned
  prefix-sum/boundary algorithm vectorized over a 8,192-series
  subsample, no per-series Python loop), not an interpreter-bound loop.

Prints ONE JSON line.
"""

import json
import time

import numpy as np

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402

S = 65_536          # series
N = 2_880           # slots = 8h at 10s
DT = 10_000
WINDOW = 300_000
STEP = 60_000
N_GROUPS = 16
K = 16              # chained shifted-grid queries
BASE = 1_600_000_000_000


def _timed(fn):
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


def _gen_device():
    """Tiles generated on device: jittered timestamps + counter values."""
    key = jax.random.PRNGKey(42)
    k1, k2 = jax.random.split(key)

    @jax.jit
    def gen():
        jit_ms = jax.random.uniform(k1, (S, N), dtype=jnp.float64,
                                    minval=-2000, maxval=2000)
        ts = BASE + jnp.arange(N, dtype=jnp.float64)[None, :] * DT + jit_ms
        incs = jax.random.uniform(k2, (S, N), dtype=jnp.float64,
                                  minval=0.0, maxval=5.0)
        vals = jnp.cumsum(incs, axis=1)
        return ts, vals
    ts, vals = gen()
    return jax.block_until_ready(ts), jax.block_until_ready(vals)


def main():
    from filodb_tpu.query import tilestore as tst

    from filodb_tpu.query import pallas_kernels as pk

    ts, vals = _gen_device()
    tiles = tst.AlignedTiles([{} for _ in range(S)], BASE, DT,
                             np.ones((S, N), bool), ts, vals)
    del ts, vals
    # warm the kernel's s-tile-major stride-permuted channels (tile-store
    # pack time, excluded like the reference's warm store), staged so
    # intermediates free before the next build step (the full chain would
    # transiently exceed HBM at this shape)
    ST = STEP // DT
    cv_t = tiles.t_channel("cv")
    cv_t.block_until_ready()
    tiles._channels.clear()
    tiles.vals = None                       # cv is cached transposed
    v_p = tiles.t_perm_split_tiled("cv", ST)   # needs ts/valid (ts plane)
    v_p.block_until_ready()
    del cv_t
    tiles.ts = tiles.valid = None
    tiles._tch.clear()
    tiles._tperm.clear()

    T = (N * DT - WINDOW) // STEP           # grid covers the whole span
    SG = S // N_GROUPS                      # group-contiguous series
    onehot = jnp.zeros((S, N_GROUPS), jnp.float32).at[
        jnp.arange(S), jnp.arange(S) // SG].set(1.0)
    w0e0 = BASE + WINDOW

    @jax.jit
    def many(shift, v_p, oh):
        acc = jnp.zeros((T, N_GROUPS), jnp.float32)
        for k in range(K):
            w0e = w0e0 + shift + k * 1000
            w0s = w0e - WINDOW
            kc0 = jnp.floor((w0e - BASE + DT / 2.0) / DT).astype(jnp.int32)
            kl0 = jnp.ceil((w0s - BASE - DT / 2.0) / DT).astype(jnp.int32)
            sums, cnts = pk.counter_groupsum(
                "rate", ST, v_p, oh, kc0, kl0,
                (w0e - BASE).astype(jnp.int32), WINDOW, STEP, T)
            acc = acc + jnp.where(cnts > 0, sums, 0.0)
        return acc.T

    noop = jax.jit(lambda x: jnp.zeros((N_GROUPS, T), jnp.float32) + x)
    np.asarray(noop(jnp.float32(0)))

    np.asarray(many(jnp.int64(0), v_p, onehot))   # compile
    runs = []
    for i in range(5):
        # the tunnel's host-sync floor drifts tens of ms between reps;
        # sample it fresh right before each measurement
        floor = min(_timed(lambda: np.asarray(noop(jnp.float32(j))))
                    for j in range(2))
        t = _timed(lambda: np.asarray(
            many(jnp.int64(i * 1000), v_p, onehot)))
        runs.append(max(t - min(floor, t * 0.5), t * 0.05) / K)
    per_query_p50 = float(np.median(runs))
    device_sps = S * N / per_query_p50

    # bytes the kernel actually reads per query: 4 boundary families x
    # (i32 ts + packed 3xf32 values), each DMA block carrying the
    # (TT+AL)/TT sublane-alignment overhead
    touched = int(T * S * 4 * (4 + 12)
                  * (pk._GS_TT + pk._GS_AL) / pk._GS_TT)
    hbm_gbps = touched / per_query_p50 / 1e9

    # batched numpy oracle (same algorithm, vectorized, subsampled)
    S_cpu = 8_192
    # un-permute the ts plane (bitcast f32 lanes 0:SS) of the packed
    # tile: [n_s, st, G, 4SS] with slot k of series (si*SS + j) at
    # [si, k % st, k // st, j]
    n_keep = S_cpu // pk._GS_SS
    perm_h = np.asarray(v_p[:n_keep, :, :, :pk._GS_SS])
    ts_h = perm_h.transpose(0, 3, 2, 1).reshape(
        S_cpu, -1)[:, :N].astype(np.float64) + BASE
    vals_raw = _gen_vals_host(S_cpu)
    vals_h = vals_raw
    t0 = time.perf_counter()
    _oracle_batched(ts_h, vals_h, T)
    oracle_sps = S_cpu * N / (time.perf_counter() - t0)

    full_series = 10_000_000
    full_samples = full_series * 8_640      # 24h at 10s
    chips = 8
    est_full_ms = full_samples / chips / device_sps * 1000.0

    # free the query tiles, then fold the ingest + downsample-batch
    # regression guards into the same driver-captured line (BASELINE.md
    # targets #2/#3; jmh IngestionBenchmark + spark BatchDownsampler)
    del v_p, tiles
    import bench_downsample
    import bench_ingest
    ing = bench_ingest.measure()
    ds = bench_downsample.measure(batches_total=1, reps=1)

    print(json.dumps({
        "metric": "rate_sum_by_samples_scanned_per_sec",
        "value": round(device_sps),
        "unit": "samples/s",
        "vs_baseline": round(device_sps / oracle_sps, 2),
        "per_query_p50_ms": round(per_query_p50 * 1000, 2),
        "shape": f"{S}x{N} (8h@10s), T={T}, window=5m",
        "hbm_read_gbps": round(hbm_gbps, 1),
        "northstar_est_ms_v5e8": round(est_full_ms, 1),
        "ingest_samples_per_s": ing["value"],
        "ingest_encode_samples_per_s": ing["encode_samples_per_s"],
        "downsample_samples_per_s": ds["value"],
        "downsample_batch_samples": ds["total_samples"],
    }))


def _gen_vals_host(s_cpu):
    """Regenerate the first s_cpu series' RAW values host-side for the
    oracle (the device tiles hold the reset-corrected channel)."""
    key = jax.random.PRNGKey(42)
    _, k2 = jax.random.split(key)
    incs = jax.random.uniform(k2, (S, N), dtype=jnp.float64,
                              minval=0.0, maxval=5.0)[:s_cpu]
    return np.cumsum(np.asarray(incs), axis=1)


def _oracle_batched(ts, vals, T):
    """Batched numpy rate + grouped sum: the aligned-slot algorithm with
    fancy indexing — no per-series Python loop."""
    Sb, Nb = vals.shape
    prev = np.concatenate([np.full((Sb, 1), np.nan), vals[:, :-1]], axis=1)
    drop = vals < prev
    cv = vals + np.cumsum(np.where(drop, prev, 0.0), axis=1)
    ps = np.concatenate([np.zeros((Sb, 1)), np.cumsum(
        np.ones_like(vals), axis=1)], axis=1)
    t = np.arange(T, dtype=np.int64)
    wend = BASE + WINDOW + t * STEP
    wstart = wend - WINDOW
    k_hi = np.floor((wend - BASE + DT / 2.0) / DT).astype(np.int64)
    k_lo = np.ceil((wstart - BASE - DT / 2.0) / DT).astype(np.int64)
    khc = np.clip(k_hi, 0, Nb - 1)
    khp = np.clip(k_hi - 1, 0, Nb - 1)
    klc = np.clip(k_lo, 0, Nb - 1)
    kln = np.clip(k_lo + 1, 0, Nb - 1)
    cnt = ps[:, np.clip(k_hi, -1, Nb - 1) + 1] - ps[:, np.clip(k_lo, 0, Nb)]
    cnt -= (ts[:, khc] > wend[None, :])
    cnt -= (ts[:, klc] < wstart[None, :])
    use1 = ts[:, khc] <= wend[None, :]
    t2 = np.where(use1, ts[:, khc], ts[:, khp])
    v2 = np.where(use1, cv[:, khc], cv[:, khp])
    useb = ts[:, klc] >= wstart[None, :]
    t1 = np.where(useb, ts[:, klc], ts[:, kln])
    v1 = np.where(useb, cv[:, klc], cv[:, kln])
    sampled = (t2 - t1) / 1000.0
    delta = v2 - v1
    with np.errstate(all="ignore"):
        avg = sampled / (cnt - 1.0)
        ds = np.minimum((t1 - wstart[None, :]) / 1000.0,
                        np.where(delta > 0, sampled * v1 / delta, np.inf))
        de = (wend[None, :] - t2) / 1000.0
        ext = sampled + np.minimum(ds, avg * 1.1) + np.minimum(de, avg * 1.1)
        rate = delta * (ext / sampled) / (WINDOW / 1000.0)
        rate = np.where(cnt >= 2, rate, np.nan)
    g = Sb // N_GROUPS
    ok = ~np.isnan(rate)
    return np.where(ok, rate, 0.0).reshape(N_GROUPS, g, T).sum(axis=1)


if __name__ == "__main__":
    main()
