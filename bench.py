"""Benchmark: PromQL `sum(rate(counter[5m])) by (job)` on device — the
BASELINE.json north-star workload at a scaled shape.

Shape: 65,536 series x 8h at 10s scrape (2,880 samples) = 188.7M samples
resident as aligned device tiles; the query grid covers the whole span
(475 steps at 60s, 5m windows). This is 1/57th of the full north star
(10M series x 24h on v5e-8); the printed extrapolation states what the
measured per-chip throughput implies for that target.

Path measured: the production fused Pallas group-sum kernel
(`pallas_kernels.counter_groupsum`, dispatched by
`tilestore.groupsum_counters`): the whole `sum by` of `rate` runs as
ONE pass — per step-tile, the window-end and window-start boundary
families ride ONE merged DMA (they share a stride-residue plane when
the window is a whole number of steps), the jitter-fallback families
are separate streams only for queries whose grid phase straddles the
tile's max scrape jitter, the f32 extrapolation epilogue runs in VMEM
on int32 relative timestamps + exact 2xint32 fixed-point boundary
deltas, and group sums/counts leave the chip as [T, G] only. The K
chained queries sweep grid phases 0..±5s, so the measured mix
exercises both the full 3-stream path and the phase-elided 2-stream
path the way a population of dashboards would. Parity vs the f64
oracle is asserted ON DEVICE every run (parity_max_rel_err below; the
compiled Mosaic kernel's group sums vs the same-algorithm numpy f64
oracle at 1e-5), so a miscompile cannot ship a green number. XLA
formulations of the same computation measured 5.5-12ms/query: row
gathers run at ~140 GB/s, and the [T, S] rate intermediate + its
grouping consumers cost an extra materialization pass.

Honesty notes:
- Data is generated ON DEVICE (the axon tunnel moves ~27 MB/s; shipping
  3 GB of tiles would swamp the measurement). Tile build + compile are
  excluded (warm store, like the reference's QueryInMemoryBenchmark
  which also measures a warm in-memory store).
- K queries with shifted step grids are chained in one program and the
  empirical host-sync floor — re-sampled right before every rep, since
  tunnel latency drifts tens of ms — is subtracted, because one tunnel
  roundtrip (~0.1s) would otherwise dominate a ~5ms query.
- `vs_baseline` divides by a BATCHED numpy oracle (the same aligned
  prefix-sum/boundary algorithm vectorized over a 8,192-series
  subsample, no per-series Python loop), not an interpreter-bound loop.

Prints ONE JSON line.
"""

import json
import sys
import time

import numpy as np


def _mark(msg):
    print(f"[bench {time.strftime('%H:%M:%S')}] {msg}", file=sys.stderr,
          flush=True)

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402

S = 65_536          # series
N = 2_880           # slots = 8h at 10s
DT = 10_000
WINDOW = 300_000
STEP = 60_000
N_GROUPS = 16
K = 32              # chained shifted-grid queries (large enough that the
#                     chain dwarfs the tunnel's host-sync floor)
BASE = 1_600_000_000_000


def _timed(fn):
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


def _gen_device():
    """Tiles generated on device: jittered timestamps + counter values."""
    key = jax.random.PRNGKey(42)
    k1, k2 = jax.random.split(key)

    @jax.jit
    def gen():
        jit_ms = jax.random.uniform(k1, (S, N), dtype=jnp.float64,
                                    minval=-2000, maxval=2000)
        ts = BASE + jnp.arange(N, dtype=jnp.float64)[None, :] * DT + jit_ms
        incs = jax.random.uniform(k2, (S, N), dtype=jnp.float64,
                                  minval=0.0, maxval=5.0)
        vals = jnp.cumsum(incs, axis=1)
        return ts, vals
    ts, vals = gen()
    return jax.block_until_ready(ts), jax.block_until_ready(vals)


def main():
    from filodb_tpu.query import tilestore as tst

    from filodb_tpu.query import pallas_kernels as pk

    ts, vals = _gen_device()
    tiles = tst.AlignedTiles([{} for _ in range(S)], BASE, DT,
                             np.ones((S, N), bool), ts, vals)
    del ts, vals
    # warm the kernel's s-tile-major stride-permuted channels (tile-store
    # pack time, excluded like the reference's warm store), staged so
    # intermediates free before the next build step (the full chain would
    # transiently exceed HBM at this shape)
    ST = STEP // DT
    DSPAN = WINDOW // STEP
    J = 2000                                # generator's jitter bound
    cv_t = tiles.t_channel("cv")
    cv_t.block_until_ready()
    tiles._channels.clear()
    tiles.vals = None                       # cv is cached transposed
    v_p = tiles.t_perm_fixed_tiled("cv", ST)   # needs ts/valid (ts plane)
    base = tiles.t_fixed_base("cv")
    v_p.block_until_ready()
    del cv_t
    tiles.ts = tiles.valid = None
    tiles._tch.clear()
    tiles._tperm.clear()

    # grid covers the whole span, minus headroom for the per-rep
    # whole-slot shifts (max 28 slots) and the per-query phase offsets
    T = (N * DT - WINDOW - 300_000) // STEP
    SG = S // N_GROUPS                      # group-contiguous series
    onehot = jnp.zeros((S, N_GROUPS), jnp.float32).at[
        jnp.arange(S), jnp.arange(S) // SG].set(1.0)

    # the K chained queries shift the grid phase by 0..15s in 1s steps
    # (static per query, like distinct dashboards); the per-rep shift
    # moves whole slots so each rep reads different tile rows. Modes are
    # the same static jitter-phase elision groupsum_counters derives
    # (bench calls the kernel directly because kc0/kl0 stay traced).
    def _modes(o_k):
        c_k = (o_k + DT // 2) // DT
        phase = o_k - c_k * DT              # == w0e_rel - kc0*DT
        hi = (pk.GS_CUR if phase >= J else
              pk.GS_ALT if phase < -J else pk.GS_BOTH)
        lo = (pk.GS_CUR if -phase >= J else
              pk.GS_ALT if -phase < -J else pk.GS_BOTH)
        return c_k, hi, lo

    # group the K phase configs by their static mode pair so each pair
    # compiles ONE Pallas kernel (driven by lax.scan over the per-query
    # slot/phase params) instead of K instantiations
    groups: dict = {}
    for k in range(K):
        c_k, hi_mode, lo_mode = _modes(k * 1000)
        groups.setdefault((hi_mode, lo_mode), []).append((k, c_k))

    @jax.jit
    def many(shift_slots, v_p, base, oh):
        acc = jnp.zeros((T, N_GROUPS), jnp.float32)
        for (hi_mode, lo_mode), ks in sorted(groups.items()):
            kl0s = jnp.asarray([WINDOW // DT + c_k - DSPAN * ST
                                for _, c_k in ks], jnp.int32) \
                + shift_slots
            w0es = jnp.asarray([WINDOW + o * 1000 for o, _ in ks],
                               jnp.int32) + shift_slots * DT

            def body(a, p, hi=hi_mode, lo=lo_mode):
                kl0, w0e_rel = p
                sums, cnts = pk.counter_groupsum(
                    "rate", ST, DSPAN, hi, lo, v_p, base, oh,
                    kl0, w0e_rel, WINDOW, STEP, T)
                return a + jnp.where(cnts > 0, sums, 0.0), jnp.int32(0)
            acc, _ = jax.lax.scan(body, acc, (kl0s, w0es))
        return acc.T

    noop = jax.jit(lambda x: jnp.zeros((N_GROUPS, T), jnp.float32) + x)
    np.asarray(noop(jnp.float32(0)))

    _mark("compiling query chain")
    np.asarray(many(jnp.int32(0), v_p, base, onehot))   # compile
    _mark("compiled; measuring")
    runs = []
    for i in range(5):
        # the tunnel's host-sync floor drifts tens of ms between reps;
        # sample it fresh right before each measurement
        floor = min(_timed(lambda: np.asarray(noop(jnp.float32(j))))
                    for j in range(2))
        t = _timed(lambda: np.asarray(
            many(jnp.int32(i * 7), v_p, base, onehot)))
        runs.append(max(t - min(floor, t * 0.5), t * 0.05) / K)
    per_query_p50 = float(np.median(runs))
    # samples one query's windows cover: the union of T sliding windows
    # of DSPAN*ST+1 slots stepping ST
    scanned = S * (DSPAN * ST + 1 + (T - 1) * ST)
    device_sps = scanned / per_query_p50

    # bytes the kernel actually reads per query, averaged over the K
    # phase configs: the merged kc/kl stream always, plus one
    # (tt+AL)-row fallback stream per non-elided side; 3 planes (i32
    # ts + fixed-point hi/lo) per row. tt/pipeline depth are per-query
    # (the _gs_pipeline chooser widens tiles when VMEM allows).
    rows_per_step = 0.0
    for k in range(K):
        _, hi_mode, lo_mode = _modes(k * 1000)
        tt_k, _nb = pk._gs_pipeline(ST, DSPAN, hi_mode, lo_mode, T,
                                    N_GROUPS)
        mlen_k = pk._gs_mlen(ST, DSPAN, tt_k)
        rows_per_step += (mlen_k + (tt_k + pk._GS_AL)
                          * ((hi_mode != pk.GS_CUR)
                             + (lo_mode != pk.GS_CUR))) / tt_k
    touched = int(T * S * 12 * (rows_per_step / K))
    hbm_gbps = touched / per_query_p50 / 1e9

    # --- on-device compiled-kernel parity gate -------------------------
    # the SAME compiled kernel shape (masked one-hot selecting the first
    # S_par series into 16 contiguous groups) vs the numpy f64 oracle;
    # guards the only link tests can't cover: Mosaic compilation on the
    # real chip (tests run the kernel in interpret mode)
    S_par = 8_192
    gpar = S_par // N_GROUPS
    oh_par = jnp.zeros((S, N_GROUPS), jnp.float32).at[
        jnp.arange(S_par), jnp.arange(S_par) // gpar].set(1.0)

    @jax.jit
    def one_query(v_p, base, oh):
        kc0 = jnp.int32(WINDOW // DT)
        return pk.counter_groupsum(
            "rate", ST, DSPAN, pk.GS_BOTH, pk.GS_BOTH, v_p, base, oh,
            kc0 - DSPAN * ST, jnp.int32(WINDOW), WINDOW, STEP, T)

    _mark("parity gate")
    sums_par, cnts_par = one_query(v_p, base, oh_par)
    sums_par = np.asarray(sums_par)

    # batched numpy oracle (same algorithm, vectorized, subsampled) —
    # doubles as the parity reference for the on-device gate above
    S_cpu = S_par
    # un-permute the ts plane (lanes 0:SS) of the packed tile:
    # [n_s, st, G, 3SS] with slot k of series (si*SS + j) at
    # [si, k % st, k // st, j]
    n_keep = S_cpu // pk._GS_SS
    perm_h = np.asarray(v_p[:n_keep, :, :, :pk._GS_SS])
    ts_h = perm_h.transpose(0, 3, 2, 1).reshape(
        S_cpu, -1)[:, :N].astype(np.float64) + BASE
    vals_raw = _gen_vals_host(S_cpu)
    vals_h = vals_raw
    t0 = time.perf_counter()
    want_par = _oracle_batched(ts_h, vals_h, T)      # [G, T] f64
    oracle_sps = S_cpu * N / (time.perf_counter() - t0)

    err = np.abs(sums_par - want_par.T)
    denom = np.maximum(np.abs(want_par.T), 1e-30)
    parity_max_rel_err = float((err / denom).max())
    assert np.all(np.asarray(cnts_par) > 0)
    assert parity_max_rel_err < 1e-5, (
        f"compiled-kernel parity vs f64 oracle failed: "
        f"{parity_max_rel_err}")

    full_series = 10_000_000
    full_samples = full_series * 8_640      # 24h at 10s
    chips = 8
    est_full_ms = full_samples / chips / device_sps * 1000.0

    # free the query tiles, then fold the ingest + downsample-batch
    # regression guards into the same driver-captured line (BASELINE.md
    # targets #2/#3; jmh IngestionBenchmark + spark BatchDownsampler)
    del v_p, tiles
    # multichip scaling sweep (weak scaling off the device-resident
    # sharded tile store; per-level subprocesses on the virtual-CPU
    # platform — independent of this process's TPU backend). Honesty
    # note: the efficiency is measured over virtual CPU devices on this
    # host, so it reflects the SOFTWARE path (dispatch amortization,
    # sharded program overhead), a lower bound the ICI fabric only
    # improves on.
    _mark("multichip scaling sweep")
    try:
        import __graft_entry__ as _ge
        mc = _ge.multichip_sweep(8)
        mc_spd = mc.get("sps_per_device_top")
        mc_eff = mc.get("scaling_efficiency")
    except Exception as e:           # sweep is telemetry, not a gate
        _mark(f"multichip sweep failed: {type(e).__name__}: {e}")
        mc_spd = mc_eff = None
    _mark("ingest + downsample sub-benches")
    import bench_downsample
    import bench_ingest
    ing = bench_ingest.measure()
    ds = bench_downsample.measure()     # full 1.07B-sample batch set
    _mark("e2e latency-under-load sub-bench")
    import bench_e2e
    try:
        e2e = bench_e2e.measure()       # gatling-analogue, own process
    except Exception as e:              # regression guard, not a gate
        e2e = {"value": None, "p95_ms": None, "qps": None,
               "error": f"{type(e).__name__}: {e}"}

    # capacity ledger (graftlint v5): certify the @capacity inventory
    # in-process and write CAPACITY.json beside this line; the resident
    # numbers below price the CERTIFIED shardstore claim at this bench
    # shape (pow2 slot capacity over N — padding is real HBM), the
    # baseline the compressed-chunks work must move (ROADMAP item 1)
    _mark("capacity certification + ledger")
    try:
        from filodb_tpu.lint import memcert
        from filodb_tpu.lint.capacity import capacity_claim
        from filodb_tpu.parallel.shardstore import _next_pow2
        ledger = memcert.capacity_ledger(samples_per_series=N)
        assert all(row["certified"] for row in ledger), \
            [r["family"] for r in ledger if not r["certified"]]
        with open("CAPACITY.json", "w") as f:
            json.dump({"samples_per_series": N,
                       "hbm_bytes_per_chip": 16 << 30,
                       "families": ledger}, f, indent=2, sort_keys=True)
            f.write("\n")
        cl = capacity_claim("shardstore-resident-channels")
        cap_slots = _next_pow2(N, 64)
        resident_bps = round(cl.bytes_per_sample * cap_slots / N, 2)
        projected_spc = cl.projected_series_per_chip(cap_slots)
    except Exception as e:              # ledger is telemetry, not a gate
        _mark(f"capacity ledger failed: {type(e).__name__}: {e}")
        resident_bps = projected_spc = None

    print(json.dumps({
        "metric": "rate_sum_by_samples_scanned_per_sec",
        "value": round(device_sps),
        "unit": "samples/s",
        "vs_baseline": round(device_sps / oracle_sps, 2),
        "per_query_p50_ms": round(per_query_p50 * 1000, 2),
        "shape": f"{S}x{N} (8h@10s), T={T}, window=5m",
        "hbm_read_gbps": round(hbm_gbps, 1),
        "parity_max_rel_err": parity_max_rel_err,
        "northstar_est_ms_v5e8": round(est_full_ms, 1),
        # multichip sweep fields (weak scaling off the device-resident
        # sharded store; efficiency measured over virtual CPU devices —
        # a software-path bound, see bench comment above)
        "multichip_sps_per_device": mc_spd,
        "scaling_efficiency_8dev": mc_eff,
        "northstar_est_ms_v5e8_scaled": (
            round(est_full_ms / mc_eff, 1) if mc_eff else None),
        "ingest_samples_per_s": ing["value"],
        "ingest_encode_samples_per_s": ing["encode_samples_per_s"],
        "downsample_samples_per_s": ds["value"],
        "downsample_batch_samples": ds["total_samples"],
        "e2e_p50_ms": e2e["value"],
        "e2e_p95_ms": e2e["p95_ms"],
        "e2e_qps": e2e["qps"],
        # certified residency (graftlint v5 capacity rail): bytes per
        # LOGICAL sample at this shape (the 20 B/padded-slot shardstore
        # claim times the pow2 capacity pad) and the resident-series
        # ceiling one 16 GB chip implies at 8h@10s retention
        "resident_bytes_per_sample": resident_bps,
        "projected_series_per_chip_16gb": projected_spc,
    }))


def _gen_vals_host(s_cpu):
    """Regenerate the first s_cpu series' RAW values host-side for the
    oracle (the device tiles hold the reset-corrected channel)."""
    key = jax.random.PRNGKey(42)
    _, k2 = jax.random.split(key)
    incs = jax.random.uniform(k2, (S, N), dtype=jnp.float64,
                              minval=0.0, maxval=5.0)[:s_cpu]
    return np.cumsum(np.asarray(incs), axis=1)


def _oracle_batched(ts, vals, T):
    """Batched numpy rate + grouped sum: the aligned-slot algorithm with
    fancy indexing — no per-series Python loop."""
    Sb, Nb = vals.shape
    prev = np.concatenate([np.full((Sb, 1), np.nan), vals[:, :-1]], axis=1)
    drop = vals < prev
    cv = vals + np.cumsum(np.where(drop, prev, 0.0), axis=1)
    ps = np.concatenate([np.zeros((Sb, 1)), np.cumsum(
        np.ones_like(vals), axis=1)], axis=1)
    t = np.arange(T, dtype=np.int64)
    wend = BASE + WINDOW + t * STEP
    wstart = wend - WINDOW
    k_hi = np.floor((wend - BASE + DT / 2.0) / DT).astype(np.int64)
    k_lo = np.ceil((wstart - BASE - DT / 2.0) / DT).astype(np.int64)
    khc = np.clip(k_hi, 0, Nb - 1)
    khp = np.clip(k_hi - 1, 0, Nb - 1)
    klc = np.clip(k_lo, 0, Nb - 1)
    kln = np.clip(k_lo + 1, 0, Nb - 1)
    cnt = ps[:, np.clip(k_hi, -1, Nb - 1) + 1] - ps[:, np.clip(k_lo, 0, Nb)]
    cnt -= (ts[:, khc] > wend[None, :])
    cnt -= (ts[:, klc] < wstart[None, :])
    use1 = ts[:, khc] <= wend[None, :]
    t2 = np.where(use1, ts[:, khc], ts[:, khp])
    v2 = np.where(use1, cv[:, khc], cv[:, khp])
    useb = ts[:, klc] >= wstart[None, :]
    t1 = np.where(useb, ts[:, klc], ts[:, kln])
    v1 = np.where(useb, cv[:, klc], cv[:, kln])
    sampled = (t2 - t1) / 1000.0
    delta = v2 - v1
    with np.errstate(all="ignore"):
        # Prometheus extrapolatedRate (RateFunctions.scala:23-79): gaps
        # under 1.1x the average sample interval extrapolate to the
        # window boundary; larger gaps add half an interval. The branch
        # is decided EXACTLY on integer milliseconds (10*(cnt-1)*gap <=
        # 11*sampled) — the same deterministic rule the Pallas kernel
        # uses; f64-in-seconds would resolve exact ties by rounding dust
        avg = sampled / (cnt - 1.0)
        ds_ms = t1 - wstart[None, :]
        de_ms = wend[None, :] - t2
        s11 = 11.0 * (t2 - t1)
        use_ds = 10.0 * (cnt - 1.0) * ds_ms <= s11
        use_de = 10.0 * (cnt - 1.0) * de_ms <= s11
        th = avg * 1.1
        ds = ds_ms / 1000.0
        dzero = np.where((delta > 0) & (v1 >= 0),
                         sampled * v1 / delta, np.inf)
        zlt = dzero < ds
        ds = np.where(zlt, dzero, ds)
        use_ds = np.where(zlt, dzero < th, use_ds)
        ext = sampled + np.where(use_ds, ds, avg * 0.5) \
            + np.where(use_de, de_ms / 1000.0, avg * 0.5)
        rate = delta * (ext / sampled) / (WINDOW / 1000.0)
        rate = np.where(cnt >= 2, rate, np.nan)
    g = Sb // N_GROUPS
    ok = ~np.isnan(rate)
    return np.where(ok, rate, 0.0).reshape(N_GROUPS, g, T).sum(axis=1)


if __name__ == "__main__":
    main()
