"""Downsampler kernel benchmark: >= 1B raw samples -> 5m + 1h resolutions
on one chip (BASELINE.md target #3; reference harness
spark-jobs BatchDownsampler over Cassandra splits).

Data is generated on device (host->device transfer over the axon tunnel is
~27 MB/s and would swamp any kernel timing; in production chunks stream in
once and downsampling is compute-bound). Timing forces a host sync through
a small checksum transfer per batch. Prints ONE JSON line.
"""

import json
import time

import numpy as np

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402

from filodb_tpu.downsample import kernels  # noqa: E402

S, N = 8_192, 16_384          # 134M samples per batch
BATCHES = 8                   # 1.074B total
DT = 10_000                   # 10s cadence
RESOLUTIONS = (300_000, 3_600_000)


def _gen_batch(seed):
    """Jittered gauge tiles generated on device."""
    k1, k2 = jax.random.split(jax.random.PRNGKey(seed))
    jitter = jax.random.randint(k1, (S, N), -2000, 2000, dtype=jnp.int32)
    ts = (jnp.arange(1, N + 1, dtype=jnp.int64) * DT)[None, :] \
        + jitter.astype(jnp.int64)
    # |jitter| < DT/2 keeps rows sorted by construction (an explicit
    # i64 sort is software-emulated on TPU and dominates the bench)
    vals = jax.random.normal(k2, (S, N), dtype=jnp.float64) * 10.0 + 50.0
    lens = jnp.full((S,), N, dtype=jnp.int32)
    return ts, vals, lens


def measure(batches_total=BATCHES, reps=2):
    base = np.int64(0)
    span = (N + 1) * DT
    res5, res1h = RESOLUTIONS
    nper5 = int(span // res5) + 1
    nper1h = int(span // res1h) + 1
    # worst-case samples per 5m period with +-2s jitter: 300s/8s + slack
    WB5 = 64
    WB1H = 16        # 12 sub-periods per hour

    def both(b):
        """Finest level from raw, 1h cascaded from 5m (the job's shape).
        Regular-cadence reshape path (the gather kernel is the ragged
        fallback; cadence passed explicitly — the generator guarantees
        it, and the host gate would pull the ts tile over the tunnel)."""
        fine = kernels.downsample_gauge_fast(
            b[0], b[1], b[2], base, res5, nper5, cadence=(DT, DT))
        coarse = kernels.cascade_gauge_aligned(fine, res1h // res5, 0)
        return fine, coarse

    @jax.jit
    def _checksum(fine0, coarse0):
        return jnp.nansum(fine0[:8]) + jnp.nansum(coarse0[:8])

    t0c = time.perf_counter()
    # a few resident batches (8 would exceed HBM), alternated —
    # per-batch kernel work is data-independent, so throughput is honest
    batches = [jax.block_until_ready(_gen_batch(i))
               for i in range(min(2, batches_total))]
    f, c = both(batches[0])
    # compile EVERYTHING outside the timed region, including the
    # checksum sync op — over the axon tunnel an op-by-op compile costs
    # seconds and would dominate the measurement
    float(np.asarray(_checksum(f[0], c[0])))
    compile_s = time.perf_counter() - t0c

    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        acc = 0.0
        for i in range(batches_total):
            b = batches[i % len(batches)]
            fine, coarse = both(b)
            acc += float(np.asarray(_checksum(fine[0], coarse[0])))  # sync
        best = min(best, time.perf_counter() - t0)
    total = S * N * batches_total
    sps = total / best

    # numpy oracle on a small subsample, extrapolated
    ts0 = np.asarray(batches[0][0][0])
    vs0 = np.asarray(batches[0][1][0])
    t0 = time.perf_counter()
    for res in RESOLUTIONS:
        nper = int(span // res) + 1
        kernels.downsample_gauge_oracle(ts0, vs0, 0, res, nper)
    oracle_sps = N / (time.perf_counter() - t0)

    return ({
        "metric": "downsample_raw_samples_per_sec",
        "value": round(sps),
        "unit": "samples/s",
        "vs_baseline": round(sps / oracle_sps, 2),
        "total_samples": total,
        "resolutions_ms": list(RESOLUTIONS),
        "compile_s": round(compile_s, 1),
    })


def main():
    print(json.dumps(measure()))


if __name__ == "__main__":
    main()
