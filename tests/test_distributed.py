"""Multi-host mesh e2e: two OS processes join one jax.distributed
cluster (4 virtual CPU devices each), build ONE global ('shard','time')
mesh, and run the fused windowed aggregate with each process holding
only ITS shard groups' samples — the grouped psum-tree reduction must
cross the process boundary to produce sums that match a single-process
oracle over ALL the data.

(SURVEY §7 step 6: jax.distributed is the multi-host path; the
reference scales out with one NCCL/Akka process per node,
coordinator/FilodbCluster.scala:39.)"""

import json
import os
import pathlib
import socket
import subprocess
import sys

import jax
import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent

_WORKER = r"""
import json, os, sys
import numpy as np

pid = int(sys.argv[1])
coord = sys.argv[2]
out_path = sys.argv[3]

import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
from filodb_tpu.parallel.distributed import (init_process,
                                             window_aggregate_distributed)
init_process(coord, 2, pid)
assert jax.device_count() == 8, jax.device_count()
assert jax.local_device_count() == 4

from filodb_tpu.parallel.mesh import make_mesh, MeshExecutor
from filodb_tpu.query.model import RangeParams, RawSeries

mesh = make_mesh()                      # all 8 global devices on 'shard'
ex = MeshExecutor(mesh)

# deterministic data for all 8 shard groups; keep only OUR half
T0 = 1_600_000_000_000
rng = np.random.default_rng(99)
all_rows, all_gids = [], []
for g in range(8):
    row, grow = [], []
    for s in range(6):
        n = 240
        ts = T0 + np.arange(n, dtype=np.int64) * 10_000 \
            + rng.integers(-2000, 2000, n)
        vals = np.cumsum(rng.uniform(0, 5, n))
        row.append(RawSeries(labels={}, ts=np.sort(ts), values=vals,
                             is_counter=True))
        grow.append((g * 6 + s) % 3)    # 3 groups spanning ALL shards
    all_rows.append(row)
    all_gids.append(grow)

local_rows = all_rows[pid * 4:(pid + 1) * 4]
local_gids = all_gids[pid * 4:(pid + 1) * 4]
params = RangeParams(T0 + 400_000, 60_000, T0 + 2_000_000)
got = window_aggregate_distributed(ex, local_rows, local_gids, params,
                                   "rate", "sum", 300_000, 3)

result = {"pid": pid, "shape": list(got.shape)}
if pid == 0:
    from filodb_tpu.query import rangefn
    steps = params.steps
    want = np.zeros((3, steps.size))
    for g in range(8):
        for s, series in enumerate(all_rows[g]):
            r = rangefn.evaluate("rate", series.ts, series.values,
                                 int(steps[0]), 60_000, int(steps[-1]),
                                 300_000)
            gid = all_gids[g][s]
            want[gid] += np.where(np.isfinite(r), r, 0.0)
    err = float(np.nanmax(np.abs(got - want)
                          / np.maximum(np.abs(want), 1e-12)))
    result["max_rel_err"] = err
    result["ok"] = bool(err < 1e-9)
with open(out_path, "w") as f:
    json.dump(result, f)
"""


@pytest.mark.skipif(
    jax.default_backend() == "cpu",
    reason="jax.distributed's cross-process collectives need a real "
           "accelerator runtime; on the CPU backend the two-process "
           "coordinator handshake fails in this container (documented "
           "environmental failure since the seed) — the single-process "
           "mesh path is covered by tests/test_mesh.py")
def test_two_process_mesh_psum_crosses_hosts(tmp_path):
    port = socket.socket()
    port.bind(("127.0.0.1", 0))
    coord = f"127.0.0.1:{port.getsockname()[1]}"
    port.close()
    worker = tmp_path / "worker.py"
    worker.write_text(_WORKER)
    procs = []
    outs = [tmp_path / f"out{i}.json" for i in range(2)]
    for i in range(2):
        env = dict(os.environ)
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get(
            "PYTHONPATH", "")
        procs.append(subprocess.Popen(
            [sys.executable, str(worker), str(i), coord, str(outs[i])],
            cwd=str(REPO), env=env))
    for p in procs:
        assert p.wait(timeout=240) == 0
    r0 = json.loads(outs[0].read_text())
    r1 = json.loads(outs[1].read_text())
    assert r0["shape"] == r1["shape"] == [3, 27]
    assert r0["ok"], r0
