"""Histogram parity: sectioned drop detection, batch downsampling,
mesh-lowered sum(rate(hist[w])), and histogram_quantile over classic
per-bucket `le` series.

(References: HistogramVector.scala:378,427 SectDelta;
ChunkDownsampler.scala:38-353 hLast/hSum; HistogramQuantileMapper.scala;
the VERDICT hist e2e: ingest -> flush -> downsample ->
histogram_quantile(0.99, sum(rate(...))) on mesh matches oracle.)
"""

import numpy as np
import pytest

from filodb_tpu.core.memstore import TimeSeriesShard
from filodb_tpu.core.record import RecordBuilder
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetRef
from filodb_tpu.memory import histogram as bh
from filodb_tpu.memory.histogram import CustomBuckets, GeometricBuckets
from filodb_tpu.promql.parser import TimeStepParams, parse_query_range
from filodb_tpu.query.engine import QueryEngine
from filodb_tpu.query.model import GridResult

REF = DatasetRef("timeseries")
RES = 300_000
T0 = (1_600_000_000_000 // RES) * RES
SAMPLE_OFF = 5_000
LES = (0.5, 2.0, 8.0, float("inf"))


# --- sectioned encoding + per-bucket drop detection ------------------------

def test_sectioned_roundtrip_and_drop_table():
    scheme = CustomBuckets(LES)
    rows = np.array([[1, 2, 3, 4],
                     [2, 4, 6, 8],
                     [0, 1, 1, 2],      # full reset
                     [1, 2, 3, 4],
                     [1, 1, 4, 5]],     # partial drop (bucket 1 only)
                    dtype=np.int64)
    buf = bh.encode_histograms(scheme, rows, counter=True)
    sch, counter, got, drops = bh.decode_histograms_full(buf)
    assert counter and isinstance(sch, CustomBuckets)
    np.testing.assert_array_equal(got, rows)
    np.testing.assert_array_equal(drops, [2, 4])


def test_partial_bucket_drop_detected():
    """Regression: a drop in a non-Inf bucket (the +Inf bucket keeps
    growing) must count as a reset."""
    rows = np.array([[5.0, 10.0, 20.0],
                     [6.0, 11.0, 21.0],
                     [1.0, 12.0, 22.0]])    # bucket 0 dropped, +Inf grew
    corr = bh.hist_counter_correction(rows)
    # reset at row 2: previous full histogram added back
    np.testing.assert_allclose(corr[2], [6.0, 11.0, 21.0])
    np.testing.assert_allclose(corr[:2], 0.0)


def test_correction_uses_encoded_drop_table():
    rows = np.array([[1.0, 2.0], [3.0, 4.0], [0.0, 1.0], [2.0, 3.0]])
    corr_scan = bh.hist_counter_correction(rows)
    corr_table = bh.hist_counter_correction(rows, drop_rows=np.array([2]))
    np.testing.assert_allclose(corr_scan, corr_table)


def test_legacy_unsectioned_vectors_still_decode():
    scheme = GeometricBuckets(2.0, 2.0, 4)
    rows = np.cumsum(np.ones((6, 4), dtype=np.int64), axis=0)
    buf = bh.encode_histograms(scheme, rows, counter=True, sectioned=False)
    sch, counter, got, drops = bh.decode_histograms_full(buf)
    np.testing.assert_array_equal(got, rows)
    assert drops is None


# --- fixtures --------------------------------------------------------------

def _hist_counts(t, s):
    """Cumulative bucket counts at sample t for series s."""
    base = np.array([1, 3, 7, 10]) * (s + 1)
    return (base * (t + 1)).astype(np.int64)


def _seed_hist(shard_or_none=None, column_store=None, n=720,
               num_series=3, reset_at=None):
    shard = shard_or_none or TimeSeriesShard(
        REF, DEFAULT_SCHEMAS, 0, column_store=column_store,
        max_chunk_rows=120)
    scheme = CustomBuckets(LES)
    b = RecordBuilder(DEFAULT_SCHEMAS)
    for s in range(num_series):
        labels = {"_metric_": "req_latency", "_ws_": "demo",
                  "_ns_": "App-0", "instance": f"i{s}"}
        for t in range(n):
            counts = _hist_counts(t, s)
            if reset_at is not None and t >= reset_at:
                counts = _hist_counts(t - reset_at, s)
            total = int(counts[-1])
            b.add_sample("prom-histogram", labels,
                         T0 + SAMPLE_OFF + t * 10_000,
                         total * 0.05, float(total),
                         (scheme, counts))
    for c in b.containers():
        shard.ingest(c)
    if column_store is not None:
        shard.flush_all(offset=1)
    return shard


# --- batch downsampling ----------------------------------------------------

def test_hist_downsample_job_writes_and_matches_rate(tmp_path):
    from filodb_tpu.downsample import (DownsampledTimeSeriesStore,
                                       DownsamplerJob)
    from filodb_tpu.store import FlatFileColumnStore
    cs = FlatFileColumnStore(str(tmp_path / "col"))
    raw = _seed_hist(column_store=cs)
    stats = DownsamplerJob(cs, resolutions=(RES,)).run("timeseries", 0)
    assert not stats.skipped_schemas, stats.skipped_schemas
    assert stats.samples_written > 0

    dstore = DownsampledTimeSeriesStore(cs, "timeseries", 1,
                                        resolutions=(RES,))
    tsp = TimeStepParams(T0 // 1000 + 1800, 600, T0 // 1000 + 7000)
    plan = parse_query_range("increase(req_latency[10m])", tsp)
    picked = dstore.plan_query(plan, 600_000, 600_000)
    assert picked is not None
    ds_shards, ds_plan = picked
    got = QueryEngine(ds_shards).execute(ds_plan)
    want = QueryEngine([raw]).execute(plan)
    assert got.is_hist() and want.is_hist()
    gmap = {k["instance"]: got.hist_values[i]
            for i, k in enumerate(got.keys)}
    for i, k in enumerate(want.keys):
        g, w = gmap[k["instance"]], want.hist_values[i]
        ok = np.isfinite(w) & np.isfinite(g)
        assert ok.sum() >= w.size * 0.9
        np.testing.assert_allclose(g[ok], w[ok], rtol=0.05)


def test_hist_tiering_stitches(tmp_path):
    """Hist e2e over the retention split: raw recent + ds old."""
    from filodb_tpu.downsample import (DownsampledTimeSeriesStore,
                                       DownsamplerJob)
    from filodb_tpu.query.planner import QueryPlanner, StitchExec
    from filodb_tpu.store import FlatFileColumnStore
    cs = FlatFileColumnStore(str(tmp_path / "col"))
    full = _seed_hist(column_store=cs)
    DownsamplerJob(cs, resolutions=(RES,)).run("timeseries", 0)
    now = T0 + 720 * 10_000
    retention = 1_800_000
    first_kept = (now - retention - T0) // 10_000
    recent = TimeSeriesShard(REF, DEFAULT_SCHEMAS, 0, max_chunk_rows=120)
    scheme = CustomBuckets(LES)
    b = RecordBuilder(DEFAULT_SCHEMAS)
    for s in range(3):
        labels = {"_metric_": "req_latency", "_ws_": "demo",
                  "_ns_": "App-0", "instance": f"i{s}"}
        for t in range(first_kept, 720):
            counts = _hist_counts(t, s)
            total = int(counts[-1])
            b.add_sample("prom-histogram", labels,
                         T0 + SAMPLE_OFF + t * 10_000,
                         total * 0.05, float(total), (scheme, counts))
    for c in b.containers():
        recent.ingest(c)
    planner = QueryPlanner(
        [recent],
        ds_store=DownsampledTimeSeriesStore(cs, "timeseries", 1,
                                            resolutions=(RES,)),
        raw_retention_ms=retention, now_ms=now)
    tsp = TimeStepParams(T0 // 1000 + 1800, 600, now // 1000)
    plan = parse_query_range("increase(req_latency[10m])", tsp)
    ex = planner.materialize(plan)
    assert isinstance(ex, StitchExec)
    got = ex.execute()
    want = QueryEngine([full]).execute(plan)
    assert got.is_hist()
    gmap = {k["instance"]: got.hist_values[i]
            for i, k in enumerate(got.keys)}
    for i, k in enumerate(want.keys):
        g, w = gmap[k["instance"]], want.hist_values[i]
        ok = np.isfinite(w) & np.isfinite(g)
        assert ok.sum() >= w.size * 0.9
        np.testing.assert_allclose(g[ok], w[ok], rtol=0.05)


# --- mesh lowering ---------------------------------------------------------

def test_mesh_sum_rate_hist_matches_oracle():
    import jax

    from filodb_tpu.parallel.mesh import MeshExecutor, make_mesh
    from filodb_tpu.query.planner import MeshAggregateExec, QueryPlanner
    if len(jax.devices()) < 2:
        pytest.skip("needs a multi-device mesh")
    shard = _seed_hist(n=360, reset_at=200)     # includes a reset
    tsp = TimeStepParams(T0 // 1000 + 600, 60, T0 // 1000 + 3000)
    plan = parse_query_range("sum(rate(req_latency[5m]))", tsp)
    planner = QueryPlanner([shard],
                           mesh_executor=MeshExecutor(make_mesh()))
    ex = planner.materialize(plan)
    assert isinstance(ex, MeshAggregateExec)
    got = ex.execute()
    want = QueryEngine([shard]).execute(plan)
    assert got.is_hist() and want.is_hist()
    np.testing.assert_array_equal(got.bucket_les, want.bucket_les)
    assert got.num_series == want.num_series == 1
    np.testing.assert_allclose(got.hist_values[0], want.hist_values[0],
                               rtol=1e-9, equal_nan=True)


def test_mesh_hist_quantile_e2e():
    """The VERDICT done-criterion: histogram_quantile(0.99,
    sum(rate(hist[w]))) with the inner aggregate on the mesh."""
    import jax

    from filodb_tpu.parallel.mesh import MeshExecutor, make_mesh
    from filodb_tpu.query.planner import QueryPlanner
    if len(jax.devices()) < 2:
        pytest.skip("needs a multi-device mesh")
    shard = _seed_hist(n=360)
    tsp = TimeStepParams(T0 // 1000 + 600, 60, T0 // 1000 + 3000)
    plan = parse_query_range(
        "histogram_quantile(0.99, sum(rate(req_latency[5m])))", tsp)
    got = QueryPlanner([shard],
                       mesh_executor=MeshExecutor(make_mesh())).execute(plan)
    want = QueryEngine([shard]).execute(plan)
    assert got.num_series == want.num_series == 1
    np.testing.assert_allclose(got.values, want.values, rtol=1e-9,
                               equal_nan=True)


# --- histogram_quantile over per-bucket le series --------------------------

def test_quantile_over_le_series():
    steps = np.arange(T0, T0 + 5 * 60_000, 60_000, dtype=np.int64)
    les = [0.5, 2.0, "+Inf"]
    keys, rows = [], []
    for inst in ("a", "b"):
        scale = 1.0 if inst == "a" else 2.0
        for j, le in enumerate(les):
            keys.append({"__name__": "lat_bucket", "le": str(le),
                         "instance": inst})
            rows.append(np.full(steps.size, (j + 1) * 10.0 * scale))
    grid = GridResult(steps, keys, np.vstack(rows))
    from filodb_tpu.query.engine import histogram_quantile
    out = histogram_quantile(grid, 0.5)
    assert out.num_series == 2
    m = {k["instance"]: out.values[i] for i, k in enumerate(out.keys)}
    # per series: buckets (10,20,30)*scale; rank=.5*30=15 -> bucket 1,
    # interpolate 0.5 + (2-0.5)*(15-10)/(20-10) = 1.25 (same for both:
    # scale cancels)
    np.testing.assert_allclose(m["a"], 1.25)
    np.testing.assert_allclose(m["b"], 1.25)
    assert all("le" not in k and "__name__" not in k for k in out.keys)


def test_quantile_le_series_requires_inf_bucket():
    """No +Inf bucket sample at a step -> NaN (Prometheus bucketQuantile)."""
    steps = np.arange(T0, T0 + 2 * 60_000, 60_000, dtype=np.int64)
    grid = GridResult(
        steps,
        [{"le": "0.5", "x": "a"}, {"le": "1.0", "x": "a"}],
        np.array([[1.0, 1.0], [2.0, 2.0]]))
    from filodb_tpu.query.engine import histogram_quantile
    out = histogram_quantile(grid, 0.99)
    assert np.isnan(out.values).all()


def test_quantile_le_series_tolerates_stale_bucket():
    """A NaN in one bucket series must not poison steps where enough other
    buckets (incl. +Inf) have samples."""
    steps = np.arange(T0, T0 + 2 * 60_000, 60_000, dtype=np.int64)
    grid = GridResult(
        steps,
        [{"le": "0.5", "x": "a"}, {"le": "2.0", "x": "a"},
         {"le": "+Inf", "x": "a"}],
        np.array([[np.nan, 5.0], [10.0, 10.0], [20.0, 20.0]]))
    from filodb_tpu.query.engine import histogram_quantile
    out = histogram_quantile(grid, 0.25)
    # step 0: only (2.0, +Inf) present -> rank 5 inside bucket le=2.0,
    # interpolated from 0 (two buckets suffice for Prometheus)
    assert np.isfinite(out.values[0, 0])
    assert np.isfinite(out.values[0, 1])


def test_at_on_non_selector_rejected():
    from filodb_tpu.promql.parser import ParseError
    tsp = TimeStepParams(T0 // 1000, 60, T0 // 1000 + 600)
    with pytest.raises(ParseError, match="@"):
        parse_query_range("sum(rate(req_latency[5m])) @ 100", tsp)
    with pytest.raises(ParseError, match="@"):
        parse_query_range("(req_latency + req_latency) @ 100", tsp)
    # @ on subqueries is supported (pinned grid)
    plan = parse_query_range("sum_over_time(req_latency[10m:1m] @ 100)",
                             tsp)
    assert plan.at_ms == 100_000


def test_drop_table_flows_to_raw_series():
    shard = _seed_hist(n=120, reset_at=60)
    from filodb_tpu.query.engine import select_raw_series
    from filodb_tpu.query.model import QueryStats
    shard.flush_all()       # encode -> sectioned chunks
    series = select_raw_series([shard], [], 0, 1 << 62, None,
                               QueryStats(), full=True)
    hist = [s for s in series if s.bucket_les is not None]
    assert hist
    for s in hist:
        assert s.hist_drop_rows is not None
        np.testing.assert_array_equal(s.hist_drop_rows, [60])


def test_quantile_le_series_end_to_end_parity_with_native():
    """Exporting a native hist as per-le series and running the classic
    join must agree with the native-histogram path."""
    shard = _seed_hist(n=120)
    tsp = TimeStepParams(T0 // 1000 + 600, 60, T0 // 1000 + 1000)
    native = QueryEngine([shard]).execute(parse_query_range(
        "histogram_quantile(0.9, rate(req_latency[5m]))", tsp))
    # build the per-le grid from the same rate result
    hist = QueryEngine([shard]).execute(parse_query_range(
        "rate(req_latency[5m])", tsp))
    keys, rows = [], []
    for i, k in enumerate(hist.keys):
        for j, le in enumerate(np.asarray(hist.bucket_les)):
            kk = dict(k)
            kk["le"] = "+Inf" if np.isposinf(le) else str(le)
            keys.append(kk)
            rows.append(hist.hist_values[i, :, j])
    grid = GridResult(hist.steps, keys, np.vstack(rows))
    from filodb_tpu.query.engine import histogram_quantile
    got = histogram_quantile(grid, 0.9)
    nm = {k["instance"]: native.values[i]
          for i, k in enumerate(native.keys)}
    assert got.num_series == native.num_series
    for i, k in enumerate(got.keys):
        np.testing.assert_allclose(got.values[i], nm[k["instance"]],
                                   rtol=1e-12, equal_nan=True)
