"""NibblePack parity tests.

Golden byte vectors ported from the reference test suite
(memory/src/test/scala/filodb.memory/format/NibblePackTest.scala) — these pin
bit-for-bit interchange compatibility with the reference wire format.
"""

import numpy as np
import pytest

from filodb_tpu.memory import nibblepack as nbp


def test_pack8_partial_nonzero_even_nibbles():
    # NibblePackTest.scala "should NibblePack 8 words partial non-zero even nibbles"
    inputs = [
        0,
        0x0000003322110000, 0x0000004433220000,
        0x0000005544330000, 0x0000006655440000,
        0, 0, 0,
    ]
    out = bytearray()
    nbp.pack8(inputs, out)
    expected = bytes([
        0x1E,        # bitmask
        0x54,        # six nibbles wide, four trailing zero nibbles
        0x11, 0x22, 0x33, 0x22, 0x33, 0x44,
        0x33, 0x44, 0x55, 0x44, 0x55, 0x66,
    ])
    assert bytes(out) == expected


def test_pack8_partial_nonzero_odd_nibbles():
    inputs = [
        0,
        0x0000003322100000, 0x0000004433200000,
        0x0000005544300000, 0x0000006655400000,
        0x0000007654300000, 0, 0,
    ]
    out = bytearray()
    nbp.pack8(inputs, out)
    expected = bytes([
        0x3E,
        0x45,        # five nibbles wide, five trailing zero nibbles
        0x21, 0x32, 0x23, 0x33, 0x44,
        0x43, 0x54, 0x45, 0x55, 0x66,
        0x43, 0x65, 0x07,
    ])
    assert bytes(out) == expected


def test_unpack8_partial_odd_nibbles():
    compressed = bytes([
        0x3E, 0x45,
        0x21, 0x32, 0x23, 0x33, 0x44,
        0x43, 0x54, 0x45, 0x55, 0x66,
        0x43, 0x65, 0x07,
    ])
    expected = [
        0,
        0x0000003322100000, 0x0000004433200000,
        0x0000005544300000, 0x0000006655400000,
        0x0000007654300000, 0, 0,
    ]
    out = [0] * 8
    pos = nbp.unpack8(compressed, 0, out)
    assert pos == len(compressed)
    assert out == expected


def test_pack_unpack_delta():
    inputs = [0, 1000, 1001, 1002, 1003, 2005, 2010, 3034, 4045, 5056, 6067, 7078]
    out = bytearray()
    nbp.pack_delta(inputs, out)
    got, _ = nbp.unpack_delta(bytes(out), 0, len(inputs))
    np.testing.assert_array_equal(got, inputs)

    inputs2 = [10000, 1032583228027]
    out2 = bytearray()
    nbp.pack_delta(inputs2, out2)
    got2, _ = nbp.unpack_delta(bytes(out2), 0, len(inputs2))
    np.testing.assert_array_equal(got2, inputs2)


def test_pack_unpack_doubles():
    inputs = [0.0, 2.5, 5.0, 7.5, 8, 13.2, 18.9, 89, 101.1, 102.3]
    out = bytearray()
    nbp.pack_doubles(inputs, out)
    got, _ = nbp.unpack_double_xor(bytes(out), 0, len(inputs))
    np.testing.assert_array_equal(got, np.asarray(inputs, dtype=np.float64))


def test_pack_unpack_non_increasing():
    inputs = [5, 1, 0, 999999, 2, 0, 0, 1 << 63, 42]
    out = bytearray()
    nbp.pack_non_increasing(inputs, out)
    got, _ = nbp.unpack_to_words(bytes(out), 0, len(inputs))
    np.testing.assert_array_equal(got, np.array(inputs, dtype=np.uint64))


@pytest.mark.parametrize("seed", range(5))
def test_property_roundtrip_increasing(seed):
    # Mirrors the ScalaCheck property test over increasing long sequences
    rng = np.random.default_rng(seed)
    n = int(rng.integers(1, 300))
    deltas = rng.integers(0, 1 << 30, size=n)
    values = np.cumsum(deltas).astype(np.int64)
    out = bytearray()
    nbp.pack_delta(values, out)
    got, _ = nbp.unpack_delta(bytes(out), 0, n)
    np.testing.assert_array_equal(got, values)


@pytest.mark.parametrize("seed", range(5))
def test_property_roundtrip_doubles(seed):
    rng = np.random.default_rng(seed + 100)
    n = int(rng.integers(1, 257))
    values = rng.normal(size=n) * (10.0 ** float(rng.integers(-3, 6)))
    out = bytearray()
    nbp.pack_doubles(values, out)
    got, _ = nbp.unpack_double_xor(bytes(out), 0, n)
    np.testing.assert_array_equal(got, values)


def test_multiple_groups_chained():
    # several groups of 8 back to back, ensures position chaining works
    values = list(range(0, 64000, 1000))
    out = bytearray()
    nbp.pack_delta(values, out)
    got, pos = nbp.unpack_delta(bytes(out), 0, len(values))
    assert pos == len(out)
    np.testing.assert_array_equal(got, values)
