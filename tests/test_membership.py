"""Elastic membership e2e (parallel/membership.py): planned drain
handoff, rejoin deferral + hand-back, topology-epoch cache coherence,
and the stale-routing bounce/retry protocol.

(Reference: coordinator/ShardManager.scala:28 — shard movement on node
join/leave as a first-class planned operation; the crash path stays in
tests/test_reassignment.py.)"""

import json
import socket
import time
import urllib.error
import urllib.parse
import urllib.request

import pytest

from filodb_tpu.query.model import StaleRoutingError
from filodb_tpu.standalone.server import FiloServer
from filodb_tpu.testing import chaos

T0 = 1_600_000_000
N_SAMPLES = 60
N_INSTANCES = 4


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _get(port, path, **params):
    qs = urllib.parse.urlencode(params, doseq=True)
    url = f"http://127.0.0.1:{port}{path}"
    if qs:
        url += "?" + qs
    try:
        with urllib.request.urlopen(url, timeout=120) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _post(port, path, body=None, **params):
    qs = urllib.parse.urlencode(params, doseq=True)
    url = f"http://127.0.0.1:{port}{path}"
    if qs:
        url += "?" + qs
    req = urllib.request.Request(
        url, data=json.dumps(body or {}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=120) as r:
        return r.status, json.loads(r.read())


def _query(port, **extra):
    """Unpruned cross-node range query touching every shard."""
    return _get(port, "/promql/timeseries/api/v1/query_range",
                query='rate({_metric_=~'
                      '"heap_usage|http_requests_total"}[5m])',
                start=T0 + 300, end=T0 + (N_SAMPLES - 1) * 10, step=60,
                **extra)


def _result_data(body):
    """Query payload minus per-request stats/timings, ordered by series
    identity — the byte-identity comparison surface."""
    rows = [(tuple(sorted(r["metric"].items())), r.get("values"))
            for r in body["data"]["result"]]
    return sorted(rows)


def _shard_owners(port):
    _, body = _get(port, "/api/v1/cluster/timeseries/status")
    return {s["shard"]: (s["status"], s["address"])
            for s in body["data"]}


def _poll(fn, timeout=60.0, interval=0.1):
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        try:
            ok, last = fn()
            if ok:
                return last
        except (urllib.error.URLError, ConnectionError, OSError):
            pass
        time.sleep(interval)
    raise TimeoutError(f"poll timed out; last={last!r}")


def _mk_cluster(tmp_path, n_nodes=2, num_shards=4, fd_interval=0.25,
                grace=0.75, **extra):
    ports = [_free_port() for _ in range(n_nodes)]
    peers = {f"node{i}": f"http://127.0.0.1:{p}"
             for i, p in enumerate(ports)}
    base = {
        "num-shards": num_shards, "num-nodes": n_nodes, "peers": peers,
        "data-dir": str(tmp_path / "data"),
        "query-sample-limit": 0, "query-series-limit": 0,
        "failure-detect-interval-s": fd_interval,
        "failure-detect-threshold": 2,
        "shard-reassign-grace-s": grace,
        "grpc-port": None,          # deterministic HTTP plane
        "handoff-timeout-s": 20.0,
        **extra,
    }
    cfgs = [{**base, "node-ordinal": i, "port": ports[i]}
            for i in range(n_nodes)]
    servers = []
    for cfg in cfgs:
        srv = FiloServer(dict(cfg)).start()
        srv.seed_dev_data(n_samples=N_SAMPLES, n_instances=N_INSTANCES,
                          start_ms=T0 * 1000)
        servers.append(srv)
    return servers, cfgs, ports


def test_drain_hands_every_shard_off_and_results_stay_identical(tmp_path):
    servers, cfgs, ports = _mk_cluster(tmp_path)
    a, b = servers
    try:
        code, full = _query(a.port)
        assert code == 200 and "partial" not in full
        golden = _result_data(full)
        node1_shards = sorted(sh for sh, (_, n) in
                              _shard_owners(a.port).items()
                              if n == "node1")
        assert node1_shards

        code, out = _post(b.port, "/admin/drain")
        assert code == 200 and out["status"] == "success"
        handed = {h["shard"] for h in out["data"]["handed_off"]}
        assert handed == set(node1_shards), out
        assert out["data"]["failed"] == []

        # the drained node owns nothing; every shard active on node0
        st_b = _shard_owners(b.port)
        assert all(n != "node1" for _, n in st_b.values()), st_b
        assert all(s == "active" for s, _ in st_b.values()), st_b
        # both entry points serve the full pre-drain result set
        for port in (a.port, b.port):
            code, body = _query(port)
            assert code == 200 and "partial" not in body
            assert _result_data(body) == golden
        # node0's mapper converged too (transfer push or its own adopt)
        _poll(lambda: (all(n == "node0" for _, n in
                           _shard_owners(a.port).values()), None))

        # topology epoch moved on both nodes; the handoff counters and
        # the epoch gauge ride /metrics
        for srv in (a, b):
            assert srv.mapper.topology_epoch > 0
        with urllib.request.urlopen(
                f"http://127.0.0.1:{b.port}/metrics", timeout=30) as r:
            mtx = r.read().decode()
        assert "filodb_topology_epoch" in mtx
        assert any(line.startswith("filodb_shard_handoff_completed_total")
                   and int(float(line.split()[-1])) >= len(node1_shards)
                   for line in mtx.splitlines())
        assert 'filodb_shard_adoptions_total{kind="planned"}' in mtx
    finally:
        for srv in servers:
            srv.stop()


def test_drain_without_peers_fails_cleanly(tmp_path):
    srv = FiloServer({"num-shards": 2, "port": 0,
                      "data-dir": str(tmp_path / "d")}).start()
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post_raise(srv.port, "/admin/drain")
        assert ei.value.code == 400
    finally:
        srv.stop()


def _post_raise(port, path, body=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body or {}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as r:
        return json.loads(r.read())


def test_rejoin_defers_claimed_shards_and_receives_them_back(tmp_path):
    servers, cfgs, ports = _mk_cluster(tmp_path)
    a, b = servers
    try:
        code, full = _query(a.port)
        golden = _result_data(full)
        node1_shards = sorted(sh for sh, (_, n) in
                              _shard_owners(a.port).items()
                              if n == "node1")

        _post(b.port, "/admin/drain")
        b.stop()
        servers[1] = None
        # node0 must notice the death and mark node1 reassignable —
        # the rejoin hand-back hook keys off that flag
        _poll(lambda: (a.detector.is_down("node1"), None))
        _poll(lambda: (a.detector._reassigned.get("node1", False),
                       None), timeout=30)

        b2 = FiloServer(dict(cfgs[1])).start()
        servers[1] = b2
        # startup deferral: node0 still serves node1's shards, so the
        # restarted node must NOT have created them
        assert set(b2.deferred_shards) | set(
            sh for sh in node1_shards
            if sh in {s.shard_num for s in b2.store.shards(b2.ref)}) \
            == set(node1_shards)

        # ...and the planned hand-back returns them: replayed, ACTIVE
        # on node1, released by node0
        def _handed_back():
            st = _shard_owners(a.port)
            ok = all(st[sh] == ("active", "node1")
                     for sh in node1_shards)
            return ok, st
        _poll(_handed_back, timeout=60)
        for port in (a.port, b2.port):
            code, body = _query(port)
            assert code == 200 and "partial" not in body
            assert _result_data(body) == golden
        # the hand-back rode the planned path, not the legacy cutover
        snap = a.membership.metrics_snapshot()
        assert snap["handoffs_completed"] >= len(node1_shards)
    finally:
        for srv in servers:
            if srv is not None:
                srv.stop()


def test_stale_routing_bounce_is_never_returned_and_retries(tmp_path):
    """Moves a shard between the plan-cache fill and the query: the
    entry node's routing (and plan cache) still name the old owner,
    which bounces stale_routing instead of answering with a silent
    subset; the entry node rewires from the bounce's owner hint and
    re-materializes — the client sees only the correct result."""
    servers, cfgs, ports = _mk_cluster(
        tmp_path, n_nodes=3, num_shards=4,
        # detectors poll so slowly that gossip never updates node0's
        # view during the test — only the bounce can fix its routing
        fd_interval=300.0, grace=None)
    a, b, c = servers
    try:
        code, full = _query(a.port)      # fills node0's plan cache
        assert code == 200
        golden = _result_data(full)
        owners0 = _shard_owners(a.port)
        node1_shards = sorted(sh for sh, (_, n) in owners0.items()
                              if n == "node1")
        assert node1_shards == [2, 3]

        # drain node1 with the ownership-transfer push to node0
        # suppressed: node0's mapper goes stale by construction
        inj = chaos.ChaosInjector()
        inj.fail("handoff.transfer",
                 match=lambda ctx: ctx.get("node") == "node0")
        with inj:
            code, out = _post(b.port, "/admin/drain")
            assert code == 200 and out["data"]["failed"] == [], out
        by_new_owner = {h["shard"]: h["to"]
                        for h in out["data"]["handed_off"]}
        # round-robin over sorted survivors: node0 and node2 got one each
        assert sorted(by_new_owner.values()) == ["node0", "node2"]
        stale_shard = next(sh for sh, n in by_new_owner.items()
                           if n == "node2")
        # node0 genuinely has a stale view of that shard
        assert _shard_owners(a.port)[stale_shard][1] == "node1"

        before = a.http.stale_routing_retries
        code, body = _query(a.port)
        assert code == 200 and "partial" not in body
        assert _result_data(body) == golden
        assert a.http.stale_routing_retries > before
        assert b.http.stale_routing_bounces >= 1
        # the bounce's owner hint rewired node0's mapper
        assert _shard_owners(a.port)[stale_shard][1] == "node2"
        # and node0's caches were invalidated on the stale world
        assert "stale-routing" in \
            a.http.plan_cache.snapshot()["invalidations_by_reason"]
    finally:
        for srv in servers:
            srv.stop()


def test_stale_routing_error_round_trips_through_strings():
    e = StaleRoutingError(owners={3: "node2", 1: "node0"}, epoch=17,
                          node="node1", detail="shards [3] moved")
    wrapped = f"remote node node1: {e}"
    back = StaleRoutingError.parse(wrapped)
    assert back is not None
    assert back.owners == {3: "node2", 1: "node0"}
    assert back.epoch == 17 and back.node == "node1"
    assert StaleRoutingError.parse("plain error") is None


def test_leaf_endpoint_bounces_unserved_shards(tmp_path):
    """POST /api/v1/raw asking for a shard this node does not serve
    answers a stale_routing envelope (owners + epoch), never a silent
    subset."""
    servers, cfgs, ports = _mk_cluster(tmp_path, fd_interval=300.0,
                                       grace=None)
    a, b = servers
    try:
        node1_shards = sorted(sh for sh, (_, n) in
                              _shard_owners(a.port).items()
                              if n == "node1")
        body = {"filters": [["_metric_", "eq", "heap_usage"]],
                "start_ms": 0, "end_ms": 1 << 60, "column": None,
                "shards": [node1_shards[0]]}
        code, payload = _post(a.port, "/api/v1/raw/timeseries", body)
        assert code == 200
        assert payload["status"] == "error"
        assert payload["errorType"] == "stale_routing"
        assert payload["owners"] == {str(node1_shards[0]): "node1"}
        assert "topo_epoch" in payload
    finally:
        for srv in servers:
            srv.stop()


def test_gossiped_watermarks_ride_health_and_stamp_remote_groups(
        tmp_path):
    """ROADMAP 4a: per-shard ingest watermarks + backfill epochs ride
    the health body, the failure detector sinks them, and the planner
    stamps remote shard groups so the results cache's freshness
    horizon covers fan-out extents."""
    servers, cfgs, ports = _mk_cluster(tmp_path, fd_interval=0.1,
                                       grace=None)
    a, b = servers
    try:
        _, health = _get(b.port, "/__health")
        assert "watermarks" in health and "backfill_epochs" in health
        assert "topo_epoch" in health
        # wait for node0's detector to gossip node1's state
        _poll(lambda: ("node1" in a.http.peer_watermarks,
                       dict(a.http.peer_watermarks)))
        planner = a.http.make_planner("timeseries")
        shards = planner._resolve_shards(None)
        remote = [s for s in shards if hasattr(s, "fetch_raw")]
        assert remote
        for grp in remote:
            assert getattr(grp, "ingest_watermark_ms", None) is not None
            assert hasattr(grp, "ingest_backfill_epoch")
    finally:
        for srv in servers:
            srv.stop()
