"""Static cost bound vs the QoS runtime estimator: the monotone
cross-check the admission price rides on.

The invariant: for EVERY plan shape, ``static_cost_bound(plan, shards)
.total >= estimate_plan_cost(plan, shards).total`` — the static lattice
is a ceiling, so QoS can never silently under-charge a plan shape the
lint-time analysis already priced. Pinned over the same bench shapes
the QoS golden-ordering test uses, plus a generated-query sweep."""

import pytest

from filodb_tpu.promql.gen import QueryGen
from filodb_tpu.promql.parser import TimeStepParams, parse_query_range
from filodb_tpu.promql.semant import static_cost_bound
from filodb_tpu.query import qos
from filodb_tpu.standalone.server import FiloServer

T0 = 1_600_000_000


@pytest.fixture(scope="module")
def server():
    srv = FiloServer({"num-shards": 2, "grpc-port": None, "port": 0,
                      "results-cache-mb": 0,
                      "batch-enabled": False}).start()
    srv.seed_dev_data(n_samples=120, n_instances=4,
                      start_ms=T0 * 1000)
    try:
        yield srv
    finally:
        srv.stop()


# the QoS golden-ordering bench shapes (tests/test_qos.py _SHAPES)
# plus heavier trees: joins, subqueries, instant functions
_SHAPES = [
    ('heap_usage{instance="instance-0"}', T0 + 400, T0 + 500, 20),
    ('heap_usage{instance="instance-0"}', T0 + 300, T0 + 1190, 10),
    ('rate(http_requests_total[5m])', T0 + 300, T0 + 1190, 10),
    ('sum(rate({_metric_=~"heap_usage|http_requests_total"}[10m])) '
     'by (instance)', T0 + 300, T0 + 1190, 5),
    ('sum by (instance) (rate(http_requests_total[5m])) / '
     'sum by (instance) (rate(http_requests_total[10m]))',
     T0 + 300, T0 + 1190, 10),
    ('clamp_min(avg_over_time(heap_usage[2m]), 0) + 1',
     T0 + 300, T0 + 900, 15),
    ('max_over_time(sum(rate(http_requests_total[1m]))[10m:1m])',
     T0 + 600, T0 + 1190, 30),
    ('heap_usage', T0 + 400, T0 + 400, 0),      # instant query
]


def _check(plan, shards):
    bound = static_cost_bound(plan, shards)
    est = qos.estimate_plan_cost(plan, shards)
    assert bound.total >= est.total, (
        f"static bound {bound.total} < runtime estimate {est.total} "
        f"— QoS could under-charge this plan shape: {plan}")
    return bound, est


def test_bound_dominates_estimate_on_golden_shapes(server):
    planner = server.http.make_planner("timeseries")
    for query, start, end, step in _SHAPES:
        plan = parse_query_range(query,
                                 TimeStepParams(start, step, end))
        bound, est = _check(plan, planner.shards)
        # the bound is a ceiling, not a fantasy: within a constant
        # factor of the estimate on these healthy shapes
        assert bound.total <= 1000 * max(est.total, 1.0), (query, bound)


def test_bound_dominates_on_generated_queries(server):
    """Property sweep: 60 generated well-typed queries, every one
    bound >= estimate."""
    planner = server.http.make_planner("timeseries")
    g = QueryGen(seed=0xB0)
    for _ in range(60):
        q = g.query()
        plan = parse_query_range(
            q, TimeStepParams(T0 + 300, 15, T0 + 900))
        _check(plan, planner.shards)


def test_bound_is_monotone_in_breadth_and_span(server):
    planner = server.http.make_planner("timeseries")

    def bound(q, start=T0 + 300, step=10, end=T0 + 600):
        plan = parse_query_range(q, TimeStepParams(start, step, end))
        return static_cost_bound(plan, planner.shards).total

    one = bound('heap_usage{instance="instance-0"}')
    metric = bound('heap_usage')
    assert one <= metric
    short = bound('rate(http_requests_total[1m])')
    wide = bound('rate(http_requests_total[10m])')
    assert short < wide
    near = bound('heap_usage', end=T0 + 400)
    far = bound('heap_usage', end=T0 + 1100)
    assert near < far


def test_planner_facade_and_json_shape(server):
    planner = server.http.make_planner("timeseries")
    plan = parse_query_range('sum(rate(http_requests_total[5m]))',
                             TimeStepParams(T0 + 300, 10, T0 + 600))
    bound = planner.static_cost_bound(plan)
    j = bound.to_json()
    assert j["total"] >= planner.estimate_cost(plan).total
    assert j["seriesUpperBound"] >= 1
    assert j["stepsUpperBound"] == 31
    assert j["leaves"] and "seriesUpperBound" in j["leaves"][0]


def test_explain_analyze_carries_static_bound(server):
    """&explain=analyze records the static bound next to the QoS cost
    (the admission headroom surface)."""
    import json
    import urllib.request
    port = server.port
    url = (f"http://127.0.0.1:{port}/promql/timeseries/api/v1/"
           f"query_range?query=sum(rate(http_requests_total[5m]))"
           f"&start={T0 + 300}&end={T0 + 600}&step=10"
           f"&explain=analyze")
    with urllib.request.urlopen(url, timeout=30) as r:
        payload = json.loads(r.read())
    stages = payload["analyze"]["stages"]
    assert stages["staticCostBound"]["total"] > 0
    assert stages["staticCostBound"]["seriesUpperBound"] >= 1
