"""Chaos brownout scenario (tenant QoS, query/qos.py): sustained
overload from one abusive tenant PLUS node loss, with the pinned
acceptance semantics:

  * interactive-tenant queries complete with ZERO failures — degraded
    (partial) responses are allowed and counted, non-200s are not;
  * the abusive tenant is throttled to its budget: its clean
    admissions stop once the bucket drains, the rest of its traffic
    gets degraded answers (each stamped with a ``shed(...)`` warning)
    or 429 + Retry-After;
  * after the load and the node loss end, responses are byte-identical
    to the pre-load golden — no degraded/stale result ever poisoned a
    cache.

Chaos-config recipe (the documented brownout runbook shape, like the
PR 8 crash runbook's): interactive clients send ``allow_partial=true``
so a mid-loss fan-out degrades instead of failing; the failure
detector polls too slowly to react, so the exec-layer resilience is
what rides through the loss window — the same window
tests/test_chaos_query.py pins without QoS."""

import json
import socket
import threading
import time
import urllib.error
import urllib.parse
import urllib.request

import pytest

from filodb_tpu.standalone.server import FiloServer
from filodb_tpu.testing import chaos

T0 = 1_600_000_000
N_SAMPLES = 60
N_INSTANCES = 4

# the abuser's budget: refill 50 cost-units/s, burst 2000. The abusive
# query shape below prices in the thousands, so the bucket drains
# within the first couple of clean admissions and stays drained under
# sustained load.
ABUSE_RATE, ABUSE_BURST = 50, 2000

INTERACTIVE_Q = dict(query='sum(rate(heap_usage[1m]))',
                     start=T0 + 300, end=T0 + 400, step=20)
ABUSE_Q = dict(query='rate({_metric_=~"heap_usage|http_requests_total"}'
                     '[5m])',
               start=T0 + 300, end=T0 + (N_SAMPLES - 1) * 10, step=10)


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _get_raw(port, params, timeout=30):
    url = (f"http://127.0.0.1:{port}/promql/timeseries/api/v1/"
           f"query_range?" + urllib.parse.urlencode(params))
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, r.read(), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read(), dict(e.headers)


def _data_bytes(raw: bytes) -> bytes:
    """Verbatim data section (exact float strings, exact series order);
    the stats tail carries wall-clock timings and legitimately differs
    — the same boundary every byte-identity golden in this repo uses."""
    body, sep, _tail = raw.partition(b',"stats":')
    assert sep, raw[:200]
    return body


def _scrape(port, name):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics",
                                timeout=10) as r:
        text = r.read().decode()
    out = {}
    for ln in text.splitlines():
        if ln.startswith(name):
            series, _, val = ln.rpartition(" ")
            out[series] = float(val)
    return out


@pytest.fixture
def cluster():
    """Two in-process nodes, half the shards each, QoS budgets on for
    the abusive tenant only (everyone else is unbudgeted and must be
    untouched by the brownout)."""
    p0, p1 = _free_port(), _free_port()
    peers = {"node0": f"http://127.0.0.1:{p0}",
             "node1": f"http://127.0.0.1:{p1}"}
    base = {
        "num-shards": 4, "num-nodes": 2, "peers": peers,
        "failure-detect-interval-s": 300.0,    # detection never reacts
        "grpc-port": None,                     # deterministic HTTP plane
        "results-cache-mb": 16,
        "results-cache-hot-window-ms": 500.0,  # old data settles fast
        "query-timeout-s": 8.0,
        "max-inflight-queries": 16,
        "admission-wait-s": 2.0,
        "peer-retry-attempts": 1,
        "peer-retry-base-delay-s": 0.01,
        "breaker-failure-threshold": 1000,     # breakers stay closed
        "qos-tenant-overrides": {
            "abuser": [ABUSE_RATE, ABUSE_BURST],
            # rung-failure regression: WIDE_Q prices ~140k, its coarse
            # rung ~35k and partial rung ~16.5k — a 60k burst lets both
            # ladder rungs charge (and then fail under chaos) while the
            # full query stays over budget
            "rungfail": [50, 60_000],
            # never-admittable regression: every shape of a real query
            # prices above this burst
            "tinyburst": [1, 5],
            # alternative-hint regression: the medium drain query
            # (~22k) admits cleanly, after which the remaining ~16k
            # cannot charge either rung (coarse ~35k, partial ~16.5k)
            # while the coarse alternative still FITS the burst;
            # near-zero refill keeps the drain in place
            "althint": [0.001, 38_000],
        },
    }
    a = FiloServer({**base, "node-ordinal": 0, "port": p0}).start()
    a.seed_dev_data(n_samples=N_SAMPLES, n_instances=N_INSTANCES,
                    start_ms=T0 * 1000)
    b = FiloServer({**base, "node-ordinal": 1, "port": p1}).start()
    b.seed_dev_data(n_samples=N_SAMPLES, n_instances=N_INSTANCES,
                    start_ms=T0 * 1000)
    try:
        yield a, b
    finally:
        chaos.uninstall()
        for srv in (a, b):
            try:
                srv.stop()
            except Exception:
                pass


def test_brownout_overload_plus_node_loss(cluster):
    a, _b = cluster
    # -- pre-load goldens (fresh, cache-off, healthy cluster) ----------
    code, raw, _ = _get_raw(a.port, {**INTERACTIVE_Q, "cache": "false"})
    assert code == 200
    golden_interactive = _data_bytes(raw)
    assert b'"partial"' not in golden_interactive
    code, raw, _ = _get_raw(a.port, {**ABUSE_Q, "cache": "false"})
    assert code == 200
    golden_abuse = _data_bytes(raw)
    # warm the cache once so the stale-serve rung has an extent
    _get_raw(a.port, INTERACTIVE_Q)
    _get_raw(a.port, ABUSE_Q)

    stop = threading.Event()
    interactive_results = []      # (code, partial, warnings)
    abuse_results = []            # (code, warnings, retry_after)
    errors = []

    def interactive_loop():
        # the documented brownout-recipe client: allow_partial so a
        # mid-loss fan-out degrades instead of failing
        params = {**INTERACTIVE_Q, "allow_partial": "true",
                  "tenant": "interactive"}
        while not stop.is_set():
            try:
                code, raw, _ = _get_raw(a.port, params)
                body = json.loads(raw)
                interactive_results.append(
                    (code, bool(body.get("partial")),
                     body.get("warnings") or []))
            except Exception as e:   # noqa: BLE001 — recorded, asserted
                errors.append(repr(e))
            time.sleep(0.02)

    def abuse_loop():
        params = {**ABUSE_Q, "tenant": "abuser"}
        while not stop.is_set():
            try:
                code, raw, hdrs = _get_raw(a.port, params)
                try:
                    body = json.loads(raw)
                except ValueError:
                    body = {}
                abuse_results.append(
                    (code, body.get("warnings") or [],
                     hdrs.get("Retry-After")))
            except Exception as e:   # noqa: BLE001
                errors.append("abuse:" + repr(e))

    threads = [threading.Thread(target=interactive_loop, daemon=True)
               for _ in range(2)]
    threads += [threading.Thread(target=abuse_loop, daemon=True)]
    for t in threads:
        t.start()

    # phase 1: pure overload (healthy cluster) ~1.2s
    time.sleep(1.2)
    # phase 2: node loss mid-overload — every peer call to node1 fails
    # with the connection-refused shape while routing still points at it
    inj = chaos.ChaosInjector()
    inj.fail("http.peer", match=lambda c: c.get("node") == "node1")
    chaos.install(inj)
    time.sleep(1.5)
    chaos.uninstall()
    # phase 3: recovered cluster, overload continues ~0.8s
    time.sleep(0.8)
    stop.set()
    for t in threads:
        t.join(timeout=10)

    # -- zero interactive failures -------------------------------------
    assert not errors, errors
    assert interactive_results, "interactive load never ran"
    non_200 = [r for r in interactive_results if r[0] != 200]
    assert not non_200, f"interactive failures: {non_200[:5]}"
    degraded = [r for r in interactive_results if r[1] or r[2]]
    # during the loss window fan-outs to node1 degrade — allowed and
    # counted, never failed
    assert len(degraded) < len(interactive_results)

    # -- the abusive tenant is throttled to its budget ----------------
    assert abuse_results, "abuse load never ran"
    shed = [r for r in abuse_results
            if any("shed(" in w for w in r[1])]
    rejected = [r for r in abuse_results if r[0] == 429]
    assert shed or rejected, \
        "abuser was never throttled: %r" % (abuse_results[:5],)
    for code, _w, retry_after in rejected:
        assert retry_after is not None      # 429 always names a backoff
    # clean admissions are budget-bounded: total cost charged by
    # try_charge can never exceed burst + rate x elapsed (forced
    # charges are zero here — no entry hops carry tenant=abuser)
    snap = a.http.admission.budgets.bucket("abuser").snapshot()
    assert snap["throttled"] > 0
    elapsed_budget = ABUSE_BURST + ABUSE_RATE * 10   # generous bound
    assert snap["charged_total"] <= elapsed_budget

    # -- byte-identical recovery --------------------------------------
    code, raw, _ = _get_raw(a.port, {**INTERACTIVE_Q, "cache": "false"})
    assert code == 200
    assert _data_bytes(raw) == golden_interactive
    code, raw, _ = _get_raw(a.port, {**ABUSE_Q, "cache": "false"})
    assert code == 200
    assert _data_bytes(raw) == golden_abuse
    # the cache-warm path is also clean: degraded results were never
    # admitted (stale serves read, they never write)
    code, raw, _ = _get_raw(a.port, INTERACTIVE_Q)
    assert code == 200
    assert _data_bytes(raw) == golden_interactive


def test_noisy_tenant_does_not_throttle_others(cluster):
    """The selectivity pin, without chaos: after the abuser drains its
    bucket, an unbudgeted tenant's identical query still executes
    cleanly (no warnings, no partial, no 429)."""
    a, _b = cluster
    # drain: abuse queries until the first non-clean answer
    for _ in range(10):
        code, raw, _ = _get_raw(a.port, {**ABUSE_Q, "tenant": "abuser"})
        body = json.loads(raw)
        if code == 429 or body.get("warnings"):
            break
    else:
        pytest.fail("abuser never throttled")
    # the same query as another tenant: clean
    code, raw, _ = _get_raw(a.port,
                            {**ABUSE_Q, "cache": "false",
                             "tenant": "friendly"})
    body = json.loads(raw)
    assert code == 200
    assert not body.get("warnings") and not body.get("partial")
    # and the abuser's shed is visible in /metrics
    fams = _scrape(a.port, "filodb_tenant_throttled_total")
    assert fams.get(
        'filodb_tenant_throttled_total{tenant="abuser"}', 0) > 0


def test_qos_chaos_fault_points(cluster):
    """qos.admit / qos.shed fault points fire (testing/chaos.py): a
    brownout test can inject latency or errors exactly at the
    admission decision and the ladder entry."""
    a, _b = cluster
    inj = chaos.ChaosInjector()
    with inj:
        _get_raw(a.port, {**INTERACTIVE_Q, "tenant": "interactive"})
        assert inj.fired("qos.admit") == 1
        # over-budget entry: drain the abuser into the ladder
        for _ in range(10):
            code, raw, _ = _get_raw(a.port,
                                    {**ABUSE_Q, "tenant": "abuser"})
            if inj.fired("qos.shed"):
                break
        assert inj.fired("qos.shed") >= 1


# a wide query: >64 steps so the coarsen rung applies, fanning out
# across both nodes so a node-loss window can fail its execution
WIDE_Q = dict(query='rate({_metric_=~"heap_usage|http_requests_total"}'
                    '[5m])',
              start=T0 + 300, end=T0 + 502, step=2)


def test_shed_rung_failure_falls_through_to_429(cluster):
    """ROADMAP 5 regression: a degrade-ladder rung whose EXECUTION
    fails (here: rungs 2/3 fan out into a lost node) must fall through
    to the next rung / terminal 429 — never surface as a 400 — and the
    failed rung's charge is refunded."""
    a, _b = cluster
    params = {**WIDE_Q, "tenant": "rungfail", "cache": "false"}
    inj = chaos.ChaosInjector()
    inj.fail("http.peer", match=lambda c: c.get("node") == "node1")
    chaos.install(inj)
    try:
        code, raw, hdrs = _get_raw(a.port, params)
    finally:
        chaos.uninstall()
    body = json.loads(raw)
    assert code == 429, (code, raw[:300])
    assert body.get("errorType") == "throttled", body
    # both compute rungs charged, failed, and refunded: the bucket is
    # back near its burst (minus only eventual-refill rounding), and
    # the charges DID happen (the rungs executed, not skipped)
    snap = a.http.admission.budgets.bucket("rungfail").snapshot()
    assert snap["remaining"] >= 59_000, snap
    assert snap["admitted"] >= 2, snap
    # and with the cluster healthy again the same over-budget query
    # gets a degraded 200 from the same ladder (the rung itself works)
    code, raw, _ = _get_raw(a.port, params)
    assert code == 200, raw[:300]
    body = json.loads(raw)
    assert any("shed(" in w for w in body.get("warnings") or []), body


def test_never_admittable_full_bucket(cluster):
    """ROADMAP 5 regression: a cost-above-burst query against a FULL
    bucket used to answer a misleading `Retry-After: 1` (waiting can
    never help — burst is the largest clean admission). It must now
    carry an explicit never-admittable marker, and when no degraded
    shape fits the burst either, omit Retry-After entirely."""
    a, _b = cluster
    code, raw, hdrs = _get_raw(
        a.port, {**WIDE_Q, "tenant": "tinyburst", "cache": "false"})
    body = json.loads(raw)
    assert code == 429, (code, raw[:300])
    assert "never admittable" in body.get("error", ""), body
    assert "Retry-After" not in hdrs, hdrs
    # the bucket was full the whole time: nothing charged
    snap = a.http.admission.budgets.bucket("tinyburst").snapshot()
    assert snap["remaining"] >= 4.5, snap


def test_never_admittable_names_cheaper_alternative(cluster):
    """When a degraded shape of the query WOULD fit the burst (but the
    partially-drained bucket can't charge it right now), the 429 body
    names that alternative and Retry-After reflects it — not the
    impossible full-cost admission."""
    a, _b = cluster
    # drain partway: a medium query that admits cleanly
    med = dict(query='sum(rate(heap_usage[5m]))',
               start=T0 + 300, end=T0 + 500, step=2,
               tenant="althint", cache="false")
    code, raw, _ = _get_raw(a.port, med)
    assert code == 200, raw[:300]
    snap = a.http.admission.budgets.bucket("althint").snapshot()
    assert snap["remaining"] < snap["burst"], snap
    # the wide query prices above burst; its degraded shapes fit the
    # burst but not the drained tokens -> rejection with the hint
    code, raw, hdrs = _get_raw(
        a.port, {**WIDE_Q, "tenant": "althint", "cache": "false"})
    body = json.loads(raw)
    if code == 200:
        # the drain left enough tokens for a ladder rung — legitimate
        # degraded answer; the regression target is only the 429 shape
        assert any("shed(" in w for w in body.get("warnings") or [])
        return
    assert code == 429, (code, raw[:300])
    assert "never admit" in body.get("error", ""), body
    assert "fits the burst" in body.get("error", ""), body
    assert hdrs.get("Retry-After") is not None, hdrs
