"""Well-typed query generator tests: determinism, validity through the
type checker, and surface breadth (the generator must keep exercising
a wide slice of PromQL or the differential rail silently narrows)."""

import re

from filodb_tpu.promql import semant
from filodb_tpu.promql.gen import DEFAULT_METRICS, MetricSpec, QueryGen
from filodb_tpu.promql.parser import TimeStepParams, parse_query_range


def test_deterministic_per_seed():
    a = QueryGen(seed=7).queries(30)
    b = QueryGen(seed=7).queries(30)
    assert a == b
    c = QueryGen(seed=8).queries(30)
    assert a != c


def test_every_query_is_well_typed_and_plannable():
    g = QueryGen(seed=123)
    schemas = semant.MetricSchemas(
        {m.name: m.kind for m in DEFAULT_METRICS})
    params = TimeStepParams(1_600_000_000, 30, 1_600_000_600)
    for q in g.queries(100):
        diags = semant.errors(semant.lint_query(q, schemas))
        assert not diags, (q, [d.rule for d in diags])
        parse_query_range(q, params)    # must not raise


def test_surface_breadth():
    """One seed's first 150 queries must cover range functions,
    aggregation, binary ops, subqueries and instant functions."""
    qs = QueryGen(seed=0xBEEF).queries(150)
    text = "\n".join(qs)
    assert "rate(" in text
    assert re.search(r"\b(sum|avg|min|max|count) ", text) or \
        re.search(r"\b(sum|avg|min|max|count)\(", text)
    assert "[4m:" in text or "[6m:" in text or "[10m:" in text  # subquery
    assert re.search(r"\bbool\b", text)
    assert re.search(r"\boffset\b", text)
    assert re.search(r"\b(and|or|unless)\b", text)
    assert re.search(r"\bclamp", text)
    fns = set(re.findall(r"([a-z_0-9]+)\(", text))
    assert len(fns) >= 12, sorted(fns)


def test_counter_metrics_feed_counter_functions_only():
    """Schema discipline by construction: rate/increase/irate never
    see a gauge metric, delta/deriv never see a counter."""
    qs = QueryGen(seed=5).queries(120)
    from filodb_tpu.promql.gen import DEFAULT_HISTOGRAM
    counters = {m.name for m in DEFAULT_METRICS if m.kind == "counter"}
    counters.add(DEFAULT_HISTOGRAM.name)      # buckets are counters
    gauges = {m.name for m in DEFAULT_METRICS if m.kind == "gauge"}
    for q in qs:
        for m in re.finditer(
                r"\b(rate|increase|irate|resets)\(([a-z_0-9]+)", q):
            assert m.group(2) in counters, q
        for m in re.finditer(r"\b(delta|idelta|deriv)\(([a-z_0-9]+)",
                             q):
            assert m.group(2) in gauges, q


def test_custom_metric_universe():
    spec = (MetricSpec("my_total", "counter",
                       (("dc", ("a", "b")),)),)
    g = QueryGen(seed=1, metrics=spec)
    qs = g.queries(20)
    assert all("my_total" in q or "dc" in q or
               not re.search(r"[a-z_]+\{", q) for q in qs)
    for q in qs:
        assert "http_requests_total" not in q


def test_generator_self_check_fails_loudly():
    """With validation on, a drifted generator raises instead of
    emitting invalid queries (sanity: validate=False still yields)."""
    g = QueryGen(seed=2, validate=False)
    assert len(g.queries(5)) == 5
