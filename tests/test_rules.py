"""Recording rules & alerting (filodb_tpu/rules): loader/validator
units, the alert state machine under a deterministic clock, single-owner
election, the rule-plan cache's invalidation hook, the factored
write-back rail, and the shipped example file's tier-1 validation gate.
"""

import math
import threading
import time

import numpy as np
import pytest

from filodb_tpu.core.memstore import TimeSeriesMemStore
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetRef
from filodb_tpu.obs.writeback import (IngestWriteBack, schema_for_sample)
from filodb_tpu.query.model import GridResult
from filodb_tpu.rules import (RULES_DATASET, Rule, RuleGroup,
                              RuleLoadError, RulesEngine, WebhookNotifier,
                              check_rules_file, load_groups,
                              parse_rules_text)
from filodb_tpu.rules.engine import (_render_template, STATE_FIRING,
                                     STATE_PENDING)

T0 = 1_600_000_000


# ---------------------------------------------------------------------------
# loader / validator
# ---------------------------------------------------------------------------

def test_load_groups_full_shape():
    groups = load_groups({"groups": [
        {"name": "g", "interval": "30s", "dataset": "ds1", "rules": [
            {"record": "a:rate5m", "expr": "rate(x_total[5m])",
             "labels": {"tier": "gold"}},
            {"alert": "Hot", "expr": "rate(x_total[5m]) > 1",
             "for": "2m", "labels": {"severity": "page"},
             "annotations": {"summary": "hot: {{ $value }}"}},
        ]},
    ]})
    (g,) = groups
    assert g.name == "g" and g.interval_s == 30.0 and g.dataset == "ds1"
    rec, al = g.rules
    assert rec.kind == "recording" and rec.labels == (("tier", "gold"),)
    assert al.is_alert and al.for_s == 120.0
    assert dict(al.annotations)["summary"] == "hot: {{ $value }}"


def test_load_groups_validation_errors():
    errors = []
    load_groups({"groups": [
        {"name": "g", "interval": "30s", "rules": [
            {"record": "ok:one", "expr": "sum(x)"},
            {"record": "bad name!", "expr": "sum(x)"},
            {"record": "syntax", "expr": "rate(x_total[5m"},
            {"alert": "A", "expr": "x > 1", "schema": "counter"},
            {"record": "r2", "expr": "sum(x)", "for": "1m"},
            {"expr": "sum(x)"},
            {"record": "both", "alert": "both", "expr": "sum(x)"},
        ]},
        {"name": "g", "rules": [{"record": "ok:two", "expr": "x"}]},
    ]}, errors=errors)
    text = "\n".join(errors)
    assert "invalid metric name" in text
    assert "PromQL syntax error" in text
    assert "schema: is recording-only" in text
    assert "for: is alert-only" in text
    assert "exactly one of record:/alert: required" in text
    assert "duplicate group name" in text


def test_duplicate_rule_detection_across_groups():
    errors = []
    load_groups({"groups": [
        {"name": "g1", "rules": [
            {"record": "dup:rule", "expr": "sum(x)"}]},
        {"name": "g2", "rules": [
            {"record": "dup:rule", "expr": "sum(y)"}]},
    ]}, errors=errors)
    assert any("duplicate rule" in e for e in errors)
    # same name with DIFFERENT labels is legal (distinct series)
    groups = load_groups({"groups": [
        {"name": "g1", "rules": [
            {"record": "dup:rule", "expr": "sum(x)",
             "labels": {"a": "1"}}]},
        {"name": "g2", "rules": [
            {"record": "dup:rule", "expr": "sum(y)",
             "labels": {"a": "2"}}]},
    ]})
    assert len(groups) == 2


def test_parse_rules_text_yaml_and_json():
    yaml_text = ("groups:\n- name: g\n  interval: 15s\n  rules:\n"
                 "  - record: a:b\n    expr: sum(x)\n")
    json_text = ('{"groups": [{"name": "g", "interval": 15, "rules": '
                 '[{"record": "a:b", "expr": "sum(x)"}]}]}')
    for text in (yaml_text, json_text):
        (g,) = parse_rules_text(text)
        assert g.interval_s == 15.0 and g.rules[0].name == "a:b"
    with pytest.raises(RuleLoadError):
        parse_rules_text('{"groups": []}')


def test_shipped_example_file_is_clean():
    """Tier-1 gate for the shipped example: the file every README
    snippet points at must validate with the promtool-style checker."""
    import os
    path = os.path.join(os.path.dirname(__file__), "..", "examples",
                        "rules.yaml")
    assert check_rules_file(path) == []


def test_check_cli_exit_codes(tmp_path):
    from filodb_tpu.rules.__main__ import main
    good = tmp_path / "good.json"
    good.write_text('{"groups": [{"name": "g", "rules": '
                    '[{"record": "a:b", "expr": "sum(x)"}]}]}')
    bad = tmp_path / "bad.json"
    bad.write_text('{"groups": [{"name": "g", "rules": '
                   '[{"record": "a:b", "expr": "rate(x["}]}]}')
    assert main(["--check", str(good)]) == 0
    assert main(["--check", str(bad)]) == 1
    assert main(["--check", str(tmp_path / "missing.json")]) == 1


def test_render_template():
    assert _render_template("v={{ $value }} on {{ $labels.instance }}",
                            1.5, {"instance": "i0"}) == "v=1.5 on i0"
    assert _render_template("plain", 1.0, {}) == "plain"
    assert _render_template("{{ $labels.missing }}!", None, {}) == "!"


# ---------------------------------------------------------------------------
# engine under a deterministic clock (fake evaluator)
# ---------------------------------------------------------------------------

class _FakeEvaluator:
    """Scripted evaluator: maps rule expr -> list of (labels, value)
    series for the LAST step; records every call."""

    def __init__(self):
        self.series = {}
        self.calls = []
        self.raise_for = set()

    def __call__(self, ds, query, plan, start_ms, step_ms, end_ms):
        self.calls.append((ds, query, start_ms, step_ms, end_ms))
        if query in self.raise_for:
            raise RuntimeError("injected eval failure")
        rows = self.series.get(query, [])
        steps = np.arange(start_ms, end_ms + 1, step_ms, dtype=np.int64)
        keys = [dict(labels) for labels, _v in rows]
        values = np.full((len(rows), steps.size), np.nan)
        for i, (_l, v) in enumerate(rows):
            values[i, :] = v
        return (GridResult(steps, keys, values),
                {"resultCache": "partial", "cachedSteps": steps.size - 1})


def _mk_engine(groups, evaluator=None, clock=None, **kw):
    store = TimeSeriesMemStore(DEFAULT_SCHEMAS)
    shard = store.setup(DatasetRef(RULES_DATASET), 0, num_groups=2)
    ev = evaluator or _FakeEvaluator()
    eng = RulesEngine(groups, evaluator=ev,
                      writeback=IngestWriteBack(shard),
                      default_dataset="ts", node="n0",
                      clock=clock or time.time, **kw)
    return eng, ev, shard


def _lookup(shard, metric):
    from filodb_tpu.core.index import ColumnFilter
    return shard.lookup_partitions(
        [ColumnFilter("_metric_", "eq", metric)], 0, 1 << 62)


def test_recording_rule_writes_back_with_schema():
    g = RuleGroup("g", 10.0, (
        Rule("job:x:rate", "rate(x_total[1m])", "recording",
             labels=(("tier", "gold"),)),
        Rule("job:x:events_total", "sum(increase(x_total[1m]))",
             "recording", schema="counter"),
    ))
    eng, ev, shard = _mk_engine([g])
    ev.series["rate(x_total[1m])"] = [({"instance": "i0"}, 1.5),
                                      ({"instance": "i1"}, 2.5)]
    ev.series["sum(increase(x_total[1m]))"] = [({}, 60.0)]
    out = eng.eval_group_once(g, T0 + 100)
    assert out["ok"] and out["samples"] == 3
    parts = _lookup(shard, "job:x:rate")
    labelled = {dict(p.part_key.labels)["instance"]: p for p in parts}
    assert set(labelled) == {"i0", "i1"}
    for p in parts:
        lm = dict(p.part_key.labels)
        # re-tagged into the reserved dataset + rule labels applied;
        # NO worker label (a recorded series' identity must survive
        # evaluator failover)
        assert lm["_ws_"] == RULES_DATASET and lm["_ns_"] == "n0"
        assert lm["tier"] == "gold" and "worker" not in lm
        assert p.schema.name == "gauge"     # name heuristic: not *_total
    (cp,) = _lookup(shard, "job:x:events_total")
    assert cp.schema.name == "prom-counter"  # explicit schema: counter


def test_eval_window_is_step_aligned_tail():
    g = RuleGroup("g", 10.0, (
        Rule("r:x", "sum(x)", "recording"),))
    eng, ev, _ = _mk_engine([g], span_steps=8)
    eng.eval_group_once(g, T0 + 105)        # unaligned on purpose
    (_ds, _q, start_ms, step_ms, end_ms) = ev.calls[-1]
    assert step_ms == 10_000
    assert end_ms % step_ms == 0            # boundary-aligned grid
    assert (end_ms - start_ms) // step_ms == 7   # span_steps-1 tail
    # the next tick shares the grid phase: the results-cache key is
    # identical modulo the slide (cache-warm tail recompute)
    eng.eval_group_once(g, T0 + 115)
    (_ds, _q, start2, step2, end2) = ev.calls[-1]
    assert step2 == step_ms and end2 - end_ms == 10_000
    assert start2 % step_ms == start_ms % step_ms


def test_alert_state_machine_pending_firing_inactive():
    g = RuleGroup("g", 10.0, (
        Rule("Hot", "rate(x_total[1m]) > 1", "alerting", for_s=20.0,
             labels=(("severity", "page"),),
             annotations=(("summary", "hot {{ $value }}"),)),))
    eng, ev, shard = _mk_engine([g])
    q = "rate(x_total[1m]) > 1"

    ev.series[q] = []                       # expr empty -> inactive
    eng.eval_group_once(g, T0)
    assert eng.alerts_payload()["alerts"] == []

    ev.series[q] = [({"instance": "i0"}, 3.0)]
    eng.eval_group_once(g, T0 + 10)         # active -> pending
    (a,) = eng.alerts_payload()["alerts"]
    assert a["state"] == STATE_PENDING and a["activeAt"] == T0 + 10
    assert a["labels"]["severity"] == "page"
    assert a["annotations"]["summary"] == "hot 3"

    eng.eval_group_once(g, T0 + 20)         # held 10s < for 20s
    (a,) = eng.alerts_payload()["alerts"]
    assert a["state"] == STATE_PENDING

    eng.eval_group_once(g, T0 + 30)         # held 20s -> firing
    (a,) = eng.alerts_payload()["alerts"]
    assert a["state"] == STATE_FIRING and a["value"] == 3.0

    # synthetic state series rode the write-back rail
    alerts_parts = _lookup(shard, "ALERTS")
    states = {dict(p.part_key.labels)["alertstate"]
              for p in alerts_parts}
    assert states == {"pending", "firing"}
    (fs,) = _lookup(shard, "ALERTS_FOR_STATE")
    assert dict(fs.part_key.labels)["alertname"] == "Hot"

    ev.series[q] = []                       # expr clears -> inactive
    eng.eval_group_once(g, T0 + 40)
    assert eng.alerts_payload()["alerts"] == []
    tr = [(t["from"], t["to"])
          for t in eng.alerts_payload()["transitions"]]
    assert tr == [("inactive", "pending"), ("pending", "firing"),
                  ("firing", "inactive")]


def test_alert_for_zero_fires_immediately():
    g = RuleGroup("g", 10.0, (
        Rule("Now", "x > 1", "alerting", for_s=0.0),))
    eng, ev, _ = _mk_engine([g])
    ev.series["x > 1"] = [({}, 9.0)]
    eng.eval_group_once(g, T0)
    (a,) = eng.alerts_payload()["alerts"]
    assert a["state"] == STATE_FIRING


def test_eval_failure_keeps_alert_state_and_counts():
    """An evaluation ERROR must not flap a firing alert to inactive —
    the state is kept, the failure family counts, health goes err."""
    g = RuleGroup("g", 10.0, (
        Rule("Hot", "x > 1", "alerting", for_s=0.0),))
    eng, ev, _ = _mk_engine([g])
    ev.series["x > 1"] = [({}, 2.0)]
    eng.eval_group_once(g, T0)
    assert eng.alerts_payload()["alerts"][0]["state"] == STATE_FIRING

    ev.raise_for.add("x > 1")
    eng.eval_group_once(g, T0 + 10)
    (a,) = eng.alerts_payload()["alerts"]
    assert a["state"] == STATE_FIRING       # did not flap
    payload = eng.rules_payload()
    (rule,) = payload["groups"][0]["rules"]
    assert rule["health"] == "err"
    assert "injected eval failure" in rule["lastError"]
    fails = {tuple(sorted(lbl.items())): v for lbl, v in
             eng._m_failures.series()}
    assert fails[(("group", "g"), ("rule", "Hot"))] == 1


def test_rules_payload_explain_retains_last_eval():
    g = RuleGroup("g", 10.0, (Rule("r:x", "sum(x)", "recording"),))
    eng, ev, _ = _mk_engine([g])
    ev.series["sum(x)"] = [({}, 1.0)]
    eng.eval_group_once(g, T0 + 10)
    plain = eng.rules_payload()["groups"][0]["rules"][0]
    assert "lastEval" not in plain
    assert plain["health"] == "ok" and plain["lastEvaluation"] == T0 + 10
    rich = eng.rules_payload(explain=True)["groups"][0]["rules"][0]
    le = rich["lastEval"]
    assert le["query"] == "sum(x)" and le["samples"] == 1
    assert le["stages"]["resultCache"] == "partial"
    assert le["stages"]["rulePlanCache"] in ("miss", "uncacheable")


def test_scheduler_due_skips_first_boundary_and_counts_missed():
    g = RuleGroup("g", 10.0, (Rule("r:x", "sum(x)", "recording"),))
    eng, ev, _ = _mk_engine([g])
    ev.series["sum(x)"] = [({}, 1.0)]
    # first due check only claims the current boundary (the previous
    # evaluator is assumed to have run it)
    assert eng.evaluate_due(now_s=T0 + 105) == 0
    assert eng.evaluate_due(now_s=T0 + 107) == 0
    assert eng.evaluate_due(now_s=T0 + 112) == 1    # next boundary
    # a long stall skips boundaries -> missed counter
    assert eng.evaluate_due(now_s=T0 + 145) == 1
    missed = {tuple(sorted(lbl.items())): v for lbl, v in
              eng._m_missed.series()}
    assert missed[(("group", "g"),)] == 2


def test_single_owner_election_and_takeover_skip():
    clock = {"t": T0 + 100}
    g = RuleGroup("g", 10.0, (Rule("r:x", "sum(x)", "recording"),))
    eng, ev, _ = _mk_engine([g], worker_id=1, num_workers=3,
                            clock=lambda: clock["t"])
    ev.series["sum(x)"] = [({}, 1.0)]
    # ordinal 1 of {0,1,2}: worker 0 evaluates, this engine stands by
    assert not eng.snapshot()["active"]
    assert eng.evaluate_due(now_s=T0 + 100) == 0
    # worker 0 dies at T0+105 -> this engine takes over, CLAIMING the
    # in-progress boundary at the election instant (the dead worker is
    # assumed to have run it); the next boundary evaluates
    clock["t"] = T0 + 105
    eng.note_worker_exit(0)
    assert eng.snapshot()["active"]
    assert eng.evaluator_ordinal() == 1
    assert eng.evaluate_due(now_s=T0 + 107) == 0     # claimed T0+100
    assert eng.evaluate_due(now_s=T0 + 112) == 1     # owns T0+110
    # worker 0 respawns at T0+121 -> step down, but a boundary that
    # fell due BEFORE the handover beat and had not run yet (T0+120:
    # scheduler-poll race) is still ours — ONE final catch-up pass
    clock["t"] = T0 + 121
    eng.note_worker_up(0)
    assert not eng.snapshot()["active"]
    assert eng.evaluate_due(now_s=T0 + 121.5) == 1   # catch-up: T0+120
    assert eng.evaluate_due(now_s=T0 + 135) == 0     # retired
    assert len(ev.calls) == 2


def test_plan_cache_rebases_and_invalidates():
    g = RuleGroup("g", 10.0, (
        Rule("r:x", "rate(x_total[1m])", "recording"),))
    eng, ev, _ = _mk_engine([g])
    ev.series["rate(x_total[1m])"] = [({}, 1.0)]
    eng.eval_group_once(g, T0 + 10)
    eng.eval_group_once(g, T0 + 20)
    st = eng.rules_payload(explain=True)["groups"][0]["rules"][0]
    assert st["lastEval"]["stages"]["rulePlanCache"] == "hit"
    eng.invalidate_plans("topology")
    assert eng.snapshot()["plan_invalidations"] == 1
    eng.eval_group_once(g, T0 + 30)
    st = eng.rules_payload(explain=True)["groups"][0]["rules"][0]
    assert st["lastEval"]["stages"]["rulePlanCache"] == "miss"


def test_group_limit_is_enforced():
    g = RuleGroup("g", 10.0, (Rule("r:x", "sum(x)", "recording"),),
                  limit=1)
    eng, ev, _ = _mk_engine([g])
    ev.series["sum(x)"] = [({"i": "0"}, 1.0), ({"i": "1"}, 2.0)]
    eng.eval_group_once(g, T0)
    (rule,) = eng.rules_payload()["groups"][0]["rules"]
    assert rule["health"] == "err" and "over the group limit" in \
        rule["lastError"]


# ---------------------------------------------------------------------------
# write-back rail factoring (obs/writeback.py)
# ---------------------------------------------------------------------------

def test_schema_for_sample_heuristic():
    assert schema_for_sample("counter", "x") == "prom-counter"
    assert schema_for_sample("histogram", "x_bucket") == "prom-counter"
    assert schema_for_sample("gauge", "x_total") == "prom-counter"
    assert schema_for_sample("gauge", "x") == "gauge"


def test_ingest_writeback_direct_and_flush():
    store = TimeSeriesMemStore(DEFAULT_SCHEMAS)
    shard = store.setup(DatasetRef("wbtest"), 0, num_groups=2)
    wb = IngestWriteBack(shard)
    n = wb.write([
        ("gauge", {"_metric_": "g1", "i": "0"}, T0 * 1000, 1.5),
        ("prom-counter", {"_metric_": "c_total", "i": "0"},
         T0 * 1000, 7.0),
    ])
    assert n == 2 and wb.samples_written == 2 and not wb.durable
    wb.flush()
    parts = shard.lookup_partitions([], 0, 1 << 62)
    names = sorted(dict(p.part_key.labels)["_metric_"] for p in parts)
    assert names == ["c_total", "g1"]


def test_selfmon_uses_shared_rail():
    """The factoring satellite's pin: SelfMonitor writes through the
    same IngestWriteBack class the rules engine uses."""
    from filodb_tpu.obs.selfmon import SelfMonitor
    store = TimeSeriesMemStore(DEFAULT_SCHEMAS)
    shard = store.setup(DatasetRef("__selfmon__"), 0, num_groups=2)

    def src():
        from filodb_tpu.obs.metrics import ExpositionBuilder
        b = ExpositionBuilder()
        b.sample("x_total", {}, 3, mtype="counter", help="x")
        return b
    sm = SelfMonitor(src, shard, interval_s=3600)
    assert isinstance(sm.writeback, IngestWriteBack)
    sm.collect_once(now_ms=T0 * 1000)
    assert sm.writeback.samples_written == 1


# ---------------------------------------------------------------------------
# notifier
# ---------------------------------------------------------------------------

def test_notifier_queue_full_drops_not_blocks():
    n = WebhookNotifier("http://127.0.0.1:1/none", queue_size=2)
    assert n.enqueue({"status": "firing"})
    assert n.enqueue({"status": "firing"})
    assert not n.enqueue({"status": "firing"})
    assert n.snapshot()["dropped"] == 1


def test_notifier_delivers_with_retry_through_breaker():
    """A flaky receiver (fails the first 2 attempts) still gets the
    alert: retried under the resilience policy; the breaker tracks the
    receiver."""
    import http.server
    import socketserver

    fails = {"n": 2}
    got = []

    class H(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            body = self.rfile.read(
                int(self.headers.get("Content-Length") or 0))
            if fails["n"] > 0:
                fails["n"] -= 1
                self.send_response(503)
                self.send_header("Content-Length", "0")
                self.end_headers()
                return
            got.append(body)
            self.send_response(200)
            self.send_header("Content-Length", "0")
            self.end_headers()

        def log_message(self, *a):
            pass

    with socketserver.TCPServer(("127.0.0.1", 0), H) as httpd:
        port = httpd.server_address[1]
        t = threading.Thread(target=httpd.serve_forever, daemon=True)
        t.start()
        from filodb_tpu.parallel.resilience import RetryPolicy
        n = WebhookNotifier(f"http://127.0.0.1:{port}/hook",
                            retry=RetryPolicy(max_attempts=4,
                                              base_delay_s=0.01))
        n.start()
        assert n.enqueue({"status": "firing",
                          "labels": {"alertname": "Hot"},
                          "annotations": {"summary": "s"}})
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and not got:
            time.sleep(0.05)
        n.stop()
        httpd.shutdown()
    assert got, "webhook never delivered"
    import json
    payload = json.loads(got[0])
    assert payload["alerts"][0]["labels"]["alertname"] == "Hot"
    assert payload["status"] == "firing"
    assert n.snapshot()["delivered"] == 1
    # the registry recorded the retries on the breaker key
    snap = n.breakers.metrics_snapshot()
    (entry,) = snap.values()
    assert entry["retries"] >= 2 and entry["state"] == "closed"


def test_engine_enqueues_fire_and_resolve_notifications():
    class _Spy:
        def __init__(self):
            self.items = []

        def enqueue(self, n):
            self.items.append(n)
            return True

        def stop(self, timeout=None):
            pass

    g = RuleGroup("g", 10.0, (
        Rule("Hot", "x > 1", "alerting", for_s=0.0,
             annotations=(("summary", "v={{ $value }}"),)),))
    spy = _Spy()
    eng, ev, _ = _mk_engine([g], notifier=spy)
    ev.series["x > 1"] = [({}, 2.0)]
    eng.eval_group_once(g, T0)
    ev.series["x > 1"] = []
    eng.eval_group_once(g, T0 + 10)
    assert [n["status"] for n in spy.items] == ["firing", "resolved"]
    assert spy.items[0]["annotations"]["summary"] == "v=2"
    assert spy.items[0]["labels"]["alertname"] == "Hot"
