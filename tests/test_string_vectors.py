"""String columnar vectors: const / dict-UTF8 (multi-width index) / raw
UTF8 codecs, and a string-valued data column round-tripping through
ingest -> encode -> chunk decode -> merged read.

(Parity model: memory/format/vectors/UTF8Vector.scala,
DictUTF8Vector.scala, ConstVector.scala; multi-width index stream per
IntBinaryVector.scala:15.)"""

import numpy as np
import pytest

from filodb_tpu.core.memstore import TimeSeriesShard
from filodb_tpu.core.record import RecordBuilder
from filodb_tpu.core.schemas import (Column, ColumnType, DataSchema,
                                     DatasetRef, Schemas)
from filodb_tpu.memory import vectors as bv

T0 = 1_600_000_000_000


@pytest.mark.parametrize("vals,kind", [
    (["up"] * 50, bv.K_STR_CONST),
    ((["ok", "warn", "crit"] * 40), bv.K_STR_DICT),
    ([f"unique-{i}" * 40 for i in range(300)], bv.K_STR_UTF8),
])
def test_string_codec_roundtrip(vals, kind):
    buf = bv.encode_strings(vals)
    assert buf[0] == kind
    got = bv.decode_strings(buf)
    assert list(got) == list(vals)


def test_string_codec_wide_dict_uses_16bit_indices():
    vals = [f"v{i % 1000}" for i in range(3000)]
    buf = bv.encode_strings(vals)
    assert buf[0] == bv.K_STR_DICT
    assert list(bv.decode_strings(buf)) == vals
    # dict + 16-bit codes beat the raw offsets+blob form
    raw = (["x" * 6] * 0) or None
    assert len(buf) < 3000 * 4 + sum(len(v) for v in vals)


def test_string_codec_empty_and_none():
    buf = bv.encode_strings([])
    assert list(bv.decode_strings(buf)) == []
    buf = bv.encode_strings([None, "a", None])
    assert list(bv.decode_strings(buf)) == ["", "a", ""]


STRING_SCHEMAS = Schemas(schemas={
    "event": DataSchema(
        name="event",
        columns=(Column("timestamp", ColumnType.LONG),
                 Column("count", ColumnType.DOUBLE),
                 Column("level", ColumnType.STRING)),
        value_column="count"),
})


def test_string_column_roundtrip_through_shard():
    shard = TimeSeriesShard(DatasetRef("ev"), STRING_SCHEMAS, 0,
                            max_chunk_rows=40)
    b = RecordBuilder(STRING_SCHEMAS)
    levels = ["info", "warn", "info", "error"]
    for t in range(100):
        b.add_sample("event", {"_metric_": "app_events", "_ws_": "w",
                               "_ns_": "n"},
                     T0 + t * 1000, float(t), levels[t % 4])
    for c in b.containers():
        shard.ingest(c)
    part = next(iter(shard.partitions.values()))
    # encoded chunks exist (40-row buffers switched twice) + live tail
    assert part.num_chunks >= 2
    col_i = STRING_SCHEMAS.by_name("event").columns.index(
        next(c for c in STRING_SCHEMAS.by_name("event").columns
             if c.col_type == ColumnType.STRING))
    ts, vals, chunk_len = part.read_full(col_i)
    assert ts.size == 100
    assert chunk_len < 100          # tail rows merged from live buffer
    assert list(vals) == [levels[t % 4] for t in range(100)]
    # the encoded vector is dict-encoded (4 distinct values)
    assert part.chunks[0].vectors[col_i][0] == bv.K_STR_DICT
    # flush the tail; the full read now comes from chunks alone
    shard.flush_all()
    ts2, vals2, chunk_len2 = part.read_full(col_i)
    assert chunk_len2 == 100
    assert list(vals2) == list(vals)
