"""graftlint v4 ulp-certification rail: every numeric annotation in
the tree is dynamically certified (engine-as-assertion), order claims
run at 1/2/4/8 virtual devices, and a LYING annotation — the mutated
twin — is flagged by the rail. The annotations are real production
claims; these tests make the rail's teeth non-vacuous."""

import math

import numpy as np
import pytest

from filodb_tpu.lint import numerics as nmod
from filodb_tpu.lint import ulpcert


@pytest.fixture(scope="module")
def results():
    return {r.name: r for r in ulpcert.certify_all()}


def test_every_tree_annotation_is_certified(results):
    """Every @precision/@order_insensitive claim registered by the
    engine modules certifies against its declared tolerance."""
    nmod.import_annotated_modules()
    assert nmod.PRECISION and nmod.ORDER, "annotations disappeared"
    for name in list(nmod.PRECISION) + list(nmod.ORDER):
        assert name in results, f"claim {name!r} never certified"
        r = results[name]
        assert r.ok, (f"claim {name!r} failed certification: "
                      f"measured {r.measured} vs {r.claimed} "
                      f"({r.detail})")


def test_expected_claim_inventory(results):
    """The in-tree hybrid sites the issue names are all annotated —
    the counter fast/slide path, the f32 epilogue (instant division
    chain), the fixed-point split, the donated append carry, and both
    mesh psum collectives."""
    assert {"counter-fast-hybrid", "counter-slide-hybrid",
            "counter-epilogue-f32", "counter-exact-slot-index",
            "fixed-point-split", "append-carry-exact",
            "groupsum-recombine-f32", "extrapolated-rate-f64"} \
        <= set(nmod.PRECISION)
    assert {"grouped-reduce-psum", "grouped-pair-psum"} \
        <= set(nmod.ORDER)


def test_order_claims_ran_at_1_2_4_8_devices(results):
    """The acceptance pin: order-insensitivity is certified across the
    full virtual device sweep, not vacuously at one count."""
    for name in nmod.ORDER:
        r = results[name]
        assert r.device_counts == (1, 2, 4, 8), (name, r.device_counts)


def test_measured_values_leave_headroom(results):
    """The claims are tight-but-honest: measured error is nonzero
    where rounding exists (the certification is not comparing a
    function against itself) and under the claim with margin."""
    fast = results["counter-fast-hybrid"]
    assert 0 < fast.measured <= fast.claimed
    epi = results["counter-epilogue-f32"]
    assert 0 < epi.measured <= epi.claimed
    # exact claims certify bitwise
    assert results["append-carry-exact"].measured == 0.0


def test_mutated_twin_lying_precision_claim_is_flagged():
    """THE teeth test: register a claim whose tolerance the site
    cannot meet; the rail must fail it. Restores the registry and the
    memo so the surrounding suite sees the clean world."""
    saved_memo = ulpcert._MEMO
    claim = nmod.PrecisionClaim(
        name="lying-claim", bits=24, reason="deliberately wrong",
        rel_ulps=0.01, module="filodb_tpu.query.tilestore",
        qualname="lying")

    def lying_harness():
        ref = np.linspace(1.0, 2.0, 64)
        prod = (ref + 64 * np.spacing(ref.astype(np.float32),
                                      dtype=np.float64)
                ).astype(np.float32)       # ~64 ulps off
        return prod, ref, 0.0

    nmod.PRECISION["lying-claim"] = claim
    ulpcert.HARNESSES["lying-claim"] = ("precision", lying_harness)
    try:
        res = {r.name: r for r in ulpcert.certify_all(force=True)}
        r = res["lying-claim"]
        assert not r.ok and r.measured > r.claimed
        findings = ulpcert.check_certifications()
        assert any(f.rule == "ulp-certification"
                   and "lying-claim" in f.message
                   for _rel, f in findings)
    finally:
        del nmod.PRECISION["lying-claim"]
        del ulpcert.HARNESSES["lying-claim"]
        ulpcert._MEMO = saved_memo


def test_mutated_twin_lying_order_claim_is_flagged():
    """An order claim of byte-identity over a grouping-dependent f32
    sum must fail bitwise certification."""
    saved_memo = ulpcert._MEMO
    claim = nmod.OrderClaim(
        name="lying-order", tolerance=0.0,
        reason="claims bitwise, is not",
        module="filodb_tpu.parallel.mesh", qualname="lying")
    rng = np.random.default_rng(7)
    data = rng.uniform(0.1, 1.0, 4096).astype(np.float32)

    def lying_harness(ndev):
        # grouping-dependent f32 sum: the accumulation order
        # interleaves per-"device" lanes, so the rounding sequence
        # moves with the device count
        seq = data.reshape(ndev, -1).T.ravel()
        acc = np.float32(0.0)
        for x in seq:
            acc = np.float32(acc + x)
        return np.asarray([acc], dtype=np.float32)

    nmod.ORDER["lying-order"] = claim
    ulpcert.HARNESSES["lying-order"] = ("order", lying_harness)
    try:
        res = {r.name: r for r in ulpcert.certify_all(force=True)}
        assert not res["lying-order"].ok
    finally:
        del nmod.ORDER["lying-order"]
        del ulpcert.HARNESSES["lying-order"]
        ulpcert._MEMO = saved_memo


def test_annotation_without_harness_is_flagged():
    """An annotation the rail cannot evaluate is itself a failure —
    future hybrid sites must ship a harness with the claim."""
    saved_memo = ulpcert._MEMO
    claim = nmod.PrecisionClaim(
        name="orphan-claim", bits=24, reason="no harness",
        rel_ulps=1.0, module="filodb_tpu.query.tilestore",
        qualname="orphan")
    nmod.PRECISION["orphan-claim"] = claim
    try:
        res = {r.name: r for r in ulpcert.certify_all(force=True)}
        r = res["orphan-claim"]
        assert not r.ok and "no certification harness" in r.detail
    finally:
        del nmod.PRECISION["orphan-claim"]
        ulpcert._MEMO = saved_memo


def test_certification_rides_the_lint_gate():
    """run_lint (full, contracts on) carries ulp-certification
    findings — the rail IS tier-1, via tests/test_lint_clean.py."""
    from filodb_tpu.lint import rules
    cat = rules()
    assert cat["ulp-certification"].severity == "error"
    assert cat["ulp-certification"].family == "numerics"


def test_v4_families_registered_at_error():
    from filodb_tpu.lint import rules
    cat = rules()
    for rid in ("precision-narrowing", "accumulation-bound",
                "reduction-order-determinism",
                "mixed-dtype-comparison", "ulp-certification"):
        assert cat[rid].severity == "error"
        assert cat[rid].family == "numerics"


def test_claim_lookup_and_rel_bound():
    """The certified epilogue claim exposes the bound the mesh-serving
    instant pin uses: rel_ulps f32 ulps, doubled across two
    independently-lowered programs."""
    c = nmod.precision_claim("counter-epilogue-f32")
    assert c.bits == 24 and c.rel_ulps == 4
    assert c.rel_bound() == pytest.approx(4 * 2.0 ** -23)
    assert c.rel_bound(cross_program=True) == \
        pytest.approx(8 * 2.0 ** -23)
    o = nmod.order_claim("grouped-reduce-psum")
    assert 0 < o.tolerance <= 1e-12


def test_duplicate_claim_name_rejected():
    from filodb_tpu.lint.numerics import precision
    with pytest.raises(ValueError):
        @precision("counter-fast-hybrid", bits=24, rel_ulps=1,
                   reason="collides with the tilestore claim")
        def other():
            pass


def test_empty_reason_rejected():
    from filodb_tpu.lint.numerics import order_insensitive, precision
    with pytest.raises(ValueError):
        precision("x", bits=24, reason="  ")
    with pytest.raises(ValueError):
        order_insensitive("y", tolerance=0.0, reason="")
