"""Unit coverage for the process-sharded serving tier's supervisor-side
pieces: worker config derivation (global admission split, cache budget
split, peer wiring, single gateway), the control-plane bus (fan-out,
exclusion of the sender, the apply→republish loop breaker), exposition
merging, the fan-out concurrency knob, and the deterministic response
ordering that cross-topology byte-identity rests on."""

import json
import threading
import time

import pytest

from filodb_tpu.obs.metrics import (ExpositionBuilder, merge_expositions,
                                    parse_exposition)
from filodb_tpu.standalone.bus import (BusClient, SupervisorBus,
                                       wait_connected)
from filodb_tpu.standalone.supervisor import split_quota, worker_config


# -- admission quota: global across workers, not Nx ------------------------

def test_split_quota_preserves_aggregate_bound():
    assert split_quota(6, 4) == [2, 2, 1, 1]
    assert sum(split_quota(6, 4)) == 6          # the aggregate pin
    assert split_quota(8, 4) == [2, 2, 2, 2]
    assert split_quota(4, 4) == [1, 1, 1, 1]
    assert split_quota(7, 3) == [3, 2, 2]
    assert sum(split_quota(7, 3)) == 7


def test_split_quota_edge_cases():
    # 0 = admission control off, stays off per worker
    assert split_quota(0, 4) == [0, 0, 0, 0]
    # budget below fleet size: documented lower bound of 1 per worker
    # (a zero-quota worker could never answer)
    assert split_quota(2, 4) == [1, 1, 1, 1]
    assert split_quota(5, 1) == [5]


def test_worker_config_derivation():
    base = {"num-shards": 8, "max-inflight-queries": 6,
            "results-cache-mb": 64, "gateway-port": 0,
            "serving-workers": 4, "supervisor-port": 0,
            "run-dir": "/x", "stream-dir": "/s"}
    ports = [9001, 9002, 9003, 9004]
    cfgs = [worker_config(base, i, 4, ports, 8080, 7000)
            for i in range(4)]
    for i, cfg in enumerate(cfgs):
        assert cfg["num-nodes"] == 4
        assert cfg["node-ordinal"] == i
        assert cfg["worker-id"] == i
        assert cfg["port"] == ports[i]
        assert cfg["accept-port"] == 8080
        assert cfg["bus-port"] == 7000
        assert cfg["peers"] == {f"node{j}": f"http://127.0.0.1:{p}"
                                for j, p in enumerate(ports)}
        # supervisor-only keys must not leak into the worker
        assert "serving-workers" not in cfg
        assert "run-dir" not in cfg
    # admission is GLOBAL: per-worker quotas sum to the configured max
    assert [c["max-inflight-queries"] for c in cfgs] == [2, 2, 1, 1]
    # host cache budget stays constant
    assert sum(c["results-cache-mb"] for c in cfgs) == \
        pytest.approx(64.0)
    # ONE producer edge per host
    assert cfgs[0]["gateway-port"] == 0
    assert all(c["gateway-port"] is None for c in cfgs[1:])


def test_worker_config_propagates_self_monitor():
    """--self-monitor rides into every worker: each runs its OWN loop
    over its own internal shard (shard number = worker-id, so the
    shared stream/data dirs never collide) and stamps its ordinal as
    the worker label on internal series."""
    base = {"num-shards": 4, "self-monitor": True,
            "self-monitor-interval-s": 2.5, "serving-workers": 2,
            "supervisor-port": 0, "run-dir": "/x"}
    ports = [9001, 9002]
    cfgs = [worker_config(base, i, 2, ports, 8080, 7000)
            for i in range(2)]
    for i, cfg in enumerate(cfgs):
        assert cfg["self-monitor"] is True
        assert cfg["self-monitor-interval-s"] == 2.5
        assert cfg["worker-id"] == i


def test_worker_config_fd_fallback():
    cfg = worker_config({"num-shards": 4}, 1, 2, [9001, 9002], 8080,
                        7000, accept_fd=13)
    assert cfg["accept-fd"] == 13
    assert "accept-port" not in cfg


# -- control-plane bus ------------------------------------------------------

def _wait(pred, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.01)
    return pred()


def test_bus_fans_out_to_other_workers_not_sender():
    hub = SupervisorBus().start()
    got_a, got_b = [], []
    a = BusClient(hub.port, 0, "node0").on(
        "schema", lambda ev: got_a.append(ev)).start()
    b = BusClient(hub.port, 1, "node1").on(
        "schema", lambda ev: got_b.append(ev)).start()
    try:
        assert wait_connected(a) and wait_connected(b)
        assert _wait(lambda: hub.connected_workers() == [0, 1])
        a.publish({"type": "schema", "reason": "col-added"})
        assert _wait(lambda: len(got_b) == 1)
        assert got_b[0]["reason"] == "col-added"
        assert got_b[0]["origin"] == "node0"
        time.sleep(0.1)
        assert got_a == []          # the sender never hears its own event
        # supervisor broadcast reaches everyone
        hub.broadcast({"type": "schema", "reason": "operator"})
        assert _wait(lambda: len(got_a) == 1 and len(got_b) == 2)
    finally:
        a.stop()
        b.stop()
        hub.stop()


def test_bus_apply_suppresses_republish():
    """The loop breaker: a handler that (like the mapper subscriber)
    publishes in reaction to an event must NOT echo bus-applied events
    back onto the bus."""
    hub = SupervisorBus().start()
    got_b = []
    a = BusClient(hub.port, 0, "node0")

    def react(ev):
        # what the ShardMapper subscriber does on an applied transition
        a.publish({"type": "topology", "shard": 0, "status": "active"})
    a.on("topology", react).start()
    b = BusClient(hub.port, 1, "node1").on(
        "topology", lambda ev: got_b.append(ev)).start()
    try:
        assert wait_connected(a) and wait_connected(b)
        assert _wait(lambda: hub.connected_workers() == [0, 1])
        seen0 = hub.events_seen
        b.publish({"type": "topology", "shard": 0, "status": "active"})
        assert _wait(lambda: a.applied >= 1)
        time.sleep(0.2)
        # exactly ONE event crossed the hub (b's publish); a's reactive
        # publish was suppressed by the applying guard
        assert hub.events_seen - seen0 == 1
        assert got_b == []
    finally:
        a.stop()
        b.stop()
        hub.stop()


def test_bus_client_reconnects_and_counts():
    hub = SupervisorBus().start()
    a = BusClient(hub.port, 0, "node0").start()
    try:
        assert wait_connected(a)
        assert a.metrics_snapshot()["connected"] == 1
        assert a.metrics_snapshot()["reconnects"] == 0
        a.publish({"type": "schema"})
        assert _wait(lambda: a.metrics_snapshot()["published"] == 1)
    finally:
        a.stop()
        hub.stop()


# -- exposition merge -------------------------------------------------------

_W0 = """# HELP filodb_plan_cache_hits_total Plan-cache hits
# TYPE filodb_plan_cache_hits_total counter
filodb_plan_cache_hits_total 7
# HELP filodb_shard_status Shard FSM status
# TYPE filodb_shard_status gauge
filodb_shard_status{shard="0",status="active"} 1
# HELP filodb_query_latency_seconds query latency
# TYPE filodb_query_latency_seconds histogram
filodb_query_latency_seconds_bucket{le="0.001"} 2
filodb_query_latency_seconds_bucket{le="+Inf"} 3
filodb_query_latency_seconds_sum 0.5
filodb_query_latency_seconds_count 3
"""

_W1 = """# HELP filodb_plan_cache_hits_total Plan-cache hits
# TYPE filodb_plan_cache_hits_total counter
filodb_plan_cache_hits_total 5
"""


def test_parse_exposition_families_and_histograms():
    helps = {}
    rows = parse_exposition(_W0, help_sink=helps)
    fams = {fam for fam, *_ in rows}
    assert fams == {"filodb_plan_cache_hits_total",
                    "filodb_shard_status",
                    "filodb_query_latency_seconds"}
    assert helps["filodb_plan_cache_hits_total"] == "Plan-cache hits"
    hist = [(name, labels, v) for fam, _mt, name, labels, v in rows
            if fam == "filodb_query_latency_seconds"]
    assert ("filodb_query_latency_seconds_bucket", {"le": "0.001"},
            "2") in hist
    labeled = [labels for _f, _mt, name, labels, _v in rows
               if name == "filodb_shard_status"]
    assert labeled == [{"shard": "0", "status": "active"}]


def test_merge_expositions_injects_worker_label():
    out = merge_expositions({"0": _W0, "1": _W1})
    lines = out.splitlines()
    assert 'filodb_plan_cache_hits_total{worker="0"} 7' in lines
    assert 'filodb_plan_cache_hits_total{worker="1"} 5' in lines
    # one HELP/TYPE block per family even though both workers carry it
    assert sum(1 for ln in lines
               if ln.startswith("# TYPE filodb_plan_cache_hits_total")
               ) == 1
    # histogram children keep their family grouping + worker label
    assert ('filodb_query_latency_seconds_bucket'
            '{le="0.001",worker="0"} 2') in lines \
        or ('filodb_query_latency_seconds_bucket'
            '{worker="0",le="0.001"} 2') in lines
    # worker HELP text survives the merge
    assert "# HELP filodb_plan_cache_hits_total Plan-cache hits" \
        in lines
    # merged output re-parses cleanly
    assert parse_exposition(out)


def test_merge_expositions_idempotent():
    """merge(merge(x)) == merge(x): re-merging an already-merged
    exposition (a supervisor-of-supervisors scrape, a re-aggregated
    payload) is a no-op — the worker label injected by the first merge
    is KEPT, not clobbered, and HELP/TYPE blocks survive. This is also
    what protects self-monitoring's own ``worker``-labeled internal
    series through the supervisor's aggregate view."""
    merged = merge_expositions({"0": _W0, "1": _W1})
    again = merge_expositions({"sup": merged})
    assert again == merged
    # a sample that already carried a worker label keeps it even when
    # merged under a different worker key
    assert 'worker="sup"' not in again


def test_merge_expositions_idempotent_on_real_worker_payloads():
    """The same property pinned on a REAL worker payload — a live
    FiloServer /metrics body, histograms, escapes, and all — since the
    supervisor's self-monitoring view reads through this path."""
    import urllib.request

    from filodb_tpu.obs.metrics import validate_histogram_families
    from filodb_tpu.standalone.server import FiloServer
    srv = FiloServer({"num-shards": 2, "port": 0}).start()
    try:
        srv.seed_dev_data(n_samples=30, n_instances=2,
                          start_ms=1_600_000_000_000)
        urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/promql/timeseries/api/v1/"
            f"query_range?query=up&start=1600000300&end=1600000400"
            f"&step=60", timeout=60).read()
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics",
                timeout=60) as r:
            body = r.read().decode()
    finally:
        srv.stop()
    merged = merge_expositions({"0": body, "1": body})
    again = merge_expositions({"0": merged})
    assert again == merged
    # histogram self-consistency survives the merge (registry-wide
    # validator: cumulative buckets, +Inf == _count, _sum emitted)
    assert validate_histogram_families(merged) == []


def test_merged_exposition_passes_format_validator():
    """The merged text must satisfy the same Prometheus text-format
    invariants the per-worker exposition is tested against."""
    out = merge_expositions({"0": _W0, "1": _W1})
    seen_series = set()
    declared = set()
    for ln in out.splitlines():
        if ln.startswith("# TYPE "):
            fam = ln.split()[2]
            assert fam not in declared, f"duplicate TYPE for {fam}"
            declared.add(fam)
        elif ln and not ln.startswith("#"):
            key = ln.rsplit(" ", 1)[0]
            assert key not in seen_series, f"duplicate series {key}"
            seen_series.add(key)


# -- fan-out cap knob -------------------------------------------------------

def test_fanout_workers_knob_and_auto():
    import os

    from filodb_tpu.http.server import FiloHttpServer
    srv = FiloHttpServer({"ds": []}, peer_fanout_workers=24)
    try:
        assert srv.fanout_workers == 24
        assert 'filodb_peer_fanout_workers 24' \
            in srv._metrics_text().splitlines()
    finally:
        srv.httpd.server_close()
    srv = FiloHttpServer({"ds": []})     # auto: sized from the host
    try:
        assert srv.fanout_workers == min(32, max(2, os.cpu_count() or 2))
    finally:
        srv.httpd.server_close()


# -- deterministic response ordering ---------------------------------------

def test_matrix_encode_order_is_data_dependent_not_scan_dependent():
    import numpy as np

    from filodb_tpu.http import prom_json
    from filodb_tpu.query.model import GridResult
    steps = np.array([10_000, 20_000], dtype=np.int64)
    keys = [{"_metric_": "m", "instance": "i1"},
            {"instance": "i0", "_metric_": "m"}]
    vals = np.array([[1.0, 2.0], [3.0, 4.0]])
    fwd = GridResult(steps, keys, vals)
    rev = GridResult(steps, list(reversed(keys)), vals[::-1].copy())
    out_f = prom_json.matrix(fwd)["data"]["result"]
    out_r = prom_json.matrix(rev)["data"]["result"]
    assert out_f == out_r
    assert [r["metric"]["instance"] for r in out_f] == ["i0", "i1"]
    # the pre-encoded fast path agrees byte-for-byte with the dict path
    body_f = prom_json.matrix_bytes(fwd, {"x": 1}).body
    body_r = prom_json.matrix_bytes(rev, {"x": 1}).body
    assert body_f == body_r
    env = prom_json.matrix(fwd)
    env["stats"] = {"x": 1}
    assert body_f == json.dumps(env, separators=(",", ":")).encode()


def test_supervisor_object_start_stop_without_workers(tmp_path):
    """Supervisor lifecycle without real FiloServer subprocesses: 0
    configured workers is clamped to the core count, so use the
    smallest real fleet (1) against a config that makes the worker
    exit immediately — the monitor must keep respawning with backoff,
    and stop() must terminate cleanly."""
    from filodb_tpu.standalone.supervisor import Supervisor
    sup = Supervisor({"serving-workers": 1, "port": 0,
                      "run-dir": str(tmp_path / "run"),
                      "restart-backoff-s": 30.0,
                      # invalid num-shards (not a power of 2): the
                      # worker process dies during startup
                      "num-shards": 3})
    sup.start()
    try:
        assert _wait(lambda: sup.status()["workers"]["0"]["alive"]
                     in (True, False))
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            if not sup.status()["workers"]["0"]["alive"]:
                break
            time.sleep(0.1)
        st = sup.status()
        assert st["workers"]["0"]["alive"] is False
        assert st["status"] == "healthy"
        # aggregate metrics still render with the worker down
        text = sup.metrics_text()
        assert "filodb_supervisor_workers 1" in text.splitlines()
        assert 'filodb_supervisor_worker_alive{worker="0"} 0' \
            in text.splitlines()
    finally:
        sup.stop(graceful=False)


def test_worker_config_splits_qos_budgets():
    """Tenant QoS budgets are HOST bounds like admission: each worker
    gets 1/N of every refill rate / bucket depth, so the fleet charges
    the same aggregate per-tenant budget as one process would."""
    base = {"num-shards": 8, "qos-tenant-rate": 100.0,
            "qos-tenant-burst": 1000.0,
            "qos-tenant-overrides": {"abuser": 40.0,
                                     "vip": [80.0, 400.0]}}
    ports = [9001, 9002, 9003, 9004]
    cfgs = [worker_config(base, i, 4, ports, 8080, 7000)
            for i in range(4)]
    assert sum(c["qos-tenant-rate"] for c in cfgs) == \
        pytest.approx(100.0)
    assert sum(c["qos-tenant-burst"] for c in cfgs) == \
        pytest.approx(1000.0)
    assert sum(c["qos-tenant-overrides"]["abuser"] for c in cfgs) == \
        pytest.approx(40.0)
    assert sum(c["qos-tenant-overrides"]["vip"][0] for c in cfgs) == \
        pytest.approx(80.0)
    assert sum(c["qos-tenant-overrides"]["vip"][1] for c in cfgs) == \
        pytest.approx(400.0)
    # budgets off: no keys are invented for the workers
    cfg_off = worker_config({"num-shards": 8}, 0, 4, ports, 8080, 7000)
    assert "qos-tenant-rate" not in cfg_off


_TENANT_EXPO_W0 = """\
# HELP filodb_tenant_time_series_total Per-tenant series count
# TYPE filodb_tenant_time_series_total gauge
filodb_tenant_time_series_total{_ws_="demo",_ns_="App-0"} 40
# HELP filodb_tenant_budget_remaining Per-tenant token-bucket balance
# TYPE filodb_tenant_budget_remaining gauge
filodb_tenant_budget_remaining{tenant="abuser"} 25.0
# HELP filodb_tenant_throttled_total Budget charges refused
# TYPE filodb_tenant_throttled_total counter
filodb_tenant_throttled_total{tenant="abuser"} 3
"""

_TENANT_EXPO_W1 = """\
# HELP filodb_tenant_time_series_total Per-tenant series count
# TYPE filodb_tenant_time_series_total gauge
filodb_tenant_time_series_total{_ws_="demo",_ns_="App-0"} 24
# HELP filodb_tenant_budget_remaining Per-tenant token-bucket balance
# TYPE filodb_tenant_budget_remaining gauge
filodb_tenant_budget_remaining{tenant="abuser"} -10.0
# HELP filodb_tenant_throttled_total Budget charges refused
# TYPE filodb_tenant_throttled_total counter
filodb_tenant_throttled_total{tenant="abuser"} 5
"""


def test_merge_expositions_carries_tenant_families():
    """The satellite pin: tenant cardinality/budget families flow
    through the supervisor's merged /metrics with the worker label
    injected like every other family."""
    out = merge_expositions({"0": _TENANT_EXPO_W0,
                             "1": _TENANT_EXPO_W1})
    assert ('filodb_tenant_time_series_total'
            '{_ns_="App-0",_ws_="demo",worker="0"} 40') in out
    assert ('filodb_tenant_time_series_total'
            '{_ns_="App-0",_ws_="demo",worker="1"} 24') in out
    assert 'filodb_tenant_budget_remaining{tenant="abuser",worker="0"} 25.0' \
        in out
    assert 'filodb_tenant_throttled_total{tenant="abuser",worker="1"} 5' \
        in out
    # one HELP/TYPE block per family across the fleet
    assert out.count("# TYPE filodb_tenant_time_series_total gauge") == 1


def test_aggregate_tenant_families_host_rollup():
    """filodb_host_tenant_*: per-tenant sums across workers — the
    one-series-per-tenant view a noisy-neighbor alert reads (a
    tenant's shards and its budget split spread ACROSS workers)."""
    from filodb_tpu.standalone.supervisor import aggregate_tenant_families
    out = aggregate_tenant_families({"0": _TENANT_EXPO_W0,
                                     "1": _TENANT_EXPO_W1})
    assert ('filodb_host_tenant_time_series_total'
            '{_ns_="App-0",_ws_="demo"} 64') in out
    assert 'filodb_host_tenant_budget_remaining{tenant="abuser"} 15' \
        in out
    assert 'filodb_host_tenant_throttled_total{tenant="abuser"} 8' in out
    # non-tenant families are not rolled up
    assert "filodb_host_tenant_time_series_total" in out
    assert aggregate_tenant_families({}) == ""


# -- /debug/events fleet merge ------------------------------------------------

def test_debug_events_merged_and_worker_tagged():
    """/debug/events joins the supervisor's merged debug routes: the
    admin port fans the request out to every worker and concatenates
    the event journals, each entry tagged with its worker ordinal —
    one place to read corruption/quarantine/read-only transitions for
    the whole host."""
    from filodb_tpu.standalone.supervisor import Supervisor, _Worker
    sup = Supervisor({"serving-workers": 2, "port": 0})
    sup._workers = {0: _Worker(0, "w0.json", 1), 1: _Worker(1, "w1.json", 2)}
    canned = {
        1: {"status": "success",
            "data": [{"kind": "corruption-detected", "shard": 0}]},
        2: {"status": "success",
            "data": [{"kind": "ingest-read-only", "shard": 3}]},
    }
    sup._worker_get = lambda w, path: (
        canned[w.port] if path.startswith("/debug/events") else None)
    code, body = sup._admin_route("/debug/events?limit=10")
    assert code == 200 and body["status"] == "success"
    assert {(e["kind"], e["worker"]) for e in body["data"]} == {
        ("corruption-detected", 0), ("ingest-read-only", 1)}
    # the ?query passes through to the workers
    seen = []
    sup._worker_get = lambda w, path: (seen.append(path)
                                       or canned[w.port])
    sup._admin_route("/debug/events?kind=quarantine")
    assert seen == ["/debug/events?kind=quarantine"] * 2
