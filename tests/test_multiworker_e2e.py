"""Process-sharded serving tier e2e: a supervisor-run fleet must be
indistinguishable from the single-process edge it replaces.

One WAL corpus (test-owned producer, the Kafka analogue) is served
first by a 1-worker deployment, then by a 2-worker deployment over the
same durable dirs. Byte-identity is asserted for every entry point
(public SO_REUSEPORT port, each worker's private port) with the results
cache off and on — the deterministic response ordering makes the
response a pure function of the data, independent of how many
processes scanned it. Also covered: the global admission split
(aggregate bound pinned in the derived worker configs), control-plane
invalidation fan-out observed by every worker's caches, watermark
gossip over the bus, and the supervisor's aggregate /metrics and
/debug surfaces."""

import json
import os
import pathlib
import select
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.parse
import urllib.request

REPO = pathlib.Path(__file__).resolve().parent.parent
T0 = 1_600_000_000
N_SAMPLES = 50
N_INSTANCES = 4
NUM_SHARDS = 4


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _get_raw(port, path, **params):
    qs = urllib.parse.urlencode(params, doseq=True)
    url = f"http://127.0.0.1:{port}{path}"
    if qs:
        url += "?" + qs
    with urllib.request.urlopen(url, timeout=120) as r:
        return r.read()


def _get(port, path, **params):
    return json.loads(_get_raw(port, path, **params))


def _post(port, path, **params):
    qs = urllib.parse.urlencode(params, doseq=True)
    url = f"http://127.0.0.1:{port}{path}"
    if qs:
        url += "?" + qs
    req = urllib.request.Request(url, data=b"{}", method="POST",
                                 headers={"Content-Type":
                                          "application/json"})
    with urllib.request.urlopen(req, timeout=120) as r:
        return json.loads(r.read())


def _poll(fn, timeout=150.0, interval=0.2):
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        try:
            ok, last = fn()
            if ok:
                return last
        except (urllib.error.URLError, ConnectionError, OSError):
            pass
        time.sleep(interval)
    raise TimeoutError(f"poll timed out; last={last!r}")


def _write_corpus(stream_dir):
    from filodb_tpu.core.schemas import DEFAULT_SCHEMAS
    from filodb_tpu.gateway.producer import TestTimeseriesProducer
    from filodb_tpu.ingest import LogIngestionStream
    prod = TestTimeseriesProducer(DEFAULT_SCHEMAS,
                                  num_shards=NUM_SHARDS)
    streams = {}
    for sh in range(NUM_SHARDS):
        path = os.path.join(stream_dir, f"shard={sh}", "stream.log")
        streams[sh] = LogIngestionStream(path, DEFAULT_SCHEMAS)
    for builders in (prod.gauges(T0 * 1000, N_SAMPLES, N_INSTANCES),
                     prod.counters(T0 * 1000, N_SAMPLES, N_INSTANCES)):
        for sh, b in builders.items():
            for c in b.containers():
                streams[sh].append(c)
    for s in streams.values():
        s.close()


def _spawn_supervisor(cfg_path):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen(
        [sys.executable, "-m", "filodb_tpu.standalone.supervisor",
         "--config", str(cfg_path)],
        cwd=str(REPO), env=env, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL)
    buf = b""
    deadline = time.monotonic() + 240
    while time.monotonic() < deadline and b"\n" not in buf:
        r, _, _ = select.select([proc.stdout], [], [], 1.0)
        if r:
            ch = proc.stdout.read1(4096)
            if not ch:
                raise RuntimeError("supervisor died during startup")
            buf += ch
    if b"\n" not in buf:
        proc.kill()
        raise TimeoutError("no supervisor startup line")
    return proc, json.loads(buf.split(b"\n", 1)[0])


def _stop(proc):
    proc.terminate()
    try:
        proc.wait(timeout=60)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait(timeout=30)


_QUERY = dict(query='rate({_metric_=~"heap_usage|http_requests_total"}'
                    '[5m])',
              start=T0 + 300, end=T0 + (N_SAMPLES - 1) * 10, step=60)


def _data_bytes(raw: bytes) -> bytes:
    """The verbatim data section of a response (exact float strings,
    exact series order). The per-request stats tail (wall-clock
    timings, cache disposition) legitimately differs between requests —
    the same boundary the PR 3/5 byte-identity goldens use."""
    body, sep, _tail = raw.partition(b',"stats":')
    assert sep, raw[:200]
    return body


def _settled_bytes(port, **extra):
    return _get_raw(port, "/promql/timeseries/api/v1/query_range",
                    **{**_QUERY, **extra})


def _wait_full(port, want_series):
    def probe():
        body = json.loads(_settled_bytes(port, cache="false"))
        ok = (body.get("status") == "success"
              and "partial" not in body
              and len(body["data"]["result"]) >= want_series)
        return ok, len(body.get("data", {}).get("result", ()))
    return _poll(probe)


def _base_cfg(tmp_path, workers):
    return {
        "num-shards": NUM_SHARDS, "port": _free_port(),
        "serving-workers": workers,
        "supervisor-port": 0,
        "run-dir": str(tmp_path / f"run{workers}"),
        "data-dir": str(tmp_path / "data"),
        "stream-dir": str(tmp_path / "streams"),
        "flush-interval-s": 0.4,
        # settled corpus fully chunk-resident (chunks close at 25
        # rows): the evaluation path — and therefore the response
        # bytes — is identical whether the chunks are read by one
        # process or fetched across the worker peer plane
        "max-chunks-size": 25,
        "query-sample-limit": 0, "query-series-limit": 0,
        "grpc-port": None,
        "max-inflight-queries": 6,
        "failure-detect-interval-s": 0.3,
    }


def test_multiworker_byte_identical_and_coherent(tmp_path):
    _write_corpus(str(tmp_path / "streams"))
    want = 2 * N_INSTANCES

    # -- 1-worker deployment: the golden single-process responses ------
    cfg1 = _base_cfg(tmp_path, workers=1)
    p1 = (tmp_path / "sup1.json")
    p1.write_text(json.dumps(cfg1))
    proc1, line1 = _spawn_supervisor(p1)
    try:
        _wait_full(line1["port"], want)
        time.sleep(3.0)     # full flush-group rotation: all chunks
        golden = _data_bytes(_settled_bytes(line1["port"],
                                            cache="false"))
        _settled_bytes(line1["port"])               # seed the cache
        cache_warm = _settled_bytes(line1["port"])  # cache-warm bytes
        assert _data_bytes(cache_warm) == golden
    finally:
        _stop(proc1)

    # -- 2-worker deployment over the same durable dirs ----------------
    cfg2 = _base_cfg(tmp_path, workers=2)
    p2 = (tmp_path / "sup2.json")
    p2.write_text(json.dumps(cfg2))
    proc2, line2 = _spawn_supervisor(p2)
    try:
        pub = line2["port"]
        sup_port = line2["supervisor_port"]
        worker_ports = [w["port"] for w in line2["workers"]]
        assert len(worker_ports) == 2

        # the global admission budget is SPLIT, not multiplied
        quotas = []
        for i in range(2):
            with open(tmp_path / "run2" / f"worker{i}.json") as f:
                quotas.append(json.load(f)["max-inflight-queries"])
        assert quotas == [3, 3]     # sum == configured 6, not 12

        for port in worker_ports:
            _wait_full(port, want)

        # byte-identity: every entry point, cache off and on, equals
        # the single-process golden
        def _converged():
            bodies = [_data_bytes(_settled_bytes(p, cache="false"))
                      for p in (pub, *worker_ports)]
            return all(b == golden for b in bodies), \
                [len(b) for b in bodies]
        _poll(_converged, timeout=60, interval=0.5)
        for port in (pub, *worker_ports):
            _settled_bytes(port)            # seed each entry's cache
            assert _data_bytes(_settled_bytes(port)) == golden

        # control-plane invalidation fan-out: one operator request at
        # the supervisor clears EVERY worker's plan/results caches
        out = _post(sup_port, "/admin/invalidate", reason="e2e-schema")
        assert out["status"] == "success"
        assert out["data"]["workers"] == [0, 1]

        def _invalidated():
            seen = []
            for port in worker_ports:
                text = _get_raw(port, "/metrics").decode()
                seen.append(any(
                    ln.startswith(
                        "filodb_plan_cache_invalidations_by_reason_"
                        'total{reason="e2e-schema"}')
                    for ln in text.splitlines()))
            return all(seen), seen
        _poll(_invalidated, timeout=20, interval=0.2)

        # bus liveness: every worker applied sibling events (topology
        # transitions at startup, watermark gossip beats)
        for port in worker_ports:
            text = _get_raw(port, "/metrics").decode()
            applied = [float(ln.rsplit(" ", 1)[1])
                       for ln in text.splitlines()
                       if ln.startswith(
                           "filodb_bus_events_applied_total")]
            assert applied and applied[0] > 0
            assert "filodb_bus_connected 1" in text.splitlines()

        # watermark gossip over the bus: each worker knows its
        # sibling's per-shard watermarks (the results-cache freshness
        # input for fan-out extents)
        def _gossiped():
            ok = []
            for port in worker_ports:
                h = _get(port, "/__health")
                ok.append(bool(h.get("watermarks")))
            return all(ok), ok
        _poll(_gossiped, timeout=20)

        # supervisor aggregation: merged /metrics carries per-worker
        # series; /debug/threads merges worker inventories with tags
        text = _get_raw(sup_port, "/metrics").decode()
        lines = text.splitlines()
        assert 'filodb_worker_ordinal{worker="0"} 0' in lines
        assert 'filodb_worker_ordinal{worker="1"} 1' in lines
        assert sum(1 for ln in lines
                   if ln.startswith("# TYPE filodb_plan_cache_entries ")
                   ) == 1
        assert "filodb_supervisor_workers 2" in lines
        threads = _get(sup_port, "/debug/threads")
        workers_seen = {e.get("worker") for e in threads["data"]}
        assert workers_seen == {0, 1}
        names = {e["name"] for e in threads["data"]}
        assert "worker-supervisor" not in names  # workers' roots only
        assert "bus-client" in names
        health = _get(sup_port, "/__health")
        assert health["bus_connected"] == [0, 1]
        assert all(w["alive"] and w["ready"]
                   for w in health["workers"].values())
    finally:
        _stop(proc2)
