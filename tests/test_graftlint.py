"""graftlint rule fixtures: each violation snippet trips exactly one
rule; each clean twin passes. Plus contract-object checks (VMEM budget,
tiling, grid bounds, span guard, abstract eval) and CLI exit codes."""

import json

import jax
import jax.numpy as jnp

from filodb_tpu.lint import run_lint
from filodb_tpu.lint.contracts import (Block, KernelContract,
                                       kernel_contract)
from filodb_tpu.lint.rules_kernel import check_contract


def lint_src(tmp_path, src, name="fixture.py"):
    p = tmp_path / name
    p.write_text(src)
    res = run_lint([str(p)], baseline=frozenset(), check_contracts=False)
    return res


def rules_of(res):
    return sorted({f.rule for f in res.findings})


# -- trace safety ------------------------------------------------------------

TRACE_SIDE_EFFECT = """
import functools, time
import jax

@functools.partial(jax.jit, static_argnames=("n",))
def f(x, n):
    return x + time.time()
"""

TRACE_SIDE_EFFECT_CLEAN = """
import functools, time
import jax

def now():
    return time.time()          # host helper, never traced

@functools.partial(jax.jit, static_argnames=("n",))
def f(x, n):
    return x * n
"""


def test_trace_side_effect(tmp_path):
    assert rules_of(lint_src(tmp_path, TRACE_SIDE_EFFECT)) \
        == ["trace-side-effect"]
    assert not lint_src(tmp_path, TRACE_SIDE_EFFECT_CLEAN).findings


TRACE_TRACER_LEAK = """
import jax

@jax.jit
def f(x):
    return 1.0 if bool(x) else 0.0
"""

TRACE_TRACER_LEAK_CLEAN = """
import functools
import jax
import jax.numpy as jnp

@functools.partial(jax.jit, static_argnames=("flag",))
def f(x, flag):
    return jnp.where(bool(flag), x, -x)   # static param: fine
"""


def test_trace_tracer_leak(tmp_path):
    assert rules_of(lint_src(tmp_path, TRACE_TRACER_LEAK)) \
        == ["trace-tracer-leak"]
    assert not lint_src(tmp_path, TRACE_TRACER_LEAK_CLEAN).findings


TRACE_MUTATE = """
import jax

_seen = []

@jax.jit
def f(x):
    _seen.append(x)
    return x
"""

TRACE_MUTATE_CLEAN = """
import jax

@jax.jit
def f(x):
    acc = []
    acc.append(x)               # function-local: fine
    return acc[0]
"""


def test_trace_mutate_capture(tmp_path):
    assert rules_of(lint_src(tmp_path, TRACE_MUTATE)) \
        == ["trace-mutate-capture"]
    assert not lint_src(tmp_path, TRACE_MUTATE_CLEAN).findings


TRACE_F64 = """
import jax.numpy as jnp

def kern(x_ref, o_ref):
    o_ref[:] = x_ref[:].astype(jnp.float64)
"""

TRACE_F64_CLEAN = """
import jax.numpy as jnp

def kern(x_ref, o_ref):
    o_ref[:] = x_ref[:].astype(jnp.float32)

def host_helper(x):
    return x.astype(jnp.float64)    # not a kernel body: fine
"""


def test_trace_f64_in_pallas_body(tmp_path):
    assert rules_of(lint_src(tmp_path, TRACE_F64)) \
        == ["trace-f64-constant"]
    assert not lint_src(tmp_path, TRACE_F64_CLEAN).findings


# -- kernel contracts (AST) --------------------------------------------------

CONTRACT_MISSING = """
from jax.experimental import pallas as pl
import jax
import jax.numpy as jnp

def run(x):
    def kern(x_ref, o_ref):
        o_ref[:] = x_ref[:]
    return pl.pallas_call(
        kern, out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype))(x)
"""

CONTRACT_PRESENT = """
from jax.experimental import pallas as pl
import jax
import jax.numpy as jnp
from filodb_tpu.lint.contracts import kernel_contract

@kernel_contract("toy", kind="pallas", vmem_budget=1 << 20)
def run(x):
    def kern(x_ref, o_ref):
        o_ref[:] = x_ref[:]
    return pl.pallas_call(
        kern, out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype))(x)
"""


def test_kernel_contract_missing(tmp_path):
    assert rules_of(lint_src(tmp_path, CONTRACT_MISSING)) \
        == ["kernel-contract-missing"]
    assert not lint_src(tmp_path, CONTRACT_PRESENT).findings


# -- lock discipline ---------------------------------------------------------

LOCK_ACCESS = """
import threading
from filodb_tpu.lint.locks import guarded_by

@guarded_by("_lock", "_items")
class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []

    def bad(self, x):
        self._items.append(x)
"""

LOCK_ACCESS_CLEAN = """
import threading
from filodb_tpu.lint.locks import guarded_by

@guarded_by("_lock", "_items")
class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []

    def good(self, x):
        with self._lock:
            self._items.append(x)

    def drain_locked(self):
        return list(self._items)    # *_locked: caller holds the lock
"""


def test_lock_guarded_access(tmp_path):
    assert rules_of(lint_src(tmp_path, LOCK_ACCESS)) \
        == ["lock-guarded-access"]
    assert not lint_src(tmp_path, LOCK_ACCESS_CLEAN).findings


LOCK_BLOCKING = """
import threading
import time

class Box:
    def __init__(self):
        self._lock = threading.Lock()

    def slow(self):
        with self._lock:
            time.sleep(1.0)
"""

LOCK_BLOCKING_CLEAN = """
import threading
import time

class Box:
    def __init__(self):
        self._lock = threading.Lock()

    def fine(self):
        with self._lock:
            n = 1 + 1
        time.sleep(0.0)             # outside the lock
        return n
"""


def test_lock_blocking_call(tmp_path):
    assert rules_of(lint_src(tmp_path, LOCK_BLOCKING)) \
        == ["lock-blocking-call"]
    assert not lint_src(tmp_path, LOCK_BLOCKING_CLEAN).findings


LOCK_MODULE_GLOBAL = """
import threading

_cache = {}
_cache_lock = threading.Lock()
__guarded_by__ = {"_cache": "_cache_lock"}

def bad(k):
    return _cache.get(k)
"""


def test_lock_module_global(tmp_path):
    assert rules_of(lint_src(tmp_path, LOCK_MODULE_GLOBAL)) \
        == ["lock-guarded-access"]


# -- pragmas and baseline ----------------------------------------------------

PRAGMA_OK = """
import functools, time
import jax

@functools.partial(jax.jit, static_argnames=("n",))
def f(x, n):
    # graftlint: disable=trace-side-effect (bench-only trace timestamp)
    return x + time.time()
"""

PRAGMA_NO_REASON = """
import functools, time
import jax

@functools.partial(jax.jit, static_argnames=("n",))
def f(x, n):
    return x + time.time()  # graftlint: disable=trace-side-effect
"""


def test_pragma_suppression(tmp_path):
    res = lint_src(tmp_path, PRAGMA_OK)
    assert not res.findings and res.suppressed == 1


def test_pragma_requires_reason(tmp_path):
    assert rules_of(lint_src(tmp_path, PRAGMA_NO_REASON)) \
        == ["pragma-no-reason"]


def test_baseline_grandfathers(tmp_path):
    res = lint_src(tmp_path, TRACE_SIDE_EFFECT)
    assert len(res.findings) == 1
    key = res.findings[0].key()
    p = tmp_path / "fixture.py"
    res2 = run_lint([str(p)], baseline=frozenset([key]),
                    check_contracts=False)
    assert not res2.findings and len(res2.baselined) == 1


# -- contract object checks (no execution) -----------------------------------

def _unrunnable(*a, **k):
    raise AssertionError("contract checks must never execute the kernel")


def test_vmem_budget_catches_oversized_kernel():
    """The acceptance fixture: a deliberately oversized kernel is caught
    by block arithmetic alone — the kernel body would assert if run."""
    c = KernelContract(
        name="oversized", kind="pallas", fn=_unrunnable, module="x",
        qualname="oversized",
        blocks=(Block("x", (8, 128), "float32"),),
        scratch=(Block("s", (8, 2048, 1024), "float32"),),  # 64 MB
        vmem_budget=14 << 20)
    rules = [f.rule for f in check_contract(c, "x.py")]
    assert rules == ["kernel-vmem-budget"]


def test_vmem_budget_required_for_pallas():
    c = KernelContract(name="nobudget", kind="pallas", fn=_unrunnable,
                       module="x", qualname="nobudget")
    assert "kernel-vmem-budget" in [f.rule for f in check_contract(c)]


def test_tile_alignment():
    bad = KernelContract(
        name="tiles", kind="pallas", fn=_unrunnable, module="x",
        qualname="tiles", vmem_budget=1 << 20,
        blocks=(Block("a", (7, 128), "float32"),     # sublane 7 % 8
                Block("b", (8, 100), "float32"),     # lane 100 % 128
                Block("c", (16, 128), "bfloat16"),   # ok: 16 % 16
                Block("d", (8, 128), "float64")))    # 8-byte in VMEM
    rules = sorted(f.rule for f in check_contract(bad))
    assert rules == ["kernel-tile-alignment"] * 3


def test_grid_bounds():
    bad = KernelContract(
        name="grid", kind="pallas", fn=_unrunnable, module="x",
        qualname="grid", vmem_budget=1 << 20, grid=(4,),
        blocks=(Block("a", (8, 128), "float32",
                      array_shape=(16, 128),     # only 2 blocks fit
                      index_map=lambda i: (i, 0)),))
    assert [f.rule for f in check_contract(bad)] == ["kernel-grid-bounds"]


def test_span_guard_must_resolve():
    bad = KernelContract(
        name="span", kind="dispatch", fn=_unrunnable,
        module="filodb_tpu.query.tilestore", qualname="span",
        rel_time_bits=31, span_guard="_no_such_predicate")
    assert [f.rule for f in check_contract(bad)] == ["kernel-span-guard"]
    ok = KernelContract(
        name="span2", kind="dispatch", fn=_unrunnable,
        module="filodb_tpu.query.tilestore", qualname="span2",
        rel_time_bits=31, span_guard="_slide_eligible")
    assert not check_contract(ok)


def test_abstract_eval_shape_mismatch():
    def fn(x):
        return x * 2.0

    bad = KernelContract(
        name="ev", kind="jit", fn=fn, module="x", qualname="ev",
        example=lambda: ((jax.ShapeDtypeStruct((4, 4), jnp.float32),),
                         {}),
        expect=lambda out: None if tuple(out.shape) == (8, 8)
        else f"got {out.shape}")
    assert [f.rule for f in check_contract(bad)] \
        == ["kernel-abstract-eval"]


def test_decorator_registers_and_preserves_fn():
    @kernel_contract("toy_reg", kind="jit", vmem_budget=None)
    def fn(x):
        return x

    assert fn(3) == 3
    assert fn.__kernel_contract__.name == "toy_reg"


# -- CLI ---------------------------------------------------------------------

def test_cli_json_and_exit_codes(tmp_path):
    from filodb_tpu.lint.__main__ import main
    bad = tmp_path / "bad.py"
    bad.write_text(TRACE_SIDE_EFFECT)
    good = tmp_path / "good.py"
    good.write_text(TRACE_SIDE_EFFECT_CLEAN)
    assert main(["--no-contracts", str(good)]) == 0
    assert main(["--no-contracts", str(bad)]) == 1


def test_cli_json_machine_readable(tmp_path, capsys):
    from filodb_tpu.lint.__main__ import main
    bad = tmp_path / "bad.py"
    bad.write_text(TRACE_SIDE_EFFECT)
    rc = main(["--no-contracts", "--json", str(bad)])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1 and out["exit_code"] == 1
    assert out["findings"][0]["rule"] == "trace-side-effect"
    assert {"path", "line", "message", "severity"} <= \
        set(out["findings"][0])


# -- host transfer in hot loops (serving fast path, PR 3) --------------------

HOT_TRANSFER = """
import numpy as np
from filodb_tpu.lint.hotpath import hot_path

@hot_path
def serve_query(x):
    return np.asarray(x)
"""

HOT_TRANSFER_PRAGMA = """
import numpy as np
from filodb_tpu.lint.hotpath import hot_path

@hot_path
def serve_query(x):
    # graftlint: disable=host-transfer-in-hot-loop (single designed sync point)
    return np.asarray(x)
"""

HOT_TRANSFER_COLD = """
import numpy as np

def offline_job(x):
    return np.asarray(x)        # not marked hot: out of scope
"""

HOT_TRANSFER_METHOD = """
from filodb_tpu.lint.hotpath import hot_path

@hot_path
def serve_query(x):
    return x.item()
"""

HOT_TRANSFER_NESTED = """
import numpy as np
from filodb_tpu.lint.hotpath import hot_path

@hot_path
def serve_query(xs):
    def split(i):
        return np.asarray(xs)[i]     # nested helper runs in the hot path
    return split(0)
"""

HOT_TRANSFER_DUNDER = """
import numpy as np

__hot_path__ = ("serve_query",)

def serve_query(x):
    return np.ascontiguousarray(x)
"""


def test_host_transfer_in_hot_loop(tmp_path):
    assert rules_of(lint_src(tmp_path, HOT_TRANSFER)) \
        == ["host-transfer-in-hot-loop"]
    assert not lint_src(tmp_path, HOT_TRANSFER_PRAGMA).findings
    assert lint_src(tmp_path, HOT_TRANSFER_PRAGMA).suppressed == 1
    assert not lint_src(tmp_path, HOT_TRANSFER_COLD).findings
    assert rules_of(lint_src(tmp_path, HOT_TRANSFER_METHOD)) \
        == ["host-transfer-in-hot-loop"]
    assert rules_of(lint_src(tmp_path, HOT_TRANSFER_NESTED)) \
        == ["host-transfer-in-hot-loop"]
    assert rules_of(lint_src(tmp_path, HOT_TRANSFER_DUNDER)) \
        == ["host-transfer-in-hot-loop"]


# -- CI annotations (--github) -----------------------------------------------

def test_github_annotations_format(tmp_path):
    from filodb_tpu.lint.ci_annotations import github_annotations
    res = lint_src(tmp_path, HOT_TRANSFER)
    lines = github_annotations(res.to_json())
    assert len(lines) == 1
    ln = lines[0]
    assert ln.startswith("::error file=")
    assert ",line=7," in ln
    assert "title=graftlint host-transfer-in-hot-loop" in ln
    assert ln.endswith("syncs device->host on the per-query path")


def test_github_annotations_escaping_and_levels():
    from filodb_tpu.lint.ci_annotations import github_annotations
    payload = {
        "findings": [{"path": "a,b:c.py", "line": 3, "rule": "r1",
                      "severity": "error",
                      "message": "bad\nthing 100%"}],
        "baselined": [{"path": "old.py", "line": 9, "rule": "r2",
                       "severity": "error", "message": "grandfathered"}],
    }
    lines = github_annotations(payload)
    assert lines[0] == ("::error file=a%2Cb%3Ac.py,line=3,"
                        "title=graftlint r1::bad%0Athing 100%25")
    assert lines[1].startswith("::warning file=old.py,line=9,")


def test_cli_github_flag(tmp_path):
    import subprocess
    import sys
    p = tmp_path / "hot_fixture.py"
    p.write_text(HOT_TRANSFER)
    out = subprocess.run(
        [sys.executable, "-m", "filodb_tpu.lint", "--github",
         "--no-contracts", str(p)],
        capture_output=True, text=True)
    assert out.returncode == 1
    assert out.stdout.startswith("::error file=")
    assert "host-transfer-in-hot-loop" in out.stdout


# -- span discipline (obs/trace) ---------------------------------------------

SPAN_BARE_OPEN = """
from filodb_tpu.obs import trace

def f():
    sp = trace.start_span("work")
    sp.tag(step=1)
"""

SPAN_BARE_OPEN_CLEAN = """
from filodb_tpu.obs import trace

def f():
    with trace.span("work") as sp:
        sp.tag(step=1)
"""

SPAN_DISCARDED = """
from filodb_tpu.obs import trace

def f():
    trace.span("work")
    return 1
"""

SPAN_HOT_FORMAT = """
from filodb_tpu.lint.hotpath import hot_path
from filodb_tpu.obs import trace

@hot_path
def serve(x):
    with trace.span(f"query-{x}"):
        return x
"""

SPAN_HOT_FORMAT_GUARDED = """
from filodb_tpu.lint.hotpath import hot_path
from filodb_tpu.obs import trace

@hot_path
def serve(x):
    if trace.trace_active():
        with trace.span("query", xid="%s" % x):
            return x
    return x
"""

SPAN_HOT_RAW_ARGS = """
from filodb_tpu.lint.hotpath import hot_path
from filodb_tpu.obs import trace

@hot_path
def serve(x, n):
    with trace.span("query", xid=x, series=n):
        return x
"""

SPAN_COLD_FORMAT = """
from filodb_tpu.obs import trace

def cold(x):
    with trace.span("query", xid=f"id-{x}"):
        return x
"""

SPAN_HOT_TAG_FORMAT = """
from filodb_tpu.lint.hotpath import hot_path
from filodb_tpu.obs import trace

@hot_path
def serve(x):
    with trace.span("query") as sp:
        sp.tag(detail="item {}".format(x))
        return x
"""

SPAN_HOT_FORMAT_PRAGMA = """
from filodb_tpu.lint.hotpath import hot_path
from filodb_tpu.obs import trace

@hot_path
def serve(x):
    # graftlint: disable=span-discipline (label cost accepted: debug build only)
    with trace.span(f"query-{x}"):
        return x
"""


def test_span_discipline_bare_open(tmp_path):
    assert rules_of(lint_src(tmp_path, SPAN_BARE_OPEN)) \
        == ["span-discipline"]
    assert not lint_src(tmp_path, SPAN_BARE_OPEN_CLEAN).findings


def test_span_discipline_discarded_span(tmp_path):
    assert rules_of(lint_src(tmp_path, SPAN_DISCARDED)) \
        == ["span-discipline"]


def test_span_discipline_hot_path_formatting(tmp_path):
    assert rules_of(lint_src(tmp_path, SPAN_HOT_FORMAT)) \
        == ["span-discipline"]
    # behind the sampling guard: formatting only runs when traced
    assert not lint_src(tmp_path, SPAN_HOT_FORMAT_GUARDED).findings
    # raw values are free — the span stores them without formatting
    assert not lint_src(tmp_path, SPAN_HOT_RAW_ARGS).findings
    # cold (non-@hot_path) code may format freely
    assert not lint_src(tmp_path, SPAN_COLD_FORMAT).findings
    # .tag() with formatting in hot scope is the same leak
    assert rules_of(lint_src(tmp_path, SPAN_HOT_TAG_FORMAT)) \
        == ["span-discipline"]
    # pragma with a reason suppresses
    res = lint_src(tmp_path, SPAN_HOT_FORMAT_PRAGMA)
    assert not res.findings and res.suppressed == 1


# -- interprocedural concurrency engine (graftlint v2) -----------------------
#
# Fixture convention unchanged: every violation snippet trips exactly the
# named rule; every clean twin passes. The engine fixtures additionally
# poke the call-graph internals (construction, roots, propagation).

def _build_cg(tmp_path, src, name="fix_cg.py"):
    from filodb_tpu.lint import callgraph as cgm
    from filodb_tpu.lint import load_module
    p = tmp_path / name
    p.write_text(src)
    mod = load_module(str(p), root=str(tmp_path))
    assert mod is not None
    return cgm.build([mod])


CG_CONSTRUCTION = """
import threading

class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        self.items = {}

    def start(self):
        threading.Thread(target=self._run, daemon=True).start()

    def _run(self):
        self._step_once()

    def _step_once(self):
        def inner():
            self.items["k"] = 1
        inner()

def helper():
    w = Worker()
    w.start()
"""


def test_callgraph_construction(tmp_path):
    cg = _build_cg(tmp_path, CG_CONSTRUCTION)
    # methods, closures, module functions all indexed
    assert "fix_cg:Worker._run" in cg.funcs
    assert "fix_cg:Worker._step_once.<locals>.inner" in cg.funcs
    assert "fix_cg:helper" in cg.funcs
    # Thread(target=self._run) (chained .start()) makes _run a root
    assert "fix_cg:Worker._run" in cg.roots
    # method edge _run -> _step_once, closure edge _step_once -> inner
    run_sites = cg.funcs["fix_cg:Worker._run"].sites
    assert any("fix_cg:Worker._step_once" in s.callees for s in run_sites)
    step_sites = cg.funcs["fix_cg:Worker._step_once"].sites
    assert any("fix_cg:Worker._step_once.<locals>.inner" in s.callees
               for s in step_sites)
    # constructor-typed local: w = Worker() resolves w.start()
    helper_sites = cg.funcs["fix_cg:helper"].sites
    assert any("fix_cg:Worker.start" in s.callees for s in helper_sites)
    # the closure's subscript store is attributed to Worker.items and
    # reachable from the thread root
    inner = cg.funcs["fix_cg:Worker._step_once.<locals>.inner"]
    assert [m.target for m in inner.mutations] == ["Worker.items"]
    assert inner.key in cg.reachable_from["fix_cg:Worker._run"]


LOCK_ORDER_CYCLE = """
import threading

class PairA:
    def __init__(self):
        self._la = threading.Lock()
        self.peer = PairB()

    def forward(self):
        with self._la:
            self.peer.grab_b()

    def grab_a(self):
        with self._la:
            pass

class PairB:
    def __init__(self):
        self._lb = threading.Lock()
        self.back = PairA()

    def grab_b(self):
        with self._lb:
            pass

    def reverse(self):
        with self._lb:
            self.back.grab_a()
"""

LOCK_ORDER_CYCLE_CLEAN = """
import threading

class PairA:
    def __init__(self):
        self._la = threading.Lock()
        self.peer = PairB()

    def forward(self):
        with self._la:
            self.peer.grab_b()

    def also_forward(self):
        with self._la:
            self.peer.grab_b()      # same direction: no cycle

class PairB:
    def __init__(self):
        self._lb = threading.Lock()

    def grab_b(self):
        with self._lb:
            pass
"""


def test_lock_order_cycle(tmp_path):
    # the two-lock deadlock: A held-then-B on one path, B held-then-A
    # on another — the classic cross-thread deadlock shape, visible
    # only interprocedurally (each function alone is innocent)
    assert rules_of(lint_src(tmp_path, LOCK_ORDER_CYCLE)) \
        == ["lock-order-cycle"]
    assert not lint_src(tmp_path, LOCK_ORDER_CYCLE_CLEAN).findings


LOCK_ORDER_POLICY = """
import threading

class MembershipManager:
    def __init__(self):
        self._lock = threading.Lock()

class MicroBatcher:
    def __init__(self):
        self._lock = threading.Lock()
        self.mm = MembershipManager()

    def bad(self):
        with self._lock:
            with self.mm._lock:
                pass
"""

LOCK_ORDER_POLICY_CLEAN = """
import threading

class MembershipManager:
    def __init__(self):
        self._lock = threading.Lock()
        self.mb = MicroBatcher()

    def good(self):
        with self._lock:
            with self.mb._lock:     # outer #0 before inner #2
                pass

class MicroBatcher:
    def __init__(self):
        self._lock = threading.Lock()
"""


def test_lock_order_policy(tmp_path):
    # canonical order (lint/lockorder.py): MembershipManager._lock is
    # outermost — acquiring it while holding the batcher lock violates
    # the declared order even though no cycle exists yet
    assert rules_of(lint_src(tmp_path, LOCK_ORDER_POLICY)) \
        == ["lock-order-policy"]
    assert not lint_src(tmp_path, LOCK_ORDER_POLICY_CLEAN).findings


DEEP_BLOCKING = """
import threading
import urllib.request

class Client:
    def __init__(self):
        self._lock = threading.Lock()

    def serve(self):
        with self._lock:
            self._refresh_state()

    def _refresh_state(self):
        self._fetch_peer()

    def _fetch_peer(self):
        return urllib.request.urlopen("http://peer/health")
"""

DEEP_BLOCKING_CLEAN = """
import threading
import urllib.request

class Client:
    def __init__(self):
        self._lock = threading.Lock()

    def serve(self):
        with self._lock:
            want = True
        if want:
            self._refresh_state()       # RPC strictly outside the lock

    def _refresh_state(self):
        self._fetch_peer()

    def _fetch_peer(self):
        return urllib.request.urlopen("http://peer/health")
"""


def test_deep_blocking_under_lock(tmp_path):
    # the peer RPC is 3 frames below the lock acquisition — the
    # per-function rule cannot see it; the chain is in the message
    res = lint_src(tmp_path, DEEP_BLOCKING)
    assert rules_of(res) == ["lock-blocking-reachable"]
    assert "urllib.urlopen" in res.findings[0].message
    assert "_fetch_peer" in res.findings[0].message
    assert not lint_src(tmp_path, DEEP_BLOCKING_CLEAN).findings


UNGUARDED_SHARED = """
import threading

class Svc:
    def __init__(self):
        self.counts = {}

    def start(self):
        threading.Thread(target=self._poller, daemon=True).start()
        threading.Thread(target=self._flusher, daemon=True).start()

    def _poller(self):
        self.counts.setdefault("a", 0)

    def _flusher(self):
        self.counts.pop("a", None)
"""

UNGUARDED_SHARED_LOCKED = """
import threading

class Svc:
    def __init__(self):
        self._lock = threading.Lock()
        self.counts = {}

    def start(self):
        threading.Thread(target=self._poller, daemon=True).start()
        threading.Thread(target=self._flusher, daemon=True).start()

    def _poller(self):
        with self._lock:
            self.counts.setdefault("a", 0)

    def _flusher(self):
        with self._lock:
            self.counts.pop("a", None)
"""

UNGUARDED_SHARED_DECLARED = """
import threading
from filodb_tpu.lint.locks import guarded_by

@guarded_by("_lock", "counts")
class Svc:
    def __init__(self):
        self._lock = threading.Lock()
        self.counts = {}

    def start(self):
        threading.Thread(target=self._poller, daemon=True).start()

    def _poller(self):
        with self._lock:
            self.counts.setdefault("a", 0)

    def _flusher_locked(self):
        self.counts.pop("a", None)
"""

UNGUARDED_SINGLE_WRITER = """
import threading
from filodb_tpu.lint.locks import single_writer

@single_writer("instances are owned by one worker at a time")
class Svc:
    def __init__(self):
        self.counts = {}

    def start(self):
        threading.Thread(target=self._poller, daemon=True).start()
        threading.Thread(target=self._flusher, daemon=True).start()

    def _poller(self):
        self.counts.setdefault("a", 0)

    def _flusher(self):
        self.counts.pop("a", None)
"""

UNGUARDED_ATOMIC_REBIND = """
import threading

class Svc:
    def __init__(self):
        self.latest = {}

    def start(self):
        threading.Thread(target=self._poller, daemon=True).start()
        threading.Thread(target=self._flusher, daemon=True).start()

    def _poller(self):
        self.latest = {"a": 1}      # GIL-atomic publish: fine

    def _flusher(self):
        self.latest = {}
"""

UNGUARDED_THREAD_ROOT_MARKER = """
import threading
from filodb_tpu.lint.threads import thread_root

class Svc:
    def __init__(self):
        self.counts = {}

    def start(self):
        threading.Thread(target=self._poller, daemon=True).start()

    def _poller(self):
        self.counts.setdefault("a", 0)

    @thread_root("framework-callback")
    def on_event(self):
        self.counts.pop("a", None)
"""


def test_unguarded_shared_state(tmp_path):
    # two thread roots compound-mutate Svc.counts with no common lock
    res = lint_src(tmp_path, UNGUARDED_SHARED)
    assert rules_of(res) == ["thread-unguarded-shared-state"]
    assert "2 thread roots" in res.findings[0].message
    # common lock at every mutation site: clean
    assert not lint_src(tmp_path, UNGUARDED_SHARED_LOCKED).findings
    # @guarded_by declared: rules_lock owns enforcement, not inference
    assert not lint_src(tmp_path, UNGUARDED_SHARED_DECLARED).findings
    # @single_writer declared (per-shard ownership): exempt by design
    assert not lint_src(tmp_path, UNGUARDED_SINGLE_WRITER).findings
    # plain rebinds are the atomic-publish idiom, never compound
    assert not lint_src(tmp_path, UNGUARDED_ATOMIC_REBIND).findings


def test_thread_root_marker_is_a_root(tmp_path):
    # an @thread_root-marked framework callback counts as a root even
    # though no Thread(target=...) spawn is visible in the AST
    res = lint_src(tmp_path, UNGUARDED_THREAD_ROOT_MARKER)
    assert rules_of(res) == ["thread-unguarded-shared-state"]


def test_concurrency_finding_pragma_suppression(tmp_path):
    src = UNGUARDED_SHARED.replace(
        '        self.counts.setdefault("a", 0)',
        '        # graftlint: disable=thread-unguarded-shared-state '
        '(benign test fixture)\n'
        '        self.counts.setdefault("a", 0)')
    res = lint_src(tmp_path, src)
    assert not res.findings and res.suppressed == 1


def test_concurrency_finding_github_annotation(tmp_path):
    from filodb_tpu.lint.ci_annotations import github_annotations
    res = lint_src(tmp_path, DEEP_BLOCKING)
    lines = github_annotations(res.to_json())
    assert len(lines) == 1
    assert lines[0].startswith("::error file=")
    assert "lock-blocking-reachable" in lines[0]


def test_rules_catalog_has_concurrency_family():
    from filodb_tpu.lint import rules
    cat = rules()
    for rid in ("lock-order-cycle", "lock-order-policy",
                "lock-blocking-reachable",
                "thread-unguarded-shared-state"):
        assert rid in cat and cat[rid].family == "concurrency"
        assert cat[rid].severity == "error"


# -- --changed-only (git-diff-scoped reporting) ------------------------------

def test_report_only_filters_findings(tmp_path):
    bad1 = tmp_path / "one.py"
    bad1.write_text(TRACE_SIDE_EFFECT)
    bad2 = tmp_path / "two.py"
    bad2.write_text(TRACE_SIDE_EFFECT)
    full = run_lint([str(bad1), str(bad2)], baseline=frozenset(),
                    check_contracts=False)
    assert len(full.findings) == 2
    only = run_lint([str(bad1), str(bad2)], baseline=frozenset(),
                    check_contracts=False,
                    report_only=frozenset([full.findings[0].path]))
    assert len(only.findings) == 1
    assert only.findings[0].path == full.findings[0].path


def test_changed_only_cli_reports_nothing_when_tree_clean(tmp_path,
                                                          monkeypatch):
    # point package_root at a tmp git repo with one committed file and
    # one dirty file; --changed-only must anchor findings to the dirty
    # file only (the committed one still participates in the analysis)
    import subprocess
    import filodb_tpu.lint as lint_mod
    import filodb_tpu.lint.__main__ as lint_main
    repo = tmp_path / "repo"
    repo.mkdir()
    subprocess.run(["git", "init", "-q"], cwd=repo, check=True)
    subprocess.run(["git", "-c", "user.email=t@t", "-c", "user.name=t",
                    "commit", "-q", "--allow-empty", "-m", "seed"],
                   cwd=repo, check=True)
    committed = repo / "old.py"
    committed.write_text(TRACE_SIDE_EFFECT)
    subprocess.run(["git", "add", "old.py"], cwd=repo, check=True)
    subprocess.run(["git", "-c", "user.email=t@t", "-c", "user.name=t",
                    "commit", "-q", "-m", "add old"], cwd=repo,
                   check=True)
    dirty = repo / "new.py"
    dirty.write_text(HOT_TRANSFER)
    monkeypatch.setattr(lint_mod, "package_root", lambda: str(repo))
    monkeypatch.setattr(lint_main, "package_root", lambda: str(repo))
    changed = lint_main.changed_files()
    assert changed == frozenset(["new.py"])
    res = run_lint([str(repo)], baseline=frozenset(),
                   check_contracts=False, report_only=changed)
    assert [f.path for f in res.findings] == ["new.py"]
    assert res.findings[0].rule == "host-transfer-in-hot-loop"


# -- graftlint v3: SPMD & device-dataflow families ---------------------------

SPMD_DIVERGENT = """
import functools
import jax
from jax.sharding import Mesh, PartitionSpec as P

mesh = Mesh(jax.devices(), ("shard",))

@functools.partial(jax.shard_map, mesh=mesh, in_specs=(P("shard"),),
                   out_specs=P())
def f(x):
    if jax.process_index() == 0:
        return jax.lax.psum(x.astype(jax.numpy.int32), "shard")
    return x
"""

SPMD_DIVERGENT_CLEAN = """
import functools
import jax
from jax.sharding import Mesh, PartitionSpec as P

mesh = Mesh(jax.devices(), ("shard",))

@functools.partial(jax.jit, static_argnames=("agg",))
def run(x, agg):
    # the mesh.py idiom: the shard_map body closes over the jit
    # wrapper's STATIC parameter — branching on it is uniform across
    # devices (one trace per static value)
    @functools.partial(jax.shard_map, mesh=mesh,
                       in_specs=(P("shard"),), out_specs=P())
    def inner(x):
        out = jax.lax.psum(x.astype(jax.numpy.int32), "shard")
        if agg == "mean":
            out = out / jax.lax.psum(1.0, "shard")
        return out
    return inner(x)
"""

SPMD_DIVERGENT_PRAGMA = """
import functools
import jax
from jax.sharding import Mesh, PartitionSpec as P

mesh = Mesh(jax.devices(), ("shard",))

@functools.partial(jax.shard_map, mesh=mesh, in_specs=(P("shard"),),
                   out_specs=P())
def f(x):
    if jax.process_index() == 0:
        # graftlint: disable=spmd-collective-balance (single-host test rig)
        return jax.lax.psum(x.astype(jax.numpy.int32), "shard")
    return x
"""


def test_spmd_collective_divergent(tmp_path):
    assert rules_of(lint_src(tmp_path, SPMD_DIVERGENT)) \
        == ["spmd-collective-balance"]
    assert not lint_src(tmp_path, SPMD_DIVERGENT_CLEAN).findings
    res = lint_src(tmp_path, SPMD_DIVERGENT_PRAGMA)
    assert not res.findings and res.suppressed == 1


SPMD_BAD_AXIS = """
import functools
import jax
from jax.sharding import Mesh, PartitionSpec as P

mesh = Mesh(jax.devices(), ("shard", "time"))

@functools.partial(jax.shard_map, mesh=mesh,
                   in_specs=(P("shard"),), out_specs=P())
def f(x):
    return jax.lax.psum(x.astype(jax.numpy.int32), "shards")
"""


def test_spmd_collective_axis_mismatch(tmp_path):
    res = lint_src(tmp_path, SPMD_BAD_AXIS)
    assert rules_of(res) == ["spmd-collective-balance"]
    assert "'shards'" in res.findings[0].message


SPMD_COND_BRANCH = """
import functools
import jax
from jax.sharding import Mesh, PartitionSpec as P

mesh = Mesh(jax.devices(), ("shard",))

def then_branch(x):
    return jax.lax.psum(x.astype(jax.numpy.int32), "shard")

@functools.partial(jax.shard_map, mesh=mesh, in_specs=(P("shard"),),
                   out_specs=P())
def f(x):
    return jax.lax.cond(x.sum() > 0, then_branch, lambda v: v, x)
"""


def test_spmd_collective_in_cond_branch(tmp_path):
    assert "spmd-collective-balance" in rules_of(
        lint_src(tmp_path, SPMD_COND_BRANCH))


DONATE_USE_AFTER = """
import jax

step = jax.jit(lambda a, b: a + b, donate_argnums=(0,))

def advance(x, y):
    out = step(x, y)
    return out + x
"""

DONATE_CLEAN = """
import jax

step = jax.jit(lambda a, b: a + b, donate_argnums=(0,))

def advance(x, y):
    x = step(x, y)          # rebind: the donated name dies with the call
    return x + y


class Store:
    def __init__(self):
        self.tiles = None

    def refresh(self, delta):
        # the zero-copy refresh idiom: same state rebound from the result
        self.tiles = step(self.tiles, delta)
        return self.tiles
"""

DONATE_DOUBLE = """
import jax

step = jax.jit(lambda a, b: a + b, donate_argnums=(0,))

def advance(x):
    return step(x, x)
"""

DONATE_ALIASED = """
import jax

step = jax.jit(lambda a, b: a + b, donate_argnums=(0,))


class Store:
    def __init__(self):
        self.tiles = None

    def refresh(self, delta):
        out = step(self.tiles, delta)   # donates live state, no rebind
        return out
"""

DONATE_PRAGMA = """
import jax

step = jax.jit(lambda a, b: a + b, donate_argnums=(0,))

def advance(x, y):
    out = step(x, y)  # graftlint: disable=donation-safety (x provably dead: caller drops it)
    return out + x
"""


def test_donation_safety(tmp_path):
    assert rules_of(lint_src(tmp_path, DONATE_USE_AFTER)) \
        == ["donation-safety"]
    assert rules_of(lint_src(tmp_path, DONATE_DOUBLE)) \
        == ["donation-safety"]
    assert rules_of(lint_src(tmp_path, DONATE_ALIASED)) \
        == ["donation-safety"]
    assert not lint_src(tmp_path, DONATE_CLEAN).findings
    res = lint_src(tmp_path, DONATE_PRAGMA)
    assert not res.findings and res.suppressed == 1


DONATE_TUPLE_CLEAN = """
import functools
import jax


@functools.partial(jax.jit, donate_argnums=(0, 1))
def append(ts, vals, new):
    return ts + new, vals + new


class Store:
    def __init__(self):
        self.ts = None
        self.vals = None

    def refresh(self, new):
        # the MULTI-BUFFER zero-copy refresh idiom: every donated
        # attribute rebound from the result in the same statement
        self.ts, self.vals = append(self.ts, self.vals, new)
"""

DONATE_TUPLE_VIOLATION = """
import functools
import jax


@functools.partial(jax.jit, donate_argnums=(0, 1))
def append(ts, vals, new):
    return ts + new, vals + new


class Store:
    def __init__(self):
        self.ts = None
        self.vals = None

    def refresh(self, new):
        # self.vals is donated but NOT rebound: live state aliases a
        # freed buffer
        self.ts, _scratch = append(self.ts, self.vals, new)
"""


def test_donation_tuple_target_refresh_idiom(tmp_path):
    assert not lint_src(tmp_path, DONATE_TUPLE_CLEAN).findings
    res = lint_src(tmp_path, DONATE_TUPLE_VIOLATION)
    assert rules_of(res) == ["donation-safety"]
    assert "self.vals" in res.findings[0].message


DONATE_MISSING = """
import jax

step = jax.jit(lambda a, b: a + b)

def run(x, ys):
    for y in ys:
        x = step(x, y)
    return x
"""

DONATE_MISSING_CLEAN = """
import jax

step = jax.jit(lambda a, b: a + b, donate_argnums=(0,))

def run(x, ys):
    for y in ys:
        x = step(x, y)
    return x
"""


def test_donation_missing_advisory(tmp_path):
    res = lint_src(tmp_path, DONATE_MISSING)
    assert rules_of(res) == ["donation-missing"]
    assert res.findings[0].severity == "warning"
    assert not res.errors            # advisory: never fails the gate
    assert not lint_src(tmp_path, DONATE_MISSING_CLEAN).findings


SPEC_ARITY = """
import functools
import jax
from jax.sharding import Mesh, PartitionSpec as P

mesh = Mesh(jax.devices(), ("shard",))

@functools.partial(jax.shard_map, mesh=mesh,
                   in_specs=(P("shard"), P("shard")), out_specs=P())
def f(x):
    return x
"""

SPEC_BAD_MESH_AXIS = """
import functools
import jax
from jax.sharding import Mesh, PartitionSpec as P

mesh = Mesh(jax.devices(), ("shard", "time"))

@functools.partial(jax.shard_map, mesh=mesh,
                   in_specs=(P("stime"),), out_specs=P())
def f(x):
    return x
"""

SPEC_OUT_ARITY = """
import functools
import jax
from jax.sharding import Mesh, PartitionSpec as P

mesh = Mesh(jax.devices(), ("shard",))

@functools.partial(jax.shard_map, mesh=mesh, in_specs=(P("shard"),),
                   out_specs=(P(), P()))
def f(x):
    return x + 1.0
"""

SPEC_CLEAN = """
import functools
import jax
from jax.sharding import Mesh, PartitionSpec as P

mesh = Mesh(jax.devices(), ("shard", "time"))

@functools.partial(jax.shard_map, mesh=mesh,
                   in_specs=(P("shard", None), P("shard")),
                   out_specs=(P(None, "time"), P(None, "time")))
def f(x, g):
    return x, x * 2.0
"""


def test_partition_spec_consistency(tmp_path):
    assert rules_of(lint_src(tmp_path, SPEC_ARITY)) \
        == ["partition-spec-consistency"]
    assert rules_of(lint_src(tmp_path, SPEC_BAD_MESH_AXIS)) \
        == ["partition-spec-consistency"]
    assert rules_of(lint_src(tmp_path, SPEC_OUT_ARITY)) \
        == ["partition-spec-consistency"]
    assert not lint_src(tmp_path, SPEC_CLEAN).findings


SPEC_POSITIONAL_CLEAN = """
import functools
import jax
from jax.sharding import Mesh, PartitionSpec as P

mesh = Mesh(jax.devices(), ("shard", "time"))

@functools.partial(jax.shard_map, mesh=mesh,
                   in_specs=(P(None, 0), P(0)),
                   out_specs=P(1, 0))
def f(x, g):
    return x
"""

SPEC_POSITIONAL_OUT_OF_RANGE = """
import functools
import jax
from jax.sharding import Mesh, PartitionSpec as P

mesh = Mesh(jax.devices(), ("shard", "time"))

@functools.partial(jax.shard_map, mesh=mesh,
                   in_specs=(P(2, None),), out_specs=P())
def f(x):
    return x
"""

SPEC_POSITIONAL_DOUBLE_NEG = """
import functools
import jax
from jax.sharding import Mesh, PartitionSpec as P

mesh = Mesh(jax.devices(), ("shard", "time"))

@functools.partial(jax.shard_map, mesh=mesh,
                   in_specs=(P(-1, -1),), out_specs=P())
def f(x):
    return x
"""


def test_partition_spec_positional_indices(tmp_path):
    """Positional PartitionSpec indices (the mesh-agnostic library
    convention of the sharded tile store) resolve against the mesh
    axis order: in-range indices are clean, out-of-range and a
    repeated -1 are findings — the same errors the runtime resolver
    raises, caught at lint time."""
    assert not lint_src(tmp_path, SPEC_POSITIONAL_CLEAN).findings
    res = lint_src(tmp_path, SPEC_POSITIONAL_OUT_OF_RANGE)
    assert rules_of(res) == ["partition-spec-consistency"]
    assert "out of range" in res.findings[0].message
    res = lint_src(tmp_path, SPEC_POSITIONAL_DOUBLE_NEG)
    assert rules_of(res) == ["partition-spec-consistency"]
    assert "-1" in res.findings[0].message


# -- graftlint v3: cache-invalidation completeness ---------------------------

CACHE_WIRED = """
from filodb_tpu.lint.caches import cache_registry, event_source, publishes


@cache_registry("plans", invalidated_by={"topology": "invalidate"},
                validated_by={"epoch": ("lookup",)})
class FixtureCache:
    def __init__(self):
        self._entries = {}

    def invalidate(self, reason=""):
        self._entries.clear()

    def lookup(self, key, shards):
        if read_epoch(shards) != 0:
            return None
        return self._entries.get(key)


@event_source("epoch")
def read_epoch(shards):
    return sum(s.epoch for s in shards)


class Mapper:
    def __init__(self):
        self._subs = []

    def subscribe(self, cb):
        self._subs.append(cb)

    @publishes("topology")
    def update(self, shard):
        for cb in self._subs:
            cb(shard)


class Server:
    def __init__(self, mapper: "Mapper"):
        self.cache = FixtureCache()
        mapper.subscribe(lambda ev: self.cache.invalidate("topology"))
"""

# same world, minus the subscription line: the publisher no longer
# reaches the hook — the PR 5/6 class of bug, caught statically
CACHE_UNWIRED = CACHE_WIRED.replace(
    '        mapper.subscribe(lambda ev: self.cache.invalidate('
    '"topology"))\n', "")

# same world, but the lookup hook stopped consulting the epoch source
CACHE_ROTTED_PULL = CACHE_WIRED.replace(
    "        if read_epoch(shards) != 0:\n            return None\n",
    "")


def test_cache_completeness_wired_clean(tmp_path):
    assert not lint_src(tmp_path, CACHE_WIRED).findings


def test_cache_completeness_unwired_publisher(tmp_path):
    res = lint_src(tmp_path, CACHE_UNWIRED)
    assert rules_of(res) == ["cache-invalidation-completeness"]
    assert "does not reach" in res.findings[0].message


def test_cache_completeness_rotted_pull_hook(tmp_path):
    res = lint_src(tmp_path, CACHE_ROTTED_PULL)
    assert rules_of(res) == ["cache-invalidation-completeness"]
    assert "never reads" in res.findings[0].message


def test_cache_completeness_pragma(tmp_path):
    # the finding anchors at the publisher's `def` line
    src = CACHE_UNWIRED.replace(
        "    def update(self, shard):",
        "    def update(self, shard):"
        "  # graftlint: disable=cache-invalidation-completeness"
        " (wired at deploy time by the embedding app)")
    res = lint_src(tmp_path, src)
    assert not res.findings and res.suppressed == 1


CACHE_UNREGISTERED = """
class ShinyNewCache:
    def __init__(self):
        self._entries = {}
"""

CACHE_REGISTERED = """
from filodb_tpu.lint.caches import cache_registry


@cache_registry("shiny", keyed=("request-shape",))
class ShinyNewCache:
    def __init__(self):
        self._entries = {}
"""


def test_cache_unregistered(tmp_path):
    assert rules_of(lint_src(tmp_path, CACHE_UNREGISTERED)) \
        == ["cache-unregistered"]
    assert not lint_src(tmp_path, CACHE_REGISTERED).findings


# -- graftlint v4: numeric-precision & determinism families ------------------

NARROW_VIOLATION = """
import jax
import jax.numpy as jnp

@jax.jit
def grid(n):
    t = jnp.arange(16, dtype=jnp.int64)
    rel = t * 60000 + 5
    return rel.astype(jnp.int32)
"""

NARROW_CLEAN = """
import jax
import jax.numpy as jnp
from filodb_tpu.lint.numerics import precision

@precision("fixture-span-guard", bits=31, rel_ulps=0,
           reason="grid proved inside int32 ms by the dispatcher")
@jax.jit
def grid(n):
    t = jnp.arange(16, dtype=jnp.int64)
    rel = t * 60000 + 5
    return rel.astype(jnp.int32)
"""

NARROW_PRAGMA = """
import jax
import jax.numpy as jnp

@jax.jit
def grid(n):
    t = jnp.arange(16, dtype=jnp.int64)
    rel = t * 60000 + 5
    # graftlint: disable=precision-narrowing (fixture: span guarded upstream)
    return rel.astype(jnp.int32)
"""


def test_precision_narrowing(tmp_path):
    assert rules_of(lint_src(tmp_path, NARROW_VIOLATION)) \
        == ["precision-narrowing"]
    assert not lint_src(tmp_path, NARROW_CLEAN).findings
    res = lint_src(tmp_path, NARROW_PRAGMA)
    assert not res.findings and res.suppressed == 1


NARROW_F64_VIOLATION = """
import jax
import jax.numpy as jnp

@jax.jit
def shrink(x):
    v = x.astype(jnp.float64) * 2.0
    return v.astype(jnp.float32)
"""


def test_precision_narrowing_f64_to_f32(tmp_path):
    assert rules_of(lint_src(tmp_path, NARROW_F64_VIOLATION)) \
        == ["precision-narrowing"]


ACCUM_VIOLATION = """
import jax
import jax.numpy as jnp

@jax.jit
def total(x):
    y = x.astype(jnp.float32)
    return jnp.sum(y)
"""

ACCUM_CLEAN_ANNOTATED = """
import jax
import jax.numpy as jnp
from filodb_tpu.lint.numerics import precision

@precision("fixture-accum", bits=24, rel_ulps=4, accum_terms=1 << 20,
           reason="at most 2**20 window terms by the dispatcher bound")
@jax.jit
def total(x):
    y = x.astype(jnp.float32)
    return jnp.sum(y)
"""

ACCUM_CLEAN_F64 = """
import jax
import jax.numpy as jnp

@jax.jit
def total(x):
    y = x.astype(jnp.float32)
    return jnp.sum(y, dtype=jnp.float64)
"""

ACCUM_OVERCLAIM = """
import jax
import jax.numpy as jnp
from filodb_tpu.lint.numerics import precision

@precision("fixture-accum-over", bits=24, rel_ulps=4,
           accum_terms=1 << 30,
           reason="bound exceeds the f32 mantissa on purpose")
@jax.jit
def total(x):
    y = x.astype(jnp.float32)
    return jnp.sum(y)
"""


def test_accumulation_bound(tmp_path):
    assert rules_of(lint_src(tmp_path, ACCUM_VIOLATION)) \
        == ["accumulation-bound"]
    assert not lint_src(tmp_path, ACCUM_CLEAN_ANNOTATED).findings
    assert not lint_src(tmp_path, ACCUM_CLEAN_F64).findings
    res = lint_src(tmp_path, ACCUM_OVERCLAIM)
    assert rules_of(res) == ["accumulation-bound"]
    assert "2**24" in res.findings[0].message


ORDER_VIOLATION = """
import functools
import jax
from jax.sharding import Mesh, PartitionSpec as P

mesh = Mesh(jax.devices(), ("shard",))

@functools.partial(jax.shard_map, mesh=mesh, in_specs=(P("shard"),),
                   out_specs=P())
def f(x):
    return jax.lax.psum(x, "shard")
"""

ORDER_CLEAN_ANNOTATED = """
import functools
import jax
from jax.sharding import Mesh, PartitionSpec as P
from filodb_tpu.lint.numerics import order_insensitive

mesh = Mesh(jax.devices(), ("shard",))

@order_insensitive("fixture-psum", tolerance=1e-12,
                   reason="f64 partials; a few ulps across regroupings")
@functools.partial(jax.shard_map, mesh=mesh, in_specs=(P("shard"),),
                   out_specs=P())
def f(x):
    return jax.lax.psum(x, "shard")
"""

ORDER_CLEAN_INT = """
import functools
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

mesh = Mesh(jax.devices(), ("shard",))

@functools.partial(jax.shard_map, mesh=mesh, in_specs=(P("shard"),),
                   out_specs=P())
def f(x):
    counts = x.astype(jnp.int32)
    return jax.lax.psum(counts, "shard")
"""

ORDER_PRAGMA = """
import functools
import jax
from jax.sharding import Mesh, PartitionSpec as P

mesh = Mesh(jax.devices(), ("shard",))

@functools.partial(jax.shard_map, mesh=mesh, in_specs=(P("shard"),),
                   out_specs=P())
def f(x):
    # graftlint: disable=reduction-order-determinism (fixture rig)
    return jax.lax.psum(x, "shard")
"""


def test_reduction_order_determinism(tmp_path):
    assert rules_of(lint_src(tmp_path, ORDER_VIOLATION)) \
        == ["reduction-order-determinism"]
    assert not lint_src(tmp_path, ORDER_CLEAN_ANNOTATED).findings
    assert not lint_src(tmp_path, ORDER_CLEAN_INT).findings
    res = lint_src(tmp_path, ORDER_PRAGMA)
    assert not res.findings and res.suppressed == 1


MIXED_CMP_VIOLATION = """
import jax
import jax.numpy as jnp

def kern(x_ref, o_ref):
    idx = jax.lax.broadcasted_iota(jnp.int32, (8, 128), 0)
    fidx = idx.astype(jnp.float32)
    o_ref[...] = jnp.where(fidx > 3.0, x_ref[...], 0.0)
"""

MIXED_CMP_CLEAN = """
import jax
import jax.numpy as jnp

def kern(x_ref, o_ref):
    idx = jax.lax.broadcasted_iota(jnp.int32, (8, 128), 0)
    o_ref[...] = jnp.where(idx > 3, x_ref[...], 0.0)
"""

MIXED_CMP_PRAGMA = """
import jax
import jax.numpy as jnp

def kern(x_ref, o_ref):
    idx = jax.lax.broadcasted_iota(jnp.int32, (8, 128), 0)
    fidx = idx.astype(jnp.float32)
    # graftlint: disable=mixed-dtype-comparison (indices bounded < 2**24)
    o_ref[...] = jnp.where(fidx > 3.0, x_ref[...], 0.0)
"""


def test_mixed_dtype_comparison(tmp_path):
    res = lint_src(tmp_path, MIXED_CMP_VIOLATION)
    assert "mixed-dtype-comparison" in rules_of(res)
    assert not lint_src(tmp_path, MIXED_CMP_CLEAN).findings
    res = lint_src(tmp_path, MIXED_CMP_PRAGMA)
    assert not res.findings and res.suppressed >= 1


def test_numerics_families_flow_through_json_github_changed(tmp_path):
    """The v4 families ride the generic reporting rails: --json carries
    the rule ids, --github renders annotation lines, and report_only
    (--changed-only) filters findings anchored elsewhere."""
    from filodb_tpu.lint.ci_annotations import github_annotations
    res = lint_src(tmp_path, NARROW_VIOLATION)
    payload = res.to_json()
    assert payload["exit_code"] == 1
    assert [f["rule"] for f in payload["findings"]] \
        == ["precision-narrowing"]
    lines = github_annotations(payload)
    assert len(lines) == 1 and "graftlint precision-narrowing" in lines[0]
    assert lines[0].startswith("::error ")
    # report_only: same tree, findings anchored outside the changed set
    # are dropped while the analysis stays whole-program
    p = tmp_path / "fixture.py"
    full = run_lint([str(p)], baseline=frozenset(),
                    check_contracts=False)
    assert full.findings
    other = run_lint([str(p)], baseline=frozenset(),
                     check_contracts=False,
                     report_only=frozenset(["somewhere/else.py"]))
    assert not other.findings


# -- SARIF (--sarif) ---------------------------------------------------------

def test_sarif_report_shape(tmp_path):
    """SARIF 2.1.0: findings as results, the FULL rule catalog in the
    tool driver (every graftlint family), stable fingerprints."""
    from filodb_tpu.lint import rules
    from filodb_tpu.lint.ci_annotations import sarif_report
    res = lint_src(tmp_path, NARROW_VIOLATION)
    doc = sarif_report(res.to_json())
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "graftlint"
    ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert ids == set(rules())         # ALL families, not just v4
    families = {r["properties"]["family"]
                for r in run["tool"]["driver"]["rules"]}
    assert {"kernel", "trace", "lock", "concurrency", "spmd", "cache",
            "promql", "numerics", "capacity", "meta"} <= families
    (result,) = run["results"]
    assert result["ruleId"] == "precision-narrowing"
    assert result["level"] == "error"
    loc = result["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"].endswith("fixture.py")
    assert loc["region"]["startLine"] >= 1
    assert "graftlint/key" in result["partialFingerprints"]


def test_sarif_baselined_as_note():
    from filodb_tpu.lint.ci_annotations import sarif_report
    payload = {"findings": [], "baselined": [
        {"rule": "trace-side-effect", "path": "a.py", "line": 3,
         "message": "old finding", "severity": "error", "context": "c"}]}
    doc = sarif_report(payload)
    (result,) = doc["runs"][0]["results"]
    assert result["level"] == "note"


def test_cli_sarif_flag(tmp_path):
    import subprocess
    import sys
    bad = tmp_path / "bad.py"
    bad.write_text(NARROW_VIOLATION)
    proc = subprocess.run(
        [sys.executable, "-m", "filodb_tpu.lint", "--sarif",
         "--no-contracts", str(bad)],
        capture_output=True, text=True)
    assert proc.returncode == 1
    doc = json.loads(proc.stdout)
    assert doc["version"] == "2.1.0"
    assert doc["runs"][0]["results"][0]["ruleId"] == "precision-narrowing"


# -- graftlint v5: device-memory residency & capacity families ---------------

RESIDENT_VIOLATION = """
import jax.numpy as jnp


class Store:
    def __init__(self):
        self._buf = jnp.zeros((64, 64))
"""

RESIDENT_CLEAN = """
import jax.numpy as jnp
from filodb_tpu.lint.capacity import capacity


@capacity("fixture-store", bytes_per_sample=8.0,
          reason="one f64 cell per padded slot")
class Store:
    def __init__(self):
        self._buf = jnp.zeros((64, 64))
"""

RESIDENT_PRAGMA = """
import jax.numpy as jnp


class Store:
    def __init__(self):
        # graftlint: disable=hbm-residency-budget (fixture: priced elsewhere)
        self._buf = jnp.zeros((64, 64))
"""

RESIDENT_MODULE_GLOBAL = """
import jax.numpy as jnp

LUT = jnp.arange(4096)
"""


def test_hbm_residency_budget(tmp_path):
    assert rules_of(lint_src(tmp_path, RESIDENT_VIOLATION)) \
        == ["hbm-residency-budget"]
    assert not lint_src(tmp_path, RESIDENT_CLEAN).findings
    assert not lint_src(tmp_path, RESIDENT_PRAGMA).findings
    res = lint_src(tmp_path, RESIDENT_MODULE_GLOBAL)
    assert rules_of(res) == ["hbm-residency-budget"]
    assert "process lifetime" in res.findings[0].message


LEAK_NO_EVICTION = """
import jax.numpy as jnp
from filodb_tpu.lint.caches import cache_registry
from filodb_tpu.lint.capacity import capacity


@cache_registry("fixture-tiles", keyed=("selection",))
@capacity("fixture-tile-store", bytes_per_sample=8.0,
          reason="tiles priced per slot")
class TileCache:
    def __init__(self):
        self._tiles = {}

    def insert(self, key):
        self._tiles[key] = jnp.zeros((64,))
"""

LEAK_EVICTED = LEAK_NO_EVICTION + """
    def evict(self, key):
        self._tiles.pop(key, None)
"""

LEAK_DOUBLE_RETENTION = """
import jax.numpy as jnp
from filodb_tpu.lint.capacity import capacity


@capacity("fixture-pair", bytes_per_sample=8.0, reason="priced")
class Pair:
    def __init__(self):
        buf = jnp.zeros((64,))
        self._a = buf
        self._b = buf
"""


def test_device_buffer_leak(tmp_path):
    res = lint_src(tmp_path, LEAK_NO_EVICTION)
    assert rules_of(res) == ["device-buffer-leak"]
    assert "no eviction operation" in res.findings[0].message
    assert not lint_src(tmp_path, LEAK_EVICTED).findings
    res = lint_src(tmp_path, LEAK_DOUBLE_RETENTION)
    assert rules_of(res) == ["device-buffer-leak"]
    assert "2 stores" in res.findings[0].message


TRANSFER_PULL = """
import numpy as np
from filodb_tpu.lint.hotpath import hot_path


class Chan:
    @hot_path
    def read_all(self):
        # graftlint: disable=host-transfer-in-hot-loop (fixture: sync noted)
        return np.asarray(self._dev)
"""

TRANSFER_PULL_SLICED = """
import numpy as np
from filodb_tpu.lint.hotpath import hot_path


class Chan:
    @hot_path
    def read_window(self, n):
        # graftlint: disable=host-transfer-in-hot-loop (fixture: sync noted)
        return np.asarray(self._dev[:n])
"""

TRANSFER_PADDED = """
import numpy as np
import jax
from filodb_tpu.lint.hotpath import hot_path


def _next_pow2(n, lo):
    p = lo
    while p < n:
        p *= 2
    return p


@hot_path
def ship(ts):
    cap = _next_pow2(ts.size, 64)
    buf = np.zeros((cap,))
    buf[:ts.size] = ts
    return jax.device_put(buf)
"""

TRANSFER_PADDED_PRICED = TRANSFER_PADDED.replace(
    "@hot_path",
    """from filodb_tpu.lint.capacity import capacity


@capacity("fixture-staged", bytes_per_sample=8.0,
          reason="padded staging block priced per slot")
@hot_path""")


def test_oversized_transfer(tmp_path):
    res = lint_src(tmp_path, TRANSFER_PULL)
    assert rules_of(res) == ["oversized-transfer"]
    assert "whole resident channel" in res.findings[0].message
    assert not lint_src(tmp_path, TRANSFER_PULL_SLICED).findings
    res = lint_src(tmp_path, TRANSFER_PADDED)
    assert rules_of(res) == ["oversized-transfer"]
    assert "pow2-capacity-padded" in res.findings[0].message
    assert not lint_src(tmp_path, TRANSFER_PADDED_PRICED).findings


VMEM_OVER_BUDGET = """
def choose(nsteps, vmem_budget=32 << 20):
    for tt in (512, 256):
        if tt * nsteps * 4 <= vmem_budget:
            return tt
    return None
"""

VMEM_UNTESTED = """
def walk(nsteps, vmem_budget=14 << 20):
    total = 0
    for tt in (512, 256):
        total += tt * nsteps
    return total
"""

VMEM_CLEAN = """
def choose(nsteps, vmem_budget=14 << 20):
    for tt in (512, 256):
        if tt * nsteps * 4 <= vmem_budget:
            return tt
    return None
"""

VMEM_PRAGMA = """
# graftlint: disable=vmem-frontier-budget (fixture: host-side prototype)
def walk(nsteps, vmem_budget=14 << 20):
    total = 0
    for tt in (512, 256):
        total += tt * nsteps
    return total
"""


def test_vmem_frontier_budget(tmp_path):
    res = lint_src(tmp_path, VMEM_OVER_BUDGET)
    assert rules_of(res) == ["vmem-frontier-budget"]
    assert "exceeds physical per-core VMEM" in res.findings[0].message
    res = lint_src(tmp_path, VMEM_UNTESTED)
    assert rules_of(res) == ["vmem-frontier-budget"]
    assert "never compares" in res.findings[0].message
    assert not lint_src(tmp_path, VMEM_CLEAN).findings
    assert not lint_src(tmp_path, VMEM_PRAGMA).findings


def test_capacity_families_flow_through_json_github_changed(tmp_path):
    """The v5 families ride the generic reporting rails: --json carries
    the rule ids, --github renders error annotations, --sarif carries
    the capacity family in the driver catalog, and report_only
    (--changed-only) filters findings anchored elsewhere."""
    from filodb_tpu.lint.ci_annotations import github_annotations, \
        sarif_report
    res = lint_src(tmp_path, RESIDENT_VIOLATION)
    payload = res.to_json()
    assert payload["exit_code"] == 1
    assert [f["rule"] for f in payload["findings"]] \
        == ["hbm-residency-budget"]
    lines = github_annotations(payload)
    assert len(lines) == 1 \
        and "graftlint hbm-residency-budget" in lines[0]
    assert lines[0].startswith("::error ")
    doc = sarif_report(payload)
    run = doc["runs"][0]
    assert "capacity" in {r["properties"]["family"]
                          for r in run["tool"]["driver"]["rules"]}
    assert run["results"][0]["ruleId"] == "hbm-residency-budget"
    # report_only: same tree, findings anchored outside the changed set
    # are dropped while the analysis stays whole-program
    p = tmp_path / "fixture.py"
    full = run_lint([str(p)], baseline=frozenset(),
                    check_contracts=False)
    assert full.findings
    other = run_lint([str(p)], baseline=frozenset(),
                     check_contracts=False,
                     report_only=frozenset(["somewhere/else.py"]))
    assert not other.findings
