"""graftlint rule fixtures: each violation snippet trips exactly one
rule; each clean twin passes. Plus contract-object checks (VMEM budget,
tiling, grid bounds, span guard, abstract eval) and CLI exit codes."""

import json

import jax
import jax.numpy as jnp

from filodb_tpu.lint import run_lint
from filodb_tpu.lint.contracts import (Block, KernelContract,
                                       kernel_contract)
from filodb_tpu.lint.rules_kernel import check_contract


def lint_src(tmp_path, src, name="fixture.py"):
    p = tmp_path / name
    p.write_text(src)
    res = run_lint([str(p)], baseline=frozenset(), check_contracts=False)
    return res


def rules_of(res):
    return sorted({f.rule for f in res.findings})


# -- trace safety ------------------------------------------------------------

TRACE_SIDE_EFFECT = """
import functools, time
import jax

@functools.partial(jax.jit, static_argnames=("n",))
def f(x, n):
    return x + time.time()
"""

TRACE_SIDE_EFFECT_CLEAN = """
import functools, time
import jax

def now():
    return time.time()          # host helper, never traced

@functools.partial(jax.jit, static_argnames=("n",))
def f(x, n):
    return x * n
"""


def test_trace_side_effect(tmp_path):
    assert rules_of(lint_src(tmp_path, TRACE_SIDE_EFFECT)) \
        == ["trace-side-effect"]
    assert not lint_src(tmp_path, TRACE_SIDE_EFFECT_CLEAN).findings


TRACE_TRACER_LEAK = """
import jax

@jax.jit
def f(x):
    return 1.0 if bool(x) else 0.0
"""

TRACE_TRACER_LEAK_CLEAN = """
import functools
import jax
import jax.numpy as jnp

@functools.partial(jax.jit, static_argnames=("flag",))
def f(x, flag):
    return jnp.where(bool(flag), x, -x)   # static param: fine
"""


def test_trace_tracer_leak(tmp_path):
    assert rules_of(lint_src(tmp_path, TRACE_TRACER_LEAK)) \
        == ["trace-tracer-leak"]
    assert not lint_src(tmp_path, TRACE_TRACER_LEAK_CLEAN).findings


TRACE_MUTATE = """
import jax

_seen = []

@jax.jit
def f(x):
    _seen.append(x)
    return x
"""

TRACE_MUTATE_CLEAN = """
import jax

@jax.jit
def f(x):
    acc = []
    acc.append(x)               # function-local: fine
    return acc[0]
"""


def test_trace_mutate_capture(tmp_path):
    assert rules_of(lint_src(tmp_path, TRACE_MUTATE)) \
        == ["trace-mutate-capture"]
    assert not lint_src(tmp_path, TRACE_MUTATE_CLEAN).findings


TRACE_F64 = """
import jax.numpy as jnp

def kern(x_ref, o_ref):
    o_ref[:] = x_ref[:].astype(jnp.float64)
"""

TRACE_F64_CLEAN = """
import jax.numpy as jnp

def kern(x_ref, o_ref):
    o_ref[:] = x_ref[:].astype(jnp.float32)

def host_helper(x):
    return x.astype(jnp.float64)    # not a kernel body: fine
"""


def test_trace_f64_in_pallas_body(tmp_path):
    assert rules_of(lint_src(tmp_path, TRACE_F64)) \
        == ["trace-f64-constant"]
    assert not lint_src(tmp_path, TRACE_F64_CLEAN).findings


# -- kernel contracts (AST) --------------------------------------------------

CONTRACT_MISSING = """
from jax.experimental import pallas as pl
import jax
import jax.numpy as jnp

def run(x):
    def kern(x_ref, o_ref):
        o_ref[:] = x_ref[:]
    return pl.pallas_call(
        kern, out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype))(x)
"""

CONTRACT_PRESENT = """
from jax.experimental import pallas as pl
import jax
import jax.numpy as jnp
from filodb_tpu.lint.contracts import kernel_contract

@kernel_contract("toy", kind="pallas", vmem_budget=1 << 20)
def run(x):
    def kern(x_ref, o_ref):
        o_ref[:] = x_ref[:]
    return pl.pallas_call(
        kern, out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype))(x)
"""


def test_kernel_contract_missing(tmp_path):
    assert rules_of(lint_src(tmp_path, CONTRACT_MISSING)) \
        == ["kernel-contract-missing"]
    assert not lint_src(tmp_path, CONTRACT_PRESENT).findings


# -- lock discipline ---------------------------------------------------------

LOCK_ACCESS = """
import threading
from filodb_tpu.lint.locks import guarded_by

@guarded_by("_lock", "_items")
class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []

    def bad(self, x):
        self._items.append(x)
"""

LOCK_ACCESS_CLEAN = """
import threading
from filodb_tpu.lint.locks import guarded_by

@guarded_by("_lock", "_items")
class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []

    def good(self, x):
        with self._lock:
            self._items.append(x)

    def drain_locked(self):
        return list(self._items)    # *_locked: caller holds the lock
"""


def test_lock_guarded_access(tmp_path):
    assert rules_of(lint_src(tmp_path, LOCK_ACCESS)) \
        == ["lock-guarded-access"]
    assert not lint_src(tmp_path, LOCK_ACCESS_CLEAN).findings


LOCK_BLOCKING = """
import threading
import time

class Box:
    def __init__(self):
        self._lock = threading.Lock()

    def slow(self):
        with self._lock:
            time.sleep(1.0)
"""

LOCK_BLOCKING_CLEAN = """
import threading
import time

class Box:
    def __init__(self):
        self._lock = threading.Lock()

    def fine(self):
        with self._lock:
            n = 1 + 1
        time.sleep(0.0)             # outside the lock
        return n
"""


def test_lock_blocking_call(tmp_path):
    assert rules_of(lint_src(tmp_path, LOCK_BLOCKING)) \
        == ["lock-blocking-call"]
    assert not lint_src(tmp_path, LOCK_BLOCKING_CLEAN).findings


LOCK_MODULE_GLOBAL = """
import threading

_cache = {}
_cache_lock = threading.Lock()
__guarded_by__ = {"_cache": "_cache_lock"}

def bad(k):
    return _cache.get(k)
"""


def test_lock_module_global(tmp_path):
    assert rules_of(lint_src(tmp_path, LOCK_MODULE_GLOBAL)) \
        == ["lock-guarded-access"]


# -- pragmas and baseline ----------------------------------------------------

PRAGMA_OK = """
import functools, time
import jax

@functools.partial(jax.jit, static_argnames=("n",))
def f(x, n):
    # graftlint: disable=trace-side-effect (bench-only trace timestamp)
    return x + time.time()
"""

PRAGMA_NO_REASON = """
import functools, time
import jax

@functools.partial(jax.jit, static_argnames=("n",))
def f(x, n):
    return x + time.time()  # graftlint: disable=trace-side-effect
"""


def test_pragma_suppression(tmp_path):
    res = lint_src(tmp_path, PRAGMA_OK)
    assert not res.findings and res.suppressed == 1


def test_pragma_requires_reason(tmp_path):
    assert rules_of(lint_src(tmp_path, PRAGMA_NO_REASON)) \
        == ["pragma-no-reason"]


def test_baseline_grandfathers(tmp_path):
    res = lint_src(tmp_path, TRACE_SIDE_EFFECT)
    assert len(res.findings) == 1
    key = res.findings[0].key()
    p = tmp_path / "fixture.py"
    res2 = run_lint([str(p)], baseline=frozenset([key]),
                    check_contracts=False)
    assert not res2.findings and len(res2.baselined) == 1


# -- contract object checks (no execution) -----------------------------------

def _unrunnable(*a, **k):
    raise AssertionError("contract checks must never execute the kernel")


def test_vmem_budget_catches_oversized_kernel():
    """The acceptance fixture: a deliberately oversized kernel is caught
    by block arithmetic alone — the kernel body would assert if run."""
    c = KernelContract(
        name="oversized", kind="pallas", fn=_unrunnable, module="x",
        qualname="oversized",
        blocks=(Block("x", (8, 128), "float32"),),
        scratch=(Block("s", (8, 2048, 1024), "float32"),),  # 64 MB
        vmem_budget=14 << 20)
    rules = [f.rule for f in check_contract(c, "x.py")]
    assert rules == ["kernel-vmem-budget"]


def test_vmem_budget_required_for_pallas():
    c = KernelContract(name="nobudget", kind="pallas", fn=_unrunnable,
                       module="x", qualname="nobudget")
    assert "kernel-vmem-budget" in [f.rule for f in check_contract(c)]


def test_tile_alignment():
    bad = KernelContract(
        name="tiles", kind="pallas", fn=_unrunnable, module="x",
        qualname="tiles", vmem_budget=1 << 20,
        blocks=(Block("a", (7, 128), "float32"),     # sublane 7 % 8
                Block("b", (8, 100), "float32"),     # lane 100 % 128
                Block("c", (16, 128), "bfloat16"),   # ok: 16 % 16
                Block("d", (8, 128), "float64")))    # 8-byte in VMEM
    rules = sorted(f.rule for f in check_contract(bad))
    assert rules == ["kernel-tile-alignment"] * 3


def test_grid_bounds():
    bad = KernelContract(
        name="grid", kind="pallas", fn=_unrunnable, module="x",
        qualname="grid", vmem_budget=1 << 20, grid=(4,),
        blocks=(Block("a", (8, 128), "float32",
                      array_shape=(16, 128),     # only 2 blocks fit
                      index_map=lambda i: (i, 0)),))
    assert [f.rule for f in check_contract(bad)] == ["kernel-grid-bounds"]


def test_span_guard_must_resolve():
    bad = KernelContract(
        name="span", kind="dispatch", fn=_unrunnable,
        module="filodb_tpu.query.tilestore", qualname="span",
        rel_time_bits=31, span_guard="_no_such_predicate")
    assert [f.rule for f in check_contract(bad)] == ["kernel-span-guard"]
    ok = KernelContract(
        name="span2", kind="dispatch", fn=_unrunnable,
        module="filodb_tpu.query.tilestore", qualname="span2",
        rel_time_bits=31, span_guard="_slide_eligible")
    assert not check_contract(ok)


def test_abstract_eval_shape_mismatch():
    def fn(x):
        return x * 2.0

    bad = KernelContract(
        name="ev", kind="jit", fn=fn, module="x", qualname="ev",
        example=lambda: ((jax.ShapeDtypeStruct((4, 4), jnp.float32),),
                         {}),
        expect=lambda out: None if tuple(out.shape) == (8, 8)
        else f"got {out.shape}")
    assert [f.rule for f in check_contract(bad)] \
        == ["kernel-abstract-eval"]


def test_decorator_registers_and_preserves_fn():
    @kernel_contract("toy_reg", kind="jit", vmem_budget=None)
    def fn(x):
        return x

    assert fn(3) == 3
    assert fn.__kernel_contract__.name == "toy_reg"


# -- CLI ---------------------------------------------------------------------

def test_cli_json_and_exit_codes(tmp_path):
    from filodb_tpu.lint.__main__ import main
    bad = tmp_path / "bad.py"
    bad.write_text(TRACE_SIDE_EFFECT)
    good = tmp_path / "good.py"
    good.write_text(TRACE_SIDE_EFFECT_CLEAN)
    assert main(["--no-contracts", str(good)]) == 0
    assert main(["--no-contracts", str(bad)]) == 1


def test_cli_json_machine_readable(tmp_path, capsys):
    from filodb_tpu.lint.__main__ import main
    bad = tmp_path / "bad.py"
    bad.write_text(TRACE_SIDE_EFFECT)
    rc = main(["--no-contracts", "--json", str(bad)])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1 and out["exit_code"] == 1
    assert out["findings"][0]["rule"] == "trace-side-effect"
    assert {"path", "line", "message", "severity"} <= \
        set(out["findings"][0])


# -- host transfer in hot loops (serving fast path, PR 3) --------------------

HOT_TRANSFER = """
import numpy as np
from filodb_tpu.lint.hotpath import hot_path

@hot_path
def serve_query(x):
    return np.asarray(x)
"""

HOT_TRANSFER_PRAGMA = """
import numpy as np
from filodb_tpu.lint.hotpath import hot_path

@hot_path
def serve_query(x):
    # graftlint: disable=host-transfer-in-hot-loop (single designed sync point)
    return np.asarray(x)
"""

HOT_TRANSFER_COLD = """
import numpy as np

def offline_job(x):
    return np.asarray(x)        # not marked hot: out of scope
"""

HOT_TRANSFER_METHOD = """
from filodb_tpu.lint.hotpath import hot_path

@hot_path
def serve_query(x):
    return x.item()
"""

HOT_TRANSFER_NESTED = """
import numpy as np
from filodb_tpu.lint.hotpath import hot_path

@hot_path
def serve_query(xs):
    def split(i):
        return np.asarray(xs)[i]     # nested helper runs in the hot path
    return split(0)
"""

HOT_TRANSFER_DUNDER = """
import numpy as np

__hot_path__ = ("serve_query",)

def serve_query(x):
    return np.ascontiguousarray(x)
"""


def test_host_transfer_in_hot_loop(tmp_path):
    assert rules_of(lint_src(tmp_path, HOT_TRANSFER)) \
        == ["host-transfer-in-hot-loop"]
    assert not lint_src(tmp_path, HOT_TRANSFER_PRAGMA).findings
    assert lint_src(tmp_path, HOT_TRANSFER_PRAGMA).suppressed == 1
    assert not lint_src(tmp_path, HOT_TRANSFER_COLD).findings
    assert rules_of(lint_src(tmp_path, HOT_TRANSFER_METHOD)) \
        == ["host-transfer-in-hot-loop"]
    assert rules_of(lint_src(tmp_path, HOT_TRANSFER_NESTED)) \
        == ["host-transfer-in-hot-loop"]
    assert rules_of(lint_src(tmp_path, HOT_TRANSFER_DUNDER)) \
        == ["host-transfer-in-hot-loop"]


# -- CI annotations (--github) -----------------------------------------------

def test_github_annotations_format(tmp_path):
    from filodb_tpu.lint.ci_annotations import github_annotations
    res = lint_src(tmp_path, HOT_TRANSFER)
    lines = github_annotations(res.to_json())
    assert len(lines) == 1
    ln = lines[0]
    assert ln.startswith("::error file=")
    assert ",line=7," in ln
    assert "title=graftlint host-transfer-in-hot-loop" in ln
    assert ln.endswith("syncs device->host on the per-query path")


def test_github_annotations_escaping_and_levels():
    from filodb_tpu.lint.ci_annotations import github_annotations
    payload = {
        "findings": [{"path": "a,b:c.py", "line": 3, "rule": "r1",
                      "severity": "error",
                      "message": "bad\nthing 100%"}],
        "baselined": [{"path": "old.py", "line": 9, "rule": "r2",
                       "severity": "error", "message": "grandfathered"}],
    }
    lines = github_annotations(payload)
    assert lines[0] == ("::error file=a%2Cb%3Ac.py,line=3,"
                        "title=graftlint r1::bad%0Athing 100%25")
    assert lines[1].startswith("::warning file=old.py,line=9,")


def test_cli_github_flag(tmp_path):
    import subprocess
    import sys
    p = tmp_path / "hot_fixture.py"
    p.write_text(HOT_TRANSFER)
    out = subprocess.run(
        [sys.executable, "-m", "filodb_tpu.lint", "--github",
         "--no-contracts", str(p)],
        capture_output=True, text=True)
    assert out.returncode == 1
    assert out.stdout.startswith("::error file=")
    assert "host-transfer-in-hot-loop" in out.stdout


# -- span discipline (obs/trace) ---------------------------------------------

SPAN_BARE_OPEN = """
from filodb_tpu.obs import trace

def f():
    sp = trace.start_span("work")
    sp.tag(step=1)
"""

SPAN_BARE_OPEN_CLEAN = """
from filodb_tpu.obs import trace

def f():
    with trace.span("work") as sp:
        sp.tag(step=1)
"""

SPAN_DISCARDED = """
from filodb_tpu.obs import trace

def f():
    trace.span("work")
    return 1
"""

SPAN_HOT_FORMAT = """
from filodb_tpu.lint.hotpath import hot_path
from filodb_tpu.obs import trace

@hot_path
def serve(x):
    with trace.span(f"query-{x}"):
        return x
"""

SPAN_HOT_FORMAT_GUARDED = """
from filodb_tpu.lint.hotpath import hot_path
from filodb_tpu.obs import trace

@hot_path
def serve(x):
    if trace.trace_active():
        with trace.span("query", xid="%s" % x):
            return x
    return x
"""

SPAN_HOT_RAW_ARGS = """
from filodb_tpu.lint.hotpath import hot_path
from filodb_tpu.obs import trace

@hot_path
def serve(x, n):
    with trace.span("query", xid=x, series=n):
        return x
"""

SPAN_COLD_FORMAT = """
from filodb_tpu.obs import trace

def cold(x):
    with trace.span("query", xid=f"id-{x}"):
        return x
"""

SPAN_HOT_TAG_FORMAT = """
from filodb_tpu.lint.hotpath import hot_path
from filodb_tpu.obs import trace

@hot_path
def serve(x):
    with trace.span("query") as sp:
        sp.tag(detail="item {}".format(x))
        return x
"""

SPAN_HOT_FORMAT_PRAGMA = """
from filodb_tpu.lint.hotpath import hot_path
from filodb_tpu.obs import trace

@hot_path
def serve(x):
    # graftlint: disable=span-discipline (label cost accepted: debug build only)
    with trace.span(f"query-{x}"):
        return x
"""


def test_span_discipline_bare_open(tmp_path):
    assert rules_of(lint_src(tmp_path, SPAN_BARE_OPEN)) \
        == ["span-discipline"]
    assert not lint_src(tmp_path, SPAN_BARE_OPEN_CLEAN).findings


def test_span_discipline_discarded_span(tmp_path):
    assert rules_of(lint_src(tmp_path, SPAN_DISCARDED)) \
        == ["span-discipline"]


def test_span_discipline_hot_path_formatting(tmp_path):
    assert rules_of(lint_src(tmp_path, SPAN_HOT_FORMAT)) \
        == ["span-discipline"]
    # behind the sampling guard: formatting only runs when traced
    assert not lint_src(tmp_path, SPAN_HOT_FORMAT_GUARDED).findings
    # raw values are free — the span stores them without formatting
    assert not lint_src(tmp_path, SPAN_HOT_RAW_ARGS).findings
    # cold (non-@hot_path) code may format freely
    assert not lint_src(tmp_path, SPAN_COLD_FORMAT).findings
    # .tag() with formatting in hot scope is the same leak
    assert rules_of(lint_src(tmp_path, SPAN_HOT_TAG_FORMAT)) \
        == ["span-discipline"]
    # pragma with a reason suppresses
    res = lint_src(tmp_path, SPAN_HOT_FORMAT_PRAGMA)
    assert not res.findings and res.suppressed == 1
