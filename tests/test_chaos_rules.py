"""Chaos for the rules engine: evaluation keeps running under node
loss and sustained tenant brownout (tests/test_chaos_qos.py-style
load). Pins: no crash, the staleness metric rises while evaluations
fail, alerts do NOT flap to inactive on evaluation errors, the forced-
charge __rules__ tenant keeps evaluating while the overloaded default
tenant bounces, and recording resumes cleanly after recovery.
"""

import json
import threading
import time
import urllib.error
import urllib.parse
import urllib.request

import pytest

from filodb_tpu.core.record import RecordBuilder
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS
from filodb_tpu.query import qos
from filodb_tpu.standalone.server import FiloServer
from filodb_tpu.testing import chaos

T0 = 1_600_000_000


def _free_port():
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _get_raw(port, path, params, timeout=30):
    qs = urllib.parse.urlencode(params, doseq=True)
    url = f"http://127.0.0.1:{port}{path}?{qs}"
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def _poll(fn, timeout=30.0, interval=0.1, msg="condition"):
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        ok, last = fn()
        if ok:
            return last
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}: {last!r}")


@pytest.fixture()
def cluster():
    """Two in-process nodes; node0 runs the rules engine over a
    fan-out expression (its evaluation NEEDS node1), with tiny budgets
    for every ordinary tenant so sustained load browns the edge out."""
    p0, p1 = _free_port(), _free_port()
    peers = {"node0": f"http://127.0.0.1:{p0}",
             "node1": f"http://127.0.0.1:{p1}"}
    base = {
        "num-shards": 4, "num-nodes": 2, "peers": peers,
        "failure-detect-interval-s": 300.0,   # detection never reacts
        "grpc-port": None,
        "query-timeout-s": 5.0,
        "peer-retry-attempts": 1,             # rule evals fail fast
        "peer-retry-base-delay-s": 0.01,
        "breaker-failure-threshold": 1_000_000,
        "max-inflight-queries": 16,
        "admission-wait-s": 2.0,
        # every unprivileged tenant is budgeted tiny: a handful of
        # real queries drains the default bucket (the brownout)
        "qos-tenant-rate": 2, "qos-tenant-burst": 50,
        "qos-shed-degraded": False,
    }
    a = FiloServer({
        **base, "node-ordinal": 0, "port": p0,
        "rules-eval-span-steps": 4,
        "rules": {"groups": [{
            "name": "chaos", "interval": "0.5s", "rules": [
                {"record": "chaos:sig:sum", "expr": "sum(chaos_sig)"},
                {"alert": "ChaosData", "expr": "sum(chaos_sig) > 0",
                 "labels": {"severity": "page"}},
            ]}]},
    }).start()
    b = FiloServer({**base, "node-ordinal": 1, "port": p1}).start()

    # the signal series lives on BOTH nodes' shards (rule evaluation
    # fans out), one writer thread per node at wall-now
    stop = threading.Event()

    def writer(srv, shard):
        while not stop.is_set():
            rb = RecordBuilder(DEFAULT_SCHEMAS)
            rb.add_sample("gauge", {"_metric_": "chaos_sig",
                                    "shard": str(shard)},
                          int(time.time() * 1000), 1.0)
            for c in rb.containers():
                srv.store.ingest(srv.ref, shard, c)
            time.sleep(0.1)
    threads = [threading.Thread(target=writer, args=(a, 0), daemon=True),
               threading.Thread(target=writer, args=(b, 2), daemon=True)]
    for t in threads:
        t.start()
    try:
        yield a, b
    finally:
        chaos.uninstall()
        stop.set()
        for t in threads:
            t.join(timeout=5)
        for srv in (a, b):
            try:
                srv.stop()
            except Exception:
                pass


def _rule_states(srv):
    payload = srv.rules.rules_payload()
    return {r["name"]: r for g in payload["groups"]
            for r in g["rules"]}


def _staleness(srv, group="chaos"):
    text = srv.http.build_exposition().render()
    for ln in text.splitlines():
        if ln.startswith("filodb_rule_group_staleness_seconds") \
                and f'group="{group}"' in ln:
            return float(ln.rsplit(" ", 1)[1])
    return None


def test_rules_survive_node_loss_and_brownout(cluster):
    a, _b = cluster

    # -- phase 0: healthy — recording + alert firing -------------------
    def _healthy():
        states = _rule_states(a)
        rec = states.get("chaos:sig:sum", {})
        al = states.get("ChaosData", {})
        return (rec.get("health") == "ok"
                and al.get("state") == "firing"), (rec, al)
    _poll(_healthy, msg="healthy rules baseline")
    ticks0 = a.rules.snapshot()["ticks"]

    # -- phase 1: sustained brownout (chaos_qos-style load) ------------
    # the default tenant hammers the edge until its bucket drains and
    # it starts bouncing with 429; the forced-charge __rules__ tenant
    # must keep evaluating through it
    q = {"query": "sum(rate(http_requests_total[5m])) or sum(chaos_sig)",
         "start": int(time.time()) - 600, "end": int(time.time()),
         "step": 5, "cache": "false"}
    stop = threading.Event()
    codes = []

    def abuse():
        while not stop.is_set():
            code, _ = _get_raw(
                a.port, "/promql/timeseries/api/v1/query_range", q)
            codes.append(code)
    t = threading.Thread(target=abuse, daemon=True)
    t.start()
    time.sleep(2.5)
    stop.set()
    t.join(timeout=10)
    assert 429 in codes, f"brownout never tripped: {codes[:10]}"
    snap = a.rules.snapshot()
    assert snap["ticks"] > ticks0 + 2, "rules stalled under brownout"
    states = _rule_states(a)
    assert states["ChaosData"]["state"] == "firing"
    assert states["chaos:sig:sum"]["health"] == "ok"
    # the reserved tenant charged FORCED (possibly into debt), never
    # bounced
    bucket = a.http.admission.budgets.bucket(qos.RULES_TENANT)
    assert bucket is not None and bucket.forced_charges > 0

    # -- phase 2: node loss — evaluations fail, nothing flaps ----------
    inj = chaos.ChaosInjector()
    inj.fail("http.peer", match=lambda c: c.get("node") == "node1")
    chaos.install(inj)
    try:
        def _failing():
            states = _rule_states(a)
            stale = _staleness(a)
            return (states["chaos:sig:sum"]["health"] == "err"
                    and stale is not None and stale > 1.0), \
                (states["chaos:sig:sum"]["health"], stale)
        _poll(_failing, timeout=20, msg="eval failures + staleness rise")
        # the alert did NOT flap to inactive on evaluation errors
        states = _rule_states(a)
        assert states["ChaosData"]["state"] == "firing"
        assert "injected" in states["chaos:sig:sum"]["lastError"] \
            or states["chaos:sig:sum"]["lastError"]
        # the scheduler is alive and still ticking (failures counted,
        # loop never died)
        t1 = a.rules.snapshot()["ticks"]
        time.sleep(1.2)
        assert a.rules.snapshot()["ticks"] > t1
        fails = {tuple(sorted(lbl.items())): v
                 for lbl, v in a.rules._m_failures.series()}
        assert fails.get((("group", "chaos"),
                          ("rule", "chaos:sig:sum")), 0) >= 1
    finally:
        chaos.uninstall()

    # -- phase 3: recovery — health returns, staleness falls -----------
    def _recovered():
        states = _rule_states(a)
        stale = _staleness(a)
        return (states["chaos:sig:sum"]["health"] == "ok"
                and stale is not None and stale < 1.5), \
            (states["chaos:sig:sum"]["health"], stale)
    _poll(_recovered, timeout=20, msg="recovery")
    # the alert never left firing across the whole scenario
    walk = [(t["from"], t["to"])
            for t in a.rules.alerts_payload()["transitions"]
            if t["alert"] == "ChaosData"]
    assert walk == [("inactive", "firing")]

    # recording resumed: fresh samples keep landing after recovery
    (rec_shard,) = [s for s in
                    a.http.shards_by_dataset["__rules__"]]
    from filodb_tpu.core.index import ColumnFilter
    def _fresh_sample():
        parts = rec_shard.lookup_partitions(
            [ColumnFilter("_metric_", "eq", "chaos:sig:sum")],
            0, 1 << 62)
        if not parts:
            return False, None
        wm = rec_shard.ingest_watermark_ms
        return (wm is not None
                and wm > (time.time() - 3.0) * 1000), wm
    _poll(_fresh_sample, timeout=15, msg="post-recovery recording")
