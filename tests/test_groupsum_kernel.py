"""Fused Pallas counter group-sum kernel (pallas_kernels.counter_groupsum
via tilestore.groupsum_counters): parity vs the per-series transposed
evaluator + numpy grouping on jittered huge-counter data with resets,
plus dispatcher fallbacks. Runs in interpret mode on the CPU test mesh;
the real-TPU compile path is exercised by bench.py.

(Reference semantics: rangefn/RateFunctions.scala:23-79 extrapolated
rate; the grouping matches exec/AggrOverRangeVectors sum-by.)"""

import numpy as np
import pytest

from filodb_tpu.query import tilestore as tst

BASE = 1_600_000_000_000
DT = 10_000


def _tiles(S=100, N=288, huge=True, seed=7):
    rng = np.random.default_rng(seed)
    ts = (BASE + np.arange(N)[None, :] * DT
          + rng.uniform(-2000, 2000, (S, N)))
    vals = np.cumsum(rng.uniform(0, 5, (S, N)), axis=1)
    if huge:
        vals = 1e15 + vals
    vals[5 % S, N // 2:] *= 0.99          # counter reset
    return tst.AlignedTiles([{} for _ in range(S)], BASE, DT,
                            np.ones((S, N), bool), ts, vals)


@pytest.mark.parametrize("func", ["rate", "increase", "delta"])
def test_groupsum_matches_per_series_eval(func):
    S, G = 100, 5
    tiles = _tiles(S)
    steps = np.arange(BASE + 400_000, BASE + 2_400_000, 60_000,
                      dtype=np.int64)
    gid = np.arange(S) % G
    onehot = np.zeros((S, G), np.float32)
    onehot[np.arange(S), gid] = 1.0
    res = tst.groupsum_counters(tiles, func, steps, 300_000, onehot,
                                interpret=True)
    assert res is not None
    sums, cnts = np.asarray(res[0]), np.asarray(res[1])
    per = np.asarray(tst.evaluate_counters_t(tiles, func, steps, 300_000))
    ok = ~np.isnan(per)
    want_s = np.stack([np.where(ok[:, gid == g], per[:, gid == g], 0)
                       .sum(axis=1) for g in range(G)], 1)
    want_c = np.stack([ok[:, gid == g].sum(axis=1)
                       for g in range(G)], 1).astype(np.float32)
    np.testing.assert_array_equal(cnts, want_c)
    np.testing.assert_allclose(sums, want_s, rtol=1e-5, atol=1e-7)


def _want(tiles, func, steps, window, gid, G):
    per = np.asarray(tst.evaluate_counters_t(tiles, func, steps, window))
    ok = ~np.isnan(per)
    want_s = np.stack([np.where(ok[:, gid == g], per[:, gid == g], 0)
                       .sum(axis=1) for g in range(G)], 1)
    want_c = np.stack([ok[:, gid == g].sum(axis=1)
                       for g in range(G)], 1).astype(np.float32)
    return want_s, want_c


@pytest.mark.parametrize("phase", [3000, -3000])
def test_groupsum_phase_elided_families(phase):
    """Grid phases that clear the tile's jitter compile the CUR/ALT
    static modes (no fallback-family stream); results must still match
    the per-series evaluator exactly."""
    S, G = 64, 4
    rng = np.random.default_rng(11)
    N = 288
    ts = (BASE + np.arange(N)[None, :] * DT
          + rng.uniform(-500, 500, (S, N)))          # small jitter
    vals = np.cumsum(rng.uniform(0, 5, (S, N)), axis=1) + 1e12
    tiles = tst.AlignedTiles([{} for _ in range(S)], BASE, DT,
                             np.ones((S, N), bool), ts, vals)
    assert tiles.jitter_ms() <= 500
    steps = np.arange(BASE + 400_000 + phase, BASE + 2_400_000, 60_000,
                      dtype=np.int64)
    gid = np.arange(S) % G
    onehot = np.zeros((S, G), np.float32)
    onehot[np.arange(S), gid] = 1.0
    res = tst.groupsum_counters(tiles, "rate", steps, 300_000, onehot,
                                interpret=True)
    assert res is not None
    want_s, want_c = _want(tiles, "rate", steps, 300_000, gid, G)
    np.testing.assert_array_equal(np.asarray(res[1]), want_c)
    np.testing.assert_allclose(np.asarray(res[0]), want_s,
                               rtol=1e-5, atol=1e-7)


def test_groupsum_st1_single_stream():
    """step == dt puts every boundary family inside the one merged
    residue plane (single DMA stream per tile)."""
    S, G = 48, 3
    tiles = _tiles(S, 400)
    steps = np.arange(BASE + 400_000, BASE + 2_000_000, 10_000,
                      dtype=np.int64)
    gid = np.arange(S) % G
    onehot = np.zeros((S, G), np.float32)
    onehot[np.arange(S), gid] = 1.0
    res = tst.groupsum_counters(tiles, "increase", steps, 300_000,
                                onehot, interpret=True)
    assert res is not None
    want_s, want_c = _want(tiles, "increase", steps, 300_000, gid, G)
    np.testing.assert_array_equal(np.asarray(res[1]), want_c)
    np.testing.assert_allclose(np.asarray(res[0]), want_s,
                               rtol=1e-5, atol=1e-7)


def test_groupsum_dispatcher_fallbacks():
    tiles = _tiles(16, 288)
    onehot = np.ones((16, 1), np.float32)
    # irregular step (not a slot multiple)
    steps = np.arange(BASE + 400_000, BASE + 1_000_000, 61_000,
                      dtype=np.int64)
    assert tst.groupsum_counters(tiles, "rate", steps, 300_000,
                                 onehot, interpret=True) is None
    # grid past the tile end
    steps = np.arange(BASE + 400_000, BASE + 288 * DT + 600_000, 60_000,
                      dtype=np.int64)
    assert tst.groupsum_counters(tiles, "rate", steps, 300_000,
                                 onehot, interpret=True) is None
    # gappy tiles
    rng = np.random.default_rng(3)
    valid = rng.random((16, 288)) > 0.2
    ts = BASE + np.arange(288)[None, :] * DT + np.zeros((16, 1))
    vals = np.cumsum(np.ones((16, 288)), axis=1)
    gappy = tst.AlignedTiles([{} for _ in range(16)], BASE, DT,
                             valid, ts, vals)
    steps = np.arange(BASE + 400_000, BASE + 1_000_000, 60_000,
                      dtype=np.int64)
    assert tst.groupsum_counters(gappy, "rate", steps, 300_000,
                                 onehot, interpret=True) is None
    # window not a whole number of steps: merged kc/kl stream contract
    steps = np.arange(BASE + 400_000, BASE + 1_000_000, 60_000,
                      dtype=np.int64)
    assert tst.groupsum_counters(tiles, "rate", steps, 290_000,
                                 onehot, interpret=True) is None
    # window/step beyond the merged-stream row cap
    steps = np.arange(BASE + 900_000, BASE + 2_000_000, 10_000,
                      dtype=np.int64)
    assert tst.groupsum_counters(tiles, "rate", steps, 600_000,
                                 onehot, interpret=True) is None
    # non-finite values fall back to the exact f64 path
    bad = _tiles(16, 288)
    bad.vals = bad.vals.at[0, 5].set(np.inf) if hasattr(
        bad.vals, "at") else bad.vals
    import jax.numpy as jnp
    bad.vals = jnp.asarray(np.where(
        np.arange(288)[None, :] == 5, np.inf, np.asarray(bad.vals)))
    bad._channels.clear()
    bad._tch.clear()
    steps = np.arange(BASE + 400_000, BASE + 1_000_000, 60_000,
                      dtype=np.int64)
    assert tst.groupsum_counters(bad, "rate", steps, 300_000,
                                 onehot, interpret=True) is None


# ---------------------------------------------------------------------------
# tile widening + DMA pipeline depth (PR 14: the deferred counter_groupsum
# DMA pipelining / tile widening)
# ---------------------------------------------------------------------------

def test_gs_pipeline_chooser_frontier():
    from filodb_tpu.query import pallas_kernels as pk

    # long range, single stream: the widened 512-step tile + the
    # triple-buffered DMA pipeline both fit
    tt, nbuf = pk._gs_pipeline(6, 5, pk.GS_CUR, pk.GS_CUR, 460, 16)
    assert tt == pk._GS_TT_WIDE and nbuf == pk._GS_NBUF_MAX
    # three streams: widening would blow the scratch budget — fall to
    # the 256 tile, and the deepest pipeline that still fits
    tt3, nbuf3 = pk._gs_pipeline(6, 5, pk.GS_BOTH, pk.GS_BOTH, 460, 16)
    assert tt3 == pk._GS_TT and nbuf3 >= 2
    # short ranges never widen (nothing to amortize)
    tt1, _ = pk._gs_pipeline(6, 5, pk.GS_CUR, pk.GS_CUR, 100, 16)
    assert tt1 == pk._GS_TT
    # an impossible footprint yields None (dispatcher falls back): a
    # giant group count makes even the smallest config exceed VMEM
    assert pk._gs_pipeline(6, 5, pk.GS_BOTH, pk.GS_BOTH, 30_000,
                           4096) is None


@pytest.mark.parametrize("nsteps", [300, 520])
def test_groupsum_wide_tile_parity(nsteps):
    """Step grids past 256 ride the widened 512-step tile (and the
    deeper DMA pipeline where it fits): parity vs the per-series
    evaluator must hold through the new tiling."""
    from filodb_tpu.query import pallas_kernels as pk

    S, G = 64, 4
    # enough slots that the wide grid stays interior
    tiles = _tiles(S, N=max(512, nsteps * 6 // 1 + 96), huge=False)
    steps = (BASE + 400_000
             + np.arange(nsteps, dtype=np.int64) * 60_000)
    gid = np.arange(S) % G
    onehot = np.zeros((S, G), np.float32)
    onehot[np.arange(S), gid] = 1.0
    assert pk._gs_pipeline(6, 5, pk.GS_BOTH, pk.GS_BOTH, nsteps,
                           G) is not None
    res = tst.groupsum_counters(tiles, "rate", steps, 300_000, onehot,
                                interpret=True)
    assert res is not None
    sums, cnts = np.asarray(res[0]), np.asarray(res[1])
    assert sums.shape == (nsteps, G)
    want_s, want_c = _want(tiles, "rate", steps, 300_000, gid, G)
    np.testing.assert_array_equal(cnts, want_c)
    np.testing.assert_allclose(sums, want_s, rtol=1e-5, atol=1e-7)


def test_groupsum_widest_config_parity_interpret():
    """The (512-step tile, triple-buffered) config — reachable only in
    the phase-elided single-stream case — must run the full DMA
    pipeline correctly (interpret mode emulates the async copies)."""
    from filodb_tpu.query import pallas_kernels as pk

    S, N, G = 48, 2200, 4
    # ZERO jitter + on-slot grid phase: both fallback families elide
    # (GS_CUR/GS_CUR), leaving the single merged stream
    ts = (BASE + np.arange(N)[None, :] * DT) * np.ones((S, 1))
    vals = np.cumsum(np.random.default_rng(2).uniform(0, 5, (S, N)),
                     axis=1)
    tiles = tst.AlignedTiles([{} for _ in range(S)], BASE, DT,
                             np.ones((S, N), bool), ts, vals)
    assert tiles.jitter_ms() == 0.0
    T = 300
    steps = BASE + 400_000 + np.arange(T, dtype=np.int64) * 60_000
    assert pk._gs_pipeline(6, 5, pk.GS_CUR, pk.GS_CUR, T, G) \
        == (pk._GS_TT_WIDE, pk._GS_NBUF_MAX)
    gid = np.arange(S) % G
    onehot = np.zeros((S, G), np.float32)
    onehot[np.arange(S), gid] = 1.0
    res = tst.groupsum_counters(tiles, "rate", steps, 300_000, onehot,
                                interpret=True)
    assert res is not None
    sums, cnts = np.asarray(res[0]), np.asarray(res[1])
    want_s, want_c = _want(tiles, "rate", steps, 300_000, gid, G)
    np.testing.assert_array_equal(cnts, want_c)
    np.testing.assert_allclose(sums, want_s, rtol=1e-5, atol=1e-7)
