"""Continuous profiling + tail-sampled traces + exemplar-linked
metrics (the observability tentpole):

  * tail-based trace retention — errors, QoS sheds, and slow queries
    ALWAYS keep their trace regardless of the sampling coin, so every
    slow-query-log record links a trace id that resolves in
    ``/debug/traces``;
  * the in-process wall-clock sampling profiler — ``/debug/profile``
    windows/bursts, folded flamegraph text, thread-root attribution,
    and the registered sampler/exporter thread roots;
  * OpenMetrics exemplars on the latency histograms behind
    ``/metrics?exemplars=1``;
  * the OTLP/JSON trace exporter (bounded queue, drop-oldest,
    resilient transport);
  * and the byte-identity contract: with everything OFF (the
    defaults), API responses and ``/metrics`` are indistinguishable
    from a pre-PR server.
"""

import json
import threading
import urllib.error
import urllib.parse
import urllib.request

import pytest

from filodb_tpu.obs import trace as obt
from filodb_tpu.obs.profiler import UNATTRIBUTED, SamplingProfiler
from filodb_tpu.standalone.server import FiloServer

T0 = 1_600_000_000
QUERY = 'rate({_metric_=~"heap_usage|http_requests_total"}[5m])'


def _clear_global_exemplars():
    """The latency histograms ride the process-global registry, so
    exemplars recorded by one test's server would bleed into the next
    server's scrape — drop them for a deterministic baseline."""
    from filodb_tpu.obs.metrics import GLOBAL_REGISTRY
    with GLOBAL_REGISTRY._lock:
        hists = list(GLOBAL_REGISTRY._hists.values())
    for h in hists:
        with h._lock:
            h._exemplars = None


def _get_raw(port, path, **params):
    qs = urllib.parse.urlencode(params, doseq=True)
    url = f"http://127.0.0.1:{port}{path}" + (f"?{qs}" if qs else "")
    with urllib.request.urlopen(url, timeout=120) as r:
        return r.headers.get("Content-Type", ""), r.read()


def _get(port, path, **params):
    return json.loads(_get_raw(port, path, **params)[1])


def _query(port, **extra):
    params = dict(query=QUERY, start=T0 + 300, end=T0 + 500, step=60)
    params.update(extra)
    return _get(port, "/promql/timeseries/api/v1/query_range",
                **params)


# -- tracer tail-retention semantics (unit) ----------------------------------

def test_tail_retention_reasons_and_precedence():
    tr = obt.Tracer(enabled=True, sample_rate=0.0, slow_ms=100.0)

    # coin-fail start still hands out a PENDING trace; a boring
    # outcome at finish drops it (counted), so it never resolves
    t = tr.start()
    assert t is not None and not t.sampled
    assert tr.finish_request(t, duration_ms=1.0) is False
    assert tr.get(t.trace_id) is None
    assert tr.snapshot()["tail_dropped"] == 1

    # error beats every other signal
    t = tr.start()
    assert tr.finish_request(t, error=True, shed=True,
                             duration_ms=500.0) is True
    assert t.retain_reason == "error"
    assert tr.get(t.trace_id).to_json()["retained"] == "error"

    t = tr.start()
    assert tr.finish_request(t, shed=True, duration_ms=500.0) is True
    assert t.retain_reason == "shed"

    t = tr.start()
    assert tr.finish_request(t, duration_ms=500.0) is True
    assert t.retain_reason == "slow"        # >= slow_ms threshold

    t = tr.start()
    assert tr.finish_request(t, duration_ms=1.0, force=True) is True
    assert t.retain_reason == "forced"

    snap = tr.snapshot()
    assert snap["retained"] == {"sampled": 0, "error": 1, "shed": 1,
                                "slow": 1, "forced": 1}
    assert snap["tail_dropped"] == 1

    # coin-win keeps the boring outcome under reason "sampled"
    tr2 = obt.Tracer(enabled=True, sample_rate=1.0)
    t = tr2.start()
    assert t.sampled
    assert tr2.finish_request(t, duration_ms=1.0) is True
    assert t.retain_reason == "sampled"
    # untagged to_json (head-sampled legacy path) has no retained key
    plain = obt.Trace()
    assert "retained" not in plain.to_json()


# -- server-level: errors + slow queries always resolve ----------------------

@pytest.fixture
def tail_server():
    """Tracing on at a 1% coin with an always-trips slow threshold:
    the coin keeps (almost) nothing, the tail keeps everything that
    matters."""
    srv = FiloServer({
        "num-shards": 2, "port": 0,
        "trace-enabled": True, "trace-sample-rate": 0.01,
        "slow-query-ms": 0.001,         # everything is "slow"
        "results-cache-mb": 0,
    }).start()
    try:
        srv.seed_dev_data(n_samples=60, n_instances=3,
                          start_ms=T0 * 1000)
        yield srv
    finally:
        srv.stop()


def test_error_and_slow_queries_always_retain_traces(tail_server):
    srv = tail_server
    # 5 parse errors: every one must retain a trace under "error"
    # (the malformed query answers 4xx/5xx; either way the in-flight
    # exception/error code drives retention)
    for _ in range(5):
        with pytest.raises(urllib.error.HTTPError) as ei:
            _query(srv.port, query="rate(")
        assert ei.value.code in (400, 500)
    # 5 good-but-slow queries (threshold 0.001ms): retained as "slow"
    for _ in range(5):
        body = _query(srv.port)
        assert body["status"] == "success"
    snap = srv.http.tracer.snapshot()
    assert snap["retained"]["error"] == 5
    assert snap["retained"]["slow"] >= 5
    # every slowlog record links a trace id that RESOLVES
    slow = _get(srv.port, "/debug/slow_queries")
    assert slow["status"] == "success" and slow["data"]
    for rec in slow["data"]:
        assert rec.get("trace_id"), rec
        tr = _get(srv.port, "/debug/traces", id=rec["trace_id"])
        assert tr["status"] == "success"
        assert tr["data"]["retained"] in ("error", "shed", "slow",
                                          "forced", "sampled")
    # the retention counters ride /metrics (tracer is enabled here)
    _, text = _get_raw(srv.port, "/metrics")
    text = text.decode()
    assert 'filodb_traces_retained_total{reason="error"} 5' in text
    assert "filodb_traces_tail_dropped_total" in text


# -- byte-identity with everything off (the defaults) ------------------------

def test_defaults_keep_responses_and_metrics_byte_identical():
    """Profiler off + tracing off + exemplars unrequested (ALL
    defaults): responses stay on the canonical compact-JSON fast path
    (re-encoding the parsed body reproduces the exact bytes), carry no
    trace keys, and /metrics exposes none of the new families and no
    exemplar suffixes."""
    _clear_global_exemplars()
    # results-cache off so the second request re-executes (a cache hit
    # zeroes the scan stats — unrelated, pre-existing behavior)
    srv = FiloServer({"num-shards": 2, "port": 0,
                      "results-cache-mb": 0}).start()
    try:
        srv.seed_dev_data(n_samples=60, n_instances=3,
                          start_ms=T0 * 1000)
        qs = urllib.parse.urlencode(dict(
            query=QUERY, start=T0 + 300, end=T0 + 500, step=60))
        url = (f"http://127.0.0.1:{srv.port}/promql/timeseries/api/v1/"
               f"query_range?{qs}")
        with urllib.request.urlopen(url, timeout=120) as r:
            raw1 = r.read()
        with urllib.request.urlopen(url, timeout=120) as r:
            raw2 = r.read()
        parsed1, parsed2 = json.loads(raw1), json.loads(raw2)
        assert "trace" not in parsed1 and "trace_spans" not in parsed1
        assert raw1 == json.dumps(parsed1,
                                  separators=(",", ":")).encode()
        parsed1["stats"].pop("timings")
        parsed2["stats"].pop("timings")
        assert parsed1 == parsed2
        assert srv.http.tracer.snapshot()["started"] == 0
        assert srv.http.profiler is None
        # the exemplars=1 flag must be the ONLY way suffixes appear —
        # and with no retained traces there are none to attach anyway
        _, plain = _get_raw(srv.port, "/metrics")
        _, flagged = _get_raw(srv.port, "/metrics", exemplars=1)
        for text in (plain.decode(), flagged.decode()):
            assert " # {" not in text
            assert "filodb_profile_self_seconds_total" not in text
            assert "filodb_profiler_tick_seconds" not in text
            assert "filodb_trace_export" not in text
            assert "filodb_traces_retained_total" not in text
            assert "filodb_traces_tail_dropped_total" not in text
        # /debug/profile is a clean 404 when the profiler is off
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(srv.port, "/debug/profile")
        assert ei.value.code == 404
    finally:
        srv.stop()


# -- exemplars on the wire ---------------------------------------------------

def test_latency_exemplars_link_retained_traces():
    _clear_global_exemplars()
    srv = FiloServer({
        "num-shards": 2, "port": 0,
        "trace-enabled": True, "trace-sample-rate": 1.0,
        "results-cache-mb": 0,
    }).start()
    try:
        srv.seed_dev_data(n_samples=60, n_instances=3,
                          start_ms=T0 * 1000)
        for _ in range(3):
            assert _query(srv.port)["status"] == "success"
        _, plain = _get_raw(srv.port, "/metrics")
        _, flagged = _get_raw(srv.port, "/metrics", exemplars=1)
        plain, flagged = plain.decode(), flagged.decode()
        assert " # {" not in plain          # opt-in only
        ex_lines = [ln for ln in flagged.splitlines()
                    if ln.startswith("filodb_query_latency_seconds_"
                                     "bucket") and " # {" in ln]
        assert ex_lines, "no exemplar-bearing latency buckets"
        # every exemplar's trace id resolves in /debug/traces
        ids = set()
        for ln in ex_lines:
            suffix = ln.rsplit(" # ", 1)[1]
            assert suffix.startswith('{trace_id="')
            ids.add(suffix.split('"')[1])
        for tid in ids:
            got = _get(srv.port, "/debug/traces", id=tid)
            assert got["status"] == "success"
    finally:
        srv.stop()


# -- profiler ----------------------------------------------------------------

def test_sampler_and_exporter_are_registered_thread_roots():
    from filodb_tpu.lint.threads import THREAD_ROOTS
    names = {info["name"] for info in THREAD_ROOTS.values()}
    assert "profiler-sampler" in names
    assert "trace-exporter" in names


def test_profiler_tick_attributes_registered_roots():
    """A direct tick() with a thread parked inside a @thread_root
    function attributes that stack by FRAME match (the thread's OS
    name is a stdlib default, so name fallback can't be the one
    matching)."""
    prof = SamplingProfiler(hz=50.0)
    release = threading.Event()

    from filodb_tpu.obs.selfmon import SelfMonitor
    mon = SelfMonitor.__new__(SelfMonitor)
    mon.interval_s = 60.0
    mon._stop = release

    def park():
        # sits inside SelfMonitor._run (@thread_root "selfmon-loop")
        # waiting on the event — the sampled stack walks through it
        mon._run()

    t = threading.Thread(target=park)    # default "Thread-N" name
    t.start()
    try:
        for _ in range(3):
            prof.tick()
        folded, selfs = prof.tables()
        assert any(k.startswith("selfmon-loop;") for k in folded)
        assert any(root == "selfmon-loop" for root, _ in selfs)
        snap = prof.snapshot()
        assert snap["samples"] > 0 and snap["attributed"] > 0
        # folded text is flamegraph-shaped: "stack count" lines
        for ln in prof.folded_text().splitlines():
            stack, n = ln.rsplit(" ", 1)
            assert ";" in stack and int(n) >= 1
    finally:
        release.set()
        t.join(timeout=5)


def test_profiler_bounded_stacks_overflow_bucket():
    prof = SamplingProfiler(hz=10.0, max_stacks=64)
    with prof._lock:
        for i in range(64):
            prof._folded[f"r;m.f{i}"] = 1
    # a NEW distinct stack past the cap folds into the overflow bucket
    release = threading.Event()
    t = threading.Thread(target=release.wait)
    t.start()
    try:
        prof.tick()
    finally:
        release.set()
        t.join(timeout=5)
    folded, _ = prof.tables()
    assert len([k for k in folded if ";" + "(overflow)" in k
                or k.endswith("(overflow)")]) >= 1
    assert prof.snapshot()["dropped_stacks"] >= 1


@pytest.fixture
def prof_server():
    srv = FiloServer({"num-shards": 2, "port": 0,
                      "profiler-enabled": True,
                      "profiler-hz": 97.0}).start()
    try:
        srv.seed_dev_data(n_samples=60, n_instances=3,
                          start_ms=T0 * 1000)
        yield srv
    finally:
        srv.stop()


def test_debug_profile_window_and_folded(prof_server):
    srv = prof_server
    assert srv.http.profiler is not None and srv.http.profiler.running
    for _ in range(2):
        _query(srv.port)
    body = _get(srv.port, "/debug/profile", seconds=0.4)
    assert body["status"] == "success"
    rep = body["data"]
    assert rep["samples"] > 0
    assert rep["window_s"] == 0.4
    assert rep["top_self"] and all(
        set(e) == {"root", "func", "samples", "self_seconds"}
        for e in rep["top_self"])
    # the handler thread itself is parked in the window — attributed
    # to the http-handler root by frame walk, not thread name
    assert "http-handler" in rep["roots"]
    known = sum(n for r, n in rep["roots"].items()
                if r != UNATTRIBUTED)
    assert known > 0
    ctype, text = _get_raw(srv.port, "/debug/profile", seconds=0.2,
                           format="folded")
    assert ctype.startswith("text/plain")
    lines = text.decode().splitlines()
    assert lines and all(ln.rsplit(" ", 1)[1].isdigit()
                         for ln in lines)
    # the sampler exports its self-time gauge + tick histogram
    _, mtext = _get_raw(srv.port, "/metrics")
    mtext = mtext.decode()
    assert "filodb_profile_self_seconds_total" in mtext
    assert "filodb_profiler_tick_seconds_count" in mtext
    assert "filodb_profiler_running 1" in mtext


# -- trace exporter ----------------------------------------------------------

def _mk_trace(name="q"):
    tr = obt.Trace(node="n0")
    with obt.activate(tr):
        with obt.span(name, ds="timeseries"):
            pass
    tr.retain_reason = "slow"
    return tr


def test_exporter_ships_otlp_batches_and_drops_oldest():
    sent = []

    def transport(url, body, timeout_s):
        sent.append((url, json.loads(body)))
        return 200

    exp = obt.TraceExporter("http://sink:4318/v1/traces", batch_max=2,
                            queue_max=3, transport=transport)
    for i in range(5):                  # queue_max=3: 2 oldest dropped
        exp.enqueue(_mk_trace(f"q{i}"))
    assert exp.snapshot()["dropped"] == 2
    shipped = exp.flush()
    assert shipped == 3 and len(sent) == 2      # 2+1 in batch_max bites
    url, payload = sent[0]
    assert url == "http://sink:4318/v1/traces"
    rs = payload["resourceSpans"][0]
    attrs = {a["key"]: a["value"] for a in rs["resource"]["attributes"]}
    assert attrs["service.name"] == {"stringValue": "filodb-tpu"}
    spans = rs["scopeSpans"][0]["spans"]
    assert spans
    for sp in spans:
        assert len(sp["traceId"]) == 32 and len(sp["spanId"]) == 16
        int(sp["startTimeUnixNano"])    # stringified nanos
    snap = exp.snapshot()
    assert snap["batches"] == 2 and snap["spans_exported"] == shipped


def test_exporter_counts_failures_and_keeps_serving():
    def transport(url, body, timeout_s):
        from filodb_tpu.parallel.resilience import TransportError
        raise TransportError("sink down")

    from filodb_tpu.parallel.resilience import RetryPolicy
    exp = obt.TraceExporter("http://down-sink:4318/v1/traces",
                            transport=transport,
                            retry=RetryPolicy(max_attempts=2,
                                              base_delay_s=0.0,
                                              jitter=0.0))
    exp.enqueue(_mk_trace())
    assert exp.flush() == 0
    snap = exp.snapshot()
    assert snap["failures"] == 1 and snap["batches"] == 0
    # a later healthy flush is unaffected (fresh queue drains clean)
    exp._transport = lambda url, body, t: 200
    exp.enqueue(_mk_trace())
    assert exp.flush() == 1


def test_exporter_thread_lifecycle_with_stub_transport():
    got = threading.Event()

    def transport(url, body, timeout_s):
        got.set()
        return 200

    exp = obt.TraceExporter("http://sink:4318/v1/traces",
                            interval_s=0.05, transport=transport)
    exp.start()
    try:
        assert exp.running
        exp.enqueue(_mk_trace())
        assert got.wait(5.0)
    finally:
        exp.stop()
    assert not exp.running
    assert exp.snapshot()["spans_exported"] >= 1
