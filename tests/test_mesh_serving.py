"""Multi-chip serving correctness (the tentpole's acceptance pins).

Mesh-vs-single byte identity: with the device-resident sharded tile
path on (mesh-enabled server), /api/v1/query_range responses carry a
byte-identical DATA section for the tilestore-served shapes — the
sharded evaluator computes the same element values bit-for-bit.
Grouped / topk / histogram shapes are checked against the CPU oracle
at 1/2/4/8 virtual devices.
"""

import json
import urllib.parse
import urllib.request

import numpy as np
import pytest

import jax

from filodb_tpu.core.memstore import TimeSeriesMemStore
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetRef
from filodb_tpu.gateway.producer import (TestTimeseriesProducer,
                                         ingest_builders)
from filodb_tpu.parallel.mesh import MeshExecutor, make_mesh
from filodb_tpu.parallel.shardmapper import ShardMapper, assign_shards_evenly
from filodb_tpu.promql.parser import TimeStepParams, parse_query_range
from filodb_tpu.query.engine import QueryEngine
from filodb_tpu.query.planner import (LocalEngineExec, MeshAggregateExec,
                                      MeshTileExec, QueryPlanner)
from filodb_tpu.standalone.server import FiloServer

REF = DatasetRef("timeseries")
T0 = 1_600_000_000


def _plan(q, start=T0 + 600, end=T0 + 3000, step=60):
    return parse_query_range(q, TimeStepParams(start, step, end))


# ---------------------------------------------------------------------------
# e2e: byte-identical data sections, mesh on vs off
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def pair():
    """Two servers over identically-seeded stores: mesh-tile serving on
    vs plain single-device."""
    srvs = []
    for mesh in (False, True):
        srv = FiloServer({"num-shards": 2, "grpc-port": None, "port": 0,
                          "mesh-enabled": mesh,
                          "results-cache-mb": 0}).start()
        srv.seed_dev_data(n_samples=60, n_instances=3, start_ms=T0 * 1000)
        srvs.append(srv)
    yield srvs
    for s in srvs:
        s.stop()


def _get(port, path, **params):
    qs = urllib.parse.urlencode(params)
    url = f"http://127.0.0.1:{port}{path}?{qs}"
    with urllib.request.urlopen(url, timeout=60) as r:
        return r.read()


def _data(raw: bytes) -> str:
    return json.dumps(json.loads(raw)["data"], sort_keys=True)


QUERIES = [
    "rate(http_requests_total[5m])",
    "increase(http_requests_total[5m])",
    "delta(heap_usage[5m])",
    "sum_over_time(heap_usage[5m])",
    "avg_over_time(heap_usage[2m])",
    "sum(rate(http_requests_total[5m])) by (instance)",
    "avg(rate(http_requests_total[5m])) by (instance)",
    "count(rate(http_requests_total[5m]))",
]


@pytest.mark.parametrize("q", QUERIES)
def test_mesh_on_off_data_byte_identity(pair, q):
    plain, meshed = pair
    assert meshed.backend.mesh_eval is not None
    params = dict(query=q, start=T0 + 300, end=T0 + 500, step=60)
    a = _get(plain.port, "/promql/timeseries/api/v1/query_range",
             **params)
    b = _get(meshed.port, "/promql/timeseries/api/v1/query_range",
             **params)
    assert _data(a) == _data(b), q


def test_mesh_instant_query_matches(pair):
    """Instant queries ride the mesh too (the tilestore instant shape).
    XLA lowers the f32 division chain of the epilogue slightly
    differently between the plain jit and the shard_map program (the
    sharded result is the correctly-rounded one), so instant values
    are pinned to the CERTIFIED ulp budget rather than bytes: the
    'counter-epilogue-f32' @precision claim (graftlint v4) is
    dynamically certified to rel_ulps f32 ulps of the f64 reference by
    the ulpcert rail, and two independently-lowered programs can
    differ by at most twice that (rel_bound(cross_program=True)). The
    range-query byte-identity above is the acceptance pin."""
    from filodb_tpu.lint.numerics import precision_claim
    tol = precision_claim("counter-epilogue-f32").rel_bound(
        cross_program=True)
    assert tol <= 1e-5, "certified budget regressed past the old pin"
    plain, meshed = pair
    params = dict(query="rate(http_requests_total[5m])", time=T0 + 400)
    a = json.loads(_get(plain.port, "/promql/timeseries/api/v1/query",
                        **params))["data"]["result"]
    b = json.loads(_get(meshed.port, "/promql/timeseries/api/v1/query",
                        **params))["data"]["result"]
    assert len(a) == len(b) > 0
    for ra, rb in zip(a, b):
        assert ra["metric"] == rb["metric"]
        va, vb = float(ra["value"][1]), float(rb["value"][1])
        assert va == pytest.approx(vb, rel=tol), (
            f"mesh-on/off instant delta exceeds the certified "
            f"cross-program ulp budget {tol:.3g}")


def test_mesh_dispatches_actually_happened(pair):
    _plain, meshed = pair
    assert meshed.backend.mesh_dispatches >= 1
    snap = meshed.backend.mesh_eval.snapshot()
    assert snap["placements"] >= 1 and snap["devices"] == 8


def test_mesh_executables_attributed_per_device_count(pair):
    """devprof attribution: the sharded executables show up under the
    'mesh-tiles' site with the mesh shape in their keys, with XLA
    cost_analysis captured by the AOT build path."""
    from filodb_tpu.obs import devprof
    entries = [e for e in devprof.GLOBAL_PROFILER.snapshot()
               if e["site"] == "mesh-tiles"]
    assert entries, "no mesh-tiles executables profiled"
    assert any("flops" in e or "bytes_accessed" in e for e in entries)
    # the device count rides the key (the _mesh_key tuple tail)
    assert any("8" in e["executable"] for e in entries)


# ---------------------------------------------------------------------------
# plan lowering
# ---------------------------------------------------------------------------

def test_planner_lowers_tilestore_shapes_to_mesh_tile_exec(pair):
    _plain, meshed = pair
    planner = meshed.http.make_planner("timeseries")
    assert isinstance(planner.materialize(
        _plan("rate(http_requests_total[5m])")), MeshTileExec)
    assert isinstance(planner.materialize(
        _plan("sum_over_time(heap_usage[5m])")), MeshTileExec)
    # fused grouped shape rides the resident path too
    assert isinstance(planner.materialize(
        _plan("sum(rate(http_requests_total[5m])) by (instance)")),
        MeshTileExec)
    # min/max keep the scatter-gather collective, order statistics stay
    # local
    assert isinstance(planner.materialize(
        _plan("max(rate(http_requests_total[5m]))")), MeshAggregateExec)
    assert isinstance(planner.materialize(
        _plan("quantile_over_time(0.9, heap_usage[5m])")),
        LocalEngineExec)


def test_planner_without_mesh_eval_keeps_local(pair):
    plain, _meshed = pair
    planner = plain.http.make_planner("timeseries")
    assert isinstance(planner.materialize(
        _plan("rate(http_requests_total[5m])")), LocalEngineExec)


# ---------------------------------------------------------------------------
# scatter-gather shapes vs the CPU oracle at 1/2/4/8 devices
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def cluster():
    store = TimeSeriesMemStore(DEFAULT_SCHEMAS)
    for sh in range(8):
        store.setup(REF, sh)
    producer = TestTimeseriesProducer(DEFAULT_SCHEMAS, num_shards=8,
                                      spread=1)
    ingest_builders(store, REF, producer.counters(T0 * 1000, 360, 6))
    ingest_builders(store, REF, producer.gauges(T0 * 1000, 360, 6))
    store.flush_all(REF)
    mapper = ShardMapper(8)
    assign_shards_evenly(mapper, ["node0"])
    for s in range(8):
        mapper.activate(s)
    return store, mapper


@pytest.mark.parametrize("ndev,tp", [(1, 1), (2, 1), (4, 2), (8, 2)])
@pytest.mark.parametrize("q", [
    "topk(2, rate(http_requests_total[5m]))",
    "sum(rate(http_requests_total[5m])) by (instance)",
    "min(sum_over_time(heap_usage[2m])) by (instance)",
    "sum(rate(request_latency[5m])) by (instance)",     # histogram
])
def test_mesh_aggregate_matches_oracle_across_device_counts(
        cluster, ndev, tp, q):
    store, mapper = cluster
    shards = store.shards(REF)
    mesh = make_mesh(n_shard_groups=ndev // tp, time_parallel=tp,
                     devices=jax.devices()[:ndev])
    planner = QueryPlanner(shards, shard_mapper=mapper,
                           mesh_executor=MeshExecutor(mesh), spread=1)
    mat = planner.materialize(_plan(q))
    assert isinstance(mat, MeshAggregateExec), q
    got = mat.execute()
    want = QueryEngine(shards).execute(_plan(q))
    gmap = {tuple(sorted(k.items())): i for i, k in enumerate(got.keys)}
    assert len(gmap) == want.num_series
    for i, k in enumerate(want.keys):
        j = gmap[tuple(sorted(k.items()))]
        if want.is_hist():
            np.testing.assert_allclose(
                got.hist_values[j], want.hist_values[i], rtol=1e-8,
                equal_nan=True, err_msg=q)
        else:
            np.testing.assert_allclose(got.values[j], want.values[i],
                                       rtol=1e-8, equal_nan=True,
                                       err_msg=q)


# ---------------------------------------------------------------------------
# cross-flush donated refresh, end to end
# ---------------------------------------------------------------------------

def test_mesh_results_track_ingest_across_flush(pair):
    """New samples ingested + flushed after the placement was built
    must show up in mesh-served responses exactly as in the plain
    server's (the refresh path re-places or donates — either way, no
    stale serving)."""
    plain, meshed = pair
    for srv in (plain, meshed):
        srv.seed_dev_data(n_samples=20, n_instances=3,
                          start_ms=(T0 + 600) * 1000)
    params = dict(query="rate(http_requests_total[5m])",
                  start=T0 + 550, end=T0 + 750, step=60, cache="false")
    deadline = 30
    import time
    for _ in range(deadline):
        a = _get(plain.port, "/promql/timeseries/api/v1/query_range",
                 **params)
        b = _get(meshed.port, "/promql/timeseries/api/v1/query_range",
                 **params)
        if _data(a) == _data(b):
            break
        time.sleep(0.5)
    assert _data(a) == _data(b)
