"""Seed discovery (akka-bootstrapper analogue): DNS-SRV and Consul peer
resolution with deterministic cluster-wide ordinals, and the standalone
server's discovery-driven bootstrap."""

import pytest

from filodb_tpu.parallel.discovery import discover_peers


def test_explicit_list_passthrough():
    peers = {"node0": "http://a:1", "node1": "http://b:2"}
    assert discover_peers({"mode": "explicit-list",
                           "peers": peers}) == peers
    assert discover_peers({}) == {}


def test_dns_srv_deterministic_ordinals():
    """Every node resolves the same SRV name; sorted targets give the
    same node ids regardless of DNS answer order."""
    answers = [("host-b.local", 9090), ("host-a.local", 9090),
               ("host-c.local", 9091)]
    got = discover_peers({"mode": "dns-srv",
                          "srv-name": "_filodb._tcp.cluster.local"},
                         srv_resolver=lambda name: list(answers))
    shuffled = discover_peers({"mode": "dns-srv",
                               "srv-name": "_filodb._tcp.cluster.local"},
                              srv_resolver=lambda name: answers[::-1])
    assert got == shuffled
    assert got == {"node0": "http://host-a.local:9090",
                   "node1": "http://host-b.local:9090",
                   "node2": "http://host-c.local:9091"}


def test_consul_catalog():
    rows = [{"Address": "10.0.0.2", "ServiceAddress": "",
             "ServicePort": 8080},
            {"Address": "10.0.0.1", "ServiceAddress": "10.0.0.1",
             "ServicePort": 8080}]
    seen = {}

    def fetch(url):
        seen["url"] = url
        return rows
    got = discover_peers({"mode": "consul",
                          "url": "http://consul:8500/",
                          "service": "filodb"}, consul_fetcher=fetch)
    assert seen["url"] == "http://consul:8500/v1/catalog/service/filodb"
    assert got == {"node0": "http://10.0.0.1:8080",
                   "node1": "http://10.0.0.2:8080"}


def test_unknown_mode_rejected():
    with pytest.raises(ValueError):
        discover_peers({"mode": "zookeeper"})


def test_server_bootstrap_via_discovery(tmp_path, monkeypatch):
    """A FiloServer with no explicit peers derives ordinal + peer map
    from discovery and its advertise-url."""
    import filodb_tpu.parallel.discovery as disc_mod
    from filodb_tpu.standalone.server import FiloServer

    def fake_resolver(name):
        return [("127.0.0.1", 7101), ("127.0.0.1", 7102)]
    monkeypatch.setattr(disc_mod, "_default_srv_resolver",
                        lambda name: fake_resolver(name))
    srv = FiloServer({
        "num-shards": 4, "port": 0,
        "discovery": {"mode": "dns-srv", "srv-name": "_f._tcp.x"},
        "advertise-url": "http://127.0.0.1:7102",
    })
    srv.start()
    try:
        assert srv.node_id == "node1"
        assert srv.config["num-nodes"] == 2
        assert srv.config["peers"] == {"node0": "http://127.0.0.1:7101"}
        assert sorted(srv.owned_shards) == [2, 3]
    finally:
        srv.stop()

    # unmatched advertise-url fails loudly rather than joining wrong
    with pytest.raises(ValueError):
        FiloServer({
            "num-shards": 4, "port": 0,
            "discovery": {"mode": "dns-srv", "srv-name": "_f._tcp.x"},
            "advertise-url": "http://10.9.9.9:1",
        }).start()
