"""`@` modifier execution + per-query limits.

(@: Prometheus @-modifier pins selector evaluation to one instant and
broadcasts it across the step grid. Limits: ExecPlan.scala:46 enforces
sample/series budgets per plan; over-limit queries abort with an error
instead of OOMing the node.)
"""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from filodb_tpu.core.memstore import TimeSeriesShard
from filodb_tpu.core.record import RecordBuilder
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetRef
from filodb_tpu.http.server import FiloHttpServer
from filodb_tpu.promql.parser import (TimeStepParams, parse_query,
                                      parse_query_range)
from filodb_tpu.query.engine import QueryEngine
from filodb_tpu.query.model import QueryLimitError, QueryLimits

T0 = 1_600_000_000_000
N = 360


def _mk_shard():
    shard = TimeSeriesShard(DatasetRef("timeseries"), DEFAULT_SCHEMAS, 0)
    b = RecordBuilder(DEFAULT_SCHEMAS)
    for s in range(3):
        g = {"_metric_": "cpu", "_ws_": "demo", "_ns_": "App-0",
             "instance": f"i{s}"}
        c = {"_metric_": "reqs_total", "_ws_": "demo", "_ns_": "App-0",
             "instance": f"i{s}"}
        for t in range(N):
            ts = T0 + t * 10_000
            b.add_sample("gauge", g, ts, float(t + 100 * s))
            b.add_sample("prom-counter", c, ts, float((t + 1) * (s + 1)))
    for cont in b.containers():
        shard.ingest(cont)
    return shard


# --- @ modifier ------------------------------------------------------------

def test_at_pins_instant_selector_across_grid():
    shard = _mk_shard()
    at_s = (T0 + 1_000_000) // 1000            # t index 100
    tsp = TimeStepParams(T0 // 1000 + 600, 60, T0 // 1000 + 1200)
    plan = parse_query_range(f"cpu @ {at_s}", tsp)
    got = QueryEngine([shard]).execute(plan)
    assert got.num_series == 3
    vals = {k["instance"]: got.values[i] for i, k in enumerate(got.keys)}
    for s in range(3):
        expect = float(100 + 100 * s)          # value at t=100
        np.testing.assert_allclose(vals[f"i{s}"],
                                   np.full(got.steps.size, expect))


def test_at_matches_unpinned_instant_eval():
    """rate(...[5m] @ t) must equal rate(...[5m]) evaluated at t."""
    shard = _mk_shard()
    at_s = (T0 + 2_000_000) // 1000
    tsp = TimeStepParams(T0 // 1000 + 600, 60, T0 // 1000 + 1800)
    pinned = QueryEngine([shard]).execute(
        parse_query_range(f"rate(reqs_total[5m] @ {at_s})", tsp))
    plain = QueryEngine([shard]).execute(
        parse_query(f"rate(reqs_total[5m])", at_s))
    pv = {k["instance"]: pinned.values[i]
          for i, k in enumerate(pinned.keys)}
    for i, k in enumerate(plain.keys):
        want = plain.values[i][0]
        np.testing.assert_allclose(pv[k["instance"]],
                                   np.full(pinned.steps.size, want))


def test_at_outside_query_range_fetches_data():
    """@ far before the query range still finds the pinned data."""
    shard = _mk_shard()
    at_s = (T0 + 300_000) // 1000              # t=30, well before start
    tsp = TimeStepParams(T0 // 1000 + 3000, 60, T0 // 1000 + 3500)
    got = QueryEngine([shard]).execute(
        parse_query_range(f"cpu @ {at_s}", tsp))
    vals = {k["instance"]: got.values[i] for i, k in enumerate(got.keys)}
    for s in range(3):
        np.testing.assert_allclose(vals[f"i{s}"],
                                   np.full(got.steps.size,
                                           float(30 + 100 * s)))


def test_at_with_offset():
    """offset composes with @: data window ends at at - offset."""
    shard = _mk_shard()
    at_s = (T0 + 1_000_000) // 1000
    tsp = TimeStepParams(T0 // 1000 + 600, 60, T0 // 1000 + 1200)
    got = QueryEngine([shard]).execute(
        parse_query_range(f"cpu @ {at_s} offset 5m", tsp))
    vals = {k["instance"]: got.values[i] for i, k in enumerate(got.keys)}
    for s in range(3):
        expect = float(70 + 100 * s)           # value at t=100-30
        np.testing.assert_allclose(vals[f"i{s}"],
                                   np.full(got.steps.size, expect))


def test_at_in_aggregate():
    shard = _mk_shard()
    at_s = (T0 + 1_000_000) // 1000
    tsp = TimeStepParams(T0 // 1000 + 600, 60, T0 // 1000 + 1200)
    got = QueryEngine([shard]).execute(
        parse_query_range(f"sum(cpu @ {at_s})", tsp))
    assert got.num_series == 1
    np.testing.assert_allclose(
        got.values[0], np.full(got.steps.size, float(100 + 200 + 300)))


# --- limits ----------------------------------------------------------------

def test_series_limit_aborts_selection():
    shard = _mk_shard()
    tsp = TimeStepParams(T0 // 1000 + 600, 60, T0 // 1000 + 1200)
    plan = parse_query_range("cpu", tsp)
    eng = QueryEngine([shard], limits=QueryLimits(series_limit=2))
    with pytest.raises(QueryLimitError, match="series"):
        eng.execute(plan)


def test_sample_limit_aborts_selection():
    shard = _mk_shard()
    tsp = TimeStepParams(T0 // 1000, 60, T0 // 1000 + 3600)
    plan = parse_query_range("rate(reqs_total[5m])", tsp)
    eng = QueryEngine([shard], limits=QueryLimits(sample_limit=100))
    with pytest.raises(QueryLimitError, match="samples"):
        eng.execute(plan)


def test_under_limit_query_succeeds():
    shard = _mk_shard()
    tsp = TimeStepParams(T0 // 1000 + 600, 60, T0 // 1000 + 1200)
    plan = parse_query_range("cpu", tsp)
    eng = QueryEngine([shard], limits=QueryLimits(series_limit=10,
                                                  sample_limit=10_000))
    out = eng.execute(plan)
    assert out.num_series == 3


def test_mesh_limit_budget_is_per_query():
    """Regression: a reused planner with a mesh executor must not
    accumulate scanned samples across queries into the limit check."""
    import jax

    from filodb_tpu.parallel.mesh import MeshExecutor, make_mesh
    from filodb_tpu.query.planner import MeshAggregateExec, QueryPlanner
    if len(jax.devices()) < 2:
        pytest.skip("needs a multi-device mesh")
    shard = _mk_shard()
    tsp = TimeStepParams(T0 // 1000 + 600, 60, T0 // 1000 + 1800)
    plan = parse_query_range("sum(rate(reqs_total[5m]))", tsp)
    planner = QueryPlanner([shard], mesh_executor=MeshExecutor(make_mesh()),
                           limits=QueryLimits(sample_limit=2000))
    ex = planner.materialize(plan)
    assert isinstance(ex, MeshAggregateExec)
    for _ in range(5):      # each query scans ~1080 samples; 5x > limit
        out = planner.materialize(plan).execute()
        assert out.num_series == 1


def test_http_over_limit_returns_422():
    shard = _mk_shard()
    srv = FiloHttpServer({"timeseries": [shard]},
                         query_limits=QueryLimits(series_limit=2))
    srv.start()
    try:
        url = (f"http://127.0.0.1:{srv.port}/promql/timeseries/api/v1/"
               f"query_range?query=cpu&start={T0 // 1000 + 600}"
               f"&end={T0 // 1000 + 1200}&step=60")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(url, timeout=30)
        assert ei.value.code == 422
        body = json.loads(ei.value.read())
        assert body["errorType"] == "query_limit"
        # health and under-limit queries still fine
        ok = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/promql/timeseries/api/v1/"
            f"query_range?query=cpu{{instance=\"i0\"}}"
            f"&start={T0 // 1000 + 600}&end={T0 // 1000 + 1200}&step=60",
            timeout=30).read())
        assert ok["status"] == "success"
    finally:
        srv.stop()
