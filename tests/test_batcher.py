"""Micro-batcher parity + mechanics (serving fast path, PR 3).

The acceptance contract: batched and unbatched execution must be
bit-for-bit on the same inputs, on CPU (with the Pallas paths in
interpret mode — conftest flips the gates). Covers the aligned
tilestore families (slide/fast counters + the general evaluator), the
packed general path (series-axis stacking with per-row window
vectors), the executor-queued TPU-style path and the CPU inline path,
failure propagation, and the occupancy counters /metrics reads."""

import threading

import numpy as np
import pytest

from filodb_tpu.query.batcher import (DeviceExecutor, MicroBatcher,
                                      SplitResult)
from filodb_tpu.query.model import RangeParams, RawSeries
from filodb_tpu.query.tpu import TpuBackend

BASE = 1_600_000_000_000


def _series(n=300, S=5, regular=True, counter=True, seed=0,
            snap=True):
    rng = np.random.default_rng(seed)
    out = []
    for s in range(S):
        if regular:
            ts = BASE + np.arange(n, dtype=np.int64) * 10_000
        else:
            ts = BASE + np.cumsum(
                rng.integers(8_000, 12_000, n)).astype(np.int64)
        vals = np.cumsum(rng.random(n) * 4).astype(np.float64)
        out.append(RawSeries(
            {"i": str(s)}, ts, vals, is_counter=counter,
            snapshot_key=("ds", 0, s, 7, 0) if snap else None,
            chunk_len=n if snap else -1))
    return out


def _params(k, nsteps=16, step=60_000):
    start = BASE + 600_000 + k * step
    return RangeParams(start, step, start + (nsteps - 1) * step)


def _run_concurrent(backend, series, func, window_ms, n=8, nsteps=16):
    """Fire n same-shape queries concurrently through the backend;
    returns {k: values}."""
    outs = {}
    lock = threading.Lock()
    barrier = threading.Barrier(n)

    def worker(k):
        barrier.wait()
        g = backend.periodic_samples(series, _params(k, nsteps=nsteps),
                                     func, window_ms)
        with lock:
            outs[k] = g.values
    ths = [threading.Thread(target=worker, args=(k,)) for k in range(n)]
    for t in ths:
        t.start()
    for t in ths:
        t.join()
    return outs


@pytest.mark.parametrize("use_executor", [False, True],
                         ids=["cpu-inline", "executor-queued"])
@pytest.mark.parametrize("func,regular,window_ms", [
    ("rate", True, 300_000),           # aligned slide/fast family
    ("avg_over_time", True, 600_000),  # aligned general evaluator
    ("rate", False, 300_000),          # packed path (vs pallas single)
    ("max_over_time", False, 300_000),  # packed gather family
    ("sum_over_time", False, 300_000),  # packed prefix-sum family
])
def test_batched_equals_unbatched_bit_for_bit(func, regular, window_ms,
                                              use_executor):
    series = _series(regular=regular)
    # references: batcher disabled -> single-query kernel paths only
    ref_backend = TpuBackend(batcher=MicroBatcher(enabled=False))
    refs = {k: ref_backend.periodic_samples(
        series, _params(k), func, window_ms).values for k in range(8)}
    backend = TpuBackend(batcher=MicroBatcher(
        use_executor=use_executor, max_batch=8))
    for _ in range(3):      # repeat: batch composition varies per run
        outs = _run_concurrent(backend, series, func, window_ms)
        for k in range(8):
            assert np.array_equal(outs[k], refs[k], equal_nan=True), \
                (func, regular, use_executor, k)
    snap = backend.batcher.stats.snapshot()
    assert snap["queries"] >= 24
    assert snap["occupancy_max"] >= 1


def test_batched_queries_actually_batch():
    """With the executor-queued mode and a barrier start, most of the
    8 concurrent same-shape queries must share dispatches."""
    series = _series()
    backend = TpuBackend(batcher=MicroBatcher(use_executor=True,
                                              max_batch=8))
    for _ in range(3):
        _run_concurrent(backend, series, "rate", 300_000)
    snap = backend.batcher.stats.snapshot()
    assert snap["batched_queries"] > 0
    assert snap["occupancy_max"] >= 2
    assert snap["batches"] < snap["queries"]


def test_mixed_shapes_do_not_share_batches():
    """Queries with different step counts resolve to different batch
    keys and still match their unbatched references."""
    series = _series()
    ref_backend = TpuBackend(batcher=MicroBatcher(enabled=False))
    backend = TpuBackend(batcher=MicroBatcher(use_executor=True))
    refs, outs = {}, {}
    lock = threading.Lock()
    barrier = threading.Barrier(8)
    for k in range(8):
        nsteps = 16 if k % 2 == 0 else 31
        refs[k] = ref_backend.periodic_samples(
            series, _params(k, nsteps=nsteps), "rate", 300_000).values

    def worker(k):
        barrier.wait()
        nsteps = 16 if k % 2 == 0 else 31
        g = backend.periodic_samples(series, _params(k, nsteps=nsteps),
                                     "rate", 300_000)
        with lock:
            outs[k] = g.values
    ths = [threading.Thread(target=worker, args=(k,)) for k in range(8)]
    for t in ths:
        t.start()
    for t in ths:
        t.join()
    for k in range(8):
        assert np.array_equal(outs[k], refs[k], equal_nan=True), k


def test_shape_bucketing_is_invisible():
    """Pow2 S/T bucketing pads with sentinel rows/steps: results for
    non-pow2 series counts and step counts equal the oracle-free
    reference computed series-by-series."""
    series = _series(S=5, regular=False, counter=False)
    backend = TpuBackend(batcher=MicroBatcher(enabled=False))
    for nsteps in (3, 10, 17):
        g = backend.periodic_samples(series, _params(0, nsteps=nsteps),
                                     "sum_over_time", 300_000)
        assert g.values.shape == (5, nsteps)
        one = backend.periodic_samples(series[:1],
                                       _params(0, nsteps=nsteps),
                                       "sum_over_time", 300_000)
        assert np.array_equal(g.values[:1], one.values, equal_nan=True)
    assert backend.executable_cache_stats()["misses"] >= 1


def test_batch_failure_fails_all_members():
    b = MicroBatcher(use_executor=True)
    b.enter()
    b.enter()           # simulate a second in-flight query thread
    errs = []
    barrier = threading.Barrier(4)

    def run_batch(members):
        raise RuntimeError("kernel exploded")

    def worker(i):
        barrier.wait()
        try:
            b.submit("k", i, run_batch)
        except RuntimeError as e:
            errs.append(str(e))
    ths = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
    for t in ths:
        t.start()
    for t in ths:
        t.join()
    assert len(errs) == 4
    b.exit()
    b.exit()


def test_split_result_single_sync():
    calls = []

    class FakeDev:
        def __array__(self, dtype=None):
            calls.append(1)
            return np.arange(6, dtype=np.float64).reshape(3, 2)

    sr = SplitResult(FakeDev(), 3)
    got = [sr.get(i) for i in range(3)]
    assert len(calls) == 1          # one device->host sync per batch
    assert np.array_equal(got[1], [2.0, 3.0])


def test_executor_owns_submissions_in_order():
    ex = DeviceExecutor()
    seen = []
    done = threading.Event()
    for i in range(5):
        ex.submit(lambda i=i: seen.append(i))
    ex.submit(done.set)
    assert done.wait(5)
    assert seen == [0, 1, 2, 3, 4]
    ex.stop()
