"""IngestionStream tests: container serde round-trip, log tailing across
"process" boundaries (separate stream objects over one file), torn tails.

(Parity model: kafka/src/test SourceSinkSuite + RecordContainerSerde;
IngestionStream.scala contract.)"""

import os

import numpy as np

from filodb_tpu.core.record import RecordBuilder
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS
from filodb_tpu.ingest import (LogIngestionStream, MemoryIngestionStream,
                               decode_container, encode_container)
from filodb_tpu.memory.histogram import CustomBuckets


def _containers(n_samples=10, t0=1_000_000):
    b = RecordBuilder(DEFAULT_SCHEMAS)
    for i in range(n_samples):
        b.add_sample("gauge",
                     {"_metric_": "heap_usage", "_ws_": "demo",
                      "_ns_": "App-0", "instance": "i0"},
                     t0 + i * 1000, float(i))
        b.add_sample("prom-counter",
                     {"_metric_": "reqs_total", "_ws_": "demo",
                      "_ns_": "App-0", "instance": "i0"},
                     t0 + i * 1000, float(i * 10))
    return b.containers()


def test_container_serde_roundtrip():
    for cont in _containers():
        buf = encode_container(cont)
        got, end = decode_container(buf, 0, DEFAULT_SCHEMAS)
        assert end == len(buf)
        assert got.schema.name == cont.schema.name
        assert got.timestamps == cont.timestamps
        assert got.part_keys == cont.part_keys
        for a, b in zip(got.columns, cont.columns):
            np.testing.assert_allclose(a, b)


def test_container_serde_histogram():
    b = RecordBuilder(DEFAULT_SCHEMAS)
    scheme = CustomBuckets((1.0, 5.0, float("inf")))
    b.add_sample("prom-histogram",
                 {"_metric_": "lat", "_ws_": "demo", "_ns_": "App-0"},
                 1_000, 12.5, 3.0, (scheme, np.array([1.0, 2.0, 3.0])))
    (cont,) = b.containers()
    got, _ = decode_container(encode_container(cont), 0, DEFAULT_SCHEMAS)
    s, c = got.columns[2][0]
    assert s == scheme
    np.testing.assert_allclose(c, [1.0, 2.0, 3.0])
    assert got.columns[0][0] == 12.5 and got.columns[1][0] == 3.0


def test_memory_stream_poll():
    st = MemoryIngestionStream()
    conts = _containers()
    for c in conts:
        st.append(c)
    assert st.end_offset() == len(conts)
    batch = st.read(0)
    assert [sd.offset for sd in batch] == list(range(len(conts)))
    assert st.read(len(conts)) == []
    assert st.read(1, max_records=1)[0].offset == 1


def test_log_stream_cross_process_tail(tmp_path):
    """Producer and consumer as separate stream objects over one file —
    the gateway-process/server-process split."""
    path = str(tmp_path / "shard=0" / "stream.log")
    producer = LogIngestionStream(path, DEFAULT_SCHEMAS)
    consumer = LogIngestionStream(path, DEFAULT_SCHEMAS)
    conts = _containers()
    assert producer.append(conts[0]) == 0
    batch = consumer.read(0)
    assert len(batch) == 1 and batch[0].offset == 0
    assert batch[0].container.timestamps == conts[0].timestamps
    # consumer sees later appends without reopening
    assert producer.append(conts[1]) == 1
    batch = consumer.read(1)
    assert len(batch) == 1 and batch[0].offset == 1
    assert consumer.end_offset() == 2


def test_log_stream_replay_from_offset(tmp_path):
    path = str(tmp_path / "stream.log")
    producer = LogIngestionStream(path, DEFAULT_SCHEMAS)
    conts = _containers(n_samples=4)
    for c in conts:
        producer.append(c)
    # "restarted" consumer replays from offset 1
    consumer = LogIngestionStream(path, DEFAULT_SCHEMAS)
    batch = consumer.read(1, max_records=100)
    assert [sd.offset for sd in batch] == [1]
    got, want = batch[0].container, conts[1]
    assert got.timestamps == want.timestamps


def test_log_stream_torn_tail_not_consumed(tmp_path):
    """A torn (mid-write) tail record is invisible to readers and
    truncated by the next writer takeover."""
    path = str(tmp_path / "stream.log")
    producer = LogIngestionStream(path, DEFAULT_SCHEMAS)
    conts = _containers(n_samples=3)
    producer.append(conts[0])
    producer.append(conts[1])
    producer.close()
    with open(path, "ab") as f:       # crash mid-append
        f.write(encode_container(conts[0])[:11])
    consumer = LogIngestionStream(path, DEFAULT_SCHEMAS)
    assert consumer.end_offset() == 2          # torn tail ignored
    # new writer truncates the torn tail, then appends cleanly
    producer2 = LogIngestionStream(path, DEFAULT_SCHEMAS)
    assert producer2.append(conts[1]) == 2
    consumer2 = LogIngestionStream(path, DEFAULT_SCHEMAS)
    assert consumer2.end_offset() == 3
    assert [sd.offset for sd in consumer2.read(0, 100)] == [0, 1, 2]


def test_group_commit_coalesces_fsyncs(tmp_path, monkeypatch):
    """Group-commit fsync (ROADMAP follow-up): with the window open,
    consecutive appends share one fsync instead of paying one each; the
    time/size bounds and close() bound the durability window; the
    fsync histogram counts real fsyncs only."""
    from filodb_tpu.obs import metrics as obm
    obm.GLOBAL_REGISTRY.reset()
    calls = []
    real_fsync = os.fsync

    def counting_fsync(fd):
        calls.append(fd)
        return real_fsync(fd)

    monkeypatch.setattr(os, "fsync", counting_fsync)
    path = str(tmp_path / "gc" / "stream.log")
    st = LogIngestionStream(path, DEFAULT_SCHEMAS,
                            group_commit_s=60.0)   # window never closes
    conts = _containers(n_samples=20)
    for c in conts:
        st.append(c)
    # first append syncs (stale last_sync_t), later ones coalesce
    assert st.appends == len(conts)
    assert st.fsyncs < st.appends
    assert len(calls) == st.fsyncs
    # reader sees every record regardless of sync state
    assert st.end_offset() == len(conts)
    before = st.fsyncs
    st.sync()                                      # checkpoint barrier
    assert st.fsyncs == before + 1
    st.sync()                                      # nothing unsynced
    assert st.fsyncs == before + 1
    st.append(conts[0])
    st.close()                                     # tail forced out
    assert st.fsyncs == before + 2
    # histogram counted exactly the real fsyncs
    h = obm.GLOBAL_REGISTRY.get("filodb_ingest_fsync_seconds")
    assert h is not None and h.snapshot()["count"] == st.fsyncs
    ha = obm.GLOBAL_REGISTRY.get("filodb_ingest_append_seconds")
    assert ha is not None and ha.snapshot()["count"] == st.appends
    obm.GLOBAL_REGISTRY.reset()


def test_group_commit_size_bound_and_strict_default(tmp_path,
                                                    monkeypatch):
    calls = []
    real_fsync = os.fsync
    monkeypatch.setattr(os, "fsync",
                        lambda fd: (calls.append(fd),
                                    real_fsync(fd))[1])
    # strict default: every append fsyncs (pre-PR behavior)
    st = LogIngestionStream(str(tmp_path / "strict" / "s.log"),
                            DEFAULT_SCHEMAS)
    conts = _containers(n_samples=5)
    for c in conts:
        st.append(c)
    assert st.fsyncs == len(conts)
    st.close()
    # size bound: a tiny byte budget forces a sync despite a huge window
    calls.clear()
    st2 = LogIngestionStream(str(tmp_path / "sz" / "s.log"),
                             DEFAULT_SCHEMAS, group_commit_s=60.0,
                             group_commit_bytes=1)
    for c in conts:
        st2.append(c)
    assert st2.fsyncs == len(conts)      # every append trips the bound
    st2.close()
