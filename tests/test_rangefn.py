"""Range function numeric parity tests.

Golden cases ported from the reference
(query/src/test/scala/filodb/query/exec/rangefn/RateFunctionsSpec.scala,
AggrOverTimeFunctionsSpec.scala) — the primary numeric oracle for the TPU
kernels (SURVEY.md §4)."""

import numpy as np
import pytest

from filodb_tpu.query import rangefn as rf

COUNTER_SAMPLES = [
    (8072000, 4419.00), (8082100, 4511.00), (8092196, 4614.00),
    (8102215, 4724.00), (8112223, 4909.00), (8122388, 4948.00),
    (8132570, 5000.00), (8142822, 5095.00), (8152858, 5102.00),
    (8162999, 5201.00),
]

GAUGE_SAMPLES = [
    (8072000, 7419.00), (8082100, 5511.00), (8092196, 4614.00),
    (8102215, 3724.00), (8112223, 4909.00), (8122388, 4948.00),
    (8132570, 5000.00), (8142822, 3095.00), (8152858, 5102.00),
    (8162999, 8201.00),
]


def _arrays(samples):
    ts = np.array([t for t, _ in samples], dtype=np.int64)
    vs = np.array([v for _, v in samples], dtype=np.float64)
    return ts, vs


def _eval_single_window(func, samples, wstart, wend, **kw):
    ts, vs = _arrays(samples)
    out = rf.RANGE_FUNCTIONS[func](
        ts, vs, np.array([wstart], dtype=np.int64),
        np.array([wend], dtype=np.int64), **kw)
    return out[0]


def test_rate_start_end_outside_window():
    # RateFunctionsSpec "rate should work when start and end are outside window"
    start_ts, end_ts = 8071950, 8163070
    ts, vs = _arrays(COUNTER_SAMPLES)
    expected = (vs[-1] - vs[0]) / (ts[-1] - ts[0]) * 1000
    got = _eval_single_window("rate", COUNTER_SAMPLES, start_ts, end_ts)
    assert got == pytest.approx(expected, abs=1e-7)


def test_rate_with_reset_at_chunk_boundary():
    # RateFunctionsSpec "should compute rate correctly when reset occurs at
    # chunk boundaries" — chunk boundaries don't exist in the dense
    # formulation; the correction math must still match.
    chunk2 = [(8173000, 325.00), (8183000, 511.00), (8193000, 614.00),
              (8203000, 724.00), (8213000, 909.00)]
    samples = COUNTER_SAMPLES + chunk2
    start_ts, end_ts = 8071950, 8213070
    correction = COUNTER_SAMPLES[-1][1]   # 5201
    expected = (chunk2[-1][1] + correction - COUNTER_SAMPLES[0][1]) / \
        (chunk2[-1][0] - COUNTER_SAMPLES[0][0]) * 1000
    got = _eval_single_window("rate", samples, start_ts, end_ts)
    assert got == pytest.approx(expected, abs=1e-7)


def test_rate_with_drops_in_middle():
    # RateFunctionsSpec "should compute rate correctly when drops occur in
    # middle of chunks"
    reset1 = [(8072000, 4419.0), (8082100, 4511.0), (8092196, 4614.0),
              (8102215, 4724.0), (8112223, 4909.0), (8122388, 948.0),
              (8132570, 1000.0), (8142822, 1095.0), (8152858, 1102.0),
              (8162999, 1201.0)]
    reset2 = [(8173000, 1325.0), (8183000, 1511.0), (8193000, 214.0),
              (8203000, 324.0), (8213000, 409.0)]
    samples = reset1 + reset2
    start_ts, end_ts = 8071950, 8213070
    corrections = 4909.0 + 1511.0
    expected = (reset2[-1][1] + corrections - reset1[0][1]) / \
        (reset2[-1][0] - reset1[0][0]) * 1000
    got = _eval_single_window("rate", samples, start_ts, end_ts)
    assert got == pytest.approx(expected, abs=1e-7)


def test_increase_matches_rate_times_duration_shape():
    start_ts, end_ts = 8071950, 8163070
    ts, vs = _arrays(COUNTER_SAMPLES)
    expected_rate = (vs[-1] - vs[0]) / (ts[-1] - ts[0]) * 1000
    got_inc = _eval_single_window("increase", COUNTER_SAMPLES, start_ts, end_ts)
    assert got_inc == pytest.approx(
        expected_rate * (end_ts - start_ts) / 1000, abs=1e-6)


def test_delta_on_gauge():
    # delta is not counter-corrected
    start_ts, end_ts = 8071950, 8163070
    ts, vs = _arrays(GAUGE_SAMPLES)
    expected = (vs[-1] - vs[0]) / (ts[-1] - ts[0]) * 1000 * \
        (end_ts - start_ts) / 1000
    got = _eval_single_window("delta", GAUGE_SAMPLES, start_ts, end_ts)
    assert got == pytest.approx(expected, abs=1e-6)


def test_rate_insufficient_samples_nan():
    got = _eval_single_window("rate", COUNTER_SAMPLES[:1], 8071950, 8163070)
    assert np.isnan(got)
    got = _eval_single_window("rate", [], 8071950, 8163070)
    assert np.isnan(got)


def test_sum_avg_count_over_time():
    ts, vs = _arrays(GAUGE_SAMPLES)
    s = _eval_single_window("sum_over_time", GAUGE_SAMPLES, 8071950, 8163070)
    assert s == pytest.approx(vs.sum())
    a = _eval_single_window("avg_over_time", GAUGE_SAMPLES, 8071950, 8163070)
    assert a == pytest.approx(vs.mean())
    c = _eval_single_window("count_over_time", GAUGE_SAMPLES, 8071950, 8163070)
    assert c == 10


def test_min_max_over_time():
    assert _eval_single_window(
        "min_over_time", GAUGE_SAMPLES, 8071950, 8163070) == 3095.0
    assert _eval_single_window(
        "max_over_time", GAUGE_SAMPLES, 8071950, 8163070) == 8201.0


def test_stddev_stdvar_over_time():
    ts, vs = _arrays(GAUGE_SAMPLES)
    var = np.mean((vs - vs.mean()) ** 2)
    assert _eval_single_window(
        "stdvar_over_time", GAUGE_SAMPLES, 8071950, 8163070) == \
        pytest.approx(var)
    assert _eval_single_window(
        "stddev_over_time", GAUGE_SAMPLES, 8071950, 8163070) == \
        pytest.approx(np.sqrt(var))


def test_windows_slide_correctly():
    # multi-step evaluation: each step only sees its own window
    out = rf.evaluate("sum_over_time",
                      *_arrays(GAUGE_SAMPLES),
                      start_ms=8102215, step_ms=10000, end_ms=8162999,
                      window_ms=20000)
    # window [8082215, 8102215]: samples at 8092196, 8102215
    assert out[0] == pytest.approx(4614.0 + 3724.0)


def test_changes_and_resets():
    samples = [(1000, 1.0), (2000, 1.0), (3000, 2.0), (4000, 1.0),
               (5000, 1.0), (6000, 3.0)]
    assert _eval_single_window("changes", samples, 500, 6500) == 3
    assert _eval_single_window("resets", samples, 500, 6500) == 1


def test_irate_uses_last_two_samples():
    ts, vs = _arrays(COUNTER_SAMPLES)
    expected = (vs[-1] - vs[-2]) / (ts[-1] - ts[-2]) * 1000
    assert _eval_single_window("irate", COUNTER_SAMPLES, 8071950, 8163070) == \
        pytest.approx(expected)


def test_deriv_linear_data_exact():
    # perfectly linear data -> deriv == slope
    samples = [(i * 1000, 5.0 * i + 2) for i in range(20)]
    got = _eval_single_window("deriv", samples, 0, 19000)
    assert got == pytest.approx(5.0)  # 5 per second


def test_predict_linear():
    samples = [(i * 1000, 5.0 * i + 2) for i in range(20)]
    # predict 10s past window end: value = 5*(19+10)+2
    got = _eval_single_window("predict_linear", samples, 0, 19000, scalar=10.0)
    assert got == pytest.approx(5.0 * 29 + 2)


def test_quantile_over_time():
    samples = [(i * 1000, float(i)) for i in range(11)]
    got = _eval_single_window("quantile_over_time", samples, 0, 10000,
                              scalar=0.5)
    assert got == pytest.approx(5.0)


def test_holt_winters_constant_series():
    samples = [(i * 1000, 42.0) for i in range(10)]
    got = _eval_single_window("holt_winters", samples, 0, 9000,
                              scalar=0.5, scalar2=0.5)
    assert got == pytest.approx(42.0)


def test_absent_present_over_time():
    assert _eval_single_window("absent_over_time", [], 0, 10000) == 1.0
    samples = [(5000, 1.0)]
    assert np.isnan(_eval_single_window("absent_over_time", samples, 0, 10000))
    assert _eval_single_window("present_over_time", samples, 0, 10000) == 1.0


def test_last_sample_lookback_staleness():
    samples = [(1000, 1.0), (2000, 2.0)]
    ts, vs = _arrays(samples)
    # step at 6000 with 5m lookback window should see sample at 2000
    out = rf.RANGE_FUNCTIONS["last_sample"](
        ts, vs, np.array([2000 - 300000]), np.array([6000]))
    assert out[0] == 2.0
    # NaN sample marks staleness — excluded from value but makes step stale
    samples2 = [(1000, 1.0), (2000, np.nan)]
    ts2, vs2 = _arrays(samples2)
    out2 = rf.RANGE_FUNCTIONS["last_sample"](
        ts2, vs2, np.array([2000 - 300000]), np.array([6000]))
    assert np.isnan(out2[0])


def test_nan_samples_dropped_in_aggregates():
    samples = [(1000, 1.0), (2000, np.nan), (3000, 3.0)]
    assert _eval_single_window("sum_over_time", samples, 0, 3500) == 4.0
    assert _eval_single_window("count_over_time", samples, 0, 3500) == 2


def test_rate_over_delta():
    samples = [(i * 1000, 10.0) for i in range(1, 11)]  # delta counter incr 10
    got = _eval_single_window("rate_over_delta", samples, 0, 10000)
    assert got == pytest.approx(100.0 / 10.0)  # 100 total over 10s


def test_z_score():
    samples = [(i * 1000, float(i)) for i in range(10)]
    vs = np.arange(10.0)
    expected = (9.0 - vs.mean()) / vs.std()
    assert _eval_single_window("z_score", samples, 0, 9000) == \
        pytest.approx(expected)
