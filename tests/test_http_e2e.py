"""End-to-end HTTP slice: FiloServer startup -> seed dev data -> Prometheus
API over a real socket (the reference dev loop: filodb-dev-start.sh +
dev-gateway.sh + PrometheusApiRoute; parity model http/src/test
PrometheusApiRouteSpec)."""

import json
import urllib.parse
import urllib.request

import numpy as np
import pytest

from filodb_tpu.standalone.server import FiloServer

T0 = 1_600_000_000


@pytest.fixture(scope="module")
def server():
    srv = FiloServer({"num-shards": 4, "port": 0}).start()
    srv.seed_dev_data(n_samples=360, n_instances=4, start_ms=T0 * 1000)
    yield srv
    srv.stop()


def _get(server, path, **params):
    qs = urllib.parse.urlencode(params, doseq=True)
    url = f"http://127.0.0.1:{server.port}{path}"
    if qs:
        url += "?" + qs
    with urllib.request.urlopen(url, timeout=30) as r:
        return r.status, json.loads(r.read())


def test_health(server):
    status, body = _get(server, "/__health")
    assert status == 200 and body["status"] == "healthy"


def test_cluster_status(server):
    status, body = _get(server, "/api/v1/cluster/timeseries/status")
    assert status == 200
    assert len(body["data"]) == 4
    assert all(s["status"] == "active" for s in body["data"])


def test_query_range_rate(server):
    end = T0 + 3600
    status, body = _get(
        server, "/promql/timeseries/api/v1/query_range",
        query='rate(http_requests_total{job="test"}[5m])',
        start=T0 + 600, end=end, step=60)
    assert status == 200 and body["status"] == "success"
    data = body["data"]
    assert data["resultType"] == "matrix"
    assert len(data["result"]) == 4            # 4 instances
    # counter increases by (inst+1)*10 per 10s -> rate = (inst+1) * 1.0
    by_inst = {r["metric"]["instance"]: r for r in data["result"]}
    for inst in range(4):
        r = by_inst[f"instance-{inst}"]
        assert r["metric"]["__name__"] == "http_requests_total"
        vals = np.array([float(v) for _, v in r["values"]])
        np.testing.assert_allclose(vals, (inst + 1) * 1.0, rtol=1e-6)


def test_query_range_aggregation(server):
    status, body = _get(
        server, "/promql/timeseries/api/v1/query_range",
        query='sum(rate(http_requests_total[5m]))',
        start=T0 + 600, end=T0 + 1200, step=60)
    assert status == 200
    res = body["data"]["result"]
    assert len(res) == 1
    vals = np.array([float(v) for _, v in res[0]["values"]])
    np.testing.assert_allclose(vals, 10.0, rtol=1e-6)   # 1+2+3+4


def test_instant_query_vector(server):
    status, body = _get(
        server, "/promql/timeseries/api/v1/query",
        query="heap_usage", time=T0 + 1800)
    assert status == 200
    data = body["data"]
    assert data["resultType"] == "vector"
    assert len(data["result"]) == 4
    for r in data["result"]:
        t, v = r["value"]
        assert t == T0 + 1800
        assert 5.0 < float(v) < 25.0


def test_instant_query_scalar(server):
    status, body = _get(server, "/promql/timeseries/api/v1/query",
                        query="42 + 1", time=T0)
    assert status == 200
    assert body["data"]["resultType"] == "scalar"
    assert float(body["data"]["result"][1]) == 43.0


def test_labels_and_label_values(server):
    status, body = _get(server, "/promql/timeseries/api/v1/labels",
                        start=T0, end=T0 + 3600)
    assert status == 200
    assert {"job", "instance", "host", "_ws_", "_ns_"} <= set(body["data"])
    status, body = _get(server,
                        "/promql/timeseries/api/v1/label/instance/values",
                        start=T0, end=T0 + 3600)
    assert body["data"] == [f"instance-{i}" for i in range(4)]


def test_series_endpoint(server):
    status, body = _get(server, "/promql/timeseries/api/v1/series",
                        **{"match[]": 'heap_usage{instance="instance-1"}',
                           "start": T0, "end": T0 + 3600})
    assert status == 200
    assert len(body["data"]) == 1
    assert body["data"][0]["__name__"] == "heap_usage"


def test_histogram_quantile_over_http(server):
    status, body = _get(
        server, "/promql/timeseries/api/v1/query_range",
        query='histogram_quantile(0.9, '
              'sum(rate(http_request_latency[5m])) by (le))',
        start=T0 + 600, end=T0 + 1200, step=60)
    assert status == 200
    res = body["data"]["result"]
    assert len(res) >= 1
    vals = [float(v) for _, v in res[0]["values"]]
    assert all(0.0 < x <= 64.0 for x in vals)


def test_bad_query_returns_400(server):
    status = None
    try:
        _get(server, "/promql/timeseries/api/v1/query_range",
             query="rate(", start=T0, end=T0 + 60, step=60)
    except urllib.error.HTTPError as e:
        status = e.code
        body = json.loads(e.read())
        assert body["status"] == "error"
    assert status in (400, 500)


def test_unknown_dataset_400(server):
    try:
        _get(server, "/promql/nope/api/v1/query", query="x", time=T0)
        assert False
    except urllib.error.HTTPError as e:
        assert e.code == 400


def test_unknown_route_404(server):
    try:
        _get(server, "/promql/timeseries/api/v1/bogus")
        assert False
    except urllib.error.HTTPError as e:
        assert e.code == 404
