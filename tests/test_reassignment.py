"""Elastic recovery e2e: SIGKILL a node with NO buddy configured and
watch survivors adopt its shards — bootstrap from the shared ColumnStore,
replay the shared stream logs from the checkpoint watermark, then serve
queries AND new ingest for the dead node's shards.

(Reference: ShardManager.scala:28 assignShardsToNodes,
ShardAssignmentStrategy.scala:188 round-robin re-add,
IngestionActor.scala:297 recovery protocol. The shared data-dir /
stream-dir stands in for Cassandra + Kafka, which outlive any node.)
"""

import json
import os
import pathlib
import select
import signal
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.parse
import urllib.request

REPO = pathlib.Path(__file__).resolve().parent.parent
T0 = 1_600_000_000
N_SERIES = 16           # spread across all shards
N_SAMPLES = 40


def _grpc_rpcs(port) -> int:
    """grpc_rpcs_served_total from a node's /metrics exposition."""
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
        txt = r.read().decode()
    for line in txt.splitlines():
        if line.startswith("#"):
            continue                 # # HELP / # TYPE comment lines
        if "grpc_rpcs_served_total" in line:
            return int(float(line.split()[-1]))
    return 0


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn(cfg, tmp_path, name):
    cfg_path = tmp_path / f"{name}.json"
    cfg_path.write_text(json.dumps(cfg))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.Popen(
        [sys.executable, "-m", "filodb_tpu.standalone.server",
         "--config", str(cfg_path)],
        cwd=str(REPO), env=env, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL)


def _wait_ready(proc, timeout=120.0):
    deadline = time.monotonic() + timeout
    buf = b""
    while time.monotonic() < deadline:
        r, _, _ = select.select([proc.stdout], [], [], 1.0)
        if not r:
            if proc.poll() is not None:
                raise RuntimeError("server died during startup")
            continue
        ch = proc.stdout.read1(4096)
        if not ch:
            raise RuntimeError("stdout closed")
        buf += ch
        if b"\n" in buf:
            return json.loads(buf.split(b"\n", 1)[0])
    raise TimeoutError("no startup line")


def _get(port, path, **params):
    qs = urllib.parse.urlencode(params, doseq=True)
    url = f"http://127.0.0.1:{port}{path}"
    if qs:
        url += "?" + qs
    with urllib.request.urlopen(url, timeout=120) as r:
        return json.loads(r.read())


def _poll(fn, timeout=120.0, interval=0.3):
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        try:
            ok, last = fn()
            if ok:
                return last
        except (urllib.error.URLError, ConnectionError, OSError):
            pass
        time.sleep(interval)
    raise TimeoutError(f"poll timed out; last={last!r}")


def _send_lines(port, lines):
    with socket.create_connection(("127.0.0.1", port), timeout=10) as s:
        s.sendall(("\n".join(lines) + "\n").encode())


def _lines(first_t, last_t):
    out = []
    for t in range(first_t, last_t):
        ts_ns = (T0 + t * 10) * 1_000_000_000
        for s in range(N_SERIES):
            out.append(f"reqs,instance=i{s} counter={(t + 1) * (s + 1)}"
                       f" {ts_ns}")
    return out


def _instances_at(port, t_idx):
    body = _get(port, "/promql/timeseries/api/v1/query", query="reqs",
                time=T0 + (t_idx - 1) * 10)
    return {r["metric"]["instance"]: float(r["value"][1])
            for r in body["data"]["result"]}


def _shard_status(port):
    body = _get(port, "/api/v1/cluster/timeseries/status")
    return {s["shard"]: (s["status"], s.get("address") or s.get("node"))
            for s in body["data"]}


def test_sigkill_node_without_buddy_recovers_full_coverage(tmp_path):
    ports = [_free_port() for _ in range(3)]
    peers = {f"node{i}": f"http://127.0.0.1:{p}"
             for i, p in enumerate(ports)}
    data_dir = str(tmp_path / "data")
    stream_dir = str(tmp_path / "streams")
    base = {
        "num-shards": 8, "num-nodes": 3, "peers": peers,
        "data-dir": data_dir, "stream-dir": stream_dir,
        "flush-interval-s": 0.5,
        "query-sample-limit": 0, "query-series-limit": 0,
        "failure-detect-interval-s": 0.25,
        "failure-detect-threshold": 3,
        "shard-reassign-grace-s": 1.0,
    }
    gw_port = _free_port()
    procs = {}
    try:
        procs[0] = _spawn({**base, "node-ordinal": 0, "port": ports[0],
                           "gateway-port": gw_port}, tmp_path, "node0")
        procs[1] = _spawn({**base, "node-ordinal": 1, "port": ports[1]},
                          tmp_path, "node1")
        procs[2] = _spawn({**base, "node-ordinal": 2, "port": ports[2]},
                          tmp_path, "node2")
        for p in procs.values():
            _wait_ready(p)
        _poll(lambda: (all(st == "active" for st, _ in
                           _shard_status(ports[0]).values()), None))

        _send_lines(gw_port, _lines(0, N_SAMPLES))
        want = {f"i{s}": float(N_SAMPLES * (s + 1))
                for s in range(N_SERIES)}
        _poll(lambda: ((lambda got: (got == want, got))(
            _instances_at(ports[0], N_SAMPLES))))
        time.sleep(1.5)          # several flush rotations -> checkpoints

        # which shards did node1 own?
        node1_shards = sorted(sh for sh, (_, node) in
                              _shard_status(ports[0]).items()
                              if node == "node1")
        assert node1_shards, "node1 must own some shards"

        os.kill(procs[1].pid, signal.SIGKILL)
        procs[1].wait(timeout=30)

        # survivors adopt: ALL shards active again, none owned by node1,
        # and no buddy is configured anywhere
        def _recovered():
            st = _shard_status(ports[0])
            ok = (all(s == "active" for s, _ in st.values())
                  and all(node != "node1" for _, node in st.values()))
            return ok, st
        status = _poll(_recovered, timeout=120.0)
        adopters = {status[sh][1] for sh in node1_shards}
        assert adopters <= {"node0", "node2"}, status

        # full pre-kill coverage from BOTH survivors (flushed data via
        # ColumnStore bootstrap, unflushed tail via stream replay)
        for port in (ports[0], ports[2]):
            _poll(lambda p=port: ((lambda got: (got == want, got))(
                _instances_at(p, N_SAMPLES))))

        # ingest continues into the adopted shards through the gateway
        _send_lines(gw_port, _lines(N_SAMPLES, N_SAMPLES + 10))
        want2 = {f"i{s}": float((N_SAMPLES + 10) * (s + 1))
                 for s in range(N_SERIES)}
        _poll(lambda: ((lambda got: (got == want2, got))(
            _instances_at(ports[0], N_SAMPLES + 10))))

        # the whole e2e rode the default binary data plane: every
        # survivor served gRPC leaf fetches (discovered via health-body
        # gossip, no configured addresses)
        def _grpc_both():
            _instances_at(ports[0], N_SAMPLES + 10)
            _instances_at(ports[2], N_SAMPLES + 10)
            served = [_grpc_rpcs(ports[0]), _grpc_rpcs(ports[2])]
            return all(s > 0 for s in served), served
        _poll(_grpc_both, timeout=30)
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in procs.values():
            if p.poll() is None:
                try:
                    p.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait(timeout=30)
