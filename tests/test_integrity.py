"""Storage-integrity rail tests: frame codec, scan/quarantine on every
durable file kind, mixed-version files, read-time verification, the
ENOSPC ingest-read-only degradation, the quarantine knob, and the
result cache's refusal to cache over quarantined shards.

The acceptance bar (ISSUE 16): a single flipped bit in ANY durable file
is detected, quarantined, surfaced via metrics + the event ring — and
never reaches a query result or silently truncates replay."""

import errno
import os

import pytest

from filodb_tpu.core.memstore import TimeSeriesShard
from filodb_tpu.core.record import RecordBuilder
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetRef
from filodb_tpu.gateway.server import GatewayServer
from filodb_tpu.ingest import IngestionDriver, LogIngestionStream
from filodb_tpu.ingest import health as ingest_health
from filodb_tpu.ingest.stream import encode_container, legacy_wal_probe
from filodb_tpu.obs import events as obs_events
from filodb_tpu.obs import metrics as obs_metrics
from filodb_tpu.parallel.shardmapper import ShardMapper, ShardStatus
from filodb_tpu.promql.parser import TimeStepParams, parse_query_range
from filodb_tpu.query.engine import QueryEngine
from filodb_tpu.store import FlatFileColumnStore, integrity
from filodb_tpu.testing import chaos

REF = DatasetRef("timeseries")
T0 = 1_600_000_000


@pytest.fixture(autouse=True)
def _clean_globals():
    obs_metrics.GLOBAL_REGISTRY.reset()
    obs_events.GLOBAL_EVENTS.clear()
    ingest_health.GLOBAL.reset()
    yield
    obs_metrics.GLOBAL_REGISTRY.reset()
    obs_events.GLOBAL_EVENTS.clear()
    ingest_health.GLOBAL.reset()


def _corruption_total(**want) -> float:
    fam = obs_metrics.GLOBAL_REGISTRY.counter(
        "filodb_storage_corruption_total", "")
    return sum(v for labels, v in fam.series()
               if all(labels.get(k) == v2 for k, v2 in want.items()))


def _batch(i, n_rows=4):
    b = RecordBuilder(DEFAULT_SCHEMAS)
    for r in range(n_rows):
        b.add_sample("gauge",
                     {"_metric_": "heap_usage", "_ws_": "demo",
                      "_ns_": "App-0", "instance": f"i{i}"},
                     (T0 + i * 100 + r) * 1000, float(i * 1000 + r))
    return b.containers()


def _flip_byte(path, pos, mask=0x01):
    with open(path, "r+b") as f:
        f.seek(pos)
        b = f.read(1)
        f.seek(pos)
        f.write(bytes([b[0] ^ mask]))


# -- frame codec -----------------------------------------------------------

def test_frame_roundtrip():
    for payload in (b"", b"x", b"hello world" * 100, bytes(range(256))):
        frame = integrity.encode_frame(payload)
        got, nxt = integrity.decode_frame(frame)
        assert got == payload
        assert nxt == len(frame)


def test_frame_every_byte_position_flip_detected():
    """Flipping ANY single bit of a frame must not verify (header
    flips raise or fail the sniff; payload flips fail the CRC)."""
    payload = b"the quick brown fox"
    frame = bytearray(integrity.encode_frame(payload))
    for pos in range(len(frame)):
        bad = bytes(frame[:pos]) + bytes([frame[pos] ^ 0x10]) \
            + bytes(frame[pos + 1:])
        try:
            got, _ = integrity.decode_frame(bad)
        except integrity.FrameError:
            continue
        # decode may return None (torn: a length flip pushed the
        # declared end past the buffer) but NEVER the wrong payload
        assert got is None or got != payload or pos >= len(frame), \
            f"flip at byte {pos} verified silently"
        assert got != payload


def test_frame_torn_buffer_returns_none():
    frame = integrity.encode_frame(b"abcdef")
    for cut in range(1, len(frame)):
        got, off = integrity.decode_frame(frame[:cut])
        assert got is None and off == 0


# -- scanner ---------------------------------------------------------------

def test_scan_mixed_framed_and_legacy_records():
    legacy = b"".join(encode_container(c) for c in _batch(0))
    framed = b"".join(integrity.encode_frame(encode_container(c))
                      for c in _batch(1))
    res = integrity.scan_buffer(legacy + framed, probe=legacy_wal_probe)
    assert res.tail_state == "clean"
    assert not res.corrupt
    kinds = [r.framed for r in res.records]
    assert False in kinds and True in kinds


def test_scan_resyncs_past_garbage_between_frames():
    f1 = integrity.encode_frame(b"payload-one")
    f2 = integrity.encode_frame(b"payload-two")
    buf = f1 + b"\x00\xde\xad\xbe\xef\x00\x17" + f2
    res = integrity.scan_buffer(buf, probe=lambda b, o: 0)
    assert len(res.records) == 2
    assert len(res.corrupt) == 1
    assert res.corrupt[0].offset == len(f1)
    assert res.tail_state == "clean"


# -- WAL: scan-time detection ---------------------------------------------

def test_wal_bitflip_quarantined_replay_continues(tmp_path):
    """Single bit flip mid-log: the damaged record is quarantined, the
    records on either side still replay, metric + event fire, and the
    flipped bytes land in the sidecar (never in results)."""
    path = str(tmp_path / "stream.log")
    prod = LogIngestionStream(path, DEFAULT_SCHEMAS)
    offs = []
    for i in range(5):
        for c in _batch(i):
            offs.append(prod.append(c))
    prod.close()
    recs = prod._records
    victim = recs[2]
    _flip_byte(path, victim.payload_off + victim.payload_len // 2)

    cons = LogIngestionStream(path, DEFAULT_SCHEMAS)
    got = cons.read(0, 100)
    # 4 survivors; replay did NOT halt at the damage
    assert len(got) == 4
    assert all(len(sd.container.timestamps) == 4 for sd in got)
    assert cons.quarantined_records() == 1
    assert cons.quarantined_bytes() == victim.length
    assert _corruption_total(file_kind="wal") >= 1
    evs = obs_events.GLOBAL_EVENTS.snapshot(kind="corruption")
    assert evs and evs[0]["file_kind"] == "wal"
    qdir = integrity.quarantine_dir(path)
    names = os.listdir(qdir)
    assert f"stream.log.{victim.offset}.bad" in names
    assert "MANIFEST.jsonl" in names
    cons.close()


def test_wal_read_time_two_strike_skip(tmp_path):
    """Damage that lands AFTER scan (same-process producer index) is
    caught by read-path re-verification: first failure retries, second
    quarantines and advances with an empty batch."""
    path = str(tmp_path / "stream.log")
    s = LogIngestionStream(path, DEFAULT_SCHEMAS)
    for i in range(5):
        for c in _batch(i):
            s.append(c)
    victim = s._records[2]
    _flip_byte(path, victim.payload_off + 2)

    got1 = s.read(0, 100)
    assert [sd.offset for sd in got1] == [0, 1]      # strike 1: stop
    assert s.quarantined_records() == 0
    assert _corruption_total(file_kind="wal", action="read-retry") == 1
    got2 = s.read(2, 100)                            # strike 2: skip
    assert [sd.offset for sd in got2] == [2, 3, 4]
    assert len(got2[0].container.timestamps) == 0    # empty placeholder
    assert len(got2[1].container.timestamps) > 0
    assert s.quarantined_records() == 1
    assert _corruption_total(file_kind="wal", action="skipped") == 1
    s.close()


def test_wal_legacy_garbage_no_silent_halt(tmp_path):
    """Satellite: the pre-integrity reader stopped indexing forever at
    the first struct-invalid legacy record, silently truncating replay.
    Now the region is counted, quarantined, and replay resumes."""
    path = str(tmp_path / "stream.log")
    prod = LogIngestionStream(path, DEFAULT_SCHEMAS,
                              integrity_frames=False)
    for i in range(4):
        for c in _batch(i):
            prod.append(c)
    prod.close()
    second = prod._records[1]
    # stomp the record's magic: struct-invalid, not just a bad CRC
    _flip_byte(path, second.offset, mask=0xFF)
    _flip_byte(path, second.offset + 1, mask=0xFF)

    cons = LogIngestionStream(path, DEFAULT_SCHEMAS)
    got = cons.read(0, 100)
    assert len(got) == 3                             # NOT 1: no halt
    assert cons.quarantined_records() >= 1
    assert _corruption_total(file_kind="wal") >= 1
    cons.close()


def test_wal_mixed_version_file_replays_fully(tmp_path):
    """A stream dir written partly by an old (unframed) build and partly
    by the new one replays every record through one consumer."""
    path = str(tmp_path / "stream.log")
    old = LogIngestionStream(path, DEFAULT_SCHEMAS,
                             integrity_frames=False)
    for i in range(3):
        for c in _batch(i):
            old.append(c)
    old.close()
    new = LogIngestionStream(path, DEFAULT_SCHEMAS)
    for i in range(3, 6):
        for c in _batch(i):
            new.append(c)
    new.close()

    cons = LogIngestionStream(path, DEFAULT_SCHEMAS)
    got = cons.read(0, 100)
    assert len(got) == 6
    assert all(len(sd.container.timestamps) == 4 for sd in got)
    assert cons.quarantined_records() == 0
    framed = [r.framed for r in cons._records]
    assert framed == [False] * 3 + [True] * 3
    cons.close()


def test_wal_torn_tail_truncated_on_takeover(tmp_path):
    path = str(tmp_path / "stream.log")
    s = LogIngestionStream(path, DEFAULT_SCHEMAS)
    for c in _batch(0):
        s.append(c)
    s.close()
    size = os.path.getsize(path)
    with open(path, "ab") as f:
        f.write(integrity.encode_frame(b"x" * 64)[:20])   # torn append
    cons = LogIngestionStream(path, DEFAULT_SCHEMAS)
    cons.end_offset()                      # force a scan
    assert cons.tail_state() == "torn"
    for c in _batch(1):
        cons.append(c)                     # takeover truncates the tear
    got = cons.read(0, 100)
    assert len(got) == 2
    assert cons.tail_state() == "clean"
    cons.close()


# -- column store: chunks / partkeys / checkpoints -------------------------

def _flushed_store(tmp_path):
    cs = FlatFileColumnStore(str(tmp_path / "col"))
    shard = TimeSeriesShard(REF, DEFAULT_SCHEMAS, 0, num_groups=2,
                            max_chunk_rows=32, column_store=cs)
    b = RecordBuilder(DEFAULT_SCHEMAS)
    for s in range(3):
        labels = {"_metric_": "disk_io_total", "_ws_": "demo",
                  "_ns_": "App-0", "instance": f"i{s}"}
        for t in range(100):
            b.add_sample("prom-counter", labels,
                         (T0 + t * 10) * 1000, float((t + 1) * (s + 1)))
    for c in b.containers():
        shard.ingest(c, 7)
    shard.flush_all(offset=7)
    cs.close()
    d = cs._shard_dir("timeseries", 0)
    return {"chunks": os.path.join(d, "chunks.log"),
            "partkeys": os.path.join(d, "partkeys.log"),
            "checkpoint": os.path.join(d, "checkpoints.json"),
            "root": str(tmp_path / "col")}


def test_chunklog_bitflip_skipped_counted_query_survives(tmp_path):
    paths = _flushed_store(tmp_path)
    # flip a payload byte inside the SECOND framed record so the scan
    # index stays intact but that chunk's CRC fails
    with open(paths["chunks"], "rb") as f:
        buf = f.read()
    res = integrity.scan_buffer(buf, probe=lambda b, o: 0)
    assert len(res.records) >= 2 and all(r.framed for r in res.records)
    victim = res.records[1]
    _flip_byte(paths["chunks"], victim.payload_off + victim.payload_len // 2)

    cs = FlatFileColumnStore(paths["root"])
    shard = TimeSeriesShard(REF, DEFAULT_SCHEMAS, 0, num_groups=2,
                            max_chunk_rows=32, column_store=cs)
    shard.bootstrap_from_store()
    plan = parse_query_range("disk_io_total",
                             TimeStepParams(T0, 60, T0 + 990))
    res_q = QueryEngine([shard]).execute(plan)   # must not raise
    assert cs.quarantined_records("timeseries", 0) >= 1
    assert _corruption_total(file_kind="chunklog") >= 1
    assert obs_events.GLOBAL_EVENTS.snapshot(kind="corruption")
    cs.close()


def test_partkeys_bitflip_entry_skipped_and_counted(tmp_path):
    paths = _flushed_store(tmp_path)
    with open(paths["partkeys"], "rb") as f:
        buf = f.read()
    res = integrity.scan_buffer(buf, probe=lambda b, o: 0)
    n_entries = len(res.records)
    assert n_entries == 3
    victim = res.records[1]
    _flip_byte(paths["partkeys"], victim.payload_off + 4)

    cs = FlatFileColumnStore(paths["root"])
    entries = list(cs.scan_part_keys("timeseries", 0))
    assert len(entries) == n_entries - 1
    assert cs.quarantined_records("timeseries", 0) >= 1
    assert _corruption_total(file_kind="partkeys") >= 1
    cs.close()


def test_checkpoint_bitflip_read_empty_and_counted(tmp_path):
    paths = _flushed_store(tmp_path)
    size = os.path.getsize(paths["checkpoint"])
    _flip_byte(paths["checkpoint"], size // 2)

    cs = FlatFileColumnStore(paths["root"])
    # unverifiable checkpoint -> replay from 0 (safe), never bad data
    assert cs.read_checkpoints("timeseries", 0) == {}
    assert cs.quarantined_records("timeseries", 0) >= 1
    assert _corruption_total(file_kind="checkpoint") >= 1
    cs.close()


def test_checkpoint_rewrite_heals(tmp_path):
    paths = _flushed_store(tmp_path)
    _flip_byte(paths["checkpoint"], os.path.getsize(paths["checkpoint"]) // 2)
    cs = FlatFileColumnStore(paths["root"])
    assert cs.read_checkpoints("timeseries", 0) == {}
    cs.write_checkpoint("timeseries", 0, 0, 11)
    cs.write_checkpoint("timeseries", 0, 1, 12)
    assert cs.read_checkpoints("timeseries", 0) == {0: 11, 1: 12}
    cs.close()


# -- ENOSPC: clean ingest-read-only degradation ----------------------------

def test_health_enospc_flips_read_only_and_recovers():
    h = ingest_health.IngestHealth(probe_interval_s=0.0)
    e = OSError(errno.ENOSPC, "no space left on device")
    assert h.note_write_error(e, "unit") is True
    assert h.read_only()
    # non-space errors are the caller's problem, state unchanged
    assert h.note_write_error(OSError(errno.EPERM, "x"), "unit") is False
    with pytest.raises(ingest_health.IngestReadOnly) as ei:
        raise h.reject()
    assert ei.value.retry_after_s > 0
    h.note_write_ok()
    assert not h.read_only()
    evs = obs_events.GLOBAL_EVENTS.snapshot(kind="ingest-read-only")
    assert [e["state"] for e in evs] == ["recovered", "entered"]


def test_gateway_enospc_degrades_then_auto_recovers(tmp_path):
    """ENOSPC mid-ingest through the gateway publish path: process
    flips to ingest-read-only (counted drops, no crashed thread),
    queries would keep serving, and the first successful probe write
    recovers automatically."""
    path = str(tmp_path / "stream.log")
    stream = LogIngestionStream(path, DEFAULT_SCHEMAS)
    gw = GatewayServer({0: stream}, DEFAULT_SCHEMAS, num_shards=1,
                       spread=0)
    ingest_health.GLOBAL.probe_interval_s = 0.0   # probe every publish
    line = "reqs,instance=i0 total=1 1600000000000000000"
    try:
        inj = chaos.ChaosInjector()
        inj.fail("wal.append", exc=chaos.enospc, times=1)
        with inj:
            builders = {}
            assert gw._route_line(line, builders)
            gw._publish(builders)                 # hits injected ENOSPC
        assert ingest_health.GLOBAL.read_only()
        assert gw.batches_dropped == 1
        n0 = stream.end_offset()
        # next publish is the recovery probe; disk is "fixed" now
        builders = {}
        gw._route_line(line, builders)
        gw._publish(builders)
        assert not ingest_health.GLOBAL.read_only()
        assert stream.end_offset() == n0 + 1      # probe write landed
    finally:
        gw._server.server_close()
        stream.close()


def test_gateway_read_only_raises_for_http_edge(tmp_path):
    path = str(tmp_path / "stream.log")
    stream = LogIngestionStream(path, DEFAULT_SCHEMAS)
    gw = GatewayServer({0: stream}, DEFAULT_SCHEMAS, num_shards=1,
                       spread=0)
    ingest_health.GLOBAL.probe_interval_s = 3600.0
    ingest_health.GLOBAL.note_write_error(
        OSError(errno.ENOSPC, "no space"), "unit")
    ingest_health.GLOBAL.should_probe()           # burn the probe slot
    try:
        builders = {}
        gw._route_line("reqs,instance=i0 total=1 1600000000000000000",
                       builders)
        with pytest.raises(ingest_health.IngestReadOnly):
            gw._publish(builders, raise_on_error=True)
    finally:
        gw._server.server_close()
        stream.close()


# -- quarantine knob: shard degrades to read-only --------------------------

def test_quarantine_knob_degrades_shard_to_read_only(tmp_path):
    """integrity-max-quarantined-records=0 (the default): ANY
    quarantined record stops the shard from applying NEW batches —
    but startup replay still applies every checksum-verified survivor
    (read-only must not turn one bad record into a whole-shard
    truncation), and the mapper stays ACTIVE so queries keep serving."""
    path = str(tmp_path / "stream.log")
    prod = LogIngestionStream(path, DEFAULT_SCHEMAS)
    for i in range(4):
        for c in _batch(i):
            prod.append(c)
    prod.close()
    victim = prod._records[1]
    _flip_byte(path, victim.payload_off + 3)

    stream = LogIngestionStream(path, DEFAULT_SCHEMAS)
    mapper = ShardMapper(1)
    shard = TimeSeriesShard(REF, DEFAULT_SCHEMAS, 0, num_groups=2,
                            max_chunk_rows=64)
    drv = IngestionDriver(shard, stream, mapper=mapper,
                          poll_interval_s=0.01,
                          max_quarantined_records=0)
    drv.start()
    import time
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and not shard.integrity_read_only:
        time.sleep(0.01)
    assert shard.integrity_read_only
    # recovery completes past the trip: all 3 surviving batches land
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and drv.next_offset < 3:
        time.sleep(0.01)
    assert drv.next_offset == 3
    assert shard.stats.rows_ingested == 3 * 4
    # ...but NEW post-recovery appends are gated by read-only
    for c in _batch(9):
        stream.append(c)
    time.sleep(0.2)
    drv.stop()
    assert shard.stats.rows_ingested == 3 * 4
    assert shard.integrity_quarantined_records == 1
    # read-only != down: still queryable
    assert mapper.status(0).queryable
    evs = obs_events.GLOBAL_EVENTS.snapshot(kind="integrity-read-only")
    assert evs and evs[0]["shard"] == 0
    gauges = obs_metrics.GLOBAL_REGISTRY.gauge(
        "filodb_shard_integrity_read_only", "").series()
    assert any(v == 1.0 for _, v in gauges)
    stream.close()


def test_quarantine_knob_tolerance_allows_bounded_loss(tmp_path):
    """A nonzero knob tolerates that much loss and keeps ingesting."""
    path = str(tmp_path / "stream.log")
    prod = LogIngestionStream(path, DEFAULT_SCHEMAS)
    for i in range(4):
        for c in _batch(i):
            prod.append(c)
    prod.close()
    victim = prod._records[1]
    _flip_byte(path, victim.payload_off + 3)

    stream = LogIngestionStream(path, DEFAULT_SCHEMAS)
    mapper = ShardMapper(1)
    shard = TimeSeriesShard(REF, DEFAULT_SCHEMAS, 0, num_groups=2,
                            max_chunk_rows=64)
    drv = IngestionDriver(shard, stream, mapper=mapper,
                          poll_interval_s=0.01,
                          max_quarantined_records=5)
    drv.start()
    import time
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and drv.next_offset < 3:
        time.sleep(0.01)
    drv.stop()
    assert not shard.integrity_read_only
    assert shard.stats.rows_ingested == 3 * 4     # 3 surviving batches
    assert shard.integrity_quarantined_records == 1
    stream.close()


# -- result cache refusal --------------------------------------------------

def test_resultcache_refuses_quarantined_shards():
    from filodb_tpu.query.resultcache import ResultCache, shards_quarantine

    class _Shard:
        def __init__(self, wm, q=0):
            self.ingest_watermark_ms = wm
            self.ingest_backfill_epoch = 0
            self.integrity_quarantined_records = q

    class _Eng:
        def __init__(self, shards):
            self.shards = shards

    assert shards_quarantine([_Shard(0, 0), _Shard(0, 2)]) == 2
    rc = ResultCache(hot_window_ms=0)
    clean = _Eng([_Shard(10_000_000_000)])
    dirty = _Eng([_Shard(10_000_000_000, q=1)])
    plan = parse_query_range("up", TimeStepParams(1000, 60, 2000))
    h = rc.begin(clean, "ds", "up", plan, 1_000_000, 60_000, 2_000_000)
    assert h.state != "uncacheable"
    h2 = rc.begin(dirty, "ds", "up", plan, 1_000_000, 60_000, 2_000_000)
    assert h2.state == "uncacheable"
    assert rc.integrity_refused == 1
    assert rc.stale_serve(dirty, "ds", "up", plan, 1_000_000, 60_000,
                          2_000_000) is None
