"""promlint wiring tests: rules --check semantic rejection, normalized
duplicate detection, the graftlint promql family (--json/--github
emitters, --changed-only soak skip, 30s perf guard), and the HTTP
edge's lint warnings / &lint=strict behavior."""

import json
import time
import urllib.parse
import urllib.request

import pytest

from filodb_tpu.rules import __main__ as rules_main
from filodb_tpu.rules.loader import (RuleLoadError, check_rules_file_full,
                                     load_groups, parse_rules_text)

BAD_RULES = """
groups:
  - name: bad
    interval: 30s
    rules:
      - record: app:mem:avg
        expr: avg(mem_usage)
        schema: gauge
      - record: app:mem:rate
        expr: rate(app:mem:avg[5m])
      - record: app:join
        expr: sum by (job) (cpu_usage) * on (instance) sum by (instance) (mem_usage)
"""

DUP_RULES = """
groups:
  - name: dup
    interval: 30s
    rules:
      - record: app:a
        expr: sum(rate(http_requests_total[5m]))
      - record: app:b
        expr: sum ( rate( http_requests_total[5m] ) )
"""


# ---------------------------------------------------------------------------
# rules --check gains semantic diagnostics
# ---------------------------------------------------------------------------

def test_rules_check_rejects_semantic_errors(tmp_path):
    """Acceptance fixture: rate() on a gauge-schema metric AND a
    dropped-label vector match — both rejected, both with a spanned
    diagnostic; the whole file fails to load."""
    p = tmp_path / "bad.yaml"
    p.write_text(BAD_RULES)
    errors, _warnings = check_rules_file_full(str(p))
    text = "\n".join(errors)
    assert "promql-counter-fn-on-gauge" in text
    assert "promql-match-on-dropped-label" in text
    assert "^" in text          # caret spans in the rendering
    assert "rate(app:mem:avg[5m])" in text
    assert rules_main.main(["--check", str(p)]) == 1
    with pytest.raises(RuleLoadError):
        parse_rules_text(BAD_RULES)


def test_rules_check_shipped_examples_sweep_clean():
    errors, warnings = check_rules_file_full("examples/rules.yaml")
    assert errors == [], errors
    assert warnings == [], warnings
    assert rules_main.main(["--check", "examples/rules.yaml"]) == 0


def test_normalized_duplicate_detection():
    """Whitespace/normalization-variant recording rules are caught by
    parser-normalized comparison (raw text comparison would miss
    them) — a warning, not a rejection."""
    errors, warnings = [], []
    parse_rules_text(DUP_RULES, errors=errors, warnings=warnings)
    assert errors == []
    assert any("semantically identical" in w for w in warnings), warnings


def test_semantic_warnings_do_not_reject():
    groups = load_groups({"groups": [{"name": "g", "rules": [
        {"record": "x:delta",
         "expr": "delta(http_requests_total[5m])"}]}]})
    assert len(groups) == 1     # warning-severity finding only


# ---------------------------------------------------------------------------
# graftlint promql family
# ---------------------------------------------------------------------------

def test_promql_rules_registered_in_catalog():
    from filodb_tpu.lint import rules
    cat = rules()
    fam = {rid: r for rid, r in cat.items()
           if r.family == "promql"}
    assert "promql-counter-fn-on-gauge" in fam
    assert "promql-differential-mismatch" in fam
    assert all(rid.startswith("promql-") for rid in fam)


def test_rule_file_sweep_findings_and_github_flow(tmp_path):
    """A broken rule file under examples/ becomes spanned findings
    that flow through the --json/--github emitters with their
    promql- rule ids."""
    from filodb_tpu.lint import Finding, LintResult
    from filodb_tpu.lint.ci_annotations import github_annotations
    from filodb_tpu.lint.rules_promql import _rule_file_findings
    root = tmp_path
    ex = tmp_path / "examples"
    ex.mkdir()
    bad = ex / "bad.yaml"
    bad.write_text(BAD_RULES)
    found = _rule_file_findings(str(bad), str(root))
    rules_seen = {f.rule for _rel, f in found}
    assert "promql-counter-fn-on-gauge" in rules_seen
    assert "promql-match-on-dropped-label" in rules_seen
    by_rule = {f.rule: f for _rel, f in found}
    f = by_rule["promql-counter-fn-on-gauge"]
    assert f.path == "examples/bad.yaml"
    assert f.line > 1           # anchored at the expr's line, not 1
    res = LintResult(findings=[f for _rel, f in found])
    lines = github_annotations(res.to_json())
    assert any("::error" in l and "promql-counter-fn-on-gauge" in
               urllib.parse.unquote(l.replace("%3A", ":")) or
               "promql-counter-fn-on-gauge" in l for l in lines)


def test_shipped_examples_sweep_clean_through_lint():
    from filodb_tpu.lint import package_root
    from filodb_tpu.lint.rules_promql import check_project
    found = check_project([], package_root(), skip_soak=True)
    assert found == [], [f.render() for _r, f in found]


def test_changed_only_skips_differential_soak(monkeypatch):
    from filodb_tpu.lint import rules_promql
    called = []
    monkeypatch.setattr(rules_promql, "_soak_findings",
                        lambda root: called.append(root) or [])
    rules_promql.check_project([], "/nonexistent", skip_soak=True)
    assert called == []
    rules_promql.check_project([], "/nonexistent", skip_soak=False)
    assert called


def test_differential_micro_soak_clean_and_under_perf_guard():
    """The lint-gate soak arm: zero mismatches at the fixed seed, and
    the FULL promql family sweep (rule files + soak) stays under the
    30s budget — it runs inside every full lint invocation."""
    from filodb_tpu.lint import package_root
    from filodb_tpu.lint.rules_promql import check_project
    t0 = time.perf_counter()
    found = check_project([], package_root(), skip_soak=False)
    elapsed = time.perf_counter() - t0
    assert found == [], [f.render() for _r, f in found]
    assert elapsed < 30.0, f"promql lint sweep took {elapsed:.1f}s"


# ---------------------------------------------------------------------------
# HTTP edge: warnings + &lint=strict
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def server():
    from filodb_tpu.standalone.server import FiloServer
    srv = FiloServer({"num-shards": 2, "grpc-port": None, "port": 0,
                      "results-cache-mb": 0,
                      "batch-enabled": False}).start()
    srv.seed_dev_data(n_samples=60, n_instances=2,
                      start_ms=1_600_000_000_000)
    try:
        yield srv
    finally:
        srv.stop()


def _get(port, **params):
    url = (f"http://127.0.0.1:{port}/promql/timeseries/api/v1/"
           f"query_range?" + urllib.parse.urlencode(params))
    try:
        with urllib.request.urlopen(url, timeout=30) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


T0 = 1_600_000_000
_RANGE = dict(start=T0 + 100, end=T0 + 400, step=10)


def test_http_lint_warnings_ride_the_envelope(server):
    code, payload = _get(server.port,
                         query="delta(http_requests_total[2m])",
                         **_RANGE)
    assert code == 200
    warns = payload.get("warnings", [])
    assert any("promql-gauge-fn-on-counter" in w for w in warns), \
        payload.get("warnings")


def test_http_lint_strict_rejects_with_diagnostics(server):
    # lints as an error (dc was provably dropped by both sides'
    # aggregations) yet still evaluates — both sides are single-series
    # so the degenerate match is one-to-one
    q = ("sum(rate(http_requests_total[2m])) * "
         "on (dc) sum(heap_usage)")
    code, payload = _get(server.port, query=q, lint="strict", **_RANGE)
    assert code == 400
    assert payload["errorType"] == "bad_data"
    assert "promql-match-on-dropped-label" in payload["error"]
    assert payload["lint"][0]["rule"].startswith("promql-")
    assert payload["lint"][0]["pos"] >= 0
    # non-strict: same query answers 200 with the finding as a warning
    code2, payload2 = _get(server.port, query=q, **_RANGE)
    assert code2 == 200
    assert any("promql-match-on-dropped-label" in w
               for w in payload2.get("warnings", []))


def test_http_lint_off_disables(server):
    code, payload = _get(server.port,
                         query="delta(http_requests_total[2m])",
                         lint="off", **_RANGE)
    assert code == 200
    assert not any("promlint" in w
                   for w in payload.get("warnings", []))


def test_http_lint_clean_query_untouched(server):
    code, payload = _get(server.port,
                         query="rate(http_requests_total[2m])",
                         **_RANGE)
    assert code == 200
    assert not any("promlint" in w
                   for w in payload.get("warnings", []))
