"""Observability: /metrics Prometheus exposition + query stats in API
responses (TimeSeriesShardStats surface, TimeSeriesShard.scala:41; QueryStats
threaded through results, core/query/QueryContext.scala), the stage
latency histograms, the slow-query log / in-flight registry debug
endpoints, and the TenantMetering daemon-thread lifecycle.
"""

import json
import time
import urllib.request

from filodb_tpu.core.metering import TenantMetering
from filodb_tpu.standalone.server import FiloServer

T0 = 1_600_000_000


def _get_text(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                timeout=30) as r:
        return r.headers.get("Content-Type", ""), r.read().decode()


def _get_json(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                timeout=60) as r:
        return json.loads(r.read())


def _samples(text):
    """{series_line_without_value: float} for every non-comment line."""
    out = {}
    for ln in text.strip().splitlines():
        if ln.startswith("#") or not ln:
            continue
        name, val = ln.rsplit(" ", 1)
        out[name] = float(val)
    return out


def test_metrics_and_query_stats():
    srv = FiloServer({"num-shards": 2, "port": 0,
                      "slow-query-ms": 0.001}).start()
    try:
        srv.seed_dev_data(n_samples=60, n_instances=3, start_ms=T0 * 1000)
        ctype, text = _get_text(srv.port, "/metrics")
        assert ctype.startswith("text/plain")
        lines = _samples(text)
        # per-shard ingest gauges present and summing to the seeded rows
        ingested = sum(v for k, v in lines.items()
                       if k.startswith("filodb_rows_ingested"))
        assert ingested > 0
        assert any(k.startswith("filodb_num_series") for k in lines)
        assert any(k.startswith("filodb_shard_status") for k in lines)
        assert any(k.startswith("filodb_cardinality_total_series")
                   for k in lines)
        assert any(k.startswith("filodb_tile_builds_total")
                   for k in lines)
        # every family carries # HELP and # TYPE
        assert "# HELP filodb_rows_ingested" in text
        assert "# TYPE filodb_shard_status gauge" in text
        assert "# TYPE filodb_plan_cache_hits_total counter" in text

        # query stats ride the API response
        url = (f"http://127.0.0.1:{srv.port}/promql/timeseries/api/v1/"
               f"query_range?query=rate(http_requests_total[5m])"
               f"&start={T0 + 300}&end={T0 + 500}&step=60")
        body = json.loads(urllib.request.urlopen(url, timeout=60).read())
        assert body["status"] == "success"
        st = body["stats"]
        assert st["seriesScanned"] == 3
        assert st["samplesScanned"] > 0
        assert st["resultBytes"] > 0
        # query-path spans (parse/plan/exec) ride the stats
        tm = st["timings"]
        assert tm["execMs"] >= 0 and tm["plan"]

        # stage-latency histograms appear once a query was served:
        # well-formed _bucket/_sum/_count with # TYPE histogram
        _, text2 = _get_text(srv.port, "/metrics")
        assert "# TYPE filodb_query_latency_seconds histogram" in text2
        lines2 = _samples(text2)
        assert lines2['filodb_query_latency_seconds_bucket{le="+Inf"}'] \
            >= 1
        assert "filodb_query_latency_seconds_count" in lines2
        assert any(k.startswith("filodb_batcher_queue_wait_seconds_bucket")
                   for k in lines2)
        assert any(k.startswith("filodb_device_execute_seconds_bucket")
                   for k in lines2)
    finally:
        srv.stop()


def test_debug_queries_and_slow_query_log():
    # threshold of ~0: every query lands in the slow-query log with a
    # per-stage breakdown summing to ~the total
    srv = FiloServer({"num-shards": 2, "port": 0,
                      "slow-query-ms": 0.001}).start()
    try:
        srv.seed_dev_data(n_samples=60, n_instances=3, start_ms=T0 * 1000)
        url = (f"http://127.0.0.1:{srv.port}/promql/timeseries/api/v1/"
               f"query_range?query=rate(http_requests_total[5m])"
               f"&start={T0 + 300}&end={T0 + 500}&step=60")
        json.loads(urllib.request.urlopen(url, timeout=60).read())
        body = _get_json(srv.port, "/debug/slow_queries")
        assert body["status"] == "success"
        assert body["summary"]["recorded"] >= 1
        rec = body["data"][0]
        assert rec["query"] == "rate(http_requests_total[5m])"
        assert rec["dataset"] == "timeseries"
        assert rec["shards"] == [0, 1]
        assert rec["seriesScanned"] == 3
        stages = rec["stages"]
        # per-stage breakdown sums to ~total (encode of the sampled
        # response shape is in-stage; envelope write is outside)
        stage_sum = sum(v for k, v in stages.items()
                        if k.endswith("Ms"))
        assert stage_sum <= rec["elapsed_ms"] + 1e-3
        assert stage_sum >= 0.5 * rec["elapsed_ms"]
        # in-flight registry is empty once the query finished
        body = _get_json(srv.port, "/debug/queries")
        assert body["status"] == "success" and body["data"] == []
    finally:
        srv.stop()


def test_explain_trace_and_debug_traces():
    srv = FiloServer({"num-shards": 2, "port": 0}).start()
    try:
        srv.seed_dev_data(n_samples=60, n_instances=3, start_ms=T0 * 1000)
        url = (f"http://127.0.0.1:{srv.port}/promql/timeseries/api/v1/"
               f"query_range?query=rate(http_requests_total[5m])"
               f"&start={T0 + 300}&end={T0 + 500}&step=60"
               f"&explain=trace")
        body = json.loads(urllib.request.urlopen(url, timeout=60).read())
        assert body["status"] == "success"
        tr = body["trace"]
        names = {s["name"] for s in tr["spans"]}
        # the single-node span catalog: edge stages + engine + device
        assert {"query", "parse", "plan", "execute",
                "select-series", "device-eval", "encode"} <= names, names
        # one stitched parent chain: every non-root span's parent exists
        ids = {s["span_id"] for s in tr["spans"]}
        roots = [s for s in tr["spans"] if s["parent_id"] is None]
        assert len(roots) == 1 and roots[0]["name"] == "query"
        for s in tr["spans"]:
            if s["parent_id"] is not None:
                assert s["parent_id"] in ids
        # retrievable from the ring buffer
        listing = _get_json(srv.port, "/debug/traces")
        assert any(t["trace_id"] == tr["trace_id"]
                   for t in listing["data"])
        one = _get_json(srv.port, f"/debug/traces?id={tr['trace_id']}")
        assert one["data"]["num_spans"] == tr["num_spans"]

        # tracing was NOT globally enabled: a plain query stays on the
        # pre-encoded fast path with no trace keys
        plain = json.loads(urllib.request.urlopen(
            url.replace("&explain=trace", ""), timeout=60).read())
        assert "trace" not in plain and "trace_spans" not in plain
    finally:
        srv.stop()


def test_tenant_metering_lifecycle_and_gauges():
    class _Rec:
        def __init__(self, prefix):
            self.prefix = prefix
            self.ts_count = 5
            self.active_ts_count = 3

    class _Tracker:
        def scan(self, prefix, depth):
            return [_Rec(("demo", "App-0"))]

    m = TenantMetering({0: _Tracker()}, interval_s=0.05)
    assert not m.alive
    m.start()
    assert m.alive
    assert m.snapshots >= 1 and m.latest[("demo", "App-0")] == (5, 3)
    time.sleep(0.15)
    assert m.snapshots >= 2            # the loop ticks
    assert m.last_snapshot_age_s is not None \
        and m.last_snapshot_age_s < 5
    m.stop()
    assert not m.alive                  # joined, not orphaned
    m.stop()                            # idempotent
    # stop before start is safe too
    m2 = TenantMetering({0: _Tracker()}, interval_s=60)
    m2.stop()
    assert not m2.alive


def test_server_stops_metering_thread():
    srv = FiloServer({"num-shards": 1, "port": 0,
                      "tenant-metering-interval-s": 0.1}).start()
    meter = srv.tenant_metering
    assert meter is not None and meter.alive
    # interval + last-snapshot age are exported
    _, text = _get_text(srv.port, "/metrics")
    lines = _samples(text)
    assert lines["filodb_tenant_metering_interval_seconds"] == 0.1
    assert "filodb_tenant_metering_last_snapshot_age_seconds" in lines
    assert lines["filodb_tenant_metering_snapshots_total"] >= 1
    srv.stop()
    assert not meter.alive              # stopped AND joined on shutdown
