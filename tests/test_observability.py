"""Observability: /metrics Prometheus exposition + query stats in API
responses (TimeSeriesShardStats surface, TimeSeriesShard.scala:41; QueryStats
threaded through results, core/query/QueryContext.scala).
"""

import json
import urllib.request

from filodb_tpu.standalone.server import FiloServer

T0 = 1_600_000_000


def _get_text(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                timeout=30) as r:
        return r.headers.get("Content-Type", ""), r.read().decode()


def test_metrics_and_query_stats():
    srv = FiloServer({"num-shards": 2, "port": 0}).start()
    try:
        srv.seed_dev_data(n_samples=60, n_instances=3, start_ms=T0 * 1000)
        ctype, text = _get_text(srv.port, "/metrics")
        assert ctype.startswith("text/plain")
        lines = dict()
        for ln in text.strip().splitlines():
            name, val = ln.rsplit(" ", 1)
            lines[name] = float(val)
        # per-shard ingest gauges present and summing to the seeded rows
        ingested = sum(v for k, v in lines.items()
                       if k.startswith("filodb_rows_ingested"))
        assert ingested > 0
        assert any(k.startswith("filodb_num_series") for k in lines)
        assert any(k.startswith("filodb_shard_status") for k in lines)
        assert any(k.startswith("filodb_cardinality_total_series")
                   for k in lines)
        assert any(k.startswith("filodb_tile_builds_total")
                   for k in lines)

        # query stats ride the API response
        url = (f"http://127.0.0.1:{srv.port}/promql/timeseries/api/v1/"
               f"query_range?query=rate(http_requests_total[5m])"
               f"&start={T0 + 300}&end={T0 + 500}&step=60")
        body = json.loads(urllib.request.urlopen(url, timeout=60).read())
        assert body["status"] == "success"
        st = body["stats"]
        assert st["seriesScanned"] == 3
        assert st["samplesScanned"] > 0
        assert st["resultBytes"] > 0
        # query-path spans (parse/plan/exec) ride the stats
        tm = st["timings"]
        assert tm["execMs"] >= 0 and tm["plan"]

        # tile cache counters move once the backend served a query
        _, text2 = _get_text(srv.port, "/metrics")
        assert "filodb_tile_builds_total" in text2
    finally:
        srv.stop()
