"""Per-shard-key spread overrides: one SpreadProvider drives both the
ingest edge and the query planner (core/SpreadProvider.scala;
doc/sharding.md "Spread" — hot keys fan across 2^spread shards).
"""

import numpy as np

from filodb_tpu.core.record import (PartKey, ingestion_shard,
                                    shard_key_hash)
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, PartitionSchema
from filodb_tpu.core.spread import SpreadProvider


def test_overrides_and_default():
    sp = SpreadProvider(1, {"demo,hot-ns": 2})
    assert sp.spread_for(["demo", "App-0"]) == 1
    assert sp.spread_for(["demo", "hot-ns"]) == 2
    assert sp.spread_for_labels({"_ws_": "demo", "_ns_": "hot-ns"},
                                ("_ws_", "_ns_")) == 2


def test_ingest_and_query_agree_per_key():
    """Every series the gateway-routing puts in a shard must be inside
    the planner's pruned shard set, for BOTH default and override keys."""
    from filodb_tpu.core.record import query_shards
    sp = SpreadProvider(0, {"demo,hot-ns": 2})
    part_schema = PartitionSchema()
    num_shards = 8
    for ns, metric in (("App-0", "cpu"), ("hot-ns", "cpu")):
        spread = sp.spread_for(["demo", ns])
        qshards = set(query_shards(
            shard_key_hash(["demo", ns], metric), spread, num_shards))
        assert len(qshards) == 1 << spread
        for i in range(64):
            labels = {"_metric_": metric, "_ws_": "demo", "_ns_": ns,
                      "instance": f"i{i}"}
            pk = PartKey.make(DEFAULT_SCHEMAS.by_name("gauge"), labels)
            sh = ingestion_shard(pk.shard_key_hash(part_schema),
                                 pk.part_hash(), spread, num_shards)
            assert sh in qshards


def test_planner_uses_provider(tmp_path):
    from filodb_tpu.core.memstore import TimeSeriesShard
    from filodb_tpu.core.record import RecordBuilder
    from filodb_tpu.core.schemas import DatasetRef
    from filodb_tpu.parallel.shardmapper import (ShardMapper,
                                                 assign_shards_evenly)
    from filodb_tpu.promql.parser import TimeStepParams, parse_query_range
    from filodb_tpu.query.planner import QueryPlanner
    sp = SpreadProvider(0, {"demo,hot-ns": 1})
    mapper = ShardMapper(4)
    assign_shards_evenly(mapper, ["n0"])
    for i in range(4):
        mapper.activate(i)
    shards = [TimeSeriesShard(DatasetRef("timeseries"), DEFAULT_SCHEMAS, i)
              for i in range(4)]
    planner = QueryPlanner(shards, shard_mapper=mapper,
                           spread_provider=sp)
    tsp = TimeStepParams(1_600_000_000, 60, 1_600_000_600)
    cold = parse_query_range('cpu{_ws_="demo",_ns_="App-0"}', tsp)
    hot = parse_query_range('cpu{_ws_="demo",_ns_="hot-ns"}', tsp)
    n_cold = len(planner.shards_from_filters(cold.raw.filters))
    n_hot = len(planner.shards_from_filters(hot.raw.filters))
    assert n_cold == 1 and n_hot == 2


def test_regex_shard_key_fanout():
    """ShardKeyRegexPlanner.scala:31: literal-alternation regex /
    in-lists on shard-key columns prune to the union of per-value shard
    sets instead of fanning to all shards."""
    from filodb_tpu.core.index import ColumnFilter
    from filodb_tpu.core.record import query_shards
    from filodb_tpu.parallel.shardmapper import (ShardMapper,
                                                 assign_shards_evenly)
    from filodb_tpu.query.planner import QueryPlanner
    mapper = ShardMapper(16)
    assign_shards_evenly(mapper, ["n0"])
    for i in range(16):
        mapper.activate(i)
    planner = QueryPlanner([], shard_mapper=mapper, spread=0)
    f = [ColumnFilter("_metric_", "eq", "cpu"),
         ColumnFilter("_ws_", "eq", "demo"),
         ColumnFilter("_ns_", "re", "App-0|App-1|App-2")]
    got = planner.shards_from_filters(f)
    want = set()
    for ns in ("App-0", "App-1", "App-2"):
        want.update(query_shards(shard_key_hash(["demo", ns], "cpu"),
                                 0, 16))
    assert got == sorted(want)
    assert 0 < len(got) < 16
    # true regex (metacharacters) still fans out to all shards
    f2 = [ColumnFilter("_metric_", "eq", "cpu"),
          ColumnFilter("_ws_", "eq", "demo"),
          ColumnFilter("_ns_", "re", "App-.*")]
    assert planner.shards_from_filters(f2) is None
    # metric alternation works too
    f3 = [ColumnFilter("_metric_", "re", "cpu|mem"),
          ColumnFilter("_ws_", "eq", "demo"),
          ColumnFilter("_ns_", "eq", "App-0")]
    got3 = planner.shards_from_filters(f3)
    want3 = set()
    for m in ("cpu", "mem"):
        want3.update(query_shards(shard_key_hash(["demo", "App-0"], m),
                                  0, 16))
    assert got3 == sorted(want3)
