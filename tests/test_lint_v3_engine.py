"""graftlint v3 engine-as-assertion tests: the dataflow layer run over
the REAL modules, pinning the wiring the cache/SPMD families verify.

These are regression pins, not fixture games: if someone deletes the
ShardMapper subscription in http/server.py, stops reading the epoch in
the results-cache lookup, or renames a mesh axis, the assertions here
fail with a named path — the same condition the tier-1 lint gate
enforces, but stated directly against the production wiring."""

import os

import pytest

from filodb_tpu.lint import (iter_py_files, load_module, package_root,
                             run_lint)
from filodb_tpu.lint import callgraph as cgmod
from filodb_tpu.lint import dataflow as dfmod
from filodb_tpu.lint import rules_cache, rules_spmd


@pytest.fixture(scope="module")
def df():
    root = package_root()
    files = iter_py_files([os.path.join(root, "filodb_tpu")])
    mods = [m for m in (load_module(p, root=root) for p in files) if m]
    cg = cgmod.build(mods)
    return dfmod.DeviceDataflow(mods, cg), mods


PUB_TOPOLOGY = "filodb_tpu.parallel.shardmapper:ShardMapper.update"
PUB_SCHEMA = ("filodb_tpu.http.server:"
              "FiloHttpServer.invalidate_plan_cache")
HOOK_PLAN = "filodb_tpu.query.plancache:PlanCache.invalidate"
HOOK_RESULTS = "filodb_tpu.query.resultcache:ResultCache.invalidate"


def test_topology_publisher_reaches_both_cache_hooks(df):
    flow, _ = df
    for hook in (HOOK_PLAN, HOOK_RESULTS):
        path = flow.reaches(PUB_TOPOLOGY, hook)
        assert path is not None, \
            f"ShardMapper.update no longer reaches {hook} — the " \
            f"subscription wiring in http/server.py is gone"
    # the path genuinely crosses the listener bridge (publish loop ->
    # registered lambda), not some accidental direct edge
    path = flow.reaches(PUB_TOPOLOGY, HOOK_RESULTS)
    quals = [flow.cg.funcs[k].qualname for k in path]
    assert "ShardMapper._publish" in quals
    assert any("<lambda>" in q or "_bus_publish" in q for q in quals)


def test_schema_publisher_reaches_both_cache_hooks(df):
    flow, _ = df
    for hook in (HOOK_PLAN, HOOK_RESULTS):
        assert flow.reaches(PUB_SCHEMA, hook) is not None


def test_result_cache_lookups_read_every_pull_source(df):
    flow, _ = df
    sources = {
        "watermark": "filodb_tpu.query.resultcache:shards_watermark",
        "coverage": "filodb_tpu.query.resultcache:watermark_coverage",
        "backfill": "filodb_tpu.query.resultcache:shards_epoch",
        "scope": "filodb_tpu.query.resultcache:dispatch_scope",
    }
    for hook in ("filodb_tpu.query.resultcache:ResultCache.begin",
                 "filodb_tpu.query.resultcache:ResultCache.stale_serve"):
        for name, src in sources.items():
            assert flow.reaches(hook, src) is not None, \
                f"{hook} no longer reads the {name} event source"


def test_mesh_spmd_sites_discovered(df):
    flow, _ = df
    mesh_sites = [s for s in flow.sites
                  if s.relpath == "filodb_tpu/parallel/mesh.py"
                  and s.kind == "shard_map"]
    assert len(mesh_sites) >= 3      # _step, _step_topk, check site
    for s in mesh_sites:
        assert flow.site_axes(s) <= {"shard", "time"}
    # the grouped-reduce collective helper runs under shard_map context
    # with the merged axis environment
    gr = "filodb_tpu.parallel.mesh:_grouped_reduce"
    assert gr in flow.spmd_reachable
    assert {"shard", "time"} >= flow.axes_env[gr] >= {"shard"}


def test_mesh_static_propagation(df):
    """`agg` flows into _grouped_reduce from the jit wrapper's
    static_argnames through the shard_map body's closure — which is
    exactly why its `if agg == ...` branches around psum are uniform
    and NOT collective-balance findings."""
    flow, _ = df
    st = flow.param_status.get("filodb_tpu.parallel.mesh:_grouped_reduce",
                               {})
    assert st.get("agg") == "static", st
    assert st.get("local") == "dynamic", st


def test_spmd_and_cache_families_clean_on_real_modules(df):
    flow, mods = df
    assert not [f for _, f in rules_spmd.check_project(mods, df=flow)
                if f.severity == "error"]
    assert not [f for _, f in rules_cache.check_project(mods, df=flow)]


def test_registered_cache_inventory_names(df):
    """The README inventory table and the registry must agree — every
    declared cache the docs promise exists in code."""
    flow, mods = df
    regs, _ = rules_cache._collect_registries(flow.cg, mods)
    names = {r.name for r in regs}
    assert {"plan", "results", "device-tile", "packed-executable",
            "partition-decode", "partition-merge", "mesh-executable",
            "tilestore-executables", "shardstore-executables",
            "sharded-tile-placement"} <= names


# -- the multi-chip serving wiring (PR 14): non-vacuous family pins ----------
#
# graftlint's donation-safety / donation-missing /
# partition-spec-consistency families were error-severity with nothing
# in-tree to police. These assertions pin that the NEW production sites
# — the donated tile-refresh jit and the sharded-evaluator shard_map
# lowerings — are DISCOVERED by the engine on the real modules, so the
# families can never go silently vacuous again.

SHARDSTORE = "filodb_tpu/parallel/shardstore.py"


def test_shardstore_donate_site_discovered(df):
    flow, _ = df
    sites = [s for s in flow.sites if s.relpath == SHARDSTORE
             and s.kind == "jit" and s.donate_nums]
    assert sites, "the donated tile-refresh jit site is gone"
    assert any(s.donate_nums == (0, 1, 2) for s in sites), \
        [s.donate_nums for s in sites]
    # it wraps _append_step (decorator form -> body key resolved)
    assert any("_append_step" in bk for s in sites for bk in s.body_keys)


def test_shardstore_shard_map_sites_discovered_with_positional_axes(df):
    flow, _ = df
    sites = [s for s in flow.sites if s.relpath == SHARDSTORE
             and s.kind == "shard_map"]
    # counter single+batch, grouped, grouped-pair lowerings at least
    assert len(sites) >= 4, [s.line for s in sites]
    for s in sites:
        # positional PartitionSpec indices resolve against the module's
        # ('shard', 'time') mesh order
        assert flow.site_axes(s) <= {"shard", "time"}, \
            (s.line, flow.site_axes(s))
    assert any(sp.pos_entries for s in sites for sp in s.all_specs), \
        "positional spec entries no longer parsed"


def test_shardstore_families_clean_and_nonvacuous(df):
    """The real modules sweep clean — and the SAME engine flags a
    mutated twin of the refresh idiom, so 'clean' is a checked verdict,
    not an unimplemented one."""
    import ast

    flow, mods = df
    spmd = [f for _, f in rules_spmd.check_project(mods, df=flow)
            if f.path == SHARDSTORE]
    assert not spmd, [f"{f.rule}:{f.line}" for f in spmd]
    # mutate: drop the same-statement rebind from the donated call —
    # the donate-of-live-state finding MUST appear
    path = os.path.join(package_root(), SHARDSTORE)
    with open(path) as f:
        src = f.read()
    mutated = src.replace(
        "        self._tsr, self._v, self._cv = _append_step(",
        "        _ignored = _append_step(")
    assert mutated != src
    from filodb_tpu.lint import ModuleSource, _parse_pragmas
    lines = mutated.splitlines()
    mod = ModuleSource(path=path, relpath=SHARDSTORE, source=mutated,
                       tree=ast.parse(mutated), lines=lines,
                       pragmas=_parse_pragmas(lines))
    finds = [f for _, f in rules_spmd.check_project([mod])
             if f.rule == "donation-safety"]
    assert finds, "donation-safety missed the un-rebound refresh twin"


# -- CI wiring: the v3 families flow through --json/--github/--changed-only

SPMD_VIOLATION = """
import functools
import jax
from jax.sharding import Mesh, PartitionSpec as P

mesh = Mesh(jax.devices(), ("shard",))

@functools.partial(jax.shard_map, mesh=mesh, in_specs=(P("shard"),),
                   out_specs=P())
def f(x):
    if jax.process_index() == 0:
        return jax.lax.psum(x, "shard")
    return x
"""


def test_v3_findings_flow_through_json_and_github(tmp_path):
    p = tmp_path / "viol.py"
    p.write_text(SPMD_VIOLATION)
    res = run_lint([str(p)], baseline=frozenset(),
                   check_contracts=False)
    js = res.to_json()
    assert js["exit_code"] == 1
    assert any(f["rule"] == "spmd-collective-balance"
               for f in js["findings"])
    from filodb_tpu.lint.ci_annotations import github_annotations
    lines = github_annotations(js)
    assert any(l.startswith("::error") and "spmd-collective-balance"
               in l for l in lines)


def test_v3_findings_respect_changed_only_scope(tmp_path):
    p = tmp_path / "viol.py"
    p.write_text(SPMD_VIOLATION)
    root = package_root()
    rel = os.path.relpath(str(p), root).replace(os.sep, "/")
    hit = run_lint([str(p)], baseline=frozenset(),
                   check_contracts=False,
                   report_only=frozenset({rel}))
    assert hit.findings
    miss = run_lint([str(p)], baseline=frozenset(),
                    check_contracts=False,
                    report_only=frozenset({"filodb_tpu/other.py"}))
    assert not miss.findings
