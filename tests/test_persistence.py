"""Persistence + restart recovery: flush writes chunks/partkeys/checkpoints
to the ColumnStore; a fresh process bootstraps the index, answers queries
over aged-out ranges via ODP read-through, and reads the recovery watermark
from disk.

(Parity model: CassandraColumnStore.scala:54 write :200 readRawPartitions
:699, CheckpointTable.scala:26, IndexBootstrapper.scala:43,
OnDemandPagingShard.scala:26.)"""

import numpy as np
import pytest

from filodb_tpu.core.memstore import TimeSeriesMemStore, TimeSeriesShard
from filodb_tpu.core.record import RecordBuilder
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetRef
from filodb_tpu.promql.parser import TimeStepParams, parse_query_range
from filodb_tpu.query.engine import QueryEngine
from filodb_tpu.store import FlatFileColumnStore, NullColumnStore

REF = DatasetRef("timeseries")
T0 = 1_600_000_000


def _ingest(shard, n_samples=200, n_series=3, t0_s=T0, offset=-1):
    b = RecordBuilder(DEFAULT_SCHEMAS)
    for s in range(n_series):
        labels = {"_metric_": "disk_io_total", "_ws_": "demo",
                  "_ns_": "App-0", "instance": f"i{s}"}
        for t in range(n_samples):
            b.add_sample("prom-counter", labels, (t0_s + t * 10) * 1000,
                         float((t + 1) * 100 * (s + 1)))
    n = 0
    for c in b.containers():
        n += shard.ingest(c, offset)
    return n


def _query(shard, q="rate(disk_io_total[5m])", start=T0 + 600,
           end=T0 + 1900, step=60):
    plan = parse_query_range(q, TimeStepParams(start, step, end))
    return QueryEngine([shard]).execute(plan)


def test_restart_recovers_index_chunks_and_watermark(tmp_path):
    root = str(tmp_path / "col")
    # -- process 1: ingest, flush with offsets, remember the answer
    cs1 = FlatFileColumnStore(root)
    shard1 = TimeSeriesShard(REF, DEFAULT_SCHEMAS, 0, num_groups=4,
                             max_chunk_rows=64, column_store=cs1)
    _ingest(shard1)
    for g in range(4):
        shard1.flush_group(g, offset=1000 + g)
    want = _query(shard1)
    assert want.num_series == 3 and np.isfinite(want.values).any()

    # -- process 2: fresh store objects over the same directory
    cs2 = FlatFileColumnStore(root)
    shard2 = TimeSeriesShard(REF, DEFAULT_SCHEMAS, 0, num_groups=4,
                             max_chunk_rows=64, column_store=cs2)
    n = shard2.bootstrap_from_store()
    assert n == 3                                # index rebuilt
    assert shard2.checkpoints == {0: 1000, 1: 1001, 2: 1002, 3: 1003}
    assert shard2.recovery_watermark() == 1000   # min over groups, from disk
    got = _query(shard2)                         # pages chunks in via ODP
    assert shard2.stats.partitions_paged_in == 3
    gmap = {k["instance"]: got.values[i] for i, k in enumerate(got.keys)}
    for i, k in enumerate(want.keys):
        np.testing.assert_allclose(gmap[k["instance"]], want.values[i],
                                   rtol=1e-9, equal_nan=True)


def test_eviction_then_odp_readthrough(tmp_path):
    cs = FlatFileColumnStore(str(tmp_path / "col"))
    shard = TimeSeriesShard(REF, DEFAULT_SCHEMAS, 0, num_groups=2,
                            max_chunk_rows=64, column_store=cs)
    _ingest(shard)
    shard.flush_all(offset=5)
    want = _query(shard)
    # age everything out of memory; index entries stay (ODP shells)
    n_ev = shard.evict_partitions(cutoff_ts=(T0 + 10_000) * 1000)
    assert n_ev == 3
    assert all(p.num_chunks == 0 for p in shard.partitions.values())
    got = _query(shard)                          # read-through page-in
    assert shard.stats.partitions_paged_in == 3
    gmap = {k["instance"]: got.values[i] for i, k in enumerate(got.keys)}
    for i, k in enumerate(want.keys):
        np.testing.assert_allclose(gmap[k["instance"]], want.values[i],
                                   rtol=1e-9, equal_nan=True)


def test_ingest_after_bootstrap_continues_series(tmp_path):
    root = str(tmp_path / "col")
    cs1 = FlatFileColumnStore(root)
    shard1 = TimeSeriesShard(REF, DEFAULT_SCHEMAS, 0, column_store=cs1,
                             max_chunk_rows=64)
    _ingest(shard1, n_samples=100)
    shard1.flush_all(offset=1)

    shard2 = TimeSeriesShard(REF, DEFAULT_SCHEMAS, 0,
                             column_store=FlatFileColumnStore(root),
                             max_chunk_rows=64)
    shard2.bootstrap_from_store()
    # ingest the continuation; OOO guard must see the persisted history
    added = _ingest(shard2, n_samples=100, t0_s=T0 + 1000)
    assert added == 300
    dup = _ingest(shard2, n_samples=100)         # replay of old data
    assert dup == 0                              # all dropped as OOO
    res = _query(shard2, start=T0 + 600, end=T0 + 1900)
    assert res.num_series == 3
    assert np.isfinite(res.values).any()


def test_torn_tail_ignored(tmp_path):
    root = str(tmp_path / "col")
    cs = FlatFileColumnStore(root)
    shard = TimeSeriesShard(REF, DEFAULT_SCHEMAS, 0, column_store=cs,
                            max_chunk_rows=64)
    _ingest(shard, n_samples=100)
    shard.flush_all(offset=1)
    # simulate a crash mid-append: truncate the chunk log by a few bytes
    path = cs._chunks_path("timeseries", 0)
    import os
    sz = os.path.getsize(path)
    with open(path, "ab") as f:
        f.truncate(sz - 7)
    cs2 = FlatFileColumnStore(root)
    shard2 = TimeSeriesShard(REF, DEFAULT_SCHEMAS, 0, column_store=cs2,
                             max_chunk_rows=64)
    shard2.bootstrap_from_store()
    res = _query(shard2)                         # must not crash
    assert res.num_series == 3


def test_torn_tail_truncated_before_append(tmp_path):
    """A crash leaving a torn record must not make post-crash appends
    unreachable: the store truncates to the last valid boundary before
    appending (ADVICE r2: silent data loss on append-after-torn-tail)."""
    import os
    root = str(tmp_path / "col")
    cs = FlatFileColumnStore(root)
    shard = TimeSeriesShard(REF, DEFAULT_SCHEMAS, 0, column_store=cs,
                            max_chunk_rows=64)
    _ingest(shard, n_samples=100)
    shard.flush_all(offset=1)
    path = cs._chunks_path("timeseries", 0)
    with open(path, "ab") as f:          # torn record then crash
        f.truncate(os.path.getsize(path) - 7)

    # "restarted process" re-ingests from the watermark and appends
    cs2 = FlatFileColumnStore(root)
    shard2 = TimeSeriesShard(REF, DEFAULT_SCHEMAS, 0, column_store=cs2,
                             max_chunk_rows=64)
    shard2.bootstrap_from_store()
    _ingest(shard2, n_samples=100, t0_s=T0 + 1000)
    shard2.flush_all(offset=2)

    # a third bootstrap must see the post-crash chunks (the appends landed
    # on a valid boundary, not after torn bytes)
    cs3 = FlatFileColumnStore(root)
    shard3 = TimeSeriesShard(REF, DEFAULT_SCHEMAS, 0, column_store=cs3,
                             max_chunk_rows=64)
    shard3.bootstrap_from_store()
    res = _query(shard3, start=T0 + 1200, end=T0 + 1900)
    assert res.num_series == 3
    assert np.isfinite(res.values).any()


def test_duplicate_chunk_appends_dedupe(tmp_path):
    """Crash-replay re-persisting the same chunks must not double samples:
    reads dedupe by chunk_id, last record wins (C* upsert semantics)."""
    root = str(tmp_path / "col")
    cs = FlatFileColumnStore(root)
    shard = TimeSeriesShard(REF, DEFAULT_SCHEMAS, 0, column_store=cs,
                            max_chunk_rows=64)
    _ingest(shard, n_samples=100)
    shard.flush_all(offset=1)
    want = _query(shard)
    # re-append every persisted chunk (simulates replay re-flush)
    for part in shard.partitions.values():
        cs.write_chunks("timeseries", 0, part.part_key.to_bytes(),
                        part.chunks)
    cs2 = FlatFileColumnStore(root)
    shard2 = TimeSeriesShard(REF, DEFAULT_SCHEMAS, 0, column_store=cs2,
                             max_chunk_rows=64)
    shard2.bootstrap_from_store()
    got = _query(shard2)
    assert got.num_series == 3
    for part in shard2.partitions.values():
        n_rows = sum(c.num_rows for c in part.chunks)
        assert n_rows == 100                     # not doubled
    gmap = {k["instance"]: got.values[i] for i, k in enumerate(got.keys)}
    for i, k in enumerate(want.keys):
        np.testing.assert_allclose(gmap[k["instance"]], want.values[i],
                                   rtol=1e-9, equal_nan=True)


def test_null_column_store_is_noop():
    shard = TimeSeriesShard(REF, DEFAULT_SCHEMAS, 0,
                            column_store=NullColumnStore())
    _ingest(shard, n_samples=50)
    shard.flush_all(offset=9)
    assert shard.bootstrap_from_store() == 0
    assert _query(shard).num_series == 3


def test_filoserver_restart_e2e(tmp_path):
    import json
    import urllib.request

    from filodb_tpu.standalone.server import FiloServer

    root = str(tmp_path / "data")
    cfg = {"dataset": "timeseries", "num-shards": 2, "port": 0,
           "data-dir": root}
    srv1 = FiloServer(dict(cfg)).start()
    shard0 = srv1.store.get_shard(DatasetRef("timeseries"), 0)
    _ingest(shard0)
    srv1.store.flush_all(DatasetRef("timeseries"))
    url = (f"/promql/timeseries/api/v1/query_range?"
           f"query=rate(disk_io_total%5B5m%5D)&start={T0+600}"
           f"&end={T0+1900}&step=60")
    r1 = json.load(urllib.request.urlopen(
        f"http://127.0.0.1:{srv1.port}{url}"))
    srv1.stop()

    srv2 = FiloServer(dict(cfg)).start()          # "new process"
    r2 = json.load(urllib.request.urlopen(
        f"http://127.0.0.1:{srv2.port}{url}"))
    srv2.stop()
    assert r1["data"]["result"], r1
    assert sorted(json.dumps(s, sort_keys=True)
                  for s in r2["data"]["result"]) == \
        sorted(json.dumps(s, sort_keys=True) for s in r1["data"]["result"])
