"""Pallas window-extract kernel parity tests (interpret mode on CPU).

Brute-force oracle over random ragged series incl. duplicate timestamps,
boundary-coincident samples and empty windows."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from filodb_tpu.query.pallas_kernels import (TR_PAD, combine3, split3,
                                             window_extract)


def _oracle(ts, vals, lens, step, window, T):
    S = ts.shape[0]
    cnt = np.zeros((S, T), np.int64)
    tlo = np.zeros((S, T), np.int64)
    thi = np.zeros((S, T), np.int64)
    vlo = np.zeros((S, T))
    vhi = np.zeros((S, T))
    for s in range(S):
        r_ts, r_v = ts[s, :lens[s]], vals[s, :lens[s]]
        for t in range(T):
            m = (r_ts >= t * step) & (r_ts <= t * step + window)
            cnt[s, t] = m.sum()
            if cnt[s, t]:
                i0 = np.argmax(m)
                i1 = len(m) - 1 - np.argmax(m[::-1])
                tlo[s, t], thi[s, t] = r_ts[i0], r_ts[i1]
                vlo[s, t], vhi[s, t] = r_v[i0], r_v[i1]
    return cnt, tlo, thi, vlo, vhi


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_window_extract_matches_bruteforce(seed):
    rng = np.random.default_rng(seed)
    S = int(rng.integers(1, 12))
    N = int(rng.integers(2, 150))
    T = int(rng.integers(1, 80))
    step = int(rng.integers(1_000, 120_000))
    window = int(rng.integers(1_000, 600_000))
    ts = np.sort(rng.integers(0, 3_000_000, (S, N))).astype(np.int64)
    lens = rng.integers(1, N + 1, S)
    vals = rng.normal(1e6, 1.0, (S, N))   # large offset stresses split3
    tr = ts.astype(np.int32)
    for i, n in enumerate(lens):
        tr[i, n:] = TR_PAD
    masked = np.where(np.arange(N)[None, :] < lens[:, None], vals, 0.0)
    pay = split3(jnp.asarray(masked)).astype(jnp.float32)
    cnt, tlo, thi, plo, phi = window_extract(
        jnp.asarray(tr), pay, step, window, T, interpret=True)
    v_lo = np.asarray(combine3(plo))
    v_hi = np.asarray(combine3(phi))
    ocnt, otlo, othi, ovlo, ovhi = _oracle(ts, vals, lens, step, window, T)
    np.testing.assert_array_equal(np.asarray(cnt), ocnt)
    has = ocnt >= 1
    np.testing.assert_array_equal(np.asarray(tlo)[has], otlo[has])
    np.testing.assert_array_equal(np.asarray(thi)[has], othi[has])
    # triple-f32 extraction must be bit-exact
    np.testing.assert_array_equal(v_lo[has], ovlo[has])
    np.testing.assert_array_equal(v_hi[has], ovhi[has])


def test_split3_exact_roundtrip():
    rng = np.random.default_rng(3)
    v = rng.normal(0, 1e12, (4, 64)) + rng.normal(0, 1e-6, (4, 64))
    s = split3(jnp.asarray(v))
    back = np.asarray(s[:, 0, :].astype(np.float64)
                      + s[:, 1, :].astype(np.float64)
                      + s[:, 2, :].astype(np.float64))
    np.testing.assert_array_equal(back, v)
