"""Cross-cluster federation + HA buddy planner units.

(MultiPartitionPlanner.scala:53 / SinglePartitionPlanner.scala:17 —
route a query to the cluster owning its workspace partition;
HighAvailabilityPlanner.scala:31 — DOWN shards served from the buddy.)
"""

import numpy as np
import pytest

from filodb_tpu.gateway.producer import (TestTimeseriesProducer,
                                         ingest_builders)
from filodb_tpu.promql.parser import TimeStepParams, parse_query_range
from filodb_tpu.query.engine import QueryEngine
from filodb_tpu.standalone.server import FiloServer

T0 = 1_600_000_000


@pytest.fixture
def two_clusters():
    """Cluster B owns workspace 'prod'; cluster A owns 'demo' and
    federates prod queries to B."""
    from filodb_tpu.core.schemas import DEFAULT_SCHEMAS
    b = FiloServer({"num-shards": 2, "port": 0,
                    "query-sample-limit": 0,
                    "query-series-limit": 0}).start()
    producer = TestTimeseriesProducer(DEFAULT_SCHEMAS, num_shards=2,
                                      ws="prod")
    ingest_builders(b.store, b.ref,
                    producer.counters(T0 * 1000, 60, 3))
    b.store.flush_all(b.ref)
    a = FiloServer({"num-shards": 2, "port": 0,
                    "partitions": {"prod": f"http://127.0.0.1:{b.port}"},
                    "query-sample-limit": 0,
                    "query-series-limit": 0}).start()
    a.seed_dev_data(n_samples=60, n_instances=2, start_ms=T0 * 1000)
    yield a, b
    a.stop()
    b.stop()


def test_partition_routing_forwards_whole_query(two_clusters):
    import json
    import urllib.parse
    import urllib.request
    a, b = two_clusters
    q = urllib.parse.quote(
        'sum(rate(http_requests_total{_ws_="prod"}[5m]))')
    url = (f"http://127.0.0.1:{a.port}/promql/timeseries/api/v1/"
           f"query_range?query={q}&start={T0 + 300}&end={T0 + 500}"
           f"&step=60")
    body = json.loads(urllib.request.urlopen(url, timeout=60).read())
    assert body["status"] == "success"
    got = body["data"]["result"]
    assert len(got) == 1 and got[0]["values"]
    # parity with asking cluster B directly
    plan = parse_query_range('sum(rate(http_requests_total[5m]))',
                             TimeStepParams(T0 + 300, 60, T0 + 500))
    want = QueryEngine(b.store.shards(b.ref)).execute(plan)
    got_vals = {int(float(t)): float(v) for t, v in got[0]["values"]}
    for i, step in enumerate(want.steps // 1000):
        if np.isfinite(want.values[0][i]):
            np.testing.assert_allclose(got_vals[int(step)],
                                       want.values[0][i], rtol=1e-5)


def test_local_partition_stays_local(two_clusters):
    a, b = two_clusters
    from filodb_tpu.parallel.cluster import PromQlRemoteExec
    from filodb_tpu.query.planner import QueryPlanner
    planner = QueryPlanner(
        a.store.shards(a.ref), shard_mapper=a.mapper,
        partitions={"prod": f"http://127.0.0.1:{b.port}"})
    tsp = TimeStepParams(T0 + 300, 60, T0 + 500)
    local = parse_query_range('rate(http_requests_total{_ws_="demo"}[5m])',
                              tsp)
    assert not isinstance(planner.materialize(local), PromQlRemoteExec)
    remote = parse_query_range(
        'rate(http_requests_total{_ws_="prod"}[5m])', tsp)
    assert isinstance(planner.materialize(remote), PromQlRemoteExec)
    # a federation map naming OUR OWN workspace must not self-forward
    planner_self = QueryPlanner(
        a.store.shards(a.ref), shard_mapper=a.mapper,
        partitions={"demo": f"http://127.0.0.1:{a.port}"},
        local_partitions=["demo"])
    assert not isinstance(planner_self.materialize(local),
                          PromQlRemoteExec)
    # cross-partition joins stay local (leaf fetch semantics preserved)
    mixed = parse_query_range(
        '(rate(http_requests_total{_ws_="demo"}[5m])) + '
        '(rate(http_requests_total{_ws_="prod"}[5m]))', tsp)
    assert not isinstance(planner.materialize(mixed), PromQlRemoteExec)
