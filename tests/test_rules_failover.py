"""Single-owner rule scheduling under the worker supervisor: the rules
config propagates to every worker, exactly ONE worker evaluates
(lowest alive announced ordinal), and killing the evaluator mid-run
re-elects via the bus worker-exit event with no missed and no
duplicated tick — then the respawned ordinal 0 reclaims evaluation in
one worker-up beat.
"""

import json
import os
import select
import signal
import subprocess
import sys
import time
import urllib.parse
import urllib.request

import pytest

from filodb_tpu.rules import RULES_DATASET
from filodb_tpu.standalone.supervisor import worker_config

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the recorded value is the evaluation timestamp itself: a REAL tick's
# sample satisfies value == timestamp, while PromQL's lookback
# forward-fill (which repeats the last sample across later grid steps)
# does not — the exactly-once audit below keys on that
RULES_CFG = {"groups": [{
    "name": "fo", "interval": "1s", "rules": [
        {"record": "fo:tick:value", "expr": "time()"}]}]}


def test_worker_config_propagates_rules():
    """Satellite pin: the supervisor-derived worker configs carry the
    rules config verbatim (every worker loads it; election decides who
    evaluates)."""
    base = {"num-shards": 4, "rules": RULES_CFG,
            "rules-eval-span-steps": 4,
            "rules-webhook-url": "http://127.0.0.1:1/hook",
            "max-inflight-queries": 8}
    for ordinal in (0, 1):
        cfg = worker_config(base, ordinal, 2, [1001, 1002], 9000, 9100)
        assert cfg["rules"] == RULES_CFG
        assert cfg["rules-eval-span-steps"] == 4
        assert cfg["rules-webhook-url"] == "http://127.0.0.1:1/hook"
        assert cfg["worker-id"] == ordinal
        assert cfg["num-nodes"] == 2


def _get(port, path, **params):
    qs = urllib.parse.urlencode(params, doseq=True)
    url = f"http://127.0.0.1:{port}{path}" + (f"?{qs}" if qs else "")
    with urllib.request.urlopen(url, timeout=30) as r:
        return json.loads(r.read())


def _poll(fn, timeout=180.0, interval=0.1, msg="condition"):
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        try:
            ok, last = fn()
        except (OSError, ValueError) as e:
            ok, last = False, repr(e)
        if ok:
            return last
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}: {last!r}")


def _recorded_ts(port):
    """The ACTUAL recorded tick boundaries on one worker's private
    port (its own __rules__ shard): grid points where the recorded
    value equals the grid timestamp are real samples; lookback-filled
    points repeat an older value and are filtered out."""
    now = int(time.time())
    out = _get(port, f"/promql/{RULES_DATASET}/api/v1/query_range",
               query="fo:tick:value", start=now - 300, end=now + 2,
               step=1)
    return [int(float(t))
            for r in out["data"]["result"] for t, v in r["values"]
            if int(float(t)) == int(round(float(v)))]


def test_kill_evaluator_no_missed_or_duplicated_tick(tmp_path):
    cfg = {
        "num-shards": 4, "port": 0,
        "serving-workers": 2,
        "supervisor-port": 0,
        "run-dir": str(tmp_path / "run"),
        "monitor-interval-s": 0.1,
        # hold the respawn back so the stand-in's takeover window
        # spans several 1s boundaries (a warm dev rig restarts a
        # worker in under a second otherwise; backoff counts from the
        # last SPAWN, so a cold boot eats into it — but a cold reboot
        # is itself slow enough to leave a window)
        "restart-backoff-s": 12.0,
        "grpc-port": None,
        "failure-detect-interval-s": 300.0,
        "max-inflight-queries": 8,
        "rules": RULES_CFG,
    }
    cfg_path = tmp_path / "sup.json"
    cfg_path.write_text(json.dumps(cfg))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen(
        [sys.executable, "-m", "filodb_tpu.standalone.supervisor",
         "--config", str(cfg_path)],
        cwd=REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL)
    try:
        buf = b""
        deadline = time.monotonic() + 240
        while time.monotonic() < deadline and b"\n" not in buf:
            r, _, _ = select.select([proc.stdout], [], [], 1.0)
            if r:
                ch = proc.stdout.read1(4096)
                if not ch:
                    raise RuntimeError("supervisor died during startup")
                buf += ch
        line = json.loads(buf.split(b"\n", 1)[0])
        sup_port = line["supervisor_port"]
        ports = {w["ordinal"]: w["port"] for w in line["workers"]}

        # worker 0 is the announced evaluator; worker 1 stands by
        def _w0_evaluating():
            out = _get(ports[0], "/api/v1/rules", __local__=1)
            return out["data"].get("evaluating") is True, out["data"]
        _poll(_w0_evaluating, msg="worker 0 elected")
        out1 = _get(ports[1], "/api/v1/rules", __local__=1)
        assert out1["data"]["evaluating"] is False

        # the stand-by worker PROXIES /api/v1/rules to the evaluator,
        # so the public surface answers authoritatively from any worker
        proxied = _get(ports[1], "/api/v1/rules")
        assert proxied["data"]["evaluating"] is True
        assert proxied["data"]["worker"] == 0

        # wait for a few ticks, then kill RIGHT AFTER a fresh tick
        # lands (maximum distance to the next boundary)
        def _ticks(n):
            def check():
                ts = _recorded_ts(ports[0])
                return len(ts) >= n, len(ts)
            return check
        _poll(_ticks(3), msg="pre-kill ticks")
        n0 = len(_recorded_ts(ports[0]))
        _poll(_ticks(n0 + 1), msg="fresh tick before kill")

        health = _get(sup_port, "/__health")
        victim_pid = health["workers"]["0"]["pid"]
        restarts0 = health["workers"]["0"]["restarts"]
        os.kill(victim_pid, signal.SIGKILL)
        t_kill = time.time()

        # worker 1 takes over via the bus worker-exit event and keeps
        # the recorded series advancing
        def _w1_took_over():
            out = _get(ports[1], "/api/v1/rules", __local__=1)
            ts = _recorded_ts(ports[1])
            return (out["data"].get("evaluating") is True
                    and any(t >= t_kill for t in ts)), \
                (out["data"].get("evaluating"), len(ts))
        _poll(_w1_took_over, timeout=60, msg="worker 1 takeover")

        # the supervisor respawns worker 0; its worker-up broadcast
        # makes worker 1 step down and worker 0 reclaim in one beat
        def _respawned():
            h = _get(sup_port, "/__health")["workers"]["0"]
            return (h["restarts"] > restarts0 and h["alive"]
                    and h["ready"]), h
        _poll(_respawned, timeout=240, msg="worker 0 respawn")

        def _reclaimed():
            out0 = _get(ports[0], "/api/v1/rules", __local__=1)
            out1 = _get(ports[1], "/api/v1/rules", __local__=1)
            return (out0["data"].get("evaluating") is True
                    and out1["data"].get("evaluating") is False), \
                (out0["data"].get("evaluating"),
                 out1["data"].get("evaluating"))
        _poll(_reclaimed, timeout=60, msg="worker 0 reclaim")

        # let the reclaimed evaluator run a few boundaries, then audit
        time.sleep(3.5)

        # -- the exactly-once audit ------------------------------------
        # every recorded sample's timestamp is its interval boundary;
        # union the two workers' shards: a duplicated tick would show
        # the same boundary on BOTH workers, a missed tick a hole in
        # the contiguous boundary walk
        ts0 = _recorded_ts(ports[0])
        ts1 = _recorded_ts(ports[1])
        dup = set(ts0) & set(ts1)
        assert not dup, f"duplicated ticks (both workers wrote): {dup}"
        union = sorted(set(ts0) | set(ts1))
        assert len(union) >= 8
        holes = [t for t in range(union[0], union[-1] + 1)
                 if t not in union]
        assert not holes, (
            f"missed ticks {holes} (worker0={sorted(ts0)}, "
            f"worker1={sorted(ts1)})")
        # both sides actually contributed (the failover really ran)
        assert ts0 and ts1
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=30)
