"""End-to-end query engine tests: ingest synthetic series, run PromQL,
verify numerics (parity model: query/src/test WindowIteratorSpec,
AggrOverRangeVectorsSpec, BinaryJoinExecSpec)."""

import numpy as np
import pytest

from filodb_tpu.core.memstore import TimeSeriesShard
from filodb_tpu.core.record import RecordBuilder
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetRef
from filodb_tpu.memory.histogram import CustomBuckets
from filodb_tpu.promql.parser import TimeStepParams, parse_query_range
from filodb_tpu.query.engine import QueryEngine
from filodb_tpu.query.model import GridResult

REF = DatasetRef("timeseries")

T0 = 1_600_000_000  # seconds


def make_shard(max_chunk_rows=100):
    return TimeSeriesShard(REF, DEFAULT_SCHEMAS, 0,
                           max_chunk_rows=max_chunk_rows)


def ingest_counters(shard, n_series=4, n_samples=360, step_s=10,
                    rate_per_s=10.0):
    """Counters increasing by rate_per_s * step per sample."""
    b = RecordBuilder(DEFAULT_SCHEMAS)
    for s in range(n_series):
        labels = {"_metric_": "http_requests_total", "_ws_": "demo",
                  "_ns_": "App-0", "job": "api", "instance": f"i{s}"}
        v = 0.0
        for t in range(n_samples):
            v += rate_per_s * step_s * (s + 1)
            b.add_sample("prom-counter", labels,
                         (T0 + t * step_s) * 1000, v)
    for c in b.containers():
        shard.ingest(c)
    shard.flush_all()


def ingest_gauges(shard, series_vals, metric="cpu_usage", n_samples=100,
                  step_s=10):
    b = RecordBuilder(DEFAULT_SCHEMAS)
    for labels_extra, base in series_vals:
        labels = {"_metric_": metric, "_ws_": "demo", "_ns_": "App-0",
                  **labels_extra}
        for t in range(n_samples):
            b.add_sample("gauge", labels, (T0 + t * step_s) * 1000,
                         base + t)
    for c in b.containers():
        shard.ingest(c)
    shard.flush_all()


def run(shard, promql, start=None, step=60, end=None):
    start = start if start is not None else T0 + 600
    end = end if end is not None else T0 + 3000
    plan = parse_query_range(promql, TimeStepParams(start, step, end))
    return QueryEngine([shard]).execute(plan)


def test_rate_basic():
    shard = make_shard()
    ingest_counters(shard, n_series=2)
    res = run(shard, "rate(http_requests_total[5m])")
    assert isinstance(res, GridResult)
    assert res.num_series == 2
    # steady counters: rate == per-second increase; series s increases at
    # 10*(s+1)/s per sample of 10s => rate = 10*(s+1)
    by_instance = {k["instance"]: res.values[i]
                   for i, k in enumerate(res.keys)}
    np.testing.assert_allclose(by_instance["i0"], 10.0, rtol=1e-9)
    np.testing.assert_allclose(by_instance["i1"], 20.0, rtol=1e-9)


def test_sum_rate_by_job():
    shard = make_shard()
    ingest_counters(shard, n_series=4)
    res = run(shard, "sum(rate(http_requests_total[5m])) by (job)")
    assert res.num_series == 1
    assert res.keys[0] == {"job": "api"}
    # sum over 4 series: 10*(1+2+3+4) = 100
    np.testing.assert_allclose(res.values[0], 100.0, rtol=1e-9)


def test_increase():
    shard = make_shard()
    ingest_counters(shard, n_series=1)
    res = run(shard, "increase(http_requests_total[5m])")
    np.testing.assert_allclose(res.values[0], 10.0 * 300, rtol=1e-9)


def test_instant_selector_lookback():
    shard = make_shard()
    ingest_gauges(shard, [({"host": "a"}, 100.0)])
    res = run(shard, "cpu_usage")
    assert res.num_series == 1
    # at T0+600 (sample index 60), value = 100 + 60
    assert res.values[0][0] == pytest.approx(160.0)


def test_gauge_avg_and_max_over_time():
    shard = make_shard()
    ingest_gauges(shard, [({"host": "a"}, 0.0)])
    res = run(shard, "max_over_time(cpu_usage[5m])",
              start=T0 + 600, step=300, end=T0 + 900)
    # window [T0+300, T0+600]: samples 30..60 -> max 60
    assert res.values[0][0] == pytest.approx(60.0)
    res2 = run(shard, "avg_over_time(cpu_usage[5m])",
               start=T0 + 600, step=300, end=T0 + 900)
    assert res2.values[0][0] == pytest.approx(np.mean(np.arange(30, 61)))


def test_binary_join_one_to_one():
    shard = make_shard()
    ingest_gauges(shard, [({"host": "a"}, 100.0), ({"host": "b"}, 200.0)],
                  metric="mem_used")
    ingest_gauges(shard, [({"host": "a"}, 1000.0), ({"host": "b"}, 2000.0)],
                  metric="mem_total")
    res = run(shard, "mem_used / mem_total")
    assert res.num_series == 2
    by_host = {k["host"]: res.values[i] for i, k in enumerate(res.keys)}
    # ratio at step 0 (sample 60): (100+60)/(1000+60)
    assert by_host["a"][0] == pytest.approx(160.0 / 1060.0)
    assert by_host["b"][0] == pytest.approx(260.0 / 2060.0)
    # metric label dropped
    assert all("_metric_" not in k for k in res.keys)


def test_scalar_ops_and_comparison_filter():
    shard = make_shard()
    ingest_gauges(shard, [({"host": "a"}, 0.0), ({"host": "b"}, 1000.0)])
    res = run(shard, "cpu_usage > 500")
    by_host = {k["host"]: res.values[i] for i, k in enumerate(res.keys)}
    assert np.isnan(by_host["a"][0])
    assert by_host["b"][0] == pytest.approx(1060.0)
    res2 = run(shard, "cpu_usage * 2 + 1")
    by_host2 = {k["host"]: res2.values[i] for i, k in enumerate(res2.keys)}
    assert by_host2["a"][0] == pytest.approx(60.0 * 2 + 1)


def test_topk():
    shard = make_shard()
    ingest_gauges(shard, [({"host": "a"}, 0.0), ({"host": "b"}, 100.0),
                          ({"host": "c"}, 200.0)])
    res = run(shard, "topk(2, cpu_usage)")
    hosts = {k["host"] for k in res.keys}
    assert hosts == {"b", "c"}


def test_absent():
    shard = make_shard()
    ingest_gauges(shard, [({"host": "a"}, 0.0)])
    res = run(shard, 'absent(nonexistent_metric{job="x"})')
    assert res.num_series == 1
    assert res.keys[0] == {"job": "x"}
    assert np.all(res.values[0] == 1.0)
    # over a range where the series has data at every step -> empty result
    res2 = run(shard, "absent(cpu_usage)", start=T0 + 600, end=T0 + 900)
    assert res2.num_series == 0


def test_histogram_quantile_pipeline():
    shard = make_shard()
    scheme = CustomBuckets((0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                            float("inf")))
    b = RecordBuilder(DEFAULT_SCHEMAS)
    labels = {"_metric_": "http_req_latency", "_ws_": "demo",
              "_ns_": "App-0", "job": "api"}
    counts = np.zeros(8, dtype=np.int64)
    incr = np.array([1, 2, 4, 8, 12, 14, 15, 16])
    for t in range(360):
        counts = counts + incr
        b.add_sample("prom-histogram", labels,
                     (T0 + t * 10) * 1000, 0.0, float(counts[-1]),
                     (scheme, counts.copy()))
    for c in b.containers():
        shard.ingest(c)
    shard.flush_all()
    res = run(shard, "histogram_quantile(0.5, rate(http_req_latency[5m]))")
    assert res.num_series == 1
    # rate per bucket is proportional to incr; median rank=8 falls in bucket
    # with cumulative >= 8 -> le=0.25 bucket (cum 8); interpolation between
    # 0.1 (cum 4) and 0.25: 0.1 + 0.15*(8-4)/(8-4)... compute expected:
    rate = incr / 10.0
    total = rate[-1]
    rank = 0.5 * total
    from filodb_tpu.memory.histogram import quantile
    expected = quantile(0.5, np.array(scheme.le_values), rate.astype(float))
    np.testing.assert_allclose(res.values[0], expected, rtol=1e-9)


def test_subquery_max_of_rate():
    shard = make_shard()
    ingest_counters(shard, n_series=1)
    res = run(shard, "max_over_time(rate(http_requests_total[5m])[10m:1m])")
    np.testing.assert_allclose(res.values[0], 10.0, rtol=1e-9)


def test_subquery_with_offset_covers_window():
    """min/avg over an offset subquery must see the FULL window: the
    inner grid extends to start - offset - window (a truncated inner
    grid silently shrank early windows)."""
    shard = make_shard()
    ingest_gauges(shard, [({}, 0.0)], n_samples=300)
    res = run(shard, "avg_over_time(cpu_usage[10m:1m] offset 20m)")
    plain = run(shard, "avg_over_time(cpu_usage[10m:1m])",
                start=T0 + 600 - 1200, step=60, end=T0 + 3000 - 1200)
    np.testing.assert_allclose(res.values[0], plain.values[0], rtol=1e-9,
                               equal_nan=True)


def test_subquery_at_pinned():
    """expr[w:s] @ t pins the subquery grid; every outer step carries the
    pinned value (LogicalPlan.scala:349, ast/SubqueryUtils)."""
    shard = make_shard()
    # gauge rising by 1 per 10s: avg over a pinned 10m window is a fixed
    # number regardless of the outer step
    ingest_gauges(shard, [({}, 0.0)], n_samples=300)
    pin = T0 + 2000
    res = run(shard, f"avg_over_time(cpu_usage[10m:1m] @ {pin}.0)")
    assert res.values.shape[1] > 1
    assert np.allclose(res.values[0], res.values[0][0])
    # oracle: unpinned instant evaluation at the pin time
    one = run(shard, "avg_over_time(cpu_usage[10m:1m])",
              start=pin, step=60, end=pin)
    np.testing.assert_allclose(res.values[0][0], one.values[0][0],
                               rtol=1e-9)
    # @ end() == pinning to the query range end
    res2 = run(shard, "avg_over_time(cpu_usage[10m:1m] @ end())")
    one2 = run(shard, "avg_over_time(cpu_usage[10m:1m])",
               start=T0 + 3000, step=60, end=T0 + 3000)
    np.testing.assert_allclose(res2.values[0][0], one2.values[0][0],
                               rtol=1e-9)


def test_label_replace_e2e():
    shard = make_shard()
    ingest_gauges(shard, [({"host": "node-7"}, 0.0)])
    res = run(shard,
              'label_replace(cpu_usage, "node_id", "$1", "host", '
              '"node-(.*)")')
    assert res.keys[0]["node_id"] == "7"


def test_vector_and_scalar_functions():
    shard = make_shard()
    res = run(shard, "vector(42)")
    assert res.num_series == 1
    assert np.all(res.values[0] == 42.0)
    ingest_gauges(shard, [({"host": "a"}, 100.0)])
    res2 = run(shard, "scalar(cpu_usage) * 2")
    from filodb_tpu.query.model import ScalarResult
    assert isinstance(res2, ScalarResult)
    assert res2.values[0] == pytest.approx(320.0)


def test_offset_query():
    shard = make_shard()
    ingest_gauges(shard, [({"host": "a"}, 0.0)])
    res = run(shard, "cpu_usage offset 5m")
    # value at T0+600 with 5m offset = sample at T0+300 = 30
    assert res.values[0][0] == pytest.approx(30.0)


def test_stale_nan_excluded_from_rate():
    shard = make_shard()
    b = RecordBuilder(DEFAULT_SCHEMAS)
    labels = {"_metric_": "c_total", "_ws_": "w", "_ns_": "n"}
    v = 0.0
    for t in range(100):
        v += 100.0
        val = np.nan if t == 50 else v
        b.add_sample("prom-counter", labels, (T0 + t * 10) * 1000, val)
    for c in b.containers():
        shard.ingest(c)
    shard.flush_all()
    res = run(shard, "rate(c_total[5m])", start=T0 + 600, step=60,
              end=T0 + 900)
    assert np.all(np.isfinite(res.values[0]))
    np.testing.assert_allclose(res.values[0], 10.0, rtol=1e-6)


def test_and_or_unless():
    shard = make_shard()
    ingest_gauges(shard, [({"host": "a"}, 0.0), ({"host": "b"}, 1000.0)],
                  metric="m1")
    ingest_gauges(shard, [({"host": "a"}, 5.0)], metric="m2")
    res = run(shard, "m1 and m2")
    assert {k["host"] for k in res.keys} == {"a"}
    res = run(shard, "m1 unless m2")
    finite = [k["host"] for i, k in enumerate(res.keys)
              if np.isfinite(res.values[i]).any()]
    assert finite == ["b"]
    res = run(shard, "m2 or m1")
    assert {k["host"] for k in res.keys} == {"a", "b"}


def test_binary_join_group_left_noncommutative():
    """many OP one keeps operand order (BinaryJoinExecSpec group_left).

    Values at step 0 (60s into the data): many m{mode}=3, one o=2."""
    shard = make_shard()
    ingest_gauges(shard, [({"job": "api", "mode": "r"}, -57.0),
                          ({"job": "api", "mode": "w"}, -57.0)], metric="m")
    ingest_gauges(shard, [({"job": "api"}, -58.0)], metric="o")
    for op, want in [("-", 1.0), ("/", 1.5), ("^", 9.0), ("%", 1.0)]:
        res = run(shard, f"m {op} on (job) group_left o")
        assert res.num_series == 2, op
        for i in range(2):
            assert res.values[i][0] == pytest.approx(want), op
        assert {k["mode"] for k in res.keys} == {"r", "w"}


def test_binary_join_group_right_noncommutative():
    """one OP many must compute one/many, not many/one (the round-1 bug:
    reference BinaryJoinExec.scala:58 one-to-many semantics)."""
    shard = make_shard()
    ingest_gauges(shard, [({"job": "api", "mode": "r"}, -57.0),
                          ({"job": "api", "mode": "w"}, -57.0)], metric="m")
    ingest_gauges(shard, [({"job": "api"}, -58.0)], metric="o")
    for op, want in [("-", -1.0), ("/", 2.0 / 3.0), ("^", 8.0),
                     ("%", 2.0)]:
        res = run(shard, f"o {op} on (job) group_right m")
        assert res.num_series == 2, op
        for i in range(2):
            assert res.values[i][0] == pytest.approx(want), op
        # output labels come from the many (rhs) side
        assert {k["mode"] for k in res.keys} == {"r", "w"}


def test_binary_join_group_left_include_labels():
    shard = make_shard()
    ingest_gauges(shard, [({"job": "api", "mode": "r"}, 0.0)], metric="m")
    ingest_gauges(shard, [({"job": "api", "version": "v9"}, 1.0)],
                  metric="o")
    res = run(shard, "m * on (job) group_left (version) o")
    assert res.num_series == 1
    assert res.keys[0].get("version") == "v9"


def test_labels_api_match_union():
    """labels/label-values union across multiple match[] selectors
    (PrometheusApiRoute semantics; round-1 only honored matches[0])."""
    import json
    import urllib.request

    from filodb_tpu.http.server import FiloHttpServer
    from filodb_tpu.query.tpu import TpuBackend

    shard = make_shard()
    ingest_gauges(shard, [({"host": "a"}, 1.0)], metric="m1")
    ingest_gauges(shard, [({"zone": "z"}, 1.0)], metric="m2")
    srv = FiloHttpServer({"timeseries": [shard]}, backend=None, port=0)
    srv.start()
    try:
        base = f"http://127.0.0.1:{srv.port}/promql/timeseries/api/v1"
        q = "match%5B%5D=m1&match%5B%5D=m2"
        labels = json.load(urllib.request.urlopen(f"{base}/labels?{q}"))
        assert "host" in labels["data"] and "zone" in labels["data"]
        vals = json.load(urllib.request.urlopen(
            f"{base}/label/_metric_/values?{q}"))
        assert set(vals["data"]) >= {"m1", "m2"}
        series = json.load(urllib.request.urlopen(
            f"{base}/series?match%5B%5D=m1&match%5B%5D=m1"))
        assert len(series["data"]) == 1  # deduped across selectors
    finally:
        srv.stop()
