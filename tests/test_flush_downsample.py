"""Flush-time downsample emission + memory-pressure headroom eviction.

(ShardDownsampler.scala:40,62 populateDownsampleRecords;
PartitionEvictionPolicy / headroom task equivalents.)
"""

import numpy as np

from filodb_tpu.core.memstore import TimeSeriesShard
from filodb_tpu.core.record import RecordBuilder
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetRef
from filodb_tpu.downsample import DownsampledTimeSeriesStore
from filodb_tpu.downsample.flush import FlushDownsampler
from filodb_tpu.promql.parser import TimeStepParams, parse_query_range
from filodb_tpu.query.engine import QueryEngine
from filodb_tpu.store import FlatFileColumnStore

REF = DatasetRef("timeseries")
RES = 300_000
T0 = (1_600_000_000_000 // RES) * RES
OFF = 5_000


def _seed(shard, n=720):
    b = RecordBuilder(DEFAULT_SCHEMAS)
    for s in range(3):
        g = {"_metric_": "cpu", "_ws_": "demo", "_ns_": "App-0",
             "instance": f"i{s}"}
        c = {"_metric_": "reqs_total", "_ws_": "demo", "_ns_": "App-0",
             "instance": f"i{s}"}
        for t in range(n):
            ts = T0 + OFF + t * 10_000
            b.add_sample("gauge", g, ts, 50.0 + s + np.sin(t / 9.0) * 20)
            b.add_sample("prom-counter", c, ts, float((t + 1) * (s + 1)))
    for cont in b.containers():
        shard.ingest(cont)


def test_flush_emission_serves_ds_queries(tmp_path):
    cs = FlatFileColumnStore(str(tmp_path / "col"))
    shard = TimeSeriesShard(REF, DEFAULT_SCHEMAS, 0, column_store=cs,
                            max_chunk_rows=120)
    shard.flush_downsampler = FlushDownsampler(
        cs, "timeseries", 0, DEFAULT_SCHEMAS, resolutions=(RES,))
    _seed(shard)
    shard.flush_all(offset=1)
    assert shard.flush_downsampler.samples_emitted > 0

    # ds tier is immediately queryable WITHOUT running the batch job
    dstore = DownsampledTimeSeriesStore(cs, "timeseries", 1,
                                        resolutions=(RES,))
    tsp = TimeStepParams(T0 // 1000 + 1800, 600, T0 // 1000 + 7000)
    for q, rtol in [("min_over_time(cpu[10m])", 0.0),
                    ("sum_over_time(cpu[10m])", 0.0),
                    ("increase(reqs_total[10m])", 0.05)]:
        plan = parse_query_range(q, tsp)
        picked = dstore.plan_query(plan, 600_000, 600_000)
        assert picked is not None, q
        ds_shards, ds_plan = picked
        got = QueryEngine(ds_shards).execute(ds_plan)
        want = QueryEngine([shard]).execute(plan)
        gmap = {k["instance"]: got.values[i]
                for i, k in enumerate(got.keys)}
        assert len(gmap) == want.num_series, q
        for i, k in enumerate(want.keys):
            g, w = gmap[k["instance"]], want.values[i]
            ok = np.isfinite(w) & np.isfinite(g)
            assert ok.sum() >= w.size - 2, q
            if rtol:
                np.testing.assert_allclose(g[ok], w[ok], rtol=rtol,
                                           err_msg=q)
            else:
                np.testing.assert_allclose(g[ok], w[ok], rtol=1e-9,
                                           err_msg=q)


def test_headroom_eviction(tmp_path):
    cs = FlatFileColumnStore(str(tmp_path / "col"))
    shard = TimeSeriesShard(REF, DEFAULT_SCHEMAS, 0, column_store=cs,
                            max_chunk_rows=100)
    b = RecordBuilder(DEFAULT_SCHEMAS)
    for s in range(10):
        labels = {"_metric_": "m", "_ws_": "w", "_ns_": "n",
                  "instance": f"i{s}"}
        for t in range(200):
            # staggered recency: series s ends at T0 + (s+1)*2000s
            b.add_sample("gauge", labels, T0 + s * 2_000_000 + t * 10_000,
                         float(t))
    for c in b.containers():
        shard.ingest(c)
    shard.flush_all(offset=1)
    before = shard.resident_samples()
    assert before == 2000 == shard.recount_resident()
    evicted = shard.ensure_headroom(max_samples=1000)
    assert evicted > 0
    after = shard.resident_samples()
    assert after <= 1000 * 0.75 + 200      # within headroom (+1 part slop)
    # evicted data still answers via ODP page-in
    tsp = TimeStepParams(T0 // 1000, 600, T0 // 1000 + 2_000 * 10)
    out = QueryEngine([shard]).execute(parse_query_range("m", tsp))
    assert out.num_series == 10
    assert shard.resident_samples() == shard.recount_resident()
    # under budget: no-op
    assert shard.ensure_headroom(max_samples=10_000_000) == 0


def test_flush_downsampler_memory_bounded(tmp_path):
    """Regression: ds-tier chunks must be released from memory on EVERY
    flush round, not just the first (shells that re-accumulate chunks
    stay evictable)."""
    cs = FlatFileColumnStore(str(tmp_path / "col"))
    shard = TimeSeriesShard(REF, DEFAULT_SCHEMAS, 0, column_store=cs,
                            max_chunk_rows=60)
    fds = FlushDownsampler(cs, "timeseries", 0, DEFAULT_SCHEMAS,
                           resolutions=(RES,))
    shard.flush_downsampler = fds
    for round_no in range(4):
        b = RecordBuilder(DEFAULT_SCHEMAS)
        labels = {"_metric_": "cpu", "_ws_": "w", "_ns_": "n"}
        for t in range(60):
            b.add_sample("gauge", labels,
                         T0 + OFF + (round_no * 60 + t) * 10_000,
                         float(t))
        for c in b.containers():
            shard.ingest(c)
        shard.flush_all(offset=round_no + 1)
        for sh in fds._out.values():
            assert sh.resident_samples() == 0, round_no
