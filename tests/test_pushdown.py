"""Plan→PromQL printing + whole-query pushdown to the owning peer node
(LogicalPlanParser.scala round-trip; PromQlRemoteExec.scala;
SingleClusterPlanner.scala:649 shard-aligned join pushdown).
"""

import numpy as np
import pytest

from filodb_tpu.promql.parser import TimeStepParams, parse_query_range
from filodb_tpu.query.planparser import plan_to_promql

T0 = 1_600_000_000


@pytest.mark.parametrize("q", [
    'rate(reqs_total{instance="i0"}[5m])',
    "sum(rate(reqs_total[5m])) by (instance)",
    "sum(rate(reqs_total[5m])) without (instance)",
    "topk(3, rate(reqs_total[5m]))",
    'cpu{_ws_="demo"}',
    "rate(reqs_total[5m] offset 10m)",
    "(rate(a_total[5m])) / (rate(b_total[5m]))",
    "(rate(a_total[5m])) * on (instance) group_left() (rate(b_total[5m]))",
    "histogram_quantile(0.99, sum(rate(lat[5m])))",
    "abs(cpu)",
    "(cpu) > bool (2)",
    'label_replace(cpu, "dst", "$1", "src", "(.*)")',
    "quantile_over_time(0.5, cpu[10m])",
    "predict_linear(disk_free[1h], 3600)",
    "holt_winters(cpu[30m], 0.5, 0.1)",
    "clamp(cpu, 0, 1)",
    "clamp_min(cpu, 0)",
    "round(cpu, 2)",
])
def test_plan_to_promql_roundtrip(q):
    tsp = TimeStepParams(T0, 60, T0 + 600)
    plan = parse_query_range(q, tsp)
    printed = plan_to_promql(plan)
    assert printed is not None, q
    # round-trip: re-parsing the printed text yields the SAME plan
    again = parse_query_range(printed, tsp)
    assert again == plan, f"{q!r} -> {printed!r}"


def test_printer_precision_and_metric_validation():
    """Regression: numbers round-trip at full precision (no %g
    truncation) and non-identifier metric names stay as matchers."""
    tsp = TimeStepParams(T0, 60, T0 + 600)
    for q in ["rate(reqs_total[5m] @ 1600000123)",
              "(cpu) > bool (1600000123)",
              "quantile_over_time(0.123456789, cpu[10m])"]:
        plan = parse_query_range(q, tsp)
        printed = plan_to_promql(plan)
        assert parse_query_range(printed, tsp) == plan, printed
    plan = parse_query_range('rate({__name__="my-metric"}[5m])', tsp)
    printed = plan_to_promql(plan)
    assert printed is not None
    assert parse_query_range(printed, tsp) == plan, printed


def test_unprintable_shapes_return_none():
    tsp = TimeStepParams(T0, 60, T0 + 600)
    # subqueries have no printer yet -> fall back to leaf dispatch
    plan = parse_query_range("max_over_time(rate(c_total[5m])[30m:1m])",
                             tsp)
    assert plan_to_promql(plan) is None


@pytest.mark.parametrize("q", [
    'rate(reqs_total{instance="i0"}[5m])',
    "sum(rate(reqs_total[5m])) by (instance)",
    "(rate(a_total[5m])) * on (instance) group_left() (rate(b_total[5m]))",
    "max_over_time(rate(c_total[5m])[30m:1m])",     # printer can't, wire can
    "histogram_quantile(0.99, sum(rate(lat[5m])))",
    'label_replace(cpu, "dst", "$1", "src", "(.*)")',
    "avg_over_time(cpu[10m:] @ end())",
])
def test_plan_wire_roundtrip(q):
    """Structural plan serialization (exec_plan.proto analogue) carries
    every plan shape — including ones the PromQL printer cannot."""
    from filodb_tpu.query.planwire import plan_from_wire, plan_to_wire
    tsp = TimeStepParams(T0, 60, T0 + 600)
    plan = parse_query_range(q, tsp)
    buf = plan_to_wire(plan)
    assert plan_from_wire(buf) == plan


# --- pushdown against an in-process two-node cluster -----------------------

@pytest.fixture
def two_nodes():
    from filodb_tpu.standalone.server import FiloServer
    import socket

    def free():
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        p = s.getsockname()[1]
        s.close()
        return p

    p0, p1 = free(), free()
    peers = {"node0": f"http://127.0.0.1:{p0}",
             "node1": f"http://127.0.0.1:{p1}"}
    base = {"num-shards": 4, "num-nodes": 2, "peers": peers,
            "seed-dev-data": False, "query-sample-limit": 0,
            "query-series-limit": 0}
    srv0 = FiloServer({**base, "node-ordinal": 0, "port": p0}).start()
    srv1 = FiloServer({**base, "node-ordinal": 1, "port": p1}).start()
    for srv in (srv0, srv1):
        srv.seed_dev_data(n_samples=60, n_instances=4,
                          start_ms=T0 * 1000)
    yield srv0, srv1
    srv0.stop()
    srv1.stop()


def _ns_on_node(srv, metric, node):
    """A namespace whose shard-key prunes entirely onto ``node``.

    Uses spread 0 (single-shard tenants — the reference's default for
    small apps): with spread > 0 the reference deliberately spreads one
    key across the shard space, so whole-node pushdown is a spread-0
    property (ShardMapper.scala:122)."""
    from filodb_tpu.core.record import shard_key_hash
    for i in range(256):
        ns = f"Ns-{i}"
        skh = shard_key_hash(["demo", ns], metric)
        shards = srv.mapper.query_shards(skh, 0)
        if {srv.mapper.node_of(s) for s in shards} == {node}:
            return ns
    raise AssertionError("no namespace hashes onto the target node")


def _seed_metric(srv, metric, ns, counter):
    """Seed a metric on the node owning its shards (gateway routing)."""
    from filodb_tpu.core.record import (RecordBuilder, RecordContainer,
                                        ingestion_shard)
    from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, PartitionSchema
    b = RecordBuilder(DEFAULT_SCHEMAS)
    schema = "prom-counter" if counter else "gauge"
    for inst in range(3):
        labels = {"_metric_": metric, "_ws_": "demo", "_ns_": ns,
                  "instance": f"i{inst}"}
        for t in range(60):
            v = float((t + 1) * (inst + 1)) if counter else \
                50.0 + inst + t * 0.1
            b.add_sample(schema, labels, (T0 + t * 10) * 1000, v)
    part_schema = PartitionSchema()
    for cont in b.containers():
        by_shard = {}
        for row in cont.rows():
            sh = ingestion_shard(row.part_key.shard_key_hash(part_schema),
                                 row.part_key.part_hash(), 0, 4)
            by_shard.setdefault(sh, RecordContainer(cont.schema))
            by_shard[sh].add(row.part_key, row.timestamp, *row.values)
        for sh, c2 in by_shard.items():
            srv.store.get_shard(srv.ref, sh).ingest(c2)


def _planner0(srv0, srv1):
    from filodb_tpu.query.planner import QueryPlanner
    return QueryPlanner(
        srv0.store.shards(srv0.ref), shard_mapper=srv0.mapper,
        spread=0, node_id="node0",
        peers={"node1": f"http://127.0.0.1:{srv1.port}"})


def test_whole_query_pushdown_matches_local(two_nodes):
    from filodb_tpu.parallel.cluster import PromQlRemoteExec
    from filodb_tpu.query.engine import QueryEngine
    srv0, srv1 = two_nodes
    ns = _ns_on_node(srv0, "pushed_total", "node1")
    _seed_metric(srv1, "pushed_total", ns, counter=True)
    planner = _planner0(srv0, srv1)
    tsp = TimeStepParams(T0 + 300, 60, T0 + 500)
    q = f'sum(rate(pushed_total{{_ws_="demo",_ns_="{ns}"}}[5m]))'
    plan = parse_query_range(q, tsp)
    ex = planner.materialize(plan)
    assert isinstance(ex, PromQlRemoteExec), type(ex).__name__
    got = ex.execute()
    want = QueryEngine(srv1.store.shards(srv1.ref)).execute(plan)
    assert got.num_series == want.num_series == 1
    ok = np.isfinite(want.values[0])
    assert ok.any()
    np.testing.assert_allclose(got.values[0][ok], want.values[0][ok],
                               rtol=1e-9)


def test_join_pushdown_across_nodes_ships_joined_results(two_nodes):
    """A shard-aligned self-join spanning BOTH nodes executes per node
    (each node joins its local shards) and the entry node concatenates
    joined results — raw series never cross the network
    (SingleClusterPlanner.scala:649 materializeWithPushdown)."""
    from filodb_tpu.parallel.cluster import PromQlRemoteExec
    from filodb_tpu.query.engine import QueryEngine
    from filodb_tpu.query.planner import ConcatExec, LocalEngineExec
    srv0, srv1 = two_nodes
    ns0 = _ns_on_node(srv0, "xg", "node0")
    ns1 = _ns_on_node(srv0, "xg", "node1")
    _seed_metric(srv0, "xg", ns0, counter=False)
    _seed_metric(srv1, "xg", ns1, counter=False)
    planner = _planner0(srv0, srv1)
    tsp = TimeStepParams(T0 + 300, 60, T0 + 500)
    sel = f'xg{{_ws_="demo",_ns_=~"{ns0}|{ns1}"}}'
    plan = parse_query_range(f"({sel}) + ({sel})", tsp)
    ex = planner.materialize(plan)
    assert isinstance(ex, ConcatExec), ex.plan_tree()
    kinds = {type(c).__name__ for c in ex.children}
    assert kinds == {"LocalEngineExec", "PromQlRemoteExec"}, kinds
    # the remote child carries the whole JOIN (printed PromQL), pinned
    # to the peer's local shards
    remote = next(c for c in ex.children
                  if isinstance(c, PromQlRemoteExec))
    assert remote.local_only and "+" in remote.query
    got = ex.execute()
    # oracle: single engine over ALL shards of both nodes
    both = list(srv0.store.shards(srv0.ref)) + \
        list(srv1.store.shards(srv1.ref))
    want = QueryEngine(both).execute(plan)
    assert got.num_series == want.num_series == 6
    gk = sorted(tuple(sorted(k.items())) for k in got.keys)
    wk = sorted(tuple(sorted(k.items())) for k in want.keys)
    assert gk == wk
    order = np.argsort([str(sorted(k.items())) for k in got.keys])
    worder = np.argsort([str(sorted(k.items())) for k in want.keys])
    np.testing.assert_allclose(got.values[order], want.values[worder],
                               rtol=1e-9, equal_nan=True)


def test_join_pushdown_cross_metric_stays_local(two_nodes):
    """Different metrics on the two sides can match across shards (the
    shard hash includes the metric), so the join must NOT decompose."""
    from filodb_tpu.query.planner import ConcatExec
    srv0, srv1 = two_nodes
    planner = _planner0(srv0, srv1)
    tsp = TimeStepParams(T0 + 300, 60, T0 + 500)
    plan = parse_query_range('(heap_usage{_ws_="demo",_ns_="App-0"}) / '
                             '(heap_usage2{_ws_="demo",_ns_="App-0"})',
                             tsp)
    ex = planner.materialize(plan)
    assert not isinstance(ex, ConcatExec)


def test_join_pushdown_same_node(two_nodes):
    """A binary join whose both sides live on one peer forwards whole."""
    from filodb_tpu.parallel.cluster import PromQlRemoteExec
    srv0, srv1 = two_nodes
    ns = _ns_on_node(srv0, "pushg", "node1")
    _seed_metric(srv1, "pushg", ns, counter=False)
    planner = _planner0(srv0, srv1)
    tsp = TimeStepParams(T0 + 300, 60, T0 + 500)
    sel = f'pushg{{_ws_="demo",_ns_="{ns}"}}'
    plan = parse_query_range(f"({sel}) / ({sel})", tsp)
    ex = planner.materialize(plan)
    assert isinstance(ex, PromQlRemoteExec)
    got = ex.execute()
    assert got.num_series == 3
    finite = np.isfinite(got.values)
    assert finite.any()
    np.testing.assert_allclose(got.values[finite], 1.0)
