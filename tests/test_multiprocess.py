"""Multi-process cluster e2e (the multi-jvm test analogue,
coordinator/src/multi-jvm + FiloDbClusterDiscovery.scala:50): two OS
processes each own half the shards; a query entering either node fans leaf
selection out to the peer and returns the full series set; killing one
node flips its shards DOWN on the survivor and queries exclude them.
"""

import json
import os
import pathlib
import select
import signal
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.parse
import urllib.request

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
T0 = 1_600_000_000
N_SAMPLES = 120
N_INSTANCES = 4


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn(cfg, tmp_path, name):
    cfg_path = tmp_path / f"{name}.json"
    cfg_path.write_text(json.dumps(cfg))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.Popen(
        [sys.executable, "-m", "filodb_tpu.standalone.server",
         "--config", str(cfg_path)],
        cwd=str(REPO), env=env, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL)


def _wait_ready(proc, timeout=120.0):
    deadline = time.monotonic() + timeout
    buf = b""
    while time.monotonic() < deadline:
        r, _, _ = select.select([proc.stdout], [], [], 1.0)
        if not r:
            if proc.poll() is not None:
                raise RuntimeError("server died during startup")
            continue
        ch = proc.stdout.read1(4096)
        if not ch:
            raise RuntimeError("stdout closed")
        buf += ch
        if b"\n" in buf:
            return json.loads(buf.split(b"\n", 1)[0])
    raise TimeoutError("no startup line")


def _get(port, path, **params):
    qs = urllib.parse.urlencode(params, doseq=True)
    url = f"http://127.0.0.1:{port}{path}"
    if qs:
        url += "?" + qs
    with urllib.request.urlopen(url, timeout=120) as r:
        return json.loads(r.read())


def _poll(fn, timeout=90.0, interval=0.25):
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        try:
            ok, last = fn()
            if ok:
                return last
        except (urllib.error.URLError, ConnectionError, OSError):
            pass
        time.sleep(interval)
    raise TimeoutError(f"poll timed out; last={last!r}")


def _grpc_rpcs(port) -> int:
    """grpc_rpcs_served_total from a node's /metrics exposition."""
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
        txt = r.read().decode()
    for line in txt.splitlines():
        if line.startswith("#"):
            continue                 # # HELP / # TYPE comment lines
        if "grpc_rpcs_served_total" in line:
            return int(float(line.split()[-1]))
    return 0


def _series_instances(port):
    """All heap_usage-family series visible via an unpruned query."""
    # regex selector: unprunable (fans to all shards on both nodes) and
    # double-typed only (no hist/double mixing in one vector)
    body = _get(port, "/promql/timeseries/api/v1/query",
                query='{_metric_=~"heap_usage|http_requests_total"}',
                time=T0 + (N_SAMPLES - 1) * 10)
    out = set()
    for r in body["data"]["result"]:
        m = r["metric"]
        out.add((m.get("_metric_", m.get("__name__", "?")),
                 m.get("instance", "")))
    return out


@pytest.fixture
def cluster(tmp_path):
    p0, p1 = _free_port(), _free_port()
    peers = {"node0": f"http://127.0.0.1:{p0}",
             "node1": f"http://127.0.0.1:{p1}"}
    base = {
        "num-shards": 4, "num-nodes": 2, "peers": peers,
        "seed-dev-data": True, "seed-start-ms": T0 * 1000,
        "seed-samples": N_SAMPLES, "seed-instances": N_INSTANCES,
        "query-sample-limit": 0, "query-series-limit": 0,
        "failure-detect-interval-s": 0.25,
    }
    procs = []
    try:
        procs.append(_spawn({**base, "node-ordinal": 0, "port": p0},
                            tmp_path, "node0"))
        procs.append(_spawn({**base, "node-ordinal": 1, "port": p1},
                            tmp_path, "node1"))
        for p in procs:
            _wait_ready(p)
        yield p0, p1, procs
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=30)


def test_cross_node_query_and_peer_death(cluster):
    p0, p1, procs = cluster

    # each node owns half the shards; together they hold all seeded series
    st0 = _poll(lambda: ((lambda b: (len(b["data"]) == 4, b))(
        _get(p0, "/api/v1/cluster/timeseries/status"))))
    nodes = {s["shard"]: s["address"] for s in st0["data"]}
    assert set(nodes.values()) == {"node0", "node1"}

    # both entry points see the SAME full series set (cross-node dispatch)
    all0 = _poll(lambda: ((lambda s: (len(s) > 0, s))(
        _series_instances(p0))))
    all1 = _series_instances(p1)
    assert all0 == all1

    # /series metadata fans out to peers too
    sb = _get(p0, "/promql/timeseries/api/v1/series",
              **{"match[]": '{_metric_="heap_usage"}'})
    insts = {m.get("instance") for m in sb["data"]}
    assert insts == {m[1] for m in all0 if m[0] == "heap_usage"}

    # the series set spans both nodes: each node alone (local shards only)
    # holds a strict subset — verify via the raw leaf endpoint
    def _local_count(port, shards):
        body = json.dumps({"filters": [["_metric_", "re",
                                        "heap_usage|http_requests_total"]],
                           "start_ms": 0, "end_ms": 1 << 60,
                           "column": None, "shards": shards}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/api/v1/raw/timeseries", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as r:
            return len(json.loads(r.read())["data"])

    n_local0 = _local_count(p0, [0, 1])
    n_local1 = _local_count(p1, [2, 3])
    assert n_local0 + n_local1 == len(all0)
    assert 0 < n_local0 < len(all0)

    # rate() across nodes works end to end
    body = _get(p0, "/promql/timeseries/api/v1/query_range",
                query="rate(http_requests_total[5m])",
                start=T0 + 300, end=T0 + (N_SAMPLES - 1) * 10, step=60)
    assert len(body["data"]["result"]) == N_INSTANCES

    # the binary data plane carries leaf dispatch on BOTH nodes: peers
    # discover each other's ephemeral gRPC ports through health-body
    # gossip, so poll until a cross-node query rides protobuf frames
    def _grpc_plane():
        _series_instances(p0)
        _series_instances(p1)
        # cache=false: this probe waits for the gRPC data plane to
        # carry a leaf dispatch — a results-cache hit would answer
        # without dialing the peer and the poll would never converge
        _get(p0, "/promql/timeseries/api/v1/query_range",
             query="rate(http_requests_total[5m])",
             start=T0 + 300, end=T0 + 900, step=60, cache="false")
        _get(p1, "/promql/timeseries/api/v1/query_range",
             query="rate(http_requests_total[5m])",
             start=T0 + 300, end=T0 + 900, step=60, cache="false")
        served = [_grpc_rpcs(p0), _grpc_rpcs(p1)]
        return all(s > 0 for s in served), served
    _poll(_grpc_plane, timeout=30)

    # -- kill node1: survivor flips its shards DOWN, queries exclude ------
    os.kill(procs[1].pid, signal.SIGKILL)
    procs[1].wait(timeout=30)

    def _down():
        b = _get(p0, "/api/v1/cluster/timeseries/status")
        down = {s["shard"] for s in b["data"] if s["status"] == "down"}
        return down == {2, 3}, b
    _poll(_down, timeout=30)

    # queries now answer from the surviving shards only (no error)
    partial = _series_instances(p0)
    assert len(partial) == n_local0
    assert partial < all0


def test_buddy_failover_serves_down_shards(tmp_path):
    """HA: a DOWN node's shards are served from its buddy replica
    (HighAvailabilityPlanner.scala:31 — route failed shards to the buddy
    cluster), so results stay COMPLETE through a node loss."""
    p0, p1, pb = _free_port(), _free_port(), _free_port()
    peers = {"node0": f"http://127.0.0.1:{p0}",
             "node1": f"http://127.0.0.1:{p1}"}
    base = {
        "num-shards": 4, "num-nodes": 2, "peers": peers,
        "seed-dev-data": True, "seed-start-ms": T0 * 1000,
        "seed-samples": N_SAMPLES, "seed-instances": N_INSTANCES,
        "query-sample-limit": 0, "query-series-limit": 0,
        "failure-detect-interval-s": 0.25,
    }
    procs = []
    try:
        procs.append(_spawn({**base, "node-ordinal": 0, "port": p0,
                             "buddy-peers": {
                                 "node1": f"http://127.0.0.1:{pb}"}},
                            tmp_path, "node0"))
        procs.append(_spawn({**base, "node-ordinal": 1, "port": p1},
                            tmp_path, "node1"))
        # the buddy replica of node1: same ordinal/shard layout, same
        # (deterministically seeded) data, no cluster peers of its own
        procs.append(_spawn({**base, "node-ordinal": 1, "port": pb,
                             "peers": {}},
                            tmp_path, "node1-buddy"))
        for p in procs:
            _wait_ready(p)
        full = _poll(lambda: ((lambda s: (len(s) > 0, s))(
            _series_instances(p0))))

        os.kill(procs[1].pid, signal.SIGKILL)
        procs[1].wait(timeout=30)
        _poll(lambda: ((lambda b: (any(
            s["status"] == "down" for s in b["data"]), b))(
            _get(p0, "/api/v1/cluster/timeseries/status"))), timeout=30)

        # with the buddy configured, results stay COMPLETE
        after = _series_instances(p0)
        assert after == full
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=30)


def test_peer_recovery_restores_shards(cluster, tmp_path):
    p0, p1, procs = cluster
    _poll(lambda: ((lambda s: (len(s) > 0, s))(_series_instances(p0))))
    full = _series_instances(p0)
    os.kill(procs[1].pid, signal.SIGKILL)
    procs[1].wait(timeout=30)
    _poll(lambda: ((lambda b: (any(
        s["status"] == "down" for s in b["data"]), b))(
        _get(p0, "/api/v1/cluster/timeseries/status"))), timeout=30)

    # restart node1 on the same port: detector flips shards back ACTIVE
    peers = {"node0": f"http://127.0.0.1:{p0}",
             "node1": f"http://127.0.0.1:{p1}"}
    cfg = {"num-shards": 4, "num-nodes": 2, "node-ordinal": 1, "port": p1,
           "peers": peers, "seed-dev-data": True,
           "seed-start-ms": T0 * 1000, "seed-samples": N_SAMPLES,
           "seed-instances": N_INSTANCES, "query-sample-limit": 0,
           "query-series-limit": 0, "failure-detect-interval-s": 0.25}
    procs[1] = _spawn(cfg, tmp_path, "node1b")
    _wait_ready(procs[1])
    _poll(lambda: ((lambda b: (all(
        s["status"] == "active" for s in b["data"]), b))(
        _get(p0, "/api/v1/cluster/timeseries/status"))), timeout=30)
    _poll(lambda: ((lambda s: (s == full, s))(_series_instances(p0))))
