"""Aligned tile store parity tests: the shared-column fast path must match
the numpy oracle (rangefn) on jittered, gappy, resetting, boundary-exact
series — and fall back cleanly when series don't align.

(Reference oracle: query/src/test rangefn specs — RateFunctionsSpec,
AggrOverTimeFunctionsSpec golden semantics.)"""

import numpy as np
import pytest

from filodb_tpu.query import rangefn as rf
from filodb_tpu.query import tilestore as tst
from filodb_tpu.query.model import RangeParams, RawSeries
from filodb_tpu.query.tpu import TpuBackend

PARAMS = RangeParams(300_000, 60_000, 1_500_000)
WINDOW = 300_000
DT = 10_000


def _mk(seed, n_series=6, n=150, counter=False, gaps=0.0, jitter=2000,
        resets=False):
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n_series):
        ts = np.arange(1, n + 1, dtype=np.int64) * DT \
            + rng.integers(-jitter, jitter + 1, n)
        ts = np.sort(ts)
        if counter:
            vals = np.cumsum(rng.uniform(0, 5, n))
            if resets and i % 2 == 0:
                cut = rng.integers(n // 3, 2 * n // 3)
                vals[cut:] = np.cumsum(rng.uniform(0, 5, n - cut))
        else:
            vals = rng.normal(10, 3, n)
        if gaps > 0:
            keep = rng.random(n) > gaps
            keep[0] = keep[-1] = True
            ts, vals = ts[keep], vals[keep]
        out.append(RawSeries({"i": str(i)}, ts, vals, is_counter=counter))
    return out


def _oracle(series, func, params=PARAMS, window=WINDOW, scalar=None):
    return np.vstack([
        rf.evaluate(func, s.ts, s.values, params.start_ms, params.step_ms,
                    params.end_ms, window, scalar=scalar)
        for s in series])


def _device(series, func, params=PARAMS, window=WINDOW, args=()):
    r = TpuBackend().periodic_samples(series, params, func, window,
                                      func_args=args)
    assert r is not None
    return r.values


ALL_FUNCS = sorted(tst.ALIGNED_FUNCS - {"last_sample"})

# rate/increase/delta ride the f32-hybrid fast path: int32 timestamps and
# f64 boundary deltas keep the numerator EXACT (large counters can't
# cancel), but the extrapolation factor runs in f32 — a few f32 ulps
# (~3e-7 relative) vs the f64 oracle. Documented tolerance; every other
# function stays exact-f64 at 1e-9.
_COUNTER_RTOL = 1e-5


def _rtol(func):
    return _COUNTER_RTOL if func in ("rate", "increase", "delta") else 1e-9


@pytest.mark.parametrize("func", ALL_FUNCS)
def test_aligned_parity_jittered(func):
    series = _mk(1, counter=True, resets=True)
    tiles, idx = tst.build_aligned_tiles(series)
    assert tiles is not None and len(idx) == len(series)
    got = _device(series, func)
    want = _oracle(series, func)
    np.testing.assert_allclose(got, want, rtol=_rtol(func), equal_nan=True)


@pytest.mark.parametrize("func", ["rate", "sum_over_time", "changes",
                                  "count_over_time", "last_over_time",
                                  "first_over_time", "stddev_over_time"])
def test_aligned_parity_with_gaps(func):
    series = _mk(2, counter=(func == "rate"), gaps=0.3)
    tiles, idx = tst.build_aligned_tiles(series)
    assert tiles is not None and len(idx) == len(series)
    got = _device(series, func)
    want = _oracle(series, func)
    np.testing.assert_allclose(got, want, rtol=_rtol(func), equal_nan=True)


def test_boundary_exact_samples():
    """Samples exactly at wstart/wend must be included (closed window)."""
    ts = np.array([300_000, 360_000, 420_000, 600_000], dtype=np.int64)
    vals = np.array([1.0, 2.0, 3.0, 4.0])
    series = [RawSeries({"i": "0"}, ts, vals)]
    params = RangeParams(600_000, 60_000, 720_000)
    got = _device(series, "sum_over_time", params, window=300_000)
    want = _oracle(series, "sum_over_time", params, window=300_000)
    np.testing.assert_allclose(got, want, rtol=1e-12, equal_nan=True)


def test_counter_reset_correction_matches():
    series = _mk(3, counter=True, resets=True, gaps=0.2)
    got = _device(series, "increase")
    want = _oracle(series, "increase")
    np.testing.assert_allclose(got, want, rtol=_COUNTER_RTOL,
                               equal_nan=True)


def test_irregular_series_fall_back():
    """Random (non-cadenced) timestamps: build must reject them and the
    backend must still produce oracle-parity results via the general path."""
    rng = np.random.default_rng(4)
    series = []
    for i in range(4):
        ts = np.sort(rng.integers(10_000, 1_500_000, 120)).astype(np.int64)
        ts = np.unique(ts)
        series.append(RawSeries({"i": str(i)}, ts,
                                rng.normal(10, 3, ts.size)))
    tiles, idx = tst.build_aligned_tiles(series)
    assert tiles is None or len(idx) < len(series)
    got = _device(series, "avg_over_time")
    want = _oracle(series, "avg_over_time")
    np.testing.assert_allclose(got, want, rtol=1e-9, equal_nan=True)


@pytest.mark.parametrize("func", ["rate", "increase", "delta"])
def test_irregular_rate_family_via_pallas(func):
    """Irregular series route to the Pallas boundary-extract kernel
    (interpret mode on CPU) and must match the oracle."""
    rng = np.random.default_rng(11)
    series = []
    for i in range(3):
        ts = np.unique(np.sort(rng.integers(10_000, 1_500_000, 120))
                       ).astype(np.int64)
        vals = np.cumsum(rng.uniform(0, 5, ts.size))
        if i == 0:
            vals[ts.size // 2:] = np.cumsum(
                rng.uniform(0, 5, ts.size - ts.size // 2))   # reset
        series.append(RawSeries({"i": str(i)}, ts, vals, is_counter=True))
    from filodb_tpu.query import tilestore as tst2
    tiles, idx = tst2.build_aligned_tiles(series)
    assert tiles is None or len(idx) < len(series)
    got = _device(series, func)
    want = _oracle(series, func)
    np.testing.assert_allclose(got, want, rtol=1e-9, equal_nan=True)


def test_mixed_alignment_falls_back_to_general():
    series = _mk(5, n_series=3)
    rng = np.random.default_rng(6)
    ts = np.unique(np.sort(rng.integers(10_000, 1_500_000, 200)))
    series.append(RawSeries({"i": "x"}, ts.astype(np.int64),
                            rng.normal(10, 3, ts.size)))
    got = _device(series, "max_over_time")
    want = _oracle(series, "max_over_time")
    np.testing.assert_allclose(got, want, rtol=1e-9, equal_nan=True)


def test_last_sample_with_stale_markers_falls_back():
    ts = np.arange(1, 61, dtype=np.int64) * DT
    vals = np.full(60, 5.0)
    vals[30] = np.nan                      # stale marker
    series = [RawSeries({"i": "0"}, ts, vals)]
    params = RangeParams(DT * 31, DT, DT * 35)
    got = _device(series, "last_sample", params, window=DT * 5)
    want = _oracle(series, "last_sample", params, window=DT * 5)
    np.testing.assert_allclose(got, want, rtol=1e-9, equal_nan=True)


def test_tile_cache_reused_across_queries():
    series = _mk(7)
    be = TpuBackend()
    be.periodic_samples(series, PARAMS, "sum_over_time", WINDOW)
    assert len(be._tile_cache) == 1
    be.periodic_samples(series, PARAMS, "avg_over_time", WINDOW)
    assert len(be._tile_cache) == 1       # same snapshot, no rebuild


@pytest.mark.parametrize("func", ["stddev_over_time", "stdvar_over_time",
                                  "z_score"])
def test_variance_large_offset_no_cancellation(func):
    """Variance via shifted squares must survive a large mean offset
    (round-1 advisor: E[x^2]-mean^2 diverged ~1e-7 and z_score NaN'd).

    Values ~1e8 with O(1) spread: the naive form loses all 8 digits of
    the variance; the shifted form keeps full precision."""
    rng = np.random.default_rng(11)
    series = []
    for i in range(4):
        ts = np.arange(1, 151, dtype=np.int64) * DT
        vals = 1e8 + rng.normal(0.0, 2.0, 150)
        series.append(RawSeries({"i": str(i)}, ts, vals))
    got = _device(series, func)
    want = _oracle(series, func)
    # z_score's numerator (last - mean) cancels at 1e8 scale in BOTH
    # paths; allow for op-ordering noise there
    rtol = 5e-6 if func == "z_score" else 1e-6
    np.testing.assert_allclose(got, want, rtol=rtol, atol=1e-9,
                               equal_nan=True)
    # sanity: results are finite wherever the oracle is
    assert np.isnan(got).sum() == np.isnan(want).sum()


def test_transposed_counter_eval_matches_row_major():
    """The slot-major f32-hybrid fast path (evaluate_counters_t) must
    match the exact row-major evaluator to f32-epilogue precision on
    gappy jittered tiles — identical NaN pattern, ~1e-5 relative."""
    from filodb_tpu.query import tilestore as tst
    rng = np.random.default_rng(11)
    S, N, dt = 24, 96, 10_000
    base = 1_600_000_000_000
    valid = rng.random((S, N)) > 0.15
    valid[3] = False
    valid[4, : N // 2] = False
    ts_true = (base + np.arange(N)[None, :] * dt
               + rng.integers(-2000, 2000, (S, N))).astype(np.float64)
    vals = np.cumsum(rng.uniform(0, 5, (S, N)), axis=1)
    vals[7, 40:] *= 0.2          # a counter reset
    tiles = tst.AlignedTiles([{} for _ in range(S)], base, dt, valid,
                             ts_true, vals)
    steps = base + 400_000 + np.arange(37) * 60_000
    for func in ("rate", "increase", "delta"):
        want = np.asarray(tst.evaluate_aligned(tiles, func, steps,
                                               300_000))
        got = np.asarray(tst.evaluate_counters_t(tiles, func, steps,
                                                 300_000)).T
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-9,
                                   equal_nan=True, err_msg=func)
        assert np.array_equal(np.isnan(got), np.isnan(want)), func


def test_transposed_counter_wide_grid_exact_fallback():
    """Grids that don't fit int32 ms relative to the tile base take the
    exact all-f64 path — bit-identical to the row-major evaluator."""
    from filodb_tpu.query import tilestore as tst
    rng = np.random.default_rng(12)
    S, N, dt = 8, 64, 10_000
    base = 1_600_000_000_000
    ts_true = (base + np.arange(N)[None, :] * dt
               + rng.integers(-2000, 2000, (S, N))).astype(np.float64)
    vals = np.cumsum(rng.uniform(0, 5, (S, N)), axis=1)
    tiles = tst.AlignedTiles([{} for _ in range(S)], base, dt,
                             np.ones((S, N), bool), ts_true, vals)
    # grid ends ~25 days past base: beyond int32 ms -> exact path
    steps = base + np.int64(26 * 86_400_000) + np.arange(5) * 60_000
    want = np.asarray(tst.evaluate_aligned(tiles, "rate", steps, 300_000))
    got = np.asarray(tst.evaluate_counters_t(tiles, "rate", steps,
                                             300_000)).T
    np.testing.assert_array_equal(got, want)


def test_fast_path_large_counter_exact_delta():
    """Counters at 1e15 with O(1) increments: the f64 boundary delta must
    stay exact (a pure-f32 value channel would cancel catastrophically —
    f32 ulp at 1e15 is ~1e8, dwarfing the real increase)."""
    from filodb_tpu.query import rangefn as rf
    from filodb_tpu.query import tilestore as tst
    rng = np.random.default_rng(13)
    S, N, dt = 4, 128, 10_000
    base = 1_600_000_000_000
    ts_true = (base + np.arange(N)[None, :] * dt
               + rng.integers(-2000, 2000, (S, N))).astype(np.float64)
    vals = 1e15 + np.cumsum(rng.uniform(0, 5, (S, N)), axis=1)
    tiles = tst.AlignedTiles([{} for _ in range(S)], base, dt,
                             np.ones((S, N), bool), ts_true, vals)
    steps = base + 400_000 + np.arange(20) * 60_000
    got = np.asarray(tst.evaluate_counters_t(tiles, "rate", steps,
                                             300_000)).T
    want = np.vstack([
        rf.evaluate("rate", ts_true[s].astype(np.int64), vals[s],
                    int(steps[0]), 60_000, int(steps[-1]), 300_000)
        for s in range(S)])
    np.testing.assert_allclose(got, want, rtol=1e-5, equal_nan=True)
    # rates are O(0.5/s); garbage from f32 cancellation would be O(1e8/300)
    assert np.nanmax(np.abs(got)) < 10.0


def test_dense_alias_keeps_semantics():
    """Fully-valid tiles alias ff/bf to the raw channels; results must not
    change vs a near-dense tile evaluated the general way."""
    from filodb_tpu.query import tilestore as tst
    rng = np.random.default_rng(5)
    S, N, dt = 8, 64, 10_000
    base = 1_600_000_000_000
    ts_true = (base + np.arange(N)[None, :] * dt
               + rng.integers(-2000, 2000, (S, N))).astype(np.float64)
    vals = np.cumsum(rng.uniform(0, 5, (S, N)), axis=1)
    dense = tst.AlignedTiles([{} for _ in range(S)], base, dt,
                             np.ones((S, N), bool), ts_true, vals)
    assert dense._dense
    # force the general (non-alias) fills by faking density off
    general = tst.AlignedTiles([{} for _ in range(S)], base, dt,
                               np.ones((S, N), bool), ts_true, vals)
    general._dense = False
    steps = base + 400_000 + np.arange(19) * 60_000
    for func in ("rate", "sum_over_time", "last_over_time"):
        a = np.asarray(tst.evaluate_aligned(dense, func, steps, 300_000))
        b = np.asarray(tst.evaluate_aligned(general, func, steps, 300_000))
        np.testing.assert_array_equal(a, b, err_msg=func)


def test_transposed_dense_fast_path_matches():
    """Dense tiles drop the ps/ch arrays (arithmetic counts) — results
    must still match the general row-major evaluator exactly."""
    from filodb_tpu.query import tilestore as tst
    rng = np.random.default_rng(17)
    S, N, dt = 16, 128, 10_000
    base = 1_600_000_000_000
    ts_true = (base + np.arange(N)[None, :] * dt
               + rng.integers(-2000, 2000, (S, N))).astype(np.float64)
    vals = np.cumsum(rng.uniform(0, 5, (S, N)), axis=1)
    vals[5, 60:] *= 0.1          # reset
    tiles = tst.AlignedTiles([{} for _ in range(S)], base, dt,
                             np.ones((S, N), bool), ts_true, vals)
    assert tiles._dense
    assert "ps_ones" not in tst._tiles_arrays_t(tiles, "rate")
    # query grid pokes beyond both edges to exercise the clamps
    steps = base - 120_000 + np.arange(40) * 60_000
    for func in ("rate", "increase", "delta"):
        want = np.asarray(tst.evaluate_aligned(tiles, func, steps,
                                               300_000))
        got = np.asarray(tst.evaluate_counters_t(tiles, func, steps,
                                                 300_000)).T
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-9,
                                   equal_nan=True, err_msg=func)
        assert np.array_equal(np.isnan(got), np.isnan(want)), func


def test_slide_path_bitwise_matches_gather_fast_path():
    """Regular in-bounds grids over dense tiles dispatch to the stride-
    permuted slide evaluator; results must be BITWISE identical to the
    gather fast path (same ops, different read pattern), and irregular
    or out-of-range grids must fall back."""
    import functools

    import jax
    import jax.numpy as jnp

    from filodb_tpu.query import tilestore as tst
    rng = np.random.default_rng(23)
    S, N, dt = 16, 288, 10_000
    base = 1_600_000_000_000
    ts_true = (base + np.arange(N)[None, :] * dt
               + rng.integers(-2000, 2000, (S, N))).astype(np.float64)
    vals = np.cumsum(rng.uniform(0, 5, (S, N)), axis=1)
    vals[3, 100:] *= 0.1          # reset
    tiles = tst.AlignedTiles([{} for _ in range(S)], base, dt,
                             np.ones((S, N), bool), ts_true, vals)
    steps = np.arange(base + 400_000, base + 2_000_000, 60_000,
                      dtype=np.int64)
    for func in ("rate", "increase", "delta"):
        got = np.asarray(tst.evaluate_counters_t(tiles, func, steps,
                                                 300_000))
        assert (("slide", func, steps.size, 6) in tst._EVAL_T_JIT), func
        arrs = tst._tiles_arrays_fast(tiles, func)
        ref = np.asarray(jax.jit(functools.partial(
            tst._eval_counter_fast, func, steps.size))(
                arrs, jnp.asarray(np.int64(N)), jnp.asarray(np.int64(base)),
                jnp.asarray(np.int64(dt)),
                jnp.asarray(np.int64(steps[0] - 300_000)),
                jnp.asarray(np.int64(steps[0])),
                jnp.asarray(np.int64(60_000))))
        assert got.dtype == ref.dtype == np.float32
        np.testing.assert_array_equal(got, ref, err_msg=func)
    # grid past the tile end and a non-multiple step both fall back
    # (no new slide jit entries) yet still produce results
    before = {k for k in tst._EVAL_T_JIT if k[0] == "slide"}
    over = np.arange(base + 400_000, base + N * dt + 600_000, 60_000,
                     dtype=np.int64)
    r = np.asarray(tst.evaluate_counters_t(tiles, "rate", over, 300_000))
    assert np.isfinite(r).any()
    odd = np.arange(base + 400_000, base + 2_000_000, 61_000,
                    dtype=np.int64)
    np.asarray(tst.evaluate_counters_t(tiles, "rate", odd, 300_000))
    assert {k for k in tst._EVAL_T_JIT if k[0] == "slide"} == before
