"""Offline fsck smoke tests against a fixture data dir holding a torn
tail, a bit-flipped frame, and a clean file — plus ``--repair`` and the
mixed-version format-split report (``python -m filodb_tpu.fsck``)."""

import json
import os
import subprocess
import sys

import pytest

from filodb_tpu import fsck
from filodb_tpu.core.memstore import TimeSeriesShard
from filodb_tpu.core.record import RecordBuilder
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetRef
from filodb_tpu.ingest import LogIngestionStream
from filodb_tpu.store import FlatFileColumnStore, integrity

REF = DatasetRef("timeseries")
T0 = 1_600_000_000


def _write_wal(path, n=5, framed=True):
    s = LogIngestionStream(path, DEFAULT_SCHEMAS, integrity_frames=framed)
    for i in range(n):
        b = RecordBuilder(DEFAULT_SCHEMAS)
        b.add_sample("gauge", {"_metric_": "m", "_ws_": "demo",
                               "_ns_": "App-0", "instance": f"i{i}"},
                     (T0 + i) * 1000, float(i))
        for c in b.containers():
            s.append(c)
    recs = list(s._records)
    s.close()
    return recs


def _flip(path, pos, mask=0x01):
    with open(path, "r+b") as f:
        f.seek(pos)
        b = f.read(1)
        f.seek(pos)
        f.write(bytes([b[0] ^ mask]))


@pytest.fixture
def fixture_dir(tmp_path):
    """shard-0: bit-flipped WAL frame; shard-1: torn WAL tail;
    shard-2: clean WAL; plus a flushed column-store shard dir with a
    corrupted checkpoint."""
    d0 = tmp_path / "shard-0"; d0.mkdir()
    recs = _write_wal(str(d0 / "stream.log"))
    victim = recs[2]
    _flip(str(d0 / "stream.log"),
          victim.payload_off + victim.payload_len // 2)

    d1 = tmp_path / "shard-1"; d1.mkdir()
    _write_wal(str(d1 / "stream.log"))
    with open(d1 / "stream.log", "ab") as f:
        f.write(integrity.encode_frame(b"y" * 64)[:17])

    d2 = tmp_path / "shard-2"; d2.mkdir()
    _write_wal(str(d2 / "stream.log"))

    cs = FlatFileColumnStore(str(tmp_path / "col"))
    shard = TimeSeriesShard(REF, DEFAULT_SCHEMAS, 0, num_groups=2,
                            max_chunk_rows=32, column_store=cs)
    b = RecordBuilder(DEFAULT_SCHEMAS)
    for t in range(64):
        b.add_sample("prom-counter",
                     {"_metric_": "c_total", "_ws_": "demo",
                      "_ns_": "App-0", "instance": "i0"},
                     (T0 + t * 10) * 1000, float(t))
    for c in b.containers():
        shard.ingest(c, 3)
    shard.flush_all(offset=3)
    cs.close()
    ckpt = cs._ckpt_path("timeseries", 0)
    _flip(ckpt, os.path.getsize(ckpt) // 2)
    return tmp_path


def test_fsck_reports_findings(fixture_dir):
    report = fsck.check_dir(str(fixture_dir))
    by_path = {f["path"]: f for f in report["files"]}
    s = report["summary"]
    assert s["files_checked"] >= 6      # 3 WALs + chunks + pk + ckpt
    assert s["files_with_findings"] == 3
    flipped = by_path[str(fixture_dir / "shard-0" / "stream.log")]
    assert flipped["corrupt_regions"] and not flipped["clean"]
    assert flipped["records"]["framed"] == 4
    torn = by_path[str(fixture_dir / "shard-1" / "stream.log")]
    assert torn["tail"]["state"] == "torn"
    clean = by_path[str(fixture_dir / "shard-2" / "stream.log")]
    assert clean["clean"] and clean["records"]["framed"] == 5
    ckpts = [f for f in report["files"] if f["kind"] == "checkpoint"]
    assert ckpts and not ckpts[0]["clean"]
    # the flushed chunk/partkey logs are untouched and verify clean
    for kind in ("chunklog", "partkeys"):
        assert all(f["clean"] for f in report["files"]
                   if f["kind"] == kind)


def test_fsck_repair_then_clean(fixture_dir):
    report = fsck.check_dir(str(fixture_dir), repair=True)
    assert all(f.get("repaired") for f in report["files"]
               if not f["clean"])
    again = fsck.check_dir(str(fixture_dir))
    assert again["summary"]["files_with_findings"] == 0
    # quarantine sidecars hold the damaged bytes + manifest
    q0 = integrity.quarantine_dir(
        str(fixture_dir / "shard-0" / "stream.log"))
    assert "MANIFEST.jsonl" in os.listdir(q0)
    # repaired WAL still replays its 4 surviving records
    s = LogIngestionStream(str(fixture_dir / "shard-0" / "stream.log"),
                           DEFAULT_SCHEMAS)
    assert len(s.read(0, 100)) == 4
    assert s.quarantined_records() == 0   # fsck already took the bytes
    s.close()


def test_fsck_mixed_version_format_split(tmp_path):
    """Satellite: a stream dir with BOTH unframed and framed records in
    the same file — fsck reports the format split per file."""
    d = tmp_path / "shard-0"; d.mkdir()
    path = str(d / "stream.log")
    _write_wal(path, n=3, framed=False)
    _write_wal(path, n=2, framed=True)
    report = fsck.check_dir(str(tmp_path))
    (f,) = report["files"]
    assert f["clean"]
    assert f["records"] == {"framed": 2, "legacy": 3}


def test_fsck_module_subprocess_smoke(fixture_dir):
    """One real ``python -m filodb_tpu.fsck`` invocation: JSON report,
    exit 1 on findings, exit 0 after --repair."""
    r = subprocess.run(
        [sys.executable, "-m", "filodb_tpu.fsck", str(fixture_dir),
         "--json"],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 1
    report = json.loads(r.stdout)
    assert report["summary"]["files_with_findings"] == 3
    r2 = subprocess.run(
        [sys.executable, "-m", "filodb_tpu.fsck", str(fixture_dir),
         "--repair", "--quiet"],
        capture_output=True, text=True, timeout=60)
    assert r2.returncode == 0
    r3 = subprocess.run(
        [sys.executable, "-m", "filodb_tpu.fsck", str(fixture_dir)],
        capture_output=True, text=True, timeout=60)
    assert r3.returncode == 0
    assert "0 with findings" in r3.stdout


def test_fsck_usage_error_on_missing_dir(tmp_path):
    assert fsck.main([str(tmp_path / "nope")]) == 2
