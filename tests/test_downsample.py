"""Downsampler: device kernel parity vs the numpy oracle, counter boundary
preservation, and the batch job end-to-end (raw chunks -> ds chunks ->
query at coarse resolution).

(Parity model: core/downsample ChunkDownsamplerSpec / ShardDownsampler
tests; BatchDownsampler.scala:119.)"""

import numpy as np
import pytest

from filodb_tpu.core.memstore import TimeSeriesShard
from filodb_tpu.core.record import RecordBuilder
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetRef
from filodb_tpu.downsample import (DownsampledTimeSeriesStore,
                                   DownsamplerJob, ds_dataset)
from filodb_tpu.downsample import kernels
from filodb_tpu.promql.parser import TimeStepParams, parse_query_range
from filodb_tpu.query.engine import QueryEngine
from filodb_tpu.store import FlatFileColumnStore

REF = DatasetRef("timeseries")
RES = 300_000
# period-aligned epoch; samples sit 5s past boundaries so windows aligned
# to periods nest them exactly (inclusive-bounds windows never pick up a
# boundary sample from a neighboring period)
T0_MS = (1_600_000_000_000 // RES) * RES
SAMPLE_OFF = 5_000


def test_gauge_kernel_matches_oracle():
    rng = np.random.default_rng(3)
    S, N = 5, 700
    ts = np.sort(T0_MS + rng.integers(0, 3_600_000, (S, N)), axis=1)
    # force strictly increasing
    ts = ts + np.arange(N)[None, :]
    vals = rng.normal(50, 20, (S, N))
    lens = np.full(S, N, dtype=np.int32)
    lens[2] = 300                      # one short row
    base = (int(ts.min()) // RES) * RES
    nperiods = int((ts.max() - base) // RES) + 1
    sums, cnts, mins, maxs, last_v, last_ts = [
        np.asarray(a) for a in kernels.downsample_gauge_tiles(
            ts, vals, lens, np.int64(base), np.int64(RES), nperiods)]
    for i in range(S):
        o = kernels.downsample_gauge_oracle(ts[i, :lens[i]],
                                            vals[i, :lens[i]], base, RES,
                                            nperiods)
        has = o[1] > 0
        np.testing.assert_allclose(sums[i][has], o[0][has], rtol=1e-12)
        np.testing.assert_array_equal(cnts[i], o[1])
        np.testing.assert_allclose(mins[i][has], o[2][has])
        np.testing.assert_allclose(maxs[i][has], o[3][has])
        np.testing.assert_allclose(last_v[i][has], o[4][has])
        np.testing.assert_array_equal(last_ts[i][has], o[5][has])
        assert np.all(np.isnan(sums[i][~has]))


@pytest.mark.parametrize("phase_off", [0, 8_500])
def test_gauge_regular_fast_path_matches_oracle(phase_off):
    """Reshape fast path == oracle on jittered regular-cadence tiles,
    for both boundary-crossing directions (grid phase below/above
    dt/2)."""
    rng = np.random.default_rng(11)
    S, N, DT = 6, 700, 10_000
    t0 = T0_MS + phase_off
    jit = rng.integers(-2000, 2000, (S, N))
    ts = t0 + np.arange(N, dtype=np.int64)[None, :] * DT + jit
    vals = rng.normal(50, 20, (S, N))
    lens = np.full(S, N, dtype=np.int32)
    base = (int(ts.min()) // RES) * RES
    nperiods = int((ts.max() - base) // RES) + 1
    res = kernels.downsample_gauge_fast(ts, vals, lens, base, RES,
                                        nperiods)
    assert res is not None
    sums, cnts, mins, maxs, last_v, last_ts = [np.asarray(a) for a in res]
    crossings = 0
    for i in range(S):
        o = kernels.downsample_gauge_oracle(ts[i], vals[i], base, RES,
                                            nperiods)
        has = o[1] > 0
        np.testing.assert_allclose(sums[i][has], o[0][has], rtol=1e-12)
        np.testing.assert_array_equal(cnts[i], o[1])
        np.testing.assert_allclose(mins[i][has], o[2][has])
        np.testing.assert_allclose(maxs[i][has], o[3][has])
        np.testing.assert_allclose(last_v[i][has], o[4][has])
        np.testing.assert_array_equal(last_ts[i][has], o[5][has])
        # prove jitter actually moved samples across period boundaries
        naive = (np.arange(N) * DT + (t0 - base)) // RES
        actual = (ts[i] - base) // RES
        crossings += int((naive != actual).sum())
    assert crossings > 0


@pytest.mark.parametrize("phase_off", [-2_000, 3_000, 8_000])
def test_gauge_regular_fast_path_wide_jitter(phase_off):
    """Jitter close to dt/2 with phases that put the first/last ticks on
    the wrong side of the base period boundary (the out-of-slice edge
    tick must still be folded into its period)."""
    rng = np.random.default_rng(29)
    S, N, DT = 4, 500, 10_000
    t0 = T0_MS + phase_off
    jit = rng.integers(-4000, 4000, (S, N))
    ts = t0 + np.arange(N, dtype=np.int64)[None, :] * DT + jit
    vals = rng.normal(0, 30, (S, N))
    lens = np.full(S, N, dtype=np.int32)
    base = (int(ts.min()) // RES) * RES
    nperiods = int((ts.max() - base) // RES) + 1
    res = kernels.downsample_gauge_fast(ts, vals, lens, base, RES,
                                        nperiods)
    assert res is not None
    got = [np.asarray(a) for a in res]
    for i in range(S):
        o = kernels.downsample_gauge_oracle(ts[i], vals[i], base, RES,
                                            nperiods)
        has = o[1] > 0
        np.testing.assert_allclose(got[0][i][has], o[0][has], rtol=1e-12)
        np.testing.assert_array_equal(got[1][i], o[1])
        np.testing.assert_allclose(got[2][i][has], o[2][has])
        np.testing.assert_allclose(got[3][i][has], o[3][has])
        np.testing.assert_allclose(got[4][i][has], o[4][has])
        np.testing.assert_array_equal(got[5][i][has], o[5][has])


def test_gauge_regular_edge_tick_before_base():
    """A first tick whose nominal time precedes the batch base but whose
    jitter lands it inside period 0 must be counted (up-mode edge)."""
    DT = 10_000
    base = T0_MS
    # nominal first tick 2s BEFORE base, jittered +3s into period 0
    nominal = base - 2_000 + np.arange(40, dtype=np.int64) * DT
    ts = nominal.copy()
    ts[0] += 3_000
    vals = np.arange(40, dtype=np.float64)
    S_ts = ts[None, :]
    res = kernels.downsample_gauge_fast(
        S_ts, vals[None, :], np.array([40], np.int32), base, RES,
        int((ts.max() - base) // RES) + 1)
    assert res is not None
    o = kernels.downsample_gauge_oracle(ts, vals, base, RES,
                                        int((ts.max() - base) // RES) + 1)
    np.testing.assert_array_equal(np.asarray(res[1])[0], o[1])
    has = o[1] > 0
    np.testing.assert_allclose(np.asarray(res[0])[0][has], o[0][has])


def test_gauge_regular_fast_path_gates():
    S, N, DT = 2, 600, 10_000
    lens = np.full(S, N, dtype=np.int32)
    ts = T0_MS + np.arange(N, dtype=np.int64)[None, :] * DT \
        + np.zeros((S, 1), np.int64)
    vals = np.zeros((S, N))
    # irregular cadence -> None
    ts_bad = ts.copy()
    ts_bad[0, N // 2:] += 57_000
    assert kernels.downsample_gauge_fast(ts_bad, vals, lens, T0_MS, RES,
                                         4) is None
    # ragged rows -> None
    lens2 = lens.copy()
    lens2[1] = 100
    assert kernels.downsample_gauge_fast(ts, vals, lens2, T0_MS, RES,
                                         4) is None


def test_cascade_aligned_matches_direct():
    rng = np.random.default_rng(5)
    S, N, DT = 4, 1500, 10_000
    res1h = 3_600_000
    ts = T0_MS + np.arange(N, dtype=np.int64)[None, :] * DT \
        + rng.integers(-2000, 2000, (S, N))
    vals = rng.normal(0, 5, (S, N))
    lens = np.full(S, N, dtype=np.int32)
    base5 = (int(ts.min()) // RES) * RES
    base1h = (int(ts.min()) // res1h) * res1h
    nper5 = int((ts.max() - base5) // RES) + 1
    nper1h = int((ts.max() - base1h) // res1h) + 1
    fine = kernels.downsample_gauge_fast(ts, vals, lens, base5, RES, nper5)
    lead = (base5 - base1h) // RES
    casc = [np.asarray(a) for a in kernels.cascade_gauge_aligned(
        fine, res1h // RES, int(lead))]
    for i in range(S):
        o = kernels.downsample_gauge_oracle(ts[i], vals[i], base1h,
                                            res1h, nper1h)
        has = o[1] > 0
        Q = casc[0].shape[1]
        np.testing.assert_allclose(casc[0][i][:Q][has[:Q]],
                                   o[0][has][:Q], rtol=1e-12)
        np.testing.assert_array_equal(casc[1][i][:Q], o[1][:Q])
        np.testing.assert_allclose(casc[2][i][:Q][has[:Q]], o[2][has][:Q])
        np.testing.assert_allclose(casc[3][i][:Q][has[:Q]], o[3][has][:Q])
        np.testing.assert_allclose(casc[4][i][:Q][has[:Q]], o[4][has][:Q])
        np.testing.assert_array_equal(casc[5][i][:Q][has[:Q]],
                                      o[5][has][:Q])


def test_counter_emit_mask_keeps_period_lasts_and_peaks():
    ts = np.arange(1, 61, dtype=np.int64)[None, :] * 10_000 + T0_MS
    vals = np.cumsum(np.full(60, 5.0))
    vals[30:] = np.cumsum(np.full(30, 5.0))        # reset at index 30
    vals = vals[None, :]
    lens = np.array([60], dtype=np.int32)
    base = (int(ts.min()) // RES) * RES
    nperiods = int((ts.max() - base) // RES) + 1
    mask = np.asarray(kernels.counter_emit_mask(
        ts, vals, lens, np.int64(base), np.int64(RES), nperiods))[0]
    assert mask[29]                                # peak before reset
    assert mask[30]                                # reset sample itself
    # last sample of every period kept
    p = (ts[0] - base) // RES
    for period in np.unique(p):
        last_idx = np.max(np.where(p == period))
        assert mask[last_idx], period
    # downsampled increase == raw increase from the first emitted baseline
    # (sum over reset-corrected deltas)
    def total_increase(v):
        d = np.diff(v)
        return float(np.where(d < 0, v[1:], d).sum())
    i0 = int(np.argmax(mask))
    raw = total_increase(vals[0][i0:])
    dsm = total_increase(vals[0][mask])
    assert dsm == pytest.approx(raw)


def _seed_raw(root):
    cs = FlatFileColumnStore(root)
    shard = TimeSeriesShard(REF, DEFAULT_SCHEMAS, 0, max_chunk_rows=128,
                            column_store=cs)
    b = RecordBuilder(DEFAULT_SCHEMAS)
    rng = np.random.default_rng(11)
    for s in range(4):
        labels = {"_metric_": "cpu_seconds", "_ws_": "demo",
                  "_ns_": "App-0", "instance": f"i{s}"}
        for t in range(720):                      # 2h at 10s
            b.add_sample("gauge", labels, T0_MS + SAMPLE_OFF + t * 10_000,
                         float(rng.normal(50, 10)))
    for s in range(2):
        labels = {"_metric_": "reqs_total", "_ws_": "demo",
                  "_ns_": "App-0", "instance": f"i{s}"}
        v = 0.0
        for t in range(720):
            v += 7.0 * (s + 1)
            b.add_sample("prom-counter", labels,
                         T0_MS + SAMPLE_OFF + t * 10_000, v)
    for c in b.containers():
        shard.ingest(c)
    shard.flush_all(offset=1)
    return cs, shard


def test_job_end_to_end_and_query_parity(tmp_path):
    cs, raw_shard = _seed_raw(str(tmp_path / "col"))
    job = DownsamplerJob(cs, resolutions=(RES,))
    stats = job.run("timeseries", 0)
    assert stats.partitions_read == 6
    assert stats.samples_read == 6 * 720
    assert stats.samples_written > 0 and stats.chunks_written > 0

    dstore = DownsampledTimeSeriesStore(cs, "timeseries", 1,
                                        resolutions=(RES,))
    start_s = T0_MS // 1000 + 1800
    end_s = T0_MS // 1000 + 7000
    tsp = TimeStepParams(start_s, 600, end_s)

    # gauge min/max/sum/count over nested windows: EXACT parity with raw
    for q in ["min_over_time(cpu_seconds[10m])",
              "max_over_time(cpu_seconds[10m])",
              "sum_over_time(cpu_seconds[10m])",
              "count_over_time(cpu_seconds[10m])"]:
        plan = parse_query_range(q, tsp)
        picked = dstore.plan_query(plan, 600_000, 600_000)
        assert picked is not None, q
        ds_shards, ds_plan = picked
        got = QueryEngine(ds_shards).execute(ds_plan)
        want = QueryEngine([raw_shard]).execute(plan)
        gmap = {k["instance"]: got.values[i]
                for i, k in enumerate(got.keys)}
        assert len(gmap) == want.num_series, q
        for i, k in enumerate(want.keys):
            np.testing.assert_allclose(
                gmap[k["instance"]], want.values[i], rtol=1e-9,
                equal_nan=True, err_msg=q)

    # counter rate over downsampled boundary samples: windows aligned to
    # periods see the same increase as raw
    plan = parse_query_range("increase(reqs_total[10m])", tsp)
    picked = dstore.plan_query(plan, 600_000, 600_000)
    assert picked is not None
    ds_shards, ds_plan = picked
    got = QueryEngine(ds_shards).execute(ds_plan)
    want = QueryEngine([raw_shard]).execute(plan)
    gmap = {k["instance"]: got.values[i] for i, k in enumerate(got.keys)}
    for i, k in enumerate(want.keys):
        g, w = gmap[k["instance"]], want.values[i]
        ok = np.isfinite(w) & np.isfinite(g)
        assert ok.sum() >= w.size - 2
        # extrapolated rate over sparser points: allow small tolerance
        np.testing.assert_allclose(g[ok], w[ok], rtol=0.05)


def test_resolution_selection():
    from filodb_tpu.downsample.store import select_resolution
    assert select_resolution((300_000, 3_600_000), 600_000, 300_000) == \
        300_000
    assert select_resolution((300_000, 3_600_000), 7_200_000,
                             3_600_000) == 3_600_000
    assert select_resolution((300_000, 3_600_000), 300_000, 60_000) is None


def test_cascade_matches_direct():
    """1h level cascaded from 5m level == 1h computed direct from raw."""
    rng = np.random.default_rng(9)
    S, N = 3, 2000
    ts = np.sort(T0_MS + rng.integers(0, 6 * 3_600_000, (S, N)), axis=1)
    ts = ts + np.arange(N)[None, :]
    vals = rng.normal(0, 100, (S, N))
    lens = np.full(S, N, dtype=np.int32)
    lens[1] = 1200
    base5 = (int(ts.min()) // RES) * RES
    res_h = 3_600_000
    base_h = (int(ts.min()) // res_h) * res_h
    np5 = int((ts.max() - base5) // RES) + 1
    nph = int((ts.max() - base_h) // res_h) + 1
    fine = kernels.downsample_gauge_tiles(ts, vals, lens, np.int64(base5),
                                          np.int64(RES), np5, 64)
    casc = [np.asarray(a) for a in kernels.cascade_gauge(
        fine, np.int64(base_h), np.int64(res_h), nph, 16)]
    direct = [np.asarray(a) for a in kernels.downsample_gauge_tiles(
        ts, vals, lens, np.int64(base_h), np.int64(res_h), nph, 2048)]
    for c, d, name in zip(casc, direct,
                          ["sum", "count", "min", "max", "last", "last_ts"]):
        if name == "last_ts":
            np.testing.assert_array_equal(c, d, err_msg=name)
        else:
            np.testing.assert_allclose(c, d, rtol=1e-9, atol=1e-9,
                                       equal_nan=True, err_msg=name)
