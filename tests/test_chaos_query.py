"""Chaos scenarios over the distributed query path (testing/chaos.py +
parallel/resilience.py): peer death mid-query with opt-in partial
results, fail-fast default within the deadline budget, circuit breaker
open/recover, gRPC->HTTP fallback, peer restart on a new ephemeral port
(sink re-discovery), and ingest-path fault injection.

(The reference covers this ground with Akka multi-jvm kill tests +
queryActorsCircuitBreaker config; the partial-response semantics follow
Thanos/M3 federation behavior.)"""

import json
import socket
import time
import urllib.error
import urllib.parse
import urllib.request

import numpy as np
import pytest

from filodb_tpu.parallel.resilience import (BreakerRegistry, RetryPolicy)
from filodb_tpu.standalone.server import FiloServer
from filodb_tpu.testing import chaos

T0 = 1_600_000_000
N_SAMPLES = 60
N_INSTANCES = 4


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _get(port, path, **params):
    qs = urllib.parse.urlencode(params, doseq=True)
    url = f"http://127.0.0.1:{port}{path}"
    if qs:
        url += "?" + qs
    try:
        with urllib.request.urlopen(url, timeout=120) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _query(port, **extra):
    """Unpruned cross-node range query entering the given node."""
    return _get(port, "/promql/timeseries/api/v1/query_range",
                query='rate({_metric_=~'
                      '"heap_usage|http_requests_total"}[5m])',
                start=T0 + 300, end=T0 + (N_SAMPLES - 1) * 10, step=60,
                **extra)


def _instances(body):
    """Full per-series identity set (series are spread across BOTH
    nodes, so losing one node strictly shrinks this set)."""
    return {tuple(sorted(r["metric"].items()))
            for r in body["data"]["result"]}


@pytest.fixture
def cluster():
    """Two in-process nodes, half the shards each. The failure detector
    polls so slowly it never flips shards DOWN during a test — the
    exec-layer resilience (retries/breakers/partials) is what's under
    test, i.e. the window BEFORE detection reacts."""
    p0, p1 = _free_port(), _free_port()
    peers = {"node0": f"http://127.0.0.1:{p0}",
             "node1": f"http://127.0.0.1:{p1}"}
    base = {
        "num-shards": 4, "num-nodes": 2, "peers": peers,
        "query-sample-limit": 0, "query-series-limit": 0,
        "failure-detect-interval-s": 300.0,
        "grpc-port": None,                  # deterministic HTTP plane
        # exec-layer resilience is under test: every query must actually
        # dial its peers, so the results cache stays out of the loop
        # (its own degraded-result admission guard is pinned by
        # tests/test_resultcache.py chaos scenarios)
        "results-cache-mb": 0,
        "query-timeout-s": 8.0,
        "peer-retry-attempts": 1,           # breaker math: 1 dial/query
        "peer-retry-base-delay-s": 0.01,
        "breaker-failure-threshold": 3,
        "breaker-reset-s": 0.3,
    }
    cfg0 = {**base, "node-ordinal": 0, "port": p0}
    cfg1 = {**base, "node-ordinal": 1, "port": p1}
    a = FiloServer(cfg0).start()
    a.seed_dev_data(n_samples=N_SAMPLES, n_instances=N_INSTANCES,
                    start_ms=T0 * 1000)
    b = FiloServer(cfg1).start()
    b.seed_dev_data(n_samples=N_SAMPLES, n_instances=N_INSTANCES,
                    start_ms=T0 * 1000)
    try:
        yield a, b, cfg1
    finally:
        chaos.uninstall()
        for srv in (a, b):
            try:
                srv.stop()
            except Exception:
                pass


def test_peer_death_mid_query_partial_vs_failfast(cluster):
    a, b, _ = cluster
    code, full = _query(a.port)
    assert code == 200 and "partial" not in full
    all_instances = _instances(full)
    assert len(all_instances) >= N_INSTANCES

    # node1 "dies" mid-query: every leaf fetch to it fails at the fault
    # point (connection-refused shape), while the shard mapper still
    # believes its shards are ACTIVE (detection hasn't reacted yet)
    inj = chaos.ChaosInjector()
    inj.fail("http.peer", match=lambda c: c.get("node") == "node1")
    with inj:
        # default: fail-fast with a clean query error, quickly
        t0 = time.monotonic()
        code, err = _query(a.port)
        elapsed = time.monotonic() - t0
        assert code in (400, 503)
        assert err["status"] == "error"
        assert "node1" in err["error"]
        assert elapsed < 8.0                # no flat-60s hang

        # opt-in: the surviving shards answer, flagged partial with a
        # warning naming the lost shard group
        code, body = _query(a.port, allow_partial="true")
        assert code == 200
        assert body.get("partial") is True
        assert any("node1" in w for w in body["warnings"])
        got = _instances(body)
        assert got and got < all_instances  # strict subset survived
    # chaos removed: full results return
    code, again = _query(a.port)
    assert code == 200 and _instances(again) == all_instances


def test_breaker_opens_stops_dialing_and_recovers(cluster):
    a, b, cfg1 = cluster
    _, full = _query(a.port)
    all_instances = _instances(full)
    b.stop()                                # peer really dies

    # threshold=3, one dial per query: three failing queries open it
    for _ in range(3):
        code, body = _query(a.port, allow_partial="true")
        assert code == 200 and body.get("partial") is True
    breaker = a.http.resilience.breakers.get(
        f"http://127.0.0.1:{b.port}")
    assert breaker.state == "open"

    # open breaker: served partial WITHOUT dialing the dead peer
    counter = chaos.ChaosInjector()         # counting only, no rules
    with counter:
        code, body = _query(a.port, allow_partial="true")
    assert code == 200 and body.get("partial") is True
    assert counter.fired("http.peer") == 0  # no further dials
    assert any("circuit breaker is open" in w for w in body["warnings"])

    # peer returns on the SAME port; after the reset window the
    # half-open probe closes the breaker and results are whole again
    b2 = FiloServer(cfg1).start()
    b2.seed_dev_data(n_samples=N_SAMPLES, n_instances=N_INSTANCES,
                     start_ms=T0 * 1000)
    try:
        time.sleep(0.35)                    # past breaker-reset-s
        deadline = time.monotonic() + 10
        got = set()
        while time.monotonic() < deadline:
            code, body = _query(a.port, allow_partial="true")
            got = _instances(body)
            if code == 200 and got == all_instances \
                    and "partial" not in body:
                break
            time.sleep(0.1)
        assert got == all_instances
        assert breaker.state == "closed"
    finally:
        b2.stop()


def test_blackhole_peer_deadline_budget(cluster):
    """A peer that accepts but never answers (packets dropped) must not
    hang the query: the per-hop timeout is the REMAINING deadline
    budget, and the failure surfaces as a clean error."""
    a, b, _ = cluster
    inj = chaos.ChaosInjector()
    inj.drop("http.peer", match=lambda c: c.get("node") == "node1")
    with inj:
        t0 = time.monotonic()
        code, err = _query(a.port, timeout="1s")
        elapsed = time.monotonic() - t0
    assert code in (400, 503)
    assert err["status"] == "error"
    assert elapsed < 8.0                    # stall (2s) + overhead << 60s


def test_partial_instant_query_shape(cluster):
    a, b, _ = cluster
    inj = chaos.ChaosInjector()
    inj.fail("http.peer", match=lambda c: c.get("node") == "node1")
    with inj:
        code, body = _get(
            a.port, "/promql/timeseries/api/v1/query",
            query='{_metric_=~"heap_usage|http_requests_total"}',
            time=T0 + (N_SAMPLES - 1) * 10, allow_partial="true")
    assert code == 200
    assert body.get("partial") is True
    assert any("node1" in w for w in body["warnings"])


def test_grpc_plane_falls_back_to_http(cluster):
    """gRPC transport failure downgrades leaf dispatch to the HTTP
    control plane instead of failing the query."""
    pytest.importorskip("grpc")
    from filodb_tpu.core.index import ColumnFilter
    from filodb_tpu.grpcsvc.client import GrpcShardGroup
    a, b, _ = cluster
    g = GrpcShardGroup(
        "node1", f"127.0.0.1:{_free_port()}",   # nothing listens here
        "timeseries", None, timeout_s=5.0,
        retry=RetryPolicy(max_attempts=1, base_delay_s=0.0),
        breakers=BreakerRegistry(failure_threshold=99),
        http_fallback=f"http://127.0.0.1:{b.port}")
    series = g.fetch_raw([ColumnFilter("_metric_", "eq", "heap_usage")],
                         T0 * 1000, (T0 + N_SAMPLES * 10) * 1000, None)
    assert len(series) > 0                  # served via the HTTP plane


def test_peer_restart_new_port_updates_grpc_sink():
    """FailureDetector re-points grpc_peer_sink when a peer advertises a
    different host:port, and forgets it while the peer is down (advisor:
    restarted peers were dialed at their dead address forever)."""
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from filodb_tpu.parallel.cluster import FailureDetector
    from filodb_tpu.parallel.shardmapper import ShardMapper

    adv = {"grpc_port": 7001, "healthy": True}

    class H(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            if not adv["healthy"]:
                self.send_error(500)
                return
            body = json.dumps({"status": "healthy", "shards": {},
                               "down_peers": [],
                               "grpc_port": adv["grpc_port"]}).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{httpd.server_port}"
    mapper = ShardMapper(2)
    mapper.assign(0, "node1")
    mapper.assign(1, "node1")
    sink = {}
    det = FailureDetector(mapper, {"node1": url}, {"node1": [0, 1]},
                          threshold=2, timeout_s=2.0,
                          grpc_peer_sink=sink)
    try:
        det.poll_once()
        assert sink == {"node1": "127.0.0.1:7001"}
        # restart on a new ephemeral port: advertisement changes
        adv["grpc_port"] = 7002
        det.poll_once()
        assert sink == {"node1": "127.0.0.1:7002"}
        # peer down: the sink entry is dropped, not kept stale
        httpd.shutdown()
        httpd.server_close()
        det.poll_once()
        det.poll_once()
        assert det.is_down("node1")
        assert "node1" not in sink
    finally:
        try:
            httpd.server_close()
        except OSError:
            pass


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_ingest_chaos_flips_shard_to_error():
    """A failing stream consumer (the Kafka-poll failure analogue) is
    surfaced as shard ERROR status instead of a silent dead thread; the
    driver intentionally re-raises after flipping the status."""
    from filodb_tpu.core.memstore import TimeSeriesShard
    from filodb_tpu.core.record import RecordBuilder
    from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetRef
    from filodb_tpu.ingest import IngestionDriver, MemoryIngestionStream
    from filodb_tpu.parallel.shardmapper import ShardMapper, ShardStatus

    stream = MemoryIngestionStream()
    b = RecordBuilder(DEFAULT_SCHEMAS)
    b.add_sample("prom-counter",
                 {"_metric_": "reqs_total", "_ws_": "demo",
                  "_ns_": "App-0", "instance": "i0"},
                 T0 * 1000, 1.0)
    for c in b.containers():
        stream.append(c)
    mapper = ShardMapper(1)
    shard = TimeSeriesShard(DatasetRef("timeseries"), DEFAULT_SCHEMAS, 0,
                            num_groups=2)
    inj = chaos.ChaosInjector().fail("ingest.batch", times=1)
    with inj:
        drv = IngestionDriver(shard, stream, mapper=mapper,
                              poll_interval_s=0.01).start()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if mapper.status(0) is ShardStatus.ERROR:
                break
            time.sleep(0.02)
        assert mapper.status(0) is ShardStatus.ERROR
        drv.stop(flush=False)
    assert inj.fired("ingest.batch") == 1
