"""Device compile/cost profiling (obs/devprof.py) + &explain=analyze.

Covers: executable-profile accounting (builds, hits, shape-churn
recompiles), AOT cost capture in the tilestore dispatch tables, lazy
cost probes on the packed path, the /metrics collector families, and
the end-to-end &explain=analyze envelope for both the tilestore and
packed kernel paths — with the no-analyze response byte-contract
preserved.
"""

import json
import urllib.parse
import urllib.request

import numpy as np
import pytest

import jax

from filodb_tpu.obs import devprof
from filodb_tpu.obs import metrics as obs_metrics
from filodb_tpu.standalone.server import FiloServer

T0 = 1_600_000_000


# ---------------------------------------------------------------------------
# unit: profiler bookkeeping
# ---------------------------------------------------------------------------

def test_arg_sig_and_key_forms():
    a = np.zeros((4, 8), np.float64)
    b = np.int64(7)
    sig = devprof.arg_sig(((a, a), b))
    assert sig == ((((4, 8), "float64"), ((4, 8), "float64")),
                   ((), "int64"))
    assert devprof.key_str(("slide", "rate", 4, 6)) == "slide/rate/4/6"
    assert devprof.shape_bucket(("slide", "rate", 4, 6)) == "4x6"
    assert devprof.shape_bucket(("x",)) == "x"


def test_profiler_build_hit_recompile_counters():
    p = devprof.DeviceProfiler()
    assert p.note_build("s", ("k", 1), 0.5, sig=("a",)) is False
    assert p.note_build("s", ("k", 1), 0.2) is True       # recompile
    assert p.note_call("s", ("k", 1), sig=("a",)) is False  # known sig
    assert p.note_call("s", ("k", 1), sig=("b",)) is True   # churn
    (e,) = p.snapshot()
    assert e["builds"] == 2 and e["hits"] == 2
    assert e["recompiles"] == 2     # one rebuild + one churned sig
    assert e["build_s_total"] == pytest.approx(0.7)


def test_profiler_lazy_cost_probe_runs_once():
    p = devprof.DeviceProfiler()
    calls = []

    def probe():
        calls.append(1)
        f = jax.jit(lambda x: x + 1.0)
        return f.lower(np.ones(4)).compile()
    p.note_build("s", ("k",), 0.0, lazy_probe=probe)
    c1 = p.ensure_cost("s", ("k",))
    c2 = p.ensure_cost("s", ("k",))
    assert len(calls) == 1
    assert c1 == c2 and c1 is not None
    assert "flops" in c1 or "bytes_accessed" in c1


def test_profiler_collector_families():
    p = devprof.DeviceProfiler()
    p.note_build("tilestore", ("slide", "rate", 4, 6), 0.1,
                 cost={"flops": 12.0, "bytes_accessed": 34.0})
    p.note_build("tilestore", ("slide", "rate", 4, 6), 0.1)  # recompile
    b = obs_metrics.ExpositionBuilder()
    p.collect(b)
    text = b.render()
    assert ('filodb_executable_builds_total{bucket="4x6",'
            'site="tilestore"} 2') in text
    assert ('filodb_executable_recompiles_total{bucket="4x6",'
            'site="tilestore"} 1') in text
    assert ('filodb_executable_flops{executable="slide/rate/4/6",'
            'site="tilestore"} 12.0') in text
    assert "filodb_executables 1" in text


def test_profiled_executable_aot_and_fallback():
    devprof.GLOBAL_PROFILER.reset()
    built = []

    def build():
        built.append(1)
        return jax.jit(lambda x, n: x * 2.0 + n)
    args = (np.ones(8), np.int64(3))
    pe = devprof.build_profiled("t", ("dbl", 8), build, cost_args=args)
    assert len(built) == 1
    out = pe(*args)                       # matches the AOT signature
    assert np.allclose(np.asarray(out), 5.0)
    out2 = pe(np.ones(16), np.int64(3))   # churned shape -> jit path
    assert np.allclose(np.asarray(out2), 5.0)
    snap = {(e["site"], e["executable"]): e
            for e in devprof.GLOBAL_PROFILER.snapshot()}
    e = snap[("t", "dbl/8")]
    assert e["builds"] == 1 and e["hits"] == 2
    assert e["recompiles"] == 1           # the 16-wide retrace
    assert e.get("flops") is not None


def test_analyze_payload_attribution():
    devprof.GLOBAL_PROFILER.reset()
    devprof.GLOBAL_PROFILER.note_build(
        "tilestore", ("fast", "rate", 4), 0.25,
        cost={"flops": 99.0, "bytes_accessed": 11.0})
    spans = [
        {"name": "executable", "dur_us": 0,
         "tags": {"site": "tilestore", "key": "fast/rate/4",
                  "disposition": "build"}},
        {"name": "executable", "dur_us": 0,
         "tags": {"site": "tilestore", "key": "fast/rate/4",
                  "disposition": "aot"}},
        {"name": "device-dispatch", "dur_us": 1200,
         "tags": {"path": "aligned", "batch": 2}},
        {"name": "batcher-dispatch", "dur_us": 0,
         "tags": {"size": 2, "active": 3, "priority": 0}},
        {"name": "parse", "dur_us": 10, "tags": {}},
    ]
    out = devprof.analyze_payload(spans, {"qosShed": "stale"},
                                  batcher_stats={"occupancy_avg": 1.5},
                                  qos_info={"tenant": "t"})
    (e,) = out["device"]["executables"]
    assert e["executable"] == "fast/rate/4"
    assert e["dispatches"] == 2
    assert sorted(e["dispositions"]) == ["aot", "build"]
    assert e["flops"] == 99.0 and e["bytes_accessed"] == 11.0
    names = [d["span"] for d in out["device"]["dispatches"]]
    assert "device-dispatch" in names and "batcher-dispatch" in names
    assert "parse" not in names
    assert out["stages"]["qosShed"] == "stale"
    assert out["batcher"]["occupancy_avg"] == 1.5
    assert out["qos"]["tenant"] == "t"
    assert "residency" not in out          # no snapshot passed


def test_analyze_payload_residency_section():
    """The graftlint v5 residency registry rides &explain=analyze:
    per-family shard breakdown plus a total."""
    out = devprof.analyze_payload(
        [], {}, residency={"shardstore-resident-channels":
                           {"1": 20480, "2": 40960}})
    res = out["residency"]["shardstore-resident-channels"]
    assert res["shards"] == {"1": 20480, "2": 40960}
    assert res["total_bytes"] == 61440
    assert "residency" not in devprof.analyze_payload([], {},
                                                     residency={})


# ---------------------------------------------------------------------------
# e2e: &explain=analyze over a live server
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def server():
    # the tilestore dispatch tables are module-global: earlier test
    # files may have compiled the shapes this fixture queries (and the
    # unit tests above reset the global profiler), which would make
    # the e2e dispatch a profile-less table hit. Clear the tables so
    # the queries below provably BUILD — the disposition/cost
    # assertions then exercise the full miss path. (Later tests just
    # rebuild on demand; the tables are a cache.)
    from filodb_tpu.query import tilestore as tst
    for table in (tst._EVAL_JIT, tst._EVAL_T_JIT, tst._EVAL_VMAP,
                  tst._EVAL_T_VMAP):
        table.clear()
    devprof.GLOBAL_PROFILER.reset()
    srv = FiloServer({"num-shards": 2, "port": 0}).start()
    srv.seed_dev_data(n_samples=60, n_instances=3, start_ms=T0 * 1000)
    yield srv
    srv.stop()


def _get_raw(port, **params):
    qs = urllib.parse.urlencode(params)
    url = (f"http://127.0.0.1:{port}/promql/timeseries/api/v1/"
           f"query_range?{qs}")
    with urllib.request.urlopen(url, timeout=120) as r:
        return r.read()


def test_analyze_tilestore_path(server):
    body = json.loads(_get_raw(
        server.port, query="rate(http_requests_total[5m])",
        start=T0 + 300, end=T0 + 500, step=60, cache="false",
        explain="analyze"))
    az = body["analyze"]
    assert set(az) >= {"stages", "device"}
    execs = az["device"]["executables"]
    ts_execs = [e for e in execs if e["site"].startswith("tilestore")]
    assert ts_execs, f"no tilestore executables in {execs}"
    e = ts_execs[0]
    assert e["dispositions"]            # compile disposition present
    assert e["builds"] >= 1
    assert "flops" in e and "bytes_accessed" in e
    # cache dispositions + stage timings ride the envelope
    assert az["stages"]["resultCache"] in ("off", "miss", "hit",
                                           "partial", "bypass")
    assert "parseMs" in az["stages"]
    # the trace itself still attaches (analyze extends explain=trace)
    assert "trace" in body and body["trace"]["num_spans"] > 0


def test_analyze_packed_path(server):
    body = json.loads(_get_raw(
        server.port, query="min_over_time(http_requests_total[3m])",
        start=T0 + 300, end=T0 + 500, step=67, cache="false",
        explain="analyze"))
    execs = body["analyze"]["device"]["executables"]
    packed = [e for e in execs if e["site"] == "packed"]
    assert packed, f"no packed executables in {execs}"
    e = packed[0]
    assert e["dispositions"]
    assert "flops" in e and "bytes_accessed" in e
    # batcher occupancy at dispatch recorded
    dispatch_spans = [d for d in body["analyze"]["device"]["dispatches"]
                     if d["span"] == "batcher-dispatch"]
    assert dispatch_spans and "size" in dispatch_spans[0]


def test_analyze_instant_path(server):
    qs = urllib.parse.urlencode(dict(
        query="rate(http_requests_total[5m])", time=T0 + 500,
        cache="false", explain="analyze"))
    url = (f"http://127.0.0.1:{server.port}/promql/timeseries/api/v1/"
           f"query?{qs}")
    with urllib.request.urlopen(url, timeout=120) as r:
        body = json.loads(r.read())
    assert "analyze" in body and "stages" in body["analyze"]


def test_no_analyze_responses_stay_canonical(server):
    """Without explain, the response carries neither analyze nor trace
    keys and stays on the canonical compact-encoding fast path."""
    raw = _get_raw(server.port,
                   query="rate(http_requests_total[5m])",
                   start=T0 + 300, end=T0 + 500, step=60)
    parsed = json.loads(raw)
    assert "analyze" not in parsed and "trace" not in parsed
    assert raw == json.dumps(parsed, separators=(",", ":")).encode()


def test_recompile_counter_rides_metrics(server):
    # the queries above compiled executables: the compile-event
    # families must be on /metrics
    url = f"http://127.0.0.1:{server.port}/metrics"
    with urllib.request.urlopen(url, timeout=60) as r:
        text = r.read().decode()
    assert "filodb_executable_builds_total{" in text
    assert "filodb_executables " in text
    assert "filodb_executable_flops{" in text
