"""Device tile store lifecycle: tiles are published per store snapshot
(keyed dataset/shard/part/num_chunks), reused across queries with zero
rebuilds, survive ingest into write buffers (tail steps spliced from the
live path), and are invalidated by flushes.

(Reference model: chunks are immutable once encoded —
memstore/TimeSeriesPartition.scala:248 encodeOneChunkset; queries read
buffers + chunks through one API.)"""

import numpy as np
import pytest

from filodb_tpu.core.memstore import TimeSeriesShard
from filodb_tpu.core.record import RecordBuilder
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetRef
from filodb_tpu.promql.parser import TimeStepParams, parse_query_range
from filodb_tpu.query.engine import QueryEngine
from filodb_tpu.query.tpu import TpuBackend

REF = DatasetRef("timeseries")
T0 = 1_600_000_000


def _ingest(shard, n_samples, t_start_s, n_series=4, metric="reqs_total"):
    b = RecordBuilder(DEFAULT_SCHEMAS)
    for s in range(n_series):
        labels = {"_metric_": metric, "_ws_": "demo", "_ns_": "App-0",
                  "job": "api", "instance": f"i{s}"}
        for t in range(n_samples):
            ts = (t_start_s + t * 10) * 1000
            b.add_sample("prom-counter", labels, ts,
                         10.0 * (s + 1) * (ts - T0 * 1000) / 10_000.0)
    for c in b.containers():
        shard.ingest(c)


def _run(engine, q, start, end, step=60):
    plan = parse_query_range(q, TimeStepParams(start, step, end))
    return engine.execute(plan)


def test_second_identical_query_zero_tile_builds():
    shard = TimeSeriesShard(REF, DEFAULT_SCHEMAS, 0)
    _ingest(shard, 360, T0)
    shard.flush_all()
    backend = TpuBackend()
    engine = QueryEngine([shard], backend=backend)
    r1 = _run(engine, "rate(reqs_total[5m])", T0 + 600, T0 + 3000)
    builds = backend.tile_builds
    assert builds >= 1
    r2 = _run(engine, "rate(reqs_total[5m])", T0 + 600, T0 + 3000)
    assert backend.tile_builds == builds          # ZERO new builds
    np.testing.assert_array_equal(r1.values, r2.values)
    # a different grid over the same snapshot also reuses the tiles
    _run(engine, "rate(reqs_total[5m])", T0 + 900, T0 + 2400, step=30)
    assert backend.tile_builds == builds


def test_ingest_tail_does_not_invalidate_tiles_and_is_correct():
    shard = TimeSeriesShard(REF, DEFAULT_SCHEMAS, 0, max_chunk_rows=10_000)
    _ingest(shard, 300, T0)
    shard.flush_all()
    backend = TpuBackend()
    engine = QueryEngine([shard], backend=backend)
    _run(engine, "rate(reqs_total[5m])", T0 + 600, T0 + 2900)
    builds = backend.tile_builds
    # new samples land in write buffers; published chunks unchanged
    _ingest(shard, 30, T0 + 3000)
    got = _run(engine, "rate(reqs_total[5m])", T0 + 600, T0 + 3290)
    assert backend.tile_builds == builds          # tiles NOT rebuilt
    oracle = QueryEngine([shard], backend=None)
    want = _run(oracle, "rate(reqs_total[5m])", T0 + 600, T0 + 3290)
    # align by labels
    gmap = {tuple(sorted(k.items())): got.values[i]
            for i, k in enumerate(got.keys)}
    for i, k in enumerate(want.keys):
        np.testing.assert_allclose(gmap[tuple(sorted(k.items()))],
                                   want.values[i], rtol=1e-5,
                                   equal_nan=True)


def test_flush_publishes_new_tiles():
    shard = TimeSeriesShard(REF, DEFAULT_SCHEMAS, 0, max_chunk_rows=10_000)
    _ingest(shard, 300, T0)
    shard.flush_all()
    backend = TpuBackend()
    engine = QueryEngine([shard], backend=backend)
    _run(engine, "rate(reqs_total[5m])", T0 + 600, T0 + 2900)
    builds = backend.tile_builds
    _ingest(shard, 30, T0 + 3000)
    shard.flush_all()                              # publishes new chunks
    r = _run(engine, "rate(reqs_total[5m])", T0 + 600, T0 + 3290)
    assert backend.tile_builds == builds + 1       # rebuilt once
    assert np.isfinite(r.values).any()


def test_http_second_query_zero_builds():
    import json
    import urllib.request

    from filodb_tpu.http.server import FiloHttpServer

    shard = TimeSeriesShard(REF, DEFAULT_SCHEMAS, 0)
    _ingest(shard, 360, T0)
    shard.flush_all()
    backend = TpuBackend()
    # results cache off: the second query must reach the DEVICE tile
    # cache (a results-cache hit would short-circuit above it)
    srv = FiloHttpServer({"timeseries": [shard]}, backend=backend,
                         port=0, results_cache_mb=0)
    srv.start()
    try:
        url = (f"http://127.0.0.1:{srv.port}/promql/timeseries/api/v1/"
               f"query_range?query=rate(reqs_total%5B5m%5D)"
               f"&start={T0 + 600}&end={T0 + 3000}&step=60")
        r1 = json.load(urllib.request.urlopen(url))
        assert r1["status"] == "success" and r1["data"]["result"]
        builds = backend.tile_builds
        assert builds >= 1
        r2 = json.load(urllib.request.urlopen(url))
        # wall-clock span timings legitimately differ run to run
        r1["stats"].pop("timings", None)
        r2["stats"].pop("timings", None)
        assert r2 == r1
        assert backend.tile_builds == builds       # ZERO builds on repeat
    finally:
        srv.stop()
