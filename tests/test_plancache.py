"""Plan-cache correctness (serving fast path, PR 3).

Pins the ISSUE's contract: the same query text with different
start/end must HIT the cache and still produce exactly the grids a
fresh parse would; topology and schema changes invalidate; with the
cache disabled, responses are byte-identical (golden comparison —
which also pins the direct-to-bytes matrix encoder against the dict
path both servers share)."""

import json
import urllib.parse
import urllib.request

import pytest

from filodb_tpu.parallel.shardmapper import ShardStatus
from filodb_tpu.promql.parser import TimeStepParams, parse_query_range
from filodb_tpu.query.plancache import PlanCache
from filodb_tpu.standalone.server import FiloServer

T0 = 1_600_000_000


@pytest.fixture(scope="module")
def servers():
    cached = FiloServer({"num-shards": 4, "port": 0}).start()
    cached.seed_dev_data(n_samples=360, n_instances=4,
                         start_ms=T0 * 1000)
    plain = FiloServer({"num-shards": 4, "port": 0,
                        "plan-cache-size": 0}).start()
    plain.seed_dev_data(n_samples=360, n_instances=4,
                        start_ms=T0 * 1000)
    yield cached, plain
    cached.stop()
    plain.stop()


def _get_raw(server, path, **params):
    qs = urllib.parse.urlencode(params, doseq=True)
    url = f"http://127.0.0.1:{server.port}{path}?{qs}"
    with urllib.request.urlopen(url, timeout=30) as r:
        return r.status, r.read()


QUERIES = [
    "rate(http_requests_total[5m])",
    "sum(rate(http_requests_total[5m])) by (instance)",
    "avg_over_time(heap_usage[10m])",
    "max(heap_usage) by (instance)",
    "http_requests_total",
]


def test_cache_hits_produce_identical_bodies(servers):
    """Same text, sliding ranges: the cached server must answer every
    request byte-for-byte like the cache-disabled server, while
    actually serving from the cache (rebased plans)."""
    cached, plain = servers
    pc = cached.http.plan_cache
    base = pc.snapshot()
    for q in QUERIES:
        for k in range(4):          # distinct (start, end) per text
            start = T0 + 600 + k * 97
            end = start + 900 + k * 60
            _, body_c = _get_raw(
                cached, "/promql/timeseries/api/v1/query_range",
                query=q, start=start, end=end, step=60)
            _, body_p = _get_raw(
                plain, "/promql/timeseries/api/v1/query_range",
                query=q, start=start, end=end, step=60)
            # identical modulo the timings block (wall-clock values)
            jc = json.loads(body_c)
            jp = json.loads(body_p)
            tc = jc["stats"].pop("timings")
            tp = jp["stats"].pop("timings")
            assert jc == jp, (q, start, end)
            assert tc["planCache"] in ("hit", "miss")
            assert tp["planCache"] == "off"
    snap = pc.snapshot()
    # first occurrence of each text misses, the 3 reruns hit + rebase
    assert snap["hits"] - base["hits"] >= 3 * len(QUERIES)
    assert snap["rebases"] - base["rebases"] >= 2 * len(QUERIES)


def test_rebased_plan_equals_fresh_parse():
    pc = PlanCache(capacity=8)
    q = "sum(rate(http_requests_total[5m])) by (instance)"
    p0 = parse_query_range(q, TimeStepParams(1000, 60, 2000))
    pc.store("ds", q, 1000 * 1000, 60 * 1000, 2000 * 1000, p0)
    got = pc.lookup("ds", q, 3000 * 1000, 60 * 1000, 4200 * 1000)
    want = parse_query_range(q, TimeStepParams(3000, 60, 4200))
    assert got == want          # dataclass tree equality
    # exact-range hit returns the canonical plan itself
    assert pc.lookup("ds", q, 1000 * 1000, 60 * 1000,
                     2000 * 1000) is p0


def test_uncacheable_shapes_are_not_stored():
    pc = PlanCache(capacity=8)
    # @-pinned evaluation does not rebase on the grid -> uncacheable
    q = "rate(http_requests_total[5m] @ 1500)"
    plan = parse_query_range(q, TimeStepParams(1000, 60, 2000))
    pc.store("ds", q, 1000 * 1000, 60 * 1000, 2000 * 1000, plan)
    assert len(pc) == 0
    assert pc.snapshot()["uncacheable"] == 1
    # subqueries are not lp_replace_range-rewritable either
    q2 = "max_over_time(rate(http_requests_total[1m])[10m:1m])"
    plan2 = parse_query_range(q2, TimeStepParams(1000, 60, 2000))
    pc.store("ds", q2, 1000 * 1000, 60 * 1000, 2000 * 1000, plan2)
    assert len(pc) == 0


def test_topology_change_invalidates(servers):
    cached, _ = servers
    pc = cached.http.plan_cache
    _get_raw(cached, "/promql/timeseries/api/v1/query_range",
             query=QUERIES[0], start=T0 + 600, end=T0 + 1500, step=60)
    assert len(pc) > 0
    inv0 = pc.snapshot()["invalidations"]
    # a shard status transition is a topology change: mapper events
    # clear the cache
    cached.mapper.update(0, ShardStatus.DOWN, cached.node_id)
    assert len(pc) == 0
    assert pc.snapshot()["invalidations"] > inv0
    cached.mapper.update(0, ShardStatus.ACTIVE, cached.node_id)


def test_schema_change_hook_invalidates(servers):
    cached, _ = servers
    pc = cached.http.plan_cache
    _get_raw(cached, "/promql/timeseries/api/v1/query_range",
             query=QUERIES[0], start=T0 + 600, end=T0 + 1500, step=60)
    assert len(pc) > 0
    inv0 = pc.snapshot()["invalidations"]
    cached.http.invalidate_plan_cache("schema")
    assert len(pc) == 0
    assert pc.snapshot()["invalidations"] == inv0 + 1


def test_instant_queries_cache_and_match(servers):
    cached, plain = servers
    for t in (T0 + 900, T0 + 1200):
        _, c = _get_raw(cached, "/promql/timeseries/api/v1/query",
                        query="max(heap_usage) by (instance)", time=t)
        _, p = _get_raw(plain, "/promql/timeseries/api/v1/query",
                        query="max(heap_usage) by (instance)", time=t)
        assert json.loads(c)["data"] == json.loads(p)["data"]


def test_lru_eviction():
    pc = PlanCache(capacity=2)
    q = "rate(http_requests_total[5m])"
    for i in range(3):
        plan = parse_query_range(f"{q} + {i}",
                                 TimeStepParams(1000, 60, 2000))
        pc.store("ds", f"{q} + {i}", 1000 * 1000, 60 * 1000,
                 2000 * 1000, plan)
    assert len(pc) == 2
    assert pc.lookup("ds", f"{q} + 0", 1000 * 1000, 60 * 1000,
                     2000 * 1000) is None   # evicted (LRU)
    assert pc.lookup("ds", f"{q} + 2", 1000 * 1000, 60 * 1000,
                     2000 * 1000) is not None
