"""Memstore ingest→flush→query cycle tests (parity model:
core/src/test/.../memstore/TimeSeriesMemStoreSpec.scala,
TimeSeriesPartitionSpec.scala, PartKeyLuceneIndexSpec.scala)."""

import numpy as np

from filodb_tpu.core.index import ColumnFilter as CF
from filodb_tpu.core.memstore import TimeSeriesMemStore, TimeSeriesShard
from filodb_tpu.core.record import PartKey, RecordBuilder
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetRef

REF = DatasetRef("timeseries")


def _gauge_labels(i):
    return {"_metric_": "heap_usage", "_ws_": "demo", "_ns_": "App-0",
            "host": f"H{i % 4}", "instance": f"inst-{i}"}


def _ingest_series(shard, n_series=10, n_samples=100, t0=1_000_000,
                   step=10_000):
    b = RecordBuilder(DEFAULT_SCHEMAS)
    for t in range(n_samples):
        for s in range(n_series):
            b.add_sample("gauge", _gauge_labels(s), t0 + t * step,
                         float(s * 1000 + t))
    for c in b.containers():
        shard.ingest(c)


def test_ingest_and_lookup():
    shard = TimeSeriesShard(REF, DEFAULT_SCHEMAS, 0)
    _ingest_series(shard, n_series=10, n_samples=50)
    assert shard.stats.rows_ingested == 500
    assert shard.stats.num_series == 10
    parts = shard.lookup_partitions(
        [CF.eq("_metric_", "heap_usage")], 0, 10_000_000_000)
    assert len(parts) == 10
    parts = shard.lookup_partitions(
        [CF.eq("_metric_", "heap_usage"), CF.eq("host", "H1")],
        0, 10_000_000_000)
    assert len(parts) == 3  # instances 1, 5, 9


def test_read_range_merges_chunks_and_buffer():
    shard = TimeSeriesShard(REF, DEFAULT_SCHEMAS, 0, max_chunk_rows=64)
    _ingest_series(shard, n_series=1, n_samples=200)
    part = shard.lookup_partitions([], 0, 1 << 60)[0]
    assert part.num_chunks == 3          # 200 rows / 64 -> 3 encoded + tail
    ts, vals = part.read_range(0, 1 << 60, 1)
    assert ts.size == 200
    np.testing.assert_array_equal(vals, np.arange(200, dtype=np.float64))
    # range slicing: only samples within [t, t2]
    ts2, vals2 = part.read_range(1_000_000 + 50 * 10_000,
                                 1_000_000 + 99 * 10_000, 1)
    assert ts2.size == 50
    np.testing.assert_array_equal(vals2, np.arange(50, 100, dtype=np.float64))


def test_out_of_order_dropped():
    shard = TimeSeriesShard(REF, DEFAULT_SCHEMAS, 0)
    b = RecordBuilder(DEFAULT_SCHEMAS)
    labels = _gauge_labels(0)
    b.add_sample("gauge", labels, 1000, 1.0)
    b.add_sample("gauge", labels, 2000, 2.0)
    b.add_sample("gauge", labels, 1500, 9.0)   # OOO
    b.add_sample("gauge", labels, 2000, 9.0)   # dup
    b.add_sample("gauge", labels, 3000, 3.0)
    for c in b.containers():
        shard.ingest(c)
    assert shard.stats.rows_ingested == 3
    assert shard.stats.out_of_order_dropped == 2
    part = shard.lookup_partitions([], 0, 1 << 60)[0]
    ts, vals = part.read_range(0, 1 << 60, 1)
    np.testing.assert_array_equal(vals, [1.0, 2.0, 3.0])


def test_flush_groups_and_checkpoints():
    shard = TimeSeriesShard(REF, DEFAULT_SCHEMAS, 0, num_groups=4)
    _ingest_series(shard, n_series=8, n_samples=10)
    # nothing encoded yet (buffers below max rows)
    assert shard.stats.chunks_encoded == 0
    for g in range(4):
        shard.flush_group(g, offset=100 + g)
    assert shard.stats.chunks_encoded == 8
    assert shard.recovery_watermark() == 100
    # all data still readable after flush
    part = shard.lookup_partitions([], 0, 1 << 60)[0]
    ts, _ = part.read_range(0, 1 << 60, 1)
    assert ts.size == 10


def test_histogram_ingest_roundtrip():
    from filodb_tpu.memory.histogram import GeometricBuckets
    shard = TimeSeriesShard(REF, DEFAULT_SCHEMAS, 0)
    scheme = GeometricBuckets(2.0, 2.0, 4)
    b = RecordBuilder(DEFAULT_SCHEMAS)
    labels = {"_metric_": "http_latency", "_ws_": "demo", "_ns_": "App-0"}
    counts = np.array([0, 0, 0, 0], dtype=np.int64)
    for t in range(20):
        counts = counts + np.array([1, 2, 3, 4])
        b.add_sample("prom-histogram", labels, 1000 + t * 10,
                     float(counts[-1] * 0.1), float(counts[-1]),
                     (scheme, counts.copy()))
    for c in b.containers():
        shard.ingest(c)
    shard.flush_all()
    part = shard.lookup_partitions([], 0, 1 << 60)[0]
    h_index = part.schema.value_column_index()
    ts, rows = part.read_range(0, 1 << 60, h_index)
    assert rows.shape == (20, 4)
    np.testing.assert_array_equal(rows[-1], [20, 40, 60, 80])


def test_eviction():
    shard = TimeSeriesShard(REF, DEFAULT_SCHEMAS, 0)
    _ingest_series(shard, n_series=5, n_samples=10, t0=1_000)
    shard.flush_all()
    _ingest_series(shard, n_series=1, n_samples=10, t0=10_000_000)
    n = shard.evict_partitions(cutoff_ts=5_000_000)
    # the 4 series not re-ingested at t0=10M get evicted (series 0 overlaps)
    assert n == 4
    assert shard.index.num_parts == 1


def test_memstore_multi_shard():
    store = TimeSeriesMemStore()
    for s in range(4):
        store.setup(REF, s)
    b = RecordBuilder(DEFAULT_SCHEMAS)
    b.add_sample("gauge", _gauge_labels(1), 1000, 42.0)
    for c in b.containers():
        store.ingest(REF, 2, c)
    assert store.get_shard(REF, 2).stats.rows_ingested == 1
    assert len(store.shards(REF)) == 4


def test_label_values_and_names():
    shard = TimeSeriesShard(REF, DEFAULT_SCHEMAS, 0)
    _ingest_series(shard, n_series=8, n_samples=2)
    assert shard.index.label_values("host") == ["H0", "H1", "H2", "H3"]
    assert "host" in shard.index.label_names()
    # filtered label values
    vals = shard.index.label_values(
        "instance", [CF.eq("host", "H0")], 0, 1 << 60)
    assert vals == ["inst-0", "inst-4"]


def test_regex_and_neq_filters():
    shard = TimeSeriesShard(REF, DEFAULT_SCHEMAS, 0)
    _ingest_series(shard, n_series=6, n_samples=2)
    got = shard.lookup_partitions([CF.regex("host", "H[01]")], 0, 1 << 60)
    assert len(got) == 4  # hosts H0 (0,4), H1 (1,5)
    got = shard.lookup_partitions([CF.neq("host", "H0")], 0, 1 << 60)
    assert len(got) == 4
    got = shard.lookup_partitions([CF.prefix("instance", "inst-")], 0, 1 << 60)
    assert len(got) == 6


def test_concurrent_reads_during_flush_no_corruption():
    """Concurrent query threads sharing the decode cache while flushes
    publish chunks must never duplicate or drop samples (ADVICE r2 high:
    unsynchronized decode-cache population)."""
    import threading

    shard = TimeSeriesShard(REF, DEFAULT_SCHEMAS, 0, max_chunk_rows=32)
    part_holder = {}
    stop = threading.Event()
    errors = []

    def reader():
        while not stop.is_set():
            part = part_holder.get("p")
            if part is None:
                continue
            ts, vals, _ = part.read_full(1)
            if ts.size:
                if np.any(np.diff(ts) <= 0):
                    errors.append("non-monotonic/duplicated timestamps")
                    return
                if vals.shape[0] != ts.shape[0]:
                    errors.append("ts/vals length mismatch")
                    return

    threads = [threading.Thread(target=reader) for _ in range(4)]
    for t in threads:
        t.start()
    try:
        b = RecordBuilder(DEFAULT_SCHEMAS)
        t0 = 1_000_000
        for t in range(600):
            b.add_sample("gauge", _gauge_labels(0), t0 + t * 1000, float(t))
        for c in b.containers():
            shard.ingest(c)
            part_holder["p"] = next(iter(shard.partitions.values()))
            shard.flush_all()
    finally:
        stop.set()
        for t in threads:
            t.join()
    assert not errors, errors
    part = part_holder["p"]
    ts, vals, _ = part.read_full(1)
    assert ts.size == 600
    np.testing.assert_array_equal(vals, np.arange(600, dtype=np.float64))


def _one(shard, labels, ts_ms, val=1.0):
    b = RecordBuilder(DEFAULT_SCHEMAS)
    b.add_sample("gauge", labels, ts_ms, val)
    for c in b.containers():
        shard.ingest(c)


def test_ingest_watermark_is_min_over_partitions():
    """The watermark is a SETTLED-time bound: min over per-partition
    last timestamps. The OOO guard is per-partition, so a lagging
    series can still ingest far below the freshest series' last — the
    max would claim those steps settled, the min never does."""
    shard = TimeSeriesShard(REF, DEFAULT_SCHEMAS, 0)
    assert shard.ingest_watermark_ms == -1
    _ingest_series(shard, n_series=2, n_samples=10, t0=1_000_000,
                   step=10_000)
    assert shard.ingest_watermark_ms == 1_000_000 + 9 * 10_000
    # OOO rows are dropped and must not move the watermark backwards
    b = RecordBuilder(DEFAULT_SCHEMAS)
    b.add_sample("gauge", _gauge_labels(0), 500_000, 1.0)
    for c in b.containers():
        shard.ingest(c)
    assert shard.ingest_watermark_ms == 1_000_000 + 9 * 10_000
    # a fresher series does NOT raise the bound: series 0 (still at
    # 1_090_000) can legitimately ingest anywhere above its own last
    _one(shard, _gauge_labels(1), 2_000_000)
    assert shard.ingest_watermark_ms == 1_000_000 + 9 * 10_000
    # ...and does: 1_500_000 lands fine despite being < the max
    _one(shard, _gauge_labels(0), 1_500_000)
    assert shard.stats.out_of_order_dropped == 1    # only the 500_000 row
    # the laggard advanced: the min rises to the new laggard
    assert shard.ingest_watermark_ms == 1_500_000
    _one(shard, _gauge_labels(0), 3_000_000)
    assert shard.ingest_watermark_ms == 2_000_000   # series 1 lags now


def test_backfill_epoch_bumps_on_new_series_below_watermark():
    """A NEW series is outside every per-partition OOO guard and can
    land below the watermark, dirtying steps already considered
    settled; the shard flags the event with a monotone epoch the
    results cache invalidates on."""
    shard = TimeSeriesShard(REF, DEFAULT_SCHEMAS, 0)
    _one(shard, _gauge_labels(0), 10_000)
    assert shard.ingest_backfill_epoch == 0     # first contribution
    # entering ABOVE the watermark touches no settled step: no bump
    _one(shard, _gauge_labels(1), 50_000)
    assert shard.ingest_backfill_epoch == 0
    assert shard.ingest_watermark_ms == 10_000
    # entering AT/BELOW the watermark is a backfill into settled time
    _one(shard, _gauge_labels(2), 4_000)
    assert shard.ingest_backfill_epoch == 1
    assert shard.ingest_watermark_ms == 4_000   # entrant joins the min


def test_decode_cache_bytes_and_trim(tmp_path):
    """The decode/merge caches are observable and boundable: persisted
    partitions release their decoded duplicates under a byte budget,
    and reads after a trim re-decode correctly."""
    from filodb_tpu.store import FlatFileColumnStore
    cs = FlatFileColumnStore(str(tmp_path / "col"))
    shard = TimeSeriesShard(REF, DEFAULT_SCHEMAS, 0, num_groups=1,
                            max_chunk_rows=32, column_store=cs)
    _ingest_series(shard, n_series=4, n_samples=96)
    shard.flush_all(offset=0)               # everything persisted
    assert shard.decode_cache_bytes() == 0  # nothing read yet
    parts = shard.lookup_partitions([], 0, 2**62)
    before = [p.read_full(1) for p in parts]
    used = shard.decode_cache_bytes()
    assert used > 0
    # over-budget: persisted partitions give their caches back
    freed = shard.trim_decode_caches(max_bytes=1)
    assert freed > 0
    assert shard.decode_cache_bytes() < used
    # under-budget: a no-op
    assert shard.trim_decode_caches(max_bytes=1 << 30) == 0
    # reads after the trim re-decode to identical data
    for p, (ts, vals, chunk_len) in zip(parts, before):
        ts2, vals2, chunk_len2 = p.read_full(1)
        np.testing.assert_array_equal(ts, ts2)
        np.testing.assert_array_equal(vals, vals2)
        assert chunk_len == chunk_len2


def test_trim_decode_caches_keeps_unpersisted_partitions():
    """Without a column store nothing is persisted: caches are the only
    decode of in-memory chunks' hot read path and must survive a trim."""
    shard = TimeSeriesShard(REF, DEFAULT_SCHEMAS, 0, num_groups=1,
                            max_chunk_rows=32)
    _ingest_series(shard, n_series=2, n_samples=64)
    shard.flush_all()
    for p in shard.lookup_partitions([], 0, 2**62):
        p.read_full(1)
    used = shard.decode_cache_bytes()
    assert used > 0
    assert shard.trim_decode_caches(max_bytes=1) == 0
    assert shard.decode_cache_bytes() == used
