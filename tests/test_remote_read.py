"""Prometheus remote-read: snappy codec, protobuf wire, HTTP endpoint.

(remote-storage.proto + PrometheusApiRoute.scala:129.)
"""

import urllib.request

import numpy as np
import pytest

from filodb_tpu.http import remote_read as rr
from filodb_tpu.standalone.server import FiloServer

T0 = 1_600_000_000


# --- snappy ---------------------------------------------------------------

@pytest.mark.parametrize("n", [0, 1, 59, 60, 61, 255, 256, 70_000])
def test_snappy_roundtrip_sizes(n):
    rng = np.random.default_rng(n)
    data = rng.integers(0, 256, n, dtype=np.uint8).tobytes()
    assert rr.snappy_decompress(rr.snappy_compress(data)) == data


def test_snappy_decompress_copies():
    """Hand-built compressed stream with all three copy tags (a real
    compressor emits these; our decompressor must handle them)."""
    # "abcdabcdabcdXY" via literal 'abcd' + copy(offset=4, len=8) + 'XY'
    out = bytearray()
    out += bytes([14])                          # uvarint ulen=14
    out += bytes([(4 - 1) << 2]) + b"abcd"      # literal len 4
    out += bytes([((8 - 4) << 2) | 1, 4])      # copy1: len=8, offset=4
    out += bytes([(2 - 1) << 2]) + b"XY"        # literal 'XY'
    assert rr.snappy_decompress(bytes(out)) == b"abcdabcdabcdXY"
    # copy2 form
    out2 = bytearray()
    out2 += bytes([8])
    out2 += bytes([(4 - 1) << 2]) + b"wxyz"
    out2 += bytes([((4 - 1) << 2) | 2]) + (4).to_bytes(2, "little")
    assert rr.snappy_decompress(bytes(out2)) == b"wxyzwxyz"


# --- protobuf wire --------------------------------------------------------

def test_read_request_roundtrip():
    queries = [{"start_ms": T0 * 1000, "end_ms": (T0 + 600) * 1000,
                "matchers": [("__name__", "eq", "cpu"),
                             ("instance", "re", "i.*"),
                             ("dc", "neq", "east")]}]
    buf = rr.encode_read_request(queries)
    assert rr.decode_read_request(buf) == queries


def test_read_response_roundtrip():
    results = [[({"__name__": "cpu", "instance": "i0"},
                 [(T0 * 1000, 1.5), (T0 * 1000 + 10_000, -2.25)])],
               []]
    buf = rr.encode_read_response(results)
    got = rr.decode_read_response(buf)
    assert got == [[({"__name__": "cpu", "instance": "i0"},
                     [(T0 * 1000, 1.5), (T0 * 1000 + 10_000, -2.25)])],
                   []]


# --- endpoint -------------------------------------------------------------

def test_remote_read_endpoint():
    srv = FiloServer({"num-shards": 2, "port": 0}).start()
    try:
        srv.seed_dev_data(n_samples=30, n_instances=2,
                          start_ms=T0 * 1000)
        req_body = rr.snappy_compress(rr.encode_read_request([{
            "start_ms": T0 * 1000,
            "end_ms": (T0 + 300) * 1000,
            "matchers": [("__name__", "eq", "heap_usage")],
        }]))
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/promql/timeseries/api/v1/read",
            data=req_body,
            headers={"Content-Type": "application/x-protobuf",
                     "Content-Encoding": "snappy"})
        with urllib.request.urlopen(req, timeout=60) as r:
            assert r.headers["Content-Type"] == "application/x-protobuf"
            assert r.headers["Content-Encoding"] == "snappy"
            payload = r.read()
        results = rr.decode_read_response(rr.snappy_decompress(payload))
        assert len(results) == 1
        series = results[0]
        assert len(series) == 2                 # two instances
        for labels, samples in series:
            assert labels["__name__"] == "heap_usage"
            assert "_metric_" not in labels
            assert len(samples) == 30
            ts = [t for t, _ in samples]
            assert ts == sorted(ts)
    finally:
        srv.stop()


def test_snappy_bomb_rejected():
    """A tiny body declaring a huge output must be rejected up front."""
    bomb = bytearray()
    n = 1 << 40
    while True:
        b = n & 0x7F
        n >>= 7
        bomb.append(b | (0x80 if n else 0))
        if not n:
            break
    bomb += bytes([0]) + b"x"
    with pytest.raises(ValueError, match="limit|too long"):
        rr.snappy_decompress(bytes(bomb))
    # a 5-byte varint within spec but over the byte limit also rejects
    big = rr.snappy_compress(b"x" * 100)
    with pytest.raises(ValueError, match="limit"):
        rr.snappy_decompress(big, max_len=10)
