"""Chaos scenarios for elastic membership: the ROADMAP tentpole target
(rolling restart of a 2-node cluster with ZERO failed queries under
continuous load, no dual-ingest window) plus mid-handoff faults — the
successor dying mid-replay (shard falls back to the draining owner) and
the draining node dying halfway (remaining shards take the normal
crash/adoption path).

The per-shard single-writer invariant is pinned by a sampler thread
that continuously counts live ingestion drivers per shard across every
node object in the cluster: the make-before-break protocol stops the
old writer (with a final flush) strictly before the successor's
replay driver starts."""

import json
import socket
import threading
import time
import urllib.error
import urllib.parse
import urllib.request

from filodb_tpu.core.schemas import DEFAULT_SCHEMAS
from filodb_tpu.gateway.producer import TestTimeseriesProducer
from filodb_tpu.ingest import LogIngestionStream
from filodb_tpu.lint.threads import thread_root
from filodb_tpu.standalone.server import FiloServer
from filodb_tpu.testing import chaos

T0 = 1_600_000_000
N_SAMPLES = 50
N_INSTANCES = 4
NUM_SHARDS = 4


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _get(port, path, **params):
    qs = urllib.parse.urlencode(params, doseq=True)
    url = f"http://127.0.0.1:{port}{path}"
    if qs:
        url += "?" + qs
    try:
        with urllib.request.urlopen(url, timeout=60) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _post(port, path, body=None, **params):
    qs = urllib.parse.urlencode(params, doseq=True)
    url = f"http://127.0.0.1:{port}{path}"
    if qs:
        url += "?" + qs
    req = urllib.request.Request(
        url, data=json.dumps(body or {}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=120) as r:
        return r.status, json.loads(r.read())


def _query(port, **extra):
    """Settled-range query touching every shard: its result must be
    byte-stable across the whole roll (data is fully ingested and
    flushed before the restarts begin)."""
    return _get(port, "/promql/timeseries/api/v1/query_range",
                query='rate({_metric_=~'
                      '"heap_usage|http_requests_total"}[5m])',
                start=T0 + 300, end=T0 + (N_SAMPLES - 1) * 10, step=60,
                **extra)


def _result_data(body):
    rows = [(tuple(sorted(r["metric"].items())), r.get("values"))
            for r in body["data"]["result"]]
    return sorted(rows)


def _approx_equal(got, want, rtol=1e-5):
    """Same series set, same step timestamps, values within float32
    noise. Byte-exactness only holds per entry node within a stable
    ownership regime (local series with live buffer tails evaluate
    f64-spliced, remote-fetched ones ride the f32 device tiles); the
    continuous-load invariant across regime changes is numeric
    identity, with the exact-bytes pin applied entry-per-entry once
    ownership is back to stable."""
    if len(got) != len(want):
        return False
    for (gk, gv), (wk, wv) in zip(got, want):
        if gk != wk or len(gv or ()) != len(wv or ()):
            return False
        for (gt, gx), (wt, wx) in zip(gv or (), wv or ()):
            if gt != wt:
                return False
            fg, fw = float(gx), float(wx)
            if abs(fg - fw) > rtol * max(abs(fg), abs(fw), 1e-9):
                return False
    return True


def _shard_owners(port):
    _, body = _get(port, "/api/v1/cluster/timeseries/status")
    return {s["shard"]: (s["status"], s["address"])
            for s in body["data"]}


def _poll(fn, timeout=90.0, interval=0.1):
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        try:
            ok, last = fn()
            if ok:
                return last
        except (urllib.error.URLError, ConnectionError, OSError):
            pass
        time.sleep(interval)
    raise TimeoutError(f"poll timed out; last={last!r}")


class _Producer:
    """The test owns the WAL producer plane (the gateway analogue): one
    writer stream per shard, independent of any node's lifecycle — a
    rolling restart must not take the ingest edge down with a node."""

    def __init__(self, stream_dir):
        import os
        self.prod = TestTimeseriesProducer(DEFAULT_SCHEMAS,
                                           num_shards=NUM_SHARDS)
        self.streams = {}
        for sh in range(NUM_SHARDS):
            path = os.path.join(stream_dir, f"shard={sh}", "stream.log")
            self.streams[sh] = LogIngestionStream(path, DEFAULT_SCHEMAS)

    def write(self, start_ms, n_samples):
        for builders in (self.prod.gauges(start_ms, n_samples,
                                          N_INSTANCES),
                         self.prod.counters(start_ms, n_samples,
                                            N_INSTANCES)):
            for sh, b in builders.items():
                for c in b.containers():
                    self.streams[sh].append(c)

    def close(self):
        for s in self.streams.values():
            s.close()


class _Cluster:
    """Two in-process streaming nodes over shared data/stream dirs."""

    def __init__(self, tmp_path, grace=0.75):
        self.ports = [_free_port(), _free_port()]
        peers = {f"node{i}": f"http://127.0.0.1:{p}"
                 for i, p in enumerate(self.ports)}
        self.base = {
            "num-shards": NUM_SHARDS, "num-nodes": 2, "peers": peers,
            "data-dir": str(tmp_path / "data"),
            "stream-dir": str(tmp_path / "streams"),
            "flush-interval-s": 0.4,
            # chunks close at 25 rows: the settled corpus (N_SAMPLES
            # per series) is fully CHUNK-resident before the roll, so
            # its evaluation path — and therefore its response bytes —
            # is restart-stable. Buffer-resident tails are not (a
            # rebuilt node reloads them as chunks), which is an
            # engine-wide property independent of membership.
            "max-chunks-size": 25,
            "query-sample-limit": 0, "query-series-limit": 0,
            "failure-detect-interval-s": 0.2,
            "failure-detect-threshold": 2,
            "shard-reassign-grace-s": grace,
            "grpc-port": None,
            "handoff-timeout-s": 25.0,
        }
        self.cfgs = [{**self.base, "node-ordinal": i,
                      "port": self.ports[i]} for i in range(2)]
        # live server objects by ordinal (None while a node is down);
        # the single-writer sampler reads this
        self.nodes = [FiloServer(dict(self.cfgs[0])).start(),
                      FiloServer(dict(self.cfgs[1])).start()]

    def stop(self):
        for srv in self.nodes:
            if srv is not None:
                try:
                    srv.stop()
                except Exception:
                    pass


class _WriterSampler(threading.Thread):
    """Continuously asserts the per-shard single-writer invariant: at
    most one live ingestion driver per shard across all node objects."""

    def __init__(self, cluster):
        super().__init__(daemon=True)
        self.cluster = cluster
        self.violations = []
        self._halt = threading.Event()

    @thread_root("chaos-writer-sampler")
    def run(self):
        while not self._halt.wait(0.01):
            writers = {}
            for srv in list(self.cluster.nodes):
                if srv is None:
                    continue
                for sh, drv in list(srv.drivers.items()):
                    th = getattr(drv, "_thread", None)
                    if th is not None and th.is_alive() \
                            and not drv._stop.is_set():
                        writers.setdefault(sh, []).append(srv.node_id)
            for sh, nodes in writers.items():
                if len(nodes) > 1:
                    self.violations.append((sh, tuple(nodes)))

    def stop(self):
        self._halt.set()
        self.join(timeout=5)


class _QueryLoad(threading.Thread):
    """Continuous query load against the currently-designated entry
    node; records every failure and every response whose settled-range
    data deviates from the golden answer."""

    def __init__(self, entry, golden, allow_partial=False):
        super().__init__(daemon=True)
        self.entry = entry              # mutable {"port": int}
        self.golden = golden
        self.allow_partial = allow_partial
        self.failures = []
        self.mismatches = []
        self.partials = 0
        self.ok = 0
        self._halt = threading.Event()

    @thread_root("chaos-query-load")
    def run(self):
        while not self._halt.is_set():
            port = self.entry["port"]
            extra = {"allow_partial": "true"} if self.allow_partial \
                else {}
            try:
                code, body = _query(port, **extra)
            except (OSError, ValueError) as e:
                if port != self.entry["port"]:
                    continue            # raced an entry switch
                self.failures.append(f"transport: {e}")
                continue
            if code != 200 or body.get("status") != "success":
                self.failures.append((code, body.get("error")))
                continue
            if body.get("partial"):
                if not self.allow_partial:
                    self.failures.append(("partial", body.get(
                        "warnings")))
                else:
                    self.partials += 1
                continue
            if not _approx_equal(_result_data(body), self.golden):
                self.mismatches.append(len(body["data"]["result"]))
                continue
            self.ok += 1
            # yield: the load must exercise the roll, not starve the
            # ingest/replay threads of the GIL on small CI hosts
            self._halt.wait(0.05)

    def stop(self):
        self._halt.set()
        self.join(timeout=30)


def _wait_full_results(port, want_series, timeout=150):
    def probe():
        code, body = _query(port)
        ok = (code == 200 and "partial" not in body
              and len(body["data"]["result"]) >= want_series)
        return ok, len(body["data"]["result"]) if code == 200 else code
    return _poll(probe, timeout=timeout)


def test_rolling_restart_zero_failed_queries(tmp_path):
    """The acceptance scenario: restart BOTH nodes of a 2-node cluster
    in sequence (drain -> stop -> rejoin -> hand back) under continuous
    query load — zero failed queries, zero result deviations, and no
    instant with two live writers for any shard."""
    producer = _Producer(str(tmp_path / "streams"))
    cluster = _Cluster(tmp_path)
    sampler = _WriterSampler(cluster)
    load = None
    try:
        producer.write(T0 * 1000, N_SAMPLES)
        _wait_full_results(cluster.ports[0], 2 * N_INSTANCES)
        _wait_full_results(cluster.ports[1], 2 * N_INSTANCES)
        # settle: a FULL flush-group rotation moves every settled row
        # into chunks, so the byte-identity reference is free of write-
        # buffer tails (a restarted node reloads the same chunks from
        # the ColumnStore; buffer splits are not restart-stable)
        time.sleep(4.0)
        code, full = _query(cluster.ports[0])
        golden = _result_data(full)
        # per-entry exact goldens: the "stable cluster" reference each
        # node must reproduce byte-for-byte once the roll completes and
        # ownership is back where it started
        golden_exact = {p: _result_data(_query(p)[1])
                        for p in cluster.ports}
        assert _approx_equal(golden_exact[cluster.ports[1]], golden)

        sampler.start()
        entry = {"port": cluster.ports[0]}
        load = _QueryLoad(entry, golden)
        load.start()

        for victim in (1, 0):
            survivor = 1 - victim
            entry["port"] = cluster.ports[survivor]
            time.sleep(0.3)             # drain in-flight entry switches
            srv = cluster.nodes[victim]
            code, out = _post(srv.port, "/admin/drain")
            assert code == 200 and out["data"]["failed"] == [], out
            # live ingest continues mid-roll through the shared WAL
            producer.write((T0 + (N_SAMPLES + victim * 10) * 10) * 1000,
                           5)
            srv.stop()
            cluster.nodes[victim] = None
            surv = cluster.nodes[survivor]
            _poll(lambda: (surv.detector.is_down(f"node{victim}"),
                           None))
            _poll(lambda: (surv.detector._reassigned.get(
                f"node{victim}", False), None), timeout=60)
            # rejoin: deferral + planned hand-back
            back = FiloServer(dict(cluster.cfgs[victim])).start()
            cluster.nodes[victim] = back

            def _handed_back():
                st = _shard_owners(surv.port)
                mine = [sh for sh in range(NUM_SHARDS)
                        if sh in back.owned_shards]
                ok = all(st[sh] == ("active", f"node{victim}")
                         for sh in mine)
                return ok, st
            _poll(_handed_back, timeout=90)
            # both entries serve the golden settled range again
            for port in (surv.port, back.port):
                code, body = _query(port)
                assert code == 200
                assert _approx_equal(_result_data(body), golden)

        load.stop()
        sampler.stop()
        assert load.ok > 0
        assert load.failures == [], load.failures[:5]
        assert load.mismatches == [], load.mismatches[:5]
        assert sampler.violations == [], sampler.violations[:5]

        # ownership is back to the stable layout: each entry must now
        # answer BYTE-IDENTICALLY to its own pre-roll stable-cluster
        # response (the handoff RECOVERY windows are over)
        for port in cluster.ports:
            def _exact(p=port):
                _, body = _query(p)
                return (_result_data(body) == golden_exact[p],
                        len(body["data"]["result"]))
            _poll(_exact, timeout=60, interval=0.5)

        # the mid-roll WAL appends were consumed by whoever owned each
        # shard at the time: both nodes agree on the full tail
        def _tails_agree():
            c0, b0 = _get(
                cluster.nodes[0].port,
                "/promql/timeseries/api/v1/query_range",
                query='{_metric_="heap_usage"}',
                start=T0, end=T0 + (N_SAMPLES + 30) * 10, step=10)
            c1, b1 = _get(
                cluster.nodes[1].port,
                "/promql/timeseries/api/v1/query_range",
                query='{_metric_="heap_usage"}',
                start=T0, end=T0 + (N_SAMPLES + 30) * 10, step=10)
            if c0 != 200 or c1 != 200:
                return False, (c0, c1)
            d0, d1 = _result_data(b0), _result_data(b1)
            n0 = sum(len(v or ()) for _, v in d0)
            want = N_INSTANCES * (N_SAMPLES + 10)
            return (_approx_equal(d0, d1) and n0 >= want), (n0, want)
        _poll(_tails_agree, timeout=60)
    finally:
        if load is not None and load.is_alive():
            load.stop()
        if sampler.is_alive():
            sampler.stop()
        chaos.uninstall()
        cluster.stop()
        producer.close()


def test_successor_unreachable_mid_handoff_falls_back(tmp_path):
    """The successor never advertises ACTIVE (it died / is partitioned
    mid-replay): the shard must FALL BACK to the draining owner — its
    driver restarts from the checkpoint and queries keep answering in
    full — never go dark or flip to a half-replayed copy."""
    producer = _Producer(str(tmp_path / "streams"))
    cluster = _Cluster(tmp_path)
    sampler = _WriterSampler(cluster)
    load = None
    try:
        producer.write(T0 * 1000, N_SAMPLES)
        _wait_full_results(cluster.ports[0], 2 * N_INSTANCES)
        _wait_full_results(cluster.ports[1], 2 * N_INSTANCES)
        time.sleep(1.0)
        golden = _result_data(_query(cluster.ports[0])[1])
        a, b = cluster.nodes
        node1_shards = sorted(sh for sh, (_, n) in
                              _shard_owners(a.port).items()
                              if n == "node1")

        sampler.start()
        entry = {"port": a.port}
        load = _QueryLoad(entry, golden)
        load.start()

        inj = chaos.ChaosInjector()
        inj.fail("handoff.await")       # successor looks dead forever
        with inj:
            code, out = _post(b.port, "/admin/drain",
                              timeout="3")
        assert code == 200
        assert out["data"]["handed_off"] == [], out
        assert {f["shard"] for f in out["data"]["failed"]} \
            == set(node1_shards)

        # rolled back: node1 still owns + ingests its shards, node0's
        # half-adoption was aborted and its mapper claim restored
        st = _shard_owners(b.port)
        assert all(st[sh] == ("active", "node1")
                   for sh in node1_shards), st
        assert all(sh in b.drivers for sh in node1_shards)

        def _aborted_on_a():
            local = {s.shard_num for s in a.store.shards(a.ref)}
            st_a = _shard_owners(a.port)
            ok = all(sh not in local
                     and st_a[sh] == ("active", "node1")
                     for sh in node1_shards)
            return ok, (sorted(local), st_a)
        _poll(_aborted_on_a, timeout=30)
        assert b.membership.metrics_snapshot()["handoffs_failed"] \
            == len(node1_shards)

        time.sleep(0.5)                 # keep the load running a beat
        load.stop()
        sampler.stop()
        assert load.ok > 0
        assert load.failures == [], load.failures[:5]
        assert load.mismatches == [], load.mismatches[:5]
        assert sampler.violations == []
        # and the cluster still answers in full from both entries
        for port in (a.port, b.port):
            code, body = _query(port)
            assert code == 200
            assert _approx_equal(_result_data(body), golden)
    finally:
        if load is not None and load.is_alive():
            load.stop()
        if sampler.is_alive():
            sampler.stop()
        chaos.uninstall()
        cluster.stop()
        producer.close()


def test_draining_node_dies_halfway_crash_path_covers_the_rest(
        tmp_path):
    """kill -9 halfway through a drain: the already-handed-off shard
    stays with its new owner; the shards still on the dead node take
    the normal crash/adoption path. Continuous load (allow_partial)
    sees zero non-partial failures throughout."""
    producer = _Producer(str(tmp_path / "streams"))
    cluster = _Cluster(tmp_path, grace=0.6)
    sampler = _WriterSampler(cluster)
    load = None
    try:
        producer.write(T0 * 1000, N_SAMPLES)
        _wait_full_results(cluster.ports[0], 2 * N_INSTANCES)
        _wait_full_results(cluster.ports[1], 2 * N_INSTANCES)
        time.sleep(1.0)
        golden = _result_data(_query(cluster.ports[0])[1])
        a, b = cluster.nodes
        node1_shards = sorted(sh for sh, (_, n) in
                              _shard_owners(a.port).items()
                              if n == "node1")
        assert len(node1_shards) >= 2

        sampler.start()
        entry = {"port": a.port}
        load = _QueryLoad(entry, golden, allow_partial=True)
        load.start()

        # "halfway": hand ONE shard off cleanly...
        ok, err = b.membership.handoff_shard(node1_shards[0], "node0")
        assert ok, err
        # ...then the draining node dies with the rest still on it
        b.stop()
        cluster.nodes[1] = None

        # the crash path adopts the remaining shards on node0
        def _recovered():
            st = _shard_owners(a.port)
            ok = all(s == "active" and n == "node0"
                     for s, n in st.values())
            return ok, st
        _poll(_recovered, timeout=90)
        _poll(lambda: ((lambda d: (_approx_equal(d, golden), len(d)))(
            _result_data(_query(a.port)[1]))), timeout=90)

        load.stop()
        sampler.stop()
        assert load.ok > 0
        # zero NON-partial failures; partial responses during the
        # detection/adoption window are the designed degraded mode
        assert load.failures == [], load.failures[:5]
        assert sampler.violations == []
    finally:
        if load is not None and load.is_alive():
            load.stop()
        if sampler.is_alive():
            sampler.stop()
        chaos.uninstall()
        cluster.stop()
        producer.close()
