"""gRPC query service (grpcsvc): protobuf wire round-trips, FetchRaw /
Exec parity with the HTTP path, wire-size wins over the JSON hop, and
span-bounded leaf payloads.

(Reference: http/PromQLGrpcServer.scala:44, grpc/src/main/protobuf/
query_service.proto + range_vector.proto; SerializedRangeVector
RangeVector.scala:452.)"""

import json

import numpy as np
import pytest

from filodb_tpu.core.index import ColumnFilter
from filodb_tpu.core.memstore import TimeSeriesShard
from filodb_tpu.core.record import RecordBuilder
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetRef
from filodb_tpu.grpcsvc import (GrpcQueryServer, GrpcRemoteExec,
                                GrpcShardGroup, wire)
from filodb_tpu.http.server import FiloHttpServer
from filodb_tpu.parallel.cluster import series_to_wire
from filodb_tpu.query.model import QueryError, RawSeries

REF = DatasetRef("timeseries")
T0 = 1_600_000_000
N_SAMPLES = 300


def _shard(n_samples=N_SAMPLES, n_series=4):
    shard = TimeSeriesShard(REF, DEFAULT_SCHEMAS, 0)
    b = RecordBuilder(DEFAULT_SCHEMAS)
    for s in range(n_series):
        labels = {"_metric_": "reqs_total", "_ws_": "demo",
                  "_ns_": "App-0", "job": "api", "instance": f"i{s}"}
        for t in range(n_samples):
            b.add_sample("prom-counter", labels, (T0 + t * 10) * 1000,
                         5.0 * (s + 1) * t)
    for c in b.containers():
        shard.ingest(c)
    shard.flush_all()
    return shard


@pytest.fixture
def served():
    shard = _shard()
    http = FiloHttpServer({"timeseries": [shard]}, port=0,
                          node_id="nodeA")
    http.start()
    grpc_srv = GrpcQueryServer(http, port=0).start()
    yield shard, http, grpc_srv
    grpc_srv.stop()
    http.stop()


def test_series_wire_roundtrip():
    rng = np.random.default_rng(0)
    s = RawSeries(
        labels={"_metric_": "m", "instance": "i0"},
        ts=np.arange(100, dtype=np.int64) * 10_000 + 1_600_000_000_000,
        values=np.cumsum(rng.uniform(0, 5, 100)),
        is_counter=True,
        snapshot_key=("nodeA", "timeseries", 0, 7, 3, 1,
                      1_600_000_000_000, 1_600_000_500_000),
        chunk_len=80)
    out = wire.decode_series(wire.encode_series(s))
    assert out.labels == dict(s.labels)
    np.testing.assert_array_equal(out.ts, s.ts)
    np.testing.assert_array_equal(out.values, s.values)
    assert out.is_counter and out.chunk_len == 80
    assert out.snapshot_key == s.snapshot_key


def test_fetch_raw_parity_and_wire_size(served):
    shard, http, grpc_srv = served
    filters = [ColumnFilter("_metric_", "eq", "reqs_total")]
    start, end = (T0 + 100) * 1000, (T0 + 2000) * 1000
    group = GrpcShardGroup("nodeA", f"127.0.0.1:{grpc_srv.port}",
                           "timeseries", [0])
    got = group.fetch_raw(filters, start, end, None, full=True)
    assert len(got) == 4
    # parity with the JSON leaf endpoint
    import urllib.request
    body = json.dumps({"filters": [["_metric_", "eq", "reqs_total"]],
                       "start_ms": start, "end_ms": end,
                       "column": None, "shards": [0]}).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{http.port}/api/v1/raw/timeseries", data=body,
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as r:
        json_payload = r.read()
    want = json.loads(json_payload)["data"]
    by_inst = {s.labels["instance"]: s for s in got}
    assert grpc_srv.rpcs_served >= 1
    for d in want:
        s = by_inst[d["labels"]["instance"]]
        assert s.ts.size == d["n"]
        assert s.snapshot_key is not None
        assert s.snapshot_key[0] == "nodeA"
    # the protobuf+NibblePack frame is >2x smaller than the base64-JSON
    # payload for the same series (VERDICT round-3 wire-size criterion)
    pb = wire.encode_raw_response(got)
    assert len(pb) * 2 < len(json_payload), (len(pb), len(json_payload))


def test_leaf_payload_scales_with_span_not_retention(served):
    """The SerializedRangeVector contract: wire bytes follow the query
    span. 10x the span => ~10x the payload; retention (N_SAMPLES) does
    not appear."""
    shard, http, grpc_srv = served
    filters = [ColumnFilter("_metric_", "eq", "reqs_total")]
    group = GrpcShardGroup("nodeA", f"127.0.0.1:{grpc_srv.port}",
                           "timeseries", [0])

    def payload_bytes(span_samples):
        start = (T0 + 100) * 1000
        end = start + span_samples * 10_000
        got = group.fetch_raw(filters, start, end, None, full=True)
        assert all(s.ts.size <= span_samples + 1 for s in got)
        return len(wire.encode_raw_response(got))

    # the per-series sample counts (asserted above) ARE the contract;
    # byte ratios soften under NibblePack + fixed label overhead, but a
    # small span must still ship far less than the resident retention
    small = payload_bytes(20)
    big = payload_bytes(200)
    assert small < big
    retention_equiv = payload_bytes(N_SAMPLES + 50)   # whole retention
    assert small * 3 < retention_equiv


def test_exec_parity_with_http(served):
    shard, http, grpc_srv = served
    q = "sum(rate(reqs_total[5m])) by (instance)"
    start_s, end_s, step_s = T0 + 300, T0 + 2500, 60
    ex = GrpcRemoteExec(q, start_s * 1000, step_s * 1000, end_s * 1000,
                        "nodeA", f"127.0.0.1:{grpc_srv.port}",
                        "timeseries")
    grid = ex.execute()
    assert len(grid.keys) == 4
    import urllib.parse
    import urllib.request
    url = (f"http://127.0.0.1:{http.port}/promql/timeseries/api/v1/"
           f"query_range?" + urllib.parse.urlencode(
               {"query": q, "start": start_s, "end": end_s,
                "step": step_s}))
    want = json.load(urllib.request.urlopen(url, timeout=60))
    by_inst = {k["instance"]: grid.values[i]
               for i, k in enumerate(grid.keys)}
    for r in want["data"]["result"]:
        vals = {int(float(t)): float(v) for t, v in r["values"]}
        row = by_inst[r["metric"]["instance"]]
        for i, st in enumerate(grid.steps // 1000):
            if int(st) in vals:
                np.testing.assert_allclose(row[i], vals[int(st)],
                                           rtol=1e-6)


def test_exec_error_propagates(served):
    shard, http, grpc_srv = served
    ex = GrpcRemoteExec("this is not promql(", T0 * 1000, 60_000,
                        (T0 + 600) * 1000, "nodeA",
                        f"127.0.0.1:{grpc_srv.port}", "timeseries")
    with pytest.raises(QueryError):
        ex.execute()


def test_histogram_series_roundtrip():
    rng = np.random.default_rng(1)
    s = RawSeries(
        labels={"_metric_": "lat"},
        ts=np.arange(50, dtype=np.int64) * 10_000,
        values=np.cumsum(rng.uniform(0, 2, (50, 4)), axis=0),
        is_counter=True,
        bucket_les=np.array([0.1, 1.0, 10.0, np.inf]),
        hist_drop_rows=np.array([7, 21], dtype=np.int64))
    out = wire.decode_series(wire.encode_series(s))
    np.testing.assert_array_equal(out.values, s.values)
    np.testing.assert_array_equal(out.bucket_les, s.bucket_les)
    np.testing.assert_array_equal(out.hist_drop_rows, s.hist_drop_rows)


def test_multiprocess_cluster_exchanges_protobuf_frames(tmp_path):
    """2-node cluster with gRPC data plane: a query entering node0 for
    node1's shards rides /filodb.QueryService (asserted via the peer's
    grpc_rpcs_served counter), and results match the all-HTTP path."""
    import os
    import pathlib
    import select
    import signal
    import socket
    import subprocess
    import sys
    import time
    import urllib.request

    REPO = pathlib.Path(__file__).resolve().parent.parent

    def free_port():
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        p = s.getsockname()[1]
        s.close()
        return p

    ports = [free_port(), free_port()]
    gports = [free_port(), free_port()]
    peers = {f"node{i}": f"http://127.0.0.1:{p}"
             for i, p in enumerate(ports)}
    grpc_peers = {f"node{i}": f"127.0.0.1:{p}"
                  for i, p in enumerate(gports)}
    base = {
        "num-shards": 4, "num-nodes": 2, "peers": peers,
        "grpc-peers": grpc_peers,
        "seed-dev-data": True, "seed-start-ms": T0 * 1000,
        "seed-samples": 60, "seed-instances": 4,
        "query-sample-limit": 0, "query-series-limit": 0,
    }
    procs = []
    try:
        for i in range(2):
            cfg = {**base, "node-ordinal": i, "port": ports[i],
                   "grpc-port": gports[i]}
            cfg_path = tmp_path / f"n{i}.json"
            cfg_path.write_text(json.dumps(cfg))
            env = dict(os.environ)
            env["JAX_PLATFORMS"] = "cpu"
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "filodb_tpu.standalone.server",
                 "--config", str(cfg_path)],
                cwd=str(REPO), env=env, stdout=subprocess.PIPE,
                stderr=subprocess.DEVNULL))
        for p in procs:
            deadline = time.monotonic() + 120
            buf = b""
            while time.monotonic() < deadline:
                r, _, _ = select.select([p.stdout], [], [], 1.0)
                if r:
                    buf += p.stdout.read1(4096)
                    if b"\n" in buf:
                        break
            assert b"\n" in buf, "startup line missing"

        def metric(port, name):
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=30) as r:
                for line in r.read().decode().splitlines():
                    if line.startswith(f"filodb_{name}"):
                        return float(line.rsplit(" ", 1)[1])
            return 0.0

        before = metric(ports[1], "grpc_rpcs_served_total")
        url = (f"http://127.0.0.1:{ports[0]}/promql/timeseries/api/v1/"
               f"query?query=%7B_metric_%3D~%22heap_usage%7C"
               f"http_requests_total%22%7D&time={T0 + 590}")
        body = json.load(urllib.request.urlopen(url, timeout=120))
        assert body["status"] == "success"
        assert len(body["data"]["result"]) >= 8   # both nodes' shards
        after = metric(ports[1], "grpc_rpcs_served_total")
        assert after > before, (before, after)   # protobuf frames flowed
    finally:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in procs:
            if p.poll() is None:
                try:
                    p.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait(timeout=30)
