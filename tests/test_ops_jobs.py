"""Operational batch jobs: downsample-index migration, cross-store chunk
copier with bit-level validation, and the cardinality buster.

(Parity model: spark-jobs index/DSIndexJob.scala,
repair/ChunkCopier.scala:25, cardbuster/CardinalityBuster.scala.)"""

import numpy as np

from filodb_tpu.core.index import ColumnFilter
from filodb_tpu.core.memstore import TimeSeriesShard
from filodb_tpu.core.record import PartKey, RecordBuilder
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetRef
from filodb_tpu.downsample.job import ds_dataset
from filodb_tpu.jobs import CardBuster, ChunkCopier, DSIndexJob
from filodb_tpu.store import FlatFileColumnStore

T0 = 1_600_000_000_000


def _populate(store, n_series=6, metric="reqs_total"):
    shard = TimeSeriesShard(DatasetRef("timeseries"), DEFAULT_SCHEMAS, 0,
                            max_chunk_rows=50, column_store=store)
    b = RecordBuilder(DEFAULT_SCHEMAS)
    for s in range(n_series):
        labels = {"_metric_": metric, "_ws_": "demo", "_ns_": "App-0",
                  "instance": f"i{s}"}
        v = 0.0
        for t in range(120):
            v += float(s + 1)
            b.add_sample("prom-counter", labels, T0 + t * 10_000, v)
    for c in b.containers():
        shard.ingest(c)
    shard.flush_all()
    return shard


def test_ds_index_migration(tmp_path):
    store = FlatFileColumnStore(str(tmp_path / "store"))
    _populate(store)
    job = DSIndexJob(store)
    stats = job.run("timeseries", 0)
    assert stats.scanned == 6 and stats.migrated == 6
    for res in (300_000, 3_600_000):
        entries = list(store.scan_part_keys(
            ds_dataset("timeseries", res), 0))
        assert len(entries) == 6
        for e in entries:
            pk = PartKey.from_bytes(e.part_key)
            # schema mapped to the declared downsample schema, labels
            # and time bounds preserved
            ds_schema = DEFAULT_SCHEMAS.by_id(pk.schema_id)
            assert ds_schema.name == DEFAULT_SCHEMAS.by_name(
                "prom-counter").downsample_schema or \
                ds_schema.name == "prom-counter"
            assert e.start_ts <= e.end_ts
            assert dict(pk.labels)["_metric_"] == "reqs_total"

    # incremental run with a future watermark migrates nothing new
    stats2 = job.run("timeseries", 0,
                     updated_since_ms=T0 + 10_000_000_000)
    assert stats2.migrated == 0


def test_chunk_copier_bit_identical(tmp_path):
    src = FlatFileColumnStore(str(tmp_path / "src"))
    dst = FlatFileColumnStore(str(tmp_path / "dst"))
    _populate(src)
    copier = ChunkCopier(src, dst)
    assert len(copier.diff("timeseries", 0)) == 6
    stats = copier.run("timeseries", 0)
    assert stats.part_keys == 6
    assert stats.chunks_copied > 0
    assert stats.validation_failures == 0
    assert stats.chunks_validated == stats.chunks_copied
    assert copier.diff("timeseries", 0) == []
    # bit-identical: every vector byte-equal between the stores
    for e in src.scan_part_keys("timeseries", 0):
        a = src.read_chunks("timeseries", 0, e.part_key)
        b = dst.read_chunks("timeseries", 0, e.part_key)
        assert [c.vectors for c in a] == [c.vectors for c in b]
        assert [c.chunk_id for c in a] == [c.chunk_id for c in b]


def test_chunk_copier_detects_corruption(tmp_path):
    from filodb_tpu.core.memstore import ChunkSetInfo
    from filodb_tpu.jobs import ChunkCopierStats
    src = FlatFileColumnStore(str(tmp_path / "src"))
    dst = FlatFileColumnStore(str(tmp_path / "dst"))
    _populate(src)
    copier = ChunkCopier(src, dst)
    copier.run("timeseries", 0, validate=False)
    # overwrite one target chunk with corrupted vectors (upsert-by-append:
    # the bad record wins the dedupe)
    e = next(iter(src.scan_part_keys("timeseries", 0)))
    chunks = dst.read_chunks("timeseries", 0, e.part_key)
    bad = ChunkSetInfo(chunks[0].chunk_id, chunks[0].num_rows,
                       chunks[0].start_ts, chunks[0].end_ts,
                       tuple(v + b"x" for v in chunks[0].vectors))
    dst.write_chunks("timeseries", 0, e.part_key, [bad])
    stats = ChunkCopierStats()
    copier._validate("timeseries", "timeseries", 0, 0, 1 << 62, stats)
    assert stats.validation_failures >= 1


def test_cardbuster_deletes_matching_series(tmp_path):
    store = FlatFileColumnStore(str(tmp_path / "store"))
    _populate(store)
    buster = CardBuster(store)
    dry = buster.run("timeseries", 0,
                     [ColumnFilter.regex("instance", "i[01]")],
                     dry_run=True)
    assert dry.deleted == 2
    assert len(list(store.scan_part_keys("timeseries", 0))) == 6
    stats = buster.run("timeseries", 0,
                       [ColumnFilter.regex("instance", "i[01]")])
    assert stats.deleted == 2
    left = list(store.scan_part_keys("timeseries", 0))
    assert len(left) == 4
    for e in left:
        inst = PartKey.from_bytes(e.part_key).label_map["instance"]
        assert inst not in ("i0", "i1")
        # surviving chunks still readable and intact
        chunks = store.read_chunks("timeseries", 0, e.part_key)
        assert chunks and all(c.vectors for c in chunks)
    # deleted series have no chunks left
    import pytest
    with pytest.raises(ValueError):
        buster.run("timeseries", 0, [])
