"""Mesh scatter-gather distribution tests (8-device virtual CPU mesh).

Analogue of the reference's multi-jvm cluster tests + DistConcat/
ReduceAggregate exec specs (coordinator/src/multi-jvm, query/src/test
AggrOverRangeVectorsSpec): the distributed answer must equal the
single-process numpy oracle."""

import numpy as np
import pytest

import jax

from filodb_tpu.parallel import MeshExecutor, ShardMapper, ShardStatus
from filodb_tpu.parallel.mesh import make_mesh, pack_sharded
from filodb_tpu.parallel.shardmapper import (assign_shards_evenly,
                                             shards_for_ordinal)
from filodb_tpu.query import rangefn as rf
from filodb_tpu.query.model import RangeParams, RawSeries


def _mk_series(seed, n_series, t0=10_000, dt=10_000, n=120, counter=False):
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n_series):
        ts = t0 + np.arange(n, dtype=np.int64) * dt \
            + rng.integers(-500, 500, n)
        ts = np.sort(ts)
        if counter:
            vals = np.cumsum(rng.uniform(0, 5, n))
        else:
            vals = rng.normal(10, 3, n)
        out.append(RawSeries({"job": f"j{i % 3}", "i": str(i)}, ts, vals,
                             is_counter=counter))
    return out


def _oracle_agg(series, params, func, window_ms, agg, group_of):
    steps = params.steps
    groups = {}
    for s in series:
        row = rf.evaluate(func, s.ts, s.values, params.start_ms,
                          params.step_ms, params.end_ms, window_ms)
        groups.setdefault(group_of(s), []).append(row)
    out = {}
    for g, rows in groups.items():
        m = np.vstack(rows)
        ok = ~np.isnan(m)
        cnt = ok.sum(axis=0)
        with np.errstate(invalid="ignore"):
            if agg == "sum":
                r = np.where(ok, m, 0.0).sum(axis=0)
            elif agg == "avg":
                r = np.where(ok, m, 0.0).sum(axis=0) / cnt
            elif agg == "count":
                r = cnt.astype(float)
            elif agg == "min":
                r = np.nanmin(np.where(ok, m, np.inf), axis=0)
                r[np.isinf(r)] = np.nan
            elif agg == "max":
                r = np.nanmax(np.where(ok, m, -np.inf), axis=0)
                r[np.isinf(r)] = np.nan
        r = np.where(cnt > 0, r, np.nan)
        out[g] = r
    return out


@pytest.fixture(scope="module")
def mesh8():
    return MeshExecutor(make_mesh())  # all 8 devices on shard axis


@pytest.fixture(scope="module")
def mesh42():
    return MeshExecutor(make_mesh(n_shard_groups=4, time_parallel=2))


PARAMS = RangeParams(300_000, 60_000, 1_200_000)
WINDOW = 300_000


@pytest.mark.parametrize("agg", ["sum", "avg", "count", "min", "max"])
def test_mesh_agg_matches_oracle(mesh8, agg):
    series = _mk_series(1, 64, counter=True)
    # 8 shards, one per device slice; group by job label
    by_shard = [series[i::8] for i in range(8)]
    jobs = sorted({s.labels["job"] for s in series})
    gid = {j: i for i, j in enumerate(jobs)}
    gids = [[gid[s.labels["job"]] for s in row] for row in by_shard]
    out = mesh8.window_aggregate(by_shard, PARAMS, "rate", WINDOW, agg,
                                 gids, len(jobs))
    oracle = _oracle_agg(series, PARAMS, "rate", WINDOW, agg,
                         lambda s: s.labels["job"])
    assert out.shape == (len(jobs), PARAMS.num_steps)
    for j, job in enumerate(jobs):
        np.testing.assert_allclose(out[j], oracle[job], rtol=1e-9,
                                   equal_nan=True)


def test_mesh_time_parallel_matches(mesh42):
    """2D mesh: 4-way shard × 2-way time (sequence parallel) — same answer."""
    series = _mk_series(2, 32)
    by_shard = [series[i::4] for i in range(4)]
    gids = [[0] * len(row) for row in by_shard]
    out = mesh42.window_aggregate(by_shard, PARAMS, "sum_over_time", WINDOW,
                                  "sum", gids, 1)
    oracle = _oracle_agg(series, PARAMS, "sum_over_time", WINDOW, "sum",
                         lambda s: 0)
    np.testing.assert_allclose(out[0], oracle[0], rtol=1e-9, equal_nan=True)


@pytest.mark.parametrize("func,agg", [("min_over_time", "min"),
                                      ("max_over_time", "max")])
def test_mesh_gather_funcs(mesh8, func, agg):
    """Order-statistic range functions route through _window_gather."""
    series = _mk_series(9, 16)
    by_shard = [series[i::8] for i in range(8)]
    gids = [[0] * len(r) for r in by_shard]
    out = mesh8.window_aggregate(by_shard, PARAMS, func, WINDOW, agg,
                                 gids, 1)
    oracle = _oracle_agg(series, PARAMS, func, WINDOW, agg, lambda s: 0)
    np.testing.assert_allclose(out[0], oracle[0], rtol=1e-9, equal_nan=True)


def test_mesh_empty_step_grid(mesh8):
    series = _mk_series(10, 8)
    by_shard = [series[i::8] for i in range(8)]
    gids = [[0] * len(r) for r in by_shard]
    out = mesh8.window_aggregate(
        by_shard, RangeParams(300_000, 60_000, 200_000), "rate", WINDOW,
        "sum", gids, 1)
    assert out.shape == (1, 0)


def test_mesh_ragged_shards(mesh8):
    """Shards with different series counts / sample counts pad cleanly."""
    series = _mk_series(3, 20)
    by_shard = [series[:1], series[1:4], series[4:10], series[10:11],
                series[11:15], series[15:16], series[16:19], series[19:]]
    gids = [[0] * len(r) for r in by_shard]
    out = mesh8.window_aggregate(by_shard, PARAMS, "avg_over_time", WINDOW,
                                 "avg", gids, 1)
    oracle = _oracle_agg(series, PARAMS, "avg_over_time", WINDOW, "avg",
                         lambda s: 0)
    np.testing.assert_allclose(out[0], oracle[0], rtol=1e-9, equal_nan=True)


def test_pack_sharded_shapes():
    series = _mk_series(4, 6, n=100)
    ts, vals, lens, keys = pack_sharded([series[:4], series[4:]])
    assert ts.shape[0] == 2 and ts.shape[1] == 4
    assert ts.shape[2] >= 100 and (ts.shape[2] & (ts.shape[2] - 1)) == 0
    assert lens[1, 2] == 0          # padding series empty
    assert len(keys[0]) == 4 and len(keys[1]) == 2


# -- ShardMapper FSM ------------------------------------------------------

def test_shard_mapper_fsm_and_routing():
    m = ShardMapper(32)
    assert m.unassigned_shards() == list(range(32))
    assign_shards_evenly(m, ["node0", "node1", "node2", "node3"])
    assert m.shards_for_node("node0") == list(range(8))
    assert m.status(0) is ShardStatus.ASSIGNED
    assert not m.all_queryable()
    events = []
    m.subscribe(events.append)
    for s in range(32):
        m.activate(s)
    assert m.all_queryable()
    assert len(events) == 32
    # routing consistency: ingestion shard is one of query_shards
    for skh, ph in [(0xDEADBEEF, 0x1234), (7, 99), (2**31, 2**30)]:
        for spread in (0, 3, 5):
            ing = m.ingestion_shard(skh, ph, spread)
            assert ing in m.query_shards(skh, spread)
    # recovery status is still queryable (ShardStatus.scala semantics)
    m.update(3, ShardStatus.RECOVERY, progress_pct=40)
    assert m.status(3).queryable
    m.update(3, ShardStatus.DOWN)
    assert m.active_shards() == [s for s in range(32) if s != 3]


def test_shards_for_ordinal():
    allsh = []
    for o in range(4):
        allsh += shards_for_ordinal(o, 4, 16)
    assert allsh == list(range(16))
    with pytest.raises(ValueError):
        shards_for_ordinal(4, 4, 16)


def test_mesh_absent_over_time_padding_not_counted(mesh8):
    """Padding rows must not leak absent_over_time=1.0 into group 0
    (round-1 advisor finding: padding gids defaulted to 0)."""
    # shard 0 has 3 series (padded to pow2=4), all with data in-window
    by_shard = [_mk_series(5, 3)] + [[] for _ in range(7)]
    gids = [[0, 0, 0]] + [[] for _ in range(7)]
    ex = mesh8
    out = ex.window_aggregate(by_shard, PARAMS, "absent_over_time",
                              WINDOW, "sum", gids, 1)
    # every real series has samples in every window => absent sums to NaN
    assert np.all(np.isnan(out[0]))
    cnt = ex.window_aggregate(by_shard, PARAMS, "present_over_time",
                              WINDOW, "count", gids, 1)
    assert np.nanmax(cnt[0]) == 3.0
