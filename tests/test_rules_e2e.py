"""E2E acceptance for the in-process rules engine (live FiloServer,
wall-clock scheduler):

  * a recording rule's output series is queryable over PromQL from the
    reserved __rules__ dataset with correct rate() semantics (counter
    schema via the `schema:` extension);
  * an alerting rule with for: transitions inactive -> pending ->
    firing on schedule and back, visible in /api/v1/rules,
    /api/v1/alerts, and the synthetic ALERTS series;
  * alert webhooks are delivered (flaky receiver, retried through the
    breaker);
  * with rules disabled, user-facing responses are byte-identical to a
    rules-free server;
  * recorded series survive a restart via the WAL replay path.
"""

import json
import threading
import time
import urllib.parse
import urllib.request

import pytest

from filodb_tpu.core.record import RecordBuilder
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS
from filodb_tpu.rules import RULES_DATASET
from filodb_tpu.standalone.server import FiloServer


def _get(port, path, **params):
    qs = urllib.parse.urlencode(params, doseq=True)
    url = f"http://127.0.0.1:{port}{path}" + (f"?{qs}" if qs else "")
    with urllib.request.urlopen(url, timeout=60) as r:
        return json.loads(r.read())


def _query_range(port, ds, **params):
    return _get(port, f"/promql/{ds}/api/v1/query_range", **params)


def _poll(fn, timeout=30.0, interval=0.1, msg="condition"):
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        ok, last = fn()
        if ok:
            return last
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}: {last!r}")


def _ingest(srv, schema, metric, ts_ms, value, **labels):
    b = RecordBuilder(DEFAULT_SCHEMAS)
    b.add_sample(schema, {"_metric_": metric, **labels}, int(ts_ms),
                 float(value))
    for c in b.containers():
        srv.store.ingest(srv.ref, 0, c)


# ---------------------------------------------------------------------------
# recording: rate() over a recorded counter
# ---------------------------------------------------------------------------

def test_recorded_counter_rate_semantics():
    srv = FiloServer({
        "num-shards": 2, "port": 0,
        "rules": {"groups": [{
            "name": "rec", "interval": "0.5s", "rules": [
                {"record": "e2e:reqs:total",
                 "expr": "sum(e2e_reqs_total)",
                 "schema": "counter"},
            ]}]},
    }).start()
    try:
        # a counter with an exact 10/s slope, pre-covering the next
        # ~40s of wall time so every tick's instant lookback hits it
        base_ms = int(time.time() * 1000) - 5_000
        b = RecordBuilder(DEFAULT_SCHEMAS)
        for i in range(0, 450):
            b.add_sample("prom-counter",
                         {"_metric_": "e2e_reqs_total", "i": "0"},
                         base_ms + i * 100, i * 1.0)
        for c in b.containers():
            srv.store.ingest(srv.ref, 0, c)
        srv.store.flush_all(srv.ref)

        def _recorded():
            now = int(time.time())
            out = _query_range(srv.port, RULES_DATASET,
                               query="e2e:reqs:total",
                               start=now - 30, end=now + 1, step=1)
            res = out["data"]["result"]
            if not res:
                return False, (res, 0)
            ts = [float(t) for t, _v in res[0]["values"]]
            # wait until the recorded series SPANS the rate window
            # below, so the slope is fully covered (a younger series
            # under-extrapolates)
            return max(ts) - min(ts) >= 12.0, (res, len(ts))
        res, _n = _poll(_recorded, timeout=45,
                        msg="recorded counter samples")
        (series,) = res
        assert series["metric"]["_ws_"] == RULES_DATASET
        # the recorded series is a MONOTONE counter tracking the source
        vals = [float(v) for _t, v in series["values"]]
        assert vals == sorted(vals) and vals[-1] > vals[0]

        # rate() over the recorded series sees the source's 10/s slope
        # (counter schema: reset correction + extrapolation apply)
        now = int(time.time())
        out = _query_range(srv.port, RULES_DATASET,
                           query="rate(e2e:reqs:total[10s])",
                           start=now - 5, end=now, step=1)
        rates = [float(v)
                 for r in out["data"]["result"]
                 for _t, v in r["values"]]
        assert rates, "no rate() points over the recorded counter"
        assert all(6.0 < v < 14.0 for v in rates), rates

        # the engine observed cache-warm tail recomputes: with ticks
        # 0.5s apart and an 8-step window, later ticks partially hit
        payload = _get(srv.port, "/api/v1/rules", explain="analyze")
        (rule,) = payload["data"]["groups"][0]["rules"]
        assert rule["health"] == "ok"
        assert rule["lastEval"]["stages"]["rulePlanCache"] in \
            ("hit", "miss")
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# alerting: the for: lifecycle on schedule, live
# ---------------------------------------------------------------------------

def test_alert_lifecycle_live_with_webhook():
    import http.server
    import socketserver

    hooks = []
    fails = {"n": 1}            # first delivery attempt bounces (5xx)

    class H(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            body = self.rfile.read(
                int(self.headers.get("Content-Length") or 0))
            if fails["n"] > 0:
                fails["n"] -= 1
                self.send_response(503)
                self.send_header("Content-Length", "0")
                self.end_headers()
                return
            hooks.append(json.loads(body))
            self.send_response(200)
            self.send_header("Content-Length", "0")
            self.end_headers()

        def log_message(self, *a):
            pass

    httpd = socketserver.TCPServer(("127.0.0.1", 0), H)
    hook_port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()

    srv = FiloServer({
        "num-shards": 2, "port": 0,
        "rules-webhook-url": f"http://127.0.0.1:{hook_port}/hook",
        "rules": {"groups": [{
            "name": "al", "interval": "0.4s", "rules": [
                {"alert": "SignalHigh",
                 "expr": "sum(e2e_signal) > 0.5",
                 "for": "1.2s",
                 "labels": {"severity": "page"},
                 "annotations": {"summary": "sig={{ $value }}"}},
            ]}]},
    }).start()

    # a single writer thread ingests the signal at wall-now so the
    # alert expression's instant lookback always sees a fresh value
    level = {"v": 0.0}
    stop = threading.Event()

    def writer():
        while not stop.is_set():
            _ingest(srv, "gauge", "e2e_signal",
                    time.time() * 1000, level["v"], i="0")
            time.sleep(0.1)
    wt = threading.Thread(target=writer, daemon=True)
    wt.start()

    def _alert_state():
        out = _get(srv.port, "/api/v1/alerts")
        alerts = out["data"]["alerts"]
        return alerts[0]["state"] if alerts else "inactive", out["data"]
    try:
        # phase 0: signal low -> inactive
        time.sleep(1.5)
        state, _ = _alert_state()
        assert state == "inactive"

        # phase 1: signal high -> pending, then firing after for: held
        level["v"] = 1.0
        _poll(lambda: (_alert_state()[0] == "pending",
                       _alert_state()[0]),
              timeout=15, msg="pending")
        t_pending = time.monotonic()
        _poll(lambda: (_alert_state()[0] == "firing",
                       _alert_state()[0]),
              timeout=15, msg="firing")
        # the for: hold was honored (>= ~1.2s between the states)
        assert time.monotonic() - t_pending >= 0.7

        # visible in /api/v1/rules with state + annotations rendered
        payload = _get(srv.port, "/api/v1/rules")
        (rule,) = payload["data"]["groups"][0]["rules"]
        assert rule["type"] == "alerting"
        assert rule["state"] == "firing"
        (inst,) = rule["alerts"]
        assert inst["labels"]["severity"] == "page"
        assert inst["annotations"]["summary"].startswith("sig=")

        # the synthetic ALERTS series rode the write-back rail and is
        # a PromQL query away
        def _alerts_series():
            now = int(time.time())
            out = _query_range(
                srv.port, RULES_DATASET,
                query='ALERTS{alertname="SignalHigh"}',
                start=now - 30, end=now + 1, step=1)
            states = {r["metric"].get("alertstate")
                      for r in out["data"]["result"]}
            return "firing" in states, states
        _poll(_alerts_series, msg="ALERTS series")

        # phase 2: signal clears -> inactive (resolved webhook)
        level["v"] = 0.0
        _poll(lambda: (_alert_state()[0] == "inactive",
                       _alert_state()[0]),
              timeout=15, msg="resolve")

        # transitions ring recorded the full walk, in order
        _, data = _alert_state()
        walk = [(t["from"], t["to"]) for t in data["transitions"]]
        assert walk == [("inactive", "pending"), ("pending", "firing"),
                        ("firing", "inactive")]

        # webhooks: firing + resolved both delivered; the first bounce
        # was retried through the resilience stack
        def _hooks():
            statuses = [h["status"] for h in hooks]
            return "firing" in statuses and "resolved" in statuses, \
                statuses
        _poll(_hooks, timeout=15, msg="webhook deliveries")
        snap = srv.rules.notifier.snapshot()
        assert snap["delivered"] >= 2 and snap["breaker"] == "closed"
        (bk,) = srv.rules.notifier.breakers.metrics_snapshot().values()
        assert bk["retries"] >= 1        # the injected 503 was retried
    finally:
        stop.set()
        wt.join(timeout=5)
        srv.stop()
        httpd.shutdown()
        httpd.server_close()


# ---------------------------------------------------------------------------
# transparency: rules disabled == byte-identical user responses
# ---------------------------------------------------------------------------

T0 = 1_600_000_000


def test_rules_enabled_user_responses_unchanged():
    """Rules on must not perturb user-dataset responses: the data
    section matches a rules-free server byte-for-byte (modulo the
    wall-clock timings block) — and a rules-free server carries no
    /api/v1/rules state at all."""
    with_rules = FiloServer({
        "num-shards": 2, "port": 0,
        "rules": {"groups": [{
            "name": "g", "interval": "0.3s", "rules": [
                {"record": "r:req:rate",
                 "expr": "sum(rate(http_requests_total[5m]))"}]}]},
    }).start()
    plain = FiloServer({"num-shards": 2, "port": 0}).start()
    try:
        for s in (with_rules, plain):
            s.seed_dev_data(n_samples=60, n_instances=3,
                            start_ms=T0 * 1000)
        time.sleep(1.0)         # let the engine tick a few times
        q = dict(query="rate(http_requests_total[5m])",
                 start=T0 + 300, end=T0 + 500, step=60, cache="false")
        a = _query_range(with_rules.port, "timeseries", **q)
        b = _query_range(plain.port, "timeseries", **q)
        a["stats"].pop("timings", None)
        b["stats"].pop("timings", None)
        assert a == b
        # rules-free server: empty rules surface, not an error
        out = _get(plain.port, "/api/v1/rules")
        assert out["data"]["groups"] == []
        out = _get(plain.port, "/api/v1/alerts")
        assert out["data"]["alerts"] == []
    finally:
        with_rules.stop()
        plain.stop()


# ---------------------------------------------------------------------------
# durability: recorded series survive restart via WAL replay
# ---------------------------------------------------------------------------

def test_recorded_series_survive_restart_via_wal(tmp_path):
    cfg = {
        "num-shards": 2, "port": 0,
        "data-dir": str(tmp_path / "data"),
        "stream-dir": str(tmp_path / "streams"),
        "flush-interval-s": 0.3,
        "rules": {"groups": [{
            "name": "g", "interval": "0.4s", "rules": [
                {"record": "wal:recorded:value",
                 "expr": "vector(42)"}]}]},
    }
    srv = FiloServer(dict(cfg)).start()
    try:
        def _recorded():
            now = int(time.time())
            out = _query_range(srv.port, RULES_DATASET,
                               query="wal:recorded:value",
                               start=now - 30, end=now + 1, step=1)
            res = out["data"]["result"]
            return bool(res) and len(res[0]["values"]) >= 3, res
        res = _poll(_recorded, msg="recorded samples before restart")
        pre_ts = [int(float(t)) for t, _v in res[0]["values"]]
    finally:
        srv.stop()

    # restart over the same dirs: the rules WAL replays through the
    # normal IngestionDriver path; the PRE-restart samples (timestamps
    # the new engine can never re-produce) must be queryable again
    srv2 = FiloServer(dict(cfg)).start()
    try:
        lo, hi = min(pre_ts) - 1, max(pre_ts) + 1

        def _replayed():
            out = _query_range(srv2.port, RULES_DATASET,
                               query="wal:recorded:value",
                               start=lo, end=hi, step=1)
            res = out["data"]["result"]
            got = {int(float(t)) for r in res for t, _v in r["values"]}
            return set(pre_ts) <= got, (sorted(got), pre_ts)
        _poll(_replayed, timeout=45, msg="WAL replay of recorded series")
    finally:
        srv2.stop()
