"""promlint semantic analyzer tests: type & schema checking, label
dataflow, pragmas, spans (parity model: promtool check rules +
Prometheus parser type-checking errors)."""

import pytest

from filodb_tpu.promql import semant as sm
from filodb_tpu.promql.parser import ParseError, Parser


def rules_of(q, schemas=None):
    return [d.rule for d in sm.lint_query(q, schemas)]


def errors_of(q, schemas=None):
    return [d.rule for d in sm.errors(sm.lint_query(q, schemas))]


# ---------------------------------------------------------------------------
# type checking
# ---------------------------------------------------------------------------

def test_clean_queries():
    for q in (
            'sum(rate(http_requests_total[5m])) by (job)',
            'histogram_quantile(0.9, sum by (le) (rate(b_bucket[5m])))',
            'avg_over_time(rate(x_total[1m])[10m:1m])',
            'clamp(cpu_usage, 0, 10) + 1',
            'foo > bool 10',
            '1 + 2 * 3',
            'sum by (job) (a) / on (job) sum by (job) (b)',
            'label_replace(up, "dst", "$1", "src", "(.*)")',
    ):
        assert rules_of(q) == [], q


def test_range_fn_requires_range_vector():
    assert "promql-range-arg" in errors_of("rate(foo)")
    assert "promql-range-arg" in errors_of("sum(increase(foo))")


def test_agg_requires_instant_vector():
    assert "promql-instant-arg" in errors_of("sum(foo[5m])")
    assert "promql-instant-arg" in errors_of("avg(2)")


def test_top_level_range_vector_rejected():
    assert "promql-top-level-range" in errors_of("foo[5m]")
    assert "promql-top-level-range" in errors_of("foo[10m:1m]")


def test_subquery_inner_must_be_instant():
    assert "promql-subquery-inner" in errors_of(
        "avg_over_time(foo[5m][10m:1m])")


def test_bool_modifier_rules():
    assert "promql-bool-modifier" in errors_of("a + bool b")
    assert "promql-cmp-scalar-needs-bool" in errors_of("1 > 2")
    assert errors_of("1 > bool 2") == []


def test_set_op_operand_rules():
    assert "promql-setop-operand" in errors_of("foo and 3")
    assert "promql-setop-operand" in errors_of("2 or foo")


def test_matching_with_scalar_rejected():
    assert "promql-matching-with-scalar" in errors_of(
        "foo * on (job) 3")


def test_arity_checking():
    assert "promql-arity" in errors_of("clamp(foo)")
    assert "promql-arity" in errors_of("holt_winters(foo[5m], 0.5)")
    assert "promql-arity" in errors_of("time(foo)")
    assert errors_of("round(foo, 2)") == []


def test_scalar_and_string_params():
    assert "promql-scalar-arg" in errors_of(
        "quantile_over_time(foo, bar[5m])")
    assert "promql-string-arg" in errors_of(
        "label_join(foo, bar, baz)")


# ---------------------------------------------------------------------------
# schema checking (counter/gauge semantics)
# ---------------------------------------------------------------------------

def test_counter_fn_on_declared_gauge_is_error():
    s = sm.MetricSchemas({"heap_used": "gauge"})
    assert "promql-counter-fn-on-gauge" in errors_of(
        "rate(heap_used[5m])", s)
    assert "promql-counter-fn-on-gauge" in errors_of(
        "irate(heap_used[1m])", s)
    # unknown metrics stay silent — a heuristic guess must not reject
    assert errors_of("rate(some_unknown_metric[5m])") == []


def test_gauge_fn_on_counter_warns():
    diags = sm.lint_query("delta(http_requests_total[5m])")
    assert [d.rule for d in diags] == ["promql-gauge-fn-on-counter"]
    assert diags[0].severity == sm.WARNING
    # declared counter too
    s = sm.MetricSchemas({"reqs": "counter"})
    assert "promql-gauge-fn-on-counter" in rules_of(
        "deriv(reqs[5m])", s)


def test_schema_resolution_sources():
    s = sm.MetricSchemas({"x": "gauge"})
    assert s.resolve("x") == ("gauge", True)
    assert s.resolve("foo_total") == ("counter", False)
    assert s.resolve("mystery") == (None, False)


def test_from_rule_groups():
    from filodb_tpu.rules.loader import load_groups
    groups = load_groups({"groups": [
        {"name": "g", "rules": [
            {"record": "app:mem", "expr": "avg(mem)",
             "schema": "gauge"}]}]})
    s = sm.MetricSchemas.from_rule_groups(groups)
    assert s.resolve("app:mem") == ("gauge", True)


# ---------------------------------------------------------------------------
# label dataflow
# ---------------------------------------------------------------------------

def test_match_on_dropped_label_is_error():
    ds = sm.lint_query(
        "sum by (job) (a) * on (instance) sum by (instance) (b)")
    es = sm.errors(ds)
    assert len(es) == 1 and es[0].rule == "promql-match-on-dropped-label"
    assert "left-hand side" in es[0].message


def test_without_keeps_labels_flowing():
    assert errors_of(
        "sum without (instance) (a) * on (job) b") == []


def test_many_to_many_warning():
    ds = sm.lint_query(
        "sum by (job, instance) (a) / on (job) "
        "sum by (job, instance) (b)")
    assert [d.rule for d in ds] == ["promql-many-to-many"]
    assert ds[0].severity == sm.WARNING
    # a group modifier silences it
    assert rules_of(
        "sum by (job, instance) (a) / on (job) group_left "
        "sum by (job) (b)") == []


def test_include_dropped_label_warning():
    assert "promql-include-dropped-label" in rules_of(
        "a * on (job) group_left (version) sum by (job) (b)")


def test_by_absent_label_warning():
    assert "promql-by-absent-label" in rules_of(
        "sum by (instance) (sum by (job) (a))")


# ---------------------------------------------------------------------------
# pragmas, spans, rendering
# ---------------------------------------------------------------------------

def test_pragma_suppresses_with_reason():
    s = sm.MetricSchemas({"g": "gauge"})
    q = ("rate(g[5m])  # promlint: disable=promql-counter-fn-on-gauge "
         "(schema migration in flight)")
    assert rules_of(q, s) == []


def test_pragma_without_reason_is_finding():
    q = "rate(g[5m])  # promlint: disable=promql-counter-fn-on-gauge"
    assert "promql-pragma-no-reason" in rules_of(
        q, sm.MetricSchemas({"g": "gauge"}))


def test_pragma_unknown_rule_is_finding():
    assert "promql-pragma-unknown-rule" in rules_of(
        "up  # promlint: disable=promql-nonexistent (x)")


def test_diagnostic_spans_point_at_the_construct():
    q = "sum by (job) (a) * on (instance) sum by (instance) (b)"
    (d,) = sm.errors(sm.lint_query(q))
    # span anchors on the operator token of the join
    assert q[d.pos] == "*"
    r = d.render(q)
    assert "^" in r and q in r


def test_syntax_errors_become_spanned_diagnostics():
    (d,) = sm.lint_query("sum(")
    assert d.rule == "promql-syntax" and d.pos >= 0


def test_rule_catalog_is_prefixed_and_documented():
    for rid, (sev, doc) in sm.RULES.items():
        assert rid.startswith("promql-")
        assert sev in (sm.ERROR, sm.WARNING)
        assert doc
