"""Unit tests for the observability spine (filodb_tpu.obs): the span
API's no-op fast path and context plumbing, trace wire round-trips,
the fixed-bucket histogram, the exposition builder's dedup/escaping,
and the slow-query / in-flight primitives."""

import json
import math
import threading

import pytest

from filodb_tpu.obs import metrics as obm
from filodb_tpu.obs import trace as obt
from filodb_tpu.obs.slowlog import InflightRegistry, SlowQueryLog


# -- trace -------------------------------------------------------------------

def test_span_is_noop_without_active_trace():
    assert not obt.trace_active()
    sp = obt.span("x", a=1)
    assert sp is obt._NOOP          # the shared no-op, no allocation
    with sp as s:
        s.tag(b=2)                  # tag() works on the no-op too
    assert obt.inject_header() is None
    obt.event("nothing", c=3)       # no-op, no error


def test_span_nesting_and_parentage():
    tr = obt.Trace(node="n0")
    with obt.activate(tr):
        assert obt.trace_active()
        with obt.span("outer", k="v") as outer:
            with obt.span("inner"):
                obt.event("dot", hit=True)
    assert not obt.trace_active()
    spans = {s.name: s for s in tr.spans}
    assert spans["outer"].parent_id is None
    assert spans["inner"].parent_id == spans["outer"].span_id
    assert spans["dot"].parent_id == spans["inner"].span_id
    assert spans["dot"].dur_ns == 0
    assert spans["outer"].tags == {"k": "v"}
    assert spans["outer"].dur_ns >= 0
    # inner recorded BEFORE outer (exit order), both present
    assert [s.name for s in tr.spans] == ["dot", "inner", "outer"]


def test_span_records_exception_as_error():
    tr = obt.Trace()
    with obt.activate(tr):
        with pytest.raises(ValueError):
            with obt.span("boom"):
                raise ValueError("nope")
    assert tr.spans[0].error == "ValueError: nope"


def test_capture_use_across_threads():
    tr = obt.Trace()
    got = {}

    def worker(ctx):
        with obt.use(ctx):
            with obt.span("on-worker"):
                pass
        got["done"] = True

    with obt.activate(tr):
        with obt.span("parent") as parent:
            ctx = obt.capture()
            t = threading.Thread(target=worker, args=(ctx,))
            t.start()
            t.join()
    assert got["done"]
    by_name = {s.name: s for s in tr.spans}
    assert by_name["on-worker"].parent_id == by_name["parent"].span_id
    # use(None) is a harmless no-op
    with obt.use(None):
        pass


def test_header_roundtrip_and_malformed():
    tr = obt.Trace("aabbccdd00112233")
    with obt.activate(tr):
        with obt.span("s") as sp:
            hdr = obt.inject_header()
            assert hdr == f"aabbccdd00112233-{sp.span_id}-1"
            ctx = obt.parse_context(hdr)
            assert ctx == ("aabbccdd00112233", sp.span_id)
    assert obt.parse_context(None) is None
    assert obt.parse_context("") is None
    assert obt.parse_context("-") is None
    assert obt.parse_context("tid") == ("tid", None)


def test_spans_wire_roundtrip_and_garbage():
    tr = obt.Trace("t1")
    with obt.activate(tr):
        with obt.span("a", x=1):
            pass
    buf = obt.spans_wire(tr)
    tr2 = obt.Trace("t1")
    with obt.activate(tr2):
        obt.absorb_wire(buf)
        obt.absorb_wire(b"not json")        # tolerated
        obt.absorb_wire(b"")
        obt.absorb_spans([{"name": "b", "span_id": "s2",
                           "dur_us": 5}, "garbage-entry"])
    names = [s.name for s in tr2.spans]
    assert names == ["a", "b"]
    assert tr2.spans[0].tags == {"x": 1}


def test_trace_span_cap():
    tr = obt.Trace()
    with obt.activate(tr):
        for _ in range(obt.MAX_SPANS + 10):
            with obt.span("s"):
                pass
    assert len(tr.spans) == obt.MAX_SPANS
    assert tr.truncated


def test_tracer_sampling_ring_and_force():
    t = obt.Tracer(enabled=False)
    assert t.start() is None                      # disabled: untraced
    assert t.start(force=True) is not None        # &explain=trace
    assert t.start(ctx=("tid", "par")) is not None  # propagated: honored
    # tail sampling: a coin-fail start still returns a PENDING trace
    # (marked sampled=False) so retention can be decided at finish time
    t2 = obt.Tracer(enabled=True, sample_rate=0.0, max_traces=2)
    pend = t2.start()
    assert pend is not None and not pend.sampled
    assert t2.sampled_out == 1
    t3 = obt.Tracer(enabled=True, max_traces=2)
    ids = []
    for _ in range(3):
        tr = t3.start()
        t3.finish(tr)
        ids.append(tr.trace_id)
    assert t3.get(ids[0]) is None                 # evicted (ring of 2)
    assert t3.get(ids[2]) is not None
    assert [x.trace_id for x in t3.recent(10)] == [ids[2], ids[1]]


# -- metrics -----------------------------------------------------------------

def test_histogram_observe_and_exposition():
    h = obm.Histogram("t_seconds", "help text",
                      buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.5, 5.0, 50.0):
        h.observe(v)
    snap = h.snapshot()
    assert snap["count"] == 5
    assert snap["counts"] == [1, 2, 1, 1]         # per-bucket + +Inf
    assert snap["sum"] == pytest.approx(56.05)
    b = obm.ExpositionBuilder()
    b.histogram(h)
    text = b.render()
    assert "# TYPE t_seconds histogram" in text
    assert 't_seconds_bucket{le="0.1"} 1' in text
    assert 't_seconds_bucket{le="1"} 3' in text   # cumulative
    assert 't_seconds_bucket{le="10"} 4' in text
    assert 't_seconds_bucket{le="+Inf"} 5' in text
    assert "t_seconds_count 5" in text


def test_histogram_quantile_interpolation():
    h = obm.Histogram("q", "h", buckets=(0.01, 0.1, 1.0))
    assert math.isnan(h.quantile(0.5))
    for _ in range(100):
        h.observe(0.05)       # all in the (0.01, 0.1] bucket
    q50 = h.quantile(0.5)
    assert 0.01 < q50 <= 0.1
    # overflow tail clamps to the top finite bucket
    h2 = obm.Histogram("q2", "h", buckets=(0.01,))
    h2.observe(5.0)
    assert h2.quantile(0.99) == 0.01


def test_histogram_rejects_unsorted_buckets():
    with pytest.raises(ValueError):
        obm.Histogram("bad", "h", buckets=(1.0, 0.5))


def test_exposition_builder_dedupes_and_escapes():
    b = obm.ExpositionBuilder()
    b.sample("m_total", {"p": 'a"b\\c\nd'}, 1, mtype="counter",
             help="line1\nline2")
    b.sample("m_total", {"p": 'a"b\\c\nd'}, 99, mtype="counter")
    text = b.render()
    # duplicate series dropped (first wins), label escaped, help escaped
    assert text.count("m_total{") == 1
    assert 'm_total{p="a\\"b\\\\c\\nd"} 1' in text
    assert "# HELP m_total line1\\nline2" in text
    assert "# TYPE m_total counter" in text


def test_timed_and_global_registry():
    obm.GLOBAL_REGISTRY.reset()
    with obm.timed("x_seconds", "h"):
        pass
    h = obm.GLOBAL_REGISTRY.get("x_seconds")
    assert h is not None and h.snapshot()["count"] == 1
    obm.observe("x_seconds", "h", 0.2)
    assert h.snapshot()["count"] == 2
    obm.GLOBAL_REGISTRY.reset()


# -- slowlog -----------------------------------------------------------------

def test_slow_query_log_threshold_and_ring():
    log = SlowQueryLog(threshold_ms=10, capacity=2)
    assert not log.maybe_record(5, {"query": "fast"})
    assert log.maybe_record(50, {"query": "q1"})
    assert log.maybe_record(60, {"query": "q2"})
    assert log.maybe_record(70, {"query": "q3"})
    recs = log.records()
    assert [r["query"] for r in recs] == ["q3", "q2"]   # ring of 2
    assert recs[0]["elapsed_ms"] == 70
    assert log.snapshot()["recorded"] == 3
    off = SlowQueryLog(threshold_ms=0)
    assert not off.enabled
    assert not off.maybe_record(10_000, {"query": "x"})


def test_inflight_registry():
    reg = InflightRegistry()
    e1 = reg.register("q1", "ds", kind="range")
    e2 = reg.register("q2", "ds", kind="instant")
    reg.stage(e1, "execute")
    snap = reg.snapshot()
    assert len(snap) == 2 and len(reg) == 2
    assert snap[0]["query"] == "q1" and snap[0]["stage"] == "execute"
    assert snap[0]["elapsed_ms"] >= 0
    assert json.dumps(snap)            # JSON-safe for /debug/queries
    reg.unregister(e1)
    reg.unregister(e1)                 # idempotent
    reg.unregister(None)               # tolerated
    assert len(reg) == 1
    reg.unregister(e2)
    assert reg.snapshot() == []
