"""Gateway tests: influx line protocol parsing + producer sharding
(reference: gateway/src/test InfluxProtocolParserSpec shapes,
TestTimeseriesProducer)."""

import numpy as np
import pytest

from filodb_tpu.core.memstore import TimeSeriesMemStore
from filodb_tpu.core.record import RecordBuilder
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetRef
from filodb_tpu.gateway.influx import (InfluxParseError, parse_line,
                                       parse_lines, record_to_builder)
from filodb_tpu.gateway.producer import (TestTimeseriesProducer,
                                         ingest_builders)
from filodb_tpu.core.index import ColumnFilter


def test_parse_basic_gauge_line():
    r = parse_line(
        "heap_usage,host=h0,dc=dc1 gauge=12.5 1600000000000000000")
    assert r.measurement == "heap_usage"
    assert r.tags == {"host": "h0", "dc": "dc1"}
    assert r.fields == {"gauge": 12.5}
    assert r.timestamp_ms == 1_600_000_000_000


def test_parse_escapes_and_int_suffix():
    r = parse_line(
        r"my\ metric,tag\,x=a\ b counter=42i 1600000000000000000")
    assert r.measurement == "my metric"
    assert r.tags == {"tag,x": "a b"}
    assert r.fields == {"counter": 42.0}


def test_parse_missing_timestamp_uses_now():
    r = parse_line("m value=1.0", now_ms=123_000)
    assert r.timestamp_ms == 123_000


@pytest.mark.parametrize("bad", ["justname", "m,badtag value=1 x y z",
                                 "m novalue", "m f=abc"])
def test_parse_errors(bad):
    with pytest.raises(InfluxParseError):
        parse_line(bad)


def test_histogram_mapping_and_query():
    b = RecordBuilder(DEFAULT_SCHEMAS)
    used = record_to_builder(parse_line(
        "lat,host=h0 sum=100.0,count=10,2=1,4=4,8=9,+Inf=10 "
        "1600000000000000000"), b)
    assert used == ["prom-histogram"]


def test_counter_lines_end_to_end_query():
    store = TimeSeriesMemStore(DEFAULT_SCHEMAS)
    ref = DatasetRef("ts")
    store.setup(ref, 0)
    b = RecordBuilder(DEFAULT_SCHEMAS)
    t0 = 1_600_000_000_000
    lines = []
    v = 0
    for i in range(60):
        v += 100
        lines.append(f"reqs,host=h0 counter={v} {(t0 + i * 10_000) * 10**6}")
    n = parse_lines("\n".join(lines), b)
    assert n == 60
    for c in b.containers():
        store.ingest(ref, 0, c)
    store.flush_all(ref)
    parts = store.lookup_partitions(
        ref, 0, [ColumnFilter.eq("_metric_", "reqs")], t0, t0 + 10**9)
    assert len(parts) == 1


def test_producer_shards_consistently():
    p = TestTimeseriesProducer(DEFAULT_SCHEMAS, num_shards=8, spread=2)
    labels = p._labels("heap_usage", 1)
    s1 = p.shard_for("gauge", labels)
    s2 = p.shard_for("gauge", labels)
    assert s1 == s2 and 0 <= s1 < 8
    builders = p.gauges(1_600_000_000_000, 30, n_instances=8)
    assert sum(len(c) for b in builders.values()
               for c in b.containers()) == 240


def test_producer_ingest_roundtrip():
    store = TimeSeriesMemStore(DEFAULT_SCHEMAS)
    ref = DatasetRef("ts")
    for i in range(4):
        store.setup(ref, i)
    p = TestTimeseriesProducer(DEFAULT_SCHEMAS, num_shards=4)
    rows = ingest_builders(store, ref,
                           p.counters(1_600_000_000_000, 100))
    assert rows == 400
