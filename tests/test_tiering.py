"""Raw/downsample query tiering (LongTimeRangePlanner.scala:30 +
StitchRvsExec.scala:116): queries reaching beyond raw retention split into
a downsample-side exec and a raw-side exec, stitched on the step grid.

Parity oracle: a shard holding the FULL history answers the same query
all-raw; the tiered answer (recent-only raw shard + downsample store built
by the batch job over the full history) must match.
"""

import numpy as np

from filodb_tpu.core.memstore import TimeSeriesShard
from filodb_tpu.core.record import RecordBuilder
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetRef
from filodb_tpu.downsample import (DownsampledTimeSeriesStore,
                                   DownsamplerJob)
from filodb_tpu.promql.parser import TimeStepParams, parse_query_range
from filodb_tpu.query.engine import QueryEngine
from filodb_tpu.query.model import GridResult
from filodb_tpu.query.planner import (QueryPlanner, StitchExec,
                                      plan_range, stitch_grids)
from filodb_tpu.store import FlatFileColumnStore

REF = DatasetRef("timeseries")
RES = 300_000                       # 5m downsample resolution
T0 = (1_600_000_000_000 // RES) * RES
SAMPLE_OFF = 5_000                  # samples 5s past period boundaries
N_SAMPLES = 720                     # 2h at 10s
SPAN_MS = N_SAMPLES * 10_000
NOW = T0 + SPAN_MS
RETENTION_MS = 1_800_000            # raw keeps the last 30min
EARLIEST_RAW = NOW - RETENTION_MS


def _add_all(builder, first, last):
    """Gauges + counters for sample index range [first, last)."""
    for s in range(3):
        glabels = {"_metric_": "cpu", "_ws_": "demo", "_ns_": "App-0",
                   "instance": f"i{s}"}
        clabels = {"_metric_": "reqs_total", "_ws_": "demo",
                   "_ns_": "App-0", "instance": f"i{s}"}
        for t in range(first, last):
            ts = T0 + SAMPLE_OFF + t * 10_000
            builder.add_sample("gauge", glabels, ts,
                               50.0 + 40.0 * np.sin(t / 7.0 + s))
            builder.add_sample("prom-counter", clabels, ts,
                               float((t + 1) * (s + 1)))


def _mk_shard(first, last, column_store=None):
    shard = TimeSeriesShard(REF, DEFAULT_SCHEMAS, 0,
                            column_store=column_store, max_chunk_rows=120)
    b = RecordBuilder(DEFAULT_SCHEMAS)
    _add_all(b, first, last)
    for c in b.containers():
        shard.ingest(c)
    if column_store is not None:
        shard.flush_all(offset=1)
    return shard


def _setup(tmp_path):
    # oracle: everything raw
    full_shard = _mk_shard(0, N_SAMPLES)
    # production: persisted full history -> downsampler job -> ds store,
    # plus a raw shard holding only what retention keeps
    cs = FlatFileColumnStore(str(tmp_path / "col"))
    _mk_shard(0, N_SAMPLES, column_store=cs)
    DownsamplerJob(cs, resolutions=(RES,)).run("timeseries", 0)
    first_kept = (EARLIEST_RAW - T0) // 10_000
    recent_shard = _mk_shard(first_kept, N_SAMPLES)
    ds_store = DownsampledTimeSeriesStore(cs, "timeseries", 1,
                                          resolutions=(RES,))
    planner = QueryPlanner([recent_shard], ds_store=ds_store,
                           raw_retention_ms=RETENTION_MS, now_ms=NOW)
    return full_shard, planner


def _compare(full_shard, planner, query, tsp, rtol=0.0):
    plan = parse_query_range(query, tsp)
    want = QueryEngine([full_shard]).execute(plan)
    got = planner.execute(plan)
    assert isinstance(got, GridResult)
    np.testing.assert_array_equal(got.steps, want.steps)
    gmap = {tuple(sorted(k.items())): got.values[i]
            for i, k in enumerate(got.keys)}
    assert len(gmap) == want.num_series, query
    for i, k in enumerate(want.keys):
        g = gmap[tuple(sorted(k.items()))]
        if rtol == 0.0:
            np.testing.assert_allclose(g, want.values[i], rtol=1e-12,
                                       equal_nan=True, err_msg=query)
        else:
            ok = np.isfinite(want.values[i]) & np.isfinite(g)
            assert ok.sum() >= want.values[i].size - 2, query
            np.testing.assert_allclose(g[ok], want.values[i][ok],
                                       rtol=rtol, err_msg=query)


def test_split_plan_shape(tmp_path):
    full_shard, planner = _setup(tmp_path)
    tsp = TimeStepParams(T0 // 1000 + 1800, 600, NOW // 1000)
    plan = parse_query_range("min_over_time(cpu[10m])", tsp)
    ex = planner.materialize(plan)
    assert isinstance(ex, StitchExec)
    assert ex.ds_exec is not None and ex.raw_exec is not None
    # raw side starts at the first step whose window is inside retention
    rng = plan_range(ex.raw_exec.plan)
    assert rng[0] - rng[3] >= EARLIEST_RAW
    # ds side ends exactly one step earlier
    ds_rng = plan_range(ex.ds_exec.plan)
    assert ds_rng[2] == rng[0] - rng[1]


def test_gauge_queries_stitch_exactly(tmp_path):
    full_shard, planner = _setup(tmp_path)
    # step grid on period boundaries; 10m windows nest 5m ds periods
    tsp = TimeStepParams(T0 // 1000 + 1800, 600, NOW // 1000)
    for q in ["min_over_time(cpu[10m])",
              "max_over_time(cpu[10m])",
              "sum_over_time(cpu[10m])",
              "count_over_time(cpu[10m])",
              "sum(min_over_time(cpu[10m])) by (instance)",
              "avg(max_over_time(cpu[10m]))"]:
        _compare(full_shard, planner, q, tsp)


def test_counter_rate_stitches(tmp_path):
    full_shard, planner = _setup(tmp_path)
    tsp = TimeStepParams(T0 // 1000 + 1800, 600, NOW // 1000)
    # ds counter chunks keep period boundary samples: small extrapolation
    # differences only
    _compare(full_shard, planner, "increase(reqs_total[10m])", tsp,
             rtol=0.05)
    _compare(full_shard, planner, "sum(rate(reqs_total[10m]))", tsp,
             rtol=0.05)


def test_fully_beyond_retention_serves_from_ds(tmp_path):
    full_shard, planner = _setup(tmp_path)
    # whole query older than retention: every step from the ds tier
    tsp = TimeStepParams(T0 // 1000 + 1800, 600,
                        (EARLIEST_RAW - 1_200_000) // 1000)
    plan = parse_query_range("min_over_time(cpu[10m])", tsp)
    ex = planner.materialize(plan)
    assert isinstance(ex, StitchExec) and ex.raw_exec is None
    _compare(full_shard, planner, "min_over_time(cpu[10m])", tsp)


def test_recent_query_stays_raw(tmp_path):
    full_shard, planner = _setup(tmp_path)
    tsp = TimeStepParams((EARLIEST_RAW + 1_200_000) // 1000, 600,
                         NOW // 1000)
    plan = parse_query_range("min_over_time(cpu[10m])", tsp)
    ex = planner.materialize(plan)
    assert not isinstance(ex, StitchExec)
    _compare(full_shard, planner, "min_over_time(cpu[10m])", tsp)


def test_no_ds_mapping_falls_back_to_raw(tmp_path):
    full_shard, planner = _setup(tmp_path)
    tsp = TimeStepParams(T0 // 1000 + 1800, 600, NOW // 1000)
    # quantile_over_time has no exact ds column: raw-only (and therefore
    # silent about the pre-retention region, matching reference behavior)
    plan = parse_query_range("quantile_over_time(0.5, cpu[10m])", tsp)
    ex = planner.materialize(plan)
    assert not isinstance(ex, StitchExec)


def test_replace_range_keeps_offset_in_raw_bounds():
    """Regression: the tier split's range rewrite must shift raw fetch
    bounds by the offset (the mesh path reads raw.start/end directly)."""
    from filodb_tpu.query.engine import lp_replace_range
    tsp = TimeStepParams(1000, 60, 2000)
    plan = parse_query_range("rate(reqs_total[5m] offset 1h)", tsp)
    out = lp_replace_range(plan, 1_500_000, 60_000, 2_000_000)
    assert out.raw.start_ms == 1_500_000 - 300_000 - 3_600_000
    assert out.raw.end_ms == 2_000_000 - 3_600_000


def test_offset_query_stitches(tmp_path):
    full_shard, planner = _setup(tmp_path)
    tsp = TimeStepParams(T0 // 1000 + 3600, 600, NOW // 1000)
    _compare(full_shard, planner,
             "min_over_time(cpu[10m] offset 30m)", tsp)


def test_mixed_windows_use_min_for_resolution(tmp_path):
    """Regression: a small window alongside a large one must veto a
    resolution too coarse for the small window (else silently wrong)."""
    full_shard, planner = _setup(tmp_path)
    tsp = TimeStepParams(T0 // 1000 + 1800, 600, NOW // 1000)
    # 10m window alone would pick res=5m; the 5m window (5m < 2*5m)
    # rejects it -> whole query answers from raw (no stitch)
    plan = parse_query_range(
        "min_over_time(cpu[10m]) + min_over_time(cpu[5m])", tsp)
    ex = planner.materialize(plan)
    assert not isinstance(ex, StitchExec)


def test_at_pinned_beyond_retention_routes_to_ds(tmp_path):
    """Regression: @ pinned before raw retention must consult the ds tier
    (the step grid itself is recent, but the data read is not)."""
    full_shard, planner = _setup(tmp_path)
    at_s = (T0 + 1_800_000) // 1000          # well before earliest_raw
    tsp = TimeStepParams((EARLIEST_RAW + 1_200_000) // 1000, 600,
                         NOW // 1000)
    plan = parse_query_range(f"min_over_time(cpu[10m] @ {at_s})", tsp)
    ex = planner.materialize(plan)
    assert isinstance(ex, StitchExec) and ex.raw_exec is None
    got = ex.execute()
    want = QueryEngine([full_shard]).execute(plan)
    assert got.num_series == want.num_series == 3
    gmap = {k["instance"]: got.values[i] for i, k in enumerate(got.keys)}
    for i, k in enumerate(want.keys):
        np.testing.assert_allclose(gmap[k["instance"]], want.values[i],
                                   rtol=1e-12, equal_nan=True)


def test_stitch_grids_prefers_first_non_nan():
    steps_a = np.array([0, 60, 120], dtype=np.int64)
    steps_b = np.array([120, 180], dtype=np.int64)
    a = GridResult(steps_a, [{"x": "1"}],
                   np.array([[1.0, np.nan, 3.0]]))
    b = GridResult(steps_b, [{"x": "1"}, {"x": "2"}],
                   np.array([[9.0, 4.0], [7.0, 8.0]]))
    out = stitch_grids(a, b)
    np.testing.assert_array_equal(out.steps, [0, 60, 120, 180])
    m = {k["x"]: out.values[i] for i, k in enumerate(out.keys)}
    # overlap at 120: first side's non-NaN wins
    np.testing.assert_allclose(m["1"], [1.0, np.nan, 3.0, 4.0],
                               equal_nan=True)
    np.testing.assert_allclose(m["2"], [np.nan, np.nan, 7.0, 8.0],
                               equal_nan=True)
