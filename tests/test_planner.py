"""Planner tests: shard pruning from shard-key filters, mesh lowering of
the aggregate shape, fallback paths, and the HTTP e2e through an
8-virtual-device mesh (parity model: SingleClusterPlannerSpec golden
plans + multi-jvm cluster specs)."""

import json
import urllib.request

import numpy as np
import pytest

from filodb_tpu.core.memstore import TimeSeriesMemStore
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetRef
from filodb_tpu.gateway.producer import TestTimeseriesProducer, ingest_builders
from filodb_tpu.parallel.mesh import MeshExecutor, make_mesh
from filodb_tpu.parallel.shardmapper import ShardMapper, assign_shards_evenly
from filodb_tpu.promql.parser import TimeStepParams, parse_query_range
from filodb_tpu.query.engine import QueryEngine
from filodb_tpu.query.planner import (LocalEngineExec, MeshAggregateExec,
                                      QueryPlanner)

REF = DatasetRef("timeseries")
T0 = 1_600_000_000
NUM_SHARDS = 8
SPREAD = 1


@pytest.fixture(scope="module")
def cluster():
    """8-shard store seeded via the producer (ingestion_shard routing)."""
    store = TimeSeriesMemStore(DEFAULT_SCHEMAS)
    for sh in range(NUM_SHARDS):
        store.setup(REF, sh)
    producer = TestTimeseriesProducer(DEFAULT_SCHEMAS,
                                      num_shards=NUM_SHARDS, spread=SPREAD)
    ingest_builders(store, REF, producer.counters(T0 * 1000, 360, 6))
    ingest_builders(store, REF, producer.gauges(T0 * 1000, 360, 6))
    store.flush_all(REF)
    mapper = ShardMapper(NUM_SHARDS)
    assign_shards_evenly(mapper, ["node0"])
    for s in range(NUM_SHARDS):
        mapper.activate(s)
    return store, mapper


def _plan(q, start=T0 + 600, end=T0 + 3000, step=60):
    return parse_query_range(q, TimeStepParams(start, step, end))


def test_shard_pruning_touches_only_hashed_shards(cluster):
    store, mapper = cluster
    shards = store.shards(REF)
    # spy on lookup calls
    calls = {s.shard_num: 0 for s in shards}
    orig = {}
    for s in shards:
        orig[s.shard_num] = s.lookup_partitions
        def mk(sh, fn):
            def wrapper(*a, **k):
                calls[sh.shard_num] += 1
                return fn(*a, **k)
            return wrapper
        s.lookup_partitions = mk(s, s.lookup_partitions)
    try:
        planner = QueryPlanner(shards, shard_mapper=mapper, spread=SPREAD)
        res = planner.execute(_plan(
            'rate(http_requests_total{_ws_="demo",_ns_="App-0"}[5m])'))
        assert res.num_series > 0
        touched = {sh for sh, c in calls.items() if c > 0}
        # the shard-key (demo, App-0, http_requests_total) at spread 1
        # maps to exactly 2 shards
        from filodb_tpu.core.record import shard_key_hash
        skh = shard_key_hash(["demo", "App-0"], "http_requests_total")
        expected = set(mapper.query_shards(skh, SPREAD))
        assert len(expected) == 2 ** SPREAD
        assert touched == expected
    finally:
        for s in shards:
            s.lookup_partitions = orig[s.shard_num]


def test_pruned_result_matches_full_fanout(cluster):
    store, mapper = cluster
    shards = store.shards(REF)
    planner = QueryPlanner(shards, shard_mapper=mapper, spread=SPREAD)
    q = 'sum(rate(http_requests_total{_ws_="demo",_ns_="App-0"}[5m]))'
    got = planner.execute(_plan(q))
    want = QueryEngine(shards).execute(_plan(q))
    np.testing.assert_allclose(got.values, want.values, rtol=1e-9,
                               equal_nan=True)


def test_no_shard_key_filters_fans_out(cluster):
    store, mapper = cluster
    planner = QueryPlanner(store.shards(REF), shard_mapper=mapper,
                           spread=SPREAD)
    mat = planner.materialize(_plan("rate(http_requests_total[5m])"))
    assert isinstance(mat, LocalEngineExec)
    assert len(mat.shards) == NUM_SHARDS


def test_down_shards_excluded(cluster):
    store, mapper = cluster
    from filodb_tpu.parallel.shardmapper import ShardStatus
    planner = QueryPlanner(store.shards(REF), shard_mapper=mapper,
                           spread=SPREAD)
    mapper.update(3, ShardStatus.DOWN)
    try:
        mat = planner.materialize(_plan("rate(http_requests_total[5m])"))
        assert all(s.shard_num != 3 for s in mat.shards)
    finally:
        mapper.activate(3)


@pytest.fixture(scope="module")
def mesh8():
    return MeshExecutor(make_mesh())


def test_mesh_lowering_shape(cluster, mesh8):
    store, mapper = cluster
    planner = QueryPlanner(store.shards(REF), shard_mapper=mapper,
                           mesh_executor=mesh8, spread=SPREAD)
    mat = planner.materialize(_plan(
        "sum(rate(http_requests_total[5m])) by (instance)"))
    assert isinstance(mat, MeshAggregateExec)
    # topk/bottomk and `without` grouping lower onto the mesh too
    for q in ["topk(2, rate(http_requests_total[5m]))",
              "sum(rate(http_requests_total[5m])) without (instance)"]:
        assert isinstance(planner.materialize(_plan(q)),
                          MeshAggregateExec), q
    # non-lowerable shapes stay local
    for q in ["rate(http_requests_total[5m])",
              "sum(abs(heap_usage))",
              "quantile(0.5, rate(http_requests_total[5m]))"]:
        assert isinstance(planner.materialize(_plan(q)), LocalEngineExec), q


@pytest.mark.parametrize("q", [
    "sum(rate(http_requests_total[5m])) by (instance)",
    "sum(rate(http_requests_total[5m]))",
    "max(increase(http_requests_total[5m])) by (instance)",
    "count(delta(heap_usage[5m])) by (instance)",
    "avg(sum_over_time(heap_usage[2m])) by (instance)",
    'min(max_over_time(heap_usage{_ws_="demo",_ns_="App-0"}[5m]))',
    "sum(rate(http_requests_total[5m])) without (instance)",
    "topk(2, rate(http_requests_total[5m]))",
    "bottomk(1, rate(http_requests_total[5m]))",
    "topk(2, sum_over_time(heap_usage[2m])) by (instance)",
])
def test_mesh_execution_matches_oracle(cluster, mesh8, q):
    store, mapper = cluster
    shards = store.shards(REF)
    planner = QueryPlanner(shards, shard_mapper=mapper, mesh_executor=mesh8,
                           spread=SPREAD)
    mat = planner.materialize(_plan(q))
    assert isinstance(mat, MeshAggregateExec), q
    got = mat.execute()
    want = QueryEngine(shards).execute(_plan(q))
    gmap = {tuple(sorted(k.items())): got.values[i]
            for i, k in enumerate(got.keys)}
    assert len(gmap) == want.num_series
    for i, k in enumerate(want.keys):
        np.testing.assert_allclose(gmap[tuple(sorted(k.items()))],
                                   want.values[i], rtol=1e-8,
                                   equal_nan=True, err_msg=q)


def test_http_e2e_through_mesh(cluster, mesh8):
    from filodb_tpu.http.server import FiloHttpServer

    store, mapper = cluster
    shards = store.shards(REF)
    srv = FiloHttpServer({"timeseries": shards}, backend=None,
                         shard_mapper=mapper, mesh_executor=mesh8,
                         spread=SPREAD, port=0)
    srv.start()
    try:
        url = (f"http://127.0.0.1:{srv.port}/promql/timeseries/api/v1/"
               f"query_range?query=sum(rate(http_requests_total%5B5m%5D))"
               f"%20by%20(instance)&start={T0 + 600}&end={T0 + 3000}&step=60")
        resp = json.load(urllib.request.urlopen(url))
        assert resp["status"] == "success"
        result = resp["data"]["result"]
        assert len(result) == 6          # one row per instance
        want = QueryEngine(shards).execute(_plan(
            "sum(rate(http_requests_total[5m])) by (instance)"))
        wmap = {k["instance"]: want.values[i]
                for i, k in enumerate(want.keys)}
        for series in result:
            inst = series["metric"]["instance"]
            for ts_s, v in series["values"]:
                idx = (int(ts_s) * 1000 - (T0 + 600) * 1000) // 60_000
                np.testing.assert_allclose(float(v), wmap[inst][idx],
                                           rtol=1e-8)
    finally:
        srv.stop()
