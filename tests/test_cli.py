"""CLI surface (cli/CliMain.scala:159-266 equivalents) against a live
in-process server + offline debug commands."""

import contextlib
import io
import json

import numpy as np
import pytest

from filodb_tpu import cli
from filodb_tpu.standalone.server import FiloServer

T0 = 1_600_000_000


@pytest.fixture(scope="module")
def server():
    srv = FiloServer({"num-shards": 2, "port": 0}).start()
    srv.seed_dev_data(n_samples=30, n_instances=2, start_ms=T0 * 1000)
    yield srv
    srv.stop()


def _run(*argv):
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        cli.main(list(argv))
    return json.loads(buf.getvalue())


def test_status(server):
    out = _run("--host", f"http://127.0.0.1:{server.port}", "status")
    assert {s["shard"] for s in out["data"]} == {0, 1}


def test_labels_and_values(server):
    host = f"http://127.0.0.1:{server.port}"
    labels = _run("--host", host, "labels")
    assert "_ws_" in labels["data"]
    vals = _run("--host", host, "labelvalues", "_ws_")
    assert vals["data"] == ["demo"]


def test_query_range(server):
    host = f"http://127.0.0.1:{server.port}"
    out = _run("--host", host, "query-range",
               "rate(http_requests_total[5m])",
               "--start", str(T0 + 100), "--end", str(T0 + 290),
               "--step", "60")
    assert out["status"] == "success"


def test_tscard_and_topk(server):
    host = f"http://127.0.0.1:{server.port}"
    out = _run("--host", host, "tscard", "--prefix", "demo")
    assert out["data"][0]["tsCount"] > 0
    top = _run("--host", host, "topkcard", "--prefix", "demo", "-k", "1")
    assert len(top) == 1


def test_find_query_shards():
    out = _run("find-query-shards", "demo,App-0", "heap_usage",
               "--spread", "1", "--num-shards", "4")
    assert len(out["shards"]) == 2


def test_validate_schemas():
    out = _run("validate-schemas")
    assert out["ok"] and "prom-counter" in out["schemas"]


def test_decode_vector_roundtrip():
    from filodb_tpu.memory import vectors as bv
    vals = np.arange(10, dtype=np.float64) * 1.5
    buf = bv.encode_doubles(vals)
    out = _run("decode-vector", "hex:" + buf.hex())
    np.testing.assert_allclose(out["values"], vals)


def test_decode_chunk_info(tmp_path):
    from filodb_tpu.core.memstore import TimeSeriesShard
    from filodb_tpu.core.record import RecordBuilder
    from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetRef
    from filodb_tpu.store import FlatFileColumnStore
    cs = FlatFileColumnStore(str(tmp_path))
    shard = TimeSeriesShard(DatasetRef("timeseries"), DEFAULT_SCHEMAS, 0,
                            column_store=cs)
    b = RecordBuilder(DEFAULT_SCHEMAS)
    for t in range(20):
        b.add_sample("gauge", {"_metric_": "m", "_ws_": "w", "_ns_": "n"},
                     1000 + t * 10, float(t))
    for c in b.containers():
        shard.ingest(c)
    shard.flush_all(offset=1)
    out = _run("decode-chunk-info", str(tmp_path))
    assert out and out[0]["numRows"] == 20
