"""Distributed-trace propagation across the cluster planes
(obs/trace + grpcsvc + parallel/cluster), under chaos faults:

  * a range query through a 2-node cluster on the gRPC data plane with
    one injected transport failure yields ONE stitched trace — entry
    node stages, the remote-peer subspan, the failed attempt as a
    SIBLING span tagged with the failure, and the peer's own spans
    shipped back over the wire;
  * the gRPC -> HTTP plane fallback keeps propagating the context
    (header on the JSON control plane) and stitches the peer's spans;
  * breaker rejections land as point events on the trace;
  * with tracing disabled (the default), responses carry no trace keys
    and stay on the canonical pre-encoded fast path byte-for-byte.
"""

import json
import socket
import urllib.parse
import urllib.request

import pytest

from filodb_tpu.standalone.server import FiloServer
from filodb_tpu.testing import chaos

T0 = 1_600_000_000
N_SAMPLES = 60
N_INSTANCES = 4
QUERY = 'rate({_metric_=~"heap_usage|http_requests_total"}[5m])'


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _get(port, path, **params):
    qs = urllib.parse.urlencode(params, doseq=True)
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}?{qs}", timeout=120) as r:
        return json.loads(r.read())


def _query(port, **extra):
    return _get(port, "/promql/timeseries/api/v1/query_range",
                query=QUERY, start=T0 + 300,
                end=T0 + (N_SAMPLES - 1) * 10, step=60, **extra)


@pytest.fixture
def cluster():
    """Two in-process nodes, half the shards each, gRPC data plane with
    HTTP fallback. Failure detection polls too slowly to react — the
    trace must capture what the exec layer does in that window."""
    pytest.importorskip("grpc")
    p0, p1 = _free_port(), _free_port()
    g0, g1 = _free_port(), _free_port()
    peers = {"node0": f"http://127.0.0.1:{p0}",
             "node1": f"http://127.0.0.1:{p1}"}
    grpc_peers = {"node0": f"127.0.0.1:{g0}",
                  "node1": f"127.0.0.1:{g1}"}
    base = {
        "num-shards": 4, "num-nodes": 2, "peers": peers,
        "grpc-peers": grpc_peers,
        "query-sample-limit": 0, "query-series-limit": 0,
        "failure-detect-interval-s": 300.0,
        # traces must capture the FULL pipeline (select/eval/peer hops)
        # on every request — a results-cache hit would short-circuit the
        # spans (and the scan stats) these tests pin
        "results-cache-mb": 0,
        "query-timeout-s": 8.0,
        "peer-retry-attempts": 3,
        "peer-retry-base-delay-s": 0.01,
        "breaker-failure-threshold": 5,
        "breaker-reset-s": 0.3,
    }
    a = FiloServer({**base, "node-ordinal": 0, "port": p0,
                    "grpc-port": g0}).start()
    a.seed_dev_data(n_samples=N_SAMPLES, n_instances=N_INSTANCES,
                    start_ms=T0 * 1000)
    b = FiloServer({**base, "node-ordinal": 1, "port": p1,
                    "grpc-port": g1}).start()
    b.seed_dev_data(n_samples=N_SAMPLES, n_instances=N_INSTANCES,
                    start_ms=T0 * 1000)
    try:
        yield a, b
    finally:
        chaos.uninstall()
        for srv in (a, b):
            try:
                srv.stop()
            except Exception:
                pass


def _spans_by_name(tr):
    by = {}
    for s in tr["spans"]:
        by.setdefault(s["name"], []).append(s)
    return by


def test_stitched_trace_across_grpc_with_injected_retry(cluster):
    a, b = cluster
    inj = chaos.ChaosInjector()
    # exactly ONE transport failure against node1's gRPC service: the
    # second attempt succeeds, so the query completes normally
    inj.fail("grpc.call", times=1,
             match=lambda c: c.get("node") == "node1")
    with inj:
        body = _query(a.port, **{"explain": "trace"})
    assert body["status"] == "success"
    assert len(body["data"]["result"]) >= 2 * N_INSTANCES
    tr = body["trace"]
    spans = tr["spans"]
    assert tr["num_spans"] == len(spans) >= 10, tr["num_spans"]
    by = _spans_by_name(tr)
    ids = {s["span_id"] for s in spans}

    # ONE stitched trace: a single root, every parent resolves
    roots = [s for s in spans if s["parent_id"] is None]
    assert len(roots) == 1 and roots[0]["name"] == "query"
    for s in spans:
        if s["parent_id"] is not None:
            assert s["parent_id"] in ids, s

    # entry-node stage catalog
    for name in ("parse", "plan", "execute", "select-series",
                 "device-eval", "encode"):
        assert name in by, (name, sorted(by))

    # the remote-peer subspan with the peer's OWN spans stitched under
    # the successful attempt (trace context crossed the gRPC wire)
    (peer,) = by["remote-peer"]
    assert peer["tags"]["node"] == "node1"
    assert peer["tags"]["plane"] == "grpc"
    attempts = sorted(by["peer-attempt"], key=lambda s: s["start_us"])
    assert len(attempts) == 2
    # siblings under the remote-peer span; the first tagged w/ failure
    assert {s["parent_id"] for s in attempts} == {peer["span_id"]}
    assert attempts[0]["tags"]["retry"] is False
    assert "error" in attempts[0] and "unreachable" in \
        attempts[0]["error"]
    assert attempts[1]["tags"]["retry"] is True
    assert "error" not in attempts[1]
    remote = by["peer-fetch-raw"]
    assert remote and remote[0]["tags"]["node"] == "node1"
    assert remote[0]["parent_id"] == attempts[1]["span_id"]
    # the peer's select span rides under its peer-fetch-raw span
    selects = by["select-span"]
    assert any(s["parent_id"] == remote[0]["span_id"] for s in selects)

    # the trace is retrievable from the entry node's ring buffer and
    # identical in span count
    stored = _get(a.port, "/debug/traces", id=tr["trace_id"])
    assert stored["data"]["num_spans"] == tr["num_spans"]


def test_fallback_to_http_plane_keeps_the_trace(cluster):
    a, b = cluster
    inj = chaos.ChaosInjector()
    # every gRPC dial to node1 fails -> retries exhaust -> the client
    # downgrades to the JSON control plane, which must keep propagating
    # the trace context via the HTTP header
    inj.fail("grpc.call", match=lambda c: c.get("node") == "node1")
    with inj:
        body = _query(a.port, **{"explain": "trace"})
    assert body["status"] == "success"
    by = _spans_by_name(body["trace"])
    planes = {s["tags"]["plane"] for s in by["remote-peer"]}
    assert planes == {"grpc", "http"}       # nested fallback hop
    assert "plane-fallback" in by
    # the peer's spans arrived over the HTTP plane response envelope
    remote = [s for s in by["peer-fetch-raw"]
              if s["tags"].get("plane") == "http"]
    assert remote and remote[0]["tags"]["node"] == "node1"
    # failed gRPC attempts are siblings tagged with the failure
    failed = [s for s in by["peer-attempt"] if "error" in s]
    assert len(failed) == 3                 # retry policy exhausted


def test_breaker_rejection_lands_on_the_trace(cluster):
    a, b = cluster
    # trip node1's breaker at the entry node (threshold 5): the next
    # dial is REJECTED without being attempted. With allow_partial the
    # query still succeeds (peer's shard group drops out) and the trace
    # must carry the rejection as a point event under the remote hop.
    reg = a.http.resilience.breakers
    addr = a.http.grpc_peers["node1"]
    br = reg.get(addr)
    for _ in range(5):
        br.record_failure()
    assert br.state == "open"
    body = _query(a.port, **{"explain": "trace",
                             "allow_partial": "true"})
    assert body["status"] == "success" and body.get("partial") is True
    by = _spans_by_name(body["trace"])
    (rej,) = by["breaker-rejected"]
    assert rej["tags"]["peer"] == "node1" and rej["dur_us"] == 0
    # rejected = not dialed: no attempt spans, no peer subspans
    assert "peer-attempt" not in by
    assert "peer-fetch-raw" not in by


def test_disabled_tracing_responses_are_byte_identical(cluster):
    """Tracing off (default): the response must stay on the canonical
    compact-JSON fast path with NO trace keys — re-encoding the parsed
    body compactly reproduces the exact bytes (the pre-PR encoder
    contract), and equal requests return equal bytes modulo the
    wall-clock timings block."""
    a, b = cluster
    qs = urllib.parse.urlencode(
        dict(query=QUERY, start=T0 + 300, end=T0 + (N_SAMPLES - 1) * 10,
             step=60))
    url = (f"http://127.0.0.1:{a.port}/promql/timeseries/api/v1/"
           f"query_range?{qs}")
    with urllib.request.urlopen(url, timeout=120) as r:
        raw1 = r.read()
    with urllib.request.urlopen(url, timeout=120) as r:
        raw2 = r.read()
    parsed1 = json.loads(raw1)
    parsed2 = json.loads(raw2)
    assert "trace" not in parsed1 and "trace_spans" not in parsed1
    # canonical compact encoding: matrix_bytes output == compact dump
    assert raw1 == json.dumps(parsed1, separators=(",", ":")).encode()
    # identical request -> identical bytes modulo wall-clock timings
    parsed1["stats"].pop("timings")
    parsed2["stats"].pop("timings")
    assert parsed1 == parsed2
    # and nothing was traced server-side
    assert a.http.tracer.snapshot()["started"] == 0