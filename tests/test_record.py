"""Record format + hashing tests.

Hash compatibility is the critical parity surface: xxh32 vectors are pinned
against the published XXH32 test vectors, and shard routing math mirrors
coordinator/ShardMapper.scala:93,122.
"""

import numpy as np

from filodb_tpu.core import record as rec
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, PartitionSchema
from filodb_tpu.utils.xxhash import xxhash32


def test_xxh32_known_vectors():
    # Published XXH32 sanity vectors (seed 0): xxh32("") = 0x02cc5d05,
    # xxh32("Hello, world!") with seed 0 = 0x31b7405d... use authoritative ones:
    assert xxhash32(b"", 0) == 0x02CC5D05
    assert xxhash32(b"a", 0) == 0x550D7456
    assert xxhash32(b"abc", 0) == 0x32D153FF
def test_xxh32_seeded_and_signed():
    # must return Java Int (signed) semantics
    h = xxhash32(b"some_metric_name")
    assert -(1 << 31) <= h < (1 << 31)


def test_combine_hash_java_overflow():
    # 31*h1+h2 wraps like a JVM Int: 31*2^30 + 2^30 = 2^35 ≡ 0 (mod 2^32)
    assert rec.combine_hash(2**30, 2**30) == 0
    assert rec.combine_hash(-1, -1) == -32  # 31*(-1) + (-1)


def test_shard_key_hash_deterministic():
    h1 = rec.shard_key_hash(["demo", "App-0"], "heap_usage")
    h2 = rec.shard_key_hash(["demo", "App-0"], "heap_usage")
    h3 = rec.shard_key_hash(["demo", "App-1"], "heap_usage")
    assert h1 == h2
    assert h1 != h3


def test_ingestion_shard_spread_semantics():
    # ShardMapper.scala:122 — same shard key spreads over 2^spread shards,
    # and every one of those shards is in queryShards for that key.
    num_shards, spread = 32, 2
    skh = rec.shard_key_hash(["demo", "App-0"], "http_requests_total")
    qshards = rec.query_shards(skh, spread, num_shards)
    assert len(qshards) == 1 << spread
    seen = set()
    for i in range(200):
        ph = rec.partition_key_hash({"_metric_": "http_requests_total",
                                     "_ws_": "demo", "_ns_": "App-0",
                                     "instance": str(i)})
        s = rec.ingestion_shard(skh, ph, spread, num_shards)
        assert s in qshards
        seen.add(s)
    assert len(seen) == 1 << spread  # partition hash spreads across the group


def test_spread_zero_single_shard():
    skh = rec.shard_key_hash(["ws", "ns"], "m")
    ph = rec.partition_key_hash({"a": "b"})
    assert rec.query_shards(skh, 0, 16) == \
        [rec.ingestion_shard(skh, ph, 0, 16)]


def test_partkey_roundtrip():
    schema = DEFAULT_SCHEMAS.by_name("prom-counter")
    labels = {"_metric_": "http_requests_total", "_ws_": "demo",
              "_ns_": "App-0", "instance": "inst-3", "job": "api"}
    pk = rec.PartKey.make(schema, labels)
    pk2 = rec.PartKey.from_bytes(pk.to_bytes())
    assert pk == pk2
    assert pk2.label_map == labels
    assert pk2.schema_id == schema.schema_id


def test_partkey_hashes_stable_under_label_order():
    schema = DEFAULT_SCHEMAS.by_name("gauge")
    l1 = {"b": "2", "a": "1", "_metric_": "m", "_ws_": "w", "_ns_": "n"}
    l2 = dict(reversed(list(l1.items())))
    pk1, pk2 = rec.PartKey.make(schema, l1), rec.PartKey.make(schema, l2)
    assert pk1 == pk2
    ps = PartitionSchema()
    assert pk1.shard_key_hash(ps) == pk2.shard_key_hash(ps)
    assert pk1.part_hash() == pk2.part_hash()


def test_record_builder_containers():
    b = rec.RecordBuilder(DEFAULT_SCHEMAS)
    for i in range(10):
        b.add_sample("gauge",
                     {"_metric_": "cpu", "_ws_": "w", "_ns_": "n",
                      "host": f"h{i % 3}"},
                     1000 + i * 10, float(i))
    conts = b.containers()
    assert len(conts) == 1
    c = conts[0]
    assert len(c) == 10
    rows = list(c.rows())
    assert rows[5].timestamp == 1050
    assert rows[5].values == (5.0,)
    assert b.containers() == []  # drained


def test_schema_ids_unique_and_stable():
    ids = {s.schema_id for s in DEFAULT_SCHEMAS.schemas.values()}
    assert len(ids) == len(DEFAULT_SCHEMAS.schemas)
    # stable across processes: pin a couple of values
    assert DEFAULT_SCHEMAS.by_name("gauge").schema_id == \
        DEFAULT_SCHEMAS.by_name("gauge").schema_id
