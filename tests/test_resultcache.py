"""Incremental range-query results cache (query/resultcache.py).

Pins the ISSUE contract end to end: cache-off and cache-on servers
answer fresh computes byte-identically; stitched cached responses
exactly equal a fresh full recompute (golden vs the &cache=false bypass
of the SAME server — same data, same pipeline, cache out of the loop);
steps above the ingest watermark are never served from cache (new data
appears on the next refresh); watermark regressions invalidate; series
churn computes through; the LRU honours its byte budget; and degraded/
partial results are provably never admitted (chaos-injected peer
failure scenario)."""

import json
import socket
import time
import urllib.parse
import urllib.request

import numpy as np
import pytest

from filodb_tpu.core.record import RecordBuilder
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS
from filodb_tpu.grpcsvc import wire
from filodb_tpu.promql.parser import TimeStepParams, parse_query_range
from filodb_tpu.query.model import GridResult, QueryStats
from filodb_tpu.query.resultcache import (ResultCache, result_cacheable,
                                          shards_watermark)
from filodb_tpu.standalone.server import FiloServer
from filodb_tpu.testing import chaos

T0 = 1_600_000_000


# ---------------------------------------------------------------------------
# unit layer: a stub engine whose "pipeline" is a deterministic function
# of (series, step) — evaluation ranges and call counts are observable
# ---------------------------------------------------------------------------

class _StubExec:
    def __init__(self, eng, plan):
        self.eng = eng
        self.plan = plan

    def execute(self):
        from filodb_tpu.query.planner import plan_range
        start, step, end, _, _ = plan_range(self.plan)
        self.eng.executed.append((start, step, end))
        steps = np.arange(start, end + 1, step, dtype=np.int64)
        keys = [{"_metric_": "up", "instance": f"i{s}"}
                for s in range(self.eng.n_series)]
        vals = np.array([[s * 1e6 + t / 1000.0 for t in steps]
                         for s in range(self.eng.n_series)])
        if not keys:
            vals = np.zeros((0, steps.size))
        g = GridResult(steps, keys, vals)
        g.partial = self.eng.partial
        return g


class _StubEngine:
    def __init__(self, n_series=2, shards=(), partial=False):
        self.n_series = n_series
        self.shards = list(shards)
        self.stats = QueryStats()
        self.partial = partial
        self.executed = []

    def materialize(self, plan):
        return _StubExec(self, plan)


class _Shard:
    def __init__(self, wm, epoch=0):
        self.ingest_watermark_ms = wm
        self.ingest_backfill_epoch = epoch


def _plan(start_s, step_s, end_s, q="up"):
    return parse_query_range(q, TimeStepParams(start_s, step_s, end_s))


def _run(rc, eng, start_s, end_s, step_s=60, q="up", bypass=False):
    plan = _plan(start_s, step_s, end_s, q)
    res, ses = rc.execute(eng, "ds", q, plan, start_s * 1000,
                          step_s * 1000, end_s * 1000, bypass=bypass)
    return res, ses


def test_miss_then_full_hit_then_tail_only():
    rc = ResultCache(max_bytes=1 << 20)
    eng = _StubEngine(shards=[_Shard(10_000_000 * 1000)])
    res, ses = _run(rc, eng, 1000, 1600)
    assert ses.state == "miss" and len(eng.executed) == 1
    full = res
    # same range again: every step from cache, nothing executes
    res2, ses2 = _run(rc, eng, 1000, 1600)
    assert ses2.state == "hit"
    assert len(eng.executed) == 1           # no new evaluation
    assert res2.keys == full.keys
    assert np.array_equal(res2.values, full.values, equal_nan=True)
    # slid window: only the uncovered tail evaluates
    res3, ses3 = _run(rc, eng, 1120, 1720)
    assert ses3.state == "partial"
    assert eng.executed[-1] == (1660 * 1000, 60 * 1000, 1720 * 1000)
    fresh = _StubExec(eng, _plan(1120, 60, 1720)).execute()
    assert np.array_equal(res3.values, fresh.values, equal_nan=True)
    assert [dict(k) for k in res3.keys] == [dict(k) for k in fresh.keys]
    snap = rc.snapshot()
    assert snap["hits"] == 1 and snap["partial_hits"] == 1
    assert snap["misses"] == 1 and snap["cached_steps_served"] > 0


def test_head_and_tail_spans():
    rc = ResultCache(max_bytes=1 << 20)
    eng = _StubEngine(shards=[_Shard(10_000_000 * 1000)])
    _run(rc, eng, 1000, 1600)
    # widened both ways: head AND tail evaluate, middle comes cached
    res, ses = _run(rc, eng, 880, 1720)
    assert ses.state == "partial"
    assert eng.executed[-2:] == [
        (880 * 1000, 60 * 1000, 940 * 1000),
        (1660 * 1000, 60 * 1000, 1720 * 1000)]
    fresh = _StubExec(eng, _plan(880, 60, 1720)).execute()
    assert np.array_equal(res.values, fresh.values, equal_nan=True)


def test_step_alignment_is_part_of_the_key():
    rc = ResultCache(max_bytes=1 << 20)
    eng = _StubEngine(shards=[_Shard(10_000_000 * 1000)])
    _run(rc, eng, 1000, 1600)
    # same query/step, phase shifted by 30s: cached columns sit between
    # this grid's steps — must NOT be reused
    _, ses = _run(rc, eng, 1030, 1630)
    assert ses.state == "miss"


def test_hot_window_blocks_recent_steps():
    now_s = 2000.0
    rc = ResultCache(max_bytes=1 << 20, hot_window_ms=300_000,
                     clock=lambda: now_s)
    eng = _StubEngine(shards=[_Shard(10_000_000 * 1000)])
    # horizon = 2000s - 300s = 1700s: steps above 1700 never cache
    _run(rc, eng, 1000, 1900)
    _, ses = _run(rc, eng, 1000, 1900)
    assert ses.state == "partial"
    # the hot tail (1720..1900) re-evaluated despite the repeat
    assert eng.executed[-1] == (1720 * 1000, 60 * 1000, 1900 * 1000)


def test_watermark_caps_the_extent():
    rc = ResultCache(max_bytes=1 << 20)
    wm = 1300 * 1000
    eng = _StubEngine(shards=[_Shard(wm)])
    _run(rc, eng, 1000, 1600)
    _, ses = _run(rc, eng, 1000, 1600)
    # steps above the shard watermark may still receive samples: they
    # are recomputed every refresh, only the settled prefix comes from
    # cache. The raw 5-step tail (1360..1600) widens to the 8-step
    # pow2 bucket (1180..1600) so the device executor's shape set stays
    # tiny across slides — the overlap recomputes bit-identical values.
    assert ses.state == "partial"
    assert eng.executed[-1] == (1180 * 1000, 60 * 1000, 1600 * 1000)
    assert ses.cached_steps == 3            # 1000..1120
    assert ses.computed_steps == 8


def test_watermark_regression_invalidates():
    rc = ResultCache(max_bytes=1 << 20)
    sh = _Shard(2000 * 1000)
    eng = _StubEngine(shards=[sh])
    _run(rc, eng, 1000, 1600)
    assert len(rc) == 1
    sh.ingest_watermark_ms = 1200 * 1000    # stream replay / re-adoption
    _, ses = _run(rc, eng, 1000, 1600)
    assert ses.state == "miss"
    assert rc.snapshot()["watermark_invalidations"] == 1


def test_backfill_epoch_invalidates():
    """A new series entering a shard below its watermark (per-partition
    OOO guards can't stop it) bumps the shard's backfill epoch; extents
    recorded under the old epoch are dropped on lookup — the steps they
    hold as settled may now miss samples."""
    rc = ResultCache(max_bytes=1 << 20)
    sh = _Shard(10_000_000 * 1000)
    eng = _StubEngine(shards=[sh])
    _run(rc, eng, 1000, 1600)
    _, ses = _run(rc, eng, 1000, 1600)
    assert ses.state == "hit"
    sh.ingest_backfill_epoch += 1
    _, ses = _run(rc, eng, 1000, 1600)
    assert ses.state == "miss"
    assert rc.snapshot()["backfill_invalidations"] == 1
    # re-seeded under the new epoch: serves again
    _, ses = _run(rc, eng, 1000, 1600)
    assert ses.state == "hit"


def test_dispatch_scope_is_part_of_the_key():
    """A dispatch=local / gRPC local_only evaluation (the pushdown
    loop-prevention hop) sees only this node's shards; its extents and
    a full fan-out query's extents must never serve each other."""
    rc = ResultCache(max_bytes=1 << 20)
    fan = _StubEngine(shards=[_Shard(10_000_000 * 1000)])
    local = _StubEngine(shards=[_Shard(10_000_000 * 1000)])
    local.local_dispatch = True
    _run(rc, fan, 1000, 1600)
    _, ses = _run(rc, local, 1000, 1600)
    assert ses.state == "miss"              # fan-out extent not reused
    _, ses = _run(rc, local, 1000, 1600)
    assert ses.state == "hit"               # local scope serves itself
    _, ses = _run(rc, fan, 1000, 1600)
    assert ses.state == "hit"               # fan-out extent untouched
    assert len(rc) == 2


def test_watermark_appearing_invalidates():
    """An extent cached when NO shard had ingested (watermark None)
    must not survive a shard starting to ingest: its backfill may land
    below every cached step."""
    rc = ResultCache(max_bytes=1 << 20)
    eng = _StubEngine(shards=[])            # no local ingest yet
    _run(rc, eng, 1000, 1600)
    _, ses = _run(rc, eng, 1000, 1600)
    assert ses.state == "hit"               # hot window alone bounds it
    eng.shards = [_Shard(1200 * 1000)]      # ingest starts at old time
    _, ses = _run(rc, eng, 1000, 1600)
    assert ses.state == "miss"
    assert rc.snapshot()["watermark_invalidations"] == 1


def test_watermark_coverage_change_invalidates():
    """A never-ingested shard entering the watermark min-set at EXACTLY
    the old minimum moves neither the min nor any backfill epoch (an
    empty shard's first series has no watermark to land below) — yet
    every cached step may now miss its series. The coverage count makes
    that transition visible (PR 6: also the fan-out case via gossip-
    stamped remote groups)."""
    rc = ResultCache(max_bytes=1 << 20)
    lagging = _Shard(-1)                    # never ingested
    eng = _StubEngine(shards=[_Shard(1600 * 1000), lagging])
    _run(rc, eng, 1000, 1600)
    _, ses = _run(rc, eng, 1000, 1600)
    assert ses.state == "hit"
    # first series lands with last == the other shard's watermark
    lagging.ingest_watermark_ms = 1600 * 1000
    _, ses = _run(rc, eng, 1000, 1600)
    assert ses.state == "miss"
    assert rc.snapshot()["watermark_invalidations"] == 1
    _, ses = _run(rc, eng, 1000, 1600)
    assert ses.state == "hit"               # re-seeded, serves again


def test_series_churn_computes_through():
    rc = ResultCache(max_bytes=1 << 20)
    eng = _StubEngine(n_series=1, shards=[_Shard(10_000_000 * 1000)])
    _run(rc, eng, 1000, 1600)
    eng.n_series = 2                        # a new series appears
    res, ses = _run(rc, eng, 1120, 1720)
    assert ses.state == "churn"
    # the full range re-evaluated (not just the tail)
    assert eng.executed[-1] == (1120 * 1000, 60 * 1000, 1720 * 1000)
    assert res.num_series == 2
    # the re-seeded extent serves the new world
    _, ses2 = _run(rc, eng, 1120, 1720)
    assert ses2.state == "hit"


def test_vanished_series_keeps_nan_tail():
    rc = ResultCache(max_bytes=1 << 20)
    eng = _StubEngine(n_series=2, shards=[_Shard(10_000_000 * 1000)])
    _run(rc, eng, 1000, 1600)
    eng.n_series = 0                        # series stop reporting
    res, ses = _run(rc, eng, 1120, 1720)
    assert ses.state == "partial"
    assert res.num_series == 2
    # cached steps keep their values; the tail is stale-NaN
    tail = res.values[:, -2:]
    assert np.isnan(tail).all()


def test_lru_byte_budget_eviction():
    rc = ResultCache(max_bytes=1200)
    eng = _StubEngine(n_series=1, shards=[_Shard(10_000_000 * 1000)])
    # each extent: 11 steps * 8B + key/entry overhead ~= 470B -> the
    # budget holds two; storing four must evict the oldest
    for i in range(4):
        _run(rc, eng, 1000, 1600, q=f"up + {i}")
    snap = rc.snapshot()
    assert snap["bytes"] <= 1200
    assert snap["evictions"] >= 1
    assert len(rc) < 4
    # oldest key evicted, newest resident
    _, ses = _run(rc, eng, 1000, 1600, q="up + 0")
    assert ses.state == "miss"
    _, ses = _run(rc, eng, 1000, 1600, q="up + 3")
    assert ses.state == "hit"


def test_bypass_neither_reads_nor_seeds():
    rc = ResultCache(max_bytes=1 << 20)
    eng = _StubEngine(shards=[_Shard(10_000_000 * 1000)])
    _, ses = _run(rc, eng, 1000, 1600, bypass=True)
    assert ses.state == "bypass" and len(rc) == 0
    _run(rc, eng, 1000, 1600)               # seed
    _, ses = _run(rc, eng, 1000, 1600, bypass=True)
    assert ses.state == "bypass"
    assert eng.executed[-1] == (1000 * 1000, 60 * 1000, 1600 * 1000)
    assert rc.snapshot()["bypassed"] == 2


def test_degraded_results_never_admitted():
    rc = ResultCache(max_bytes=1 << 20)
    eng = _StubEngine(shards=[_Shard(10_000_000 * 1000)], partial=True)
    _, ses = _run(rc, eng, 1000, 1600)
    assert ses.state == "miss" and len(rc) == 0
    assert rc.snapshot()["degraded_skips"] == 1
    # engine-stats warnings (dropped shard group) also block admission
    eng2 = _StubEngine(shards=[_Shard(10_000_000 * 1000)])
    eng2.stats.warnings.append("partial result: node1 unavailable")
    _, _ = _run(rc, eng2, 1000, 1600)
    assert len(rc) == 0
    assert rc.snapshot()["degraded_skips"] == 2
    # a degraded TAIL stitches (response flagged) but must not roll the
    # extent forward
    eng3 = _StubEngine(shards=[_Shard(10_000_000 * 1000)])
    _run(rc, eng3, 1000, 1600)
    stores0 = rc.snapshot()["stores"]
    eng3.partial = True
    res, ses = _run(rc, eng3, 1120, 1720)
    assert ses.state == "partial" and res.partial
    assert rc.snapshot()["stores"] == stores0


def test_uncacheable_shapes():
    rc = ResultCache(max_bytes=1 << 20)
    assert not result_cacheable(_plan(
        1000, 60, 1600, "rate(up[5m] @ 1500)"))
    assert not result_cacheable(_plan(
        1000, 60, 1600, "max_over_time(rate(up[1m])[10m:1m])"))
    # sort()/limit order by range, not per step: extents can't reuse
    assert not result_cacheable(_plan(1000, 60, 1600, "sort(up)"))
    assert result_cacheable(_plan(1000, 60, 1600,
                                  "sum(rate(up[5m])) by (instance)"))
    eng = _StubEngine(shards=[_Shard(10_000_000 * 1000)])
    _, ses = _run(rc, eng, 1000, 1600, q="sort(up)")
    assert ses.state == "uncacheable" and len(rc) == 0


def test_watermark_helper_ignores_empty_shards():
    assert shards_watermark([]) is None
    assert shards_watermark([object()]) is None
    assert shards_watermark([_Shard(-1)]) is None
    assert shards_watermark([_Shard(5000), _Shard(-1)]) == 5000
    assert shards_watermark([_Shard(5000), _Shard(3000)]) == 3000


def test_exec_request_no_cache_roundtrip():
    buf = wire.encode_exec_request("ds", "up", 1000, 60, 2000,
                                   no_cache=True)
    assert wire.decode_exec_request(buf)["no_cache"] is True
    buf = wire.encode_exec_request("ds", "up", 1000, 60, 2000)
    assert wire.decode_exec_request(buf)["no_cache"] is False


# ---------------------------------------------------------------------------
# server layer: end-to-end over the HTTP edge
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def servers():
    cached = FiloServer({"num-shards": 4, "port": 0}).start()
    cached.seed_dev_data(n_samples=360, n_instances=4,
                         start_ms=T0 * 1000)
    plain = FiloServer({"num-shards": 4, "port": 0,
                        "results-cache-mb": 0}).start()
    plain.seed_dev_data(n_samples=360, n_instances=4,
                        start_ms=T0 * 1000)
    yield cached, plain
    cached.stop()
    plain.stop()


def _get_json(server, path="/promql/timeseries/api/v1/query_range",
              **params):
    qs = urllib.parse.urlencode(params, doseq=True)
    url = f"http://127.0.0.1:{server.port}{path}?{qs}"
    with urllib.request.urlopen(url, timeout=60) as r:
        return r.status, json.loads(r.read())


QUERIES = [
    "rate(http_requests_total[5m])",
    "sum(rate(http_requests_total[5m])) by (instance)",
    "avg_over_time(heap_usage[10m])",
    "max(heap_usage) by (instance)",
]


def test_cache_on_vs_cache_off_byte_identical(servers):
    """Fresh computes (first sight of each text) and stitched re-issues
    alike must match the cache-disabled server exactly — the response
    DATA is compared verbatim (exact float strings), only the wall-clock
    timings block and scan stats legitimately differ."""
    cached, plain = servers
    dispositions = []
    for q in QUERIES:
        for k in range(4):          # sliding window per text
            start = T0 + 600 + k * 60
            end = start + 900
            _, jc = _get_json(cached, query=q, start=start, end=end,
                              step=60)
            _, jp = _get_json(plain, query=q, start=start, end=end,
                              step=60)
            dispositions.append(
                jc["stats"]["timings"]["resultCache"])
            assert jp["stats"]["timings"]["resultCache"] == "off"
            assert jc["data"] == jp["data"], (q, start, end)
    assert "miss" in dispositions and "partial" in dispositions


def test_full_hit_serves_without_selection(servers):
    cached, _ = servers
    q = QUERIES[1]
    start, end = T0 + 600, T0 + 1500
    _get_json(cached, query=q, start=start, end=end, step=60)
    _, body = _get_json(cached, query=q, start=start, end=end, step=60)
    assert body["stats"]["timings"]["resultCache"] == "hit"
    assert body["stats"]["timings"]["plan"] == "ResultCacheHit"
    # nothing was selected/scanned for a full hit
    assert body["stats"]["seriesScanned"] == 0
    assert body["stats"]["samplesScanned"] == 0


def test_cache_false_escape_hatch(servers):
    cached, plain = servers
    q = QUERIES[0]
    start, end = T0 + 700, T0 + 1600
    _get_json(cached, query=q, start=start, end=end, step=60)
    snap0 = cached.http.result_cache.snapshot()
    _, body = _get_json(cached, query=q, start=start, end=end, step=60,
                        cache="false")
    assert body["stats"]["timings"]["resultCache"] == "bypass"
    snap1 = cached.http.result_cache.snapshot()
    assert snap1["bypassed"] == snap0["bypassed"] + 1
    assert snap1["stores"] == snap0["stores"]
    # bypassed response still exactly matches the cache-off server
    _, jp = _get_json(plain, query=q, start=start, end=end, step=60,
                      cache="false")
    assert body["data"] == jp["data"]


def test_instant_queries_skip_the_cache(servers):
    cached, _ = servers
    snap0 = cached.http.result_cache.snapshot()
    _get_json(cached, path="/promql/timeseries/api/v1/query",
              query="max(heap_usage) by (instance)", time=T0 + 900)
    snap1 = cached.http.result_cache.snapshot()
    assert snap1["stores"] == snap0["stores"]


def test_metrics_exposition_has_cache_families(servers):
    cached, _ = servers
    url = f"http://127.0.0.1:{cached.port}/metrics"
    with urllib.request.urlopen(url, timeout=30) as r:
        body = r.read().decode()
    for fam in ("filodb_result_cache_hits_total",
                "filodb_result_cache_partial_hits_total",
                "filodb_result_cache_bytes",
                "filodb_result_cache_cached_steps_served_total",
                "filodb_result_cache_backfill_invalidations_total",
                "filodb_decode_cache_bytes",
                "filodb_ingest_watermark_ms",
                "filodb_resultcache_cached_steps_bucket"):
        assert fam in body, fam


def test_explain_trace_carries_disposition(servers):
    cached, _ = servers
    q = QUERIES[2]
    start, end = T0 + 600, T0 + 1500
    _get_json(cached, query=q, start=start, end=end, step=60)
    _, body = _get_json(cached, query=q, start=start, end=end, step=60,
                        explain="trace")
    spans = body["trace"]["spans"]
    ex = [s for s in spans if s["name"] == "execute"]
    assert ex and ex[0]["tags"]["result_cache"] in ("hit", "partial")
    assert "cached_steps" in ex[0]["tags"]


# -- freshness: new samples appear despite the cache ----------------------

@pytest.fixture
def fresh_srv():
    srv = FiloServer({"num-shards": 4, "port": 0}).start()
    srv.seed_dev_data(n_samples=60, n_instances=4, start_ms=T0 * 1000)
    yield srv
    srv.stop()


def _ingest_gauge(srv, metric, instance, t_lo, t_hi, value):
    b = RecordBuilder(DEFAULT_SCHEMAS)
    for t in range(t_lo, t_hi):
        b.add_sample("gauge", {"_metric_": metric, "instance": instance},
                     (T0 + t * 10) * 1000, float(value))
    for c in b.containers():
        srv.store.ingest(srv.ref, 0, c)


def test_ingest_watermark_freshness(fresh_srv):
    """Steps above the watermark are recomputed every refresh: data
    ingested between two identical queries shows up in the second —
    the cached prefix never masks it."""
    srv = fresh_srv
    _ingest_gauge(srv, "fresh_gauge", "i0", 0, 60, 1.0)
    q = "avg_over_time(fresh_gauge[5m])"
    start, end = T0 + 300, T0 + 900         # data ends at T0+590
    _, first = _get_json(srv, query=q, start=start, end=end, step=60)
    assert first["stats"]["timings"]["resultCache"] == "miss"
    _, again = _get_json(srv, query=q, start=start, end=end, step=60)
    assert again["stats"]["timings"]["resultCache"] == "partial"
    assert again["data"] == first["data"]
    # new samples (a different level) land beyond the old watermark:
    # the averages at steps above T0+590 must move
    _ingest_gauge(srv, "fresh_gauge", "i0", 60, 90, 5.0)
    _, after = _get_json(srv, query=q, start=start, end=end, step=60)
    assert after["data"] != first["data"]
    # golden: exactly what a cache-bypassing fresh compute sees
    _, golden = _get_json(srv, query=q, start=start, end=end, step=60,
                          cache="false")
    assert after["data"] == golden["data"]


def test_server_watermark_regression_invalidates(fresh_srv):
    srv = fresh_srv
    q = "rate(http_requests_total[5m])"
    start, end = T0 + 300, T0 + 580
    _get_json(srv, query=q, start=start, end=end, step=60)
    _, hit = _get_json(srv, query=q, start=start, end=end, step=60)
    assert hit["stats"]["timings"]["resultCache"] == "hit"
    # a replaying/re-adopted shard reports a LOWER watermark
    shard = srv.store.shards(srv.ref)[0]
    shard.ingest_watermark_ms = (T0 + 100) * 1000
    _, body = _get_json(srv, query=q, start=start, end=end, step=60)
    assert body["stats"]["timings"]["resultCache"] == "miss"
    assert srv.http.result_cache.snapshot()[
        "watermark_invalidations"] >= 1


def test_server_series_churn_recomputes(fresh_srv):
    """A brand-new series landing inside the tail window forces a
    compute-through; the response equals a fresh full compute."""
    srv = fresh_srv
    q = "rate(reqs_total[5m])"
    start, end = T0 + 300, T0 + 900
    b = RecordBuilder(DEFAULT_SCHEMAS)
    for t in range(0, 60):
        b.add_sample("prom-counter", {"_metric_": "reqs_total",
                                      "instance": "i0"},
                     (T0 + t * 10) * 1000, float(t))
    for c in b.containers():
        srv.store.ingest(srv.ref, 0, c)
    _, first = _get_json(srv, query=q, start=start, end=end, step=60)
    assert first["stats"]["timings"]["resultCache"] == "miss"
    # second series appears ABOVE the watermark (T0+590 — no backfill
    # invalidation fires), samples inside the tail's lookback: the
    # stitch must notice the unknown series and compute through
    b2 = RecordBuilder(DEFAULT_SCHEMAS)
    for t in range(60, 70):
        b2.add_sample("prom-counter", {"_metric_": "reqs_total",
                                       "instance": "i1"},
                      (T0 + t * 10) * 1000, float(t))
    for c in b2.containers():
        srv.store.ingest(srv.ref, 0, c)
    _, after = _get_json(srv, query=q, start=start, end=end, step=60)
    assert after["stats"]["timings"]["resultCache"] in ("partial",
                                                        "churn")
    _, golden = _get_json(srv, query=q, start=start, end=end, step=60,
                          cache="false")
    assert after["data"] == golden["data"]
    metrics = {tuple(sorted(r["metric"].items()))
               for r in after["data"]["result"]}
    assert len(metrics) == 2
    assert srv.http.result_cache.snapshot()["churn_recomputes"] >= 1


def test_server_backfilled_series_invalidates(fresh_srv):
    """A new series whose rows land entirely BELOW the watermark and
    beyond the recomputed tail's lookback reach: churn stitching can
    never see it, so the shard-side watermark/backfill signal must drop
    the extent — the next query recomputes fresh and includes it."""
    srv = fresh_srv
    q = "rate(bf_total[1m])"
    start, end = T0 + 300, T0 + 900
    b = RecordBuilder(DEFAULT_SCHEMAS)
    for t in range(0, 60):
        b.add_sample("prom-counter", {"_metric_": "bf_total",
                                      "instance": "i0"},
                     (T0 + t * 10) * 1000, float(t))
    for c in b.containers():
        srv.store.ingest(srv.ref, 0, c)
    _, first = _get_json(srv, query=q, start=start, end=end, step=60)
    assert first["stats"]["timings"]["resultCache"] == "miss"
    _, again = _get_json(srv, query=q, start=start, end=end, step=60)
    assert again["stats"]["timings"]["resultCache"] == "partial"
    # i1 backfills T0+300..370 only: far below the watermark (T0+590)
    # and invisible to the recomputed tail (1m windows reach ~T0+420
    # after pow2 widening) — pre-invalidation this served stale cached
    # steps missing the series
    b2 = RecordBuilder(DEFAULT_SCHEMAS)
    for t in range(30, 38):
        b2.add_sample("prom-counter", {"_metric_": "bf_total",
                                       "instance": "i1"},
                      (T0 + t * 10) * 1000, float(t))
    for c in b2.containers():
        srv.store.ingest(srv.ref, 0, c)
    _, after = _get_json(srv, query=q, start=start, end=end, step=60)
    assert after["stats"]["timings"]["resultCache"] == "miss"
    _, golden = _get_json(srv, query=q, start=start, end=end, step=60,
                          cache="false")
    assert after["data"] == golden["data"]
    metrics = {tuple(sorted(r["metric"].items()))
               for r in after["data"]["result"]}
    assert len(metrics) == 2
    snap = srv.http.result_cache.snapshot()
    assert (snap["watermark_invalidations"]
            + snap["backfill_invalidations"]) >= 1


def test_topology_change_invalidates(fresh_srv):
    from filodb_tpu.parallel.shardmapper import ShardStatus
    srv = fresh_srv
    q = "avg_over_time(heap_usage[10m])"
    _get_json(srv, query=q, start=T0 + 300, end=T0 + 580, step=60)
    assert len(srv.http.result_cache) > 0
    srv.mapper.update(0, ShardStatus.DOWN, srv.node_id)
    assert len(srv.http.result_cache) == 0
    assert srv.http.result_cache.snapshot()["invalidations"] >= 1
    srv.mapper.update(0, ShardStatus.ACTIVE, srv.node_id)


# ---------------------------------------------------------------------------
# chaos: degraded/partial results are provably never cached
# ---------------------------------------------------------------------------

def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _rows(body):
    return {(tuple(sorted(r["metric"].items())), tuple(map(tuple,
             r["values"]))) for r in body["data"]["result"]}


def _ns_on_node(srv, metric, node):
    """A namespace whose spread-0 shard key prunes onto ``node``."""
    from filodb_tpu.core.record import shard_key_hash
    for i in range(256):
        ns = f"Ns-{i}"
        skh = shard_key_hash(["demo", ns], metric)
        shards = srv.mapper.query_shards(skh, 0)
        if {srv.mapper.node_of(s) for s in shards} == {node}:
            return ns
    raise AssertionError("no namespace hashes onto the target node")


def _seed_metric(srv, metric, ns):
    """Seed a gauge on the node owning its shards (gateway routing)."""
    from filodb_tpu.core.record import (RecordBuilder, RecordContainer,
                                        ingestion_shard)
    from filodb_tpu.core.schemas import PartitionSchema
    b = RecordBuilder(DEFAULT_SCHEMAS)
    for inst in range(3):
        labels = {"_metric_": metric, "_ws_": "demo", "_ns_": ns,
                  "instance": f"i{inst}"}
        for t in range(60):
            b.add_sample("gauge", labels, (T0 + t * 10) * 1000,
                         50.0 + inst + t * 0.1)
    part_schema = PartitionSchema()
    for cont in b.containers():
        by_shard = {}
        for row in cont.rows():
            sh = ingestion_shard(row.part_key.shard_key_hash(part_schema),
                                 row.part_key.part_hash(), 0, 4)
            by_shard.setdefault(sh, RecordContainer(cont.schema))
            by_shard[sh].add(row.part_key, row.timestamp, *row.values)
        for sh, c2 in by_shard.items():
            srv.store.get_shard(srv.ref, sh).ingest(c2)


def test_pushdown_local_scope_never_serves_fanout():
    """The pushdown hop (dispatch=local) evaluates only the target
    node's shards; the extent it caches must live under a different key
    than a direct fan-out query of the SAME text/step/phase on that
    node — otherwise the user query would be served a local-only
    (missing-series) result."""
    p0, p1 = _free_port(), _free_port()
    peers = {"node0": f"http://127.0.0.1:{p0}",
             "node1": f"http://127.0.0.1:{p1}"}
    base = {
        "num-shards": 4, "num-nodes": 2, "peers": peers,
        "default-spread": 0, "query-sample-limit": 0,
        "query-series-limit": 0, "failure-detect-interval-s": 300.0,
        "grpc-port": None, "query-timeout-s": 8.0,
    }
    a = FiloServer({**base, "node-ordinal": 0, "port": p0}).start()
    b = FiloServer({**base, "node-ordinal": 1, "port": p1}).start()
    try:
        ns0 = _ns_on_node(a, "xg", "node0")
        ns1 = _ns_on_node(a, "xg", "node1")
        _seed_metric(a, "xg", ns0)
        _seed_metric(b, "xg", ns1)
        # shard-aligned self-join spanning both nodes: the planner
        # pushes the WHOLE query to each owning node with
        # dispatch=local (loop prevention) — the reviewed
        # contamination path
        sel = f'xg{{_ws_="demo",_ns_=~"{ns0}|{ns1}"}}'
        q = f"({sel}) + ({sel})"
        args = dict(query=q, start=T0 + 300, end=T0 + 580, step=60)
        # a fans out; b evaluates its shards under dispatch=local and
        # caches the local-only extent on the way
        _, via_a = _get_json(a, **args)
        assert len(via_a["data"]["result"]) == 6    # both nodes' series
        assert b.http.result_cache.snapshot()["stores"] >= 1
        # the same text/step/phase as a DIRECT fan-out query on b must
        # NOT see that extent: it recomputes across both nodes and
        # returns the full series set
        _, via_b = _get_json(b, **args)
        assert via_b["stats"]["timings"]["resultCache"] != "hit"
        assert _rows(via_b) == _rows(via_a)
        # and the local hop keeps serving its own scope: a repeat from
        # a stitches/hits against b's local extent, unpolluted by b's
        # fan-out extent
        _, again = _get_json(a, **args)
        assert _rows(again) == _rows(via_a)
    finally:
        for srv in (a, b):
            try:
                srv.stop()
            except Exception:
                pass


def test_chaos_degraded_results_never_cached():
    """Injected peer failure -> allow_partial response -> the next
    un-degraded query must not see cached degraded steps (it recomputes
    and returns the FULL series set)."""
    p0, p1 = _free_port(), _free_port()
    peers = {"node0": f"http://127.0.0.1:{p0}",
             "node1": f"http://127.0.0.1:{p1}"}
    base = {
        "num-shards": 4, "num-nodes": 2, "peers": peers,
        "query-sample-limit": 0, "query-series-limit": 0,
        "failure-detect-interval-s": 300.0,
        "grpc-port": None, "query-timeout-s": 8.0,
        "peer-retry-attempts": 1, "peer-retry-base-delay-s": 0.01,
        "breaker-failure-threshold": 100,
    }
    a = FiloServer({**base, "node-ordinal": 0, "port": p0}).start()
    a.seed_dev_data(n_samples=60, n_instances=4, start_ms=T0 * 1000)
    b = FiloServer({**base, "node-ordinal": 1, "port": p1}).start()
    b.seed_dev_data(n_samples=60, n_instances=4, start_ms=T0 * 1000)
    try:
        q = ('rate({_metric_=~"heap_usage|http_requests_total"}[5m])')
        args = dict(query=q, start=T0 + 300, end=T0 + 580, step=60)
        inj = chaos.ChaosInjector()
        inj.fail("http.peer", match=lambda c: c.get("node") == "node1")
        with inj:
            _, degraded = _get_json(a, allow_partial="true", **args)
        assert degraded.get("partial") is True
        rc = a.http.result_cache.snapshot()
        assert rc["degraded_skips"] >= 1
        assert rc["stores"] == 0 and rc["entries"] == 0
        deg_series = {tuple(sorted(r["metric"].items()))
                      for r in degraded["data"]["result"]}
        # chaos healed: the SAME query must recompute (nothing cached)
        # and see the full series set again
        _, healed = _get_json(a, **args)
        assert healed["stats"]["timings"]["resultCache"] == "miss"
        assert "partial" not in healed
        full_series = {tuple(sorted(r["metric"].items()))
                       for r in healed["data"]["result"]}
        assert deg_series < full_series
        # ...and only the clean result was admitted
        _, hit = _get_json(a, **args)
        assert hit["stats"]["timings"]["resultCache"] == "hit"
        assert {tuple(sorted(r["metric"].items()))
                for r in hit["data"]["result"]} == full_series
    finally:
        chaos.uninstall()
        for srv in (a, b):
            try:
                srv.stop()
            except Exception:
                pass
