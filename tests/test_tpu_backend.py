"""TPU/JAX backend parity tests: every device function must match the numpy
oracle (runs on the 8-device virtual CPU mesh; the same code path runs on
real TPU)."""

import numpy as np
import pytest

from filodb_tpu.query import rangefn as rf
from filodb_tpu.query.model import RangeParams, RawSeries
from filodb_tpu.query.tpu import DEVICE_FUNCS, TpuBackend, pack_series


def make_series(n_series=5, n_samples=300, seed=0, counter=False,
                with_nans=False, irregular=False):
    rng = np.random.default_rng(seed)
    out = []
    for s in range(n_series):
        if irregular:
            dts = rng.integers(5_000, 15_000, n_samples)
        else:
            dts = np.full(n_samples, 10_000)
        ts = 1_600_000_000_000 + np.cumsum(dts).astype(np.int64)
        if counter:
            vals = np.cumsum(rng.uniform(0, 100, n_samples))
            # inject resets
            if s % 2 == 1:
                vals[n_samples // 2 :] -= vals[n_samples // 2] * 0.9
        else:
            vals = rng.normal(100, 25, n_samples)
        if with_nans:
            vals = vals.copy()
            vals[rng.integers(0, n_samples, n_samples // 20)] = np.nan
        out.append(RawSeries({"instance": f"i{s}"}, ts,
                             np.asarray(vals, dtype=np.float64),
                             is_counter=counter))
    return out


PARAMS = RangeParams(1_600_001_000_000, 60_000, 1_600_003_000_000)
WINDOW = 300_000

ALL_FUNCS = sorted(DEVICE_FUNCS - {"last_over_time"})


@pytest.mark.parametrize("func", ALL_FUNCS)
def test_device_matches_oracle(func):
    counter = func in ("rate", "increase", "irate", "resets")
    series = make_series(counter=counter, with_nans=True, irregular=True)
    args = (0.9,) if func == "quantile_over_time" else ()
    backend = TpuBackend()
    from filodb_tpu.query.engine import periodic_samples
    oracle = periodic_samples(series, PARAMS, func, WINDOW, args)
    got = backend.periodic_samples(series, PARAMS, func, WINDOW, args)
    assert got is not None, f"{func} fell back to oracle"
    assert got.values.shape == oracle.values.shape
    np.testing.assert_allclose(got.values, oracle.values, rtol=1e-9,
                               atol=1e-9, equal_nan=True,
                               err_msg=f"mismatch for {func}")


def test_pack_series_drops_nans():
    series = make_series(n_series=2, with_nans=True)
    ts, vals, lens = pack_series(series)
    assert ts.shape == vals.shape
    assert not np.isnan(vals[0, : lens[0]]).any()
    # padded tail has sentinel timestamps
    if lens[0] < ts.shape[1]:
        assert ts[0, lens[0]] > 1 << 59


def test_offset_parity():
    series = make_series(counter=True)
    backend = TpuBackend()
    from filodb_tpu.query.engine import periodic_samples
    oracle = periodic_samples(series, PARAMS, "rate", WINDOW, (),
                              offset_ms=600_000)
    got = backend.periodic_samples(series, PARAMS, "rate", WINDOW, (),
                                   offset_ms=600_000)
    # rate rides the tilestore f32-hybrid path (exact delta, f32
    # extrapolation factor): ~3e-7 relative vs the f64 oracle
    np.testing.assert_allclose(got.values, oracle.values, rtol=1e-5,
                               equal_nan=True)


def test_histograms_fall_back():
    s = RawSeries({"a": "b"}, np.array([1000], dtype=np.int64),
                  np.ones((1, 4)), bucket_les=np.array([1.0, 2, 4, np.inf]))
    backend = TpuBackend()
    assert backend.periodic_samples([s], PARAMS, "rate", WINDOW) is None


def test_engine_with_tpu_backend_e2e():
    """QueryEngine wired with the TPU backend produces oracle-equal results."""
    from filodb_tpu.core.memstore import TimeSeriesShard
    from filodb_tpu.core.record import RecordBuilder
    from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetRef
    from filodb_tpu.promql.parser import TimeStepParams, parse_query_range
    from filodb_tpu.query.engine import QueryEngine

    shard = TimeSeriesShard(DatasetRef("ts"), DEFAULT_SCHEMAS, 0)
    b = RecordBuilder(DEFAULT_SCHEMAS)
    t0 = 1_600_000_000
    for s in range(6):
        labels = {"_metric_": "reqs_total", "_ws_": "w", "_ns_": "n",
                  "instance": f"i{s}"}
        v = 0.0
        for t in range(360):
            v += 7.0 * (s + 1)
            b.add_sample("prom-counter", labels, (t0 + t * 10) * 1000, v)
    for c in b.containers():
        shard.ingest(c)
    shard.flush_all()

    plan = parse_query_range("sum(rate(reqs_total[5m]))",
                             TimeStepParams(t0 + 600, 60, t0 + 3000))
    oracle_res = QueryEngine([shard]).execute(plan)
    backend = TpuBackend()
    tpu_res = QueryEngine([shard], backend=backend).execute(plan)
    # rate rides the tilestore f32-hybrid path: ~3e-7 relative vs oracle
    np.testing.assert_allclose(tpu_res.values, oracle_res.values, rtol=1e-5,
                               equal_nan=True)
    # steady increase of 7*(s+1) per 10s across 6 series
    expected = sum(0.7 * (s + 1) for s in range(6))
    np.testing.assert_allclose(tpu_res.values[0], expected, rtol=1e-5)
    # the whole sum(rate(...)) ran inside the fused Pallas group-sum
    # kernel — no [S, T] per-series intermediate
    assert backend.fused_aggs == 1

    # grouped + avg/count variants ride the same fused path
    for q in ("sum(rate(reqs_total[5m])) by (instance)",
              "avg(rate(reqs_total[5m]))",
              "count(rate(reqs_total[5m]))"):
        plan = parse_query_range(q, TimeStepParams(t0 + 600, 60, t0 + 3000))
        want = QueryEngine([shard]).execute(plan)
        got = QueryEngine([shard], backend=backend).execute(plan)
        assert [dict(k) for k in got.keys] == [dict(k) for k in want.keys]
        np.testing.assert_allclose(got.values, want.values, rtol=1e-5,
                                   equal_nan=True)
    assert backend.fused_aggs == 4
