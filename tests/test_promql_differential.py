"""Type-driven differential correctness rail (promlint tentpole).

Seeded well-typed queries (filodb_tpu.promql.gen) run through the REAL
engine — oracle path and the results-cache path (cache on + off, cold
and warm) — and through the deliberately slow pure-Python reference
evaluator (filodb_tpu.promql.refeval). Any numeric/keyset discrepancy
fails with the (seed, index, query) triple so it can be pinned.

The two pinned tests at the bottom are REAL discrepancies this rail
found during development; both were engine bugs and stay as named
regression tests.
"""

import math
import random

import numpy as np
import pytest

from filodb_tpu.core.memstore import TimeSeriesShard
from filodb_tpu.core.record import RecordBuilder
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetRef
from filodb_tpu.promql.gen import QueryGen
from filodb_tpu.promql.parser import TimeStepParams, parse_query_range
from filodb_tpu.promql.refeval import RefEvalError, RefSeries, ref_eval
from filodb_tpu.query.engine import QueryEngine
from filodb_tpu.query.model import GridResult, ScalarResult
from filodb_tpu.query.planner import QueryPlanner
from filodb_tpu.query.resultcache import ResultCache

T0 = 1_600_000_000
START, STEP, END = T0 + 900, 60, T0 + 2100

SOAK_SEED = 0xD1FF
SOAK_N = 200            # acceptance floor: >= 200 generated queries


def _build():
    """One shard of irregular synthetic data mirrored into RefSeries:
    counters with gaps and one mid-stream reset, noisy gauges."""
    shard = TimeSeriesShard(DatasetRef("timeseries"), DEFAULT_SCHEMAS, 0)
    b = RecordBuilder(DEFAULT_SCHEMAS)
    rng = random.Random(7)
    ref = []
    for metric in ("http_requests_total", "errors_total"):
        for job in ("api", "web"):
            for inst in ("i0", "i1", "i2"):
                labels = {"_metric_": metric, "_ws_": "demo",
                          "_ns_": "App-0", "job": job, "instance": inst}
                v = 0.0
                ts, vals = [], []
                for k in range(240):
                    t = T0 + k * 10
                    if rng.random() < 0.05:
                        continue                    # scrape gap
                    v += rng.random() * 5
                    if metric == "errors_total" and inst == "i1" \
                            and k == 150:
                        v = rng.random()            # counter reset
                    b.add_sample("prom-counter", labels, t * 1000, v)
                    ts.append(t * 1000)
                    vals.append(v)
                ref.append(RefSeries(dict(labels), ts, vals))
    # classic-bucket histogram: cumulative bucket counters, monotone
    # across le per scrape (a complete set per job/instance)
    les = ("0.1", "0.5", "1", "2.5", "+Inf")
    for job in ("api", "web"):
        for inst in ("i0", "i1"):
            cum = [0.0] * len(les)
            series = {le: ([], []) for le in les}
            for k in range(240):
                t = T0 + k * 10
                if rng.random() < 0.05:
                    continue                    # whole-scrape gap
                incs = [rng.random() * 3 for _ in les]
                run = 0.0
                for bi, le in enumerate(les):
                    run += incs[bi]             # cumulative across le
                    cum[bi] += run
                    series[le][0].append(t * 1000)
                    series[le][1].append(cum[bi])
            for le in les:
                labels = {"_metric_": "http_request_duration_seconds_bucket",
                          "_ws_": "demo", "_ns_": "App-0", "job": job,
                          "instance": inst, "le": le}
                ts, vals = series[le]
                for t, v in zip(ts, vals):
                    b.add_sample("prom-counter", labels, t, v)
                ref.append(RefSeries(dict(labels), list(ts), list(vals)))
    for metric in ("cpu_usage", "queue_depth"):
        for job in (("api", "web") if metric == "cpu_usage"
                    else ("api",)):
            for inst in ("i0", "i1", "i2"):
                labels = {"_metric_": metric, "_ws_": "demo",
                          "_ns_": "App-0", "job": job, "instance": inst}
                ts, vals = [], []
                for k in range(240):
                    t = T0 + k * 10
                    if rng.random() < 0.05:
                        continue
                    v = 50 * math.sin(k / 17.0) + rng.random() * 10 - 5
                    b.add_sample("gauge", labels, t * 1000, v)
                    ts.append(t * 1000)
                    vals.append(v)
                ref.append(RefSeries(dict(labels), ts, vals))
    for c in b.containers():
        shard.ingest(c)
    shard.flush_all()
    return shard, ref


@pytest.fixture(scope="module")
def world():
    return _build()


def _canon(res):
    if isinstance(res, ScalarResult):
        return {(): list(res.values)}
    assert isinstance(res, GridResult), type(res)
    out = {}
    for i, k in enumerate(res.keys):
        key = tuple(sorted(k.items()))
        assert key not in out, f"duplicate engine key {key}"
        out[key] = list(res.values[i])
    return out


def _close(a, b):
    if math.isnan(a) and math.isnan(b):
        return True
    if math.isinf(a) or math.isinf(b):
        return a == b
    return abs(a - b) <= 1e-6 + 1e-6 * max(abs(a), abs(b))


def _compare(tag, q, eng, rf):
    assert set(eng) == set(rf), (
        f"{tag}: series keysets differ for {q!r}:\n"
        f"  engine only: {sorted(set(eng) - set(rf))[:3]}\n"
        f"  ref only:    {sorted(set(rf) - set(eng))[:3]}")
    for k in eng:
        for j, (a, b) in enumerate(zip(eng[k], rf[k])):
            assert _close(a, b), (
                f"{tag}: {q!r} diverges at series {k} step {j}: "
                f"engine={a!r} reference={b!r}")


def test_differential_soak_engine(world):
    """>= 200 seeded well-typed queries: engine oracle vs reference,
    zero discrepancies (the tier-1 acceptance soak)."""
    shard, ref = world
    g = QueryGen(seed=SOAK_SEED)
    for i in range(SOAK_N):
        q = g.query()
        plan = parse_query_range(q, TimeStepParams(START, STEP, END))
        eng = _canon(QueryEngine([shard]).execute(plan))
        rf = ref_eval(q, ref, START, STEP, END)
        _compare(f"soak[{i}]", q, eng, rf)


def _through_cache(shard, cache, q):
    """One range evaluation through the results-cache split path (the
    HTTP edge's plan -> begin -> materialize -> finish pipeline)."""
    planner = QueryPlanner([shard])
    plan = parse_query_range(q, TimeStepParams(START, STEP, END))
    ses = cache.begin(planner, "timeseries", q, plan, START * 1000,
                      STEP * 1000, END * 1000)
    exs = [planner.materialize(p) for p in ses.plans]
    return _canon(ses.finish(planner, [ex.execute() for ex in exs]))


def test_differential_soak_result_cache(world):
    """The same differential property through the results cache: cold
    store, then a warm re-issue served from the cached extent — both
    must match the reference bit-for-bit (the cache path must never
    change an answer)."""
    shard, ref = world
    cache = ResultCache(max_bytes=32 << 20)
    g = QueryGen(seed=SOAK_SEED + 1)
    served = 0
    for i in range(40):
        q = g.query()
        rf = ref_eval(q, ref, START, STEP, END)
        cold = _through_cache(shard, cache, q)
        warm = _through_cache(shard, cache, q)
        _compare(f"cache-cold[{i}]", q, cold, rf)
        _compare(f"cache-warm[{i}]", q, warm, rf)
    served = cache.cached_steps_served
    assert cache.hits > 0 and served > 0, (
        "the warm pass never hit the results cache — the soak "
        "stopped exercising the cache path", cache.hits, served)


def test_differential_refeval_rejects_out_of_scope(world):
    """The reference evaluator fails LOUDLY outside its scope instead
    of silently passing a vacuous comparison. (topk moved INTO scope
    with the v4 widening — quantile() remains out.)"""
    _shard, ref = world
    with pytest.raises(RefEvalError):
        ref_eval("quantile(0.9, cpu_usage)", ref, START, STEP, END)


# ---------------------------------------------------------------------------
# pinned discrepancies — real engine bugs the rail found in development
# ---------------------------------------------------------------------------

def test_pinned_scalar_lhs_comparison_filter(world):
    """PINNED (found by the differential rail): a filtering comparison
    with the scalar on the LEFT (`0.25 <= queue_depth`) returned the
    broadcast scalar instead of the vector's sample values. Prometheus
    semantics: a filter comparison always yields the vector side."""
    shard, ref = world
    q = '0.25 <= queue_depth{job="api",instance="i0"}'
    plan = parse_query_range(q, TimeStepParams(START, STEP, END))
    eng = _canon(QueryEngine([shard]).execute(plan))
    rf = ref_eval(q, ref, START, STEP, END)
    _compare("pinned-scalar-lhs", q, eng, rf)
    # and explicitly: every retained sample is a real gauge value from
    # the selector, never the 0.25 literal
    (vals,) = eng.values()
    finite = [v for v in vals if not math.isnan(v)]
    assert finite, "filter retained nothing — fixture drifted"
    assert all(v >= 0.25 and v != 0.25 for v in finite)


def test_pinned_nested_subquery_rebase(world):
    """PINNED (found by the differential rail): lp_replace_range did
    not rebase SubqueryWithWindowing, so a NESTED subquery kept its
    parse-time grid and the enclosing subquery windowed over a
    truncated inner range (first steps systematically wrong)."""
    shard, ref = world
    q = ('avg_over_time(last_over_time('
         'http_requests_total{job="web",instance="i0"}[10m:])[6m:30s])')
    plan = parse_query_range(q, TimeStepParams(START, STEP, END))
    eng = _canon(QueryEngine([shard]).execute(plan))
    rf = ref_eval(q, ref, START, STEP, END)
    _compare("pinned-nested-subquery", q, eng, rf)


def test_pinned_rebase_subquery_node_directly():
    """The unit-level shape of the nested-subquery fix: rebasing a
    SubqueryWithWindowing rewrites its outer grid."""
    from filodb_tpu.query import logical as lp
    from filodb_tpu.query.engine import lp_replace_range
    raw = lp.RawSeriesPlan((), 0, 1000)
    sub = lp.SubqueryWithWindowing(
        lp.PeriodicSeries(raw, 0, 60_000, 1_000_000), "avg_over_time",
        600_000, 60_000, 0, 60_000, 1_000_000)
    moved = lp_replace_range(sub, 500_000, 30_000, 2_000_000)
    assert (moved.start_ms, moved.step_ms, moved.end_ms) == \
        (500_000, 30_000, 2_000_000)
    assert moved.window_ms == 600_000 and moved.function == \
        "avg_over_time"


# ---------------------------------------------------------------------------
# v4 widening: histogram_quantile, grouped joins, topk (ROADMAP 5
# remainder) — the shapes that exercise float-compare and partial-sort
# determinism the graftlint v4 numerics families reason about
# ---------------------------------------------------------------------------

def test_soak_stream_covers_new_shapes():
    """The seeded soak stream actually exercises the widened surface —
    the coverage is not vacuous."""
    g = QueryGen(seed=SOAK_SEED)
    qs = g.queries(SOAK_N)
    assert any("histogram_quantile" in q for q in qs)
    assert any("topk(" in q or "bottomk(" in q for q in qs)
    assert any("group_left" in q or "group_right" in q for q in qs)


def _one(world, q):
    shard, ref = world
    plan = parse_query_range(q, TimeStepParams(START, STEP, END))
    eng = _canon(QueryEngine([shard]).execute(plan))
    rf = ref_eval(q, ref, START, STEP, END)
    _compare("pinned", q, eng, rf)
    return eng


def test_pinned_histogram_quantile_bucket_join(world):
    """Classic-bucket histogram_quantile: the le-series join, running-
    max monotonicity, and bucket interpolation agree engine-vs-
    reference (including through a by-(le,job) re-aggregation)."""
    eng = _one(world, 'histogram_quantile(0.9, '
               'rate(http_request_duration_seconds_bucket[5m]))')
    assert eng, "no histogram groups came back"
    assert all("le" not in dict(k) for k in eng)
    finite = [v for row in eng.values() for v in row
              if not math.isnan(v)]
    assert finite and all(0 <= v <= 2.5 for v in finite)
    _one(world, 'histogram_quantile(0.5, sum by (le,job) '
         '(rate(http_request_duration_seconds_bucket[2m])))')


def test_pinned_grouped_join(world):
    """group_left/group_right many-to-one joins: original operand
    sides, many-side labels, duplicate-one-side detection."""
    eng = _one(world, '(rate(errors_total[5m]) / on (job) group_left '
               'sum by (job) (rate(http_requests_total[5m])))')
    # many side labels survive (job AND instance)
    assert all("instance" in dict(k) for k in eng)
    _one(world, '(sum by (instance) (rate(errors_total[5m])) * '
         'on (instance) group_right rate(http_requests_total[5m]))')


def test_pinned_topk(world):
    """topk/bottomk: per-step partial-sort selection keeps member
    series with NaN at unselected steps, identically on both arms."""
    eng = _one(world, 'topk(2, rate(http_requests_total[5m]))')
    # per step at most 2 non-NaN values across all series
    rows = list(eng.values())
    for t in range(len(rows[0])):
        live = sum(1 for r in rows if not math.isnan(r[t]))
        assert live <= 2
    _one(world, 'bottomk(1, avg_over_time(cpu_usage[5m]))')
