"""Chaos scenario for the process-sharded serving tier: kill -9 one
query worker mid-load — ZERO failed queries.

The supervisor is the first responder: it waitpid's the corpse within a
monitor tick, broadcasts ``worker-exit`` on the bus (siblings
immediately drop the dead worker's gossiped watermarks and data-plane
channel), and respawns the worker with the identical config — same
ordinal, same ports. Sibling routing therefore does NOT rewire: peer
calls targeting the dead worker's shards ride their retry budget
through the restart window (the chaos config widens retries and holds
the breaker closed, the documented operator recipe for supervised
single-host fleets where "peer down" means "restarting right here").

The load client speaks through the shared PUBLIC port like a real LB
client: a connection severed by the kill is reconnected and the
request reissued (query_range is idempotent); an HTTP error or a
partial/deviating response counts as a FAILED query. After recovery
the responses must be byte-identical to the pre-kill golden."""

import json
import os
import pathlib
import select
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.parse
import urllib.request

from filodb_tpu.lint.threads import thread_root

REPO = pathlib.Path(__file__).resolve().parent.parent
T0 = 1_600_000_000
N_SAMPLES = 50
N_INSTANCES = 4
NUM_SHARDS = 4

_QUERY = dict(query='rate({_metric_=~"heap_usage|http_requests_total"}'
                    '[5m])',
              start=T0 + 300, end=T0 + (N_SAMPLES - 1) * 10, step=60,
              timeout="90s")


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _write_corpus(stream_dir):
    from filodb_tpu.core.schemas import DEFAULT_SCHEMAS
    from filodb_tpu.gateway.producer import TestTimeseriesProducer
    from filodb_tpu.ingest import LogIngestionStream
    prod = TestTimeseriesProducer(DEFAULT_SCHEMAS,
                                  num_shards=NUM_SHARDS)
    for sh in range(NUM_SHARDS):
        path = os.path.join(stream_dir, f"shard={sh}", "stream.log")
        stream = LogIngestionStream(path, DEFAULT_SCHEMAS)
        for builders in (prod.gauges(T0 * 1000, N_SAMPLES,
                                     N_INSTANCES),
                         prod.counters(T0 * 1000, N_SAMPLES,
                                       N_INSTANCES)):
            for s, b in builders.items():
                if s == sh:
                    for c in b.containers():
                        stream.append(c)
        stream.close()


def _get(port, path, **params):
    qs = urllib.parse.urlencode(params, doseq=True)
    url = f"http://127.0.0.1:{port}{path}"
    if qs:
        url += "?" + qs
    with urllib.request.urlopen(url, timeout=120) as r:
        return r.status, r.read()


def _poll(fn, timeout=180.0, interval=0.2):
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        try:
            ok, last = fn()
            if ok:
                return last
        except (OSError, ValueError):
            pass
        time.sleep(interval)
    raise TimeoutError(f"poll timed out; last={last!r}")


def _data_bytes(raw: bytes) -> bytes:
    body, sep, _ = raw.partition(b',"stats":')
    assert sep, raw[:200]
    return body


class _LbClient:
    """A load-balancer-faithful client on the shared public port: one
    keep-alive connection; a connection severed mid-exchange (the
    victim worker died under it) reconnects — the kernel's reuseport
    balancing lands the fresh connection on a live worker — and
    reissues the idempotent GET. Only an HTTP-level error is a query
    failure."""

    def __init__(self, port):
        self.port = port
        self.sock = None
        self.buf = b""

    def _connect(self):
        self.sock = socket.create_connection(("127.0.0.1", self.port),
                                             timeout=120)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.buf = b""

    def get(self, path, **params):
        qs = urllib.parse.urlencode(params, doseq=True)
        req = (f"GET {path}?{qs} HTTP/1.1\r\nHost: x\r\n\r\n").encode()
        last_exc = None
        for _attempt in range(40):      # transport retries, not query
            try:
                if self.sock is None:
                    self._connect()
                self.sock.sendall(req)
                return self._read_response()
            except OSError as e:
                last_exc = e
                self.close()
                time.sleep(0.1)
        raise last_exc

    def _read_response(self):
        while b"\r\n\r\n" not in self.buf:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise OSError("closed mid-headers")
            self.buf += chunk
        head, self.buf = self.buf.split(b"\r\n\r\n", 1)
        clen = 0
        for ln in head.split(b"\r\n")[1:]:
            k, _, v = ln.partition(b":")
            if k.lower() == b"content-length":
                clen = int(v.strip())
                break
        while len(self.buf) < clen:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise OSError("closed mid-body")
            self.buf += chunk
        body, self.buf = self.buf[:clen], self.buf[clen:]
        status = int(head.split(b" ", 2)[1])
        return status, body

    def close(self):
        if self.sock is not None:
            try:
                self.sock.close()
            except OSError:
                pass
            self.sock = None
            self.buf = b""


class _QueryLoad(threading.Thread):
    def __init__(self, port, golden):
        super().__init__(daemon=True)
        self.client = _LbClient(port)
        self.golden = golden
        self.failures = []
        self.mismatches = []
        self.ok = 0
        self._halt = threading.Event()

    @thread_root("chaos-worker-load")
    def run(self):
        while not self._halt.is_set():
            try:
                status, body = self.client.get(
                    "/promql/timeseries/api/v1/query_range", **_QUERY)
            except OSError as e:
                self.failures.append(f"transport-exhausted: {e}")
                continue
            if status != 200:
                self.failures.append((status, body[:200]))
                continue
            parsed = json.loads(body)
            if parsed.get("status") != "success" \
                    or parsed.get("partial"):
                self.failures.append(
                    (parsed.get("errorType"),
                     parsed.get("error") or parsed.get("warnings")))
                continue
            if _data_bytes(body) != self.golden:
                self.mismatches.append(len(body))
                continue
            self.ok += 1
            self._halt.wait(0.05)

    def stop(self):
        self._halt.set()
        self.join(timeout=120)
        self.client.close()


def test_kill9_worker_mid_load_zero_failed_queries(tmp_path):
    _write_corpus(str(tmp_path / "streams"))
    cfg = {
        "num-shards": NUM_SHARDS, "port": _free_port(),
        "serving-workers": 2,
        "supervisor-port": 0,
        "run-dir": str(tmp_path / "run"),
        "data-dir": str(tmp_path / "data"),
        "stream-dir": str(tmp_path / "streams"),
        "flush-interval-s": 0.4,
        "max-chunks-size": 25,
        "query-sample-limit": 0, "query-series-limit": 0,
        "grpc-port": None,
        "monitor-interval-s": 0.1,
        "restart-backoff-s": 0.2,
        # the supervised-fleet overload recipe: a dead sibling is
        # "restarting right here", so peer calls out-wait the restart
        # window instead of failing fast — wide retry budget, breaker
        # held closed, detector never flips shards DOWN (a DOWN flip
        # would surface partial results, which this scenario forbids)
        "query-timeout-s": 120.0,
        "peer-retry-attempts": 25,
        "peer-retry-base-delay-s": 0.4,
        "breaker-failure-threshold": 1_000_000,
        "failure-detect-interval-s": 0.25,
        "failure-detect-threshold": 1_000_000,
        "max-inflight-queries": 8,
    }
    cfg_path = tmp_path / "sup.json"
    cfg_path.write_text(json.dumps(cfg))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen(
        [sys.executable, "-m", "filodb_tpu.standalone.supervisor",
         "--config", str(cfg_path)],
        cwd=str(REPO), env=env, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL)
    load = None
    try:
        buf = b""
        deadline = time.monotonic() + 240
        while time.monotonic() < deadline and b"\n" not in buf:
            r, _, _ = select.select([proc.stdout], [], [], 1.0)
            if r:
                ch = proc.stdout.read1(4096)
                if not ch:
                    raise RuntimeError("supervisor died during startup")
                buf += ch
        line = json.loads(buf.split(b"\n", 1)[0])
        pub, sup_port = line["port"], line["supervisor_port"]
        want = 2 * N_INSTANCES

        def _full():
            _, body = _get(pub, "/promql/timeseries/api/v1/query_range",
                           **{**_QUERY, "cache": "false"})
            parsed = json.loads(body)
            ok = (parsed.get("status") == "success"
                  and "partial" not in parsed
                  and len(parsed["data"]["result"]) >= want)
            return ok, len(parsed.get("data", {}).get("result", ()))
        _poll(_full)
        time.sleep(3.0)         # settle: corpus fully chunk-resident
        _, raw = _get(pub, "/promql/timeseries/api/v1/query_range",
                      **_QUERY)
        golden = _data_bytes(raw)

        load = _QueryLoad(pub, golden)
        load.start()
        time.sleep(1.5)
        assert load.ok > 0, (load.failures[:3], load.mismatches[:3])

        # -- kill -9 worker 1 mid-load ---------------------------------
        _, hb = _get(sup_port, "/__health")
        health = json.loads(hb)
        victim_pid = health["workers"]["1"]["pid"]
        restarts0 = health["workers"]["1"]["restarts"]
        os.kill(victim_pid, signal.SIGKILL)

        # the supervisor reaps + respawns; the worker comes back READY
        def _respawned():
            _, hb2 = _get(sup_port, "/__health")
            w = json.loads(hb2)["workers"]["1"]
            return (w["restarts"] > restarts0 and w["alive"]
                    and w["ready"] and w["pid"] != victim_pid), w
        _poll(_respawned, timeout=120)

        # keep the load running through the recovery tail, then assert
        # the zero-failure invariant
        time.sleep(3.0)

        def _replayed():
            _, body = _get(pub, "/promql/timeseries/api/v1/query_range",
                           **{**_QUERY, "cache": "false"})
            return _data_bytes(body) == golden, len(body)
        _poll(_replayed, timeout=120)
        time.sleep(1.0)
        load.stop()

        assert load.failures == [], load.failures[:5]
        assert load.mismatches == [], load.mismatches[:5]
        assert load.ok > 10, load.ok

        # supervisor metrics recorded exactly one restart
        _, mtext = _get(sup_port, "/metrics")
        lines = mtext.decode().splitlines()
        assert ('filodb_supervisor_worker_restarts_total{worker="1"} 1'
                in lines), [ln for ln in lines if "restarts" in ln]
    finally:
        if load is not None and load.is_alive():
            load._halt.set()
        proc.terminate()
        try:
            proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=30)


# -- crash-recovery soak (storage-integrity rail) --------------------------

def _post_lines(port, lines, timeout=30):
    body = ("\n".join(lines) + "\n").encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/api/v1/ingest/influx", data=body,
        headers={"Content-Type": "text/plain"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def test_kill9_soak_zero_loss_of_acked_samples(tmp_path):
    """Crash-recovery soak: kill -9 the worker under sustained HTTP
    ingest for >= 5 cycles. With group commit OFF a 200 from
    /api/v1/ingest/influx means the batch was appended AND fsync'd —
    so after the dust settles, every acked sample must be present in
    the WALs. Lost un-acked samples are fine; lost ACKED samples are
    the bug this soak exists to catch."""
    n_shards = 2
    cfg = {
        "num-shards": n_shards, "port": _free_port(),
        "serving-workers": 1,            # the gateway rides worker 0
        "supervisor-port": 0,
        "gateway-port": 0,
        "run-dir": str(tmp_path / "run"),
        "data-dir": str(tmp_path / "data"),
        "stream-dir": str(tmp_path / "streams"),
        "stream-group-commit-ms": 0,     # fsync per append: 200 == durable
        "flush-interval-s": 0.5,
        "query-sample-limit": 0, "query-series-limit": 0,
        "grpc-port": None,
        "monitor-interval-s": 0.1,
        "restart-backoff-s": 0.2,
    }
    cfg_path = tmp_path / "soak.json"
    cfg_path.write_text(json.dumps(cfg))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen(
        [sys.executable, "-m", "filodb_tpu.standalone.supervisor",
         "--config", str(cfg_path)],
        cwd=str(REPO), env=env, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL)
    acked = []                  # values whose batch got a 200
    seq = [0]

    def _lines(n=4):
        out = []
        for _ in range(n):
            seq[0] += 1
            ts_ns = (T0 + seq[0]) * 1_000_000_000
            out.append(f"soak_heap,instance=i{seq[0] % 4} "
                       f"gauge={float(seq[0])} {ts_ns}")
        return out

    try:
        buf = b""
        deadline = time.monotonic() + 240
        while time.monotonic() < deadline and b"\n" not in buf:
            r, _, _ = select.select([proc.stdout], [], [], 1.0)
            if r:
                ch = proc.stdout.read1(4096)
                if not ch:
                    raise RuntimeError("supervisor died during startup")
                buf += ch
        line = json.loads(buf.split(b"\n", 1)[0])
        pub, sup_port = line["port"], line["supervisor_port"]

        def _ready():
            _, hb = _get(sup_port, "/__health")
            w = json.loads(hb)["workers"]["0"]
            return (w["alive"] and w["ready"]), w
        _poll(_ready, timeout=180)

        for cycle in range(5):
            # sustained ingest: acked batches join the ledger; batches
            # that die with the worker (connection error / non-200) are
            # allowed to be lost
            sent_this_cycle = 0
            deadline = time.monotonic() + 20
            while sent_this_cycle < 6 and time.monotonic() < deadline:
                batch = _lines()
                try:
                    status, body = _post_lines(pub, batch)
                except (OSError, ValueError):
                    continue
                if status == 200 \
                        and body["data"]["rejected"] == 0:
                    acked.extend(batch)
                    sent_this_cycle += 1
            assert sent_this_cycle >= 1, f"cycle {cycle}: no acks"

            _, hb = _get(sup_port, "/__health")
            w = json.loads(hb)["workers"]["0"]
            victim_pid, restarts0 = w["pid"], w["restarts"]
            # fire one more batch and kill while it may be in flight
            killer_batch = _lines()
            try:
                status, body = _post_lines(pub, killer_batch, timeout=5)
                if status == 200 and body["data"]["rejected"] == 0:
                    acked.extend(killer_batch)
            except (OSError, ValueError):
                pass
            os.kill(victim_pid, signal.SIGKILL)

            def _respawned():
                _, hb2 = _get(sup_port, "/__health")
                w2 = json.loads(hb2)["workers"]["0"]
                return (w2["restarts"] > restarts0 and w2["alive"]
                        and w2["ready"] and w2["pid"] != victim_pid), w2
            _poll(_respawned, timeout=180)

        # post-recovery ingest still works (replay + takeover healed
        # any torn tail the kills left behind)
        final = _lines()

        def _final_ack():
            status, body = _post_lines(pub, final)
            return (status == 200
                    and body["data"]["rejected"] == 0), body
        _poll(_final_ack, timeout=60)
        acked.extend(final)
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=30)

    # -- audit: every acked value must be durable in some WAL ----------
    from filodb_tpu.core.schemas import DEFAULT_SCHEMAS
    from filodb_tpu.ingest import LogIngestionStream
    durable = set()
    for sh in range(n_shards):
        path = os.path.join(str(tmp_path / "streams"), f"shard={sh}",
                            "stream.log")
        if not os.path.exists(path):
            continue
        s = LogIngestionStream(path, DEFAULT_SCHEMAS)
        off = 0
        while True:
            batch = s.read(off, 256)
            if not batch:
                break
            for sd in batch:
                cont = sd.container
                if cont.schema.name == "gauge":
                    durable.update(cont.columns[0])
                off = sd.offset + 1
        assert s.quarantined_records() == 0, \
            "kill -9 must tear tails, never corrupt acked records"
        s.close()

    acked_vals = {float(ln.split("gauge=")[1].split()[0])
                  for ln in acked}
    missing = acked_vals - durable
    assert not missing, (f"{len(missing)} fsync-acked samples lost "
                         f"across 5 kill -9 cycles: "
                         f"{sorted(missing)[:10]}")
    assert len(acked_vals) >= 5 * 4 * 4   # real coverage, not vacuous
