"""Unit tests for the degraded-mode primitives (parallel/resilience.py)
and the chaos-injection harness (testing/chaos.py)."""

import errno
import os
import time

import pytest

from filodb_tpu.parallel.resilience import (BreakerOpenError,
                                            BreakerRegistry, CircuitBreaker,
                                            Deadline, DeadlineExceeded,
                                            RetryPolicy, TransportError,
                                            resilient_call)
from filodb_tpu.testing import chaos


# -- Deadline --------------------------------------------------------------

def test_deadline_remaining_and_clip():
    t = [100.0]
    d = Deadline(10.0, clock=lambda: t[0])
    assert d.remaining() == pytest.approx(10.0)
    assert d.clip(60.0) == pytest.approx(10.0)   # budget below flat 60s
    assert d.clip(5.0) == pytest.approx(5.0)     # flat below budget
    t[0] = 105.0
    assert d.remaining() == pytest.approx(5.0)
    assert not d.expired
    t[0] = 111.0
    assert d.expired
    with pytest.raises(DeadlineExceeded):
        d.check("unit test")
    with pytest.raises(DeadlineExceeded):
        d.clip(60.0)


# -- RetryPolicy -----------------------------------------------------------

def test_retry_backoff_grows_and_caps():
    p = RetryPolicy(max_attempts=5, base_delay_s=0.1, max_delay_s=0.35,
                    multiplier=2.0, jitter=0.0)
    delays = [p.delay_s(a, rng=lambda: 0.0) for a in (1, 2, 3, 4)]
    assert delays == pytest.approx([0.1, 0.2, 0.35, 0.35])
    # full jitter shrinks, never grows
    assert RetryPolicy(jitter=0.5).delay_s(1, rng=lambda: 1.0) \
        < RetryPolicy(jitter=0.5).delay_s(1, rng=lambda: 0.0)


# -- CircuitBreaker --------------------------------------------------------

def test_breaker_opens_after_threshold_and_half_open_recovers():
    t = [0.0]
    b = CircuitBreaker(failure_threshold=3, reset_timeout_s=5.0,
                      clock=lambda: t[0])
    assert b.state == CircuitBreaker.CLOSED
    for _ in range(2):
        b.record_failure()
    assert b.state == CircuitBreaker.CLOSED and b.allow()
    b.record_failure()                       # third consecutive: open
    assert b.state == CircuitBreaker.OPEN
    assert not b.allow()
    t[0] = 4.9
    assert not b.allow()                     # still inside the window
    t[0] = 5.1
    assert b.allow()                         # half-open probe claimed
    assert b.state == CircuitBreaker.HALF_OPEN
    assert not b.allow()                     # only ONE probe in flight
    b.record_success()
    assert b.state == CircuitBreaker.CLOSED and b.allow()


def test_breaker_halfopen_failure_reopens():
    t = [0.0]
    b = CircuitBreaker(failure_threshold=1, reset_timeout_s=5.0,
                      clock=lambda: t[0])
    b.record_failure()
    assert b.state == CircuitBreaker.OPEN
    t[0] = 6.0
    assert b.allow()
    b.record_failure()                       # probe failed: re-open
    assert b.state == CircuitBreaker.OPEN
    assert not b.allow()
    t[0] = 11.5
    assert b.allow()                         # another full window later


def test_success_resets_consecutive_failure_count():
    b = CircuitBreaker(failure_threshold=3)
    b.record_failure()
    b.record_failure()
    b.record_success()
    b.record_failure()
    b.record_failure()
    assert b.state == CircuitBreaker.CLOSED  # never 3 CONSECUTIVE


# -- resilient_call --------------------------------------------------------

def test_resilient_call_retries_then_succeeds():
    calls = []

    def flaky(timeout_s):
        calls.append(timeout_s)
        if len(calls) < 3:
            raise TransportError("nope")
        return "ok"

    out = resilient_call(
        flaky, key="k1", node_id="n", timeout_s=60.0,
        retry=RetryPolicy(max_attempts=3, base_delay_s=0.0),
        breakers=BreakerRegistry(failure_threshold=10), sleep=lambda s: None)
    assert out == "ok" and len(calls) == 3


def test_resilient_call_exhausts_and_raises():
    reg = BreakerRegistry(failure_threshold=10)
    with pytest.raises(TransportError):
        resilient_call(
            lambda t: (_ for _ in ()).throw(TransportError("down")),
            key="k2", node_id="n", timeout_s=60.0,
            retry=RetryPolicy(max_attempts=2, base_delay_s=0.0),
            breakers=reg, sleep=lambda s: None)


def test_resilient_call_does_not_dial_open_breaker():
    reg = BreakerRegistry(failure_threshold=2, reset_timeout_s=60.0)
    calls = []

    def down(timeout_s):
        calls.append(1)
        raise TransportError("down")

    for _ in range(2):
        with pytest.raises((TransportError, BreakerOpenError)):
            resilient_call(down, key="k3", node_id="n", timeout_s=1.0,
                           retry=RetryPolicy(max_attempts=1),
                           breakers=reg, sleep=lambda s: None)
    n_before = len(calls)
    with pytest.raises(BreakerOpenError):
        resilient_call(down, key="k3", node_id="n", timeout_s=1.0,
                       retry=RetryPolicy(max_attempts=1),
                       breakers=reg, sleep=lambda s: None)
    assert len(calls) == n_before            # breaker open: NO dial


def test_resilient_call_application_error_not_retried():
    from filodb_tpu.query.model import QueryError
    calls = []

    def answered(timeout_s):
        calls.append(1)
        raise QueryError("bad query")        # peer ANSWERED with an error

    with pytest.raises(QueryError):
        resilient_call(answered, key="k4", node_id="n", timeout_s=1.0,
                       retry=RetryPolicy(max_attempts=5),
                       breakers=BreakerRegistry(), sleep=lambda s: None)
    assert len(calls) == 1
    # and it did not count against the breaker
    assert BreakerRegistry().get("k4").state == CircuitBreaker.CLOSED


def test_application_error_closes_half_open_breaker():
    """A peer that ANSWERS an error through a half-open probe proves the
    transport recovered: the breaker must close, not jam half-open."""
    from filodb_tpu.query.model import QueryError
    reg = BreakerRegistry(failure_threshold=1, reset_timeout_s=0.05)
    with pytest.raises(TransportError):
        resilient_call(
            lambda t: (_ for _ in ()).throw(TransportError("down")),
            key="k6", node_id="n", timeout_s=1.0,
            retry=RetryPolicy(max_attempts=1), breakers=reg,
            sleep=lambda s: None)
    assert reg.get("k6").state == CircuitBreaker.OPEN
    time.sleep(0.06)
    with pytest.raises(QueryError):
        resilient_call(
            lambda t: (_ for _ in ()).throw(QueryError("bad query")),
            key="k6", node_id="n", timeout_s=1.0,
            retry=RetryPolicy(max_attempts=1), breakers=reg,
            sleep=lambda s: None)
    assert reg.get("k6").state == CircuitBreaker.CLOSED


def test_resilient_call_respects_deadline():
    t = [0.0]
    d = Deadline(5.0, clock=lambda: t[0])

    def down(timeout_s):
        # per-attempt timeout is clipped to the remaining budget
        assert timeout_s <= 5.0
        t[0] += 3.0                          # each attempt burns 3s
        raise TransportError("slow death")

    with pytest.raises((TransportError, DeadlineExceeded)):
        resilient_call(down, key="k5", node_id="n", timeout_s=60.0,
                       retry=RetryPolicy(max_attempts=10,
                                         base_delay_s=0.0),
                       breakers=BreakerRegistry(failure_threshold=99),
                       deadline=d, sleep=lambda s: None)
    assert t[0] <= 6.1                       # ~2 attempts, never 10


# -- chaos harness ---------------------------------------------------------

def test_chaos_noop_when_not_installed():
    chaos.fire("grpc.call", node="x")        # must not raise


def test_chaos_fail_rule_counts_and_disarms():
    inj = chaos.ChaosInjector()
    inj.fail("http.peer", times=2,
             match=lambda c: c.get("node") == "node1")
    with inj:
        with pytest.raises(chaos.ChaosError):
            chaos.fire("http.peer", node="node1")
        chaos.fire("http.peer", node="node0")    # no match: clean
        with pytest.raises(chaos.ChaosError):
            chaos.fire("http.peer", node="node1")
        chaos.fire("http.peer", node="node1")    # rule exhausted
    assert chaos.installed() is None
    assert inj.fired("http.peer") == 4
    assert [e["node"] for e in inj.log] == ["node1", "node0", "node1",
                                            "node1"]


def test_chaos_delay_rule():
    inj = chaos.ChaosInjector().delay("grpc.call", 0.05, times=1)
    t0 = time.monotonic()
    with inj:
        chaos.fire("grpc.call")
        chaos.fire("grpc.call")              # only the first delays
    assert time.monotonic() - t0 >= 0.05


def test_chaos_error_is_oserror():
    # http.peer maps OSError -> TransportError; the injected fault must
    # ride the same path as a real refused connection
    assert issubclass(chaos.ChaosError, OSError)


# -- disk-fault layer (storage-integrity rail) -----------------------------

class _Buf:
    def __init__(self):
        self.data = b""

    def write(self, b):
        self.data += b
        return len(b)


def test_chaos_write_passthrough_when_not_installed():
    f = _Buf()
    assert chaos.write("wal.append", f, b"abc") == 3
    assert f.data == b"abc"
    assert chaos.filter_read("wal.read", b"xyz") == b"xyz"


def test_chaos_torn_write_lands_prefix_then_errors():
    inj = chaos.ChaosInjector().torn_write("wal.append", keep=0.5,
                                           times=1)
    f = _Buf()
    with inj:
        with pytest.raises(OSError) as ei:
            chaos.write("wal.append", f, b"0123456789")
        assert ei.value.errno == chaos.eio().errno
        assert f.data == b"01234"            # the torn prefix IS on disk
        assert chaos.write("wal.append", f, b"abc") == 3   # exhausted
    assert f.data == b"01234abc"


def test_chaos_torn_write_byte_count_keep():
    inj = chaos.ChaosInjector().torn_write("wal.append", keep=3, times=1)
    f = _Buf()
    with inj:
        with pytest.raises(OSError):
            chaos.write("wal.append", f, b"0123456789")
    assert f.data == b"012"


def test_chaos_bit_flip_on_write_and_read():
    inj = chaos.ChaosInjector().bit_flip("wal.append", offset=0,
                                         mask=0xFF, times=1)
    inj.bit_flip("wal.read", offset=-1, mask=0x01, times=1)
    f = _Buf()
    with inj:
        chaos.write("wal.append", f, b"\x00abc")
        assert f.data == b"\xffabc"          # write-side flip persisted
        got = chaos.filter_read("wal.read", b"abc\x10")
        assert got == b"abc\x11"             # read-side flip, last byte
        assert chaos.filter_read("wal.read", b"abc") == b"abc"


def test_chaos_enospc_rule_on_write_point():
    inj = chaos.ChaosInjector()
    inj.fail("wal.append", exc=chaos.enospc, times=1)
    f = _Buf()
    with inj:
        with pytest.raises(OSError) as ei:
            chaos.write("wal.append", f, b"abc")
        assert ei.value.errno == errno.ENOSPC
        assert f.data == b""                 # nothing landed


def test_chaos_documented_fault_points_match_call_sites():
    """The docstring's fault-point registry IS the contract tests and
    runbooks rely on: every point named in production code must be
    documented, and every documented disk point must exist in code."""
    import re
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    pkg = os.path.join(root, "filodb_tpu")
    used = set()
    for dirpath, _, files in os.walk(pkg):
        for name in files:
            if not name.endswith(".py") or name == "chaos.py":
                continue
            with open(os.path.join(dirpath, name)) as f:
                src = f.read()
            used.update(re.findall(
                r"chaos\.(?:fire|write|filter_read)\(\s*[\"']([a-z_.]+)[\"']",
                src))
            # disk points also travel as plain arguments (e.g. the
            # read_point parameter of _scan_log) — their prefixes are
            # distinctive, so any such literal counts as a call site
            used.update(re.findall(
                r"[\"']((?:wal|chunklog|partkeys|checkpoint)"
                r"\.(?:read|write|append|fsync))[\"']", src))
    documented = set(re.findall(r"``([a-z_]+\.[a-z_]+)``",
                                chaos.__doc__))
    assert used, "no fault points found — the grep is broken"
    missing = used - documented
    assert not missing, f"undocumented fault points: {sorted(missing)}"
    disk_docs = {p for p in documented
                 if p.split(".")[0] in ("wal", "chunklog", "partkeys",
                                        "checkpoint")}
    dead = disk_docs - used
    assert not dead, f"documented but unused disk points: {sorted(dead)}"
