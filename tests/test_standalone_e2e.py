"""Full standalone-node e2e: gateway TCP ingest -> durable shard streams
-> ingestion drivers -> HTTP queries, then SIGKILL + restart replaying
from the checkpoint watermark.

This is the analogue of the reference's dev loop (filodb-dev-start.sh +
dev-gateway.sh) plus the recovery protocol e2e
(coordinator/IngestionActor.scala:174-345): a killed node must come back
with bit-identical query results, rebuilding from the ColumnStore and
replaying the stream tail that never flushed.
"""

import json
import os
import pathlib
import select
import signal
import socket
import subprocess
import sys
import time

import numpy as np
import urllib.error
import urllib.parse
import urllib.request

REPO = pathlib.Path(__file__).resolve().parent.parent
T0 = 1_600_000_000
N_SAMPLES = 60          # per series, 10s apart
N_SERIES = 3


def _spawn(cfg_path):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.Popen(
        [sys.executable, "-m", "filodb_tpu.standalone.server",
         "--config", str(cfg_path)],
        cwd=str(REPO), env=env, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL)


def _read_ports(proc, timeout=120.0):
    """First stdout line is machine-readable {"port":..,"gateway_port":..}."""
    deadline = time.monotonic() + timeout
    buf = b""
    while time.monotonic() < deadline:
        r, _, _ = select.select([proc.stdout], [], [], 1.0)
        if not r:
            if proc.poll() is not None:
                raise RuntimeError("server died during startup")
            continue
        ch = proc.stdout.read1(4096)
        if not ch:
            raise RuntimeError("server stdout closed before startup line")
        buf += ch
        if b"\n" in buf:
            return json.loads(buf.split(b"\n", 1)[0])
    raise TimeoutError("no startup line")


def _get(port, path, **params):
    qs = urllib.parse.urlencode(params, doseq=True)
    url = f"http://127.0.0.1:{port}{path}"
    if qs:
        url += "?" + qs
    # generous: under a full-suite run the server subprocess competes for
    # CPU with other tests while JIT-compiling its first query
    with urllib.request.urlopen(url, timeout=120) as r:
        return json.loads(r.read())


def _poll(fn, timeout=90.0, interval=0.2):
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        try:
            ok, last = fn()
            if ok:
                return last
        except (urllib.error.URLError, ConnectionError, OSError):
            pass
        time.sleep(interval)
    raise TimeoutError(f"poll timed out; last={last!r}")


def _send_lines(port, lines):
    with socket.create_connection(("127.0.0.1", port), timeout=10) as s:
        s.sendall(("\n".join(lines) + "\n").encode())


def _counter_lines(first_t, last_t):
    """Influx counter lines for N_SERIES series, sample index range
    [first_t, last_t)."""
    out = []
    for t in range(first_t, last_t):
        ts_ns = (T0 + t * 10) * 1_000_000_000
        for s in range(N_SERIES):
            out.append(f"reqs,instance=i{s} counter={(t + 1) * (s + 1)}"
                       f" {ts_ns}")
    return out


def _rate_query(port):
    """rate() over the whole run, keyed by instance (result order is not
    part of the API contract — bootstrap order differs from ingest order)."""
    body = _get(port, "/promql/timeseries/api/v1/query_range",
                query="rate(reqs[5m])",
                start=T0 + 300, end=T0 + (N_SAMPLES - 1) * 10, step=30)
    return {r["metric"]["instance"]: (r["metric"], r["values"])
            for r in body["data"]["result"]}


def test_kill_minus_9_restart_replays_to_identical_results(tmp_path):
    cfg = {
        "num-shards": 2, "groups-per-shard": 2, "port": 0,
        "data-dir": str(tmp_path / "data"),
        "stream-dir": str(tmp_path / "streams"),
        "gateway-port": 0,
        "flush-interval-s": 0.5,
    }
    cfg_path = tmp_path / "server.json"
    cfg_path.write_text(json.dumps(cfg))

    proc = _spawn(cfg_path)
    try:
        ports = _read_ports(proc)
        port, gw_port = ports["port"], ports["gateway_port"]
        assert gw_port is not None

        # shards come up ACTIVE (empty streams -> trivial recovery)
        _poll(lambda: ((lambda b: (len(b["data"]) == 2 and all(
            s["status"] == "active" for s in b["data"]), b))(
            _get(port, "/api/v1/cluster/timeseries/status"))))

        # batch 1: ~2/3 of the data; let flush checkpoints land
        _send_lines(gw_port, _counter_lines(0, 40))

        def _all_series_at(t_end):
            body = _get(port, "/promql/timeseries/api/v1/query",
                        query="reqs", time=T0 + (t_end - 1) * 10)
            res = body["data"]["result"]
            vals = {r["metric"]["instance"]: float(r["value"][1])
                    for r in res}
            want = {f"i{s}": float(t_end * (s + 1))
                    for s in range(N_SERIES)}
            return vals == want, vals

        _poll(lambda: _all_series_at(40))
        time.sleep(1.5)          # several flush rotations -> checkpoints

        # batch 2: the tail; kill before the flush interval can persist it
        _send_lines(gw_port, _counter_lines(40, N_SAMPLES))
        _poll(lambda: _all_series_at(N_SAMPLES))
        before = _rate_query(port)
        assert len(before) == N_SERIES

        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)

    # -- restart on the same dirs: bootstrap + replay ----------------------
    proc2 = _spawn(cfg_path)
    try:
        ports2 = _read_ports(proc2)
        port2 = ports2["port"]
        _poll(lambda: ((lambda b: (len(b["data"]) == 2 and all(
            s["status"] == "active" for s in b["data"]), b))(
            _get(port2, "/api/v1/cluster/timeseries/status"))))
        # every pre-kill sample is back (flushed ones from the ColumnStore,
        # the unflushed tail replayed from the stream logs)
        _poll(lambda: _all_series_at_port(port2, N_SAMPLES))
        after = _rate_query(port2)
        # numerically identical: pre-kill evaluation may route tail steps
        # through the exact write-buffer path while post-replay data sits
        # in chunks on the f32-hybrid fast path (documented 1e-5 rtol)
        assert after.keys() == before.keys()
        for inst in before:
            assert after[inst][0] == before[inst][0]
            bvals, avals = before[inst][1], after[inst][1]
            assert [t for t, _ in avals] == [t for t, _ in bvals]
            np.testing.assert_allclose([float(v) for _, v in avals],
                                       [float(v) for _, v in bvals],
                                       rtol=1e-5)
    finally:
        proc2.send_signal(signal.SIGTERM)
        try:
            proc2.wait(timeout=30)
        except subprocess.TimeoutExpired:
            proc2.kill()
            proc2.wait(timeout=30)


def _all_series_at_port(port, t_end):
    body = _get(port, "/promql/timeseries/api/v1/query",
                query="reqs", time=T0 + (t_end - 1) * 10)
    res = body["data"]["result"]
    vals = {r["metric"]["instance"]: float(r["value"][1]) for r in res}
    want = {f"i{s}": float(t_end * (s + 1)) for s in range(N_SERIES)}
    return vals == want, vals
