"""Exec-layer degraded-mode units + advisor-fix regressions:

* ConcatExec: child failure tolerated only under allow_partial (result
  flagged partial, warning names the lost child); mismatched histogram
  bucket schemes raise instead of silently mixing buckets; deadline
  checked between children.
* groupsum dispatcher: oversized [T,G]/scratch/onehot VMEM footprints
  fall back to the general path (None) instead of failing at Mosaic
  compile time.
* fixed-point packer: series whose value span cannot be represented at
  any in-range scale exponent return None (exact f64 fallback) instead
  of silently wrapping int64."""

import numpy as np
import pytest

from filodb_tpu.parallel.resilience import Deadline, DeadlineExceeded
from filodb_tpu.query import tilestore as tst
from filodb_tpu.query.model import GridResult, QueryError, QueryStats
from filodb_tpu.query.planner import ConcatExec

BASE = 1_600_000_000_000
DT = 10_000
STEPS = np.arange(0, 600_000, 60_000, dtype=np.int64)


class _Child:
    def __init__(self, grid=None, exc=None):
        self.grid = grid
        self.exc = exc

    def execute(self):
        if self.exc is not None:
            raise self.exc
        return self.grid

    def plan_tree(self, indent=0):
        return " " * indent + "FakeChild"


def _grid(n=2, les=None, partial=False, warnings=()):
    hv = None
    if les is not None:
        hv = np.zeros((n, STEPS.size, len(les)))
    return GridResult(STEPS, [{"i": str(k)} for k in range(n)],
                      np.zeros((n, STEPS.size)),
                      hist_values=hv,
                      bucket_les=np.asarray(les, float)
                      if les is not None else None,
                      partial=partial, warnings=list(warnings))


# -- ConcatExec degraded mode ----------------------------------------------

def test_concat_failfast_by_default():
    ex = ConcatExec([_Child(_grid()), _Child(exc=QueryError("peer died"))],
                    QueryStats())
    with pytest.raises(QueryError):
        ex.execute()


def test_concat_allow_partial_drops_child_and_flags():
    stats = QueryStats()
    ex = ConcatExec([_Child(_grid(3)),
                     _Child(exc=QueryError("node1 unreachable"))],
                    stats, allow_partial=True)
    out = ex.execute()
    assert out.num_series == 3
    assert out.partial and stats.partial
    assert any("node1 unreachable" in w for w in out.warnings)


def test_concat_all_children_failed_still_errors():
    ex = ConcatExec([_Child(exc=QueryError("a")),
                     _Child(exc=QueryError("b"))],
                    QueryStats(), allow_partial=True)
    with pytest.raises(QueryError, match="all shard groups failed"):
        ex.execute()


def test_concat_propagates_child_partial_flags():
    out = ConcatExec([_Child(_grid(1, partial=True,
                                   warnings=["shard 3 recovering"])),
                      _Child(_grid(1))], QueryStats()).execute()
    assert out.partial
    assert "shard 3 recovering" in out.warnings


def test_concat_deadline_checked_between_children():
    t = [0.0]
    d = Deadline(1.0, clock=lambda: t[0])

    class _Slow(_Child):
        def execute(self):
            t[0] += 2.0                      # burns past the budget
            return _grid()

    ex = ConcatExec([_Slow(), _Child(_grid())], QueryStats(),
                    deadline=d)
    with pytest.raises(DeadlineExceeded):
        ex.execute()


# -- ConcatExec histogram bucket verification (advisor, planner.py) --------

def test_concat_hist_prefix_les_pads_to_max_width():
    out = ConcatExec([_Child(_grid(1, les=[1, 2, 5])),
                      _Child(_grid(1, les=[1, 2, 5, 10]))],
                     QueryStats()).execute()
    assert list(out.bucket_les) == [1, 2, 5, 10]
    assert out.hist_values.shape == (2, STEPS.size, 4)
    # the narrower child's missing bucket is NaN-padded, not zero-filled
    assert np.isnan(out.hist_values[0, :, 3]).all()


def test_concat_hist_mismatched_les_raises():
    ex = ConcatExec([_Child(_grid(1, les=[1, 2, 5])),
                     _Child(_grid(1, les=[1, 3, 5]))], QueryStats())
    with pytest.raises(QueryError, match="bucket schemes"):
        ex.execute()


# -- groupsum dispatcher VMEM budget (advisor, tilestore.py) ---------------

def _tiles(S=8, N=288, seed=7, span=None):
    rng = np.random.default_rng(seed)
    ts = (BASE + np.arange(N)[None, :] * DT
          + rng.uniform(-2000, 2000, (S, N)))
    if span is None:
        vals = np.cumsum(rng.uniform(0, 5, (S, N)), axis=1)
    else:
        vals = np.linspace(-span, span, N)[None, :] * np.ones((S, 1))
    return tst.AlignedTiles([{} for _ in range(S)], BASE, DT,
                            np.ones((S, N), bool), ts, vals)


def _gs(tiles, G, func="delta", S=8):
    steps = np.arange(BASE + 400_000, BASE + 2_400_000, 60_000,
                      dtype=np.int64)
    onehot = np.zeros((S, G), np.float32)
    onehot[np.arange(S), np.arange(S) % G] = 1.0
    return tst.groupsum_counters(tiles, func, steps, 300_000, onehot,
                                 interpret=True)


def test_groupsum_vmem_budget_rejects_wide_group_tables():
    tiles = _tiles()
    # G=1500 passes the old accumulator-only check (256*1500*8 ~ 3MB
    # < 4MB) but the DMA scratch + onehot block push the total past
    # VMEM: the dispatcher must fall back, not die in Mosaic
    assert _gs(tiles, 1500) is None
    # the same tiles with a small group table still dispatch
    assert _gs(tiles, 4) is not None


# -- fixed-point scale-exponent underflow (advisor, tilestore.py) ----------

def test_fixed_channels_refuse_unrepresentable_span():
    tiles = _tiles(span=1e60)                # needs s < -96: unencodable
    # before the fix the scale exponent was clipped to -96 and the
    # int64 rint silently wrapped; now the packer refuses and the
    # dispatcher takes the non-fused fallback
    assert tiles._fixed_channels("v") is None
    assert _gs(tiles, 4) is None


def test_fixed_channels_normal_span_still_packs():
    tiles = _tiles()
    assert tiles._fixed_channels("v") is not None
