"""FailureDetector unit tests: status gossip from peers' health bodies,
quorum-gated elastic reassignment, and recovery promotion — the
Akka-cluster gossip-convergence analogue (FilodbCluster.scala), tested
without sockets by stubbing the health probe."""

import time

from filodb_tpu.parallel.cluster import FailureDetector
from filodb_tpu.parallel.shardmapper import ShardMapper, ShardStatus


def _mk(peers, shards_by_node, num_shards=8, grace=0.0, **kw):
    mapper = ShardMapper(num_shards)
    for node, shards in shards_by_node.items():
        for sh in shards:
            mapper.assign(sh, node)
            mapper.update(sh, ShardStatus.ACTIVE, node)
    fired = []
    det = FailureDetector(
        mapper, {p: f"http://{p}" for p in peers}, shards_by_node,
        interval_s=0.01, threshold=1, timeout_s=0.1,
        reassign_grace_s=grace,
        on_node_down=fired.append, **kw)
    return mapper, det, fired


def test_status_gossip_promotes_recovering_shard():
    """A shard held RECOVERY locally is promoted when its owner's
    health body advertises it ACTIVE — and not before."""
    mapper, det, _ = _mk(["b"], {"b": [3]})
    mapper.update(3, ShardStatus.RECOVERY, "b")
    bodies = {"b": {"shards": {}, "down_peers": []}}
    det._probe = lambda url: bodies["b"]
    det.poll_once()
    assert mapper.status(3) is ShardStatus.RECOVERY   # not advertised yet
    bodies["b"] = {"shards": {"3": "recovery"}, "down_peers": []}
    det.poll_once()
    assert mapper.status(3) is ShardStatus.RECOVERY
    bodies["b"] = {"shards": {"3": "active"}, "down_peers": []}
    det.poll_once()
    assert mapper.status(3) is ShardStatus.ACTIVE


def test_gossip_ignores_shards_owned_elsewhere():
    """A peer advertising a shard the mapper assigns to another node
    must not flip that shard's status (stale adopter)."""
    mapper, det, _ = _mk(["b", "c"], {"b": [1], "c": [2]})
    det._probe = lambda url: (
        {"shards": {"2": "recovery"}, "down_peers": []}
        if "b" in url else {"shards": {"2": "active"}, "down_peers": []})
    det.poll_once()
    assert mapper.status(2) is ShardStatus.ACTIVE


def test_quorum_blocks_lone_suspicion():
    """With other alive peers NOT sharing the down-view, reassignment
    must not fire (a one-sided network partition would otherwise cause
    dual ingest)."""
    mapper, det, fired = _mk(["b", "c"], {"b": [1], "c": [2]})
    det._probe = lambda url: (
        None if "b" in url
        else {"shards": {"2": "active"}, "down_peers": []})
    for _ in range(3):
        det.poll_once()
        time.sleep(0.01)
    assert det.is_down("b")
    assert fired == []                     # c disagrees: no reassignment
    assert mapper.status(1) is ShardStatus.DOWN   # still marked down


def test_quorum_agreement_fires_reassignment():
    mapper, det, fired = _mk(["b", "c"], {"b": [1], "c": [2]})
    det._probe = lambda url: (
        None if "b" in url
        else {"shards": {"2": "active"}, "down_peers": ["b"]})
    for _ in range(3):
        det.poll_once()
        time.sleep(0.01)
    assert fired == ["b"]


def test_two_node_cluster_fires_without_peers_to_consult():
    mapper, det, fired = _mk(["b"], {"b": [1]})
    det._probe = lambda url: None
    for _ in range(3):
        det.poll_once()
        time.sleep(0.01)
    assert fired == ["b"]
