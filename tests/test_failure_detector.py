"""FailureDetector unit tests: status gossip from peers' health bodies,
quorum-gated elastic reassignment, and recovery promotion — the
Akka-cluster gossip-convergence analogue (FilodbCluster.scala), tested
without sockets by stubbing the health probe."""

import time

from filodb_tpu.parallel.cluster import FailureDetector
from filodb_tpu.parallel.shardmapper import ShardMapper, ShardStatus


def _mk(peers, shards_by_node, num_shards=8, grace=0.0, **kw):
    mapper = ShardMapper(num_shards)
    for node, shards in shards_by_node.items():
        for sh in shards:
            mapper.assign(sh, node)
            mapper.update(sh, ShardStatus.ACTIVE, node)
    fired = []
    det = FailureDetector(
        mapper, {p: f"http://{p}" for p in peers}, shards_by_node,
        interval_s=0.01, threshold=1, timeout_s=0.1,
        reassign_grace_s=grace,
        on_node_down=fired.append, **kw)
    return mapper, det, fired


def test_status_gossip_promotes_recovering_shard():
    """A shard held RECOVERY locally is promoted when its owner's
    health body advertises it ACTIVE — and not before."""
    mapper, det, _ = _mk(["b"], {"b": [3]})
    mapper.update(3, ShardStatus.RECOVERY, "b")
    bodies = {"b": {"shards": {}, "down_peers": []}}
    det._probe = lambda url: bodies["b"]
    det.poll_once()
    assert mapper.status(3) is ShardStatus.RECOVERY   # not advertised yet
    bodies["b"] = {"shards": {"3": "recovery"}, "down_peers": []}
    det.poll_once()
    assert mapper.status(3) is ShardStatus.RECOVERY
    bodies["b"] = {"shards": {"3": "active"}, "down_peers": []}
    det.poll_once()
    assert mapper.status(3) is ShardStatus.ACTIVE


def test_gossip_ignores_shards_owned_elsewhere():
    """A peer advertising a shard the mapper assigns to another node
    must not flip that shard's status (stale adopter)."""
    mapper, det, _ = _mk(["b", "c"], {"b": [1], "c": [2]})
    det._probe = lambda url: (
        {"shards": {"2": "recovery"}, "down_peers": []}
        if "b" in url else {"shards": {"2": "active"}, "down_peers": []})
    det.poll_once()
    assert mapper.status(2) is ShardStatus.ACTIVE


def test_quorum_blocks_lone_suspicion():
    """With other alive peers NOT sharing the down-view, reassignment
    must not fire (a one-sided network partition would otherwise cause
    dual ingest)."""
    mapper, det, fired = _mk(["b", "c"], {"b": [1], "c": [2]})
    det._probe = lambda url: (
        None if "b" in url
        else {"shards": {"2": "active"}, "down_peers": []})
    for _ in range(3):
        det.poll_once()
        time.sleep(0.01)
    assert det.is_down("b")
    assert fired == []                     # c disagrees: no reassignment
    assert mapper.status(1) is ShardStatus.DOWN   # still marked down


def test_quorum_agreement_fires_reassignment():
    mapper, det, fired = _mk(["b", "c"], {"b": [1], "c": [2]})
    det._probe = lambda url: (
        None if "b" in url
        else {"shards": {"2": "active"}, "down_peers": ["b"]})
    for _ in range(3):
        det.poll_once()
        time.sleep(0.01)
    assert fired == ["b"]


def test_two_node_cluster_fires_without_peers_to_consult():
    mapper, det, fired = _mk(["b"], {"b": [1]})
    det._probe = lambda url: None
    for _ in range(3):
        det.poll_once()
        time.sleep(0.01)
    assert fired == ["b"]


def test_failing_on_node_up_hook_retries_instead_of_wedging():
    """The recovery-path satellite: when on_node_up raises, the bare
    except must NOT clear the reassignment flag — the hook retries on
    the next poll, and meanwhile ownership is handed back at the
    mapper level rather than wedging on the adopters forever."""
    mapper, det, _ = _mk(["b"], {"b": [1]})
    calls = []

    def flaky_hook(node):
        calls.append(node)
        if len(calls) < 3:
            raise RuntimeError("release hook failed")

    det.on_node_up = flaky_hook
    det._probe = lambda url: None
    for _ in range(3):
        det.poll_once()
        time.sleep(0.01)
    assert det.is_down("b") and det._reassigned["b"]
    det._probe = lambda url: {"shards": {"1": "active"},
                              "down_peers": []}
    det.poll_once()
    assert calls == ["b"]
    # hook raised: flag kept (retry next poll), ownership handed back
    # at the mapper level so it can't wedge
    assert det._reassigned["b"]
    assert mapper.status(1) is ShardStatus.ACTIVE
    assert mapper.node_of(1) == "b"
    det.poll_once()
    assert calls == ["b", "b"] and det._reassigned["b"]
    det.poll_once()                      # third call succeeds
    assert calls == ["b", "b", "b"]
    assert not det._reassigned["b"]
    det.poll_once()                      # settled: no more hook calls
    assert calls == ["b", "b", "b"]


def test_down_flip_tracks_current_ownership_not_startup_assignment():
    """A planned handoff moved shard 1 off node b before b died: the
    down flip must follow the mapper's CURRENT assignment (nothing, for
    a drained node) — not the startup shards_by_node table — or it
    would clobber the new owner's shards DOWN."""
    mapper, det, _ = _mk(["b", "c"], {"b": [1], "c": [2]})
    mapper.assign(1, "c")                # planned handoff b -> c
    mapper.update(1, ShardStatus.ACTIVE, "c")
    det._probe = lambda url: (
        None if "b" in url
        else {"shards": {"1": "active", "2": "active"},
              "down_peers": ["b"]})
    for _ in range(3):
        det.poll_once()
        time.sleep(0.01)
    assert det.is_down("b")
    assert mapper.status(1) is ShardStatus.ACTIVE   # c's shard untouched
    assert mapper.node_of(1) == "c"


def test_bounce_before_reassignment_restores_only_owned_shards():
    """A drained node that bounces (down then up before the grace
    window) owns nothing: recovery must not hand its ORIGINAL shards
    back to it at the mapper level."""
    mapper, det, _ = _mk(["b"], {"b": [1]}, grace=None)
    mapper.assign(1, "c")                # drained away before the bounce
    mapper.update(1, ShardStatus.ACTIVE, "c")
    det._probe = lambda url: None
    det.poll_once()
    assert det.is_down("b")
    det._probe = lambda url: {"shards": {}, "down_peers": []}
    det.poll_once()
    assert not det.is_down("b")
    assert mapper.node_of(1) == "c"      # not clobbered back to b


def test_stop_surfaces_wedged_monitor_thread():
    """stop() must check the join result: a monitor thread that fails
    to exit is surfaced via thread_wedged (the detector_thread_wedged
    gauge) instead of silently leaking a poller."""
    mapper, det, _ = _mk(["b"], {"b": [1]})

    class _Wedged:
        def join(self, timeout=None):
            pass

        def is_alive(self):
            return True

    det._thread = _Wedged()
    assert det.thread_wedged is False
    det.stop()
    assert det.thread_wedged is True


def test_peer_state_sink_gossips_watermarks_and_drops_on_death():
    sink = {}
    mapper, det, _ = _mk(["b"], {"b": [1]}, peer_state_sink=sink)
    det._probe = lambda url: {
        "shards": {"1": "active"}, "down_peers": [],
        "watermarks": {"1": 123_000}, "backfill_epochs": {"1": 2},
        "topo_epoch": 7}
    det.poll_once()
    assert sink["b"]["watermarks"] == {1: 123_000}
    assert sink["b"]["epochs"] == {1: 2}
    assert sink["b"]["topo_epoch"] == 7
    det._probe = lambda url: None
    det.poll_once()
    assert det.is_down("b")
    assert "b" not in sink               # dead peers bound nothing
