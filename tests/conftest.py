"""Test configuration: force an 8-device virtual CPU mesh so multi-chip
sharding paths can be exercised without TPU hardware (mirrors the reference's
sbt-multi-jvm strategy of multi-node tests without a real cluster —
reference: project/FiloBuild.scala:100).

Note: this environment pre-imports jax (sitecustomize) pointed at real TPU
hardware, so plain env vars are too late — use jax.config.update, which works
as long as no backend has been initialized yet.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

# exercise the fused Pallas group-sum + boundary-extract rate paths
# (interpret mode) on the CPU test mesh; production CPU nodes keep both
# off (tpu.py gates) — interpret-mode re-jits per shape, which a
# serving node must never pay per query
from filodb_tpu.query import tpu as _tpu  # noqa: E402

_tpu.FUSED_GROUPSUM_INTERPRET = True
_tpu.PALLAS_RATE_INTERPRET = True
