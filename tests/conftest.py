"""Test configuration: force an 8-device virtual CPU mesh so multi-chip
sharding paths can be exercised without TPU hardware (mirrors the reference's
sbt-multi-jvm strategy of multi-node tests without a real cluster —
reference: project/FiloBuild.scala:100)."""

import os

# Must be set before jax is imported anywhere.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "1")
