"""Self-monitoring pipeline (obs/selfmon.py): the node ingests its own
metrics into the reserved __selfmon__ dataset through the normal ingest
path and serves them back over PromQL.

Pins the PR acceptance scenario: with --self-monitor on, a range query
over filodb_query_latency_seconds_bucket (and a QoS tenant family)
through /api/v1/query_range returns real, fresh series produced by the
in-process loop — and user-dataset cardinality accounting is untouched
by internal series. Plus: the reserved tenant's forced-charge QoS
semantics under sustained overload, worker labeling, schema selection,
process-collector families, and selfmon-on byte-transparency for user
queries.
"""

import json
import time
import urllib.error
import urllib.parse
import urllib.request

import pytest

from filodb_tpu.core.memstore import TimeSeriesMemStore
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetRef
from filodb_tpu.obs import metrics as obs_metrics
from filodb_tpu.obs.selfmon import (SELFMON_DATASET, SELFMON_TENANT,
                                    SelfMonitor, _schema_for)
from filodb_tpu.standalone.server import FiloServer

T0 = 1_600_000_000


def _get(port, path, **params):
    qs = urllib.parse.urlencode(params)
    url = f"http://127.0.0.1:{port}{path}" + (f"?{qs}" if qs else "")
    with urllib.request.urlopen(url, timeout=120) as r:
        return json.loads(r.read())


def _query_range(port, ds, **params):
    return _get(port, f"/promql/{ds}/api/v1/query_range", **params)


# ---------------------------------------------------------------------------
# unit: collection + schema selection
# ---------------------------------------------------------------------------

def _fake_source():
    b = obs_metrics.ExpositionBuilder()
    b.sample("app_requests_total", {"code": "200"}, 7, mtype="counter",
             help="requests")
    b.sample("app_requests_total", {"code": "500"}, 1, mtype="counter",
             help="requests")
    b.sample("app_temperature", {}, 21.5, help="gauge")
    b.sample("app_bad", {}, "not-a-number", help="skipped")
    h = obs_metrics.Histogram("app_lat_seconds", "lat", (0.1, 1.0))
    h.observe(0.05)
    h.observe(5.0)
    b.histogram(h)
    return b


def test_schema_selection():
    assert _schema_for("counter", "x_total") == "prom-counter"
    assert _schema_for("histogram", "x_bucket") == "prom-counter"
    assert _schema_for("gauge", "x_bucket") == "prom-counter"
    assert _schema_for("gauge", "x_count") == "prom-counter"
    assert _schema_for("gauge", "x") == "gauge"
    assert _schema_for("", "x") == "gauge"


def test_collect_once_ingests_all_families():
    store = TimeSeriesMemStore(DEFAULT_SCHEMAS)
    ref = DatasetRef(SELFMON_DATASET)
    shard = store.setup(ref, 0, num_groups=2)
    sm = SelfMonitor(_fake_source, shard, interval_s=3600,
                     node="nodeX", flush_every_ticks=1)
    n = sm.collect_once(now_ms=T0 * 1000)
    # 2 counter series + 1 gauge + histogram (2 finite + Inf buckets +
    # sum + count = 5); the bad value is skipped
    assert n == 2 + 1 + 5
    from filodb_tpu.core.index import ColumnFilter
    parts = shard.lookup_partitions(
        [ColumnFilter("_metric_", "eq", "app_requests_total")],
        0, 1 << 62)
    labels = sorted(dict(p.part_key.labels)["code"] for p in parts)
    assert labels == ["200", "500"]
    for p in parts:
        lm = dict(p.part_key.labels)
        assert lm["_ws_"] == SELFMON_TENANT
        assert lm["_ns_"] == "nodeX"
        # counter families ingest under the counter schema (rate() gets
        # reset correction)
        assert p.schema.name == "prom-counter"
    (gp,) = shard.lookup_partitions(
        [ColumnFilter("_metric_", "eq", "app_temperature")], 0, 1 << 62)
    assert gp.schema.name == "gauge"
    # histogram children carried the le label through
    bucket_parts = shard.lookup_partitions(
        [ColumnFilter("_metric_", "eq", "app_lat_seconds_bucket")],
        0, 1 << 62)
    les = sorted(dict(p.part_key.labels)["le"] for p in bucket_parts)
    assert les == ["+Inf", "0.1", "1"]
    snap = sm.snapshot()
    assert snap["ticks"] == 1 and snap["samples_ingested"] == n
    assert snap["errors"] == 0


def test_worker_label_stamped():
    store = TimeSeriesMemStore(DEFAULT_SCHEMAS)
    ref = DatasetRef(SELFMON_DATASET)
    shard = store.setup(ref, 3, num_groups=2)
    sm = SelfMonitor(_fake_source, shard, interval_s=3600,
                     node="n", worker_id=3)
    sm.collect_once(now_ms=T0 * 1000)
    from filodb_tpu.core.index import ColumnFilter
    parts = shard.lookup_partitions(
        [ColumnFilter("_metric_", "eq", "app_temperature")], 0, 1 << 62)
    assert [dict(p.part_key.labels)["worker"] for p in parts] == ["3"]


def test_tick_is_idempotent_per_series_set():
    """Two ticks over the same families grow samples, not series —
    cardinality in the internal dataset is bounded by the metric
    surface, not by uptime."""
    store = TimeSeriesMemStore(DEFAULT_SCHEMAS)
    ref = DatasetRef(SELFMON_DATASET)
    shard = store.setup(ref, 0, num_groups=2)
    sm = SelfMonitor(_fake_source, shard, interval_s=3600,
                     flush_every_ticks=10)
    sm.collect_once(now_ms=T0 * 1000)
    count1 = shard.card_tracker.series_count(()) \
        if shard.card_tracker else None
    from filodb_tpu.core.index import ColumnFilter
    n_parts1 = len(shard.lookup_partitions([], 0, 1 << 62))
    sm.collect_once(now_ms=T0 * 1000 + 10_000)
    n_parts2 = len(shard.lookup_partitions([], 0, 1 << 62))
    assert n_parts1 == n_parts2
    assert count1 is None or count1 == shard.card_tracker.series_count(())


# ---------------------------------------------------------------------------
# e2e: the acceptance scenario
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def selfmon_server():
    # isolate the process-global metrics registry: earlier test modules
    # (test_qos's admission server, any HTTP e2e) leave lazily-created
    # families — e.g. filodb_query_latency_seconds with a nonzero count
    # — which the selfmon loop would ingest on its PRE-seed ticks,
    # making the assertions below depend on which module ran first in
    # this process. Families re-create lazily; collectors survive.
    obs_metrics.GLOBAL_REGISTRY.reset()
    srv = FiloServer({
        "num-shards": 2, "port": 0,
        "self-monitor": True,
        "self-monitor-interval-s": 0.25,
        "self-monitor-flush-ticks": 1,
        # a budgeted tenant so the per-tenant QoS families exist
        "qos-tenant-overrides": {"budgeted": [50, 200]},
        "tenant-metering-interval-s": 30,
    }).start()
    srv.seed_dev_data(n_samples=60, n_instances=3, start_ms=T0 * 1000)
    # serve queries so the latency histogram + tenant families populate
    for _ in range(2):
        _query_range(srv.port, "timeseries",
                     query="rate(http_requests_total[5m])",
                     start=T0 + 300, end=T0 + 500, step=60,
                     tenant="budgeted")
    yield srv
    srv.stop()


def _fresh_series(srv, metric, extra_q=()):
    """Range-query the internal dataset around now; retries briefly so
    the loop has ticked at least once."""
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        now = int(time.time())
        out = _query_range(
            srv.port, SELFMON_DATASET, query=metric,
            start=now - 60, end=now + 2, step=1,
            **dict(extra_q))
        assert out["status"] == "success"
        res = out["data"]["result"]
        if res:
            return res, now
        time.sleep(0.3)
    raise AssertionError(f"no fresh internal series for {metric}")


def test_selfmon_e2e_promql_over_own_metrics(selfmon_server):
    srv = selfmon_server
    # user-dataset cardinality BEFORE reading internal series
    user_counts = {
        sh: t.series_count(()) for sh, t in srv.card_trackers.items()}

    res, now = _fresh_series(srv, "filodb_query_latency_seconds_bucket")
    # real histogram series with le labels, values fresh (timestamps
    # within the query window ending now)
    les = {r["metric"].get("le") for r in res}
    assert "+Inf" in les and len(les) > 3
    for r in res:
        assert r["metric"]["_ws_"] == SELFMON_TENANT
        ts_last = float(r["values"][-1][0])
        assert now - 60 <= ts_last <= now + 2
    # the +Inf bucket must reflect the 2 seeded queries; the first
    # non-empty fetch can race a pre-seed tick, so poll the MONOTONE
    # counter until a post-seed tick lands (bounded)
    deadline = time.monotonic() + 15
    while True:
        inf_row = [r for r in res if r["metric"].get("le") == "+Inf"][0]
        if float(inf_row["values"][-1][1]) >= 2:
            break
        assert time.monotonic() < deadline, \
            f"+Inf bucket never reached the seeded count: {inf_row}"
        time.sleep(0.3)
        res, now = _fresh_series(
            srv, "filodb_query_latency_seconds_bucket")

    # one QoS tenant family, produced by the loop too
    res2, _ = _fresh_series(srv, "filodb_tenant_budget_remaining")
    tenants = {r["metric"].get("tenant") for r in res2}
    assert "budgeted" in tenants

    # internal series did NOT touch user-dataset cardinality
    for sh, t in srv.card_trackers.items():
        assert t.series_count(()) == user_counts[sh]
    # ...and the internal dataset has its own tracker with its own
    # (nonzero) counts, isolated under the reserved workspace
    sm_shards = srv.store.shards(DatasetRef(SELFMON_DATASET))
    assert sm_shards and sm_shards[0].card_tracker is not None
    assert sm_shards[0].card_tracker.series_count((SELFMON_TENANT,)) > 0


def test_selfmon_loop_health_rides_metrics(selfmon_server):
    srv = selfmon_server
    url = f"http://127.0.0.1:{srv.port}/metrics"
    with urllib.request.urlopen(url, timeout=60) as r:
        text = r.read().decode()
    assert "filodb_selfmon_ticks_total" in text
    assert "filodb_selfmon_alive 1" in text
    assert "filodb_selfmon_last_tick_age_seconds" in text
    # process-collector families ride every exposition (satellite)
    assert "filodb_process_resident_memory_bytes" in text
    assert "filodb_process_open_fds" in text
    assert "filodb_process_gc_collections_total" in text
    assert "filodb_process_uptime_seconds" in text
    assert 'filodb_build_info{' in text
    # the loop's own families become internal series on the next tick
    res, _ = _fresh_series(srv, "filodb_selfmon_ticks_total")
    vals = [float(v) for _t, v in res[0]["values"]]
    assert vals == sorted(vals) and vals[-1] >= 1  # monotone counter


def test_selfmon_user_responses_unchanged(selfmon_server):
    """Self-monitoring on must not perturb user-dataset responses: the
    data section matches a selfmon-off server byte-for-byte (modulo the
    wall-clock timings block)."""
    srv = selfmon_server
    plain = FiloServer({"num-shards": 2, "port": 0}).start()
    try:
        plain.seed_dev_data(n_samples=60, n_instances=3,
                            start_ms=T0 * 1000)
        # cache=false: both servers must evaluate fresh (the selfmon
        # fixture's earlier queries warmed ITS results cache, which is
        # legitimate state, not a selfmon artifact)
        q = dict(query="rate(http_requests_total[5m])",
                 start=T0 + 300, end=T0 + 500, step=60, cache="false")
        a = _query_range(srv.port, "timeseries", **q)
        b = _query_range(plain.port, "timeseries", **q)
        a["stats"].pop("timings", None)
        b["stats"].pop("timings", None)
        assert a == b
    finally:
        plain.stop()


# ---------------------------------------------------------------------------
# QoS: the reserved tenant charges FORCED (never bounces off a drained
# bucket) — the regression the satellite demands
# ---------------------------------------------------------------------------

@pytest.fixture()
def overloaded_server():
    srv = FiloServer({
        "num-shards": 2, "port": 0,
        # tiny budget for EVERY tenant (selfmon included): rate 1/s,
        # burst 5 — a single real query prices far above this
        "qos-tenant-rate": 1, "qos-tenant-burst": 5,
        "qos-shed-degraded": False,     # no ladder: over budget = 429
    }).start()
    srv.seed_dev_data(n_samples=120, n_instances=4, start_ms=T0 * 1000)
    yield srv
    srv.stop()


def test_selfmon_tenant_never_bounces_under_overload(overloaded_server):
    srv = overloaded_server
    q = dict(query="rate(http_requests_total[5m])",
             start=T0 + 300, end=T0 + 1100, step=10, cache="false")

    # sustained overload: the default tenant's bucket drains and its
    # queries bounce with 429
    saw_429 = False
    for _ in range(6):
        try:
            _query_range(srv.port, "timeseries", **q)
        except urllib.error.HTTPError as e:
            assert e.code == 429
            saw_429 = True
            e.read()
    assert saw_429, "overload harness never tripped the budget"

    # the reserved tenant keeps answering 200 — forced charges drive
    # its bucket into debt but never reject
    for _ in range(4):
        out = _query_range(srv.port, "timeseries",
                           tenant=SELFMON_TENANT, **q)
        assert out["status"] == "success"
        assert not any("shed(" in w for w in
                       out.get("warnings", []) or [])
    bucket = srv.http.admission.budgets.bucket(SELFMON_TENANT)
    assert bucket is not None
    assert bucket.forced_charges >= 4
    assert bucket.remaining() < 0          # deep in debt, still serving


def test_selfmon_tenant_runs_background_priority(overloaded_server):
    """No explicit priority + the reserved tenant = background class
    (self-telemetry must not preempt interactive work); an explicit
    priority hint still wins."""
    srv = overloaded_server
    from filodb_tpu.query import qos as qos_mod
    seen = {}
    orig = qos_mod.activate

    def spy(ctx):
        if ctx is not None:
            seen[ctx.tenant] = (ctx.priority, ctx.forced)
        return orig(ctx)
    qos_mod.activate = spy
    try:
        q = dict(query="rate(http_requests_total[5m])",
                 start=T0 + 300, end=T0 + 500, step=60)
        _query_range(srv.port, "timeseries", tenant=SELFMON_TENANT, **q)
        _query_range(srv.port, "timeseries", tenant=SELFMON_TENANT,
                     priority="interactive", **q)
    finally:
        qos_mod.activate = orig
    # last call wins in the dict; check both were observed
    assert seen[SELFMON_TENANT][1] is True          # forced either way
    # first call defaulted to background — re-run to capture separately
    seen.clear()
    qos_mod.activate = spy
    try:
        _query_range(srv.port, "timeseries", tenant=SELFMON_TENANT,
                     query="up", time=T0)
    finally:
        qos_mod.activate = orig
    prio, forced = seen[SELFMON_TENANT]
    assert prio == qos_mod.PRIORITY_BACKGROUND and forced
