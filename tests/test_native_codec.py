"""Native (C++) NibblePack codec: bit-parity with the Python oracle, and
batched-ingest equivalence with the per-row path.

(The native layer SURVEY §2.1 flags: the interchange wire format must be
identical from either implementation — NibblePackTest /
EncodingPropertiesTest are the reference's equivalents.)
"""

import numpy as np
import pytest

from filodb_tpu.core.memstore import TimeSeriesShard
from filodb_tpu.core.record import RecordBuilder
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetRef
from filodb_tpu.memory import nibblepack as nbp

pytestmark = pytest.mark.skipif(
    nbp._native is None, reason="native codec unavailable (no g++?)")


@pytest.mark.parametrize("seed", range(8))
def test_native_pack_bit_parity(seed):
    rng = np.random.default_rng(seed)
    n = int(rng.integers(0, 300))
    longs = np.sort(rng.integers(0, 1 << 50, n))
    a, b = bytearray(), bytearray()
    nbp.pack_delta(longs, a)
    nbp.pack_delta_py(longs, b)
    assert bytes(a) == bytes(b)

    u = rng.integers(0, 1 << 63, n).astype(np.uint64)
    a, b = bytearray(), bytearray()
    nbp.pack_non_increasing(u, a)
    nbp.pack_non_increasing_py(u, b)
    assert bytes(a) == bytes(b)

    d = rng.normal(0, 10.0 ** rng.integers(0, 7), max(n, 1))
    d[rng.integers(0, d.size, d.size // 10)] = np.nan
    a, b = bytearray(), bytearray()
    nbp.pack_doubles(d, a)
    nbp.pack_doubles_py(d, b)
    assert bytes(a) == bytes(b)


@pytest.mark.parametrize("seed", range(4))
def test_native_unpack_matches_python(seed):
    rng = np.random.default_rng(seed + 50)
    n = int(rng.integers(1, 300))
    longs = np.sort(rng.integers(0, 1 << 50, n))
    buf = bytearray()
    nbp.pack_delta_py(longs, buf)
    got, p1 = nbp.unpack_delta(bytes(buf), 0, n)
    exp, p2 = nbp.unpack_delta_py(bytes(buf), 0, n)
    assert p1 == p2
    np.testing.assert_array_equal(got, exp)

    d = rng.normal(size=n)
    buf = bytearray()
    nbp.pack_doubles_py(d, buf)
    got, p1 = nbp.unpack_double_xor(bytes(buf), 0, n)
    np.testing.assert_array_equal(got, d)
    assert p1 == len(buf)


def test_native_unpack_short_input_raises():
    buf = bytearray()
    nbp.pack_delta(np.arange(100, dtype=np.int64) * 1000, buf)
    with pytest.raises(nbp.InputTooShort):
        nbp.unpack_delta(bytes(buf[: len(buf) // 2]), 0, 100)


# --- batched ingest equivalence -------------------------------------------

REF = DatasetRef("timeseries")


def _shard():
    return TimeSeriesShard(REF, DEFAULT_SCHEMAS, 0, max_chunk_rows=100)


def _container(ts_rows):
    b = RecordBuilder(DEFAULT_SCHEMAS)
    for s, ts_list in enumerate(ts_rows):
        labels = {"_metric_": "cpu", "_ws_": "demo", "_ns_": "App-0",
                  "instance": f"i{s}"}
        for t in ts_list:
            b.add_sample("gauge", labels, int(t), float(t) * 0.5)
    return b.containers()


@pytest.mark.parametrize("shape", ["sorted", "ooo", "dup", "interleaved"])
def test_batched_ingest_matches_per_row(shape):
    rng = np.random.default_rng(hash(shape) % (1 << 31))
    rows = []
    for s in range(3):
        ts = 1_000_000 + np.arange(250) * 1000
        if shape == "ooo":
            ts = ts.copy()
            ts[50:60] = ts[50:60][::-1]
        elif shape == "dup":
            ts = np.repeat(ts, 2)[:250]
        elif shape == "interleaved":
            ts = np.sort(rng.choice(ts, 200, replace=False))
        rows.append(ts)
    conts = _container(rows)

    batched = _shard()
    for c in conts:
        batched.ingest(c)

    perrow = _shard()
    for c in conts:
        for row in c.rows():
            part = perrow.get_or_create_partition(row.part_key,
                                                  row.timestamp)
            if part.ingest(row.timestamp, row.values):
                perrow.index.update_end_time(part.part_id, row.timestamp)

    for pid, part in batched.partitions.items():
        other = perrow.partitions[pid]
        ts_a, v_a, _ = part.read_full(1)
        ts_b, v_b, _ = other.read_full(1)
        np.testing.assert_array_equal(ts_a, ts_b)
        np.testing.assert_array_equal(v_a, v_b)
        assert part.ooo_dropped + batched.stats.out_of_order_dropped >= 0


def test_batched_ingest_chunk_rollover_sizes():
    """Chunks must still cap at max_chunk_rows when a run overshoots."""
    shard = _shard()
    for c in _container([1_000_000 + np.arange(350) * 1000]):
        shard.ingest(c)
    part = next(iter(shard.partitions.values()))
    assert [ch.num_rows for ch in part.chunks] == [100, 100, 100]
    assert part._buf_rows == 50
