"""Prometheus text-format compliance of the whole /metrics exposition:
a validator that parses every line of a live server's scrape and
enforces what a real Prometheus scraper requires — # HELP/# TYPE per
family, valid metric/label names, consistent escaping, no duplicate
series, and well-formed histograms (cumulative buckets, +Inf == _count).
"""

import re
import urllib.request

import pytest

from filodb_tpu.standalone.server import FiloServer

T0 = 1_600_000_000

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
# one sample line: name, optional {labels}, value
_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?"
    r" (?P<value>[^ ]+)$")
_LABEL_RE = re.compile(
    r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def parse_exposition(text):
    """Parse + validate; returns {family: (type, [(name, labels, value)])}.
    Raises AssertionError on any format violation."""
    families = {}       # family -> [type, help, samples]
    seen_series = set()
    current = None
    for i, ln in enumerate(text.splitlines(), 1):
        if not ln.strip():
            continue
        if ln.startswith("# HELP "):
            parts = ln.split(" ", 3)
            assert len(parts) >= 3, f"line {i}: malformed HELP: {ln!r}"
            fam = parts[2]
            assert _NAME_RE.match(fam), f"line {i}: bad family {fam!r}"
            assert fam not in families, \
                f"line {i}: duplicate HELP block for {fam}"
            families[fam] = ["untyped", parts[3] if len(parts) > 3
                             else "", []]
            current = fam
            continue
        if ln.startswith("# TYPE "):
            parts = ln.split(" ")
            assert len(parts) == 4, f"line {i}: malformed TYPE: {ln!r}"
            fam, mtype = parts[2], parts[3]
            assert fam == current, \
                f"line {i}: TYPE for {fam} outside its HELP block"
            assert mtype in ("counter", "gauge", "histogram", "summary",
                             "untyped"), f"line {i}: bad type {mtype}"
            families[fam][0] = mtype
            continue
        assert not ln.startswith("#"), f"line {i}: stray comment {ln!r}"
        m = _SAMPLE_RE.match(ln)
        assert m, f"line {i}: unparseable sample line {ln!r}"
        name = m.group("name")
        # the sample must belong to the CURRENT family block (histogram
        # children use the family prefix)
        assert current is not None and (
            name == current or name.startswith(current + "_")), \
            f"line {i}: sample {name} outside family block {current}"
        labels = []
        raw = m.group("labels")
        if raw is not None:
            assert raw != "", f"line {i}: empty label braces in {ln!r}"
            consumed = _LABEL_RE.sub("", raw).strip(",")
            assert consumed == "", \
                f"line {i}: malformed labels {raw!r} (left: {consumed!r})"
            labels = _LABEL_RE.findall(raw)
        float(m.group("value").replace("+Inf", "inf")
              .replace("-Inf", "-inf").replace("NaN", "nan"))
        key = (name, tuple(sorted(labels)))
        assert key not in seen_series, f"line {i}: duplicate series {key}"
        seen_series.add(key)
        families[current][2].append((name, dict(labels),
                                     m.group("value")))
    return {fam: (t, samples) for fam, (t, _h, samples)
            in families.items()}


def validate_histograms(families):
    hists = 0
    for fam, (mtype, samples) in families.items():
        if mtype != "histogram":
            continue
        hists += 1
        buckets = [(s[1]["le"], float(s[2])) for s in samples
                   if s[0] == fam + "_bucket"]
        counts = [float(s[2]) for s in samples if s[0] == fam + "_count"]
        sums = [s for s in samples if s[0] == fam + "_sum"]
        assert buckets and counts and sums, f"{fam}: missing children"
        assert buckets[-1][0] == "+Inf", f"{fam}: no +Inf bucket"
        vals = [v for _, v in buckets]
        assert vals == sorted(vals), f"{fam}: non-cumulative buckets"
        les = [float(le.replace("+Inf", "inf")) for le, _ in buckets]
        assert les == sorted(les), f"{fam}: unsorted le boundaries"
        assert buckets[-1][1] == counts[0], \
            f"{fam}: +Inf bucket != _count"
    return hists


@pytest.fixture(scope="module")
def server():
    srv = FiloServer({"num-shards": 2, "port": 0,
                      "tenant-metering-interval-s": 30}).start()
    srv.seed_dev_data(n_samples=60, n_instances=3, start_ms=T0 * 1000)
    # serve one query so the query/batcher/device histograms exist
    url = (f"http://127.0.0.1:{srv.port}/promql/timeseries/api/v1/"
           f"query_range?query=rate(http_requests_total[5m])"
           f"&start={T0 + 300}&end={T0 + 500}&step=60")
    urllib.request.urlopen(url, timeout=60).read()
    yield srv
    srv.stop()


def test_whole_exposition_parses_and_validates(server):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/metrics", timeout=30) as r:
        text = r.read().decode()
    families = parse_exposition(text)
    assert len(families) > 20
    # counters are typed counter, gauges gauge
    assert families["filodb_plan_cache_hits_total"][0] == "counter"
    assert families["filodb_shard_status"][0] == "gauge"
    # the acceptance histograms are present and well-formed
    for fam in ("filodb_query_latency_seconds",
                "filodb_batcher_queue_wait_seconds",
                "filodb_device_execute_seconds"):
        assert fam in families and families[fam][0] == "histogram", fam
    assert validate_histograms(families) >= 3


def test_registry_wide_histogram_validator_clean_on_live(server):
    """The library validator (obs.metrics.validate_histogram_families)
    over the FULL live exposition: every histogram family — per label
    set — has cumulative buckets, +Inf == _count, and an emitted _sum.
    The tier-1 pin for the self-consistency satellite."""
    from filodb_tpu.obs.metrics import validate_histogram_families
    with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/metrics", timeout=30) as r:
        text = r.read().decode()
    assert validate_histogram_families(text) == []


def test_histogram_validator_flags_violations():
    from filodb_tpu.obs.metrics import validate_histogram_families
    base = ("# HELP h x\n# TYPE h histogram\n"
            'h_bucket{le="0.1"} 5\n'
            'h_bucket{le="1"} 3\n'            # NOT cumulative
            'h_bucket{le="+Inf"} 9\n'
            "h_count 8\n")                     # +Inf != count, no _sum
    v = validate_histogram_families(base)
    assert any("not cumulative" in m for m in v)
    assert any("+Inf bucket" in m for m in v)
    assert any("_sum not emitted" in m for m in v)
    # missing +Inf
    v2 = validate_histogram_families(
        "# HELP h x\n# TYPE h histogram\n"
        'h_bucket{le="1"} 3\nh_count 3\nh_sum 1.5\n')
    assert any("no +Inf bucket" in m for m in v2)
    # clean twin per label set
    clean = ("# HELP h x\n# TYPE h histogram\n"
             'h_bucket{t="a",le="0.1"} 2\n'
             'h_bucket{t="a",le="+Inf"} 4\n'
             'h_count{t="a"} 4\nh_sum{t="a"} 0.5\n'
             'h_bucket{t="b",le="0.1"} 1\n'
             'h_bucket{t="b",le="+Inf"} 1\n'
             'h_count{t="b"} 1\nh_sum{t="b"} 0.1\n')
    assert validate_histogram_families(clean) == []


def test_registry_walk_matches_rendered_text(server):
    """ExpositionBuilder.families() — the structural walk the
    self-monitoring loop reads — agrees sample-for-sample with the
    rendered /metrics text."""
    builder = server.http.build_exposition()
    walked = [(name, labels)
              for _fam, _mt, _help, samples in builder.families()
              for name, labels, _v in samples]
    families = parse_exposition(builder.render())
    rendered = [(name, labels)
                for _fam, (_mt, samples) in families.items()
                for name, labels, _v in samples]
    # same sample count (the walk dedupes exactly like render) and the
    # same sample-name universe
    assert len(walked) == len(set(walked)) == len(rendered)
    assert {n for n, _ in walked} == {n for n, _ in rendered}


def test_label_escaping_survives_hostile_values(server):
    # a label value with quote/backslash/newline must stay parseable
    from filodb_tpu.obs.metrics import ExpositionBuilder
    b = ExpositionBuilder()
    b.sample("filodb_t", {"p": 'x"\\\n'}, 1)
    families = parse_exposition(b.render())
    ((_, labels, _),) = families["filodb_t"][1]
    assert labels["p"] == 'x\\"\\\\\\n'     # escaped on the wire


def test_merge_preserves_exemplars_and_is_idempotent():
    """The supervisor merge passes OpenMetrics exemplar suffixes
    through unmangled — the worker label lands on the LABELS, never
    inside the exemplar — and re-merging an already-merged
    exemplar-bearing payload is a no-op (supervisor-of-supervisors)."""
    from filodb_tpu.obs.metrics import merge_expositions
    w0 = (
        "# HELP filodb_query_latency_seconds Latency\n"
        "# TYPE filodb_query_latency_seconds histogram\n"
        'filodb_query_latency_seconds_bucket{le="0.001"} 2'
        ' # {trace_id="aabbccdd00112233"} 0.0004 1700000000.123\n'
        'filodb_query_latency_seconds_bucket{le="+Inf"} 3'
        ' # {trace_id="ffee001122334455"} 2.5 1700000001.5\n'
        "filodb_query_latency_seconds_sum 2.51\n"
        "filodb_query_latency_seconds_count 3\n")
    w1 = (
        "# HELP filodb_query_latency_seconds Latency\n"
        "# TYPE filodb_query_latency_seconds histogram\n"
        'filodb_query_latency_seconds_bucket{le="0.001"} 1\n'
        'filodb_query_latency_seconds_bucket{le="+Inf"} 1\n'
        "filodb_query_latency_seconds_sum 0.0002\n"
        "filodb_query_latency_seconds_count 1\n")
    merged = merge_expositions({"0": w0, "1": w1})
    assert ('filodb_query_latency_seconds_bucket'
            '{le="0.001",worker="0"} 2'
            ' # {trace_id="aabbccdd00112233"} 0.0004 1700000000.123'
            ) in merged.splitlines()
    # the exemplar-less worker gains no suffix
    assert ('filodb_query_latency_seconds_bucket'
            '{le="0.001",worker="1"} 1') in merged.splitlines()
    again = merge_expositions({"sup": merged})
    assert again == merged
