"""Regression tests for the true positives graftlint v2 found on the
tree, plus the runtime thread inventory (/debug/threads).

The PR 2 pattern: every bug the analysis catches gets a test pinning
the fix, and where the fix is "this mutation now rides that lock" the
interprocedural engine itself is the assertion vehicle — it recomputes
held-lock sets on the REAL modules, so a regression (someone moves the
mutation out of the lock) fails here before the full lint gate runs.
"""

import json
import os
import threading
import urllib.request

from filodb_tpu.lint import iter_py_files, load_module, package_root
from filodb_tpu.lint import callgraph as cgm
from filodb_tpu.parallel.shardmapper import ShardMapper, ShardStatus


def _package_graph():
    root = package_root()
    mods = [m for m in (load_module(p, root=root) for p in iter_py_files(
        [os.path.join(root, "filodb_tpu")])) if m is not None]
    return cgm.build(mods)


# -- ShardMapper topology-epoch race (found by
#    thread-unguarded-shared-state: `_epoch += 1` raced between the
#    failure-detector poll thread, ingest drivers, membership workers,
#    and HTTP admin threads; a lost bump = two topologies sharing an
#    epoch = plan/results caches serving across an ownership rewire) --------

def test_topology_epoch_concurrent_updates_lose_no_bumps():
    mapper = ShardMapper(4)
    n_threads, n_updates = 4, 250

    def spin(tid):
        for i in range(n_updates):
            # every update names a brand-new node, so each one rewires
            # ownership and MUST bump the epoch exactly once
            mapper.update(0, ShardStatus.ACTIVE, node=f"n{tid}-{i}")

    ths = [threading.Thread(target=spin, args=(t,))
           for t in range(n_threads)]
    for t in ths:
        t.start()
    for t in ths:
        t.join()
    assert mapper.topology_epoch == n_threads * n_updates


def test_mapper_epoch_is_declared_guarded():
    assert ShardMapper.__guarded_by__.get("_epoch") == "_lock"


def test_mapper_publish_runs_outside_the_lock():
    """Subscribers (plan/results-cache invalidation) take their own
    locks — _publish under the mapper lock would nest them under it.
    The engine proves the callback runs lock-free."""
    cg = _package_graph()
    fi = cg.funcs["filodb_tpu.parallel.shardmapper:ShardMapper.update"]
    publish_sites = [s for s in fi.sites
                     if any("ShardMapper._publish" in c
                            for c in s.callees)]
    assert publish_sites, "update() no longer calls _publish?"
    for s in publish_sites:
        assert not s.held, "publish must not run under the mapper lock"


# -- FiloServer shard-registry maps (drivers/streams/card_trackers):
#    mutated from adopt/release/handback/drain worker threads — every
#    compound mutation must ride FiloServer._reassign_lock ------------------

def _mutation_sites(cg, target):
    out = []
    for fi in cg.funcs.values():
        for m in fi.mutations:
            if m.target == target:
                out.append((fi, m))
    return out


def test_driver_registry_mutations_ride_reassign_lock():
    cg = _package_graph()
    for target in ("FiloServer.drivers", "FiloServer.streams",
                   "FiloServer.card_trackers"):
        sites = _mutation_sites(cg, target)
        assert sites, f"no mutations of {target} found — renamed?"
        for fi, m in sites:
            held = set(m.held) | set(cg.must_held.get(fi.key, ()))
            assert "FiloServer._reassign_lock" in held, (
                f"{target} mutated without _reassign_lock at "
                f"{fi.relpath}:{m.line} ({fi.qualname})")


def test_handoff_sources_mutations_ride_membership_lock():
    cg = _package_graph()
    sites = _mutation_sites(cg, "FiloHttpServer.handoff_sources")
    assert sites, "no handoff_sources mutations found — renamed?"
    for fi, m in sites:
        held = set(m.held) | set(cg.must_held.get(fi.key, ()))
        assert "MembershipManager._lock" in held, (
            f"handoff_sources mutated without the membership lock at "
            f"{fi.relpath}:{m.line} ({fi.qualname})")


def test_memstore_shard_map_mutations_ride_shards_lock():
    cg = _package_graph()
    sites = _mutation_sites(cg, "TimeSeriesMemStore._shards")
    assert sites, "no _shards mutations found — renamed?"
    for fi, m in sites:
        held = set(m.held) | set(cg.must_held.get(fi.key, ()))
        assert "TimeSeriesMemStore._shards_lock" in held, (
            f"_shards mutated without _shards_lock at "
            f"{fi.relpath}:{m.line} ({fi.qualname})")


# -- thread inventory ---------------------------------------------------------

def test_thread_root_registry_and_inventory():
    from filodb_tpu.lint.threads import THREAD_ROOTS, thread_inventory
    # importing the subsystems registers their roots
    import filodb_tpu.core.metering           # noqa: F401
    import filodb_tpu.http.server             # noqa: F401
    import filodb_tpu.ingest.driver           # noqa: F401
    import filodb_tpu.parallel.cluster        # noqa: F401
    import filodb_tpu.parallel.membership     # noqa: F401
    import filodb_tpu.query.batcher           # noqa: F401
    names = {v["name"] for v in THREAD_ROOTS.values()}
    assert {"failure-detector", "tenant-metering", "device-executor",
            "ingest-shard", "adopt-shard", "handback",
            "http-handler"} <= names
    inv = thread_inventory()
    by_name = {e["name"]: e for e in inv}
    assert "failure-detector" in by_name
    e = by_name["failure-detector"]
    assert e["root"].endswith("FailureDetector._run")
    assert isinstance(e["guards"], dict)
    assert isinstance(e["live_threads"], list)


def test_debug_threads_endpoint():
    from filodb_tpu.standalone.server import FiloServer
    srv = FiloServer({"num-shards": 2, "port": 0}).start()
    try:
        srv.seed_dev_data(n_samples=4, n_instances=2)
        url = f"http://127.0.0.1:{srv.port}/debug/threads"
        body = json.loads(urllib.request.urlopen(url, timeout=30).read())
        assert body["status"] == "success"
        roots = {e["name"]: e for e in body["data"]}
        # the handler root serving THIS request is registered and the
        # guard summary of an annotated class resolves
        assert "http-handler" in roots
        assert "tenant-metering" in roots
    finally:
        srv.stop()
