"""Chunk vector codec tests (parity model: memory/src/test/.../ —
EncodingPropertiesTest.scala round-trips, DoubleVectorTest counter cases)."""

import numpy as np
import pytest

from filodb_tpu.memory import histogram as bh
from filodb_tpu.memory import vectors as bv


def test_regular_timestamps_become_const():
    ts = np.arange(0, 720_0000, 10_000, dtype=np.int64) + 1_600_000_000_000
    buf = bv.encode_longs(ts)
    kind, n = bv.parse_header(buf)
    assert kind == bv.K_TS_CONST
    assert n == ts.size
    assert len(buf) == 21  # header + init + slope: perfectly regular collapses
    np.testing.assert_array_equal(bv.decode_longs(buf), ts)


def test_jittered_timestamps_roundtrip():
    rng = np.random.default_rng(0)
    ts = 1_600_000_000_000 + np.cumsum(rng.integers(9_000, 11_000, 500))
    buf = bv.encode_longs(ts.astype(np.int64))
    np.testing.assert_array_equal(bv.decode_longs(buf), ts)
    # delta-delta should compress well: < 2.5 bytes/sample for jittered 10s data
    assert len(buf) / ts.size < 2.5


def test_doubles_roundtrip_and_const():
    vals = np.array([3.0, 3.0, 3.0, 3.0])
    buf = bv.encode_doubles(vals)
    assert bv.parse_header(buf)[0] == bv.K_DOUBLE_CONST
    np.testing.assert_array_equal(bv.decode_doubles(buf), vals)

    rng = np.random.default_rng(1)
    vals = rng.normal(100, 15, 300)
    buf = bv.encode_doubles(vals)
    np.testing.assert_array_equal(bv.decode_doubles(buf), vals)


def test_integral_doubles_use_long_encoding():
    vals = np.cumsum(np.ones(100)) * 5  # 5, 10, ... integral increasing
    buf = bv.encode_doubles(vals, counter=True)
    assert bv.parse_header(buf)[0] == bv.K_LONG_AS_DOUBLE
    assert bv.is_counter_vector(buf)
    np.testing.assert_array_equal(bv.decode_doubles(buf), vals)


def test_nan_doubles_roundtrip():
    vals = np.array([1.0, np.nan, 3.0, np.nan, 5.0])
    buf = bv.encode_doubles(vals)
    got = bv.decode_doubles(buf)
    np.testing.assert_array_equal(np.isnan(got), np.isnan(vals))
    np.testing.assert_array_equal(got[~np.isnan(vals)], vals[~np.isnan(vals)])


def test_counter_correction_detects_resets():
    # counter goes up, resets to low value, continues
    vals = np.array([10.0, 20, 30, 5, 15, 25, 2, 12])
    corr = bv.counter_correction(vals)
    corrected = vals + corr
    # after first reset add 30, after second add 30+25
    np.testing.assert_array_equal(
        corrected, [10, 20, 30, 35, 45, 55, 57, 67])
    assert np.all(np.diff(corrected) >= 0)


def test_counter_correction_ignores_nans():
    vals = np.array([10.0, np.nan, 30, 5])
    corr = bv.counter_correction(vals)
    assert corr[-1] == 30.0


def test_histogram_2d_roundtrip():
    scheme = bh.GeometricBuckets(2.0, 2.0, 8)
    rng = np.random.default_rng(2)
    incr = rng.integers(0, 50, size=(20, 8))
    rows = np.cumsum(np.cumsum(incr, axis=0), axis=1)  # increasing in t & bucket
    buf = bh.encode_histograms(scheme, rows)
    got_scheme, counter, got = bh.decode_histograms(buf)
    assert got_scheme == scheme
    assert counter
    np.testing.assert_array_equal(got, rows.astype(np.float64))


def test_histogram_custom_buckets_roundtrip():
    scheme = bh.CustomBuckets((0.5, 1.0, 2.5, 10.0, float("inf")))
    rows = np.array([[1, 3, 5, 7, 9], [2, 4, 6, 9, 12]], dtype=np.int64)
    buf = bh.encode_histograms(scheme, rows, counter=False)
    got_scheme, counter, got = bh.decode_histograms(buf)
    assert got_scheme.les().tolist()[:4] == [0.5, 1.0, 2.5, 10.0]
    assert not counter
    np.testing.assert_array_equal(got, rows)


def test_histogram_reset_correction():
    rows = np.array([[5, 10], [8, 16], [1, 2], [4, 8]], dtype=np.float64)
    corr = bh.hist_counter_correction(rows)
    corrected = rows + corr
    np.testing.assert_array_equal(corrected[-1], [12, 24])


def test_histogram_quantile_interpolation():
    les = np.array([1.0, 2.0, 4.0, np.inf])
    counts = np.array([0.0, 10.0, 10.0, 10.0])
    # all observations fall in (1, 2]; median interpolates to 1.5
    assert bh.quantile(0.5, les, counts) == pytest.approx(1.5)
    # q=1 returns the upper bound of the bucket containing the last observation
    assert bh.quantile(1.0, les, counts) == pytest.approx(2.0)
    # empty histogram -> NaN
    assert np.isnan(bh.quantile(0.5, les, np.zeros(4)))
