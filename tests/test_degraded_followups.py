"""PR-1 follow-ups: breaker/retry counters in /metrics, and
partial+warnings over the gRPC exec wire (matching the HTTP plane)."""

import numpy as np
import pytest

from filodb_tpu.grpcsvc import wire
from filodb_tpu.parallel.resilience import (BreakerRegistry, RetryPolicy,
                                            TransportError, resilient_call)
from filodb_tpu.query.model import GridResult, QueryStats


# -- breaker/retry counters --------------------------------------------------

def test_registry_counts_attempts_retries_exhaustions():
    reg = BreakerRegistry(failure_threshold=10)

    def always_down(timeout_s):
        raise TransportError("nope")

    with pytest.raises(TransportError):
        resilient_call(always_down, key="peer:1", node_id="n1",
                       timeout_s=1.0, retry=RetryPolicy(max_attempts=3),
                       breakers=reg, sleep=lambda s: None)
    snap = reg.metrics_snapshot()["peer:1"]
    assert snap["attempts"] == 3
    assert snap["retries"] == 2
    assert snap["exhaustions"] == 1
    assert snap["state"] == "closed"    # threshold 10 not reached


def test_registry_counts_breaker_rejections():
    from filodb_tpu.parallel.resilience import BreakerOpenError
    reg = BreakerRegistry(failure_threshold=1, reset_timeout_s=60.0)

    def always_down(timeout_s):
        raise TransportError("nope")

    with pytest.raises(TransportError):
        resilient_call(always_down, key="p", node_id="n",
                       timeout_s=1.0, retry=RetryPolicy(max_attempts=1),
                       breakers=reg, sleep=lambda s: None)
    with pytest.raises(BreakerOpenError):
        resilient_call(always_down, key="p", node_id="n",
                       timeout_s=1.0, breakers=reg, sleep=lambda s: None)
    snap = reg.metrics_snapshot()["p"]
    assert snap["state"] == "open"
    assert snap["rejections"] == 1


def test_metrics_exposition_includes_breaker_and_retry_counters():
    from filodb_tpu.http.server import FiloHttpServer
    from filodb_tpu.parallel.resilience import PeerResilience
    reg = BreakerRegistry()
    reg.record("peer:9", "attempts", 4)
    reg.record("peer:9", "retries", 2)
    reg.get("peer:9")           # materialize a breaker (closed)
    srv = FiloHttpServer({"ds": []},
                         resilience=PeerResilience(RetryPolicy(), reg))
    try:
        text = srv._metrics_text()
    finally:
        srv.httpd.server_close()
    assert 'filodb_breaker_state{peer="peer:9",state="closed"} 1' in text
    assert 'filodb_peer_call_attempts_total{peer="peer:9"} 4' in text
    assert 'filodb_peer_call_retries_total{peer="peer:9"} 2' in text


# -- partial/warnings over the gRPC exec wire --------------------------------

def _grid(partial=False, warnings=()):
    return GridResult(np.array([1000, 2000], np.int64),
                      [{"job": "a"}], np.array([[1.0, 2.0]]),
                      partial=partial, warnings=list(warnings))


def test_exec_wire_roundtrip_partial_warnings():
    st = QueryStats()
    st.partial = True
    st.warnings = ["shard group 2 dropped (breaker open)"]
    buf = wire.encode_exec_response(
        _grid(partial=False, warnings=["adopter still bootstrapping"]),
        stats=st)
    _, _, _, _, _, stats, err = wire.decode_exec_response(buf)
    assert not err
    assert stats["partial"] is True
    assert stats["warnings"] == ["adopter still bootstrapping",
                                 "shard group 2 dropped (breaker open)"]


def test_exec_wire_clean_response_has_no_markers():
    buf = wire.encode_exec_response(_grid(), stats=QueryStats())
    _, _, _, _, _, stats, err = wire.decode_exec_response(buf)
    assert stats["partial"] is False and stats["warnings"] == []


def test_grpc_remote_exec_propagates_markers(monkeypatch):
    from filodb_tpu.grpcsvc import client as gclient
    payload = wire.encode_exec_response(
        _grid(partial=True, warnings=["peer n2: shard 1 missing"]),
        stats=QueryStats())
    monkeypatch.setattr(
        gclient, "_call",
        lambda addr, method, body, timeout_s, node_id: payload)
    st = QueryStats()
    ex = gclient.GrpcRemoteExec(
        "sum(x)", 1000, 1000, 2000, node_id="n2",
        addr="127.0.0.1:1", dataset="ds", stats=st)
    grid = ex.execute()
    assert grid.partial is True
    assert grid.warnings == ["peer n2: shard 1 missing"]
    assert st.partial is True
    assert st.warnings == ["peer n2: shard 1 missing"]
    # the HTTP envelope then surfaces them, same as the HTTP plane
    from filodb_tpu.http import prom_json
    out = prom_json.attach_degraded(
        prom_json.matrix(grid), grid, st)
    assert out["partial"] is True
    assert out["warnings"] == ["peer n2: shard 1 missing"]
