"""graftlint v5 capacity-certification rail: every @capacity residency
claim in the tree is dynamically certified (live-buffer walk against
the declared bytes budget), sharded claims run at 1/2/4/8 virtual
devices, and a LYING claim — the mutated twin — is flagged by the
rail. The annotations are real production claims; these tests make the
rail's teeth non-vacuous."""

import math

import pytest

from filodb_tpu.lint import capacity as cmod
from filodb_tpu.lint import memcert


@pytest.fixture(scope="module")
def results():
    return {r.name: r for r in memcert.certify_all()}


def test_every_tree_claim_is_certified(results):
    """Every @capacity claim registered by the engine modules
    certifies against its declared bytes budget."""
    cmod.import_annotated_modules()
    assert cmod.CAPACITY, "annotations disappeared"
    for name in cmod.CAPACITY:
        assert name in results, f"claim {name!r} never certified"
        r = results[name]
        assert r.ok, (f"claim {name!r} failed certification: "
                      f"measured {r.measured} vs {r.claimed} "
                      f"({r.detail})")


def test_expected_claim_inventory(results):
    """The resident inventory the issue names is all annotated — the
    shardstore slot-major channels, the tilestore aligned tiles, the
    packed-executable constants, the backend tile cache, and the
    downsample staging buffers."""
    assert {"shardstore-resident-channels", "tilestore-aligned-tiles",
            "tilestore-executable-constants", "device-tile-cache",
            "downsample-pack-buffers"} <= set(cmod.CAPACITY)


def test_sharded_claim_ran_at_1_2_4_8_devices(results):
    """The acceptance pin: shard-alignment padding is priced at every
    mesh width, not vacuously at one count."""
    r = results["shardstore-resident-channels"]
    assert r.device_counts == (1, 2, 4, 8), r.device_counts


def test_measured_bytes_are_real_and_tight(results):
    """The claims are tight-but-honest: the walk measures real live
    buffers (nonzero) and the claim sits within the 1.25x band."""
    for name, r in results.items():
        assert 0 < r.measured <= r.claimed <= \
            memcert.OVERCLAIM_RATIO * r.measured, (name, r)
    # the shardstore channels price 20 B per padded slot exactly
    st = results["shardstore-resident-channels"]
    assert st.measured == st.claimed == 20 * st.n_samples


def test_mutated_twin_understated_claim_is_flagged():
    """THE teeth test: register a claim smaller than the store it
    covers; the rail must fail it and surface a capacity-certification
    finding. Restores the registry and the memo so the surrounding
    suite sees the clean world."""
    saved_memo = memcert._MEMO
    claim = cmod.CapacityClaim(
        name="lying-claim", bytes_per_sample=1.0,
        reason="deliberately understates the store",
        module="filodb_tpu.query.tilestore", qualname="lying")

    def lying_harness():
        # the "store" holds 4096 device bytes but the claim covers
        # 64 x 1 B — residency above budget
        return 4096, 64, 1

    cmod.CAPACITY["lying-claim"] = claim
    memcert.HARNESSES["lying-claim"] = lying_harness
    try:
        res = {r.name: r for r in memcert.certify_all(force=True)}
        r = res["lying-claim"]
        assert not r.ok and r.measured > r.claimed
        findings = memcert.check_certifications()
        assert any(f.rule == "capacity-certification"
                   and "lying-claim" in f.message
                   for _rel, f in findings)
    finally:
        del cmod.CAPACITY["lying-claim"]
        del memcert.HARNESSES["lying-claim"]
        memcert._MEMO = saved_memo


def test_mutated_twin_slack_claim_is_flagged():
    """A claim padding more than 25% over the measured footprint fails
    too — slack claims hide regressions the way slack ULP tolerances
    do."""
    saved_memo = memcert._MEMO
    claim = cmod.CapacityClaim(
        name="slack-claim", bytes_per_sample=1000.0,
        reason="pads 1000x over reality",
        module="filodb_tpu.query.tilestore", qualname="slack")
    cmod.CAPACITY["slack-claim"] = claim
    memcert.HARNESSES["slack-claim"] = lambda: (64, 64, 1)
    try:
        res = {r.name: r for r in memcert.certify_all(force=True)}
        r = res["slack-claim"]
        assert not r.ok and r.claimed > \
            memcert.OVERCLAIM_RATIO * r.measured
    finally:
        del cmod.CAPACITY["slack-claim"]
        del memcert.HARNESSES["slack-claim"]
        memcert._MEMO = saved_memo


def test_claim_without_harness_is_flagged():
    """An annotation the rail cannot evaluate is itself a failure —
    future resident stores must ship a harness with the claim."""
    saved_memo = memcert._MEMO
    claim = cmod.CapacityClaim(
        name="orphan-claim", bytes_per_sample=8.0, reason="no harness",
        module="filodb_tpu.query.tilestore", qualname="orphan")
    cmod.CAPACITY["orphan-claim"] = claim
    try:
        res = {r.name: r for r in memcert.certify_all(force=True)}
        r = res["orphan-claim"]
        assert not r.ok and "no certification harness" in r.detail
        assert not math.isfinite(r.measured)
    finally:
        del cmod.CAPACITY["orphan-claim"]
        memcert._MEMO = saved_memo


def test_device_bytes_walk_dedups_aliases():
    """Aliased references to one buffer count once; host numpy does
    not count at all."""
    import jax.numpy as jnp
    import numpy as np
    arr = jnp.zeros((64,), jnp.float64)

    class Box:
        pass

    b = Box()
    b.a = arr
    b.alias = arr
    b.host = np.zeros((1024,))
    b.nest = {"again": [arr, (arr,)]}
    assert memcert.device_bytes(b) == arr.nbytes


def test_capacity_ledger_rows(results):
    """The ledger renders one certified row per family with the
    projected resident series per 16 GB chip — the baseline the
    compressed-chunks work must move."""
    rows = {row["family"]: row for row in memcert.capacity_ledger()}
    assert set(rows) == set(cmod.CAPACITY)
    st = rows["shardstore-resident-channels"]
    assert st["certified"] and st["sharded"]
    assert st["measured_bytes"] == results[
        "shardstore-resident-channels"].measured
    assert st["projected_series_per_chip_16gb"] == \
        (16 << 30) // (20 * 2880)
    assert st["device_counts"] == [1, 2, 4, 8]


def test_certification_rides_the_lint_gate():
    """run_lint (full, contracts on) carries capacity-certification
    findings — the rail IS tier-1, via tests/test_lint_clean.py."""
    from filodb_tpu.lint import rules
    cat = rules()
    assert cat["capacity-certification"].severity == "error"
    assert cat["capacity-certification"].family == "capacity"


def test_v5_families_registered_at_error():
    from filodb_tpu.lint import rules
    cat = rules()
    for rid in ("hbm-residency-budget", "device-buffer-leak",
                "oversized-transfer", "vmem-frontier-budget",
                "capacity-certification"):
        assert cat[rid].severity == "error"
        assert cat[rid].family == "capacity"


def test_claim_lookup_and_projection():
    """The certified shardstore claim exposes the per-chip projection
    the ledger and bench emit."""
    c = cmod.capacity_claim("shardstore-resident-channels")
    assert c.sharded and c.bytes_per_sample == 20.0
    assert c.claimed_total(1024, 16) == pytest.approx(
        20.0 * 1024 + c.bytes_per_series * 16 + c.overhead_bytes)
    assert c.projected_series_per_chip(2880) == \
        int((cmod.HBM_BYTES_PER_CHIP - c.overhead_bytes)
            // (20.0 * 2880 + c.bytes_per_series))


def test_duplicate_claim_name_rejected():
    with pytest.raises(ValueError):
        @cmod.capacity("shardstore-resident-channels",
                       bytes_per_sample=1.0,
                       reason="collides with the shardstore claim")
        def other():
            pass


def test_empty_reason_rejected():
    with pytest.raises(ValueError):
        cmod.capacity("x", bytes_per_sample=1.0, reason="  ")


def test_residency_gauge_collector():
    """Annotated stores report live device bytes through the
    filodb_device_memory_bytes{family,shard} gauge (satellite 2)."""
    from filodb_tpu.obs import metrics as obs_metrics
    cmod.ensure_residency_collector()
    cmod.record_resident("memcert-test-family", "3", 0xBEEF, 12345)
    try:
        snap = cmod.residency_snapshot()
        assert snap["memcert-test-family"]["3"] == 12345
        b = obs_metrics.ExpositionBuilder()
        obs_metrics.GLOBAL_REGISTRY.collect_into(b)
        text = b.render()
        assert ('filodb_device_memory_bytes{family="memcert-test-'
                'family",shard="3"} 12345') in text
    finally:
        cmod.drop_resident("memcert-test-family", "3", 0xBEEF)
    assert "memcert-test-family" not in cmod.residency_snapshot()


def test_shardstore_records_residency():
    """A live ShardedTiles reports its channel bytes under its shard
    count, and dropping the store drops the bytes."""
    import gc

    from filodb_tpu.parallel.shardstore import ShardedTiles
    st = ShardedTiles(memcert._shard_mesh(1), memcert._seed_tiles())
    fam = "shardstore-resident-channels"
    snap = cmod.residency_snapshot()
    assert snap.get(fam, {}).get("1", 0) >= st.cap * st.S_pad * 20
    del st
    gc.collect()
    assert cmod.residency_snapshot().get(fam, {}).get("1", 0) == 0
