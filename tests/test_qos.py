"""Tenant QoS unit + golden tests (query/qos.py):

  * token-bucket semantics (deterministic injected clock) and the
    concurrent-accounting pin: no lost or double charges across
    threads;
  * cost-estimator golden ordering — the estimate may be wrong in
    absolute terms but must be MONOTONE against measured device time
    across the bench query shapes;
  * priority ordering on the device executor's dispatch queue;
  * the bounded admission gate: saturation answers 429 + Retry-After
    instead of the old indefinite semaphore hang;
  * results-cache stale_serve (the brownout ladder's first rung).
"""

import json
import threading
import time
import urllib.error
import urllib.parse
import urllib.request

import numpy as np
import pytest

from filodb_tpu.query import qos
from filodb_tpu.query.batcher import DeviceExecutor
from filodb_tpu.query.model import QueryStats
from filodb_tpu.query.resultcache import ResultCache
from filodb_tpu.promql.parser import TimeStepParams, parse_query_range
from filodb_tpu.standalone.server import FiloServer

T0 = 1_600_000_000
N_SAMPLES = 120
N_INSTANCES = 4


# ---------------------------------------------------------------------------
# token buckets
# ---------------------------------------------------------------------------

class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_token_bucket_charge_refill_retry_after():
    clk = _Clock()
    b = qos.TokenBucket(rate=10, burst=100, clock=clk)
    assert b.try_charge(60)
    assert not b.try_charge(60)         # 40 left
    assert b.remaining() == pytest.approx(40)
    # refill: 3s at 10/s -> 70 available
    clk.t = 3.0
    assert b.try_charge(60)
    assert b.remaining() == pytest.approx(10)
    # retry_after prices the wait for the (burst-capped) cost
    assert b.retry_after_s(50) == pytest.approx(4.0)
    assert b.retry_after_s(10_000) == pytest.approx(9.0)  # capped at burst
    # forced charges go negative but are debt-floored
    b.charge_forced(10_000)
    assert b.remaining() == pytest.approx(-300)           # -3 x burst
    snap = b.snapshot()
    assert snap["admitted"] == 2 and snap["throttled"] == 1
    assert snap["forced_charges"] == 1


def test_token_bucket_cost_above_burst_never_admits():
    b = qos.TokenBucket(rate=10, burst=100, clock=_Clock())
    assert not b.try_charge(101)        # the documented burst meaning


def test_concurrent_budget_accounting_no_lost_or_double_charges():
    """8 threads hammer try_charge(1) against a fixed 1000-token
    bucket: EXACTLY 1000 must win, and charged_total must equal the
    winners (atomic check-and-debit; a racy read-modify-write would
    admit more or fewer)."""
    clk = _Clock()                       # frozen: no refill mid-test
    b = qos.TokenBucket(rate=1.0, burst=1000, clock=clk)
    wins = []
    barrier = threading.Barrier(8)

    def worker():
        barrier.wait()
        n = 0
        for _ in range(500):
            if b.try_charge(1):
                n += 1
        wins.append(n)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert sum(wins) == 1000
    snap = b.snapshot()
    assert snap["charged_total"] == pytest.approx(1000)
    assert snap["admitted"] == 1000
    assert snap["throttled"] == 8 * 500 - 1000
    assert b.remaining() == pytest.approx(0)


def test_tenant_budgets_selective_and_overrides():
    budgets = qos.TenantBudgets(overrides={"abuser": [10, 50],
                                           "vip": 0},
                                clock=_Clock())
    assert budgets.enabled
    # only the abuser has a bucket; everyone else is unlimited
    assert budgets.try_charge("abuser", 50)
    assert not budgets.try_charge("abuser", 1)
    assert budgets.try_charge("anyone", 1e12)
    assert budgets.try_charge("vip", 1e12)      # explicit unlimited
    budgets.record_degraded("abuser", "stale")
    budgets.record_rejected("abuser")
    snap = budgets.snapshot()
    assert snap["abuser"]["degraded"] == {"stale": 1}
    assert snap["abuser"]["rejected"] == 1
    assert "anyone" not in snap                 # no bucket, no series


def test_budgets_disabled_short_circuits():
    budgets = qos.TenantBudgets()
    assert not budgets.enabled
    assert budgets.bucket("x") is None
    assert budgets.try_charge("x", 1e18)


# ---------------------------------------------------------------------------
# priority classes
# ---------------------------------------------------------------------------

def test_parse_priority_and_context():
    assert qos.parse_priority(None) == qos.PRIORITY_INTERACTIVE
    assert qos.parse_priority("background") == qos.PRIORITY_BACKGROUND
    assert qos.parse_priority("rules") == qos.PRIORITY_BACKGROUND
    assert qos.parse_priority("best-effort") == qos.PRIORITY_BEST_EFFORT
    assert qos.parse_priority("garbage") == qos.PRIORITY_INTERACTIVE
    assert qos.current() is None
    ctx = qos.QosContext(tenant="t", priority=qos.PRIORITY_BACKGROUND)
    with qos.activate(ctx):
        assert qos.current() is ctx
        assert qos.current_priority() == qos.PRIORITY_BACKGROUND
        assert qos.capture() is ctx
    assert qos.current() is None


def test_device_executor_priority_ordering():
    """A queued best-effort closure must not run before a queued
    interactive one: block the executor, enqueue best-effort then
    interactive, and observe the execution order."""
    ex = DeviceExecutor(name="test-prio-exec")
    order = []
    gate = threading.Event()
    first_running = threading.Event()

    def blocker():
        first_running.set()
        gate.wait(5)

    ex.submit(blocker)                  # occupies the executor thread
    assert first_running.wait(5)
    done = threading.Event()
    ex.submit(lambda: order.append("best_effort"),
              priority=qos.PRIORITY_BEST_EFFORT)
    ex.submit(lambda: order.append("background"),
              priority=qos.PRIORITY_BACKGROUND)
    ex.submit(lambda: (order.append("interactive"), done.set()),
              priority=qos.PRIORITY_INTERACTIVE)
    gate.set()
    assert done.wait(5)
    # interactive ran first even though it was enqueued last; the
    # best-effort closure (queued first) ran last. Wait for it too.
    deadline = time.monotonic() + 5
    while len(order) < 3 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert order == ["interactive", "background", "best_effort"]
    ex.stop()


def test_coarsen_step_pow2():
    assert qos.coarsen_step_s(0, 10, 590, 64) == 10      # 60 steps: fits
    assert qos.coarsen_step_s(0, 10, 1270, 64) == 20     # 128 -> 64
    assert qos.coarsen_step_s(0, 10, 5110, 64) == 80     # 512 -> 64
    assert qos.coarsen_step_s(0, 0, 100, 64) == 0        # instant: no-op


# ---------------------------------------------------------------------------
# cost estimation: golden ordering against measured device time
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def qos_server():
    srv = FiloServer({"num-shards": 2, "grpc-port": None, "port": 0,
                      "results-cache-mb": 0,
                      "batch-enabled": False}).start()
    srv.seed_dev_data(n_samples=N_SAMPLES, n_instances=N_INSTANCES,
                      start_ms=T0 * 1000)
    try:
        yield srv
    finally:
        srv.stop()


# bench query shapes in strictly increasing work order: more steps,
# more series, wider windows, heavier trees. Each step up multiplies
# the real work by a large factor so the time ordering is robust to
# scheduler noise.
_SHAPES = [
    ("tiny",   'heap_usage{instance="instance-0"}',
     T0 + 400, T0 + 500, 20),                    # 1 series, 6 steps
    ("narrow", 'heap_usage{instance="instance-0"}',
     T0 + 300, T0 + 1190, 10),                   # 1 series, 90 steps
    ("wide",   'rate(http_requests_total[5m])',
     T0 + 300, T0 + 1190, 10),                   # 4 series, windowed
    ("heavy",  'sum(rate({_metric_=~"heap_usage|http_requests_total"}'
               '[10m])) by (instance)',
     T0 + 300, T0 + 1190, 5),                    # 8 series, agg, 2x res
]


def test_cost_estimator_golden_ordering(qos_server):
    """The estimator's cost ordering must match measured execution
    time across the bench shapes (monotone, not absolutely right).
    Each shape is warmed once (XLA compile excluded), then timed as
    the median of 3 runs."""
    http = qos_server.http
    costs, times = {}, {}
    for name, query, start, end, step in _SHAPES:
        engine = http.make_planner("timeseries")
        plan = parse_query_range(query, TimeStepParams(start, step, end))
        costs[name] = engine.estimate_cost(plan).total
        engine.materialize(plan).execute()          # warm (compile)
        runs = []
        for _ in range(3):
            eng = http.make_planner("timeseries")
            p = parse_query_range(query,
                                  TimeStepParams(start, step, end))
            t0 = time.perf_counter()
            eng.materialize(p).execute()
            runs.append(time.perf_counter() - t0)
        times[name] = sorted(runs)[1]
    order = [n for n, *_ in _SHAPES]
    cost_rank = sorted(order, key=lambda n: costs[n])
    assert cost_rank == order, (costs, times)
    # the shapes were CHOSEN to separate by real work: pin that the
    # measured times agree with the intended ordering for the extreme
    # pair at least (middle pairs can jitter on a loaded CI box)
    assert times["tiny"] < times["heavy"], times
    # and the estimator separates the extremes by a wide margin
    assert costs["heavy"] > 50 * costs["tiny"]


def test_cost_estimator_cardinality_inputs(qos_server):
    """Pinned selectors price by the cardinality tree + tag-index
    postings: a one-instance selector prices below the full metric,
    which prices below the all-metrics fan."""
    http = qos_server.http
    engine = http.make_planner("timeseries")

    def cost(q):
        plan = parse_query_range(
            q, TimeStepParams(T0 + 300, 10, T0 + 600))
        return qos.estimate_plan_cost(plan, engine.shards).total

    one = cost('heap_usage{instance="instance-0"}')
    metric = cost('heap_usage')
    everything = cost('{_metric_=~"heap_usage|http_requests_total"}')
    assert one < metric <= everything


def test_estimate_leaf_cost_scales_with_span_and_series(qos_server):
    from filodb_tpu.core.index import ColumnFilter
    shards = qos_server.http.shards_by_dataset["timeseries"]
    f_narrow = [ColumnFilter.eq("_metric_", "heap_usage"),
                ColumnFilter.eq("instance", "instance-0")]
    f_wide = [ColumnFilter.eq("_metric_", "heap_usage")]
    t0, t1 = T0 * 1000, (T0 + 600) * 1000
    assert qos.estimate_leaf_cost(f_narrow, shards, t0, t1) \
        < qos.estimate_leaf_cost(f_wide, shards, t0, t1)
    assert qos.estimate_leaf_cost(f_wide, shards, t0, t1) \
        < qos.estimate_leaf_cost(f_wide, shards, t0, t1 + 3_600_000)


# ---------------------------------------------------------------------------
# bounded admission gate (satellite: no more silent hangs)
# ---------------------------------------------------------------------------

def _get(port, path, **params):
    url = f"http://127.0.0.1:{port}{path}"
    if params:
        url += "?" + urllib.parse.urlencode(params)
    try:
        with urllib.request.urlopen(url, timeout=30) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


def test_bounded_admission_429_with_retry_after():
    """Saturation maps to a bounded wait then 429 + Retry-After — not
    the old indefinite semaphore hang (clients saw nothing until their
    own timeout) and distinct from the 503 deadline path."""
    srv = FiloServer({"num-shards": 2, "grpc-port": None, "port": 0,
                      "max-inflight-queries": 1,
                      "admission-wait-s": 0.3}).start()
    srv.seed_dev_data(n_samples=30, n_instances=2, start_ms=T0 * 1000)
    try:
        adm = srv.http.admission
        assert adm.gated
        assert adm.try_acquire()            # occupy the only slot
        try:
            t0 = time.perf_counter()
            code, body, hdrs = _get(
                srv.port, "/promql/timeseries/api/v1/query_range",
                query="heap_usage", start=T0, end=T0 + 100, step=10)
            waited = time.perf_counter() - t0
        finally:
            adm.release()
        assert code == 429
        assert body["errorType"] == "throttled"
        assert int(hdrs["Retry-After"]) >= 1
        assert 0.25 < waited < 5.0          # bounded, not a hang
        assert adm.snapshot()["wait_timeouts"] == 1
        # slot released: the next query sails through
        code, body, _ = _get(
            srv.port, "/promql/timeseries/api/v1/query_range",
            query="heap_usage", start=T0, end=T0 + 100, step=10)
        assert code == 200 and body["status"] == "success"
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# stale_serve: the ladder's first rung
# ---------------------------------------------------------------------------

class _FakeEngine:
    shards = ()
    local_dispatch = False

    def __init__(self):
        self.stats = QueryStats()


def _plan(start, end, step):
    return parse_query_range("heap_usage",
                             TimeStepParams(start, step, end))


def test_stale_serve_past_horizon_and_truncation():
    now = [T0 + 1000.0]
    rc = ResultCache(max_bytes=1 << 20, hot_window_ms=10_000,
                     clock=lambda: now[0])
    eng = _FakeEngine()
    start, end, step = T0, T0 + 90, 10
    plan = _plan(start, end, step)
    ses = rc.begin(eng, "ds", "heap_usage", plan, start * 1000,
                   step * 1000, end * 1000)
    assert ses.state == "miss"
    from filodb_tpu.query.model import GridResult
    steps = np.arange(start * 1000, end * 1000 + 1, step * 1000,
                      dtype=np.int64)
    grid = GridResult(steps, [{"m": "a"}],
                      np.arange(steps.size, dtype=float)[None, :])
    res = ses.finish(eng, [grid])
    assert res is grid
    # fresh lookups inside the hot window now hit; push now far past
    # the hot window so EVERY step is stale for the normal path
    now[0] = T0 + 1_000_000.0
    ses2 = rc.begin(eng, "ds", "heap_usage", plan, start * 1000,
                    step * 1000, end * 1000)
    assert ses2.state == "hit"      # settled data: still a normal hit
    # stale_serve ignores the horizon: full range served
    g = rc.stale_serve(eng, "ds", "heap_usage", plan, start * 1000,
                       step * 1000, end * 1000)
    assert g is not None and not g.partial
    assert g.values.shape == (1, steps.size)
    # a LONGER request truncates at the extent tail -> partial
    plan_long = _plan(start, end + 50, step)
    g2 = rc.stale_serve(eng, "ds", "heap_usage", plan_long,
                        start * 1000, step * 1000, (end + 50) * 1000)
    assert g2 is not None and g2.partial
    assert g2.values.shape == (1, steps.size)
    # a head-missing request has no cheap assembly -> None
    g3 = rc.stale_serve(eng, "ds", "heap_usage",
                        _plan(start - 100, end, step),
                        (start - 100) * 1000, step * 1000, end * 1000)
    assert g3 is None
    assert rc.snapshot()["stale_serves"] == 2


def test_stale_serve_never_serves_wrong_world():
    """Stale, never WRONG: a backfill-epoch change invalidates the
    extent for stale_serve exactly like the normal lookup path."""
    now = [T0 + 1000.0]
    rc = ResultCache(max_bytes=1 << 20, hot_window_ms=1_000,
                     clock=lambda: now[0])

    class _Shard:
        ingest_watermark_ms = (T0 + 10_000) * 1000
        ingest_backfill_epoch = 0

    class _Eng(_FakeEngine):
        shards = (_Shard(),)

    eng = _Eng()
    start, end, step = T0, T0 + 90, 10
    plan = _plan(start, end, step)
    ses = rc.begin(eng, "ds", "heap_usage", plan, start * 1000,
                   step * 1000, end * 1000)
    from filodb_tpu.query.model import GridResult
    steps = np.arange(start * 1000, end * 1000 + 1, step * 1000,
                      dtype=np.int64)
    ses.finish(eng, [GridResult(steps, [{"m": "a"}],
                                np.ones((1, steps.size)))])
    assert rc.stale_serve(eng, "ds", "heap_usage", plan, start * 1000,
                          step * 1000, end * 1000) is not None
    _Shard.ingest_backfill_epoch = 1       # series entered below wm
    assert rc.stale_serve(eng, "ds", "heap_usage", plan, start * 1000,
                          step * 1000, end * 1000) is None


# ---------------------------------------------------------------------------
# wire propagation
# ---------------------------------------------------------------------------

def test_tenant_priority_wire_roundtrip():
    from filodb_tpu.grpcsvc import wire
    buf = wire.encode_exec_request(
        "ds", "q", 0, 1000, 10_000, tenant="acme",
        priority=qos.PRIORITY_BEST_EFFORT)
    req = wire.decode_exec_request(buf)
    assert req["tenant"] == "acme"
    assert req["priority"] == qos.PRIORITY_BEST_EFFORT
    # absent fields decode to defaults (older peers interop)
    req2 = wire.decode_exec_request(
        wire.encode_exec_request("ds", "q", 0, 1000, 10_000))
    assert req2["tenant"] == "" and req2["priority"] == 0
    raw = wire.decode_raw_request(wire.encode_raw_request(
        "ds", [], 0, 1000, None, None, tenant="acme",
        priority=qos.PRIORITY_BACKGROUND))
    assert raw["tenant"] == "acme"
    assert raw["priority"] == qos.PRIORITY_BACKGROUND
