"""PromQL parser tests (parity model: prometheus/src/test ParserSpec golden
LogicalPlans)."""

import pytest

from filodb_tpu.core.index import ColumnFilter as CF
from filodb_tpu.promql.parser import (ParseError, TimeStepParams,
                                      parse_duration_ms, parse_query_range)
from filodb_tpu.query import logical as lp

P = TimeStepParams(1000, 10, 2000)


def parse(q):
    return parse_query_range(q, P)


def test_durations():
    assert parse_duration_ms("5m") == 300_000
    assert parse_duration_ms("1h30m") == 5_400_000
    assert parse_duration_ms("90s") == 90_000
    assert parse_duration_ms("1d") == 86_400_000
    assert parse_duration_ms("100ms") == 100


def test_simple_selector():
    plan = parse('http_requests_total{job="api", instance!="i1"}')
    assert isinstance(plan, lp.PeriodicSeries)
    fs = plan.raw.filters
    assert CF.eq("_metric_", "http_requests_total") in fs
    assert CF.eq("job", "api") in fs
    assert CF.neq("instance", "i1") in fs
    assert plan.start_ms == 1_000_000 and plan.end_ms == 2_000_000
    assert plan.step_ms == 10_000


def test_name_matcher_and_regex():
    plan = parse('{__name__="foo", job=~"a.*"}')
    fs = plan.raw.filters
    assert CF.eq("_metric_", "foo") in fs
    assert CF.regex("job", "a.*") in fs


def test_rate_window():
    plan = parse("rate(http_requests_total[5m])")
    assert isinstance(plan, lp.PeriodicSeriesWithWindowing)
    assert plan.function == "rate"
    assert plan.window_ms == 300_000
    # raw fetch range extends back by the window
    assert plan.raw.start_ms == 1_000_000 - 300_000


def test_aggregate_by():
    plan = parse("sum by (job) (rate(http_requests_total[5m]))")
    assert isinstance(plan, lp.Aggregate)
    assert plan.op == "sum" and plan.by == ("job",)
    plan2 = parse("sum(rate(http_requests_total[5m])) by (job)")
    assert plan2.by == ("job",)
    plan3 = parse("sum without (instance) (foo)")
    assert plan3.without == ("instance",)


def test_topk_quantile_count_values():
    plan = parse("topk(5, foo)")
    assert plan.op == "topk" and plan.params == (5.0,)
    plan = parse("quantile(0.9, foo)")
    assert plan.op == "quantile" and plan.params == (0.9,)
    plan = parse('count_values("version", build_info)')
    assert plan.op == "count_values" and plan.params == ("version",)


def test_binary_join_precedence():
    plan = parse("a + b * c")
    assert isinstance(plan, lp.BinaryJoin)
    assert plan.op == "+"
    assert isinstance(plan.rhs, lp.BinaryJoin)
    assert plan.rhs.op == "*"


def test_scalar_vector_op():
    plan = parse("foo > 10")
    assert isinstance(plan, lp.ScalarVectorBinaryOperation)
    assert not plan.scalar_is_lhs
    plan = parse("10 < foo")
    assert plan.scalar_is_lhs
    plan = parse("foo > bool 10")
    assert plan.return_bool


def test_on_group_left():
    plan = parse("a * on (job) group_left (version) b")
    assert isinstance(plan, lp.BinaryJoin)
    assert plan.on == ("job",)
    assert plan.cardinality == "many-to-one"
    assert plan.include == ("version",)


def test_set_ops():
    plan = parse("a and b or c unless d")
    assert isinstance(plan, lp.BinaryJoin)
    assert plan.op == "or"


def test_offset():
    plan = parse("rate(foo[5m] offset 10m)")
    assert plan.offset_ms == 600_000
    plan = parse("foo offset 1h")
    assert plan.offset_ms == 3_600_000


def test_instant_functions():
    plan = parse("abs(foo)")
    assert isinstance(plan, lp.ApplyInstantFunction)
    plan = parse("clamp(foo, 0, 10)")
    assert plan.func_args == (0.0, 10.0)
    plan = parse("histogram_quantile(0.99, sum(rate(req_bucket[5m])) by (le))")
    assert plan.function == "histogram_quantile"
    assert plan.func_args == (0.99,)


def test_quantile_over_time_scalar_first():
    plan = parse("quantile_over_time(0.95, latency[10m])")
    assert isinstance(plan, lp.PeriodicSeriesWithWindowing)
    assert plan.function == "quantile_over_time"
    assert plan.func_args == (0.95,)


def test_predict_linear_and_holt_winters():
    plan = parse("predict_linear(foo[1h], 3600)")
    assert plan.func_args == (3600.0,)
    plan = parse("holt_winters(foo[1h], 0.5, 0.1)")
    assert plan.func_args == (0.5, 0.1)


def test_subquery():
    plan = parse("max_over_time(rate(foo[5m])[30m:1m])")
    assert isinstance(plan, lp.SubqueryWithWindowing)
    assert plan.function == "max_over_time"
    assert plan.window_ms == 1_800_000
    assert plan.sub_step_ms == 60_000
    assert isinstance(plan.inner, lp.PeriodicSeriesWithWindowing)
    assert plan.at_ms is None


def test_subquery_at_pinning():
    """expr[w:s] @ t and @ start()/end() (LogicalPlan.scala:349,
    ast/SubqueryUtils)."""
    plan = parse("max_over_time(rate(foo[5m])[30m:1m] @ 1700000000)")
    assert isinstance(plan, lp.SubqueryWithWindowing)
    assert plan.at_ms == 1_700_000_000_000
    plan = parse("avg_over_time(foo[10m:] @ start())")
    assert plan.at_ms == P.start_s * 1000
    plan = parse("avg_over_time(foo[10m:] @ end() offset 5m)")
    assert plan.at_ms == P.end_s * 1000
    assert plan.offset_ms == 300_000
    # selectors accept start()/end() too
    plan = parse("rate(foo[5m] @ end())")
    assert plan.at_ms == P.end_s * 1000


def test_scalar_exprs():
    plan = parse("1 + 2 * 3")
    assert isinstance(plan, lp.ScalarBinaryOperation)
    plan = parse("scalar(foo) + 1")
    assert isinstance(plan, lp.ScalarBinaryOperation)
    plan = parse("vector(1)")
    assert isinstance(plan, lp.VectorPlan)


def test_label_replace():
    plan = parse('label_replace(foo, "dst", "$1", "src", "(.*)")')
    assert isinstance(plan, lp.ApplyMiscellaneousFunction)
    assert plan.str_args == ("dst", "$1", "src", "(.*)")


def test_sort_absent():
    assert isinstance(parse("sort_desc(foo)"), lp.ApplySortFunction)
    plan = parse('absent(foo{job="x"})')
    assert isinstance(plan, lp.ApplyAbsentFunction)
    assert CF.eq("job", "x") in plan.filters


def test_parse_errors():
    with pytest.raises(ParseError):
        parse("rate(foo)")          # missing window
    with pytest.raises(ParseError):
        parse("foo[5m]")            # bare range vector
    with pytest.raises(ParseError):
        parse("sum(")               # truncated
    with pytest.raises(ParseError):
        parse("foo{job=bar}")       # unquoted matcher value


# ---------------------------------------------------------------------------
# duration validation + zero windows (promlint satellite)
# ---------------------------------------------------------------------------

def test_malformed_durations_rejected():
    for bad in ("", "5", "m5", "5mm", "1h2", "abc"):
        with pytest.raises(ValueError):
            parse_duration_ms(bad)
    assert parse_duration_ms("0s") == 0     # zero itself parses


def test_zero_window_rejected_with_span():
    with pytest.raises(ParseError) as ei:
        parse("rate(foo[0s])")
    assert ei.value.pos == 9 and ei.value.end == 11
    with pytest.raises(ParseError):
        parse("rate(foo[0])")                # NUMBER-form zero too


def test_zero_subquery_step_pinned():
    """[5m:0s] — explicit zero resolution is rejected at parse time
    (Prometheus rejects it too); [5m:] keeps the default step."""
    with pytest.raises(ParseError) as ei:
        parse("max_over_time(rate(foo[1m])[5m:0s])")
    q = "max_over_time(rate(foo[1m])[5m:0s])"
    assert q[ei.value.pos:ei.value.end] == "0s"
    plan = parse("max_over_time(rate(foo[1m])[5m:])")
    assert plan.sub_step_ms == 10_000       # query step


# ---------------------------------------------------------------------------
# ParseError span/position accuracy (promlint reuses these spans)
# ---------------------------------------------------------------------------

def _err_span(q):
    with pytest.raises(ParseError) as ei:
        parse(q)
    return q, ei.value.pos, ei.value.end


def test_span_quoted_labels_with_escapes():
    # a bad matcher op AFTER an escaped-quote value: the escape must
    # not shift the reported span
    q = 'foo{job="a\\"b", x<"1"}'
    _q, pos, end = _err_span(q)
    assert q[pos:end] == "<"
    # unterminated matcher block after a non-ASCII value: EOF position
    q2 = 'foo{job="a\\"b", x="✓"'
    _q, pos2, _ = _err_span(q2)
    assert pos2 == len(q2)
    # unquoted value span lands on the offending token
    q3 = 'foo{job="ok", instance=i1}'
    _q, pos3, end3 = _err_span(q3)
    assert q3[pos3:end3] == "i1"


def test_span_at_offset_combinations():
    q = "rate(foo[5m] @ end() offset bad)"
    _q, pos, end = _err_span(q)
    assert q[pos:end] == "bad"
    q2 = "1 offset 5m"
    _q, pos2, _ = _err_span(q2)
    assert q2[pos2:] == "offset 5m"
    q3 = "(a + b) @ 1000"
    _q, pos3, _ = _err_span(q3)
    assert pos3 == q3.index("@")


def test_span_utf8_metric_names():
    # non-ASCII metric characters are rejected at their exact offset
    q = "métrique"
    with pytest.raises(ParseError) as ei:
        parse(q)
    assert ei.value.pos == 1                # the é
    q2 = "sum(rate(日本語[5m]))"
    with pytest.raises(ParseError) as ei2:
        parse(q2)
    assert ei2.value.pos == q2.index("日")


def test_span_eof_and_trailing():
    q = "sum(rate(foo[5m])"
    with pytest.raises(ParseError) as ei:
        parse(q)
    assert ei.value.pos == len(q)           # at EOF
    q2 = "foo bar"
    with pytest.raises(ParseError) as ei2:
        parse(q2)
    assert q2[ei2.value.pos:ei2.value.end] == "bar"


def test_ast_spans_cover_constructs():
    from filodb_tpu.promql.parser import Parser, ast_span
    q = "sum by (job) (rate(foo[5m] offset 1m))"
    ast = Parser(q).parse()
    assert ast_span(ast) == (0, len(q))
    call = ast.expr
    assert q[call.pos:call.end] == "rate(foo[5m] offset 1m)"
    sel = call.args[0]
    assert q[sel.pos:sel.end] == "foo[5m] offset 1m"


def test_comments_are_whitespace():
    plan = parse("rate(foo[5m])  # trailing comment")
    assert isinstance(plan, lp.PeriodicSeriesWithWindowing)


def test_normalize_query_canonical():
    from filodb_tpu.promql.parser import normalize_query
    a = normalize_query('sum by (job) (rate(x{b="2",a="1"}[5m]))')
    b = normalize_query('sum ( rate( x{a="1", b="2"}[300s] ) ) by (job)')
    assert a == b
    assert normalize_query("a + b") != normalize_query("b + a")
