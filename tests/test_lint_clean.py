"""Tier-1 gate: the real package lints clean against the shipped
baseline — with the interprocedural concurrency families AND the v3
SPMD/cache families enabled at error severity — every pallas_call site
carries a verified contract, the baseline itself is empty (nothing
grandfathered), and a full run stays inside the pre-commit latency
budget."""

import json
import time

from filodb_tpu.lint import baseline_path, load_baseline, run_lint


def test_package_lints_clean_and_fast():
    t0 = time.monotonic()
    res = run_lint()        # full package, contracts included
    elapsed = time.monotonic() - t0
    assert res.files > 50
    msgs = [f.render() for f in res.findings]
    assert not msgs, "graftlint findings:\n" + "\n".join(msgs)
    # perf guard: the whole-program analysis (call graph + lock
    # propagation + contracts) must stay pre-commit-fast; ~4s on the
    # dev rig, 30s is the hard ceiling before it stops being run
    assert elapsed < 30.0, f"full lint run took {elapsed:.1f}s"


def test_concurrency_families_enabled_at_error():
    from filodb_tpu.lint import rules
    cat = rules()
    for rid in ("lock-order-cycle", "lock-order-policy",
                "lock-blocking-reachable",
                "thread-unguarded-shared-state"):
        assert cat[rid].severity == "error"


def test_v3_families_enabled_at_error():
    """The four graftlint v3 families ride the tier-1 gate at error
    severity (donation-missing is the one deliberate advisory). The
    perf guard above covers them: run_lint() builds the shared call
    graph + dataflow layer with every v3 family enabled."""
    from filodb_tpu.lint import rules
    cat = rules()
    for rid in ("spmd-collective-balance", "donation-safety",
                "partition-spec-consistency",
                "cache-invalidation-completeness",
                "cache-unregistered"):
        assert cat[rid].severity == "error"
    assert cat["donation-missing"].severity == "warning"


def test_v4_families_enabled_at_error():
    """The four graftlint v4 numerics families + the ulp-certification
    rail ride the tier-1 gate at error severity. The full run above
    exercises them: the tree sweep covers every traced/pallas body and
    check_contracts=True runs the certification rail over every
    @precision/@order_insensitive annotation (order claims at 1/2/4/8
    virtual devices)."""
    from filodb_tpu.lint import rules
    cat = rules()
    for rid in ("precision-narrowing", "accumulation-bound",
                "reduction-order-determinism", "mixed-dtype-comparison",
                "ulp-certification"):
        assert cat[rid].severity == "error"


def test_v5_families_enabled_at_error():
    """The four graftlint v5 capacity families + the capacity-
    certification rail ride the tier-1 gate at error severity. The
    full run above exercises them: the residency dataflow sweeps every
    untraced function, the frontier sweep re-derives the groupsum
    chooser grid against the kernel contract, and check_contracts=True
    certifies every @capacity claim (sharded claims at 1/2/4/8 virtual
    devices)."""
    from filodb_tpu.lint import rules
    cat = rules()
    for rid in ("hbm-residency-budget", "device-buffer-leak",
                "oversized-transfer", "vmem-frontier-budget",
                "capacity-certification"):
        assert cat[rid].severity == "error"
        assert cat[rid].family == "capacity"


def test_tree_annotations_all_certified():
    """Belt-and-braces alongside the run_lint sweep: the certification
    results themselves (memoized from the gate run) are all green."""
    from filodb_tpu.lint import ulpcert
    results = ulpcert.certify_all()
    assert len(results) >= 8
    bad = [r for r in results if not r.ok]
    assert not bad, bad


def test_tree_capacity_claims_all_certified():
    """Same for the v5 rail: every in-tree @capacity claim certifies
    (memoized from the gate run — the resident shardstore channels,
    tilestore tiles, executable constants, the tile cache, and the
    downsample staging buffers)."""
    from filodb_tpu.lint import memcert
    results = memcert.certify_all()
    assert len(results) >= 5
    bad = [r for r in results if not r.ok]
    assert not bad, bad


def test_shipped_baseline_is_empty():
    with open(baseline_path()) as f:
        data = json.load(f)
    assert data["findings"] == []
    assert load_baseline() == frozenset()


def test_every_pallas_call_site_has_contract():
    import importlib
    from filodb_tpu.lint.contracts import CONTRACTS
    for m in ("filodb_tpu.query.pallas_kernels",
              "filodb_tpu.query.tilestore", "filodb_tpu.query.tpu",
              "filodb_tpu.downsample.kernels",
              "filodb_tpu.parallel.mesh"):
        importlib.import_module(m)
    names = {k[1] for k in CONTRACTS}
    # the two real pallas_call wrappers + their dispatchers
    assert {"counter_groupsum", "window_extract", "groupsum_dispatch",
            "counters_t_dispatch", "pallas_rate"} <= names
    # kernel entry points across the named modules
    assert {"window_endpoint", "window_gather", "downsample_gauge",
            "downsample_regular", "counter_emit_mask", "cascade_aligned",
            "mesh_grouped_reduce"} <= names
