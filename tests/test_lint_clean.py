"""Tier-1 gate: the real package lints clean against the shipped
baseline, every pallas_call site carries a verified contract, and the
baseline itself is empty (nothing grandfathered)."""

import json

from filodb_tpu.lint import baseline_path, load_baseline, run_lint


def test_package_lints_clean():
    res = run_lint()        # full package, contracts included
    assert res.files > 50
    msgs = [f.render() for f in res.findings]
    assert not msgs, "graftlint findings:\n" + "\n".join(msgs)


def test_shipped_baseline_is_empty():
    with open(baseline_path()) as f:
        data = json.load(f)
    assert data["findings"] == []
    assert load_baseline() == frozenset()


def test_every_pallas_call_site_has_contract():
    import importlib
    from filodb_tpu.lint.contracts import CONTRACTS
    for m in ("filodb_tpu.query.pallas_kernels",
              "filodb_tpu.query.tilestore", "filodb_tpu.query.tpu",
              "filodb_tpu.downsample.kernels",
              "filodb_tpu.parallel.mesh"):
        importlib.import_module(m)
    names = {k[1] for k in CONTRACTS}
    # the two real pallas_call wrappers + their dispatchers
    assert {"counter_groupsum", "window_extract", "groupsum_dispatch",
            "counters_t_dispatch", "pallas_rate"} <= names
    # kernel entry points across the named modules
    assert {"window_endpoint", "window_gather", "downsample_gauge",
            "downsample_regular", "counter_emit_mask", "cascade_aligned",
            "mesh_grouped_reduce"} <= names
