"""Cardinality subsystem: quota tree, enforcement at series creation,
TsCardinalities through engine + HTTP.

(ratelimit/CardinalityTracker.scala:38, CardinalityTrackerSpec;
QuotaExceededProtocol: breach drops new series with a counted stat.)
"""

import json
import urllib.parse
import urllib.request

import numpy as np
import pytest

from filodb_tpu.core.cardinality import (CardinalityTracker,
                                         QuotaReachedException,
                                         merge_records)
from filodb_tpu.core.memstore import TimeSeriesShard
from filodb_tpu.core.record import RecordBuilder
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetRef
from filodb_tpu.query import logical as lp
from filodb_tpu.query.engine import QueryEngine

REF = DatasetRef("timeseries")
T0 = 1_600_000_000_000


def _labels(ws, ns, metric, inst):
    return {"_ws_": ws, "_ns_": ns, "_metric_": metric, "instance": inst}


def test_tracker_counts_all_levels():
    t = CardinalityTracker()
    for i in range(5):
        t.modify_count(("demo", "App-0", "cpu"), 1, 1)
    t.modify_count(("demo", "App-1", "mem"), 1, 1)
    recs = {tuple(r.prefix): r for r in t.scan((), 1)}
    assert recs[("demo",)].ts_count == 6
    recs2 = {tuple(r.prefix): r for r in t.scan(("demo",), 2)}
    assert recs2[("demo", "App-0")].ts_count == 5
    assert recs2[("demo", "App-1")].ts_count == 1
    assert t.scan((), 0)[0].ts_count == 6


def test_quota_enforced_at_any_level():
    t = CardinalityTracker()
    t.set_quota(["demo", "App-0"], 3)
    for i in range(3):
        t.modify_count(("demo", "App-0", f"m{i}"), 1, 1)
    with pytest.raises(QuotaReachedException):
        t.modify_count(("demo", "App-0", "m9"), 1, 1)
    # sibling namespace unaffected
    t.modify_count(("demo", "App-1", "m0"), 1, 1)
    # release one, then admission works again
    t.modify_count(("demo", "App-0", "m0"), -1, -1)
    t.modify_count(("demo", "App-0", "m9"), 1, 1)


def test_default_quota_by_depth():
    t = CardinalityTracker(default_quotas=(0, 0, 2, 0))
    t.modify_count(("demo", "ns1", "a"), 1)
    t.modify_count(("demo", "ns1", "b"), 1)
    with pytest.raises(QuotaReachedException):
        t.modify_count(("demo", "ns1", "c"), 1)


def test_top_k():
    t = CardinalityTracker()
    for i, n in enumerate([5, 1, 3]):
        for _ in range(n):
            t.modify_count(("demo", f"ns{i}", "m"), 1)
    top = t.top_k(("demo",), 2)
    assert [r.prefix[-1] for r in top] == ["ns0", "ns2"]
    assert [r.ts_count for r in top] == [5, 3]


def test_shard_drops_series_on_quota_breach():
    tracker = CardinalityTracker()
    tracker.set_quota(["demo", "App-0"], 2)
    shard = TimeSeriesShard(REF, DEFAULT_SCHEMAS, 0,
                            card_tracker=tracker)
    b = RecordBuilder(DEFAULT_SCHEMAS)
    for i in range(4):
        b.add_sample("gauge", _labels("demo", "App-0", "cpu", f"i{i}"),
                     T0 + i, float(i))
        # another tenant is not affected by App-0's quota
        b.add_sample("gauge", _labels("demo", "App-1", "cpu", f"i{i}"),
                     T0 + i, float(i))
    for c in b.containers():
        shard.ingest(c)
    assert shard.stats.num_series == 6          # 2 App-0 + 4 App-1
    assert shard.stats.quota_dropped_series == 2
    recs = {tuple(r.prefix): r for r in tracker.scan(("demo",), 2)}
    assert recs[("demo", "App-0")].ts_count == 2
    assert recs[("demo", "App-1")].ts_count == 4


def test_ts_cardinalities_through_engine():
    shards = []
    for sn in range(2):
        tracker = CardinalityTracker()
        shard = TimeSeriesShard(REF, DEFAULT_SCHEMAS, sn,
                                card_tracker=tracker)
        b = RecordBuilder(DEFAULT_SCHEMAS)
        for i in range(3 + sn):
            b.add_sample("gauge", _labels("demo", "App-0", "cpu", f"i{i}"),
                         T0, 1.0)
        for c in b.containers():
            shard.ingest(c)
        shards.append(shard)
    recs = QueryEngine(shards).execute(lp.TsCardinalities(("demo",), 2))
    assert len(recs) == 1
    assert recs[0].prefix == ("demo", "App-0")
    assert recs[0].ts_count == 7                # 3 + 4 across shards


def test_cardinality_http_endpoint():
    from filodb_tpu.standalone.server import FiloServer
    srv = FiloServer({"num-shards": 2, "port": 0,
                      "card-quotas": {"demo,App-0": 1000}}).start()
    try:
        srv.seed_dev_data(n_samples=10, n_instances=3,
                          start_ms=T0)
        url = (f"http://127.0.0.1:{srv.port}/api/v1/cardinality/"
               f"timeseries?prefix=demo&depth=2")
        body = json.loads(urllib.request.urlopen(url, timeout=30).read())
        assert body["status"] == "success"
        assert body["data"], body
        rec = body["data"][0]
        assert rec["prefix"][0] == "demo"
        assert rec["tsCount"] > 0
        # depth 3: per-metric counts
        url3 = (f"http://127.0.0.1:{srv.port}/api/v1/cardinality/"
                f"timeseries?depth=3")
        body3 = json.loads(urllib.request.urlopen(url3, timeout=30).read())
        metrics = {tuple(r["prefix"])[-1] for r in body3["data"]}
        assert "heap_usage" in metrics
    finally:
        srv.stop()


def test_rejected_series_do_not_grow_tree():
    """Regression: a quota-rejected flood of distinct metrics must not
    allocate tracker nodes."""
    t = CardinalityTracker()
    t.set_quota(["demo", "App-0"], 1)
    t.modify_count(("demo", "App-0", "m0"), 1, 1)
    for i in range(100):
        with pytest.raises(QuotaReachedException):
            t.modify_count(("demo", "App-0", f"flood{i}"), 1, 1)
    node = t._node_at(("demo", "App-0"))
    assert set(node.children) == {"m0"}


def test_set_quota_intermediate_nodes_get_depth_defaults():
    """Regression: an override at depth 2 must not wipe the depth-1
    default quota of the intermediate node."""
    t = CardinalityTracker(default_quotas=(0, 2, 0, 0))
    t.set_quota(["demo", "App-0"], 50)
    assert t._node_at(("demo",)).quota == 2
    t.modify_count(("demo", "a", "m"), 1)
    t.modify_count(("demo", "b", "m"), 1)
    with pytest.raises(QuotaReachedException):
        t.modify_count(("demo", "c", "m"), 1)   # ws-level default trips


def test_active_count_lifecycle_with_eviction(tmp_path):
    """Active counts survive evict -> page-in -> evict cycles and resume
    on re-ingest (ODP shells are total-counted but inactive)."""
    from filodb_tpu.store import FlatFileColumnStore
    cs = FlatFileColumnStore(str(tmp_path / "col"))
    tracker = CardinalityTracker()
    shard = TimeSeriesShard(REF, DEFAULT_SCHEMAS, 0, column_store=cs,
                            card_tracker=tracker)
    b = RecordBuilder(DEFAULT_SCHEMAS)
    for i in range(3):
        for t in range(5):
            b.add_sample("gauge", _labels("demo", "App-0", "cpu", f"i{i}"),
                         T0 + t * 1000, 1.0)
    for c in b.containers():
        shard.ingest(c)
    shard.flush_all(offset=1)
    root = tracker.scan((), 0)[0]
    assert (root.ts_count, root.active_ts_count) == (3, 3)

    shard.evict_partitions(cutoff_ts=T0 + 1 << 40)
    root = tracker.scan((), 0)[0]
    assert (root.ts_count, root.active_ts_count) == (3, 0)
    # double eviction must not decrement again
    shard.evict_partitions(cutoff_ts=T0 + 1 << 40)
    assert tracker.scan((), 0)[0].active_ts_count == 0

    # resumed ingest re-activates exactly once
    b = RecordBuilder(DEFAULT_SCHEMAS)
    b.add_sample("gauge", _labels("demo", "App-0", "cpu", "i0"),
                 T0 + 10_000_000, 2.0)
    for c in b.containers():
        shard.ingest(c)
    root = tracker.scan((), 0)[0]
    assert (root.ts_count, root.active_ts_count) == (3, 1)


def test_bootstrap_counts_total_not_active(tmp_path):
    from filodb_tpu.store import FlatFileColumnStore
    cs = FlatFileColumnStore(str(tmp_path / "col"))
    shard = TimeSeriesShard(REF, DEFAULT_SCHEMAS, 0, column_store=cs)
    b = RecordBuilder(DEFAULT_SCHEMAS)
    for i in range(4):
        b.add_sample("gauge", _labels("demo", "App-0", "cpu", f"i{i}"),
                     T0, 1.0)
    for c in b.containers():
        shard.ingest(c)
    shard.flush_all(offset=1)

    tracker = CardinalityTracker()
    shard2 = TimeSeriesShard(REF, DEFAULT_SCHEMAS, 0, column_store=cs,
                             card_tracker=tracker)
    shard2.bootstrap_from_store()
    root = tracker.scan((), 0)[0]
    assert (root.ts_count, root.active_ts_count) == (4, 0)


def test_merge_records():
    a = CardinalityTracker()
    b = CardinalityTracker()
    a.modify_count(("w", "n", "m"), 2, 2)
    b.modify_count(("w", "n", "m"), 3, 1)
    out = merge_records([a.scan(("w",), 3), b.scan(("w",), 3)])
    assert out[0].ts_count == 5 and out[0].active_ts_count == 3
