"""Ingestion driver tests: steady-state ingest with rotating group
flushes, checkpoint watermark recovery, shard status FSM transitions.

(Parity model: coordinator/src/test IngestionStreamSpec +
IngestionActor.scala:174-345 recovery protocol.)"""

import time

import numpy as np

from filodb_tpu.core.memstore import TimeSeriesShard
from filodb_tpu.core.record import RecordBuilder
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetRef
from filodb_tpu.ingest import (IngestionDriver, LogIngestionStream,
                               MemoryIngestionStream)
from filodb_tpu.parallel.shardmapper import ShardMapper, ShardStatus
from filodb_tpu.promql.parser import TimeStepParams, parse_query_range
from filodb_tpu.query.engine import QueryEngine
from filodb_tpu.store import FlatFileColumnStore

REF = DatasetRef("timeseries")
T0 = 1_600_000_000


def _publish(stream, n_batches=10, rows_per_batch=20, t0_s=T0):
    """n_batches containers of counter samples for 2 series."""
    t = 0
    for i in range(n_batches):
        b = RecordBuilder(DEFAULT_SCHEMAS)
        for _ in range(rows_per_batch // 2):
            for s in range(2):
                b.add_sample(
                    "prom-counter",
                    {"_metric_": "reqs_total", "_ws_": "demo",
                     "_ns_": "App-0", "instance": f"i{s}"},
                    (t0_s + t * 10) * 1000, float((t + 1) * (s + 1)))
            t += 1
        for c in b.containers():
            stream.append(c)


def _wait(pred, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.01)
    return False


def _query(shard, start=T0 + 100, end=T0 + 900, step=60):
    plan = parse_query_range("rate(reqs_total[5m])",
                             TimeStepParams(start, step, end))
    return QueryEngine([shard]).execute(plan)


def test_steady_state_ingest_and_flush():
    stream = MemoryIngestionStream()
    mapper = ShardMapper(1)
    shard = TimeSeriesShard(REF, DEFAULT_SCHEMAS, 0, num_groups=2,
                            max_chunk_rows=64)
    drv = IngestionDriver(shard, stream, mapper=mapper,
                          flush_every_records=2).start()
    assert _wait(lambda: mapper.status(0) is ShardStatus.ACTIVE)
    _publish(stream, n_batches=10, rows_per_batch=20)
    assert _wait(lambda: drv.next_offset == 10)
    assert shard.stats.rows_ingested == 200
    assert shard.stats.flushes_done >= 4          # rotating group flushes
    # checkpoints recorded against ingested offsets
    assert shard.checkpoints and max(shard.checkpoints.values()) <= 9
    drv.stop()
    assert shard.recovery_watermark() == 9        # final flush_all


def test_recovery_replays_from_watermark(tmp_path):
    cs = FlatFileColumnStore(str(tmp_path / "col"))
    stream_path = str(tmp_path / "stream.log")

    # -- "process 1": ingest 10 batches, flush through offset 5, crash
    stream1 = LogIngestionStream(stream_path, DEFAULT_SCHEMAS)
    _publish(stream1, n_batches=10)
    shard1 = TimeSeriesShard(REF, DEFAULT_SCHEMAS, 0, num_groups=2,
                             max_chunk_rows=64, column_store=cs)
    for sd in stream1.read(0, 6):
        shard1.ingest(sd.container, sd.offset)
    shard1.flush_all(offset=5)                    # watermark = 5
    # rows 6..9 were never ingested -> lost with the "crash"

    # -- "process 2": bootstrap + driver recovery replays 6..9
    cs2 = FlatFileColumnStore(str(tmp_path / "col"))
    shard2 = TimeSeriesShard(REF, DEFAULT_SCHEMAS, 0, num_groups=2,
                             max_chunk_rows=64, column_store=cs2)
    shard2.bootstrap_from_store()
    assert shard2.recovery_watermark() == 5
    stream2 = LogIngestionStream(stream_path, DEFAULT_SCHEMAS)
    mapper = ShardMapper(1)
    statuses = []
    drv = IngestionDriver(shard2, stream2, mapper=mapper,
                          flush_every_records=3,
                          on_event=lambda s, st, p: statuses.append(st))
    drv.start()
    assert _wait(lambda: mapper.status(0) is ShardStatus.ACTIVE)
    assert drv.next_offset == 10
    assert ShardStatus.RECOVERY in statuses       # FSM went through recovery
    drv.stop()

    # the recovered shard answers the same query as an oracle that saw
    # every sample exactly once
    oracle = TimeSeriesShard(REF, DEFAULT_SCHEMAS, 0, max_chunk_rows=64)
    stream3 = LogIngestionStream(stream_path, DEFAULT_SCHEMAS)
    for sd in stream3.read(0, 100):
        oracle.ingest(sd.container, sd.offset)
    want, got = _query(oracle), _query(shard2)
    assert got.num_series == want.num_series == 2
    wmap = {k["instance"]: want.values[i] for i, k in enumerate(want.keys)}
    for i, k in enumerate(got.keys):
        np.testing.assert_allclose(got.values[i], wmap[k["instance"]],
                                   rtol=1e-9, equal_nan=True)


def test_recovery_idempotent_replay_below_group_checkpoints(tmp_path):
    """Groups flush at different offsets; replay from the min watermark
    re-delivers rows some groups already flushed — the OOO guard must
    drop them (no duplicated samples)."""
    cs = FlatFileColumnStore(str(tmp_path / "col"))
    stream_path = str(tmp_path / "stream.log")
    stream1 = LogIngestionStream(stream_path, DEFAULT_SCHEMAS)
    _publish(stream1, n_batches=10)

    shard1 = TimeSeriesShard(REF, DEFAULT_SCHEMAS, 0, num_groups=2,
                             max_chunk_rows=64, column_store=cs)
    for sd in stream1.read(0, 5):
        shard1.ingest(sd.container, sd.offset)
    shard1.flush_group(0, offset=4)
    shard1.flush_group(1, offset=4)
    for sd in stream1.read(5, 3):
        shard1.ingest(sd.container, sd.offset)
    shard1.flush_group(0, offset=7)               # group 0 ahead of group 1
    # watermark = min(7, 4) = 4; crash here

    cs2 = FlatFileColumnStore(str(tmp_path / "col"))
    shard2 = TimeSeriesShard(REF, DEFAULT_SCHEMAS, 0, num_groups=2,
                             max_chunk_rows=64, column_store=cs2)
    shard2.bootstrap_from_store()
    assert shard2.recovery_watermark() == 4
    drv = IngestionDriver(shard2, LogIngestionStream(stream_path,
                                                     DEFAULT_SCHEMAS),
                          flush_every_records=100)
    drv.start()
    assert _wait(lambda: drv.next_offset == 10)
    drv.stop()

    # every series has each timestamp exactly once.  Go through the real
    # read path (lookup_partitions) so ODP shells page their persisted
    # history back in — a shell whose replayed rows were all beyond its
    # persisted end stays unpaged until a query touches it.
    total_expected = 10 * 20  # all batches
    parts = shard2.lookup_partitions([], 0, 2**62)
    assert len(parts) == 2
    n_rows = 0
    for p in parts:
        ts, _, _ = p.read_full(1)
        assert np.all(np.diff(ts) > 0)            # strictly increasing
        n_rows += ts.size
    assert n_rows == total_expected


def test_ingest_batch_records_knob_replays_equivalently():
    """The WAL read batch (ingest-batch-records, was hardcoded at 64)
    must not change WHAT gets ingested — tiny and huge batches deliver
    the same rows, checkpoints, and query results."""
    shards = {}
    for batch in (2, 256):
        stream = MemoryIngestionStream()
        _publish(stream, n_batches=10, rows_per_batch=20)
        shard = TimeSeriesShard(REF, DEFAULT_SCHEMAS, 0, num_groups=2,
                                max_chunk_rows=64)
        drv = IngestionDriver(shard, stream, flush_every_records=3,
                              ingest_batch_records=batch)
        drv.start()
        assert _wait(lambda: drv.next_offset == 10)
        drv.stop()
        assert shard.stats.rows_ingested == 200
        assert shard.recovery_watermark() == 9
        shards[batch] = shard
    small, big = shards[2], shards[256]
    assert small.ingest_watermark_ms == big.ingest_watermark_ms
    want, got = _query(small), _query(big)
    assert want.num_series == got.num_series == 2
    wmap = {k["instance"]: want.values[i]
            for i, k in enumerate(want.keys)}
    for i, k in enumerate(got.keys):
        np.testing.assert_array_equal(got.values[i],
                                      wmap[k["instance"]])


def test_ingest_batch_records_recovery_replay(tmp_path):
    """Recovery replay honours the knob too: a 1-record batch replays
    to the same state as the default."""
    stream_path = str(tmp_path / "stream.log")
    stream1 = LogIngestionStream(stream_path, DEFAULT_SCHEMAS)
    _publish(stream1, n_batches=8)
    results = []
    for batch in (1, 64):
        shard = TimeSeriesShard(REF, DEFAULT_SCHEMAS, 0, num_groups=2,
                                max_chunk_rows=64)
        drv = IngestionDriver(
            shard, LogIngestionStream(stream_path, DEFAULT_SCHEMAS),
            flush_every_records=100, ingest_batch_records=batch)
        drv.start()
        assert _wait(lambda: drv.next_offset == 8)
        drv.stop()
        results.append((shard.stats.rows_ingested,
                        shard.ingest_watermark_ms))
    assert results[0] == results[1]
