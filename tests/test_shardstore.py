"""Device-resident sharded tile serving (parallel/shardstore.py).

The multi-chip correctness pins: the sharded evaluator families are
BIT-FOR-BIT the single-device tilestore dispatch at every device count
(1/2/4/8 over the conftest virtual mesh), the grouped collective
matches the host oracle, and the donated cross-flush refresh serves
exactly what a from-scratch rebuild would."""

import numpy as np
import pytest

import jax

from filodb_tpu.parallel.mesh import make_mesh
from filodb_tpu.parallel.shardstore import (ShardedTileEvaluator,
                                            ShardedTiles, _append_step)
from filodb_tpu.query import tilestore as tst

BASE = 1_000_000_000_000
DT = 10_000
W = 300_000
STEP = 60_000


def _tiles(S=13, N=200, seed=3, jitter=2000, resets=False):
    rng = np.random.default_rng(seed)
    ts = (BASE + np.arange(N, dtype=np.float64)[None, :] * DT
          + rng.integers(-jitter, jitter + 1, (S, N)))
    incs = rng.uniform(0, 5, (S, N))
    vals = np.cumsum(incs, axis=1)
    if resets:
        # a mid-tile counter reset per series
        vals[:, N // 2:] = np.cumsum(incs[:, N // 2:], axis=1)
    return tst.AlignedTiles([{"i": str(i)} for i in range(S)], BASE, DT,
                            np.ones((S, N), bool), ts, vals)


def _steps(n=24, start=400_000):
    return BASE + start + np.arange(n, dtype=np.int64) * STEP


def _mesh(ndev, time_parallel=1):
    devs = jax.devices()[:ndev]
    return make_mesh(n_shard_groups=ndev // time_parallel,
                     time_parallel=time_parallel, devices=devs)


@pytest.mark.parametrize("ndev,tp", [(1, 1), (2, 1), (4, 2), (8, 2)])
@pytest.mark.parametrize("func", ["rate", "increase", "delta"])
def test_counter_parity_bitwise_across_device_counts(ndev, tp, func):
    tiles = _tiles()
    ev = ShardedTileEvaluator(_mesh(ndev, tp))
    st = ev.place(tiles)
    assert st is not None
    steps = _steps()
    ref = np.asarray(tst.evaluate_counters_t(tiles, func, steps, W))
    got = np.asarray(st.eval_counters(func, steps, W))
    assert got.dtype == ref.dtype
    assert np.array_equal(got, ref, equal_nan=True)


def test_counter_parity_instant_and_offset():
    tiles = _tiles()
    ev = ShardedTileEvaluator(_mesh(8, 2))
    st = ev.place(tiles)
    one = _steps(1)                      # instant-query shape (T=1)
    ref = np.asarray(tst.evaluate_counters_t(tiles, "rate", one, W))
    got = np.asarray(st.eval_counters("rate", one, W))
    assert np.array_equal(got, ref, equal_nan=True)
    steps = _steps(16)
    ref = np.asarray(tst.evaluate_counters_t(tiles, "rate", steps, W,
                                             offset_ms=60_000))
    got = np.asarray(st.eval_counters("rate", steps, W,
                                      offset_ms=60_000))
    assert np.array_equal(got, ref, equal_nan=True)


def test_batch_parity_bitwise():
    tiles = _tiles()
    ev = ShardedTileEvaluator(_mesh(4, 2))
    st = ev.place(tiles)
    steps = _steps()
    fam = tst.counters_batch_family(tiles, "rate", steps, W, 0)
    w0e = int(steps[0])
    w0s_l = [w0e - W + k * STEP for k in range(3)]
    w0e_l = [w0e + k * STEP for k in range(3)]
    ref = np.asarray(tst.evaluate_counters_t_batch(
        tiles, "rate", fam, steps.size, STEP, w0s_l, w0e_l))
    got = np.asarray(st.eval_counters_batch("rate", steps.size, STEP,
                                            w0s_l, w0e_l))
    assert np.array_equal(got[:3], ref[:3, :steps.size],
                          equal_nan=True)


@pytest.mark.parametrize("func", ["sum_over_time", "avg_over_time",
                                  "count_over_time", "last_over_time",
                                  "stddev_over_time"])
def test_aligned_family_parity_bitwise(func):
    tiles = _tiles()
    ev = ShardedTileEvaluator(_mesh(8, 2))
    st = ev.place(tiles)
    steps = _steps()
    ref = np.asarray(tst.evaluate_aligned(tiles, func, steps, W))
    got = np.asarray(st.eval_aligned(tiles, func, steps, W))
    assert np.array_equal(got, ref, equal_nan=True)


def test_aligned_batch_parity_bitwise():
    tiles = _tiles()
    ev = ShardedTileEvaluator(_mesh(2, 1))
    st = ev.place(tiles)
    steps = _steps()
    w0e = int(steps[0])
    w0s_l = [w0e - W, w0e - W + STEP]
    w0e_l = [w0e, w0e + STEP]
    ref = np.asarray(tst.evaluate_aligned_batch(
        tiles, "sum_over_time", steps.size, STEP, w0s_l, w0e_l))
    got = np.asarray(st.eval_aligned_batch(tiles, "sum_over_time",
                                           steps.size, STEP, w0s_l,
                                           w0e_l))
    assert np.array_equal(got[:2], ref[:2], equal_nan=True)


def _host_grouped(ref, gids, G, agg):
    out = np.full((G, ref.shape[0]), np.nan)
    for g in range(G):
        rows = ref[:, gids == g]
        ok = ~np.isnan(rows)
        any_ok = ok.any(axis=1)
        if agg == "sum":
            v = np.where(ok, rows, 0.0).sum(axis=1)
        elif agg == "count":
            v = ok.sum(axis=1).astype(float)
        elif agg == "avg":
            v = np.where(ok, rows, 0.0).sum(axis=1) / ok.sum(axis=1)
        elif agg == "min":
            v = np.nanmin(np.where(ok, rows, np.nan), axis=1)
        else:
            v = np.nanmax(np.where(ok, rows, np.nan), axis=1)
        out[g] = np.where(any_ok, v, np.nan)
    return out


@pytest.mark.parametrize("agg", ["sum", "count", "avg", "min", "max"])
def test_grouped_collective_matches_host_oracle(agg):
    tiles = _tiles()
    ev = ShardedTileEvaluator(_mesh(8, 2))
    st = ev.place(tiles)
    steps = _steps()
    gids = np.arange(13) % 3
    ref = np.asarray(tst.evaluate_counters_t(tiles, "rate", steps, W)
                     ).astype(np.float64)
    want = _host_grouped(ref, gids, 3, agg)
    got = st.eval_grouped("rate", steps, W, gids, 3, agg)
    assert np.allclose(got, want, rtol=1e-5, equal_nan=True)


def test_grouped_pair_matches_fused_contract():
    tiles = _tiles()
    ev = ShardedTileEvaluator(_mesh(4, 1))
    st = ev.place(tiles)
    steps = _steps()
    gids = np.arange(13) % 3
    sums, cnts = st.eval_grouped_pair("rate", steps, W, gids, 3)
    assert sums.shape == (steps.size, 3) and cnts.shape == sums.shape
    ref = np.asarray(tst.evaluate_counters_t(tiles, "rate", steps, W)
                     ).astype(np.float64)
    want = _host_grouped(ref, gids, 3, "sum")
    wantc = _host_grouped(ref, gids, 3, "count")
    assert np.allclose(sums.T[wantc > 0], want[wantc > 0], rtol=1e-5)
    assert np.array_equal(cnts.T, np.where(np.isnan(wantc), 0, wantc))


# ---------------------------------------------------------------------------
# eligibility gates
# ---------------------------------------------------------------------------

def test_non_dense_tiles_not_placed():
    S, N = 8, 64
    valid = np.ones((S, N), bool)
    valid[0, 5] = False
    ts = BASE + np.arange(N, dtype=np.float64)[None, :] * DT \
        + np.zeros((S, 1))
    tiles = tst.AlignedTiles([{"i": str(i)} for i in range(S)], BASE, DT,
                             valid, ts, np.ones((S, N)))
    assert not ShardedTiles.tiles_eligible(tiles)
    assert ShardedTileEvaluator(_mesh(2)).place(tiles) is None


def test_query_fits_rejects_wide_grid():
    tiles = _tiles()
    ev = ShardedTileEvaluator(_mesh(2))
    st = ev.place(tiles)
    wide = np.array([BASE + 400_000, BASE + (1 << 32)], dtype=np.int64)
    assert not st.query_fits(wide, W, 0)
    assert st.query_fits(_steps(), W, 0)


# ---------------------------------------------------------------------------
# the donated refresh
# ---------------------------------------------------------------------------

def _extend(tiles, k, seed=11, reset_at=None):
    """A fresh AlignedTiles extending ``tiles`` by k appended slots."""
    rng = np.random.default_rng(seed)
    S = len(tiles.keys)
    N = tiles.num_slots
    ts_old = np.asarray(tiles.ts)
    v_old = np.asarray(tiles.channel("v"))
    new_ts = (BASE + (N + np.arange(k, dtype=np.float64))[None, :] * DT
              + rng.integers(-2000, 2001, (S, k)))
    incs = rng.uniform(0, 5, (S, k))
    new_v = v_old[:, -1:] + np.cumsum(incs, axis=1)
    if reset_at is not None:
        new_v[:, reset_at:] = np.cumsum(incs[:, reset_at:], axis=1)
    return tst.AlignedTiles(list(tiles.keys), BASE, DT,
                            np.ones((S, N + k), bool),
                            np.concatenate([ts_old, new_ts], axis=1),
                            np.concatenate([v_old, new_v], axis=1))


def test_donated_refresh_matches_fresh_rebuild_bitwise():
    tiles = _tiles()
    ev = ShardedTileEvaluator(_mesh(4, 2))
    st = ev.place(tiles)
    tiles2 = _extend(tiles, 32)
    assert ev.refresh(tiles, tiles2)
    assert ev.snapshot()["donated_refreshes"] == 1
    st2 = ev.place(tiles2)          # the refreshed placement, reused
    assert st2 is st
    steps = _steps(30)
    ref = np.asarray(tst.evaluate_counters_t(tiles2, "rate", steps, W))
    got = np.asarray(st2.eval_counters("rate", steps, W))
    assert np.array_equal(got, ref, equal_nan=True)
    # the old placement key is gone: old tiles re-place from scratch
    assert id(tiles) not in ev._placed


def test_donated_refresh_with_counter_reset_in_appended_span():
    tiles = _tiles()
    ev = ShardedTileEvaluator(_mesh(2, 1))
    st = ev.place(tiles)
    tiles2 = _extend(tiles, 24, reset_at=8)
    assert ev.refresh(tiles, tiles2)
    steps = _steps(28)
    ref = np.asarray(tst.evaluate_counters_t(tiles2, "rate", steps, W)
                     ).astype(np.float64)
    got = np.asarray(ev.place(tiles2).eval_counters(
        "rate", steps, W)).astype(np.float64)
    # the correction carry is mathematically identical; rounding order
    # of the cumsum may differ, so pin to tight tolerance here
    assert np.allclose(got, ref, rtol=1e-9, equal_nan=True)


def test_refresh_incompatible_falls_back():
    tiles = _tiles()
    ev = ShardedTileEvaluator(_mesh(2, 1))
    st = ev.place(tiles)
    assert st is not None
    # different series set: refuse
    other = _tiles(S=14, seed=9)
    assert not ev.refresh(tiles, other)
    # beyond capacity: refuse (capacity is the pow2 of the build size)
    big = _extend(tiles, st.cap)     # n_filled + k_pad > cap
    st2 = ev.place(tiles)
    assert st2 is None or not st2.append_slots(big)


def test_placement_dropped_when_tiles_die():
    ev = ShardedTileEvaluator(_mesh(2, 1))
    tiles = _tiles(S=5, N=64)
    st = ev.place(tiles)
    assert st is not None and len(ev._placed) == 1
    del tiles
    import gc
    gc.collect()
    assert len(ev._placed) == 0


def test_append_step_is_donated():
    """The zero-copy property itself: the donated input buffer is
    consumed by the append (reading it afterwards raises), and the
    output reuses its sharding."""
    mesh = _mesh(2, 1)
    from jax.sharding import NamedSharding, PartitionSpec as P
    from filodb_tpu.parallel.mesh import resolve_spec
    col = NamedSharding(mesh, resolve_spec(mesh, P(None, 0)))
    import jax.numpy as jnp
    tsr = jax.device_put(jnp.zeros((64, 8), jnp.int32), col)
    v = jax.device_put(jnp.ones((64, 8)), col)
    cv = jax.device_put(jnp.ones((64, 8)), col)
    new_tsr = jax.device_put(jnp.ones((8, 8), jnp.int32), col)
    new_v = jax.device_put(jnp.full((8, 8), 2.0), col)
    t2, v2, c2 = _append_step(tsr, v, cv, new_tsr, new_v, np.int64(32))
    assert v2.sharding == col
    with pytest.raises(RuntimeError):
        _ = np.asarray(v)           # donated: buffer deleted


# ---------------------------------------------------------------------------
# backend integration: mesh-shaped batches + dispatch routing
# ---------------------------------------------------------------------------

def test_backend_routes_counters_through_mesh_and_matches():
    from filodb_tpu.query.model import RangeParams, RawSeries
    from filodb_tpu.query.tpu import TpuBackend

    rng = np.random.default_rng(0)
    series = []
    for i in range(9):
        ts = BASE + np.arange(128, dtype=np.int64) * DT
        series.append(RawSeries({"i": str(i)}, ts,
                                np.cumsum(rng.uniform(0, 5, 128)),
                                is_counter=True))
    params = RangeParams(BASE + 400_000, STEP, BASE + 400_000 + 23 * STEP)
    plain = TpuBackend(batcher=None)
    ref = plain.periodic_samples(series, params, "rate", W)
    meshed = TpuBackend(batcher=None,
                        mesh_eval=ShardedTileEvaluator(_mesh(8, 2)))
    got = meshed.periodic_samples(series, params, "rate", W)
    assert meshed.mesh_dispatches >= 1
    assert np.array_equal(got.values, ref.values, equal_nan=True)


def test_backend_mesh_batch_run_parity():
    """The mesh-shaped micro-batch: _aligned_run with 3 members through
    the sharded batch evaluator splits back bit-for-bit the members'
    single dispatches."""
    from filodb_tpu.query.tpu import TpuBackend

    tiles = _tiles()
    ev = ShardedTileEvaluator(_mesh(4, 2))
    st = ev.place(tiles)
    be = TpuBackend(batcher=None, mesh_eval=ev)
    steps = _steps()
    fam = tst.counters_batch_family(tiles, "rate", steps, W, 0)
    members = []
    for k in range(3):
        s = steps + k * STEP
        members.append((int(s[0]) - W, int(s[0]), s, tiles))
    res = be._aligned_run(tiles, "rate", fam, steps.size, STEP, W, 0,
                          st, members)
    for k in range(3):
        want = np.asarray(tst.evaluate_counters_t(
            tiles, "rate", steps + k * STEP, W)).T
        assert np.array_equal(res.get(k), want, equal_nan=True)


def test_fused_groupsum_rides_resident_collective():
    from filodb_tpu.query.model import RawSeries
    from filodb_tpu.query.tpu import TpuBackend

    rng = np.random.default_rng(1)
    series = []
    for i in range(8):
        ts = BASE + np.arange(128, dtype=np.int64) * DT
        series.append(RawSeries({"i": str(i)}, ts,
                                np.cumsum(rng.uniform(0, 5, 128)),
                                is_counter=True))
    be = TpuBackend(batcher=None,
                    mesh_eval=ShardedTileEvaluator(_mesh(4, 1)))
    steps = _steps(16)
    gids = np.arange(8) % 2
    res = be.fused_groupsum(series, "rate", steps, W, 0, gids, 2)
    assert res is not None
    sums, cnts = res
    assert sums.shape == (16, 2) and (cnts > 0).any()
    assert be.fused_aggs == 1 and be.mesh_dispatches == 1
